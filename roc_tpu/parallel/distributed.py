"""Distributed full-graph training over a 1-D device mesh.

TPU-native replacement for the reference's entire distribution stack
(SURVEY §2 #20-22 and §2's parallelism facets):

- **GnnMapper** (``gnn_mapper.cc:120-151``: partitions → GPUs round-robin,
  FB/ZC memory placement) → a ``jax.sharding.Mesh`` over one ``'parts'``
  axis with ``NamedSharding``s: partition p lives on device p, period.
- **Graph partition parallelism** (``gnn.cc:471-530``: vertex-range index
  launches) → ``shard_map`` over stacked per-part arrays; every op in the
  step function runs SPMD on its local partition.
- **Halo exchange** (whole-region feature requirement,
  ``scattergather.cc:70-72``; the dead explicit ``ncclAllGather`` path,
  ``gnn_kernel.cu:65-78``) → ``jax.lax.all_gather`` over ICI before each
  aggregation, in *padded part order* (edge sources are pre-remapped to
  padded coordinates at partition time).
- **Gradient reduction** (per-partition weight-grad replicas summed on one
  GPU, ``optimizer_kernel.cu:88-94``) → ``jax.lax.psum`` of local grads
  over the mesh — numerically the same sum, but bandwidth-optimal on ICI
  and with no replica memory.
- **Metrics reduction** (on-GPU atomics, ``softmax_kernel.cu:41-79``) →
  ``psum`` of the PerfMetrics sums.

Weights and optimizer state are replicated (the reference reads weights
whole in every task, ``linear.cc:95-99``); activations/labels/masks are
sharded on the node axis.  Multi-host DCN works through the same mesh via
``jax.distributed.initialize`` + ``jax.make_mesh`` over all processes'
devices.
"""

from __future__ import annotations

from dataclasses import dataclass, replace as dc_replace
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.ell import ell_from_padded_parts
from ..core.graph import Dataset, MASK_NONE
from ..core.partition import PartitionedGraph, partition_graph
from ..models.builder import GraphContext, Model
from ..obs.events import emit
from ..ops.loss import masked_softmax_cross_entropy, perf_metrics, summarize_metrics
from ..train.optimizer import AdamConfig, adam_init, adam_update
from ..train.trainer import (TrainConfig, cast_floats, compute_dtype_of,
                             remat_policy, resolve_symmetric)


# THE names of the mesh axes — defined in parallel/__init__ (the
# cycle-free home ring.py / multihost.py / models/builder.py can also
# import) and re-exported here because every collective in the step
# bodies below reduces/gathers/permutes over PARTS_AXIS and the SPMD
# collective verifier (analysis/collective_lint.py) checks the traced
# eqns' axis names against the mesh built here.  MODEL_AXIS never
# appears in a step-body collective: on a 2-D mesh it is a GSPMD
# ``auto`` axis — the partitioner propagates the model sharding of
# params/opt state through the unchanged 1-D step programs.
from . import MODEL_AXIS, PARTS_AXIS, model_shard_spec


def _shard_map(f, mesh: Mesh, in_specs, out_specs,
               auto: frozenset = frozenset()):
    """``jax.shard_map`` across jax versions: the stable API (with
    ``check_vma``) when present, else the ``jax.experimental``
    form (jax <= 0.4.x, whose flag spells ``check_rep``).  Replica
    checking stays off either way — the step functions psum
    explicitly.  ``auto`` names mesh axes left to GSPMD (the 2-D
    mesh's MODEL_AXIS: the body stays a 1-D parts program while the
    partitioner threads the model sharding through it)."""
    kw = {"auto": auto} if auto else {}
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False,
                             **kw)
    from jax.experimental.shard_map import shard_map as sm
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              check_rep=False, **kw)


def make_mesh(num_parts: Optional[int] = None,
              devices: Optional[List] = None,
              model: int = 1) -> Mesh:
    """Device mesh over graph partitions.  ``model=1`` (default) is
    the 1-D parts mesh — one partition per device, the reference sets
    numParts = numMachines * numGPUs the same way (``gnn.cc:62,754``);
    ``num_parts=None`` uses every device.  ``model > 1`` builds the
    ``(parts, model)`` 2-D mesh: ``num_parts * model`` devices
    reshaped parts-major, so the model replicas of one partition are
    ICI neighbors (``num_parts=None`` then uses
    ``len(devices) // model`` partitions).

    ``jax.devices()`` orders devices process-major, so consecutive
    partitions land on the same host — ring-halo hops cross DCN once
    per host (parallel/multihost.py relies on this layout)."""
    if devices is None:
        devices = jax.devices()
    model = int(model)
    if num_parts is None:
        num_parts = len(devices) // model if model > 1 else len(devices)
    n = num_parts * model
    assert len(devices) >= n, (
        f"need {n} devices ({num_parts}x{model}), have {len(devices)}")
    if model == 1:
        return Mesh(np.asarray(devices[:num_parts]), (PARTS_AXIS,))
    return Mesh(np.asarray(devices[:n]).reshape(num_parts, model),
                (PARTS_AXIS, MODEL_AXIS))


def remap_col_to_padded(plan, col: np.ndarray) -> np.ndarray:
    """Remap one partition's col array from global vertex ids to *padded
    row coordinates* (the row layout of the all-gathered feature matrix):
    global id g living in part p maps to
    ``p * part_nodes + (g - node_offset[p])``; the dummy source maps to
    ``num_parts * part_nodes`` (the appended zero row)."""
    offsets = np.asarray([l for l, _ in plan.bounds] + [plan.num_nodes],
                         dtype=np.int64)
    col = np.asarray(col, dtype=np.int64)
    dummy = plan.num_parts * plan.part_nodes
    out = np.full(col.shape, dummy, dtype=np.int64)
    real = col < plan.num_nodes
    g = col[real]
    p = np.searchsorted(offsets[1:plan.num_parts + 1], g, side="right")
    out[real] = p * plan.part_nodes + (g - offsets[p])
    assert (out <= dummy).all() and (out >= 0).all()
    return out.astype(np.int32)


def remap_to_padded(pg: PartitionedGraph) -> np.ndarray:
    """All-parts form of :func:`remap_col_to_padded` ([P, E_p] in/out)."""
    return remap_col_to_padded(pg, pg.part_col_idx)


def pad_nodes(arr: np.ndarray, pg: PartitionedGraph,
              fill: float = 0) -> np.ndarray:
    """Scatter a global per-node array [V, ...] into the stacked padded
    layout [P, part_nodes, ...]; padding rows get ``fill``."""
    shape = (pg.num_parts, pg.part_nodes) + arr.shape[1:]
    out = np.full(shape, fill, dtype=arr.dtype)
    for p in range(pg.num_parts):
        l, r = pg.bounds[p]
        if r < l:
            continue
        out[p, :r - l + 1] = arr[l:r + 1]
    return out


def unpad_nodes(arr: np.ndarray, pg: PartitionedGraph) -> np.ndarray:
    """Inverse of pad_nodes: [P, part_nodes, ...] -> [V, ...]."""
    parts = []
    for p in range(pg.num_parts):
        l, r = pg.bounds[p]
        if r >= l:
            parts.append(arr[p, :r - l + 1])
    return np.concatenate(parts, axis=0)


@dataclass
class ShardedData:
    """Device-resident sharded training data (leading axis = parts)."""
    feats: jax.Array       # [P, part_nodes, F]   P('parts')
    labels: jax.Array      # [P, part_nodes]      P('parts')
    mask: jax.Array        # [P, part_nodes]      P('parts')
    edge_src: jax.Array    # [P, part_edges]      P('parts'), padded coords
    edge_dst: jax.Array    # [P, part_edges]      P('parts'), local rows
    in_degree: jax.Array   # [P, part_nodes]      P('parts')
    ell_idx: Tuple[jax.Array, ...] = ()   # per bucket [P, rows_b, width_b]
    ell_row_pos: jax.Array = None         # [P, part_nodes]
    ell_row_id: Tuple[jax.Array, ...] = ()  # per bucket [P, rows_b]
    ring_idx: Tuple[jax.Array, ...] = ()  # (src, dst) [P, S, pair_edges]
    # sectioned layout (aggr_impl == "sectioned"): per section
    # [P, n_chunks_s, seg_rows, 8] / [P, n_chunks_s, seg_rows], plus
    # the static (start, size) metadata.  For aggr_impl ==
    # "attn_flat8" the same slots carry the SINGLE-section uniform
    # width-8 attention tables (ids in gathered coordinates, dummy ==
    # P*part_nodes; the step body routes them to GraphContext
    # flat8_idx/flat8_dst)
    sect_idx: Tuple[jax.Array, ...] = ()
    sect_sub_dst: Tuple[jax.Array, ...] = ()
    sect_meta: Tuple[Tuple[int, int], ...] = ()
    # block-dense MXU layout (aggr_impl == "bdense"): per-partition
    # dense [128,128] tiles over (local dst rows x gathered source
    # coords), padded to a uniform block count; () or
    # (a [P,nblk,128,128] u8, src_blk [P,nblk], dst_blk [P,nblk]).
    # The residual scattered edges ride the sect_* tables above.
    bd_tabs: Tuple[jax.Array, ...] = ()
    bd_vpad: int = 0        # dst tile space (covers part_nodes)
    bd_src_vpad: int = 0    # src tile space (covers gathered rows)
    bd_occupancy: Tuple[dict, ...] = ()   # per-part plan stats
    # the pad_plan_groups alignment the tables were built for: the
    # kernel's ``group`` MUST match it (the trainer validates injected
    # data — a mismatched group would reduce across dst-tile
    # boundaries and mis-aggregate with no shape error)
    bd_group: int = 1
    # padded slots / real edges of the ring tables (halo='ring' only);
    # surfaced so trainer setup can echo the SPMD-uniformity cost
    ring_padding_ratio: Optional[float] = None
    # fused-normalization weight tables (aggr_fuse, shapes mirror the
    # index tables they weight): per-bucket ell weights, per-section
    # sectioned weights, () or ([P, S, pair_edges],) ring weights,
    # () or (d_dst [P, vpad], d_src [P, src_vpad]) bdense tile scales.
    # Empty = the step derives d from in_degree and scales in-op.
    ell_w: Tuple[jax.Array, ...] = ()
    sect_w: Tuple[jax.Array, ...] = ()
    ring_w: Tuple[jax.Array, ...] = ()
    bd_scale: Tuple[jax.Array, ...] = ()


def _sectioned_tables(ptrs: np.ndarray, cols: np.ndarray,
                      pg: PartitionedGraph, src_rows: int,
                      section_rows: Optional[int], sect_sub_w: int,
                      sect_u16: bool, put,
                      fuse_d: Optional[Tuple[np.ndarray,
                                             np.ndarray]] = None):
    """Build + upload the stacked per-part sectioned tables — shared
    by the 'sectioned' branch (whole CSR) and the 'bdense' branch
    (residual CSR), so tuning knobs apply to both in one place.
    ``fuse_d`` = (d_dst [P, part_nodes], d_src [gathered_rows]) also
    bakes + uploads the fused-normalization weight tables.
    Returns (sect_idx, sect_sub_dst, sect_meta, sect_w)."""
    from ..core.ell import (default_section_rows,
                            sectioned_from_padded_parts)
    if section_rows is None:
        section_rows = default_section_rows(sect_u16)
    sect = sectioned_from_padded_parts(
        ptrs, cols, pg.real_nodes, pg.part_nodes, src_rows=src_rows,
        section_rows=section_rows, sub_w=sect_sub_w)
    if sect_u16:
        sect = sect.with_idx_dtype(np.uint16)
    sect_w = ()
    if fuse_d is not None:
        sect_w = tuple(put(w) for w in
                       sect.weight_tables(fuse_d[0], fuse_d[1]))
    return (tuple(put(a) for a in sect.idx),
            tuple(put(a) for a in sect.sub_dst),
            tuple(zip(sect.sec_starts, sect.sec_sizes)),
            sect_w)


def shard_dataset(dataset: Dataset, pg: PartitionedGraph,
                  mesh: Mesh, dtype=jnp.float32,
                  aggr_impl: str = "segment",
                  halo: str = "gather",
                  put=None, section_rows: Optional[int] = None,
                  sect_sub_w: int = 8, sect_u16: bool = False,
                  bdense_min_fill: int = 64,
                  bdense_a_budget: Optional[int] = 2 << 30,
                  bdense_group: int = 1,
                  aggr_fuse: bool = False
                  ) -> ShardedData:
    """Build + upload the stacked per-part arrays.  ``put`` overrides
    the upload (default: replicated-process ``device_put`` with the
    parts sharding); parallel/multihost.py passes a local-shards-only
    uploader for multi-host runs.  ``sect_sub_w``/``sect_u16`` tune the
    sectioned layout exactly like the single-device path
    (train/trainer.py build_graph_context) — user-selected config is
    never silently dropped.

    ``aggr_fuse=True`` bakes the symmetric ``D^-1/2`` scales into the
    tables (fused-aggregation weight tables / bdense tile scales) for
    models rewritten by ``Model.fuse_norm_aggregate``; without them
    the fused step still runs correctly via in-op scaling."""
    sh = NamedSharding(mesh, P(PARTS_AXIS))
    if put is None:
        put = lambda x: jax.device_put(x, sh)
    ell_idx = ()
    ell_row_pos = put(np.zeros((pg.num_parts, 1), dtype=np.int32))
    ell_row_id = ()
    ring_idx = ()
    sect_idx = ()
    sect_sub_dst = ()
    sect_meta = ()
    bd_tabs = ()
    bd_vpad = 0
    bd_src_vpad = 0
    bd_occupancy = ()
    ring_padding_ratio = None
    ell_w = ()
    sect_w = ()
    ring_w = ()
    bd_scale = ()
    fuse_d = None
    if aggr_fuse:
        # d in both coordinate systems the tables index with: local
        # padded rows per part (padding rows have degree 0 -> 0) and
        # the flattened gathered layout
        from ..ops.norm import inv_sqrt_degree_np
        d_parts = inv_sqrt_degree_np(pg.part_in_degree)
        fuse_d = (d_parts, d_parts.reshape(-1))
    if halo == "ring":
        # ring tables fully describe the aggregation — skip the O(E)
        # per-edge array construction entirely and upload stubs
        from .ring import build_ring_tables, ring_weight_tables
        rt = build_ring_tables(pg)
        ring_idx = (put(rt.src), put(rt.dst))
        if aggr_fuse:
            from ..ops.norm import inv_sqrt_degree_np as _inv
            ring_w = (put(ring_weight_tables(
                pg, rt, _inv(dataset.graph.in_degree))),)
        ring_padding_ratio = rt.padding_ratio
        col_padded = np.zeros((pg.num_parts, 1), dtype=np.int32)
        edge_dst = np.zeros((pg.num_parts, 1), dtype=np.int32)
    else:
        col_padded = remap_to_padded(pg)
        if aggr_impl in ("ell", "pallas", "sectioned", "attn_flat8",
                         "flat_sum", "bdense"):
            # table-driven paths never read the flat edge arrays —
            # upload stubs instead of two [P, E_p] tensors
            edge_dst = np.zeros((pg.num_parts, 1), dtype=np.int32)
        else:
            edge_dst = np.stack([
                np.repeat(np.arange(pg.part_nodes, dtype=np.int32),
                          np.diff(pg.part_row_ptr[p]))
                for p in range(pg.num_parts)])
        if aggr_impl in ("ell", "pallas"):
            table = ell_from_padded_parts(
                pg.part_row_ptr, col_padded, pg.real_nodes,
                pg.part_nodes, dummy=pg.num_parts * pg.part_nodes)
            ell_idx = tuple(put(a) for a in table.idx)
            ell_row_pos = put(table.row_pos)
            ell_row_id = tuple(put(a) for a in table.row_id)
            if aggr_fuse and aggr_impl == "ell":
                from ..core.ell import ell_weight_tables
                ell_w = tuple(put(w) for w in ell_weight_tables(
                    table, fuse_d[0], fuse_d[1]))
        elif aggr_impl == "sectioned":
            sect_idx, sect_sub_dst, sect_meta, sect_w = \
                _sectioned_tables(
                    pg.part_row_ptr, col_padded, pg,
                    src_rows=pg.num_parts * pg.part_nodes,
                    section_rows=section_rows, sect_sub_w=sect_sub_w,
                    sect_u16=sect_u16, put=put, fuse_d=fuse_d)
        elif aggr_impl == "bdense":
            # per-partition block-dense plans over the RECTANGULAR
            # tile space (local dst rows x gathered source coords —
            # ops/blockdense.py plan_blocks num_cols).  Stacked to a
            # uniform block count: short partitions pad with zero-A
            # tiles scattered into the dummy output tile, so every
            # device runs the same program (SPMD uniformity, exactly
            # the sectioned tables' padding-chunk scheme).
            from ..core.ell import clean_part_ptr
            from ..ops.blockdense import (BLOCK, U4_MAX, pack_a_u4,
                                          plan_blocks)
            src_rows = pg.num_parts * pg.part_nodes
            ptrs = [clean_part_ptr(pg.part_row_ptr[p],
                                   pg.real_nodes[p], pg.part_nodes)
                    for p in range(pg.num_parts)]

            def _mk(budget):
                # group>1 plans arrive per-part group-aligned, so
                # the stacked tail padding below extends in WHOLE
                # dummy-dst groups (nb and nblk_max multiples)
                return [plan_blocks(
                    ptrs[p], col_padded[p][:int(ptrs[p][-1])],
                    pg.part_nodes, min_fill=bdense_min_fill,
                    a_budget_bytes=budget,
                    num_cols=src_rows, group=bdense_group)
                    for p in range(pg.num_parts)]

            # same 2x-budget-then-pack policy as plan_blocks_packed,
            # decided ACROSS parts: the stacked table needs one
            # uniform trailing width, so pack all parts or none
            # (pack_a_u4 packs empty parts too).  The unpackable AND
            # over-budget case re-runs the census — accepted: it
            # needs multi-edge hubs past 4 bits plus a saturated
            # budget, and the native census is seconds even at
            # Reddit scale
            plans = _mk(bdense_a_budget * 2
                        if bdense_a_budget is not None else None)
            packable = all(pl.n_blocks == 0
                           or int(pl.a_blocks.max()) <= U4_MAX
                           for pl in plans)
            if packable:
                plans = [pack_a_u4(pl) for pl in plans]
            elif bdense_a_budget is not None and any(
                    pl.a_blocks.nbytes > bdense_a_budget
                    for pl in plans):
                plans = _mk(bdense_a_budget)
            bd_occupancy = tuple(pl.occupancy() for pl in plans)
            nblk_max = max(pl.n_blocks for pl in plans)
            if nblk_max:
                bd_vpad = plans[0].vpad
                bd_src_vpad = plans[0].src_vpad
                n_dst_tiles = bd_vpad // BLOCK
                a_w = BLOCK // 2 if packable else BLOCK
                a = np.zeros((pg.num_parts, nblk_max, BLOCK, a_w),
                             dtype=np.uint8)
                sblk = np.zeros((pg.num_parts, nblk_max),
                                dtype=np.int32)
                # padding blocks target the dummy output tile (index
                # n_dst_tiles) — zero A keeps them numerically inert,
                # the dummy dst keeps even rounding noise off real rows
                dblk = np.full((pg.num_parts, nblk_max), n_dst_tiles,
                               dtype=np.int32)
                for p, pl in enumerate(plans):
                    nb = pl.n_blocks
                    a[p, :nb] = pl.a_blocks
                    sblk[p, :nb] = pl.src_blk
                    dblk[p, :nb] = pl.dst_blk
                bd_tabs = (put(a), put(sblk), put(dblk))
                if aggr_fuse:
                    # in-register tile scales (ops/blockdense.py):
                    # dst covers local padded rows, src the gathered
                    # layout (identical on every part — replicated
                    # rows keep the stacked-upload convention)
                    dd = np.zeros((pg.num_parts, bd_vpad), np.float32)
                    dd[:, :pg.part_nodes] = fuse_d[0]
                    ds1 = np.zeros(bd_src_vpad, np.float32)
                    ds1[:src_rows] = fuse_d[1]
                    ds = np.broadcast_to(
                        ds1, (pg.num_parts, bd_src_vpad)).copy()
                    bd_scale = (put(dd), put(ds))
            # residual scattered edges -> the stacked sectioned tables
            # (every edge, when no tile qualifies anywhere)
            e_res = max(max(pl.res_col.shape[0] for pl in plans), 1)
            res_ptrs = np.stack([pl.res_row_ptr for pl in plans])
            res_cols = np.zeros((pg.num_parts, e_res), dtype=np.int32)
            for p, pl in enumerate(plans):
                res_cols[p, :pl.res_col.shape[0]] = pl.res_col
            sect_idx, sect_sub_dst, sect_meta, sect_w = \
                _sectioned_tables(
                    res_ptrs, res_cols, pg, src_rows=src_rows,
                    section_rows=section_rows, sect_sub_w=sect_sub_w,
                    sect_u16=sect_u16, put=put, fuse_d=fuse_d)
        elif aggr_impl in ("attn_flat8", "flat_sum"):
            # the uniform flat layout, sharded: per-partition SINGLE-
            # section tables over gathered coordinates (one uniform
            # scan shape per device — the same compile-size fix as the
            # single-chip path, train/trainer.py make_graph_context).
            # The flat tables ride the sect_* slots (ShardedData
            # docstring); the step body routes them to the
            # GraphContext flat8 fields.  FLAT_SEG_ROWS bounds the
            # per-chunk transient like there.  For the fused flat_sum
            # path the baked D^-1/2 weight tables ride the sect_w slot
            # the same way.
            from ..core.ell import flat_sum_from_padded_parts
            src_rows = pg.num_parts * pg.part_nodes
            sect = flat_sum_from_padded_parts(
                pg.part_row_ptr, col_padded, pg.real_nodes,
                pg.part_nodes, src_rows=src_rows)
            sect_idx = tuple(put(a) for a in sect.idx)
            sect_sub_dst = tuple(put(a) for a in sect.sub_dst)
            if aggr_impl == "flat_sum" and fuse_d is not None:
                sect_w = tuple(put(w) for w in sect.weight_tables(
                    fuse_d[0], fuse_d[1]))
        if aggr_impl in ("ell", "pallas", "sectioned", "attn_flat8",
                         "flat_sum", "bdense"):
            col_padded = np.zeros((pg.num_parts, 1), dtype=np.int32)
    return ShardedData(
        feats=put(pad_nodes(dataset.features, pg).astype(dtype)),
        labels=put(pad_nodes(dataset.labels, pg)),
        mask=put(pad_nodes(dataset.mask, pg, fill=MASK_NONE)),
        edge_src=put(col_padded),
        edge_dst=put(edge_dst),
        in_degree=put(pg.part_in_degree),
        ell_idx=ell_idx,
        ell_row_pos=ell_row_pos,
        ell_row_id=ell_row_id,
        ring_idx=ring_idx,
        sect_idx=sect_idx,
        sect_sub_dst=sect_sub_dst,
        sect_meta=sect_meta,
        bd_tabs=bd_tabs,
        bd_vpad=bd_vpad,
        bd_src_vpad=bd_src_vpad,
        bd_occupancy=bd_occupancy,
        bd_group=bdense_group if bd_tabs else 1,
        ring_padding_ratio=ring_padding_ratio,
        ell_w=ell_w,
        sect_w=sect_w,
        ring_w=ring_w,
        bd_scale=bd_scale,
    )


def put_replicated(tree, mesh: Mesh):
    """Place a host pytree across every device of ``mesh``: fully
    replicated on a 1-D parts mesh (the reference reads weights whole
    in every task, ``linear.cc:95-99``), and model-SHARDED on a 2-D
    ``(parts, model)`` mesh — each leaf whose shape carries a
    model-divisible dim (``parallel.model_shard_spec``, trailing dim
    first: the feature dim of every weight matrix / Adam moment here)
    splits it over MODEL_AXIS and stays replicated over parts;
    indivisible leaves (small biases) stay fully replicated.

    Single-process this is a plain ``device_put``; multi-process it
    assembles each global array from this process's addressable shards
    (``device_put`` cannot place onto non-addressable devices) — the
    bootstrap analog of the reference broadcasting initial weights to
    every GPU (``gnn.cc:78-91`` model build + Legion region mapping).
    """
    model = int(dict(mesh.shape).get(MODEL_AXIS, 1))

    def sharding_of(x):
        spec = model_shard_spec(np.shape(x), model)
        return NamedSharding(mesh, P(*spec) if spec else P())

    if jax.process_count() == 1:
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sharding_of(x)), tree)

    def put(x):
        x = np.asarray(x)
        return jax.make_array_from_callback(x.shape, sharding_of(x),
                                            lambda idx: x[idx])
    return jax.tree_util.tree_map(put, tree)


class DistributedTrainer:
    """The reference epoch loop (``gnn.cc:99-111``) run SPMD over the
    partition mesh.

    ``data`` injects pre-built sharded tables — the multi-host entry
    point: each process runs ``multihost.shard_dataset_local`` (only
    its own partitions' rows) and passes the result here; the default
    is the single-controller ``shard_dataset`` build.  The caller must
    build ``data`` with the same ``aggr_impl``/``halo`` the config
    resolves to, and should pass the ``pg`` it built the data from
    (otherwise the identical O(E) partitioning runs a second time)."""

    def __init__(self, model: Model, dataset: Dataset, num_parts: int,
                 config: TrainConfig = TrainConfig(),
                 mesh: Optional[Mesh] = None,
                 data: Optional[ShardedData] = None,
                 pg=None):
        from ..train.trainer import resolve_config, resolve_partition
        # the ONE resolve pass (train/trainer.py resolve_config):
        # fuse, the shared 'auto' rule incl. the bdense structure
        # probe (global dense fraction is the right proxy — per-part
        # plans tile contiguous local row ranges of the same vertex
        # order; the gather-table bound uses the GLOBAL node count,
        # the scatter-carry bound the per-partition output rows),
        # memory autopilot with the A-budget charged, attention impl
        # (multi-chip attention at >=20M edges auto-routes to the
        # uniform flat8 layout — VERDICT r4 weak #3).  Multi-process
        # runs skip the probe — every SPMD process must resolve
        # identically.
        model, config, _ = resolve_config(
            model, dataset, config, num_parts=num_parts,
            multiprocess=jax.process_count() > 1)
        self.model = model
        if config.features == "host":
            raise NotImplementedError(
                "features='host' streaming is single-device only; the "
                "distributed >HBM mechanism is halo='ring' (the "
                "autopilot picks it automatically for parts > 1)")
        self.config = config
        self.compute = compute_dtype_of(config)
        self.epoch = 0
        self.symmetric = resolve_symmetric(dataset, config.symmetric)
        # (parts, model) mesh knob: resolve_mesh validates the config
        # against the positional parts count (they must agree — the
        # parts axis IS the partition count); an injected mesh wins,
        # and the model width is always read back off the mesh actually
        # trained on so the sharding/step construction below cannot
        # disagree with it
        from ..train.trainer import resolve_mesh
        _, mesh_model = resolve_mesh(
            config, num_parts=num_parts,
            num_devices=len(jax.devices()) if mesh is None else None)
        self.mesh = mesh if mesh is not None else make_mesh(
            num_parts, model=mesh_model)
        self._mesh_model = int(dict(self.mesh.shape).get(MODEL_AXIS, 1))
        if pg is not None and pg.num_parts != num_parts:
            raise ValueError(f"injected pg has {pg.num_parts} parts, "
                             f"trainer was asked for {num_parts}")
        if data is not None and pg is None:
            # re-partitioning here could use different padding
            # multiples than the caller's table build — the tables
            # would silently stop corresponding to the feats sharding
            raise ValueError(
                "pass pg= alongside data= (the SAME PartitionedGraph "
                "the tables were built from)")
        # cost-model-driven partitioning (core/costmodel.py): resolve
        # the split method, hold the online ridge model, and keep the
        # dataset so maybe_rebalance can rebuild shards at epoch
        # boundaries
        from ..core.costmodel import PartitionCostModel
        self._dataset = dataset
        self._partition_method = resolve_partition(config)
        # workload flags for the φ features only this config pays:
        # the per-edge softmax column (attention models) and the
        # flat8 scan-length column (the flat layout family)
        self._phi_flags = dict(
            attn_edges=bool(self.model.uses_attention()),
            flat8=config.aggr_impl in ("attn_flat8", "flat_sum"))
        self._costmodel = PartitionCostModel(
            node_multiple=8, edge_multiple=config.chunk)
        self._rebalances = 0
        self._phi_cache = None
        if config.rebalance:
            if data is not None:
                # injected tables may have been built by a different
                # process/loader (multihost) — this trainer cannot
                # rebuild them faithfully mid-run
                raise ValueError(
                    "rebalance=True requires the trainer-owned data "
                    "build; injected data= cannot be repartitioned")
            if jax.process_count() > 1:
                raise NotImplementedError(
                    "online rebalancing is single-controller only "
                    "(every SPMD process would need to agree on the "
                    "re-split and reshard over DCN)")
        self.pg = pg if pg is not None else partition_graph(
            dataset.graph, num_parts,
            node_multiple=8, edge_multiple=config.chunk,
            method=self._partition_method,
            cost_weights=self._costmodel.search_weights(
                **self._phi_flags))
        self.data = data if data is not None else self._build_data(
            self.pg)
        if config.aggr_impl == "bdense" and config.halo != "ring" \
                and data is None:
            # own build only: injected data carries no plan to report
            # (an empty bd_tabs there means the CALLER never planned,
            # not that no tile qualified)
            for p, occ in enumerate(self.data.bd_occupancy):
                emit("plan", f"bdense part {p}: {occ['n_blocks']} "
                     f"blocks, dense_frac={occ['dense_frac']}, "
                     f"mean_fill={occ['mean_fill']}",
                     console=config.verbose, part=p, **occ)
            if not self.data.bd_tabs:
                # changes the effective execution path — echoes
                # unconditionally, like the single-device fallback
                # (train/trainer.py)
                emit("plan", "bdense: no [128,128] tile reaches "
                     f"min_fill={config.bdense_min_fill} on any "
                     "partition — running the pure sectioned residual")
        if data is not None:
            # the autopilot / auto-resolution above may have settled on
            # a different halo/aggr_impl than the caller built tables
            # for — fail HERE with the mismatch, not mid-step with an
            # opaque shape error
            if config.halo == "ring" and not self.data.ring_idx:
                raise ValueError(
                    "injected data has no ring tables but the resolved "
                    "config wants halo='ring' (build it with "
                    "shard_dataset_local(..., halo='ring') or pass "
                    "memory/halo explicitly)")
            if config.halo != "ring":
                if config.aggr_impl in ("sectioned", "attn_flat8",
                                        "flat_sum", "bdense") \
                        and not self.data.sect_idx:
                    raise ValueError(
                        f"injected data has no sectioned/flat tables "
                        f"but the resolved aggr_impl is "
                        f"{config.aggr_impl!r} — build it with the "
                        f"same aggr_impl (note: attention/sum models "
                        f"at >=20M edges auto-route to the flat "
                        f"layouts)")
                if config.aggr_impl in ("sectioned", "bdense") \
                        and self.data.sect_idx \
                        and not self.data.sect_meta:
                    # flat8-built tables carry sect_idx but no
                    # sect_meta — aggregate_ell_sect would zip over
                    # () and return all-zero aggregations silently
                    raise ValueError(
                        f"injected data carries flat8-style tables "
                        f"(no section metadata) but the resolved "
                        f"aggr_impl is {config.aggr_impl!r} — build "
                        f"it with the same aggr_impl")
                if config.aggr_impl == "bdense" \
                        and self.data.bd_tabs \
                        and self.data.bd_group != config.bdense_group:
                    # a group mismatch would reduce across dst-tile
                    # boundaries (or trip the kernel's alignment
                    # check) — fail here with the cause, not mid-step
                    raise ValueError(
                        f"injected data was built with bdense_group="
                        f"{self.data.bd_group} but the config wants "
                        f"bdense_group={config.bdense_group} — build "
                        f"it with shard_dataset(..., bdense_group="
                        f"{config.bdense_group})")
                if config.aggr_impl == "bdense" \
                        and not self.data.bd_tabs \
                        and not self.data.bd_occupancy:
                    # sectioned-built data passes the two checks above
                    # (sect_idx + sect_meta both present) but would
                    # silently run residual-only; a genuine bdense
                    # build always records per-part occupancy, even
                    # when no tile qualifies and bd_tabs stays empty
                    raise ValueError(
                        "injected data carries no block-dense plan "
                        "but the resolved aggr_impl is 'bdense' — "
                        "build it with shard_dataset(..., "
                        "aggr_impl='bdense')")
                if config.aggr_impl == "bdense" \
                        and not self.data.bd_tabs:
                    # planned, but no [128,128] tile reached min_fill:
                    # the step runs the pure sectioned residual — same
                    # echo as the own-build path below
                    emit("plan", "bdense: injected plan has no dense "
                         "tiles — running the pure sectioned residual")
                if config.aggr_impl in ("ell", "pallas") \
                        and not self.data.ell_idx:
                    raise ValueError(
                        f"injected data has no ELL tables but the "
                        f"resolved aggr_impl is "
                        f"{config.aggr_impl!r} — build it with "
                        f"aggr_impl='ell'")
                if config.aggr_impl in ("segment", "blocked", "scan",
                                        "pallas_csr") and \
                        self.data.edge_dst.shape[-1] != \
                        self.pg.part_edges:
                    # table-built data carries 1-element edge stubs; a
                    # flat-edge impl would silently aggregate one fake
                    # 0->0 edge per part
                    raise ValueError(
                        f"injected data carries edge stubs "
                        f"(shape {tuple(self.data.edge_dst.shape)}) "
                        f"but the resolved aggr_impl "
                        f"{config.aggr_impl!r} reads the flat edge "
                        f"arrays — build the data with the same "
                        f"aggr_impl")
        if config.halo == "ring" and self.data.ring_idx:
            # startup echo like the reference's config print
            # (gnn.cc:48-60): make the SPMD padding cost visible, and
            # say out loud that ring tables subsume the aggr impl
            ratio = self.data.ring_padding_ratio
            emit("plan", f"halo=ring: P={self.pg.num_parts} "
                 f"pair_edges={self.data.ring_idx[0].shape[2]} "
                 f"padding_ratio="
                 f"{'?' if ratio is None else format(ratio, '.2f')} "
                 f"overlap={'on' if config.ring_overlap else 'off'} "
                 f"(aggr_impl={config.aggr_impl!r} unused: ring tables "
                 f"drive the aggregation)", console=config.verbose,
                 num_parts=self.pg.num_parts,
                 pair_edges=int(self.data.ring_idx[0].shape[2]),
                 padding_ratio=ratio,
                 ring_overlap=bool(config.ring_overlap))
        key = jax.random.PRNGKey(config.seed)
        self.key, init_key = jax.random.split(key)
        host_params = model.init_params(init_key, dtype=config.dtype)
        self.params = put_replicated(host_params, self.mesh)
        self.opt_state = put_replicated(adam_init(host_params),
                                        self.mesh)
        self.adam_cfg = AdamConfig(weight_decay=config.weight_decay)
        # observability: per-device modeled bytes for the compile
        # observer's modeled-vs-actual check, edges for edges/sec
        from ..train.trainer import modeled_step_bytes
        self._obs_edges = int(dataset.graph.num_edges)
        self._modeled_bytes = modeled_step_bytes(
            model, dataset, config, num_parts=num_parts)
        # dataset identity for the checkpoint config fingerprint; the
        # elastic half (num_parts + quantized plan shapes) reads
        # self.pg directly (utils/checkpoint.trainer_fingerprint)
        self._fp_dataset = {"V": int(dataset.graph.num_nodes),
                            "E": int(dataset.graph.num_edges)}
        self._build_steps()
        # split-quality record: per-part padded shapes + halo rows +
        # imbalance ratios, into the manifest (every run records the
        # split it actually trained on) and the costmodel event stream
        self._partition_stats = self._emit_partition_stats()
        from ..obs.manifest import run_manifest
        run_manifest(config=self.config, dataset=dataset, model=model,
                     num_parts=num_parts,
                     extra={"modeled_step_bytes": self._modeled_bytes,
                            "bd_occupancy": list(
                                self.data.bd_occupancy),
                            "partition": self._partition_stats},
                     console=config.verbose)
        from ..utils.profiling import EpochTimer, MetricsLog
        # annotate=True routes every phase span through
        # jax.profiler.TraceAnnotation so --profile-dir device
        # traces carry the same named phases as the timeline lanes
        self.timer = EpochTimer(
            annotate=bool(config.profile_dir))
        self.metrics_log = MetricsLog(config.metrics_path)

    def _build_data(self, pg) -> ShardedData:
        """Build + upload the sharded tables for ``pg`` with the
        trainer's resolved knobs — shared by __init__ and the
        repartitioning path (the halo/ring/sectioned/bdense tables are
        all rebuilt from the new bounds here)."""
        config = self.config
        return shard_dataset(
            self._dataset, pg, self.mesh,
            dtype=self.compute,
            aggr_impl=config.aggr_impl,
            halo=config.halo,
            sect_sub_w=config.sect_sub_w,
            sect_u16=config.sect_u16,
            bdense_min_fill=config.bdense_min_fill,
            bdense_a_budget=config.bdense_a_budget,
            bdense_group=config.bdense_group,
            aggr_fuse=self.model.num_fused_aggregates() > 0)

    def _step_auto(self) -> frozenset:
        """Mesh axes the shard_map steps leave to GSPMD: the model
        axis of a 2-D mesh (the step bodies stay 1-D parts programs —
        no in/out spec names MODEL_AXIS, and the partitioner threads
        the params' model sharding through them); empty on the 1-D
        mesh so the traced programs there are byte-identical to
        before.

        Empty for halo='ring' even on a 2-D mesh: under a partial-auto
        shard_map this jax/XLA only supports ``psum`` over the manual
        axes — ``all_gather``/``ppermute`` abort the SPMD partitioner
        (IsManualSubgroup check) and ``axis_index`` lowers to an
        unsupported PartitionId.  The gather/table paths route around
        it (a psum-based gather + the part index as a sharded
        argument, below), but the ring schedule is a ppermute loop by
        construction — so ring steps run fully manual over BOTH axes
        instead: every model replica runs the identical 1-D ring
        program and params/opt state stay model-sharded AT REST only
        (the jit in/out shardings still apply)."""
        return (frozenset({MODEL_AXIS})
                if self._mesh_model > 1 and self.config.halo != "ring"
                else frozenset())

    def _step_shardings(self):
        """Explicit per-arg jit shardings for the 2-D-mesh steps, or
        None on the 1-D mesh (where today's exact jit construction —
        and hence the rigs' program keys — must stay byte-identical).
        params/opt-state leaves pin their at-rest model sharding on
        BOTH sides of the step, which is what keeps donation legal
        under sharding (the donated input and the matching output
        must agree on layout); data/table args pin the parts split
        (a pytree-prefix sharding covers each nested table tuple);
        key/lr/metrics stay replicated."""
        if self._mesh_model <= 1:
            return None
        mesh, model = self.mesh, self._mesh_model

        def of(x):
            spec = model_shard_spec(np.shape(x), model)
            return NamedSharding(mesh, P(*spec) if spec else P())
        params_sh = jax.tree_util.tree_map(of, self.params)
        opt_sh = jax.tree_util.tree_map(of, self.opt_state)
        psh = NamedSharding(mesh, P(PARTS_AXIS))
        rep = NamedSharding(mesh, P())
        # the partial-auto steps take one extra trailing arg: the
        # parts-sharded partition-index vector (_step_auto explains
        # why axis_index cannot be used there)
        extra = (psh,) if self._step_auto() else ()
        return ((params_sh, opt_sh) + (psh,) * 14 + (rep, rep) + extra,
                (params_sh, opt_sh, rep),
                (params_sh,) + (psh,) * 14 + extra,
                (rep, psh))

    def _build_steps(self) -> None:
        """(Re)build the observed step functions.  Called at init and
        after a shape-changing repartition; a shape-preserving
        repartition keeps the existing ObservedJit objects so the
        steady-state AOT executables are reused (no recompile)."""
        from ..obs.compile_watch import ObservedJit
        config = self.config
        sharded = self._step_shardings()
        # partial-auto steps read their partition index from this
        # parts-sharded vector (one extra trailing arg) because
        # lax.axis_index is not lowerable under a GSPMD auto axis
        self._pids = None
        if self._step_auto():
            self._pids = jax.device_put(
                np.arange(self.pg.num_parts, dtype=np.int32),
                NamedSharding(self.mesh, P(PARTS_AXIS)))
        # the jax.jit calls sit lexically inside ObservedJit(jitfn=...)
        # — the sanctioned form roc-lint's bare-jit rule recognizes:
        # every step compiles through the observer
        if sharded is None:
            self._train_step = ObservedJit(
                jitfn=jax.jit(self._build_train_step(),
                              donate_argnums=(0, 1)),
                name="dist_train_step", donate_argnums=(0, 1),
                modeled_bytes=self._modeled_bytes,
                verbose=config.verbose)
        else:
            # 2-D mesh: pin the at-rest model sharding of params/opt
            # state on both sides of the step (the pjit per-arg
            # partition-spec + donation-vector pattern) so donation
            # stays legal under sharding — the PR-14
            # donation-under-sharding rule is the tripwire
            t_in, t_out, _, _ = sharded
            self._train_step = ObservedJit(
                jitfn=jax.jit(self._build_train_step(),
                              in_shardings=t_in, out_shardings=t_out,
                              donate_argnums=(0, 1)),
                name="dist_train_step", donate_argnums=(0, 1),
                modeled_bytes=self._modeled_bytes,
                verbose=config.verbose)
        # eval and predict share ONE compiled program: the eval step
        # returns (replicated metrics, SHARDED per-part logits) — the
        # logits already exist inside the step, so the extra output is
        # one [part_nodes, C] device buffer per eval, no collective,
        # and the program space loses a whole compiled program per
        # config (ISSUE 7).  evaluate() fetches only the metrics.
        if sharded is None:
            self._eval_step = ObservedJit(
                jitfn=jax.jit(self._build_eval_step()),
                name="dist_eval_step", verbose=config.verbose)
        else:
            _, _, e_in, e_out = sharded
            self._eval_step = ObservedJit(
                jitfn=jax.jit(self._build_eval_step(),
                              in_shardings=e_in, out_shardings=e_out),
                name="dist_eval_step", verbose=config.verbose)
        # multi-process predict needs the sharded logits replicated
        # before the host fetch; built lazily, never on rigs/tests
        self._predict_gather = None

    def _emit_partition_stats(self) -> dict:
        """Compute + emit the split-quality record for the CURRENT
        partition; returns the stats dict.  The O(E) feature pass is
        paid ONCE here — the φ matrix lands in ``_phi_cache`` so the
        rebalance hook never recomputes it for the same split."""
        from ..core.costmodel import (partition_static_stats,
                                      phi_matrix)
        self._phi_cache = phi_matrix(
            self.pg, bd_occupancy=self.data.bd_occupancy,
            **self._phi_flags)
        stats = partition_static_stats(
            self.pg, bd_occupancy=self.data.bd_occupancy,
            phi=self._phi_cache)
        emit("costmodel",
             f"partition={self._partition_method}: "
             f"P={stats['num_parts']} "
             f"part_nodes={stats['part_nodes']} "
             f"part_edges={stats['part_edges']} "
             f"edge imbalance (max/mean) {stats['edge_imbalance']:.2f} "
             f"node {stats['node_imbalance']:.2f}",
             console=self.config.verbose,
             method=self._partition_method, **stats)
        return stats

    # ---- online load rebalancing (core/costmodel.py) ----

    @staticmethod
    def _static_signature(pg, data: ShardedData):
        """Everything the compiled step specializes on: padded shape
        statics plus every table's (shape, dtype) and the static aux
        the GraphContext pytree carries.  Two partitions with equal
        signatures trace to the same executable, so the repartition
        path may keep the compiled step; any difference forces a
        rebuild (stale trace-time constants would otherwise
        mis-aggregate silently)."""
        def sh(x):
            if x is None:
                return None
            if isinstance(x, (tuple, list)):
                return tuple(sh(v) for v in x)
            if hasattr(x, "shape"):
                return (tuple(x.shape), str(x.dtype))
            return x
        return (pg.part_nodes, pg.part_edges, pg.num_parts,
                sh(data.feats), sh(data.labels), sh(data.mask),
                sh(data.edge_src), sh(data.edge_dst),
                sh(data.in_degree), sh(data.ell_idx),
                sh(data.ell_row_pos), sh(data.ell_row_id),
                sh(data.ring_idx), sh(data.sect_idx),
                sh(data.sect_sub_dst), sh(data.sect_meta),
                sh(data.bd_tabs), data.bd_vpad, data.bd_src_vpad,
                data.bd_group, sh(data.ell_w), sh(data.sect_w),
                sh(data.ring_w), sh(data.bd_scale))

    def _phi(self) -> np.ndarray:
        """Cached per-partition feature matrix for the CURRENT split
        (recomputed only after a repartition — the O(E) halo pass must
        not run every eval)."""
        if self._phi_cache is None:
            from ..core.costmodel import phi_matrix
            self._phi_cache = phi_matrix(
                self.pg, bd_occupancy=self.data.bd_occupancy,
                **self._phi_flags)
        return self._phi_cache

    def straggler_fields(self, m: Dict[str, float]) -> Dict[str, float]:
        """Per-epoch straggler attribution (run_epoch_loop folds this
        into every eval'd metrics record): which shard the partition
        cost model predicts slowest for the measured lap, and by how
        much over the mean — the SAME attribution
        :meth:`maybe_rebalance`'s ridge observation consumes (under
        lockstep SPMD only the straggler's time is observable, PR-5
        cost model).  Emits a ``costmodel`` straggler event with the
        full predicted per-shard cost vector so the merged timeline
        (obs/timeline.py) can render per-epoch attribution markers."""
        t = (m.get("epoch_ms")
             if m.get("compile_ms") is None else None)
        if not t:
            # a record that folded the compile lap in would attribute
            # compile seconds to a shard — same skip rule as the
            # rebalance observation below
            return {}
        # _phi() is the init-cached matrix (_emit_partition_stats pays
        # the O(E) feature pass once per split, rebalance on or off);
        # predict is a P x n_features dot — per-eval cost is trivial
        pred = self._costmodel.predict(self._phi())
        p = int(np.argmax(pred))
        mean = float(np.mean(pred))
        ratio = round(float(pred[p]) / mean, 4) if mean > 0 else None
        out: Dict[str, float] = {"straggler_part": p,
                                 "straggler_ratio": ratio}
        emit("costmodel",
             f"straggler: epoch {m.get('epoch')} lap {t:.1f} ms -> "
             f"part {p} (predicted {ratio}x the {self.pg.num_parts}-"
             f"shard mean)", console=False, kind="straggler",
             epoch=m.get("epoch"), measured_ms=float(t),
             num_parts=self.pg.num_parts,
             predicted_cost=[round(float(c), 3) for c in pred], **out)
        return out

    def maybe_rebalance(self, m: Dict[str, float]) -> bool:
        """Epoch-boundary rebalancing hook (run_epoch_loop calls this
        after every eval record): feed the measured lap to the online
        ridge model (attributed to the predicted-slowest shard — under
        lockstep SPMD only the straggler's time is observable), search
        a new split under the refitted weights, and repartition when
        the predicted max-shard gain clears the hysteresis threshold
        (``rebalance_gain``, at most ``rebalance_max`` times).
        Returns True when a repartition happened."""
        cfg = self.config
        if not cfg.rebalance or self._rebalances >= cfg.rebalance_max:
            return False
        from ..core.costmodel import (bounds_max_cost,
                                      cost_balanced_bounds)
        # a record carrying compile_ms may have folded the compile
        # lap into epoch_ms (run_epoch_loop's span<=0 branch at
        # eval_every=1, and again after a shape-changing repartition)
        # — a multi-second compile observed as a step time would
        # inflate the straggler's fitted weights by orders of
        # magnitude, so that eval's observation is skipped
        t = (m.get("epoch_ms")
             if m.get("compile_ms") is None else None)
        if t:
            phi = self._phi()
            p_star = int(np.argmax(self._costmodel.predict(phi)))
            self._costmodel.observe(phi[p_star], float(t))
            emit("costmodel",
                 f"observe: epoch {m.get('epoch')} lap {t:.1f} ms "
                 f"attributed to part {p_star}", console=False,
                 part=p_star, epoch_ms=float(t),
                 n_obs=self._costmodel.n_obs)
        wn, we = self._costmodel.search_weights(**self._phi_flags)
        row_ptr = self._dataset.graph.row_ptr
        nm = self.pg.node_multiple
        em = self.pg.edge_multiple
        cur = bounds_max_cost(row_ptr, self.pg.bounds, wn, we, nm, em)
        new_bounds = cost_balanced_bounds(
            row_ptr, self.pg.num_parts, node_multiple=nm,
            edge_multiple=em, weights=(wn, we))
        new = bounds_max_cost(row_ptr, new_bounds, wn, we, nm, em)
        gain = 1.0 - new / cur if cur > 0 else 0.0
        same = [tuple(b) for b in new_bounds] == \
            [tuple(b) for b in self.pg.bounds]
        if same or gain <= cfg.rebalance_gain:
            emit("costmodel",
                 f"rebalance: predicted max-shard gain {gain:.1%} "
                 f"<= threshold {cfg.rebalance_gain:.0%} — keeping "
                 f"the current split", console=False,
                 gain=round(gain, 4), threshold=cfg.rebalance_gain)
            return False
        self._repartition(new_bounds, gain=gain)
        return True

    def _repartition(self, bounds, gain: Optional[float] = None
                     ) -> None:
        """Rebuild PartitionedGraph + ShardedData for ``bounds`` and
        resume.  Quantization to the plan's node/edge multiples means
        an unchanged static signature reuses the compiled step (no
        recompile — the tables are runtime arguments); a changed one
        rebuilds the observed steps and re-barriers the compile lap.
        Replicated params/opt state are untouched: full-batch training
        makes the switch numerics-preserving."""
        from ..core.partition import materialize_plan, plan_from_bounds
        g = self._dataset.graph
        old_edges = self.pg.part_edges
        plan = plan_from_bounds(
            g.row_ptr, [tuple(b) for b in bounds], self.pg.num_parts,
            node_multiple=self.pg.node_multiple,
            edge_multiple=self.pg.edge_multiple)
        pg2 = materialize_plan(g, plan)
        data2 = self._build_data(pg2)
        recompile = (self._static_signature(pg2, data2)
                     != self._static_signature(self.pg, self.data))
        self.pg, self.data = pg2, data2
        self._phi_cache = None
        self._rebalances += 1
        if recompile:
            self._build_steps()
            # barrier the recompile lap out of the steady timing,
            # exactly like the first compile (run_epoch_loop)
            self._loop_compiled = False
        self._partition_stats = self._emit_partition_stats()
        emit("costmodel",
             f"repartition #{self._rebalances}: predicted max-shard "
             f"gain {'?' if gain is None else format(gain, '.1%')}, "
             f"part_edges {old_edges} -> {pg2.part_edges}, "
             + ("recompiling steps" if recompile else
                "quantized shapes unchanged — compiled step reused"),
             rebalance=self._rebalances,
             gain=None if gain is None else round(gain, 4),
             recompile=recompile, part_edges=pg2.part_edges,
             part_nodes=pg2.part_nodes)

    # ---- step builders ----

    def _psum_parts(self, t):
        """``lax.psum`` over PARTS_AXIS, elided on a single-part mesh:
        a size-1 manual axis still emits a cross-partition allreduce,
        which the partial-auto partitioner rejects (1xM meshes) — and
        the sum over one part is the identity anyway."""
        if self.pg.num_parts == 1:
            return t
        return lax.psum(t, PARTS_AXIS)

    def _gctx(self) -> GraphContext:
        """GraphContext for *inside* the shard_map body (local blocks)."""
        from ..train.trainer import resolve_head_chunk
        pgr = self.pg
        return GraphContext(
            head_chunk=resolve_head_chunk(self.config, pgr.part_nodes),
            edge_src=None, edge_dst=None, in_degree=None,  # filled per-call
            num_rows=pgr.part_nodes,
            gathered_rows=pgr.num_parts * pgr.part_nodes,
            gather_features=lambda x: lax.all_gather(
                x, PARTS_AXIS, axis=0, tiled=True),
            psum=self._psum_parts,
            aggr_impl=self.config.aggr_impl,
            chunk=self.config.chunk,
            symmetric=self.symmetric,
            halo=self.config.halo,
            ring_overlap=self.config.ring_overlap,
            sect_meta=self.data.sect_meta,
            bd_vpad=self.data.bd_vpad,
            bd_src_vpad=self.data.bd_src_vpad,
            # the DATA's group, validated == config at init: the
            # tables define what the kernel may assume
            bd_group=self.data.bd_group,
        )

    def _local_gctx(self, edge_src, edge_dst, in_degree, ell_idx,
                    ell_row_pos, ell_row_id, ring_idx, sect_idx,
                    sect_sub_dst, bd_tabs=(),
                    fuse_tabs=((), (), (), ()),
                    pid=None) -> GraphContext:
        """Local-block GraphContext for a shard_map body: slice the
        parts axis off every table.  attn_flat8 and flat_sum carry
        their single-section uniform tables in the sect slots
        (ShardedData docstring) and route them to the flat8 fields
        the builder reads (flat_sum's baked weight tables ride the
        sect_w slot -> flat8_w); bdense carries its residual there
        and its dense tiles in bd_tabs.  ``fuse_tabs`` = (ell_w,
        sect_w, ring_w, bd_scale) — the baked fused-normalization
        weights (empty tuples when unfused).

        ``pid`` (partial-auto 2-D steps only) is this block's traced
        partition index; it swaps ``gather_features`` for the
        psum-based halo gather — ``lax.all_gather`` over a manual
        axis aborts the SPMD partitioner when a GSPMD auto axis is
        present (_step_auto), but a psum of disjointly-placed local
        blocks is the same gathered matrix, and psum IS supported
        there.  ~2x the all-gather bytes on ICI; only the 2-D path
        pays it."""
        flat = self.config.aggr_impl in ("attn_flat8", "flat_sum")
        ell_w, sect_w, ring_w, bd_scale = fuse_tabs
        extra = {}
        if pid is not None:
            num_parts = self.pg.num_parts

            def gather_psum(x):
                if num_parts == 1:
                    return x        # single part: gather is identity
                buf = jnp.zeros((num_parts,) + x.shape, x.dtype)
                buf = lax.dynamic_update_index_in_dim(buf, x, pid, 0)
                buf = lax.psum(buf, PARTS_AXIS)
                return buf.reshape((num_parts * x.shape[0],)
                                   + x.shape[1:])
            extra["gather_features"] = gather_psum
        return dc_replace(
            self._gctx(), edge_src=edge_src, edge_dst=edge_dst,
            in_degree=in_degree,
            ell_idx=tuple(a[0] for a in ell_idx),
            ell_row_pos=ell_row_pos[0],
            ell_row_id=tuple(a[0] for a in ell_row_id),
            ring_idx=tuple(a[0] for a in ring_idx),
            sect_idx=() if flat else tuple(a[0] for a in sect_idx),
            sect_sub_dst=(() if flat
                          else tuple(a[0] for a in sect_sub_dst)),
            # halo='ring' uploads empty sect stubs (the ring tables
            # fully describe the aggregation) — the flat8 fields must
            # stay None so the builder routes to ring_aggregate
            flat8_idx=sect_idx[0][0] if flat and sect_idx else None,
            flat8_dst=(sect_sub_dst[0][0]
                       if flat and sect_sub_dst else None),
            flat8_w=(sect_w[0][0]
                     if flat and sect_w else None),
            bd_a=bd_tabs[0][0] if bd_tabs else None,
            bd_src=bd_tabs[1][0] if bd_tabs else None,
            bd_dst=bd_tabs[2][0] if bd_tabs else None,
            ell_w=tuple(a[0] for a in ell_w),
            sect_w=() if flat else tuple(a[0] for a in sect_w),
            ring_w=ring_w[0][0] if ring_w else None,
            bd_scale=tuple(a[0] for a in bd_scale),
            **extra)

    def _build_train_step(self):
        mesh = self.mesh
        spec_p = P(PARTS_AXIS)
        spec_r = P()
        auto = self._step_auto()

        # the partial-auto variant takes one extra trailing arg: the
        # parts-sharded partition-index vector (``*pids``), standing
        # in for lax.axis_index which has no lowering under a GSPMD
        # auto axis (_step_auto).  The 1-D signature — and hence the
        # rigs' program keys — is untouched.
        def step(params, opt_state, feats, labels, mask, edge_src,
                 edge_dst, in_degree, ell_idx, ell_row_pos, ell_row_id,
                 ring_idx, sect_idx, sect_sub_dst, bd_tabs, fuse_tabs,
                 key, lr, *pids):
            # local blocks arrive with the parts axis collapsed to 1
            feats, labels, mask = feats[0], labels[0], mask[0]
            pid = pids[0][0] if pids else None
            gctx = self._local_gctx(
                edge_src[0], edge_dst[0], in_degree[0], ell_idx,
                ell_row_pos, ell_row_id, ring_idx, sect_idx,
                sect_sub_dst, bd_tabs, fuse_tabs, pid=pid)
            part_key = jax.random.fold_in(
                key, lax.axis_index(PARTS_AXIS) if pid is None else pid)

            def local_loss(p):
                # mixed precision: fp32 master params cast per step;
                # astype's vjp keeps grads (and the psum) in fp32
                logits = self.model.apply(cast_floats(p, self.compute),
                                          feats, gctx, key=part_key,
                                          train=True)
                return masked_softmax_cross_entropy(logits, labels, mask)

            if self.config.remat:
                local_loss = jax.checkpoint(
                    local_loss, policy=remat_policy(self.config))
            local_l, grads = jax.value_and_grad(local_loss)(params)
            # the reference's replica-sum gradient allreduce
            # (optimizer_kernel.cu:88-94) as an ICI psum
            grads = self._psum_parts(grads)
            loss = self._psum_parts(local_l)
            params, opt_state = adam_update(params, grads, opt_state, lr,
                                            self.adam_cfg)
            return params, opt_state, loss

        return _shard_map(
            step, mesh=mesh,
            in_specs=(spec_r, spec_r, spec_p, spec_p, spec_p, spec_p,
                      spec_p, spec_p, spec_p, spec_p, spec_p, spec_p,
                      spec_p, spec_p, spec_p, spec_p, spec_r, spec_r)
            + ((spec_p,) if auto else ()),
            out_specs=(spec_r, spec_r, spec_r),
            auto=auto)

    def _local_forward(self, params, feats, edge_src, edge_dst,
                       in_degree, ell_idx, ell_row_pos, ell_row_id,
                       ring_idx, sect_idx, sect_sub_dst, bd_tabs,
                       fuse_tabs=((), (), (), ()), pid=None):
        """Shared shard_map body: slice the parts axis off the local
        blocks, assemble the local GraphContext, run the inference
        forward — eval (adds metrics+psum) and predict (adds
        all_gather) both build on this, so the gctx wiring exists in
        ONE place.  ``pid`` threads the partial-auto partition index
        through to :meth:`_local_gctx`."""
        feats = feats[0]
        gctx = self._local_gctx(
            edge_src[0], edge_dst[0], in_degree[0], ell_idx,
            ell_row_pos, ell_row_id, ring_idx, sect_idx, sect_sub_dst,
            bd_tabs, fuse_tabs, pid=pid)
        return self.model.apply(cast_floats(params, self.compute),
                                feats, gctx, key=None, train=False)

    def _build_eval_step(self):
        mesh = self.mesh
        spec_p = P(PARTS_AXIS)
        spec_r = P()
        auto = self._step_auto()

        def step(params, feats, labels, mask, *graph_args):
            pid = None
            if auto:
                # trailing parts-sharded partition-index vector, same
                # contract as the train step
                *graph_args, pids = graph_args
                pid = pids[0]
            logits = self._local_forward(params, feats, *graph_args,
                                         pid=pid)
            m = perf_metrics(logits, labels[0], mask[0])
            # (replicated metrics, sharded logits): predict() reuses
            # this program's logits output — no second compile, no
            # collective added to the eval path
            return jax.tree_util.tree_map(self._psum_parts,
                                          m), logits

        return _shard_map(
            step, mesh=mesh,
            in_specs=(spec_r, spec_p, spec_p, spec_p, spec_p, spec_p,
                      spec_p, spec_p, spec_p, spec_p, spec_p, spec_p,
                      spec_p, spec_p, spec_p)
            + ((spec_p,) if auto else ()),
            out_specs=(spec_r, spec_p),
            auto=auto)

    # ---- loop ----

    def train(self, epochs: Optional[int] = None) -> List[Dict[str, float]]:
        from ..train.trainer import run_epoch_loop

        def do_step(step_key, lr):
            # read self.data PER STEP, not once per train() call — an
            # epoch-boundary repartition swaps the sharded tables
            # mid-run and the next step must train on the new split
            d = self.data
            extra = () if self._pids is None else (self._pids,)
            self.params, self.opt_state, _ = self._train_step(
                self.params, self.opt_state, d.feats, d.labels,
                d.mask, d.edge_src, d.edge_dst, d.in_degree,
                d.ell_idx, d.ell_row_pos, d.ell_row_id, d.ring_idx,
                d.sect_idx, d.sect_sub_dst, d.bd_tabs,
                (d.ell_w, d.sect_w, d.ring_w, d.bd_scale),
                step_key, lr, *extra)

        return run_epoch_loop(self, epochs, do_step, self.evaluate)

    def sync(self) -> None:
        """Block until all dispatched train steps have finished.  Uses
        the fetch-based barrier: ``block_until_ready`` does not reliably
        synchronize under the axon TPU relay (utils/profiling.py)."""
        from ..utils.profiling import sync
        sync(self.params)

    def _run_eval_step(self):
        d = self.data
        extra = () if self._pids is None else (self._pids,)
        return self._eval_step(
            self.params, d.feats, d.labels, d.mask, d.edge_src,
            d.edge_dst, d.in_degree, d.ell_idx, d.ell_row_pos,
            d.ell_row_id, d.ring_idx, d.sect_idx, d.sect_sub_dst,
            d.bd_tabs, (d.ell_w, d.sect_w, d.ring_w, d.bd_scale),
            *extra)

    def _eval(self, epoch: int) -> Dict[str, float]:
        # fetch ONLY the metrics: the shared eval/predict program also
        # outputs the sharded logits, which stay on device during
        # training evals
        m_dev, _ = self._run_eval_step()
        m = summarize_metrics(jax.device_get(m_dev))
        m["epoch"] = epoch
        return m

    def evaluate(self) -> Dict[str, float]:
        return self._eval(-1)

    def _padded_rows_of(self, node_ids) -> np.ndarray:
        """Original vertex ids → rows of the concatenated padded
        logits ([P * part_nodes, C] order): part ``p`` holds global
        range ``bounds[p]`` at local offset ``g - node_offset[p]``."""
        pg = self.pg
        ids = np.asarray(node_ids, dtype=np.int64).ravel()
        if ids.size and (ids.min() < 0 or ids.max() >= pg.num_nodes):
            raise ValueError(
                f"node ids out of range [0, {pg.num_nodes})")
        offs = np.asarray(pg.node_offset, dtype=np.int64)
        part = np.searchsorted(offs, ids, side="right") - 1
        return (part * pg.part_nodes + ids - offs[part]).astype(
            np.int32)

    def predict(self, node_ids=None) -> np.ndarray:
        """[V, C] inference-mode logits in ORIGINAL vertex order —
        the EVAL program's sharded logits output (one compiled program
        serves evaluate and predict; the old standalone predict step
        was a whole extra compile per config).  Single-controller
        meshes fetch the sharded result directly; multi-process meshes
        replicate it first through a tiny lazily-built all_gather
        program (a P('parts')-sharded device_get would touch
        non-addressable shards there) — rigs and tests never compile
        it.

        ``node_ids`` fetches only a row subset: the ids map to padded
        shard coordinates host-side and the rows are read PER SHARD
        from the addressable shard buffers — no device-side gather.
        The previous form dispatched ``jnp.take`` on the
        P('parts')-sharded logits, which made GSPMD all-gather the
        full [V, C] logits onto EVERY device before taking n rows —
        the dist-eval-gather full-width-materialization site the
        sharding auditor (analysis/sharding_lint.py) exists to
        catch; now only the shards holding requested rows cross
        device→host, O(V_p) each, and the request path adds no
        collective and no compiled program.  Under multi-process
        SPMD the rows are read from the replicated copy instead
        (non-addressable shards)."""
        _, logits = self._run_eval_step()
        if jax.process_count() > 1:
            if self._predict_gather is None:
                from ..obs.compile_watch import ObservedJit
                self._predict_gather = ObservedJit(
                    jitfn=jax.jit(self._build_predict_gather()),
                    name="dist_predict_gather",
                    verbose=self.config.verbose)
            logits = self._predict_gather(logits)
        if node_ids is not None:
            rows = self._padded_rows_of(node_ids)
            if jax.process_count() == 1:
                picked = self._rows_from_shards(logits, rows)
                if picked is not None:
                    return picked
            flat = np.asarray(jax.device_get(logits)).reshape(
                self.pg.padded_num_nodes, -1)
            return flat[rows]
        arr = np.asarray(jax.device_get(logits))
        arr = arr.reshape(self.pg.num_parts, self.pg.part_nodes, -1)
        return unpad_nodes(arr, self.pg)

    def _rows_from_shards(self, logits,
                          rows: np.ndarray) -> Optional[np.ndarray]:
        """Row subset of the P('parts')-sharded padded logits read
        per-shard: only shards that hold a requested row are fetched
        (O(V_p * C) device→host each), and nothing materializes on
        device.  None when the shard layout is not the expected 1-D
        padded-part split (caller falls back to a whole-array
        device_get — still collective-free)."""
        pn = self.pg.part_nodes
        C = int(logits.shape[-1])
        rows = np.asarray(rows, dtype=np.int64)
        want = set((rows // pn).tolist())
        hosts: Dict[int, np.ndarray] = {}
        try:
            for sh in logits.addressable_shards:
                idx = sh.index[0]
                start = idx.start or 0
                data = np.asarray(sh.data).reshape(-1, C)
                if data.shape[0] != pn or start % pn:
                    return None
                part = start // pn
                if part in want:
                    hosts[part] = data
        except (AttributeError, TypeError, IndexError):
            return None
        if not want.issubset(hosts):
            return None
        out = np.empty((rows.size, C), dtype=logits.dtype)
        for p in want:
            sel = (rows // pn) == p
            out[sel] = hosts[p][rows[sel] % pn]
        return out

    def _build_predict_gather(self):
        mesh = self.mesh
        spec_p = P(PARTS_AXIS)
        spec_r = P()

        def step(logits):
            # local [part_nodes, C] -> replicated [P, part_nodes, C]
            return lax.all_gather(logits, PARTS_AXIS, axis=0)

        # fully manual even on a 2-D mesh (NO auto axis): the logits
        # carry no model sharding, and all_gather over a manual axis
        # aborts the partitioner when an auto axis is present
        # (_step_auto) — manual over both axes just replicates the
        # gather across model replicas
        return _shard_map(step, mesh=mesh, in_specs=spec_p,
                          out_specs=spec_r)
