"""Multi-device execution: mesh construction and the distributed
trainer (``distributed.py``), ring halo exchange (``ring.py``),
multi-host bring-up (``multihost.py``).

This ``__init__`` must stay import-light: it holds only the constants
every submodule (and ``models/builder.py``) needs without a cycle.
"""

# THE name of the partition mesh axis.  Every collective in the step
# bodies reduces/gathers/permutes over this axis and the SPMD
# collective verifier (analysis/collective_lint.py) checks the traced
# eqns' axis names against the mesh built from it — a typo'd axis
# name is a trace-time error single-process but a hang on a real
# multi-host mesh, so the name lives in ONE place (here, where
# ring.py / multihost.py / models/builder.py can all import it
# cycle-free; distributed.py re-exports it).
PARTS_AXIS = "parts"

# THE name of the feature/model mesh axis of the ``(parts, model)``
# 2-D mesh (ROADMAP: vertex shards x feature shards).  Both trainers
# build it when ``TrainConfig.mesh`` names a model dimension > 1:
# params and Adam moments live model-sharded at rest
# (:func:`model_shard_spec` picks the dim), the step bodies stay
# 1-D shard_map programs (the model axis rides through as a GSPMD
# ``auto`` axis), and the sharding auditor (analysis/sharding_lint.py)
# + the memory model's per-axis attribution (core/memory.py) check
# the same ONE spelling.
MODEL_AXIS = "model"


def candidate_mesh_shapes(num_devices: int = 8):
    """The ``(parts, model)`` shapes the mesh-portability audit
    models on a ``num_devices``-wide rig: every factorization with
    both factors >= 1, parts-major (1x8, 2x4, 4x2, 8x1 on the
    8-virtual-device CPU rig; the degenerate all-parts shape is
    today's 1-D mesh and anchors the comparison).  Pure arithmetic —
    importable without jax."""
    return [(p, num_devices // p) for p in range(1, num_devices + 1)
            if num_devices % p == 0]


def mesh_axes(shape) -> dict:
    """``{axis-name: size}`` for a ``(parts, model)`` shape tuple —
    the one place the positional shape meets the axis names."""
    parts, model = shape
    return {PARTS_AXIS: int(parts), MODEL_AXIS: int(model)}


def model_shard_spec(shape, model: int):
    """Per-dim mesh-axis names (None | MODEL_AXIS) for ONE buffer of
    the given shape on a mesh with ``model``-wide feature axis, or
    None when no dim divides.

    THE single derivation of "which dim of this leaf carries features"
    — scanned LAST dim first (features are trailing in every param /
    moment / activation layout here), first dim whose size is a
    positive multiple of ``model`` wins.  ``put_replicated``, the
    step in/out shardings, the auditor's ledger, and checkpoint
    restore all consume this one function so they cannot drift.
    Pure shape arithmetic — importable without jax."""
    model = int(model)
    if model <= 1:
        return None
    for ax in range(len(shape) - 1, -1, -1):
        d = int(shape[ax])
        if d >= model and d % model == 0:
            return tuple([None] * ax + [MODEL_AXIS]
                         + [None] * (len(shape) - ax - 1))
    return None
