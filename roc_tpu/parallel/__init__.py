"""Multi-device execution: mesh construction and the distributed
trainer (``distributed.py``), ring halo exchange (``ring.py``),
multi-host bring-up (``multihost.py``).

This ``__init__`` must stay import-light: it holds only the constants
every submodule (and ``models/builder.py``) needs without a cycle.
"""

# THE name of the partition mesh axis.  Every collective in the step
# bodies reduces/gathers/permutes over this axis and the SPMD
# collective verifier (analysis/collective_lint.py) checks the traced
# eqns' axis names against the mesh built from it — a typo'd axis
# name is a trace-time error single-process but a hang on a real
# multi-host mesh, so the name lives in ONE place (here, where
# ring.py / multihost.py / models/builder.py can all import it
# cycle-free; distributed.py re-exports it).
PARTS_AXIS = "parts"
