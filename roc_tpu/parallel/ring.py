"""Ring halo exchange: blocked all-gather overlapped with aggregation.

The reference materializes the WHOLE node-feature region on every GPU
for each aggregation (``scattergather.cc:70-72``; explicitly
``ncclAllGather`` in the vestigial ``gnn_kernel.cu:65-78``), which caps
graph size at one device's memory.  SURVEY §7 flags the TPU fix: a ring
schedule that never holds more than one shard's features at a time.

Mechanism (the ring-attention communication shape, with CSR aggregation
as the local op): each device keeps a rotating buffer of one shard's
features.  At ring step k, device p holds shard ``(p - k) mod P``; it
aggregates the local edges whose *sources* live in that shard (a
per-source-shard ELL table built at partition time) into its running
output, while ``lax.ppermute`` rotates the buffer one hop around the ICI
ring.  After P steps every edge has been applied exactly once and peak
memory is O(V/P · F) instead of O(V · F).

The per-(partition, source-shard) edge groups are stored as stacked ELL
tables with uniform shapes across all pairs (SPMD requires identical
per-device shapes); padding cost is bounded by the densest pair, which
is modest for edge-balanced partitions of real graphs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.ell import EllTable, build_ell, stack_ell
from ..core.partition import PartitionedGraph
from ..ops.aggregate import aggregate_ell


@dataclass
class RingTables:
    """Stacked per-(partition, source-shard) ELL tables.

    idx: per width bucket, int32 [P, S, rows_b, width_b]; source ids are
      *local to the source shard* (dummy = part_nodes, the zero row
      appended to the rotating buffer).
    row_pos: int32 [P, S, part_nodes].
    """

    widths: Tuple[int, ...]
    idx: Tuple[np.ndarray, ...]
    row_pos: np.ndarray


def build_ring_tables(pg: PartitionedGraph,
                      min_width: int = 4) -> RingTables:
    """Split each partition's local CSR by source shard and build the
    uniform stacked ELL tables the ring step indexes by shard."""
    P = pg.num_parts
    offsets = np.asarray([l for l, _ in pg.bounds] + [pg.num_nodes],
                         dtype=np.int64)
    starts = np.minimum(offsets[:P], pg.num_nodes)
    per_pair: List[dict] = []
    for p in range(P):
        n = int(pg.real_nodes[p])
        ptr = pg.part_row_ptr[p, :n + 1].astype(np.int64)
        col = pg.part_col_idx[p]  # global src ids; padding == num_nodes
        dst = np.repeat(np.arange(n, dtype=np.int64), np.diff(ptr))
        col_real = col[:int(ptr[n])].astype(np.int64)
        # source shard of each edge
        src_shard = np.searchsorted(offsets[1:P + 1], col_real,
                                    side="right")
        for s in range(P):
            sel = src_shard == s
            d, c = dst[sel], col_real[sel] - starts[s]
            # rebuild a local CSR over (d, c); d is already sorted
            counts = np.bincount(d, minlength=n)
            ptr_s = np.zeros(n + 1, dtype=np.int64)
            np.cumsum(counts, out=ptr_s[1:])
            per_pair.append(build_ell(ptr_s, c.astype(np.int32),
                                      min_width=min_width))
    table = stack_ell(per_pair, pg.part_nodes, dummy=pg.part_nodes)
    idx = tuple(a.reshape(P, P, *a.shape[1:]) for a in table.idx)
    row_pos = table.row_pos.reshape(P, P, pg.part_nodes)
    return RingTables(widths=table.widths, idx=idx, row_pos=row_pos)


def ring_aggregate(x: jax.Array, ring_idx, ring_row_pos: jax.Array,
                   axis_name: str = "parts") -> jax.Array:
    """SPMD ring aggregation (call inside shard_map).

    x: [part_nodes, F] this device's shard.
    ring_idx: tuple of int32 [S, rows_b, width_b] (this device's slice).
    ring_row_pos: int32 [S, part_nodes].
    Returns [part_nodes, F] = sum aggregation over ALL global edges whose
    destination is local.
    """
    P = ring_row_pos.shape[0]
    n, F = x.shape
    me = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % P) for i in range(P)]

    def step(k, carry):
        buf, out = carry
        src_shard = jax.numpy.mod(me - k, P)
        idx_k = tuple(
            lax.dynamic_index_in_dim(a, src_shard, axis=0, keepdims=False)
            for a in ring_idx)
        pos_k = lax.dynamic_index_in_dim(ring_row_pos, src_shard, axis=0,
                                         keepdims=False)
        buf_ext = jnp.concatenate(
            [buf, jnp.zeros((1, F), dtype=buf.dtype)], axis=0)
        out = out + aggregate_ell(buf_ext, idx_k, pos_k, n)
        # rotate for the next step (skipped work on the last step is
        # harmless; keeping it unconditional lets XLA overlap the
        # permute with this step's aggregation)
        buf = lax.ppermute(buf, axis_name, perm)
        return buf, out

    out0 = jnp.zeros((n, F), dtype=x.dtype)
    _, out = lax.fori_loop(0, P, step, (x, out0))
    return out
