"""Ring halo exchange: blocked all-gather overlapped with aggregation.

The reference materializes the WHOLE node-feature region on every GPU
for each aggregation (``scattergather.cc:70-72``; explicitly
``ncclAllGather`` in the vestigial ``gnn_kernel.cu:65-78``), which caps
graph size at one device's memory.  SURVEY §7 flags the TPU fix: a ring
schedule that never holds more than one shard's features at a time.

Mechanism (the ring-attention communication shape, with CSR aggregation
as the local op): each device keeps a rotating buffer of one shard's
features.  At ring step k, device p holds shard ``(p - k) mod P``; it
aggregates the local edges whose *sources* live in that shard into its
running output, while ``lax.ppermute`` rotates the buffer one hop
around the ICI ring.  After P steps every edge has been applied exactly
once and peak memory is O(V/P * F) instead of O(V * F).

Per-(partition, source-shard) edge groups are stored as FLAT dst-sorted
edge lists padded to the max pair edge count — SPMD needs identical
shapes on every device, and for edge-balanced partitions of power-law
graphs this pads ~1.5-1.7x (the padding ratio is computed and stored on
the table; a uniform per-pair ELL layout was measured at ~8x on the
same graphs and replaced by this one).  The per-step local op is a
chunked gather + sorted scatter-add — padding edges gather the zero row
into the last output row, so they are numeric no-ops.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.partition import PartitionedGraph
from . import PARTS_AXIS


def ring_hop_perm(num_shards: int):
    """THE named hop schedule: one step of the ring rotation as a
    ``lax.ppermute`` permutation — ``[(i, (i+1) % S)]``, a single
    cycle covering the full axis.  :func:`ring_aggregate` issues
    exactly this permutation every hop, and the SPMD collective
    verifier (``analysis/collective_lint.py``) recovers and checks the
    traced ``ppermute`` eqns against it: any other shape (a two-cycle,
    a partial cover) deadlocks or drops shards at P>=2 on real
    hardware, where no trace-time error exists to catch it."""
    return [(i, (i + 1) % num_shards) for i in range(num_shards)]


@dataclass
class RingTables:
    """Flat per-(partition, source-shard) edge lists, uniform shapes.

    src: int32 [P, S, pair_edges] source ids *local to the source
      shard* (dummy = part_nodes, the zero row appended to the rotating
      buffer).
    dst: int32 [P, S, pair_edges] local destination rows, sorted
      ascending within each pair; padding uses ``part_nodes - 1`` (keeps
      the sort; the gathered zero row adds nothing).
    padding_ratio: padded slots / real edges (>= 1.0), reported so the
      memory-policy layer can echo the cost of SPMD uniformity.
    """

    src: np.ndarray
    dst: np.ndarray
    padding_ratio: float

    @property
    def pair_edges(self) -> int:
        return int(self.src.shape[2])


def build_ring_pairs(pg: PartitionedGraph, p: int,
                     col: Optional[np.ndarray] = None) -> dict:
    """Partition ``p``'s per-source-shard edge lists, built from ``p``'s
    OWN column data only: ``{s: (src_local_to_shard_s, dst_local)}``
    with dst sorted ascending within each pair.  ``col`` overrides the
    column array (multi-host partition-local loading passes the slice
    it read; global ids, NOT padded-remapped); default reads
    ``pg.part_col_idx``."""
    P = pg.num_parts
    offsets = np.asarray([l for l, _ in pg.bounds] + [pg.num_nodes],
                         dtype=np.int64)
    starts = np.minimum(offsets[:P], pg.num_nodes)
    n = int(pg.real_nodes[p])
    ptr = pg.part_row_ptr[p, :n + 1].astype(np.int64)
    if col is None:
        col = pg.part_col_idx[p]
    col = np.asarray(col[:int(ptr[n])], dtype=np.int64)
    dst = np.repeat(np.arange(n, dtype=np.int64), np.diff(ptr))
    shard = np.searchsorted(offsets[1:P + 1], col, side="right")
    pairs = {}
    for s in range(P):
        sel = shard == s
        # dst is globally sorted, so the stable mask keeps it sorted
        pairs[s] = ((col[sel] - starts[s]).astype(np.int32),
                    dst[sel].astype(np.int32))
    return pairs


def pack_ring_part(pairs: dict, num_shards: int, pair_edges: int,
                   part_nodes: int):
    """One partition's ``[S, pair_edges]`` (src, dst) tables from its
    pair lists: padding sources point at the dummy zero row
    (``part_nodes``), padding destinations at the last row (keeps the
    dst sort; the gathered zero adds nothing)."""
    src = np.full((num_shards, pair_edges), part_nodes, dtype=np.int32)
    dst = np.full((num_shards, pair_edges), part_nodes - 1,
                  dtype=np.int32)
    for s, (c, d) in pairs.items():
        src[s, :c.shape[0]] = c
        dst[s, :d.shape[0]] = d
    return src, dst


def round_pair_edges(max_pair: int) -> int:
    """Pad the pair width to an 8-multiple so chunking divides evenly."""
    return -(-max(max_pair, 1) // 8) * 8


def build_ring_tables(pg: PartitionedGraph) -> RingTables:
    """Split each partition's local CSR by source shard into flat
    dst-sorted edge lists padded to the max pair size (single-host
    form; the multi-host path builds per-partition pairs locally and
    agrees on ``pair_edges`` with an O(P) collective —
    parallel/multihost.py)."""
    P = pg.num_parts
    all_pairs = {p: build_ring_pairs(pg, p) for p in range(P)}
    max_pair = max((d.shape[0] for pairs in all_pairs.values()
                    for _, d in pairs.values()), default=1)
    total_real = sum(d.shape[0] for pairs in all_pairs.values()
                     for _, d in pairs.values())
    pair_edges = round_pair_edges(max_pair)
    src = np.empty((P, P, pair_edges), dtype=np.int32)
    dst = np.empty((P, P, pair_edges), dtype=np.int32)
    for p, pairs in all_pairs.items():
        src[p], dst[p] = pack_ring_part(pairs, P, pair_edges,
                                        pg.part_nodes)
    ratio = (P * P * pair_edges) / max(total_real, 1)
    return RingTables(src=src, dst=dst, padding_ratio=float(ratio))


def ring_weight_tables(pg: PartitionedGraph, rt: RingTables,
                       d_global: np.ndarray) -> np.ndarray:
    """Baked fused-normalization weights for the ring tables
    (:func:`ring_aggregate` ``weights``): fp32 ``[P, S, pair_edges]``
    with ``w = d[dst_global] * d[src_global]`` — the per-edge entries
    of ``D^-1/2 A D^-1/2`` in ring layout, so the fused aggregation
    runs the rotation with ZERO runtime normalization.  Padding slots
    (dummy source id ``part_nodes``) weigh 0; ``d_global`` is the
    inv-sqrt in-degree vector over ORIGINAL vertex ids [V]."""
    P, S, pe = rt.src.shape
    offsets = np.asarray([l for l, _ in pg.bounds] + [pg.num_nodes],
                         dtype=np.int64)
    starts = np.minimum(offsets[:P], pg.num_nodes)
    d = np.asarray(d_global, dtype=np.float32)
    w = np.zeros((P, S, pe), dtype=np.float32)
    for p in range(P):
        # padding dst slots use part_nodes - 1 (may exceed the real
        # rows); clip for the lookup — the src dummy mask zeroes them
        dstg = np.minimum(starts[p] + rt.dst[p].astype(np.int64),
                          pg.num_nodes - 1)
        for s in range(S):
            srcl = rt.src[p, s].astype(np.int64)
            real = srcl < pg.part_nodes
            srcg = np.minimum(starts[s] + srcl, pg.num_nodes - 1)
            w[p, s] = np.where(real, d[dstg[s]] * d[srcg], 0.0)
    return w


def ring_aggregate(x: jax.Array, ring_src: jax.Array,
                   ring_dst: jax.Array, axis_name: str = PARTS_AXIS,
                   edge_chunk: int = 1 << 17,
                   weights: Optional[jax.Array] = None,
                   overlap: bool = True) -> jax.Array:
    """SPMD ring aggregation (call inside shard_map).

    x: [part_nodes, F] this device's shard.
    ring_src/ring_dst: int32 [S, pair_edges] (this device's slice).
    Returns [part_nodes, F] = sum aggregation over ALL global edges
    whose destination is local.  The per-step local op chunks the pair's
    edges (bounding the [C, F] gather transient) and scatter-adds with
    ``indices_are_sorted`` (dst-sorted within every pair by
    construction).

    ``weights`` (optional): [S, pair_edges] per-edge weights
    (:func:`ring_weight_tables` — the baked fused-norm scales),
    applied to the gathered rows in-register before the scatter-add.

    ``overlap`` (default True): double-buffered hop schedule — the
    ``ppermute`` of the incoming buffer is ISSUED before the
    scatter-accumulate of the current one.  The two are
    data-independent once double-buffered, so XLA's latency-hiding
    scheduler can run the collective under the compute (the
    reference's interconnect/compute overlap, ICI edition).
    ``overlap=False`` keeps the strictly sequential
    compute-then-permute form: the parity/measurement reference —
    both orders produce identical values (the rotation never reads
    the accumulator), so this is a schedule knob, not a numerics one.
    """
    S, pair_edges = ring_src.shape
    n, F = x.shape
    me = lax.axis_index(axis_name)
    perm = ring_hop_perm(S)
    C = min(edge_chunk, pair_edges)
    while pair_edges % C:
        C //= 2
    n_chunks = pair_edges // C

    def local_pair(out, buf_ext, src_e, dst_e, w_e):
        xs = (src_e.reshape(n_chunks, C), dst_e.reshape(n_chunks, C))
        if w_e is not None:
            xs += (w_e.reshape(n_chunks, C),)

        def chunk_body(out, args):
            s_c, d_c = args[0], args[1]
            g = buf_ext[s_c]
            if len(args) > 2:
                g = g * args[2][:, None].astype(g.dtype)
            return out.at[d_c].add(g, indices_are_sorted=True,
                                   unique_indices=False), None
        out, _ = lax.scan(chunk_body, out, xs)
        return out

    def step(k, carry):
        buf, out = carry
        # double-buffered hop: the rotation that fills the NEXT step's
        # buffer is issued FIRST, before this step's scatter-accumulate
        # touches ``buf`` — the collective and the local aggregation
        # share no data (the permute never reads ``out``), so the
        # program order puts the ICI transfer under the gather/scatter
        # compute instead of after it.  (Skipped rotation work on the
        # last step is harmless; keeping it unconditional keeps the
        # loop body uniform.)
        nxt = (lax.ppermute(buf, axis_name, perm) if overlap else None)
        src_shard = jnp.mod(me - k, S)
        src_e = lax.dynamic_index_in_dim(ring_src, src_shard, axis=0,
                                         keepdims=False)
        dst_e = lax.dynamic_index_in_dim(ring_dst, src_shard, axis=0,
                                         keepdims=False)
        w_e = (lax.dynamic_index_in_dim(weights, src_shard, axis=0,
                                        keepdims=False)
               if weights is not None else None)
        buf_ext = jnp.concatenate(
            [buf, jnp.zeros((1, F), dtype=buf.dtype)], axis=0)
        out = local_pair(out, buf_ext, src_e, dst_e, w_e)
        if not overlap:
            # sequential reference: rotate only after the accumulate
            nxt = lax.ppermute(buf, axis_name, perm)
        return nxt, out

    out0 = jnp.zeros((n, F), dtype=x.dtype)
    _, out = lax.fori_loop(0, S, step, (x, out0))
    return out
