"""ctypes bindings for the native host-side data layer (librocio.so).

The reference implements its entire data path in C++ host code inside
CUDA task bodies (``load_task.cu``, ``gnn.cc:751-872``); here the same
components live in ``native/rocio.cc`` behind a C ABI, loaded lazily
via ctypes.  Every entry point has a pure-numpy fallback in
``roc_tpu.core`` — the native library is a performance path, not a hard
dependency, so ``available()`` gates all call sites.

The library is built with ``make -C native`` (attempted automatically
on first use if the toolchain is present).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_LIB_PATH = os.environ.get(
    "ROC_TPU_NATIVE", os.path.join(_NATIVE_DIR, "librocio.so"))

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _stale() -> bool:
    """True when a previously built .so is older than its source —
    rebuilding then keeps native tests validating current code (the
    binary is a build artifact, never checked in).  A library pinned
    via ROC_TPU_NATIVE is trusted as-is (the env var is an explicit
    operator override)."""
    if "ROC_TPU_NATIVE" in os.environ:
        return False
    try:
        lib_mtime = os.path.getmtime(_LIB_PATH)
        return any(
            os.path.getmtime(os.path.join(_NATIVE_DIR, f)) > lib_mtime
            for f in ("rocio.cc", "Makefile"))
    except OSError:
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_LIB_PATH) or _stale():
        makefile = os.path.join(_NATIVE_DIR, "Makefile")
        if os.path.exists(makefile):
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR],
                               capture_output=True, timeout=120,
                               check=False)
            except (OSError, subprocess.TimeoutExpired):
                pass
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    # ABI gate: the argtypes below describe THIS source tree's C
    # signatures; a stale or pinned .so from before an ABI bump would
    # read a pointer slot as an int (SIGSEGV or silent garbage), so
    # mismatches fall back to the numpy paths instead of loading.
    _ABI_VERSION = 5
    try:
        lib.roc_abi_version.restype = ctypes.c_int
        got = int(lib.roc_abi_version())
    except AttributeError:
        got = 1  # predates the version export
    if got != _ABI_VERSION:
        from .obs.events import emit
        emit("resolve", f"librocio.so ABI v{got} != expected "
             f"v{_ABI_VERSION}; ignoring {_LIB_PATH} (rebuild with "
             f"make -C native)", abi_got=got,
             abi_expected=_ABI_VERSION)
        return None
    # Full argtypes: int64_t params must not fall back to the 32-bit
    # c_int default (graphs with > 2^31 edges are in scope for the
    # streaming tier).
    c = ctypes
    i64, i32p, i64p, f32p = (c.c_int64, c.POINTER(c.c_int32),
                             c.POINTER(c.c_int64), c.POINTER(c.c_float))
    lib.roc_lux_header.restype = c.c_int
    lib.roc_lux_header.argtypes = [c.c_char_p, c.POINTER(c.c_uint32),
                                   c.POINTER(c.c_uint64)]
    lib.roc_lux_read.restype = c.c_int
    lib.roc_lux_read.argtypes = [c.c_char_p, i64, i64, i64p, i32p]
    lib.roc_lux_write.restype = c.c_int
    lib.roc_lux_write.argtypes = [c.c_char_p, i64, i64, i64p, i32p]
    lib.roc_load_features_csv.restype = c.c_int
    lib.roc_load_features_csv.argtypes = [c.c_char_p, f32p, i64, i64]
    lib.roc_load_features_csv_rows.restype = c.c_int
    lib.roc_load_features_csv_rows.argtypes = [c.c_char_p, f32p, i64,
                                               i64, i64]
    lib.roc_load_mask.restype = c.c_int
    lib.roc_load_mask.argtypes = [c.c_char_p, i32p, i64]
    lib.roc_edge_balanced_bounds.restype = c.c_int
    lib.roc_edge_balanced_bounds.argtypes = [i64p, i64, i64, i64p]
    lib.roc_add_self_edges.restype = c.c_int64
    lib.roc_add_self_edges.argtypes = [i64p, i32p, i64, i64p, i32p, i64]
    lib.roc_ell_widths.restype = c.c_int
    lib.roc_ell_widths.argtypes = [i64p, i64, c.c_int32, i32p]
    lib.roc_sectioned_counts.restype = c.c_int
    lib.roc_sectioned_counts.argtypes = [i64p, i32p, i64, i64, i64, i64,
                                         i64p]
    lib.roc_sectioned_fill.restype = c.c_int
    lib.roc_sectioned_fill.argtypes = [i64p, i32p, i64, i64, i64, i64,
                                       i64p, i64p, i32p, i32p]
    u8p = c.POINTER(c.c_uint8)
    lib.roc_block_counts.restype = c.c_int64
    lib.roc_block_counts.argtypes = [i64p, i32p, i64, i64, i64, i64p,
                                     i64p, i64]
    lib.roc_block_fill.restype = c.c_int64
    lib.roc_block_fill.argtypes = [i64p, i32p, i64, i64, i64, i64p,
                                   i64, u8p, i64p, i32p, i64]
    lib.roc_lpa_iterate.restype = c.c_int64
    lib.roc_lpa_iterate.argtypes = [i64p, i32p, i64, i32p, i32p]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def load_lux(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """(row_ptr int64 [V+1], col_idx int32 [E]) from a .lux file."""
    lib = _load()
    assert lib is not None
    nn = ctypes.c_uint32()
    ne = ctypes.c_uint64()
    rc = lib.roc_lux_header(path.encode(), ctypes.byref(nn),
                            ctypes.byref(ne))
    if rc != 0:
        raise IOError(f"roc_lux_header({path}) failed: {rc}")
    V, E = int(nn.value), int(ne.value)
    row_ptr = np.empty(V + 1, dtype=np.int64)
    col_idx = np.empty(E, dtype=np.int32)
    rc = lib.roc_lux_read(path.encode(), V, E, _i64p(row_ptr),
                          _i32p(col_idx))
    if rc != 0:
        raise IOError(f"roc_lux_read({path}) failed: {rc}")
    return row_ptr, col_idx


def save_lux(path: str, row_ptr: np.ndarray, col_idx: np.ndarray) -> None:
    lib = _load()
    assert lib is not None
    row_ptr = np.ascontiguousarray(row_ptr, dtype=np.int64)
    col_idx = np.ascontiguousarray(col_idx, dtype=np.int32)
    rc = lib.roc_lux_write(path.encode(), row_ptr.shape[0] - 1,
                           col_idx.shape[0], _i64p(row_ptr),
                           _i32p(col_idx))
    if rc != 0:
        raise IOError(f"roc_lux_write({path}) failed: {rc}")


def load_features_csv(path: str, rows: int, cols: int) -> np.ndarray:
    lib = _load()
    assert lib is not None
    out = np.empty((rows, cols), dtype=np.float32)
    rc = lib.roc_load_features_csv(
        path.encode(), out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        rows, cols)
    if rc != 0:
        raise IOError(f"roc_load_features_csv({path}) failed: {rc}")
    return out


def load_features_csv_rows(path: str, row_lo: int, row_hi: int,
                           cols: int) -> np.ndarray:
    """Partition-local CSV feature read: rows [row_lo, row_hi)."""
    lib = _load()
    assert lib is not None
    out = np.empty((row_hi - row_lo, cols), dtype=np.float32)
    rc = lib.roc_load_features_csv_rows(
        path.encode(), out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        row_lo, row_hi, cols)
    if rc != 0:
        raise IOError(f"roc_load_features_csv_rows({path}) failed: {rc}")
    return out


def load_mask(path: str, n: int) -> np.ndarray:
    lib = _load()
    assert lib is not None
    out = np.empty(n, dtype=np.int32)
    rc = lib.roc_load_mask(path.encode(), _i32p(out), n)
    if rc != 0:
        raise IOError(f"roc_load_mask({path}) failed: {rc}")
    return out


def edge_balanced_bounds(row_ptr: np.ndarray, num_parts: int) -> np.ndarray:
    """int64 [num_parts, 2] inclusive [left, right] ranges."""
    lib = _load()
    assert lib is not None
    row_ptr = np.ascontiguousarray(row_ptr, dtype=np.int64)
    bounds = np.empty((num_parts, 2), dtype=np.int64)
    rc = lib.roc_edge_balanced_bounds(
        _i64p(row_ptr), row_ptr.shape[0] - 1, num_parts, _i64p(bounds))
    if rc != 0:
        raise ValueError(f"roc_edge_balanced_bounds failed: {rc}")
    return bounds


def add_self_edges(row_ptr: np.ndarray, col_idx: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray]:
    lib = _load()
    assert lib is not None
    row_ptr = np.ascontiguousarray(row_ptr, dtype=np.int64)
    col_idx = np.ascontiguousarray(col_idx, dtype=np.int32)
    V = row_ptr.shape[0] - 1
    cap = col_idx.shape[0] + V
    new_ptr = np.empty(V + 1, dtype=np.int64)
    new_col = np.empty(cap, dtype=np.int32)
    rc = lib.roc_add_self_edges(_i64p(row_ptr), _i32p(col_idx), V,
                                _i64p(new_ptr), _i32p(new_col), cap)
    if rc < 0:
        raise ValueError(f"roc_add_self_edges failed: {rc}")
    return new_ptr, new_col[: col_idx.shape[0] + int(rc)].copy()


def ell_widths(row_ptr: np.ndarray, min_width: int = 8) -> np.ndarray:
    """Per-row power-of-two ELL bucket width (0 for empty rows)."""
    lib = _load()
    assert lib is not None
    row_ptr = np.ascontiguousarray(row_ptr, dtype=np.int64)
    n = row_ptr.shape[0] - 1
    out = np.empty(n, dtype=np.int32)
    rc = lib.roc_ell_widths(_i64p(row_ptr), n, min_width, _i32p(out))
    if rc != 0:
        raise ValueError(f"roc_ell_widths failed: {rc}")
    return out


def sectioned_counts(row_ptr: np.ndarray, col_idx: np.ndarray,
                     num_rows: int, section_rows: int,
                     n_sec: int, sub_w: int = 8) -> np.ndarray:
    """Per-section width-``sub_w`` sub-row totals (core/ell.py
    sectioned prep, counts pass)."""
    lib = _load()
    assert lib is not None
    row_ptr = np.ascontiguousarray(row_ptr, dtype=np.int64)
    col_idx = np.ascontiguousarray(col_idx, dtype=np.int32)
    out = np.empty(n_sec, dtype=np.int64)
    rc = lib.roc_sectioned_counts(_i64p(row_ptr), _i32p(col_idx),
                                  num_rows, section_rows, n_sec,
                                  sub_w, _i64p(out))
    if rc != 0:
        raise ValueError(f"roc_sectioned_counts failed: {rc}")
    return out


def sectioned_fill(row_ptr: np.ndarray, col_idx: np.ndarray,
                   num_rows: int, section_rows: int,
                   sec_sizes: np.ndarray, slots: np.ndarray,
                   sub_w: int = 8) -> Tuple[np.ndarray, np.ndarray]:
    """Fill pass: (idx_flat [sum(slots), sub_w], sub_dst_flat
    [sum(slots)]) with per-section regions laid out consecutively in
    section order."""
    lib = _load()
    assert lib is not None
    row_ptr = np.ascontiguousarray(row_ptr, dtype=np.int64)
    col_idx = np.ascontiguousarray(col_idx, dtype=np.int32)
    sec_sizes = np.ascontiguousarray(sec_sizes, dtype=np.int64)
    slots = np.ascontiguousarray(slots, dtype=np.int64)
    total = int(slots.sum())
    idx_flat = np.empty((total, sub_w), dtype=np.int32)
    sub_dst = np.empty(total, dtype=np.int32)
    rc = lib.roc_sectioned_fill(
        _i64p(row_ptr), _i32p(col_idx), num_rows, section_rows,
        slots.shape[0], sub_w, _i64p(sec_sizes), _i64p(slots),
        _i32p(idx_flat), _i32p(sub_dst))
    if rc != 0:
        raise ValueError(f"roc_sectioned_fill failed: {rc}")
    return idx_flat, sub_dst


def block_counts(row_ptr: np.ndarray, col_idx: np.ndarray,
                 num_rows: int, block: int,
                 num_cols: int = None
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """(keys, counts) per occupied [block x block] adjacency tile,
    key-ascending (ops/blockdense.py plan_blocks, census pass).
    ``num_cols`` sets a rectangular tile space (distributed planner:
    local dst rows x gathered source coordinates); default square."""
    lib = _load()
    assert lib is not None
    if num_cols is None:
        num_cols = num_rows
    row_ptr = np.ascontiguousarray(row_ptr, dtype=np.int64)
    col_idx = np.ascontiguousarray(col_idx, dtype=np.int32)
    n_tiles = -(-num_rows // block)
    n_src_tiles = -(-num_cols // block)
    cap = int(min(n_tiles * n_src_tiles, col_idx.shape[0], 1 << 27))
    cap = max(cap, 1)
    while True:
        keys = np.empty(cap, dtype=np.int64)
        counts = np.empty(cap, dtype=np.int64)
        nnz = int(lib.roc_block_counts(
            _i64p(row_ptr), _i32p(col_idx), num_rows, num_cols, block,
            _i64p(keys), _i64p(counts), cap))
        if nnz < 0:
            raise ValueError(f"roc_block_counts failed: {nnz}")
        if nnz <= cap:
            return keys[:nnz].copy(), counts[:nnz].copy()
        cap = nnz


def block_fill(row_ptr: np.ndarray, col_idx: np.ndarray,
               num_rows: int, block: int, dense_keys: np.ndarray,
               num_cols: int = None
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(a_blocks uint8 [nblk, block, block], res_row_ptr, res_col):
    fill the selected tiles' multiplicity tables, spill the rest (and
    saturated duplicates) to a residual dst-major CSR.  ``num_cols``
    as in :func:`block_counts`."""
    lib = _load()
    assert lib is not None
    if num_cols is None:
        num_cols = num_rows
    row_ptr = np.ascontiguousarray(row_ptr, dtype=np.int64)
    col_idx = np.ascontiguousarray(col_idx, dtype=np.int32)
    dense_keys = np.ascontiguousarray(dense_keys, dtype=np.int64)
    nblk = dense_keys.shape[0]
    a = np.zeros((nblk, block, block), dtype=np.uint8)
    res_ptr = np.empty(num_rows + 1, dtype=np.int64)
    res_col = np.empty(col_idx.shape[0], dtype=np.int32)
    rc = int(lib.roc_block_fill(
        _i64p(row_ptr), _i32p(col_idx), num_rows, num_cols, block,
        _i64p(dense_keys), nblk,
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        _i64p(res_ptr), _i32p(res_col), res_col.shape[0]))
    if rc < 0:
        raise ValueError(f"roc_block_fill failed: {rc}")
    return a, res_ptr, res_col[:rc].copy()


def lpa_iterate(nbr_ptr: np.ndarray, nbr: np.ndarray,
                labels: np.ndarray) -> Tuple[np.ndarray, int]:
    """One ASYNCHRONOUS label-propagation sweep over an undirected
    CSR, in increasing vertex order (core/reorder.py lpa_labels):
    returns (new_labels, changed)."""
    lib = _load()
    assert lib is not None
    nbr_ptr = np.ascontiguousarray(nbr_ptr, dtype=np.int64)
    nbr = np.ascontiguousarray(nbr, dtype=np.int32)
    labels = np.ascontiguousarray(labels, dtype=np.int32)
    out = np.empty_like(labels)
    rc = int(lib.roc_lpa_iterate(
        _i64p(nbr_ptr), _i32p(nbr), labels.shape[0],
        _i32p(labels), _i32p(out)))
    if rc < 0:
        raise ValueError(f"roc_lpa_iterate failed: {rc}")
    return out, rc
