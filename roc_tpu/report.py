"""Run-report CLI: summarize event/metrics JSONL artifacts into tables.

``python -m roc_tpu.report ev.jsonl [ev_p1.jsonl ...|'ev_p*.jsonl']
[--metrics m.jsonl [--metrics m2.jsonl ...]]``

Accepts MULTIPLE event files (repeat the positional, or pass a glob) —
a multi-process run writes one JSONL per process, and the report
merges them instead of silently assuming one stream (each record's
clock tuple ``host``/``proc`` identifies its stream; a "processes"
header shows what was merged).  For a merged *timeline* view of the
same artifacts use ``python -m roc_tpu.timeline``.

Renders, from the artifacts a run with ``--events``/``--metrics``
leaves behind:

- the run manifest (what code/hardware/config actually executed);
- compile cost per step function, with the modeled-vs-actual HBM
  delta (the planner-vs-residency check);
- per-phase spans (compile / train / eval / streamed sub-phases) as
  p50/p90;
- throughput (edges/sec, TFLOP/s, MFU when the chip's peak is known);
- stall heartbeats, grouped by stage — where a hung run spent its
  time.

This is a *reader*: it works on artifacts from a dead run (the JSONL
sinks flush per line) and never touches a backend — no
``jax.devices()``, no claim on the relay.  ``python -m roc_tpu.report``
does import the ``roc_tpu`` package (and thus jax) on the way in; on
a box without jax, run it as a plain script instead — this module
deliberately has no package-relative imports:
``python roc_tpu/report.py events.jsonl``.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                # a run killed mid-write leaves at most one torn tail
                # line; skip rather than refuse the whole artifact
                continue
    return out


def _fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "?"
    if abs(n) >= 1 << 28:
        return f"{n / 1024**3:.2f}GiB"
    if abs(n) >= 1 << 17:
        return f"{n / 1024**2:.1f}MiB"
    return f"{n / 1024:.1f}KiB"


def _pct(values: List[float], q: float) -> float:
    vs = sorted(values)
    if not vs:
        return 0.0
    idx = min(len(vs) - 1, max(0, int(round(q * (len(vs) - 1)))))
    return vs[idx]


def _rows(title: str, header: List[str],
          rows: List[List[str]], out) -> None:
    print(f"\n== {title} ==", file=out)
    if not rows:
        print("  (none)", file=out)
        return
    widths = [max(len(h), *(len(r[i]) for r in rows))
              for i, h in enumerate(header)]
    print("  " + "  ".join(h.ljust(w) for h, w in zip(header, widths)),
          file=out)
    for r in rows:
        print("  " + "  ".join(c.ljust(w) for c, w in zip(r, widths)),
              file=out)


def _stream_key(rec: Dict[str, Any]):
    try:
        proc = int(rec.get("proc", 0) or 0)
    except (TypeError, ValueError):
        proc = 0
    return (str(rec.get("host", "?")), proc)


def summarize(events: List[Dict[str, Any]],
              metrics: Optional[List[Dict[str, Any]]] = None,
              out=None,
              concurrency: Optional[Dict[str, Any]] = None,
              protocol: Optional[Dict[str, Any]] = None) -> int:
    out = out if out is not None else sys.stdout

    # merged multi-process artifacts: one JSONL per process, each
    # record stamped with its (host, proc) clock identity — say what
    # was merged before aggregating across it
    streams: Dict[Any, int] = {}
    for e in events:
        k = _stream_key(e)
        streams[k] = streams.get(k, 0) + 1
    if len(streams) > 1:
        print("processes (merged event streams):", file=out)
        for (host, proc), n in sorted(streams.items(),
                                      key=lambda kv: kv[0][1]):
            print(f"  proc{proc}@{host}: {n} events", file=out)

    manifests = [e for e in events if e.get("cat") == "manifest"]
    if manifests:
        m = manifests[-1]
        res = m.get("resolved") or {}
        ds = m.get("dataset") or {}
        print("run manifest:", file=out)
        print(f"  platform={m.get('platform')} "
              f"devices={m.get('device_count')} "
              f"kinds={m.get('device_kinds')} "
              f"jax={m.get('jax_version')} "
              f"sha={(m.get('git_sha') or 'none')[:12]}", file=out)
        print(f"  dataset={ds.get('name')} V={ds.get('num_nodes')} "
              f"E={ds.get('num_edges')}", file=out)
        print("  resolved: " + " ".join(
            f"{k}={v}" for k, v in res.items()), file=out)
    else:
        print("run manifest: (none recorded)", file=out)

    decisions = [e for e in events
                 if e.get("cat") in ("resolve", "plan")]
    _rows("decisions (resolve/plan)", ["cat", "message"],
          [[e["cat"], str(e.get("msg", ""))[:96]] for e in decisions],
          out)

    compiles = [e for e in events
                if e.get("cat") == "compile" and "lower_s" in e]
    rows = []
    for e in compiles:
        modeled, peak = e.get("modeled_bytes"), e.get("peak_bytes")
        ratio = (f"{peak / modeled:.2f}x"
                 if peak is not None and modeled else "?")
        flops = e.get("flops")
        rows.append([
            str(e.get("name")),
            f"{e.get('lower_s', 0) + e.get('compile_s', 0):.2f}s",
            f"{flops:.3g}" if flops is not None else "?",
            _fmt_bytes(e.get("bytes_accessed")),
            _fmt_bytes(peak), _fmt_bytes(modeled), ratio])
    _rows("compile (XLA introspection)",
          ["step", "lower+compile", "flops", "bytes", "peak_hbm",
           "modeled", "actual/model"], rows, out)

    # compile-cache prewarm: per-config warm-vs-cold summaries
    # (utils/prewarm.py emits one summary event per warmed config;
    # the bench children emit the same shape before their timed
    # phase) — a repeat run should be all-warm, and cold counts on an
    # unchanged config mean program-set or cache-key drift
    pre = [e for e in events if e.get("cat") == "compile"
           and e.get("summary") and "prewarm" in e]
    rows = []
    for e in pre:
        rows.append([
            str(e.get("prewarm")), str(e.get("programs")),
            str(e.get("compile_warm_hits")),
            str(e.get("compile_cold")),
            str(e.get("failed", 0)),
            f"{float(e.get('prewarm_s', 0)):.1f}s"])
    _rows("compile cache (prewarm warm-vs-cold)",
          ["config", "programs", "warm_hits", "cold", "failed",
           "total"], rows, out)

    # phase spans: the trainer emits a final spans summary; fall back
    # to aggregating the per-eval epoch events / metrics records
    span_events = [e for e in events
                   if e.get("cat") == "epoch" and e.get("spans")]
    rows = []
    if span_events:
        for name, s in span_events[-1]["spans"].items():
            rows.append([name, str(s.get("n")),
                         f"{s.get('p50_ms', 0):.1f}",
                         f"{s.get('p90_ms', 0):.1f}",
                         f"{s.get('total_ms', 0):.0f}"])
    else:
        series: Dict[str, List[float]] = {}
        recs = [e for e in events if e.get("cat") == "epoch"]
        recs += metrics or []
        for e in recs:
            for k in ("epoch_ms", "eval_ms", "compile_ms"):
                if isinstance(e.get(k), (int, float)):
                    series.setdefault(k[:-3], []).append(float(e[k]))
        for name, vs in series.items():
            rows.append([name, str(len(vs)), f"{_pct(vs, 0.5):.1f}",
                         f"{_pct(vs, 0.9):.1f}", f"{sum(vs):.0f}"])
    _rows("phase spans (ms)",
          ["phase", "n", "p50", "p90", "total"], rows, out)

    thr: Dict[str, List[float]] = {}
    for e in ([x for x in events if x.get("cat") == "epoch"]
              + (metrics or [])):
        for k in ("edges_per_s", "tflops_per_s", "mfu"):
            if isinstance(e.get(k), (int, float)):
                thr.setdefault(k, []).append(float(e[k]))
    rows = [[k, f"{_pct(vs, 0.5):.4g}", f"{max(vs):.4g}"]
            for k, vs in thr.items()]
    _rows("throughput", ["metric", "p50", "max"], rows, out)

    # pipelined execution: overlap_frac = fraction of host->device
    # staging latency hidden under compute (1.0 = fully overlapped,
    # 0.0 = the synchronous prefetch=0 path); h2d_wait_p50_ms = the
    # un-hidden per-block stall.  Ring hop_compute/hop_permute rows
    # come from the micro_stream probe's pipeline events.
    pipe: Dict[str, List[float]] = {}
    for e in ([x for x in events
               if x.get("cat") in ("epoch", "pipeline")]
              + (metrics or [])):
        for k in ("overlap_frac", "h2d_wait_p50_ms",
                  "h2d_stage_p50_ms", "prefetch_depth",
                  "hop_compute_ms", "hop_permute_ms"):
            if isinstance(e.get(k), (int, float)):
                pipe.setdefault(k, []).append(float(e[k]))
    rows = [[k, f"{_pct(vs, 0.5):.4g}", f"{min(vs):.4g}",
             f"{max(vs):.4g}"] for k, vs in pipe.items()]
    _rows("pipeline (h2d prefetch / ring overlap)",
          ["metric", "p50", "min", "max"], rows, out)

    # partition load balance: the manifest's split-quality record
    # (per-part padded shapes + halo rows, the shapes that gate every
    # SPMD step) plus the cost-model event stream — every recorded
    # imbalance / repartition decision of the run
    part = (manifests[-1].get("partition") or {}) if manifests else {}
    rows = []
    if part.get("real_edges"):
        cols = [part.get(k) or [] for k in
                ("padded_edges", "padded_nodes", "halo_in",
                 "halo_out")]
        for p, re_ in enumerate(part["real_edges"][:16]):
            rows.append([str(p), str(re_)]
                        + [str(c[p]) if p < len(c) else "?"
                           for c in cols])
        if len(part["real_edges"]) > 16:
            rows.append(["...", "", "", "", "", ""])
    _rows("partition load balance",
          ["part", "real_edges", "padded_edges", "padded_nodes",
           "halo_in", "halo_out"], rows, out)
    if part:
        print(f"  imbalance max/mean: edges "
              f"{part.get('edge_imbalance')} nodes "
              f"{part.get('node_imbalance')}  (padded shard "
              f"{part.get('part_nodes')} nodes x "
              f"{part.get('part_edges')} edges)", file=out)
    cm = [e for e in events if e.get("cat") == "costmodel"
          and ("rebalance" in e or "gain" in e)]
    _rows("cost model (rebalance decisions)", ["message"],
          [[str(e.get("msg", ""))[:110]] for e in cm], out)

    # program space: the auditor's compile-budget reports (one event
    # per rig config, cat=programspace) — program count vs the
    # baselined bound, the static compile-wall tripwire
    ps = [e for e in events if e.get("cat") == "programspace"
          and "programs" in e]
    rows = []
    for e in ps:
        b, d = e.get("budget"), e.get("delta")
        rows.append([
            str(e.get("config")), str(e.get("programs")),
            str(e.get("observed_programs", "?")),
            f"{float(e.get('modeled_compile_ms', 0)) / 1e3:.1f}s",
            "?" if b is None else str(b),
            "?" if d is None else f"{d:+d}"])
    _rows("program space (compile budget)",
          ["config", "programs", "observed", "modeled_compile",
           "budget", "delta"], rows, out)

    # resilience: the fault-tolerance lifecycle (roc_tpu/resilience) —
    # injected drill faults, recovery retries, corrupt-checkpoint
    # fallbacks, preemptions/emergency checkpoints, elastic restores.
    # A clean run shows (none); every row here is either a drill or an
    # incident the run survived.
    res = [e for e in events if e.get("cat") == "resilience"]
    by_kind: Dict[str, List[Dict[str, Any]]] = {}
    for e in res:
        by_kind.setdefault(str(e.get("kind", "?")), []).append(e)
    rows = [[kind, str(len(es)), str(es[-1].get("msg", ""))[:84]]
            for kind, es in sorted(by_kind.items())]
    _rows("resilience (faults injected / recoveries)",
          ["kind", "n", "last"], rows, out)

    # concurrency surface: the level-six auditor's discovered thread
    # model — every thread, sync object, and signal handler per
    # module, so the table documents what runs concurrently with the
    # step loop.  Source: the ``--concurrency`` payload (the
    # ``python -m roc_tpu.analysis --select concurrency --json``
    # report test.sh / round6_chain step 0 write), or the
    # ``concurrency_surface`` analysis event any audited run leaves
    # in its event stream.
    conc = concurrency
    if conc is None:
        evs = [e for e in events if e.get("cat") == "analysis"
               and e.get("kind") == "concurrency_surface"]
        if evs:
            conc = {"modules": evs[-1].get("modules") or [],
                    "totals": evs[-1].get("totals") or {}}
    rows = []
    for mod in (conc or {}).get("modules", []):
        threads = ", ".join(
            (str(t.get("target") or "?")
             + ("(daemon)" if t.get("daemon") else ""))
            for t in mod.get("threads", [])) or "-"
        locks = ", ".join(
            f"{lk.get('name')}[{lk.get('kind')}]"
            for lk in mod.get("locks", [])) or "-"
        handlers = ", ".join(str(h.get("handler") or "?")
                             for h in mod.get("handlers", [])) or "-"
        rows.append([str(mod.get("module", "?")), threads, locks,
                     handlers])
    _rows("concurrency surface (threads / sync objects / handlers)",
          ["module", "threads", "sync objects", "signal handlers"],
          rows, out)

    # protocol surface: the level-eight auditor's wire vocabulary +
    # model-check verdicts.  Source: the ``--protocol`` payload (the
    # ``python -m roc_tpu.analysis --select protocol --json`` report)
    # or the ``protocol_surface`` event any audited run leaves in its
    # stream.
    proto = protocol
    if proto is None:
        evs = [e for e in events
               if e.get("kind") == "protocol_surface"]
        if evs:
            proto = {"channels": evs[-1].get("channels") or [],
                     "models": evs[-1].get("models") or [],
                     "totals": evs[-1].get("totals") or {}}
    if proto:
        summarize_protocol(proto, out)

    # sharding: the level-seven auditor's replication ledger +
    # mesh-portability report (cat=sharding events, or the
    # --sharding payload below via summarize_sharding)
    sh = [e for e in events if e.get("cat") == "sharding"
          and "replicated_bytes" in e]
    if sh:
        summarize_sharding(
            [{**e, "ledger": e.get("ledger") or []} for e in sh],
            out)

    # SLO transitions: the burn-rate engine's dated breach/recovered
    # events (obs/slo.py) — every row is an objective crossing its
    # alert threshold (or coming back).  A clean run shows (none);
    # `--slo` renders the focused view of the same records plus the
    # live registry-snapshot dashboard.
    summarize_slo_events(events, out)

    stalls = [e for e in events if e.get("cat") == "stall"]
    by_stage: Dict[str, List[float]] = {}
    for e in stalls:
        by_stage.setdefault(str(e.get("stage")), []).append(
            float(e.get("elapsed_s", 0)))
    rows = [[st, str(len(vs)), f"{max(vs):.0f}s"]
            for st, vs in by_stage.items()]
    _rows("stalls (heartbeats)", ["stage", "beats", "max_wait"],
          rows, out)
    return 0


def summarize_sharding(reports: List[Dict[str, Any]],
                       out=None) -> int:
    """Render the sharding auditor's per-rig records: the
    replication-budget line, the mesh-portability per-device HBM at
    every (parts, model) shape, every full-width-materialization
    site with its modeled per-device bytes, and the top of the
    replication ledger.  Input: the ``sharding`` list of
    ``python -m roc_tpu.analysis --select sharding --json`` (or the
    equivalent ``sharding`` event records)."""
    out = out if out is not None else sys.stdout
    for rep in reports:
        cfg = rep.get("config", "?")
        b = rep.get("budget")
        d = rep.get("delta")
        shape = rep.get("canonical_shape") or ["?", "?"]
        print(f"\n== sharding {cfg} (parts={rep.get('parts')}) ==",
              file=out)
        print(f"  replicated/step on {shape[0]}x{shape[1]}: "
              f"{_fmt_bytes(rep.get('replicated_bytes'))}  "
              f"(budget "
              + ("unset — run --update-baseline" if b is None
                 else f"{_fmt_bytes(b)}, delta {d:+d} B") + ")",
              file=out)
        rows = []
        for m in rep.get("mesh_shapes") or []:
            reps_ = sorted({a for c in (m.get("components")
                                        or {}).values()
                            for a in c.get("replicated", [])})
            rows.append([f"{m.get('parts')}x{m.get('model')}",
                         _fmt_bytes(m.get("per_device_bytes")),
                         ",".join(reps_) or "-"])
        _rows(f"{cfg}: modeled per-device HBM by (parts x model)",
              ["mesh", "per_device", "replicated components"],
              rows, out)
        rows = []
        sites = rep.get("sites")
        if sites is None:
            sites = [s for slot in rep.get("slots") or []
                     for s in slot.get("sites") or []]
        for s in sites:
            per = s.get("per_device_bytes") or {}
            rows.append([
                str(s.get("op")), str(s.get("kind")),
                f"{s.get('dtype')}{s.get('shape')}",
                "/".join(s.get("lost") or []),
                str(s.get("layer")), str(s.get("src") or "-")]
                + [_fmt_bytes(per.get(k)) for k in
                   ("1x8", "2x4", "4x2")])
        _rows(f"{cfg}: full-width-materialization sites "
              f"(portability sim)",
              ["op", "kind", "tensor", "lost", "layer", "src",
               "dev@1x8", "dev@2x4", "dev@4x2"], rows, out)
        rows = []
        for e in (rep.get("ledger") or [])[:10]:
            rows.append([
                str(e.get("role")),
                f"{e.get('dtype')}{e.get('shape')}",
                _fmt_bytes(e.get("bytes")),
                ",".join(e.get("split") or []) or "-",
                ",".join(e.get("replicated") or []) or "-",
                _fmt_bytes(e.get("per_device_bytes"))])
        _rows(f"{cfg}: replication ledger (top 10, "
              f"{shape[0]}x{shape[1]})",
              ["role", "tensor", "bytes", "split", "replicated",
               "per_device"], rows, out)
    return 0


def summarize_protocol(surface: Dict[str, Any], out=None) -> int:
    """Render the level-eight protocol audit: the per-channel wire
    vocabulary (kind, field contract, send/handle sites, drift
    status), each dispatcher's unknown-kind-rejection verdict, the
    bounded model checker's per-model state counts and invariant
    verdicts (with counterexample schedules when a violation fired),
    and the lifecycle/commit transition-site index.  Input: the
    ``protocol_surface`` of ``python -m roc_tpu.analysis --select
    protocol --json`` (or the equivalent ``protocol`` event)."""
    out = out if out is not None else sys.stdout
    for chan in surface.get("channels") or []:
        rows = []
        for kind, k in sorted((chan.get("kinds") or {}).items()):
            sent_at = ",".join(str(x) for x in k.get("sent_at") or [])
            if not sent_at:
                sent_at = ("(by design)" if k.get("sent") is False
                           else "-")
            rows.append([
                kind,
                ",".join(k.get("required") or []) or "?",
                ",".join(k.get("optional") or []) or "-",
                sent_at,
                ",".join(str(x) for x in k.get("handled_at") or [])
                or "-",
                str(k.get("status", "?"))])
        _rows(f"wire vocabulary: {chan.get('name')} "
              f"({chan.get('sender')} -> {chan.get('receiver')})",
              ["kind", "required", "optional", "sent@", "handled@",
               "status"], rows, out)
        rej = ", ".join(
            f"{d.get('func')}:{d.get('line')}"
            + ("" if d.get("rejects_unknown") else " [NO REJECTION]")
            for d in chan.get("dispatchers") or []) or "(none)"
        print(f"  unknown-kind rejection: {rej}", file=out)
    rows = [[str(m.get("model", "?")), str(m.get("states")),
             str(m.get("transitions")),
             "yes" if m.get("complete") else "BUDGET EXHAUSTED",
             str(len(m.get("violations") or [])),
             ", ".join(m.get("invariants") or [])]
            for m in surface.get("models") or []]
    _rows("protocol models (bounded exhaustive exploration)",
          ["model", "states", "transitions", "complete",
           "violations", "invariants"], rows, out)
    for m in surface.get("models") or []:
        for v in m.get("violations") or []:
            print(f"  VIOLATION {m.get('model')}/"
                  f"{v.get('invariant')}: {v.get('msg')}", file=out)
            sched = " -> ".join(v.get("trace") or [])
            print(f"    schedule: {sched or '<initial state>'}",
                  file=out)
    rows = [[str(s.get("machine", "?")), str(s.get("module", "?")),
             str(s.get("site", "?")), str(s.get("line") or "-"),
             "yes" if s.get("present") else "MISSING"]
            for s in surface.get("sites") or []]
    _rows("protocol transition sites",
          ["machine", "module", "site", "line", "present"], rows, out)
    return 0


def summarize_slo_events(events: List[Dict[str, Any]],
                         out=None) -> int:
    """The dated SLO transition table: one row per burn-rate
    breach/recovered event (``cat=slo``), wall-clock stamped — the
    post-mortem's 'when did serving go out of objective, and when did
    it come back'."""
    import time as _time
    out = out if out is not None else sys.stdout
    rows = []
    for e in events:
        if e.get("cat") != "slo":
            continue
        t = e.get("t")
        when = (_time.strftime("%Y-%m-%d %H:%M:%S",
                               _time.localtime(float(t)))
                if t is not None else "?")
        rows.append([when, str(e.get("kind", "?")),
                     str(e.get("slo", "?")),
                     str(e.get("component", "?")),
                     f"{float(e.get('burn', 0)):.1f}x",
                     str(e.get("value")),
                     str(e.get("target")),
                     str(e.get("spec", ""))[:48]])
    _rows("slo transitions (burn-rate alerts)",
          ["when", "kind", "slo", "component", "burn", "value",
           "target", "spec"], rows, out)
    return 0


def summarize_slo(doc: Dict[str, Any], out=None) -> int:
    """Render one metrics-registry snapshot (the ``reg.dump`` /
    ``ROC_TPU_SLO_SNAPSHOT`` artifact) as the live text dashboard:
    the SLO verdict first (health + per-objective burn/value), then
    every counter/gauge/histogram with its windowed view.  Pairs with
    ``watch``: ``watch -n1 python -m roc_tpu.report --slo snap.json``
    is the fleet console."""
    out = out if out is not None else sys.stdout
    windows = [int(w) for w in doc.get("windows_s") or []]
    print(f"slo dashboard: registry '{doc.get('registry', '?')}'"
          + (f"  component={doc['component']}"
             if doc.get("component") else "")
          + (f"  t={doc['t']}" if doc.get("t") is not None else ""),
          file=out)
    health = doc.get("health")
    if health is not None:
        verdict = "OK" if health.get("ok") else "BREACH"
        line = f"  health: {verdict}"
        if health.get("replicas") is not None:
            line += (f"  ({health.get('replicas_alive', '?')}/"
                     f"{health['replicas']} replicas alive)")
        print(line, file=out)
        rows = []
        for ob in health.get("objectives") or []:
            state = (health.get("states") or {}).get(
                ob.get("name"), "?")
            rows.append([str(ob.get("name")),
                         str(ob.get("spec", ""))[:52],
                         state,
                         "yes" if ob.get("compliant") else "NO",
                         str(ob.get("value")),
                         str(ob.get("target")),
                         f"{float(ob.get('burn', 0)):.2f}x",
                         f"{float(ob.get('bad_frac', 0)):.4f}",
                         f"{float(ob.get('budget', 0)):.4f}"])
        _rows("objectives",
              ["name", "spec", "state", "compliant", "value",
               "target", "burn", "bad_frac", "budget"], rows, out)
    metrics = doc.get("metrics") or {}
    rows = []
    for name in sorted(metrics):
        m = metrics[name]
        if m.get("kind") == "counter":
            rows.append([name, str(m.get("total"))]
                        + [str(m.get(f"sum_{w}s", "?"))
                           for w in windows])
    _rows("counters", ["name", "total"]
          + [f"sum_{w}s" for w in windows], rows, out)
    rows = []
    for name in sorted(metrics):
        m = metrics[name]
        if m.get("kind") == "gauge":
            rows.append([name, str(m.get("value")),
                         str(m.get("ewma", "-")), str(m.get("n"))])
    _rows("gauges", ["name", "value", "ewma", "n"], rows, out)
    rows = []
    for name in sorted(metrics):
        m = metrics[name]
        if m.get("kind") == "histogram":
            row = [name, str(m.get("total")), str(m.get("mean"))]
            for w in windows:
                row += [str(m.get(f"n_{w}s", "?")),
                        str(m.get(f"p50_{w}s")),
                        str(m.get(f"p99_{w}s"))]
            rows.append(row)
    hdr = ["name", "total", "mean"]
    for w in windows:
        hdr += [f"n_{w}s", f"p50_{w}s", f"p99_{w}s"]
    _rows("histograms (ms)", hdr, rows, out)
    return 0


def _expand(patterns: List[str]) -> List[str]:
    """Literal paths plus glob patterns, deduped, order-preserving;
    a missing path / zero-match glob is KEPT so the open() below
    fails loudly.  Duplicated from obs/timeline.py expand_paths on
    purpose: this module deliberately has no package-relative imports
    (plain-script mode on boxes without jax, see module docstring) —
    keep the two behaviors in lockstep."""
    import glob as _glob
    import os
    out: List[str] = []
    for p in patterns:
        hits = [p] if os.path.exists(p) else sorted(_glob.glob(p))
        for h in (hits or [p]):
            if h not in out:
                out.append(h)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="roc_tpu.report", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("events", nargs="*",
                    help="event-log JSONL file(s) (--events / "
                         "ROC_TPU_EVENTS artifacts; repeat or glob "
                         "for multi-process runs — one file per "
                         "process).  Optional with --sharding, which "
                         "can render without a run artifact")
    ap.add_argument("--metrics", action="append", default=None,
                    help="training metrics JSONL (--metrics artifact) "
                         "to fold into the span/throughput tables; "
                         "repeatable for multi-process runs")
    ap.add_argument("--concurrency", default=None,
                    help="`python -m roc_tpu.analysis --select "
                         "concurrency --json` payload: renders the "
                         "concurrency-surface table (threads / locks "
                         "/ signal handlers per module) from it "
                         "instead of the event stream")
    ap.add_argument("--protocol", default=None, metavar="FILE",
                    help="`python -m roc_tpu.analysis --select "
                         "protocol --json` payload: renders the "
                         "level-eight wire-vocabulary, model-check "
                         "and transition-site tables from it (works "
                         "with or without event files)")
    ap.add_argument("--sharding", nargs="?", const="__live__",
                    default=None, metavar="FILE",
                    help="render the sharding auditor's replication "
                         "ledger + mesh-portability report.  With "
                         "FILE: a `python -m roc_tpu.analysis "
                         "--select sharding --json` payload.  "
                         "Without FILE (and no event files): run "
                         "the audit live on the 8-virtual-device "
                         "CPU rig — the one mode of this tool that "
                         "imports jax")
    ap.add_argument("--slo", nargs="?", const="__events__",
                    default=None, metavar="SNAPSHOT",
                    help="SLO/observability view.  With SNAPSHOT: "
                         "render a metrics-registry snapshot JSON "
                         "(the Router's ROC_TPU_SLO_SNAPSHOT / "
                         "MetricsRegistry.dump artifact) as the live "
                         "dashboard — watch-able: `watch -n1 python "
                         "-m roc_tpu.report --slo snap.json`.  "
                         "Without SNAPSHOT (bare --slo) with event "
                         "files: render only the dated SLO "
                         "transition table from the event stream")
    args = ap.parse_args(argv)
    # --slo SNAPSHOT: the registry-snapshot dashboard; renders with
    # or without event files (with them, the focused transition table
    # from the events follows)
    if args.slo is not None and args.slo != "__events__":
        try:
            with open(args.slo) as f:
                snap = json.load(f)
        except (OSError, ValueError) as e:
            print(f"error: cannot read {args.slo}: {e}",
                  file=sys.stderr)
            return 2
        summarize_slo(snap if isinstance(snap, dict) else {})
        if not args.events:
            return 0
        events = []
        for path in _expand(args.events):
            try:
                events.extend(load_jsonl(path))
            except OSError as e:
                print(f"error: cannot read {path}: {e}",
                      file=sys.stderr)
                return 2
        events.sort(key=lambda e: float(e.get("t") or 0.0))
        return summarize_slo_events(events)
    if args.slo == "__events__":
        if not args.events:
            ap.error("--slo without a SNAPSHOT file needs event "
                     "files to read transitions from")
        events = []
        for path in _expand(args.events):
            try:
                events.extend(load_jsonl(path))
            except OSError as e:
                print(f"error: cannot read {path}: {e}",
                      file=sys.stderr)
                return 2
        events.sort(key=lambda e: float(e.get("t") or 0.0))
        return summarize_slo_events(events)
    # --sharding FILE loads the payload up front, whether or not
    # event files are also given — an explicitly-passed report must
    # render either way (with events, its tables follow the event
    # summary)
    sharding_reports: Optional[List[Dict[str, Any]]] = None
    if args.sharding is not None and args.sharding != "__live__":
        try:
            with open(args.sharding) as f:
                payload = json.load(f)
        except (OSError, ValueError) as e:
            print(f"error: cannot read {args.sharding}: {e}",
                  file=sys.stderr)
            return 2
        reports = (payload.get("sharding", payload)
                   if isinstance(payload, dict) else payload)
        sharding_reports = reports if isinstance(reports, list) \
            else []
    # --protocol FILE: same contract — accepts the full --json
    # object or a bare protocol_surface dict; renders standalone
    # when no event files are given
    protocol: Optional[Dict[str, Any]] = None
    if args.protocol:
        try:
            with open(args.protocol) as f:
                payload = json.load(f)
        except (OSError, ValueError) as e:
            print(f"error: cannot read {args.protocol}: {e}",
                  file=sys.stderr)
            return 2
        surface = payload.get("protocol_surface", payload) \
            if isinstance(payload, dict) else None
        protocol = surface if isinstance(surface, dict) else None
    if not args.events:
        if args.sharding == "__live__":
            # live audit: the single backend-touching mode, kept out
            # of every artifact-reading path (module docstring) —
            # forced onto the CPU rig exactly like the analysis CLI
            from roc_tpu.analysis import force_cpu_rig
            force_cpu_rig()
            from roc_tpu.analysis.findings import load_budget
            from roc_tpu.analysis.sharding_lint import audit_sharding
            import os
            base = (os.getcwd() if os.path.isdir(
                os.path.join(os.getcwd(), "roc_tpu"))
                else os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))))
            budget = load_budget(
                os.path.join(base, "scripts", "lint_baseline.json"),
                "replication_budget")
            extras: Dict[str, Any] = {}
            audit_sharding(replication_budget=budget, extras=extras)
            return summarize_sharding(extras.get("sharding", []))
        rc = None
        if protocol is not None:
            rc = summarize_protocol(protocol)
        if sharding_reports is not None:
            rc = summarize_sharding(sharding_reports)
        if rc is not None:
            return rc
        ap.error("event files required (or --sharding / --protocol)")
    events: List[Dict[str, Any]] = []
    for path in _expand(args.events):
        try:
            events.extend(load_jsonl(path))
        except OSError as e:
            print(f"error: cannot read {path}: {e}", file=sys.stderr)
            return 2
    # merged streams interleave by wall clock so "last manifest" and
    # span ordering stay meaningful (stable: unstamped records keep
    # their file order)
    events.sort(key=lambda e: float(e.get("t") or 0.0))
    metrics = None
    if args.metrics:
        metrics = []
        for path in _expand(args.metrics):
            try:
                metrics.extend(load_jsonl(path))
            except OSError as e:
                print(f"error: cannot read {path}: {e}",
                      file=sys.stderr)
                return 2
    concurrency = None
    if args.concurrency:
        try:
            with open(args.concurrency) as f:
                payload = json.load(f)
        except (OSError, ValueError) as e:
            print(f"error: cannot read {args.concurrency}: {e}",
                  file=sys.stderr)
            return 2
        # accept the full --json object or a bare surface dict
        concurrency = payload.get("concurrency_surface", payload) \
            if isinstance(payload, dict) else None
    rc = summarize(events, metrics, concurrency=concurrency,
                   protocol=protocol)
    if sharding_reports is not None:
        summarize_sharding(sharding_reports)
    return rc


if __name__ == "__main__":
    sys.exit(main())
