"""Checkpoint-restart recovery: the loop that makes faults survivable.

Grown out of ``utils/resilience.py`` (which remains as a compat shim)
into the resilience subsystem's driver: :func:`train_with_recovery`
now serves BOTH trainers (the distributed path checkpoints through
the sharded v3 format in utils/checkpoint.py and restores through the
partition rebuild), retries every *recoverable* failure class —
numeric poisoning (:class:`NumericFailure`), watchdog-detected stalls
(:class:`StallFailure`, obs/heartbeat.py), and transient I/O errors
(``OSError``, e.g. the streamed tier's staging path) — and cooperates
with the preemption guard (:mod:`roc_tpu.resilience.preempt`): a
Preempted raise writes an emergency checkpoint through the SAME
rotation (flushed, when the rotation saves asynchronously) and
propagates, so the CLI can exit restartable.

Every decision leaves a dated ``resilience`` event; the drill matrix
(tests/test_drills.py) proves each failure class end to end.
"""

from __future__ import annotations

import math
import os
import shutil
import time
from typing import Callable, Dict, List, Optional

from ..obs.events import emit
from ..obs.heartbeat import StallFailure
from ..utils.checkpoint import (CheckpointCorrupt, checkpoint_trainer,
                                is_committed, restore_trainer)
from .preempt import Preempted


class NumericFailure(RuntimeError):
    """Raised when training metrics or parameters go NaN/Inf."""


# the failure classes the retry loop may restore-and-retry: numeric
# poisoning (restored state discards the poison), watchdog-detected
# stalls (a wedged async saver included), and transient I/O
# (staging/storage hiccups).  Anything else is a bug and must
# propagate.
RECOVERABLE = (NumericFailure, StallFailure, OSError)


def check_finite(metrics: Dict[str, float]) -> None:
    loss = metrics.get("train_loss")
    if loss is not None and not math.isfinite(loss):
        raise NumericFailure(f"non-finite train loss: {loss!r} "
                             f"at epoch {metrics.get('epoch')}")


_ALL_FINITE = None


def check_params_finite(params, opt_state=None) -> None:
    """Raise if any param (or optimizer-state) leaf holds NaN/Inf —
    the guard that keeps a poisoned state out of every checkpoint.

    ONE device sync total: the whole pytree folds into a single jitted
    all-finite reduction (the old per-leaf ``bool(isfinite(leaf)
    .all())`` walk synced the dispatch pipeline once per leaf — dozens
    of round trips per checkpoint on deep models).  The per-leaf walk
    survives only on the failure path, to name the culprit."""
    import jax
    import jax.numpy as jnp
    global _ALL_FINITE
    if _ALL_FINITE is None:
        def _impl(trees):
            ok = jnp.asarray(True)
            for leaf in jax.tree_util.tree_leaves(trees):
                if jnp.issubdtype(leaf.dtype, jnp.inexact):
                    ok = jnp.logical_and(ok, jnp.isfinite(leaf).all())
            return ok
        _ALL_FINITE = jax.jit(_impl)
    if bool(_ALL_FINITE((params, opt_state))):
        return
    for label, tree in (("param", params), ("opt_state", opt_state)):
        if tree is None:
            continue
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            if jnp.issubdtype(leaf.dtype, jnp.inexact) and \
                    not bool(jnp.isfinite(leaf).all()):
                raise NumericFailure(
                    f"non-finite {label} at "
                    f"{jax.tree_util.keystr(path)}")
    raise NumericFailure("non-finite value in params/opt state")


class CheckpointRotation:
    """Keep the most recent ``keep`` checkpoints of a trainer as
    ``<prefix>.<epoch>`` v3 directories (two-phase commit via
    checkpoint.py; legacy ``<prefix>.<epoch>.npz`` files from older
    rotations are still scanned, restored, and pruned).

    ``save`` finite-checks params AND optimizer state (one device
    sync, :func:`check_params_finite` — the guard covers EVERY
    trainer save) so a poisoned state is never persisted.  With
    ``async_save=True`` the step path pays only the finite guard +
    host snapshot; CRC + write + manifest commit (and the keep-window
    prune, which must follow the commit) run on the
    :class:`~roc_tpu.resilience.async_save.AsyncSaver` thread —
    ``flush()`` is the emergency-save barrier and ``drain()`` the
    shutdown path.  Async saving is single-writer by construction:
    a snapshot sharded across processes falls back to the synchronous
    barrier'd save with a dated event (coalescing decisions cannot be
    assumed identical across SPMD processes).

    ``restore_latest`` validates integrity on the way in — for a v3
    candidate that means the committed manifest AND every listed
    shard's bytes/CRC/coverage before anything touches the trainer —
    and falls back to the next-newest checkpoint when the newest is
    corrupt (:class:`~roc_tpu.utils.checkpoint.CheckpointCorrupt`),
    with a dated resilience event either way.  An uncommitted save
    (no manifest) is structurally invisible to the scan."""

    def __init__(self, prefix: str, keep: int = 3,
                 async_save: bool = False):
        self.prefix = prefix
        self.keep = keep
        self.async_save = bool(async_save)
        self._saver = None
        self.last_block_ms: Optional[float] = None

    def path(self, epoch: int) -> str:
        return f"{self.prefix}.{epoch}"

    def path_for(self, epoch: int) -> str:
        """The on-disk artifact serving ``epoch``: the COMMITTED v3
        directory when present, else the legacy single file (an
        uncommitted/torn v3 directory must never shadow a legacy
        checkpoint of the same epoch)."""
        p = self.path(epoch)
        if is_committed(p):
            return p
        legacy = p + ".npz"
        if os.path.isfile(legacy):
            return legacy
        return p

    def existing(self) -> List[int]:
        d = os.path.dirname(self.prefix) or "."
        base = os.path.basename(self.prefix)
        out = set()
        if not os.path.isdir(d):
            return []
        for name in os.listdir(d):
            if not name.startswith(base + "."):
                continue
            mid = name[len(base) + 1:]
            if mid.isdigit():
                # v3 directory — only a COMMITTED one exists to the
                # rotation; in-flight/torn saves (shards, tmp files,
                # no manifest) are structurally excluded
                if is_committed(os.path.join(d, name)):
                    out.add(int(mid))
            elif mid.endswith(".npz") and mid[:-4].isdigit():
                # legacy v1/v2 single file; in-flight ``.npz.tmp``
                # writers are excluded (suffix + random mkstemp name)
                out.add(int(mid[:-4]))
        return sorted(out)

    # ------------------------------------------------------ async saver

    def saver(self):
        """The lazily spawned background saver (async mode only)."""
        if self._saver is None:
            from .async_save import AsyncSaver
            self._saver = AsyncSaver()
        return self._saver

    def flush(self, timeout_s: Optional[float] = None) -> None:
        """Barrier: all submitted saves committed (no-op when saving
        synchronously).  The emergency/preemption save path calls
        this so 'checkpoint saved' means ON DISK."""
        if self._saver is not None:
            self._saver.flush(timeout_s)

    def drain(self, timeout_s: Optional[float] = None) -> None:
        """Shutdown: flush + stop + join the saver thread."""
        if self._saver is not None:
            self._saver.drain(timeout_s)

    def save_stats(self) -> Dict:
        """Saver counters/records (empty when synchronous)."""
        if self._saver is None:
            return {"saved": 0, "superseded": 0, "saves": []}
        return self._saver.stats()

    # ------------------------------------------------------ save/prune

    def _prune(self) -> None:
        """Drop checkpoints beyond the keep window.  Runs AFTER a
        commit (in async mode, on the saver thread post-commit): the
        guarantee 'a complete checkpoint always exists' would not
        survive pruning ahead of an uncommitted save.  Process 0 only
        — under multi-process SPMD every process scans one shared
        rotation."""
        import jax
        if jax.process_count() > 1 and jax.process_index() != 0:
            return
        for old in self.existing()[:-self.keep]:
            # both forms: a migrating rotation may hold a v3 dir AND
            # a legacy file for one epoch
            for p in (self.path(old), self.path(old) + ".npz"):
                try:
                    if os.path.isdir(p):
                        shutil.rmtree(p)
                    elif os.path.isfile(p):
                        os.remove(p)
                # best-effort prune: a leftover old checkpoint wastes
                # disk but harms nothing; the next save retries it
                except OSError:   # roc-lint: ok=swallowed-exception
                    pass

    def save(self, trainer) -> str:
        """Persist the trainer's state as ``<prefix>.<epoch>``.  Sync
        mode: the save is committed when this returns.  Async mode:
        only the finite guard + host snapshot run here (the step-path
        blocked time, recorded as ``last_block_ms``); the commit
        happens in the background — ``flush()`` to wait for it."""
        p = self.path(trainer.epoch)
        if not self.async_save:
            # checkpoint_trainer runs the single-sync finite guard
            # over params + opt state before anything touches disk
            checkpoint_trainer(trainer, p)
            self._prune()
            return p
        from ..utils.checkpoint import snapshot_trainer
        t0 = time.perf_counter()
        check_params_finite(trainer.params, trainer.opt_state)
        snap = snapshot_trainer(trainer)
        self.last_block_ms = snap.block_ms = round(
            (time.perf_counter() - t0) * 1e3, 3)
        if len(snap.writer_procs) > 1:
            # sharded across processes: coalescing decisions are
            # timing-dependent and would diverge between processes —
            # the commit barrier then deadlocks.  Save synchronously.
            emit("checkpoint",
                 f"async save: snapshot is sharded across "
                 f"{len(snap.writer_procs)} processes — saving "
                 f"synchronously (the commit barrier needs every "
                 f"process in lockstep)", kind="sync_fallback",
                 epoch=trainer.epoch)
            from ..utils.checkpoint import write_snapshot
            write_snapshot(p, snap)
            self._prune()
            return p
        # the keep-window prune rides the saver thread, strictly
        # AFTER this snapshot's commit — pruning ahead of an
        # uncommitted save could leave zero complete checkpoints
        self.saver().submit(snap, p, on_commit=self._prune)
        return p

    def restore_latest(self, trainer,
                       only_if_ahead: bool = False) -> Optional[int]:
        """Restore the newest intact checkpoint into ``trainer``;
        returns its epoch or None if none restored.  Every candidate
        is FULLY validated (v3: manifest + every listed shard CRC +
        coverage) before it can be selected — a manifest whose shard
        went missing falls through to the next-newest checkpoint like
        any other corruption.  ``only_if_ahead`` skips the restore
        when the trainer has already progressed past the newest
        checkpoint (never rewind live progress)."""
        # an in-flight async save must land (or fail loudly) before
        # the scan: restoring around a half-written newest checkpoint
        # would race its commit
        self.flush()
        epochs = self.existing()
        if not epochs:
            return None
        if only_if_ahead and epochs[-1] <= trainer.epoch:
            return None
        for ep in reversed(epochs):
            if only_if_ahead and ep <= trainer.epoch:
                # the newest was ahead but corrupt, and every intact
                # fallback is at/behind the live trainer — rewinding
                # live progress is exactly what only_if_ahead forbids
                return None
            path = self.path_for(ep)
            try:
                restore_trainer(trainer, path)
                return ep
            except CheckpointCorrupt as e:
                emit("resilience",
                     f"checkpoint {os.path.basename(path)} failed "
                     f"integrity validation ({e}) — falling back to "
                     f"the previous one", kind="corrupt_fallback",
                     path=path, epoch=ep)
        return None


def train_with_recovery(trainer, target_epoch: int,
                        rotation: CheckpointRotation,
                        checkpoint_every: int = 50,
                        max_retries: int = 3,
                        on_failure: Optional[Callable[[Exception], None]]
                        = None) -> List[Dict[str, float]]:
    """Train until ``trainer.epoch == target_epoch`` in checkpointed
    rounds, with bounded retry-from-last-good-checkpoint on every
    recoverable failure class (:data:`RECOVERABLE`).

    Resumes from the newest intact checkpoint first, so re-invoking
    the same command after a crash — SIGKILL, preemption, OOM —
    continues the run (elastic restart; the restore also rides onto a
    different partition count, utils/checkpoint.py).  On retry the
    trainer's PRNG key is perturbed — an identical key would
    deterministically replay the same failing trajectory (dropout
    masks included).  A :class:`~roc_tpu.resilience.preempt.Preempted`
    raise is NOT retried: it writes an emergency checkpoint through
    the same rotation (FLUSHED — 'emergency checkpoint saved' must
    mean on disk) and propagates, so the caller exits with the
    restartable code.  An async rotation is drained on the way out;
    a wedged saver surfaces as StallFailure (exit 75), never a hang.
    """
    import jax
    from . import inject
    history: List[Dict[str, float]] = []
    # resume a crashed run, but never rewind a live trainer that is
    # already past the newest checkpoint
    rotation.restore_latest(trainer, only_if_ahead=True)
    retries = 0
    try:
        while trainer.epoch < target_epoch:
            round_epochs = min(checkpoint_every,
                               target_epoch - trainer.epoch)
            try:
                hist = trainer.train(epochs=round_epochs)
                for m in hist:
                    check_finite(m)
                # save() validates params+opt state finiteness (one
                # sync) before persisting — a NaN that arose between
                # the round's last eval and the boundary is caught
                # here, BEFORE the round's records join the returned
                # history (a refused round is retried, so keeping its
                # metrics would duplicate the replayed epochs)
                path = rotation.save(trainer)
                history.extend(hist)
                retries = 0
                spec = inject.current()
                if spec is not None and not spec.fired:
                    # drills that act on the just-saved artifact
                    # (bitflip/shard corruption) need it COMMITTED;
                    # an armed saver-side site fires inside this
                    # flush, which is exactly the point
                    rotation.flush()
                inject.maybe_corrupt_checkpoint(path, trainer.epoch)
                inject.maybe_corrupt_shard(path, trainer.epoch)
            except Preempted as e:
                # emergency checkpoint through the SAME rotation,
                # flushed; a poisoned state still refuses to persist
                # (the previous good checkpoint then serves the
                # restart)
                saved: Optional[str]
                try:
                    saved = rotation.save(trainer)
                    rotation.flush()
                except NumericFailure:
                    saved = None
                emit("resilience",
                     f"preempted at epoch {trainer.epoch}: "
                     + (f"emergency checkpoint "
                        f"{os.path.basename(saved)}"
                        if saved else "state non-finite, not persisted")
                     + " — exiting restartable", kind="preempt",
                     epoch=trainer.epoch, checkpoint=saved,
                     reason=str(e))
                raise
            except RECOVERABLE as e:
                if on_failure:
                    on_failure(e)
                retries += 1
                emit("resilience",
                     f"recovering from {type(e).__name__} at epoch "
                     f"{trainer.epoch} (retry {retries}/{max_retries}): "
                     f"{e}", kind="recovery", error=type(e).__name__,
                     epoch=trainer.epoch, retry=retries,
                     max_retries=max_retries)
                if retries > max_retries:
                    raise
                if rotation.restore_latest(trainer) is None:
                    raise
                trainer.key = jax.random.fold_in(trainer.key, retries)
    finally:
        # shutdown path for the async saver: every accepted save
        # committed (or a loud StallFailure/IO error — the CLI maps
        # those to the restartable exit).  While another exception is
        # already propagating, a drain failure must not MASK it —
        # report and let the original fly.  The propagation test is
        # exc_info BEFORE the drain: a stored background error may
        # carry its own pre-existing __context__ chain from the saver
        # thread, which says nothing about THIS control flow.
        import sys as _sys
        propagating = _sys.exc_info()[0] is not None
        try:
            rotation.drain()
        except Exception as de:  # noqa: BLE001 - see below
            if not propagating:
                # clean path: the drain failure IS the outcome (a
                # wedged saver exits restartable via StallFailure, a
                # failed final save via OSError)
                raise
            emit("resilience",
                 f"saver drain failed during exception teardown: "
                 f"{type(de).__name__}: {de}", kind="saver_error",
                 error=type(de).__name__)
    return history
