"""Checkpoint-restart recovery: the loop that makes faults survivable.

Grown out of ``utils/resilience.py`` (which remains as a compat shim)
into the resilience subsystem's driver: :func:`train_with_recovery`
now serves BOTH trainers (the distributed path checkpoints replicated
state once via utils/checkpoint.py and restores through the partition
rebuild), retries every *recoverable* failure class — numeric
poisoning (:class:`NumericFailure`), watchdog-detected stalls
(:class:`StallFailure`, obs/heartbeat.py), and transient I/O errors
(``OSError``, e.g. the streamed tier's staging path) — and cooperates
with the preemption guard (:mod:`roc_tpu.resilience.preempt`): a
Preempted raise writes an emergency checkpoint through the SAME
rotation and propagates, so the CLI can exit restartable.

Every decision leaves a dated ``resilience`` event; the drill matrix
(tests/test_drills.py) proves each failure class end to end.
"""

from __future__ import annotations

import math
import os
from typing import Callable, Dict, List, Optional

from ..obs.events import emit
from ..obs.heartbeat import StallFailure
from ..utils.checkpoint import (CheckpointCorrupt, checkpoint_trainer,
                                restore_trainer)
from .preempt import Preempted


class NumericFailure(RuntimeError):
    """Raised when training metrics or parameters go NaN/Inf."""


# the failure classes the retry loop may restore-and-retry: numeric
# poisoning (restored state discards the poison), watchdog-detected
# stalls, and transient I/O (staging/storage hiccups).  Anything else
# is a bug and must propagate.
RECOVERABLE = (NumericFailure, StallFailure, OSError)


def check_finite(metrics: Dict[str, float]) -> None:
    loss = metrics.get("train_loss")
    if loss is not None and not math.isfinite(loss):
        raise NumericFailure(f"non-finite train loss: {loss!r} "
                             f"at epoch {metrics.get('epoch')}")


_ALL_FINITE = None


def check_params_finite(params, opt_state=None) -> None:
    """Raise if any param (or optimizer-state) leaf holds NaN/Inf —
    the guard that keeps a poisoned state out of every checkpoint.

    ONE device sync total: the whole pytree folds into a single jitted
    all-finite reduction (the old per-leaf ``bool(isfinite(leaf)
    .all())`` walk synced the dispatch pipeline once per leaf — dozens
    of round trips per checkpoint on deep models).  The per-leaf walk
    survives only on the failure path, to name the culprit."""
    import jax
    import jax.numpy as jnp
    global _ALL_FINITE
    if _ALL_FINITE is None:
        def _impl(trees):
            ok = jnp.asarray(True)
            for leaf in jax.tree_util.tree_leaves(trees):
                if jnp.issubdtype(leaf.dtype, jnp.inexact):
                    ok = jnp.logical_and(ok, jnp.isfinite(leaf).all())
            return ok
        _ALL_FINITE = jax.jit(_impl)
    if bool(_ALL_FINITE((params, opt_state))):
        return
    for label, tree in (("param", params), ("opt_state", opt_state)):
        if tree is None:
            continue
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            if jnp.issubdtype(leaf.dtype, jnp.inexact) and \
                    not bool(jnp.isfinite(leaf).all()):
                raise NumericFailure(
                    f"non-finite {label} at "
                    f"{jax.tree_util.keystr(path)}")
    raise NumericFailure("non-finite value in params/opt state")


class CheckpointRotation:
    """Keep the most recent ``keep`` checkpoints of a trainer as
    ``<prefix>.<epoch>.npz`` (saves are atomic via checkpoint.py).

    ``save`` finite-checks params AND optimizer state (one device
    sync, :func:`check_params_finite` via ``checkpoint_trainer`` —
    the guard covers EVERY trainer save, not just rotation rounds) so
    a poisoned state is never persisted; ``restore_latest`` validates
    integrity on the way
    in and falls back to the next-newest checkpoint when the newest is
    corrupt (:class:`~roc_tpu.utils.checkpoint.CheckpointCorrupt`),
    with a dated resilience event either way."""

    def __init__(self, prefix: str, keep: int = 3):
        self.prefix = prefix
        self.keep = keep

    def path(self, epoch: int) -> str:
        return f"{self.prefix}.{epoch}.npz"

    def existing(self) -> List[int]:
        d = os.path.dirname(self.prefix) or "."
        base = os.path.basename(self.prefix)
        out = []
        if not os.path.isdir(d):
            return out
        for name in os.listdir(d):
            # in-flight ``.npz.tmp`` writers are structurally excluded
            # (suffix + random mkstemp name): a save killed mid-write
            # can never be restored (tests/test_drills.py kill_in_save)
            if name.startswith(base + ".") and name.endswith(".npz"):
                mid = name[len(base) + 1:-4]
                if mid.isdigit():
                    out.append(int(mid))
        return sorted(out)

    def save(self, trainer) -> str:
        p = self.path(trainer.epoch)
        # checkpoint_trainer runs the single-sync finite guard over
        # params + opt state before anything touches disk
        checkpoint_trainer(trainer, p)
        for old in self.existing()[:-self.keep]:
            try:
                os.remove(self.path(old))
            # best-effort prune: a leftover old checkpoint wastes disk
            # but harms nothing, and the next save retries the prune
            except OSError:   # roc-lint: ok=swallowed-exception
                pass
        return p

    def restore_latest(self, trainer,
                       only_if_ahead: bool = False) -> Optional[int]:
        """Restore the newest intact checkpoint into ``trainer``;
        returns its epoch or None if none restored.  ``only_if_ahead``
        skips the restore when the trainer has already progressed past
        the newest checkpoint (never rewind live progress)."""
        epochs = self.existing()
        if not epochs:
            return None
        if only_if_ahead and epochs[-1] <= trainer.epoch:
            return None
        for ep in reversed(epochs):
            if only_if_ahead and ep <= trainer.epoch:
                # the newest was ahead but corrupt, and every intact
                # fallback is at/behind the live trainer — rewinding
                # live progress is exactly what only_if_ahead forbids
                return None
            path = self.path(ep)
            try:
                restore_trainer(trainer, path)
                return ep
            except CheckpointCorrupt as e:
                emit("resilience",
                     f"checkpoint {os.path.basename(path)} failed "
                     f"integrity validation ({e}) — falling back to "
                     f"the previous one", kind="corrupt_fallback",
                     path=path, epoch=ep)
        return None


def train_with_recovery(trainer, target_epoch: int,
                        rotation: CheckpointRotation,
                        checkpoint_every: int = 50,
                        max_retries: int = 3,
                        on_failure: Optional[Callable[[Exception], None]]
                        = None) -> List[Dict[str, float]]:
    """Train until ``trainer.epoch == target_epoch`` in checkpointed
    rounds, with bounded retry-from-last-good-checkpoint on every
    recoverable failure class (:data:`RECOVERABLE`).

    Resumes from the newest intact checkpoint first, so re-invoking
    the same command after a crash — SIGKILL, preemption, OOM —
    continues the run (elastic restart; the restore also rides onto a
    different partition count, utils/checkpoint.py).  On retry the
    trainer's PRNG key is perturbed — an identical key would
    deterministically replay the same failing trajectory (dropout
    masks included).  A :class:`~roc_tpu.resilience.preempt.Preempted`
    raise is NOT retried: it writes an emergency checkpoint through
    the same rotation and propagates, so the caller exits with the
    restartable code.
    """
    import jax
    history: List[Dict[str, float]] = []
    # resume a crashed run, but never rewind a live trainer that is
    # already past the newest checkpoint
    rotation.restore_latest(trainer, only_if_ahead=True)
    retries = 0
    while trainer.epoch < target_epoch:
        round_epochs = min(checkpoint_every, target_epoch - trainer.epoch)
        try:
            hist = trainer.train(epochs=round_epochs)
            for m in hist:
                check_finite(m)
            # save() validates params+opt state finiteness (one sync)
            # before persisting — a NaN that arose between the round's
            # last eval and the boundary is caught here, BEFORE the
            # round's records join the returned history (a refused
            # round is retried, so keeping its metrics would duplicate
            # the replayed epochs)
            path = rotation.save(trainer)
            history.extend(hist)
            retries = 0
            from . import inject
            inject.maybe_corrupt_checkpoint(path, trainer.epoch)
        except Preempted as e:
            # emergency checkpoint through the SAME rotation; a
            # poisoned state still refuses to persist (the previous
            # good checkpoint then serves the restart)
            saved: Optional[str]
            try:
                saved = rotation.save(trainer)
            except NumericFailure:
                saved = None
            emit("resilience",
                 f"preempted at epoch {trainer.epoch}: "
                 + (f"emergency checkpoint {os.path.basename(saved)}"
                    if saved else "state non-finite, not persisted")
                 + " — exiting restartable", kind="preempt",
                 epoch=trainer.epoch, checkpoint=saved,
                 reason=str(e))
            raise
        except RECOVERABLE as e:
            if on_failure:
                on_failure(e)
            retries += 1
            emit("resilience",
                 f"recovering from {type(e).__name__} at epoch "
                 f"{trainer.epoch} (retry {retries}/{max_retries}): "
                 f"{e}", kind="recovery", error=type(e).__name__,
                 epoch=trainer.epoch, retry=retries,
                 max_retries=max_retries)
            if retries > max_retries:
                raise
            if rotation.restore_latest(trainer) is None:
                raise
            trainer.key = jax.random.fold_in(trainer.key, retries)
    return history
