"""Preemption-safe elastic training: the resilience subsystem.

Four coordinated pieces (ISSUE 8):

- **Checkpointing** — ``utils/checkpoint.py`` writes versioned,
  CRC32-validated, config-fingerprinted atomic ``.npz`` checkpoints;
  both trainers (and the multihost path: process 0 writes, every
  process restores through ``put_replicated``) save/restore through
  it, including *elastic* restores onto a different partition count.
- **Recovery** (:mod:`.recovery`) — keep-last-k rotation with
  corrupt-checkpoint fallback + the bounded retry loop
  ``train_with_recovery`` covering numeric failures, watchdog stalls,
  and transient I/O.
- **Preemption** (:mod:`.preempt`) — SIGTERM/SIGINT grace handling:
  finish the in-flight step, emergency-checkpoint, exit with the
  restartable code (75).
- **Fault injection** (:mod:`.inject`) — the drill harness: one armed
  fault per process (``ROC_TPU_FAULT=site:epoch[:proc]``), each site
  proven by an e2e subprocess test (tests/test_drills.py).

This ``__init__`` stays import-light (inject/preempt only — they sit
on hot hook paths); the recovery layer loads lazily on first use.
"""

from . import inject, preempt  # noqa: F401  (import-light)
from .preempt import Preempted, PreemptionGuard, RESTARTABLE_EXIT_CODE  # noqa: F401

_LAZY = ("NumericFailure", "RECOVERABLE", "CheckpointRotation",
         "check_finite", "check_params_finite", "train_with_recovery")


def __getattr__(name):
    if name in _LAZY:
        from . import recovery
        return getattr(recovery, name)
    if name == "StallFailure":
        from ..obs.heartbeat import StallFailure
        return StallFailure
    if name in ("CheckpointCorrupt", "trainer_fingerprint"):
        from ..utils import checkpoint
        return getattr(checkpoint, name)
    raise AttributeError(f"module {__name__!r} has no attribute "
                         f"{name!r}")
