"""Preemption-safe elastic training: the resilience subsystem.

Five coordinated pieces (ISSUE 8 + the ISSUE 15 checkpoint-v3
rebuild):

- **Checkpointing** — ``utils/checkpoint.py`` format v3: per-process
  SHARD files under a crash-consistent two-phase commit (shards land
  via tmp-fsync-rename, process 0 publishes ``MANIFEST.json`` last —
  an uncommitted directory is invisible to restore), per-array CRC32s
  + config fingerprints, and gather-on-restore that reassembles any
  saved (P, mesh) layout onto any restore layout — including
  *elastic* restores onto a different partition count.  v1/v2
  single-file checkpoints load with a loud warning.
- **Async saving** (:mod:`.async_save`) — a dedicated saver thread
  (bounded queue depth 1, newer snapshot supersedes a queued one)
  takes the host snapshot off the step path and runs CRC + write +
  commit in the background; ``flush()`` is the emergency-save
  barrier, ``drain()`` the watchdog-bounded shutdown path.
- **Recovery** (:mod:`.recovery`) — keep-last-k rotation with
  corrupt-checkpoint fallback (every candidate's manifest + shard
  CRCs validated BEFORE selection) + the bounded retry loop
  ``train_with_recovery`` covering numeric failures, watchdog stalls,
  and transient I/O.
- **Preemption** (:mod:`.preempt`) — SIGTERM/SIGINT grace handling:
  finish the in-flight step, emergency-checkpoint, exit with the
  restartable code (75).
- **Fault injection** (:mod:`.inject`) — the drill harness: one armed
  fault per process (``ROC_TPU_FAULT=site:epoch[:proc]``), each site
  proven by an e2e subprocess test (tests/test_drills.py).

This ``__init__`` stays import-light (inject/preempt only — they sit
on hot hook paths); the recovery layer loads lazily on first use.
"""

from . import inject, preempt  # noqa: F401  (import-light)
from .preempt import Preempted, PreemptionGuard, RESTARTABLE_EXIT_CODE  # noqa: F401

_LAZY = ("NumericFailure", "RECOVERABLE", "CheckpointRotation",
         "check_finite", "check_params_finite", "train_with_recovery")


def __getattr__(name):
    if name in _LAZY:
        from . import recovery
        return getattr(recovery, name)
    if name == "AsyncSaver":
        from .async_save import AsyncSaver
        return AsyncSaver
    if name == "StallFailure":
        from ..obs.heartbeat import StallFailure
        return StallFailure
    if name in ("CheckpointCorrupt", "trainer_fingerprint"):
        from ..utils import checkpoint
        return getattr(checkpoint, name)
    raise AttributeError(f"module {__name__!r} has no attribute "
                         f"{name!r}")
