"""Preemption handling: turn SIGTERM/SIGINT into a graceful restart.

TPU capacity is preemptible: the scheduler delivers SIGTERM and gives
the process a grace window before SIGKILL.  The reference loses the
whole run; here an installed :class:`PreemptionGuard` records the
signal, the epoch loop finishes the in-flight step and raises
:class:`Preempted` at the next epoch boundary, the recovery layer
writes an emergency checkpoint through the normal rotation, and the
CLI exits with :data:`RESTARTABLE_EXIT_CODE` — the distinct code a
supervisor (or the e2e drills) uses to re-invoke the identical
command, which resumes from the emergency checkpoint.

Signal handlers only set flags (no I/O: the event bus lock is not
reentrant and a signal can land inside ``emit``); the dated
``resilience`` event is emitted from the normal control flow that
handles the raise.  A second signal restores the default disposition
and re-delivers itself — a stuck teardown can always be killed.
This flag-only contract is no longer just prose: roc-lint level six's
``signal-unsafe-handler`` rule (``analysis/concurrency_lint.py``)
fails the gate on any lock/emit/import/buffered-I/O in a registered
handler's body.
"""

from __future__ import annotations

import os
import signal
import time
from typing import Dict, Optional

# module-level on purpose: _handle runs in signal context, where an
# import could deadlock on the interpreter import lock if the signal
# lands while the main thread is mid-import (roc-lint
# signal-unsafe-handler found the old lazy import)
from ..obs.heartbeat import stall_interrupt_pending

# os.EX_TEMPFAIL: "temporary failure, retry later" — the one exit code
# a supervisor may treat as "re-invoke the same command"
RESTARTABLE_EXIT_CODE = 75

DEFAULT_GRACE_S = 30.0


class Preempted(RuntimeError):
    """Raised at an epoch boundary after a preemption signal; carries
    the restartable-exit contract (never a failure of the model)."""


class PreemptionGuard:
    """Installs SIGTERM/SIGINT handlers that request a graceful stop.

    ``requested()`` flips after the first signal; the epoch loop polls
    it once per epoch (``run_epoch_loop``) so the in-flight step always
    completes before the stop is acted on.  ``grace_s`` is advisory
    context for the emergency-checkpoint path (how long the scheduler
    gives us), recorded in the resilience event."""

    def __init__(self, grace_s: float = DEFAULT_GRACE_S):
        self.grace_s = float(grace_s)
        self.requested_at: Optional[float] = None
        self.signum: Optional[int] = None
        self._prev: Dict[int, object] = {}

    def install(self) -> "PreemptionGuard":
        for sig in (signal.SIGTERM, signal.SIGINT):
            self._prev[sig] = signal.signal(sig, self._handle)
        return self

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()

    def _handle(self, signum, frame) -> None:
        if signum == signal.SIGINT:
            # a Heartbeat stall deadline interrupts the main thread by
            # simulating SIGINT (obs/heartbeat.py); owning the handler
            # must not swallow it — re-raise so the guarded region's
            # __exit__ converts it into StallFailure
            if stall_interrupt_pending():
                raise KeyboardInterrupt
        if self.requested_at is not None:
            # second signal: stop being graceful — restore the default
            # disposition and re-deliver, so a wedged teardown dies
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
            return
        self.requested_at = time.monotonic()
        self.signum = int(signum)
        # flag-only (async-signal-safe-ish): the raw note below avoids
        # the event-bus lock; the structured resilience event is
        # emitted by whoever handles the Preempted raise
        try:
            # os.write on a raw fd is the POSIX async-signal-safe
            # primitive (no buffering, no locks — unlike print/emit):
            # roc-lint: ok=signal-unsafe-handler
            os.write(2, b"# preemption signal received; finishing the "
                        b"in-flight epoch step\n")
        # stderr gone mid-teardown: nowhere left to tell anyone
        except OSError:  # roc-lint: ok=swallowed-exception
            pass

    def requested(self) -> bool:
        return self.requested_at is not None


_GUARD: Optional[PreemptionGuard] = None


def install(grace_s: float = DEFAULT_GRACE_S) -> PreemptionGuard:
    """Install (or re-use) the process-wide guard."""
    global _GUARD
    if _GUARD is None:
        _GUARD = PreemptionGuard(grace_s=grace_s).install()
    else:
        _GUARD.grace_s = float(grace_s)
    return _GUARD


def reset() -> None:
    """Uninstall and forget the process guard (tests)."""
    global _GUARD
    if _GUARD is not None:
        _GUARD.uninstall()
        _GUARD = None


def guard() -> Optional[PreemptionGuard]:
    return _GUARD


def requested() -> bool:
    return _GUARD is not None and _GUARD.requested()


def raise_if_preempted(epoch: Optional[int] = None) -> None:
    """Epoch-boundary check (run_epoch_loop): raise :class:`Preempted`
    once a signal has been recorded."""
    if requested():
        sig = _GUARD.signum
        name = signal.Signals(sig).name if sig is not None else "?"
        # flight recorder: the grace window may not survive to a clean
        # exit (the scheduler's SIGKILL follows), so the telemetry ring
        # is persisted at the boundary, from normal control flow — the
        # signal handler itself stays flag-only
        from ..obs.events import dump_flight_record
        dump_flight_record(f"preempted:{name}")
        raise Preempted(
            f"{name} received"
            + (f" (epoch {epoch} step completed)" if epoch is not None
               else "")
            + f"; grace {_GUARD.grace_s:.0f}s")
