"""Site-based fault injection: drill the failure paths for real.

The reference's only answer to a fault is ``assert``/``exit(1)``
(``cuda_helper.h:6-28``) — and a recovery stack that is never
*exercised* is indistinguishable from one that does not work.  This
module arms exactly one fault per process (``ROC_TPU_FAULT=
site:epoch[:proc]`` or ``TrainConfig.fault``) and fires it at the
matching hook point; every site is covered by an e2e subprocess drill
(tests/test_drills.py) that injects, restarts, and asserts the run
still reaches the target epoch with the uninterrupted run's loss.

Sites (each fires AT MOST ONCE per process — ``FaultSpec.fired``):

- ``nan_grads``        poison one param leaf with NaN after the armed
                       epoch's step (the silent numeric-failure mode).
- ``sigkill``          SIGKILL this process mid-run at the armed epoch.
- ``sigterm``          deliver SIGTERM to this process at the armed
                       epoch (drills the preemption grace path).
- ``kill_in_save``     SIGKILL between the shard tmp-file write and
                       its atomic rename (atomicity drill — the torn
                       ``.npz.tmp`` must never be restorable).
- ``kill_in_async_save``  SIGKILL inside the v3 two-phase-commit
                       window: shards renamed into place, manifest
                       NOT yet published — the restart must see only
                       the previous committed checkpoint (fires on
                       the saver thread in async mode, inline in
                       sync mode; the window is the site).
- ``bitflip_checkpoint``  corrupt the just-committed checkpoint's
                       COMMIT RECORD (v3: first byte of
                       MANIFEST.json; legacy file: mid-file byte),
                       then SIGKILL — the restart must fall back.
- ``shard_corrupt``    flip one byte of a committed checkpoint's
                       shard file, then SIGKILL: the restore scan's
                       manifest-vs-shard CRC validation must reject
                       it and fall back to the previous checkpoint.
- ``saver_stall``      wedge the async saver thread indefinitely —
                       flush()/drain() deadlines must bound the
                       damage (StallFailure, restartable exit).
- ``staging_io``       raise OSError from the StagingPool's staging
                       call site at the armed epoch (streamed tier).
- ``stall_compile``    hang the first-compile barrier (the watchdog
                       deadline must convert it into a StallFailure).

Serve sites (ISSUE 13): the same ``site:epoch[:proc]`` grammar drills
the serving tier, with ``epoch`` read as the server's MICROBATCH index
(``Server`` notes it per dispatch) and ``proc`` as the REPLICA index a
router assigned (``note_proc_index`` — serve replicas are plain
subprocesses with no jax distributed identity):

- ``replica_sigkill``  SIGKILL this replica mid-dispatch — the router
                       must fail over its in-flight requests.
- ``replica_stall``    hang one dispatch indefinitely (straggler) —
                       hedged re-dispatch / deadlines must cover.
- ``table_swap_mid_query``  publish a real ``add_edges`` table-version
                       swap between a microbatch's version capture and
                       its device dispatch — the batch must finish
                       bit-exact on the version it captured.
- ``serve_io``         raise OSError from the dispatch site — the
                       replica reports a retryable failure and the
                       router re-dispatches elsewhere.

Import-light by design: the hook points live in hot setup paths
(checkpoint save, staging, the epoch loop, the serve dispatcher) and
an unarmed check is a couple of attribute reads.
"""

from __future__ import annotations

import os
import signal
import time
from dataclasses import dataclass
from typing import Optional

from ..obs.events import emit

ENV_VAR = "ROC_TPU_FAULT"

SITES = ("nan_grads", "sigkill", "sigterm", "kill_in_save",
         "kill_in_async_save", "shard_corrupt", "saver_stall",
         "bitflip_checkpoint", "staging_io", "stall_compile",
         "replica_sigkill", "replica_stall", "table_swap_mid_query",
         "serve_io")


@dataclass
class FaultSpec:
    """One armed fault: ``site:epoch[:proc]``.  ``proc`` restricts the
    fault to one SPMD process index (multihost drills); None fires on
    any process."""
    site: str
    epoch: int
    proc: Optional[int] = None
    fired: bool = False

    def spec_str(self) -> str:
        s = f"{self.site}:{self.epoch}"
        return s if self.proc is None else f"{s}:{self.proc}"


_SPEC: Optional[FaultSpec] = None
_ENV_CHECKED = False
# the epoch the training loop last entered (run_epoch_loop notes it) —
# lets sites without epoch context (staging_io) match the armed epoch
_EPOCH: Optional[int] = None
# explicit process-identity override for serve replicas: a router's
# replica subprocess has no jax distributed identity, so the ``:proc``
# arm (replica index) is pinned by the replica itself at startup
_PROC_OVERRIDE: Optional[int] = None


def parse(spec: str) -> FaultSpec:
    parts = spec.split(":")
    if len(parts) not in (2, 3) or parts[0] not in SITES:
        raise ValueError(
            f"bad fault spec {spec!r}; expected site:epoch[:proc] with "
            f"site in {SITES}")
    try:
        epoch = int(parts[1])
        proc = int(parts[2]) if len(parts) == 3 else None
    except ValueError:
        raise ValueError(f"bad fault spec {spec!r}: epoch/proc must "
                         "be integers") from None
    if epoch < 0 or (proc is not None and proc < 0):
        # every site is epoch-gated; a negative epoch can never match
        # and would silently arm a no-op drill
        raise ValueError(f"bad fault spec {spec!r}: epoch/proc must "
                         "be >= 0")
    return FaultSpec(site=parts[0], epoch=epoch, proc=proc)


def arm(spec: Optional[str]) -> Optional[FaultSpec]:
    """Arm a fault from its spec string (idempotent: re-arming the
    identical spec keeps the existing record, ``fired`` included — a
    second ``train()`` call must not re-fire a spent fault)."""
    global _SPEC
    if not spec:
        return _SPEC
    new = parse(spec)
    if _SPEC is not None and (_SPEC.site, _SPEC.epoch, _SPEC.proc) == \
            (new.site, new.epoch, new.proc):
        return _SPEC
    _SPEC = new
    return _SPEC


def disarm() -> None:
    """Reset (tests)."""
    global _SPEC, _ENV_CHECKED, _EPOCH, _PROC_OVERRIDE
    _SPEC = None
    _ENV_CHECKED = False
    _EPOCH = None
    _PROC_OVERRIDE = None


def note_proc_index(idx: int) -> None:
    """Pin this process's identity for the ``:proc`` arm — serve
    replicas call it with their router-assigned replica index (takes
    precedence over ``jax.process_index()``)."""
    global _PROC_OVERRIDE
    _PROC_OVERRIDE = int(idx)


def current() -> Optional[FaultSpec]:
    """The armed fault, arming lazily from ``ROC_TPU_FAULT`` on first
    use (an explicit :func:`arm` wins over the environment)."""
    global _ENV_CHECKED
    if _SPEC is None and not _ENV_CHECKED:
        _ENV_CHECKED = True
        env = os.environ.get(ENV_VAR)
        if env:
            arm(env)
    return _SPEC


def note_epoch(epoch: int) -> None:
    global _EPOCH
    _EPOCH = int(epoch)


def _proc_ok(spec: FaultSpec) -> bool:
    if spec.proc is None:
        return True
    if _PROC_OVERRIDE is not None:
        return _PROC_OVERRIDE == spec.proc
    try:
        import jax
        return jax.process_index() == spec.proc
    except Exception:  # jax not initialized: single process
        return spec.proc == 0


def _fire(spec: FaultSpec, detail: str, **fields) -> None:
    """Mark the fault spent and leave a dated resilience event BEFORE
    acting — a SIGKILL site must still be attributable from the JSONL
    artifact alone.  The crash flight recorder dumps here too: a
    killed process's last telemetry window (this fault event last)
    survives even when no JSONL sink was configured."""
    spec.fired = True
    emit("resilience", f"fault injected: {spec.spec_str()} — {detail}",
         kind="fault", site=spec.site, epoch=spec.epoch, **fields)
    from ..obs.events import dump_flight_record
    dump_flight_record(f"fault:{spec.site}")


def _ready(site: str, epoch: Optional[int] = None, *,
           mode: str = "exact") -> Optional[FaultSpec]:
    """The ONE readiness gate every site fires through: armed, not
    yet spent, right site, right process, and the epoch condition —
    ``exact`` (caller-passed epoch == armed epoch; None skips the
    check), ``at_least`` (caller-passed epoch >= armed epoch), or
    ``noted`` (the loop-noted ``_EPOCH`` == armed epoch — for sites
    without caller epoch context; None never matches, so staging done
    OUTSIDE the epoch loop can never eat an epoch-gated fault)."""
    spec = current()
    if spec is None or spec.fired or spec.site != site \
            or not _proc_ok(spec):
        return None
    if mode == "exact":
        if epoch is not None and epoch != spec.epoch:
            return None
    elif mode == "at_least":
        if epoch is None or epoch < spec.epoch:
            return None
    elif mode == "noted":
        if _EPOCH != spec.epoch:
            return None
    else:
        raise ValueError(f"unknown readiness mode {mode!r}")
    return spec


def _poison_params(trainer) -> None:
    import jax
    import jax.numpy as jnp
    done = [False]

    def poison(leaf):
        if not done[0] and jnp.issubdtype(leaf.dtype, jnp.floating):
            done[0] = True
            return leaf.at[(0,) * leaf.ndim].set(jnp.nan)
        return leaf

    trainer.params = jax.tree_util.tree_map(poison, trainer.params)


def epoch_hooks(trainer, epoch: int) -> None:
    """Epoch-boundary sites, called by ``run_epoch_loop`` after the
    in-flight step of ``epoch`` has been dispatched."""
    spec = _ready("nan_grads", epoch) or _ready("sigkill", epoch) \
        or _ready("sigterm", epoch)
    if spec is None:
        return
    if spec.site == "nan_grads":
        _fire(spec, "NaN written into one param leaf")
        _poison_params(trainer)
    elif spec.site == "sigkill":
        _fire(spec, "SIGKILL mid-epoch")
        os.kill(os.getpid(), signal.SIGKILL)
    elif spec.site == "sigterm":
        _fire(spec, "SIGTERM delivered (preemption drill)")
        os.kill(os.getpid(), signal.SIGTERM)


def maybe_kill_in_save(epoch: int) -> None:
    """Between the shard tmp write and the atomic rename
    (utils/checkpoint._write_shard): die with the ``.npz.tmp`` on
    disk — restore must never pick it up."""
    spec = _ready("kill_in_save", int(epoch))
    if spec is not None:
        _fire(spec, "SIGKILL mid-checkpoint-write (.npz.tmp on disk)")
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_kill_in_commit(epoch: int) -> None:
    """The v3 two-phase-commit window (utils/checkpoint.
    write_snapshot): shard files renamed into place, MANIFEST.json
    not yet published.  Dying here must leave the new directory
    INVISIBLE to restore_latest — only the previous committed
    checkpoint exists."""
    spec = _ready("kill_in_async_save", int(epoch))
    if spec is not None:
        _fire(spec, "SIGKILL between shard rename and manifest "
                    "commit (shards on disk, no manifest)")
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_saver_stall(epoch: int) -> None:
    """Async-saver wedge site (resilience/async_save.AsyncSaver
    _process): sleep far past any sane deadline ON the saver thread.
    flush()/drain() deadlines must convert the wedge into a
    StallFailure — an emergency save can be late, never unbounded."""
    spec = _ready("saver_stall", int(epoch), mode="at_least")
    if spec is not None:
        _fire(spec, "stalling the async saver thread")
        time.sleep(3600.0)


def _flip_byte(path: str, offset: Optional[int] = None) -> None:
    """Flip one byte in place (mid-file by default) + fsync."""
    with open(path, "r+b") as f:
        f.seek(0, os.SEEK_END)
        off = f.tell() // 2 if offset is None else offset
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))
        f.flush()
        os.fsync(f.fileno())


def maybe_corrupt_checkpoint(path: str, epoch: int) -> None:
    """After a committed rotation save: corrupt the COMMIT RECORD —
    v3 directory: the manifest's first byte (unparseable JSON);
    legacy file: one mid-file byte — then SIGKILL.  The restarted run
    must detect CheckpointCorrupt and fall back to the previous
    checkpoint."""
    spec = _ready("bitflip_checkpoint", int(epoch), mode="at_least")
    if spec is None:
        return
    target, off = path, None
    if os.path.isdir(path):
        target, off = os.path.join(path, "MANIFEST.json"), 0
    _fire(spec, f"bit-flipped {os.path.basename(target)}, then "
                f"SIGKILL", path=target)
    _flip_byte(target, off)
    os.kill(os.getpid(), signal.SIGKILL)


def maybe_corrupt_shard(path: str, epoch: int) -> None:
    """After a committed rotation save: flip one byte of a SHARD file
    inside the v3 directory, then SIGKILL — the restore scan must
    catch the manifest-vs-shard CRC mismatch and fall back (the
    manifest itself stays intact, which is exactly what makes this a
    different drill from bitflip_checkpoint)."""
    spec = _ready("shard_corrupt", int(epoch), mode="at_least")
    if spec is None:
        return
    target = path
    if os.path.isdir(path):
        shards = sorted(n for n in os.listdir(path)
                        if n.startswith("shard_")
                        and n.endswith(".npz"))
        if not shards:
            return
        target = os.path.join(path, shards[0])
    _fire(spec, f"bit-flipped shard {os.path.basename(target)}, then "
                f"SIGKILL", path=target)
    _flip_byte(target)
    os.kill(os.getpid(), signal.SIGKILL)


def maybe_staging_error() -> None:
    """StagingPool fault site (core/streaming._stage_block): an
    injected I/O error at the armed epoch — the recovery loop treats
    OSError from a training round as transient-retryable."""
    spec = _ready("staging_io", mode="noted")
    if spec is None:
        return
    _fire(spec, "OSError raised from the staging call site")
    raise OSError("injected StagingPool I/O fault "
                  f"({spec.spec_str()})")


def maybe_stall() -> None:
    """Compile-barrier stall site: sleep far past any sane deadline.
    Only the watchdog's ``ROC_TPU_STALL_TIMEOUT_S`` can end this
    (obs/heartbeat.py delivers SIGINT and converts it to
    StallFailure) — exactly the silent-hang class it exists for.
    Epoch-gated like every site: ``stall_compile:0`` stalls a fresh
    trainer's first compile, a later epoch stalls the recompile
    barrier of a run that reaches that epoch's barrier (e.g. after a
    shape-changing rebalance)."""
    spec = _ready("stall_compile", mode="noted")
    if spec is None:
        return
    _fire(spec, "stalling the compile barrier")
    time.sleep(3600.0)


def serve_batch_hooks(server, batch_no: int) -> None:
    """Serve-dispatch sites, called by ``Server._dispatch`` AFTER the
    microbatch captured its table version and BEFORE the device
    dispatch — exactly the window the versioned-swap and straggler
    drills target.  ``batch_no`` is the server's microbatch index;
    sites fire ``at_least`` so a burst that skips past the armed index
    still drills (fired-once like every site)."""
    spec = (_ready("replica_sigkill", batch_no, mode="at_least")
            or _ready("replica_stall", batch_no, mode="at_least")
            or _ready("table_swap_mid_query", batch_no,
                      mode="at_least")
            or _ready("serve_io", batch_no, mode="at_least"))
    if spec is None:
        return
    if spec.site == "replica_sigkill":
        _fire(spec, f"SIGKILL mid-dispatch (microbatch {batch_no})")
        os.kill(os.getpid(), signal.SIGKILL)
    elif spec.site == "replica_stall":
        _fire(spec, f"stalling dispatch of microbatch {batch_no} — "
                    f"hedging/deadlines must cover")
        time.sleep(3600.0)
    elif spec.site == "table_swap_mid_query":
        _fire(spec, f"publishing a table-version swap under "
                    f"microbatch {batch_no}'s captured version")
        try:
            # a REAL mutation (self edge on node 0): the in-flight
            # batch must finish bit-exact on the version it captured
            server.pred.invalidate([0], [0])
        except NotImplementedError:
            # backend without mutable tables (full / table-only):
            # nothing to swap — the dated fault event above still
            # records that the drill was exercised here
            emit("resilience", "table_swap_mid_query: backend has no "
                 "mutable table — swap skipped", kind="fault_noop",
                 site=spec.site)
    elif spec.site == "serve_io":
        _fire(spec, f"OSError raised from the serve dispatch site "
                    f"(microbatch {batch_no})")
        raise OSError(f"injected serve I/O fault ({spec.spec_str()})")
