"""Asynchronous checkpoint saving: d2h persistence off the step path.

The synchronous v2 save blocked the host for the full device_get +
CRC + write + fsync of the whole state tree — at scale, whole steady
epochs of wall time per checkpoint round.  The same overlap-don't-
block discipline PR 4 applied to h2d staging applies to d2h
persistence: the ONLY work that must run on the step path is the
host snapshot (``utils/checkpoint.snapshot_trainer`` — the arrays may
be donated into the very next step) plus the finite guard; CRC,
shard write, fsync, and the manifest commit all run on a dedicated
saver thread while training dispatches the next epochs.

Contract (drilled in tests/test_checkpoint_v3.py + tests/
test_drills.py):

- **Bounded queue, depth 1, coalescing**: at most one snapshot is
  queued behind the in-flight save; a newer snapshot SUPERSEDES a
  queued one (dated ``checkpoint``/``superseded`` event) — the saver
  can fall arbitrarily far behind without ever buffering more than
  two state copies or blocking the step path.
- **flush()** — the barrier preemption/emergency saves use: returns
  once the queue is empty and the in-flight save committed, bounded
  by a deadline (``ROC_TPU_STALL_TIMEOUT_S``, else
  :data:`DEFAULT_FLUSH_TIMEOUT_S`) and heartbeat-covered, so a
  wedged saver surfaces as dated ``stall`` events and a
  :class:`~roc_tpu.obs.heartbeat.StallFailure` instead of a silent
  hang.
- **drain()** — flush + stop + join: the shutdown path.  The thread
  is a daemon, so even an abandoned (wedged) saver cannot hold the
  process exit hostage.
- Background failures are stored and re-raised on the NEXT submit/
  flush — an async save never fails silently.
- Timeline: every completed save emits ``ckpt_write``/``ckpt_commit``
  span laps (the standard ``timeline``/``spans`` batch), so
  ``python -m roc_tpu.timeline`` renders the save overlapping the
  training bursts on the process lane.

Single-writer by design: coalescing decisions depend on saver timing
and therefore CANNOT be assumed identical across SPMD processes — a
snapshot whose tree is sharded across processes (``writer_procs`` >
1) must be saved synchronously (CheckpointRotation falls back and
says so); ``resolve_async_save``'s 'auto' only enables the async
path single-process.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..obs.events import emit
from ..obs.heartbeat import StallFailure, stall_timeout

DEFAULT_FLUSH_TIMEOUT_S = 600.0
# out-of-band override for the flush/drain deadline (the saver_stall
# drill pins it low WITHOUT arming the global heartbeat deadline)
ENV_FLUSH_TIMEOUT = "ROC_TPU_CKPT_FLUSH_TIMEOUT_S"
# keep the last few completed-save stat records (stats() / bench)
_STATS_KEEP = 8


def flush_timeout() -> float:
    """The flush/drain deadline: :data:`ENV_FLUSH_TIMEOUT` env >
    ``ROC_TPU_STALL_TIMEOUT_S`` (the global watchdog deadline) >
    :data:`DEFAULT_FLUSH_TIMEOUT_S`."""
    import os
    env = os.environ.get(ENV_FLUSH_TIMEOUT)
    if env:
        try:
            return float(env)
        except ValueError:
            # a typo'd deadline must not silently become 600 s
            emit("resilience",
                 f"ignoring non-numeric {ENV_FLUSH_TIMEOUT}={env!r} — "
                 f"using the default flush deadline",
                 kind="saver_error")
    return stall_timeout() or DEFAULT_FLUSH_TIMEOUT_S


class _Request:
    __slots__ = ("snap", "path", "t_submit", "on_commit")

    def __init__(self, snap, path: str, on_commit=None):
        self.snap = snap
        self.path = path
        self.t_submit = time.monotonic()
        self.on_commit = on_commit


class AsyncSaver:
    """The dedicated saver thread behind
    :class:`~roc_tpu.resilience.recovery.CheckpointRotation`'s async
    mode.  All shared state (pending slot, busy flag, stored error,
    stat ring) lives under one condition variable; the actual CRC +
    write + commit runs with NO lock held."""

    def __init__(self, name: str = "ckpt-saver"):
        self._cond = threading.Condition()
        self._name = name
        self._pending: Optional[_Request] = None
        self._busy = False
        self._stop = False
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._stats: List[Dict[str, Any]] = []
        self._superseded = 0
        self._saved = 0

    # ------------------------------------------------------ lifecycle

    def _ensure_thread_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop = False
            self._thread = threading.Thread(
                target=self._loop, name=self._name, daemon=True)
            self._thread.start()

    def submit(self, snap, path: str, on_commit=None) -> None:
        """Queue a snapshot for background save.  Raises a previously
        stored background failure (once); replaces (and reports) a
        still-queued older snapshot.  ``on_commit`` runs on the saver
        thread strictly AFTER the manifest commit (the rotation's
        keep-window prune rides it)."""
        dropped: Optional[_Request] = None
        with self._cond:
            err, self._error = self._error, None
            if err is None:
                self._ensure_thread_locked()
                if self._pending is not None:
                    dropped = self._pending
                    self._superseded += 1
                self._pending = _Request(snap, path, on_commit)
                self._cond.notify_all()
        if err is not None:
            raise err
        if dropped is not None:
            emit("checkpoint",
                 f"queued snapshot (epoch {dropped.snap.epoch}) "
                 f"superseded by epoch {snap.epoch} — queue depth 1, "
                 f"newest wins", console=False, kind="superseded",
                 epoch=dropped.snap.epoch, by=snap.epoch)

    def flush(self, timeout_s: Optional[float] = None) -> None:
        """Block until the queue is empty and no save is in flight —
        the emergency-save barrier.  Deadline-bounded: a wedged saver
        raises :class:`StallFailure` (never a silent hang), with
        heartbeat ``stall`` events dating the wait."""
        from ..obs.heartbeat import Heartbeat
        deadline = time.monotonic() + (
            timeout_s if timeout_s is not None else flush_timeout())
        # deadline_s=0: this wait has its own bounded deadline — the
        # heartbeat contributes the dated evidence trail only
        with Heartbeat("ckpt_flush", deadline_s=0):
            with self._cond:
                while self._pending is not None or self._busy:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise StallFailure(
                            "async checkpoint saver wedged: flush() "
                            "deadline exceeded with a save still in "
                            "flight")
                    self._cond.wait(timeout=min(left, 1.0))
                err, self._error = self._error, None
        if err is not None:
            raise err

    def drain(self, timeout_s: Optional[float] = None) -> None:
        """Shutdown path: flush, then stop and join the thread.  A
        wedged saver raises the flush's StallFailure; the daemon
        thread is abandoned (it cannot hold exit hostage)."""
        try:
            self.flush(timeout_s)
        finally:
            with self._cond:
                self._stop = True
                self._cond.notify_all()
            if self._thread is not None:
                # only drain/submit touch _thread, and submits after a
                # drain re-spawn it — no concurrent mutation here
                self._thread.join(timeout=5.0)

    # ----------------------------------------------------- the thread

    def _loop(self) -> None:
        while True:
            with self._cond:
                while self._pending is None and not self._stop:
                    self._cond.wait()
                if self._stop and self._pending is None:
                    return
                req = self._pending
                self._pending = None
                self._busy = True
            try:
                self._process(req)
            except Exception as e:  # noqa: BLE001 - stored, re-raised on the next submit/flush
                with self._cond:
                    self._error = e
                emit("resilience",
                     f"async checkpoint save failed "
                     f"({type(e).__name__}: {e}) — surfacing on the "
                     f"next save/flush", kind="saver_error",
                     error=type(e).__name__, epoch=req.snap.epoch)
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()

    def _process(self, req: _Request) -> None:
        from ..utils.checkpoint import write_snapshot
        from . import inject
        # fault drill site: a wedged saver thread — flush()'s deadline
        # (not this sleep) must bound the damage
        inject.maybe_saver_stall(req.snap.epoch)
        queued_ms = (time.monotonic() - req.t_submit) * 1e3
        t0 = time.monotonic()
        stats = write_snapshot(req.path, req.snap)
        if req.on_commit is not None:
            req.on_commit()
        t1 = time.monotonic()
        stats["queued_ms"] = round(queued_ms, 3)
        stats["async_wall_ms"] = round(
            (t1 - req.t_submit) * 1e3 + req.snap.block_ms, 3)
        with self._cond:
            self._saved += 1
            self._stats.append(stats)
            del self._stats[:-_STATS_KEEP]
        # timeline lane: the background write/commit spans overlap the
        # training bursts on this process's lane in the merged trace
        write_ms = stats["write_ms"]
        commit_ms = stats["commit_ms"]
        emit("timeline", f"spans: ckpt save epoch {req.snap.epoch}",
             console=False, kind="spans",
             spans=[["ckpt_write", round(t0, 6), round(write_ms, 3)],
                    ["ckpt_commit", round(t0 + write_ms / 1e3, 6),
                     round(commit_ms, 3)]])
        emit("checkpoint",
             f"async save committed: epoch {req.snap.epoch} in "
             f"{stats['save_ms']:.1f} ms (step path blocked "
             f"{req.snap.block_ms:.1f} ms)", console=False,
             kind="saved", **{k: stats[k] for k in
                              ("epoch", "path", "block_ms", "write_ms",
                               "commit_ms", "save_ms", "queued_ms",
                               "async_wall_ms", "bytes", "shards")})

    # ------------------------------------------------------ inspection

    def stats(self) -> Dict[str, Any]:
        """Saver counters + the recent completed-save records (the
        bench `ckpt_*` headline fields read these)."""
        with self._cond:
            return {"saved": self._saved,
                    "superseded": self._superseded,
                    "busy": self._busy,
                    "pending": self._pending is not None,
                    "saves": list(self._stats)}
