"""``python -m roc_tpu.timeline`` — merge N per-process event/metrics
JSONL streams into one Perfetto-loadable Chrome-trace JSON.

Thin packaged entry point over :mod:`roc_tpu.obs.timeline` (which is
stdlib-only and also runs as a plain script on a box without jax:
``python roc_tpu/obs/timeline.py ...`` — importing the ``roc_tpu``
package pulls jax in on the way, exactly like ``roc_tpu.report``).
"""

from __future__ import annotations

import sys

from .obs.timeline import (clock_offsets, expand_paths,  # noqa: F401
                           main, merge_timeline, request_trace,
                           straggler_records)

if __name__ == "__main__":
    sys.exit(main())
