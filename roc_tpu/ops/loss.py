"""Masked softmax cross-entropy and training metrics.

Reference (``softmax_kernel.cu``): the train-mode forward is a no-op and
the loss is fused into backward (``softmax.cc:45-55``) — the gradient is
``softmax(logits) - onehot(label)`` zeroed outside the train mask
(``softmax_kernel.cu:19-33``), i.e. the gradient of the *sum* (not mean)
of per-vertex cross-entropies over train vertices.  We expose that
objective directly and let ``jax.grad`` produce the identical gradient.

The printed "train loss" is NOT the cross-entropy: the reference's
``calc_loss`` kernel accumulates ``sum over train vertices of
(1 - p_true)`` (``softmax_kernel.cu:65``) plus masked argmax accuracies
for train/val/test (``softmax_kernel.cu:41-79``), reduced with on-GPU
atomics.  :func:`perf_metrics` reproduces those definitions exactly; in
the sharded step the returned struct is ``psum``-reduced over the mesh —
the ICI equivalent of the reference's atomics + single-GPU reduction.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from ..core.graph import MASK_TRAIN, MASK_VAL, MASK_TEST


def masked_softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                                 mask: jax.Array) -> jax.Array:
    """Sum of CE over MASK_TRAIN vertices.  ``grad == softmax - onehot``
    on train rows and 0 elsewhere, matching ``softmax_kernel.cu:19-33``.

    logits: [V, C] float; labels: [V] int32; mask: [V] int32 MASK_*.
    Padding rows must carry MASK_NONE.
    """
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32),
                             axis=-1)[:, 0]
    train = (mask == MASK_TRAIN).astype(jnp.float32)
    return -jnp.sum(ll * train)


def perf_metrics(logits: jax.Array, labels: jax.Array,
                 mask: jax.Array) -> Dict[str, jax.Array]:
    """Reference ``PerfMetrics`` (``softmax_kernel.cu:35-39``): unreduced
    sums, safe to ``psum`` across shards before dividing."""
    p = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    p_true = jnp.take_along_axis(p, labels[:, None].astype(jnp.int32),
                                 axis=-1)[:, 0]
    pred = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    correct = (pred == labels).astype(jnp.float32)
    out: Dict[str, jax.Array] = {}
    for name, mval in (("train", MASK_TRAIN), ("val", MASK_VAL),
                       ("test", MASK_TEST)):
        sel = (mask == mval).astype(jnp.float32)
        out[f"{name}_cnt"] = jnp.sum(sel)
        out[f"{name}_correct"] = jnp.sum(correct * sel)
    train_sel = (mask == MASK_TRAIN).astype(jnp.float32)
    # reference "loss": sum over train of (1 - p_true)  (softmax_kernel.cu:65)
    out["train_loss_sum"] = jnp.sum((1.0 - p_true) * train_sel)
    return out


def summarize_metrics(m: Dict[str, jax.Array]) -> Dict[str, float]:
    """Convert psum'd metric sums into the printed quantities
    (``softmax_kernel.cu:141-152``)."""
    def _div(a, b):
        return float(a) / max(float(b), 1.0)
    return {
        # the reference prints the raw sum, not a mean.  Callers pass
        # device_get'd numpy — post-fetch summary, not the step path:
        # roc-lint: ok=host-sync-hot-path
        "train_loss": float(m["train_loss_sum"]),
        "train_acc": _div(m["train_correct"], m["train_cnt"]),
        "val_acc": _div(m["val_correct"], m["val_cnt"]),
        "test_acc": _div(m["test_correct"], m["test_cnt"]),
        "train_cnt": int(m["train_cnt"]),
        "val_cnt": int(m["val_cnt"]),
        "test_cnt": int(m["test_cnt"]),
        "train_correct": int(m["train_correct"]),
        "val_correct": int(m["val_correct"]),
        "test_correct": int(m["test_correct"]),
    }
