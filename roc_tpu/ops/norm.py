"""In-degree normalization (the reference's InDegreeNorm / GraphNorm op).

Reference (``graphnorm_kernel.cu:45-55``): ``out[v,:] = in[v,:] /
sqrt(indegree(v))`` with the in-degree read off CSR row pointers; applied
both before and after aggregation it yields the symmetric GCN
normalization D^-1/2 A D^-1/2 (self edges pre-added).  The op is its own
linear transpose, which is why the reference backward reuses the forward
kernel (``graphnorm_kernel.cu:127-136``) — JAX autodiff gives the same.

On TPU this is a broadcast multiply by a precomputed ``deg^-1/2`` vector:
degrees are static for a fixed graph, so we fold the rsqrt at trace time
and let XLA fuse the multiply into neighboring ops — cheaper than the
reference's per-element kernel and numerically identical (same
``1/sqrt(deg)`` scalar per row).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def inv_sqrt_degree(in_degree: jax.Array) -> jax.Array:
    """deg^-1/2 with zero-degree rows mapped to 0 (padding rows have
    degree 0; the reference never sees deg 0 thanks to self edges)."""
    deg = in_degree.astype(jnp.float32)
    return jnp.where(deg > 0, jax.lax.rsqrt(jnp.maximum(deg, 1.0)), 0.0)


def indegree_norm(x: jax.Array, in_degree: jax.Array,
                  impl: str = "xla") -> jax.Array:
    """x: [V, F]; in_degree: int32 [V].  Returns x / sqrt(indegree).

    ``impl='pallas'`` routes through the explicit VMEM-tiled kernel
    (kernels/graphnorm.py) — numerically identical; the XLA path is
    the default because the multiply fuses into neighboring ops."""
    if impl == "pallas":
        from ..kernels.graphnorm import indegree_norm_pallas
        return indegree_norm_pallas(x, in_degree)
    return x * inv_sqrt_degree(in_degree)[:, None].astype(x.dtype)
