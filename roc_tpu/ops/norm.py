"""In-degree normalization (the reference's InDegreeNorm / GraphNorm op).

Reference (``graphnorm_kernel.cu:45-55``): ``out[v,:] = in[v,:] /
sqrt(indegree(v))`` with the in-degree read off CSR row pointers; applied
both before and after aggregation it yields the symmetric GCN
normalization D^-1/2 A D^-1/2 (self edges pre-added).  The op is its own
linear transpose, which is why the reference backward reuses the forward
kernel (``graphnorm_kernel.cu:127-136``) — JAX autodiff gives the same.

On TPU this is a broadcast multiply by a precomputed ``deg^-1/2`` vector:
degrees are static for a fixed graph, so we fold the rsqrt at trace time
and let XLA fuse the multiply into neighboring ops — cheaper than the
reference's per-element kernel and numerically identical (same
``1/sqrt(deg)`` scalar per row).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def inv_sqrt_degree(in_degree: jax.Array) -> jax.Array:
    """deg^-1/2 with zero-degree rows mapped to 0 (padding rows have
    degree 0; the reference never sees deg 0 thanks to self edges)."""
    deg = in_degree.astype(jnp.float32)
    return jnp.where(deg > 0, jax.lax.rsqrt(jnp.maximum(deg, 1.0)), 0.0)


def inv_sqrt_degree_np(in_degree: np.ndarray) -> np.ndarray:
    """Host-side :func:`inv_sqrt_degree` (fp32) — the d vector the
    fused-aggregation weight-table builders bake into the tables
    (core/ell.py ell_weight_tables / SectionedEll.weight_tables,
    parallel/ring.py ring_weight_tables).  Must stay numerically
    identical to the traced form: same max(deg, 1) clamp, same
    zero-degree mapping."""
    deg = np.asarray(in_degree, dtype=np.float32)
    return np.where(deg > 0,
                    1.0 / np.sqrt(np.maximum(deg, 1.0)),
                    0.0).astype(np.float32)


def indegree_norm(x: jax.Array, in_degree: jax.Array,
                  impl: str = "xla") -> jax.Array:
    """x: [V, F]; in_degree: int32 [V].  Returns x / sqrt(indegree).

    ``impl='pallas'`` routes through the explicit VMEM-tiled kernel
    (kernels/graphnorm.py) — numerically identical; the XLA path is
    the default because the multiply fuses into neighboring ops."""
    if impl == "pallas":
        from ..kernels.graphnorm import indegree_norm_pallas
        return indegree_norm_pallas(x, in_degree)
    return x * inv_sqrt_degree(in_degree)[:, None].astype(x.dtype)
