"""Graph attention aggregation (GAT) on the degree-bucketed ELL layout.

The reference implements only unweighted CSR sum aggregation
(``scattergather_kernel.cu:20-76``); attention is the framework's
TPU-native extension for the GAT model family (Velickovic et al.,
ICLR'18 — additive single-head attention):

    e_ij   = LeakyReLU(a_src . h_j + a_dst . h_i)   for j in N(i)
    alpha  = softmax_j(e_ij)
    out_i  = sum_j alpha_ij h_j

The ELL layout makes the edge softmax *exact and scatter-free*: every
row's whole neighborhood lives in ONE bucket row (bucket width >= the
row's degree, ``core/ell.py row_widths``), so the per-row max /
exp-sum / weighted sum are all reductions over the bucket's width
axis with padding masked — no segment ops, no two-pass global
normalization.  This is also why the ``sectioned`` layout cannot host
attention: it splits a row's neighbors across source sections, which
would require a cross-section softmax reduction (use ``ell``).

Gradients are plain autodiff: attention is nonlinear in both inputs,
so the reference's symmetric kernel-reuse trick does not apply.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def gat_aggregate_ell(full: jax.Array, s_full: jax.Array,
                      d_local: jax.Array, ell_idx, ell_row_id,
                      ell_row_pos: jax.Array, num_rows: int,
                      neg_slope: float = 0.2,
                      budget_elems: int = 1 << 24) -> jax.Array:
    """Attention-weighted neighbor aggregation over ELL buckets,
    multi-head: K heads attend independently over the same
    neighborhood and their outputs concatenate (the GAT paper's
    concat form; K == 1 is single-head).

    full: [G+1, K*dh] gathered features with trailing zero row (the
      halo result; G == gathered_rows); the feature axis is the K
      head slices of width dh, concatenated.
    s_full: [G+1, K] per-source logits ``a_src^k . h_j^k`` with the
      dummy slot LAST (its value is irrelevant — dummy edges are
      masked).
    d_local: [num_rows + 1, K] per-destination logits with a trailing
      dummy slot for padding bucket rows.
    ell_idx / ell_row_id / ell_row_pos: core/ell.py EllTable arrays
      (single-partition views).
    Rows with no neighbors return 0 (the sum path's convention).

    Large buckets are row-segmented with ``lax.scan`` under the same
    ``budget_elems`` transient bound as the sum/max paths.  The
    per-(row, width) transient is the [K*dh] feature gather PLUS the
    fp32 score tensors (e / w / alpha, [K] each) — at many heads and
    narrow head width the scores rival the gather, so the budget math
    counts both.
    """
    F = full.shape[1]
    K = s_full.shape[1]
    assert F % K == 0, (F, K)
    # elements per (row, width) slot the segmentation must bound
    unit = F + 3 * K
    dummy = full.shape[0] - 1
    neg = jnp.asarray(-jnp.inf, dtype=jnp.float32)

    def seg_out(idx_seg, rid_seg):
        # scores softmax in fp32 for stability regardless of compute
        # dtype (bf16 exp over a wide range loses the tail)
        e = (s_full[idx_seg].astype(jnp.float32)
             + d_local[rid_seg].astype(jnp.float32)[:, None, :])
        e = jax.nn.leaky_relu(e, neg_slope)              # [r, w, K]
        valid = (idx_seg != dummy)[:, :, None]
        e = jnp.where(valid, e, neg)
        m = jnp.max(e, axis=1, keepdims=True)
        # all-padding rows have m == -inf; zero them via the guard
        w = jnp.where(valid, jnp.exp(e - jnp.where(
            jnp.isfinite(m), m, 0.0)), 0.0)
        den = jnp.maximum(w.sum(axis=1, keepdims=True), 1e-20)
        alpha = (w / den).astype(full.dtype)             # [r, w, K]
        g = full[idx_seg].reshape(*idx_seg.shape, K, F // K)
        return jnp.einsum("rwk,rwkd->rkd", alpha,
                          g).reshape(idx_seg.shape[0], F)

    outs = []
    for idx, rid in zip(ell_idx, ell_row_id):
        R, W = idx.shape
        if R * W * unit <= budget_elems:
            outs.append(seg_out(idx, rid))
            continue
        # NOTE (compile size): every bucket that lands here emits its
        # own checkpointed scan, and autodiff doubles each — at
        # products scale (lognormal degrees -> ~18 width buckets) the
        # unrolled HLO pushed remote compile past 40 min.  Large-graph
        # attention therefore routes through gat_aggregate_flat8
        # (ONE uniform scan shape) — see resolve_attention_impl.
        segs = -(-R * W * unit // budget_elems)
        seg_rows = -(-R // segs)
        Rp = seg_rows * segs
        idx_p = jnp.concatenate(
            [idx, jnp.full((Rp - R, W), dummy, dtype=idx.dtype)], axis=0)
        rid_p = jnp.concatenate(
            [rid, jnp.full((Rp - R,), num_rows, dtype=rid.dtype)],
            axis=0)

        # remat each step: WITHOUT it, autodiff saves every step's
        # [seg_rows, W, F] feature gather as a stacked scan residual —
        # [segs, seg_rows, W, F] = 18.5 GiB at products scale
        # (observed OOM, v5e 2026-07-30).  Attention is nonlinear, so
        # unlike the sum path the backward genuinely needs the
        # gathered values; recomputing them per step in the backward
        # sweep bounds memory at one step's transient.
        seg_out_ckpt = jax.checkpoint(seg_out)

        def body(_, ch):
            return None, seg_out_ckpt(*ch)

        _, segs_out = lax.scan(body, None,
                               (idx_p.reshape(segs, seg_rows, W),
                                rid_p.reshape(segs, seg_rows)))
        outs.append(segs_out.reshape(Rp, F)[:R])
    zero = jnp.zeros((1, F), dtype=full.dtype)
    cat = jnp.concatenate(outs + [zero], axis=0)
    return cat[ell_row_pos]


def resolve_dh_chunk(num_rows: int, heads: int, dh: int,
                     carry_budget: int = 768 << 20) -> Optional[int]:
    """Per-head feature-dim chunk width for :func:`gat_aggregate_flat8`.

    The numerator scan carries ``[num_rows+1, heads*dh]`` fp32; at
    ogbn-products scale (V=2.45M, F=256) that is 2.5 GiB, and its
    backward cotangent doubles it — the measured single-chip OOM
    (16.61 G of 15.75 G HBM, 2026-07-31).  Chunking dh re-runs the
    score computation per slice (one extra ``s_full`` gather pass,
    ~E*K bytes — negligible next to the feature gather) in exchange
    for an O(1/n_chunks) carry.

    ``carry_budget`` caps the TRAINING-time peak: the chunk is sized
    against 2x the forward carry (forward + its backward cotangent
    live simultaneously — round-5 advisor: sizing against the forward
    alone made the guarantee inference-only).  Returns None when the
    doubled carry fits ``carry_budget``."""
    bytes_per_dh = (num_rows + 1) * heads * 4
    # the cotangent doubles the live carry in training
    train_budget = carry_budget // 2
    if bytes_per_dh * dh <= train_budget:
        return None
    # chunk width straight from the budget so the per-chunk carry is
    # GUARANTEED to fit (a ceil-of-ceil split can overshoot ~2x)
    return max(1, min(dh, train_budget // bytes_per_dh))


def gat_aggregate_flat8(full: jax.Array, s_full: jax.Array,
                        d_local: jax.Array, f8_idx: jax.Array,
                        f8_dst: jax.Array, num_rows: int,
                        neg_slope: float = 0.2,
                        dh_chunk: Optional[int] = None) -> jax.Array:
    """Attention aggregation over the UNIFORM width-8 sub-row layout —
    the large-graph form (same numerics as :func:`gat_aggregate_ell`,
    different reduction structure).

    The bucket path's per-width Python unrolling emits one
    checkpointed scan per large bucket and autodiff doubles each; at
    ogbn-products scale that HLO exceeded practical remote-compile
    time (>40 min, VERDICT r3).  Here every row's neighborhood is
    split into width-8 sub-rows in ONE ``[n_chunks, seg_rows, 8]``
    table (built by ``core/ell.py sectioned_from_graph`` with a single
    section spanning all sources, so ids are global and sub-rows of a
    row are consecutive/ascending), and the edge softmax becomes two
    uniform scans:

      pass 1  per-sub-row score max, combined per row with a sorted
              scatter-max (stop_gradient: softmax is invariant to the
              shift, so the max needs no backward);
      pass 2  w = exp(e - rowmax) masked; numerator (w-weighted
              feature gather-sum) and denominator scatter-added per
              row; out = num / den.

    One scan body shape total — compile size is independent of the
    degree distribution.

    full: [G+1, K*dh] gathered features, trailing zero row (== the
      dummy id in ``f8_idx``).
    s_full: [G+1, K]; d_local: [num_rows+1, K] (trailing dummy slot,
      ``f8_dst`` padding points at it).
    """
    F = full.shape[1]
    K = s_full.shape[1]
    assert F % K == 0, (F, K)
    dummy = full.shape[0] - 1
    neg = jnp.asarray(-jnp.inf, dtype=jnp.float32)

    def scores(idx_ch, dst_ch):
        e = (s_full[idx_ch].astype(jnp.float32)
             + d_local[dst_ch].astype(jnp.float32)[:, None, :])
        e = jax.nn.leaky_relu(e, neg_slope)            # [seg, 8, K]
        valid = (idx_ch != dummy)[:, :, None]
        return jnp.where(valid, e, neg), valid

    def pass1(rm, ch):
        e, _ = scores(*ch)
        m8 = jnp.max(e, axis=1)                        # [seg, K]
        return rm.at[ch[1]].max(m8, indices_are_sorted=True), None

    rm0 = jnp.full((num_rows + 1, K), -jnp.inf, dtype=jnp.float32)
    rowmax, _ = lax.scan(jax.checkpoint(pass1), rm0, (f8_idx, f8_dst))
    # rows with no finite score (no neighbors) shift by 0; softmax is
    # shift-invariant so the max carries no gradient
    rowmax = lax.stop_gradient(
        jnp.where(jnp.isfinite(rowmax), rowmax, 0.0))

    dh = F // K
    if dh_chunk is None or dh_chunk >= dh:
        def pass2(carry, ch):
            num, den = carry
            idx_ch, dst_ch = ch
            e, valid = scores(idx_ch, dst_ch)
            w = jnp.where(valid,
                          jnp.exp(e - rowmax[dst_ch][:, None, :]),
                          0.0)                         # [seg, 8, K]
            den = den.at[dst_ch].add(w.sum(axis=1),
                                     indices_are_sorted=True)
            g = full[idx_ch].reshape(*idx_ch.shape, K, dh)
            # numerator carry stays fp32: a hub row of degree d
            # receives d/8 sequential scatter-adds of full-magnitude
            # partials — accumulating those in bf16 would lose
            # low-order bits every add (the bucket path reduces a
            # whole row in one fp32-MXU einsum, and this path must
            # match its numerics)
            part = jnp.einsum("swk,swkd->skd", w.astype(full.dtype),
                              g, preferred_element_type=jnp.float32
                              ).reshape(idx_ch.shape[0], F)
            num = num.at[dst_ch].add(part, indices_are_sorted=True)
            return (num, den), None

        num0 = jnp.zeros((num_rows + 1, F), dtype=jnp.float32)
        den0 = jnp.zeros((num_rows + 1, K), dtype=jnp.float32)
        (num, den), _ = lax.scan(jax.checkpoint(pass2), (num0, den0),
                                 (f8_idx, f8_dst))
        den = jnp.maximum(den[:num_rows], 1e-20)
        numr = num[:num_rows].reshape(num_rows, K, dh)
        out = (numr / den[:, :, None]).astype(full.dtype)
        return out.reshape(num_rows, F)

    # dh-chunked numerator (resolve_dh_chunk): the fused pass2 carry
    # is [num_rows+1, F] fp32 and autodiff doubles it — the products-
    # scale OOM.  Scores are cheap (one [G+1, K] gather per pass), so
    # the denominator gets its own scan and each dh slice re-derives w
    # while carrying only [num_rows+1, K*dc] fp32.  Per-element math
    # and scatter-add order match the fused form (tested to <=3e-7;
    # XLA lowers non-dividing slice widths slightly differently).
    def passden(den, ch):
        e, valid = scores(*ch)
        w = jnp.where(valid,
                      jnp.exp(e - rowmax[ch[1]][:, None, :]), 0.0)
        return den.at[ch[1]].add(w.sum(axis=1),
                                 indices_are_sorted=True), None

    den0 = jnp.zeros((num_rows + 1, K), dtype=jnp.float32)
    den, _ = lax.scan(jax.checkpoint(passden), den0,
                      (f8_idx, f8_dst))
    den = jnp.maximum(den[:num_rows], 1e-20)
    fullr = full.reshape(full.shape[0], K, dh)
    outs = []
    for lo in range(0, dh, dh_chunk):
        dc = min(dh_chunk, dh - lo)
        # materialize the slice once per chunk ([G+1, K*dc]) so the
        # scan gathers dc-wide rows, not F-wide ones
        full_c = lax.slice_in_dim(fullr, lo, lo + dc, axis=2) \
            .reshape(full.shape[0], K * dc)

        def pass2c(num, ch, full_c=full_c, dc=dc):
            idx_ch, dst_ch = ch
            e, valid = scores(idx_ch, dst_ch)
            w = jnp.where(valid,
                          jnp.exp(e - rowmax[dst_ch][:, None, :]),
                          0.0)
            g = full_c[idx_ch].reshape(*idx_ch.shape, K, dc)
            part = jnp.einsum("swk,swkd->skd", w.astype(full.dtype),
                              g, preferred_element_type=jnp.float32
                              ).reshape(idx_ch.shape[0], K * dc)
            return num.at[dst_ch].add(part,
                                      indices_are_sorted=True), None

        num0 = jnp.zeros((num_rows + 1, K * dc), dtype=jnp.float32)
        num, _ = lax.scan(jax.checkpoint(pass2c), num0,
                          (f8_idx, f8_dst))
        numr = num[:num_rows].reshape(num_rows, K, dc)
        outs.append((numr / den[:, :, None]).astype(full.dtype))
    return jnp.concatenate(outs, axis=2).reshape(num_rows, F)
