"""Dense ops: linear, activations, elementwise, dropout.

The reference implements these as cuBLAS/cuDNN leaf tasks (``linear.cc`` /
``linear_kernel.cu``, ``activation_kernel.cu``, ``element_kernel.cu``,
``dropout_kernel.cu``).  On TPU they are single XLA ops that the compiler
fuses and lowers onto the MXU/VPU — the fused linear+ReLU of
``linear_kernel.cu:81-104`` falls out of XLA fusion for free.

Semantics parity notes:
- Linear: ``y = x @ W`` with no bias, exactly the reference
  (``linear_kernel.cu:76-80`` computes W^T·X in its column-major layout,
  which is X·W in our row-major layout).  Optional fused activation
  mirrors ``ActiMode`` (``gnn.h:82-86``).
- Dropout: inverted dropout with scale 1/(1-rate) in train mode (cuDNN's
  convention, ``dropout_kernel.cu:98-99``), identity in infer mode
  (``dropout_kernel.cu:160-180``).  We thread an explicit PRNG key —
  the functional replacement for the cuDNN dropout states cached in the
  reference's ResourceManager.
- Element add: used for residual connections when the model is deeper
  than 3 layers (``gnn.cc:86-90``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

# ActiMode mirror (gnn.h:82-86); ELU is an extension beyond the
# reference's cuDNN set, used by the GAT model family (models/gat.py)
AC_MODE_NONE = "none"
AC_MODE_RELU = "relu"
AC_MODE_SIGMOID = "sigmoid"
AC_MODE_ELU = "elu"

_ACTIVATIONS = {
    AC_MODE_NONE: lambda x: x,
    AC_MODE_RELU: jax.nn.relu,
    AC_MODE_SIGMOID: jax.nn.sigmoid,
    AC_MODE_ELU: jax.nn.elu,
}


def linear(x: jax.Array, w: jax.Array,
           activation: str = AC_MODE_NONE,
           precision=None) -> jax.Array:
    """x: [V, in_dim] @ w: [in_dim, out_dim] with optional fused
    activation.  Always accumulates in fp32 on the MXU; for fp32 inputs
    the multiply also runs at full precision (parity with the reference's
    fp32 cuBLAS GEMM, ``linear_kernel.cu:76-80``), while bf16 inputs use
    the MXU's native bf16 multiply path."""
    if precision is None and x.dtype == jnp.float32:
        precision = jax.lax.Precision.HIGHEST
    y = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32).astype(x.dtype)
    return _ACTIVATIONS[activation](y)


def linear_chunked(x: jax.Array, w: jax.Array,
                   activation: str = AC_MODE_NONE,
                   block: int = 65536) -> jax.Array:
    """:func:`linear` evaluated as a ``lax.scan`` over ``block``-row
    vertex chunks — the chunked output head (models/builder.py,
    ``TrainConfig.head_chunk``).  The compiled matmul body is
    ``[block, in] @ [in, out]`` regardless of ``V``, so the
    classification head stops compiling at full ``[V_p, C]`` width
    into the step and its program is small and shape-stable; the
    ``block`` default matches the streamed head's 65536-row staging
    blocks (core/streaming.py StreamedHead), whose machinery this is
    the in-jit twin of.  Values and input gradients are bit-identical
    to :func:`linear`: each output row's dot product (and each dX
    row's) reads the full ``in`` axis either way, and padding rows
    are sliced back off.  The weight gradient dW sums the row axis
    blockwise across scan iterations — a different (equally valid)
    fp reduction order than the one-matmul reference, so dW matches
    to fp32 roundoff (~1e-7 relative), not bit-for-bit."""
    V, in_dim = x.shape
    n = -(-V // block)
    if n <= 1:
        return linear(x, w, activation)
    vp = n * block
    xp = jnp.pad(x, ((0, vp - V), (0, 0))) if vp != V else x

    def body(_, xb):
        return None, linear(xb, w, activation)

    _, yb = jax.lax.scan(body, None, xp.reshape(n, block, in_dim))
    return yb.reshape(vp, -1)[:V]


def activation(x: jax.Array, mode: str) -> jax.Array:
    return _ACTIVATIONS[mode](x)


def element_add(a: jax.Array, b: jax.Array) -> jax.Array:
    return a + b


def element_mul(a: jax.Array, b: jax.Array) -> jax.Array:
    return a * b


def dropout(x: jax.Array, rate: float, key: Optional[jax.Array],
            train: bool) -> jax.Array:
    """Inverted dropout; identity when not training or rate == 0."""
    if not train or rate <= 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, p=keep, shape=x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
