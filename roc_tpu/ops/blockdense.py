"""Block-dense MXU aggregation: tiled-adjacency SpMM for community graphs.

The measured sectioned/ELL gather is ROW-RATE bound on v5e (~7 ns per
edge, width-insensitive below F=256 — BASELINE.md "where the epoch
goes"), i.e. the chip's gather unit, not HBM bytes, sets the 98%-of-
epoch aggregation cost.  The MXU escape hatch (VERDICT r4 #1): tile
the adjacency over the vertex id space into ``[128, 128]`` blocks and
aggregate every sufficiently-filled block as one bf16 batched matmul

    out[dst_tile] += A_tile @ x[src_tile]        (A_tile: [128, 128])

leaving the scattered residual edges to the sectioned gather.  Per
dense block the cost is pure bandwidth — A (uint8, cast on device) +
one source tile read + one fp32 output-tile update, ~0.2 us at F=256 —
so a block pays off past roughly

    fill* ~ 0.2us / 7ns ~ 30..64 edges per 128x128 block (<0.4% fill)

while a uniform-random graph at Reddit scale puts only
``E * 128^2 / V^2 ~ 35`` edges in a block (and spreads A over V^2/128^2
tiles, whose reads then dominate).  The path therefore targets graphs
with COMMUNITY structure exposed by the vertex order (real Reddit is
community-generated; ``core/reorder.py`` / the planted-community
generator's oracle order model the ordering quality) — ``plan_blocks``
reports the occupancy stats that decide it, and
``benchmarks/micro_agg.py --impls bdense`` races it.

Reference cost model being attacked: the one-thread-per-edge atomic
CSR kernel ``/root/reference/scattergather_kernel.cu:20-76``.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

BLOCK = 128          # MXU-native tile edge
_CHUNK_BLOCKS = 256  # blocks per scan step: bounds the [C,128,F] transient

# aggr_impl='auto' structure probe (probe_dense_frac): below this edge
# count the sectioned gather is cheap enough that planning overhead
# isn't worth probing; at/above this dense fraction the measured
# bdense win (1.64x at 0.52, 2.49x at 0.81 — BASELINE.md) justifies
# switching.  0.15 is conservative: every block past min_fill is
# already cheaper per edge than the 7 ns/edge gather, but a thin
# dense slice still costs A-table HBM residency next to the model.
BDENSE_AUTO_MIN_EDGES = 5_000_000
BDENSE_AUTO_MIN_FRAC = 0.15

# largest edge multiplicity a u4-packed A-table can hold — the ONE
# place the 4-bit limit lives (pack_a_u4 and both stacked builders'
# packability decisions consume it)
U4_MAX = 15


@dataclass
class BlockPlan:
    """Host-built dense-tile layout + residual CSR (static per graph).

    a_blocks: uint8 [nblk, 128, 128] edge multiplicities (the planted
      generators emit duplicate edges; segment-sum semantics require
      counts, not 0/1) — OR, after :func:`pack_a_u4`, uint4-packed
      [nblk, 128, 64] with two multiplicities per byte (low nibble =
      even column); consumers must check the trailing axis before
      indexing columns directly.
    src_blk/dst_blk: int32 [nblk] tile ids, sorted by dst_blk (the
      output scatter-add sees sorted indices).
    res_row_ptr/res_col: the residual dst-major CSR (edges in blocks
      under ``min_fill`` + multiplicities over 255), aggregated by the
      caller through the sectioned/ELL path.
    """
    num_rows: int
    vpad: int
    a_blocks: np.ndarray
    src_blk: np.ndarray
    dst_blk: np.ndarray
    res_row_ptr: np.ndarray
    res_col: np.ndarray
    dense_edges: int
    total_edges: int
    # source tile space (== vpad for the square single-device plan;
    # the distributed planner tiles local dst rows x GATHERED source
    # coordinates, so src_vpad covers num_cols instead)
    src_vpad: int = 0
    # zero-A group-alignment blocks appended by pad_plan_groups (the
    # group they enable is the kernel's ``group`` argument)
    pad_blocks: int = 0

    def __post_init__(self):
        if not self.src_vpad:
            self.src_vpad = self.vpad

    @property
    def n_blocks(self) -> int:
        return int(self.a_blocks.shape[0])

    def occupancy(self) -> dict:
        """The stats that decide whether this path can win (recorded
        with every race row).  ``mean_fill`` is over the RAW (edge-
        carrying) blocks — inert group padding must not dilute the
        evidence behind the min-fill breakeven; ``a_bytes`` is the
        real device table incl. padding."""
        nb = self.n_blocks
        raw = nb - self.pad_blocks
        occ = {
            "n_blocks": nb,
            "dense_edges": int(self.dense_edges),
            "dense_frac": round(self.dense_edges
                                / max(self.total_edges, 1), 4),
            "mean_fill": round(self.dense_edges / max(raw, 1), 1),
            # real device bytes — halved when pack_a_u4 applied
            "a_bytes": int(self.a_blocks.nbytes),
        }
        if self.pad_blocks:
            occ["pad_blocks"] = int(self.pad_blocks)
        return occ


def _select_dense(counts: np.ndarray, min_fill: int,
                  a_budget_bytes: Optional[int],
                  group: int = 1,
                  dst_of: Optional[np.ndarray] = None) -> np.ndarray:
    """Boolean selection over the occupied-tile census: at least
    ``min_fill`` edges, densest-first under the A-table budget.  ONE
    place for the rule — the native and numpy plan paths share it.

    With ``group > 1`` the budget applies to the table AFTER
    :func:`pad_plan_groups` alignment (up to ``group-1`` zero blocks
    per occupied dst tile) — padding must never silently defeat the
    byte cap the budget exists to enforce.  ``dst_of`` gives each
    candidate's dst tile id; the padded size is monotone in the
    number of kept blocks (a new block either fills an existing
    group's padding slot or opens one new group), so a binary search
    finds the largest densest-first prefix that fits."""
    dense_sel = counts >= min_fill
    if a_budget_bytes is None:
        return dense_sel
    bb = BLOCK * BLOCK
    cand = np.flatnonzero(dense_sel)
    order = cand[np.argsort(-counts[cand], kind="stable")]
    if group > 1:
        assert dst_of is not None

        def fits(k: int) -> bool:
            if k == 0:
                return True
            w = np.bincount(dst_of[order[:k]])
            padded = int((-(-w[w > 0] // group) * group).sum())
            return padded * bb <= a_budget_bytes

        keep_n = len(order)
        if not fits(keep_n):
            lo, hi = 0, keep_n
            while lo < hi:          # max k with fits(k); fits(lo) holds
                mid = (lo + hi + 1) // 2
                if fits(mid):
                    lo = mid
                else:
                    hi = mid - 1
            keep_n = lo
    else:
        keep_n = min(len(order), int(a_budget_bytes // bb))
    if keep_n < len(order):
        dense_sel = np.zeros_like(dense_sel)
        dense_sel[order[:keep_n]] = True
    return dense_sel


def plan_blocks(row_ptr: np.ndarray, col_idx: np.ndarray,
                num_rows: int, min_fill: int = 64,
                a_budget_bytes: Optional[int] = 2 << 30,
                num_cols: Optional[int] = None,
                group: int = 1,
                census: Optional[Tuple[np.ndarray, np.ndarray]] = None
                ) -> BlockPlan:
    """Tile the dst-major CSR into [128, 128] blocks; blocks with at
    least ``min_fill`` edges go dense, the rest stay residual CSR.

    ``a_budget_bytes`` caps the total uint8 A-table size (16 KiB per
    block): when more blocks qualify than fit the budget, the DENSEST
    are kept — fill, not count, is what amortizes the per-block cost,
    and an unbounded plan is unusable anyway (at Reddit scale with
    65k-row communities ~930k blocks qualify = a 15 GiB A-table that
    no 16 GiB chip can hold).  ``None`` disables the cap.

    ``num_cols`` sets a RECTANGULAR tile space: dst rows stay
    ``num_rows`` but source ids may range over ``num_cols`` (the
    distributed planner's local-rows x gathered-coordinates case).
    Default: square (``num_rows``).

    ``group > 1`` returns a :func:`pad_plan_groups`-aligned plan for
    the kernel's grouped output-tile reduction; the budget then caps
    the PADDED table (the selection accounts for alignment blocks up
    front — see _select_dense).

    ``census`` is an optional precomputed ``(keys, counts)`` from
    :func:`probe_dense_frac` over the SAME (num_rows, num_cols) tile
    space — the auto probe's O(E) walk is then not repeated (native
    path only; the numpy fallback recomputes)."""
    row_ptr = np.asarray(row_ptr, dtype=np.int64)
    col_i32 = np.ascontiguousarray(col_idx, dtype=np.int32)
    E = col_i32.shape[0]
    vpad = -(-num_rows // BLOCK) * BLOCK
    if num_cols is None:
        num_cols = num_rows
    src_vpad = -(-num_cols // BLOCK) * BLOCK
    n_tiles = src_vpad // BLOCK    # tiles per dst-tile row of keys

    from .. import native
    if native.available():
        # native census + fill: O(E) CSR walks (seconds at Reddit
        # scale vs ~15 min for the numpy argsort/unique pipeline);
        # byte-identical plans (tested).  col stays int32 throughout —
        # Graph.col_idx already is, so no full-E copies happen here
        keys_all, counts_all = census if census is not None \
            else native.block_counts(
                row_ptr, col_i32, num_rows, BLOCK, num_cols=num_cols)
        dense_keys = keys_all[_select_dense(
            counts_all, min_fill, a_budget_bytes, group=group,
            dst_of=keys_all // n_tiles)]
        a, res_ptr, res_col = native.block_fill(
            row_ptr, col_i32, num_rows, BLOCK, dense_keys,
            num_cols=num_cols)
        return pad_plan_groups(BlockPlan(
            num_rows=num_rows, vpad=vpad, a_blocks=a,
            src_blk=(dense_keys % n_tiles).astype(np.int32),
            dst_blk=(dense_keys // n_tiles).astype(np.int32),
            res_row_ptr=res_ptr, res_col=res_col,
            dense_edges=E - res_col.shape[0], total_edges=E,
            src_vpad=src_vpad), group)

    # numpy fallback works in int64 key space
    col_idx = col_i32.astype(np.int64)
    if E and (col_idx.min() < 0 or col_idx.max() >= num_cols):
        # same hard error as the native path's kErrValue — an
        # out-of-range source would otherwise build a key outside the
        # declared tile space and aggregate silently wrong
        raise ValueError(
            f"col_idx out of range [0, {num_cols}) for the declared "
            f"source space")
    deg = np.diff(row_ptr)
    dst_all = np.repeat(np.arange(num_rows, dtype=np.int64), deg)
    key = (dst_all // BLOCK) * n_tiles + col_idx // BLOCK
    order = np.argsort(key, kind="stable")
    key_s = key[order]
    blocks, starts, counts = np.unique(key_s, return_index=True,
                                       return_counts=True)
    dense_sel = _select_dense(counts, min_fill, a_budget_bytes,
                              group=group, dst_of=blocks // n_tiles)
    dense_blocks = blocks[dense_sel]
    nblk = int(dense_blocks.shape[0])
    a = np.zeros((nblk, BLOCK, BLOCK), dtype=np.uint8)
    if nblk:
        pos = np.searchsorted(dense_blocks, key_s)
        pos_c = np.minimum(pos, nblk - 1)
        in_dense = dense_blocks[pos_c] == key_s
    else:
        in_dense = np.zeros(E, dtype=bool)
    e_sel = order[in_dense]
    if nblk:
        flat = (pos_c[in_dense] * BLOCK * BLOCK
                + (dst_all[e_sel] % BLOCK) * BLOCK
                + (col_idx[e_sel] % BLOCK))
        # occupied-slot counting stays O(E_dense), never O(slots):
        # a global bincount over nblk*16384 slots is ~17 GiB of
        # transient int64 at the default A budget (round-5 advisor)
        flat_order = np.argsort(flat, kind="stable")
        flat_sorted = flat[flat_order]
        slots, counts_s = np.unique(flat_sorted, return_counts=True)
        # uint8 multiplicity with saturation: overflowing edges (deep
        # duplicates past 255) fall back to the residual CSR so the
        # semantics stay exact
        kept = np.minimum(counts_s, 255)
        a.reshape(-1)[slots] = kept.astype(np.uint8)
        dense_edges = int(kept.sum())
        overflow_edges = int((counts_s - kept).sum())
    else:
        dense_edges = 0
        overflow_edges = 0
    # residual = all edges not counted densely
    res_mask = np.ones(E, dtype=bool)
    res_mask[e_sel] = False
    if overflow_edges:
        # mark the LAST `excess` duplicates of each saturated slot
        # residual (rare pathological multi-edges)
        over = counts_s > 255
        s1 = np.searchsorted(flat_sorted, slots[over], side="right")
        for hi, ex in zip(s1, (counts_s[over] - 255)):
            res_mask[e_sel[flat_order[hi - ex:hi]]] = True
    res_dst = dst_all[res_mask]
    res_col = col_idx[res_mask]
    res_deg = np.bincount(res_dst, minlength=num_rows)
    res_ptr = np.zeros(num_rows + 1, dtype=np.int64)
    np.cumsum(res_deg, out=res_ptr[1:])
    # residual edges arrive dst-sorted already (dst_all is sorted)
    return pad_plan_groups(BlockPlan(
        num_rows=num_rows, vpad=vpad,
        a_blocks=a,
        src_blk=(dense_blocks % n_tiles).astype(np.int32),
        dst_blk=(dense_blocks // n_tiles).astype(np.int32),
        res_row_ptr=res_ptr, res_col=res_col.astype(np.int32),
        dense_edges=dense_edges, total_edges=E,
        src_vpad=src_vpad), group)


def probe_dense_frac(row_ptr: np.ndarray, col_idx: np.ndarray,
                     num_rows: int, min_fill: int = 64,
                     a_budget_bytes: Optional[int] = 2 << 30,
                     num_cols: Optional[int] = None,
                     group: int = 1, return_census: bool = False):
    """Census-only estimate of the edge fraction a bdense plan would
    put on dense tiles — the ``aggr_impl='auto'`` structure probe.

    Runs the native O(E) tile census + the budget selection but skips
    the A fill (the expensive half of planning), so ``auto`` can
    decide sectioned-vs-bdense in ~a second at Reddit scale.  Returns
    None without librocio — the numpy census costs minutes at the
    scales where probing matters, and ``auto`` must never be slower
    than what it replaces.  (The estimate ignores uint8 saturation
    overflow — pathological >255-multiplicity edges land in the
    residual at plan time; negligible for the decision.)

    ``return_census=True`` returns ``(frac, (keys, counts))`` so a
    following :func:`plan_blocks` call over the SAME tile space can
    reuse the census instead of re-walking the CSR."""
    from .. import native
    if not native.available():
        return None
    row_ptr = np.asarray(row_ptr, dtype=np.int64)
    col_i32 = np.ascontiguousarray(col_idx, dtype=np.int32)
    E = col_i32.shape[0]
    if num_cols is None:
        num_cols = num_rows
    n_tiles = -(-num_cols // BLOCK)
    if E == 0:
        empty = (np.zeros(0, np.int64), np.zeros(0, np.int64))
        return (0.0, empty) if return_census else 0.0
    keys, counts = native.block_counts(row_ptr, col_i32, num_rows,
                                       BLOCK, num_cols=num_cols)
    sel = _select_dense(counts, min_fill, a_budget_bytes, group=group,
                        dst_of=keys // n_tiles)
    # host-side numpy census in the planning probe; no device array
    # within sight: roc-lint: ok=host-sync-hot-path
    frac = float(counts[sel].sum()) / E
    return (frac, (keys, counts)) if return_census else frac


def pad_plan_groups(plan: BlockPlan, group: int) -> BlockPlan:
    """Pad each dst tile's block run to a multiple of ``group`` with
    zero-A blocks (src tile 0 — A==0 makes the contribution zero), so
    :func:`aggregate_block_dense` can reduce ``group`` blocks per
    output-tile update (``group=...``).

    Why: with group=1 every dense block costs one read-modify-write
    of a [128, F] fp32 output tile (~256 KiB at F=256) — the DOMINANT
    HBM traffic of the path (A is 16 KiB, the source tile 64 KiB
    bf16).  Blocks are already dst-major sorted, so padding runs to a
    group multiple lets one einsum reduce a whole group in registers
    and write each output tile ``group``x less often.  Padding
    overhead is <= (group-1) blocks per OCCUPIED dst tile — a few
    percent at the measured widths (mean 213 blocks/tile on the
    planted-community substrate at Reddit scale)."""
    if group <= 1 or plan.n_blocks == 0:
        return plan
    dst = plan.dst_blk
    uniq, counts = np.unique(dst, return_counts=True)
    padded = -(-counts // group) * group
    total = int(padded.sum())
    if total == plan.n_blocks:
        return plan
    new_start = np.zeros(len(uniq) + 1, np.int64)
    np.cumsum(padded, out=new_start[1:])
    old_start = np.zeros(len(uniq) + 1, np.int64)
    np.cumsum(counts, out=old_start[1:])
    run_id = np.repeat(np.arange(len(uniq)), counts)
    pos = (new_start[run_id]
           + (np.arange(plan.n_blocks) - old_start[run_id]))
    a2 = np.zeros((total, BLOCK, BLOCK), np.uint8)
    a2[pos] = plan.a_blocks
    src2 = np.zeros(total, np.int32)
    src2[pos] = plan.src_blk
    dst2 = np.repeat(uniq, padded).astype(np.int32)
    return replace(plan, a_blocks=a2, src_blk=src2, dst_blk=dst2,
                   pad_blocks=plan.pad_blocks
                   + (total - plan.n_blocks))


def plan_blocks_packed(row_ptr: np.ndarray, col_idx: np.ndarray,
                       num_rows: int, min_fill: int = 64,
                       a_budget_bytes: Optional[int] = 2 << 30,
                       num_cols: Optional[int] = None,
                       group: int = 1,
                       census=None) -> BlockPlan:
    """:func:`plan_blocks` + the u4 packing budget policy — ONE home
    for the rule (trainer and micro_agg share it): plan against
    DOUBLE the A budget first, since :func:`pack_a_u4` halves device
    bytes and a packable graph can afford 2x the blocks within the
    stated cap; unpackable plans (multi-edge hubs past 4 bits — rare)
    re-plan at the true budget, reusing ``census`` so only the fill
    repeats."""
    budget2 = (a_budget_bytes * 2
               if a_budget_bytes is not None else None)
    plan = plan_blocks(row_ptr, col_idx, num_rows, min_fill=min_fill,
                       a_budget_bytes=budget2, num_cols=num_cols,
                       group=group, census=census)
    p4 = pack_a_u4(plan)
    if p4 is not None:
        return p4
    if a_budget_bytes is not None \
            and plan.a_blocks.nbytes > a_budget_bytes:
        plan = plan_blocks(row_ptr, col_idx, num_rows,
                           min_fill=min_fill,
                           a_budget_bytes=a_budget_bytes,
                           num_cols=num_cols, group=group,
                           census=census)
    return plan


def pack_a_u4(plan: BlockPlan) -> Optional[BlockPlan]:
    """Pack the uint8 A-table to uint4 (two multiplicities per byte,
    ``byte[..., k] = col 2k | col 2k+1 << 4``) — halves the A-table's
    HBM bytes AND its read traffic (~17% of the grouped dense path's
    per-block bytes).  Exact only when every multiplicity fits 4 bits;
    returns None otherwise (community plans almost always fit — the
    mean slot multiplicity is 1-2 — but a hub-multiedge plan must
    fall back to uint8 rather than saturate silently).

    The kernel detects packing from the trailing axis
    (``BLOCK // 2``) and unpacks in-register per chunk.  Applied on
    the single-device path (make_graph_context / micro_agg) and by
    the stacked distributed/multihost builders — all parts pack or
    none (one uniform SPMD trailing width; multihost agrees the
    global max multiplicity via one extra O(P) collective)."""
    if plan.n_blocks and plan.a_blocks.max() > U4_MAX:
        return None
    # an EMPTY plan packs too (to [0, 128, 64]): the stacked
    # distributed builders need one uniform trailing width across
    # parts, and a zero-block part must not force uint8 on the rest
    a = plan.a_blocks
    packed = (a[..., 0::2] | (a[..., 1::2] << 4)).astype(np.uint8)
    return replace(plan, a_blocks=packed)


def aggregate_block_dense(x: jax.Array, a_blocks: jax.Array,
                          src_blk: jax.Array, dst_blk: jax.Array,
                          num_rows: int, vpad: int,
                          out_dtype=jnp.float32,
                          chunk_blocks: int = _CHUNK_BLOCKS,
                          src_vpad: int = 0,
                          group: int = 1,
                          scale_dst: Optional[jax.Array] = None,
                          scale_src: Optional[jax.Array] = None
                          ) -> jax.Array:
    """Dense-tile partial aggregation (the residual CSR is the
    caller's, via the sectioned/ELL path on the SAME x).

    x: [src_rows, F] source features; ``src_vpad`` (default: ``vpad``)
    is the source tile space — equal to vpad for the square
    single-device plan, the padded GATHERED row count for the
    distributed per-partition plan (x then is the all-gathered
    matrix, dst tiles cover only this partition's local rows).
    Returns [num_rows, F] in ``out_dtype`` — fp32 accumulation over
    tiles (a hub tile receives many sequential adds).

    ``group > 1`` requires a :func:`pad_plan_groups`-padded plan
    (every run of ``group`` consecutive blocks shares one dst tile):
    each group is reduced in ONE einsum and its output tile updated
    once — ``group``x less output read-modify-write traffic.

    ``scale_dst`` [vpad] / ``scale_src`` [src_vpad] (optional, set
    together): per-row fp32 scales of the fused normalization
    ``D^-1/2 A D^-1/2`` (train fused path).  Applied per tile
    IN-REGISTER around the einsum — the integer A-table (and its u4
    packing) stays untouched and no extra HBM pass happens: the
    source tile is scaled after its load, the fp32 accumulator before
    its scatter-add.
    """
    F = x.shape[1]
    nblk = a_blocks.shape[0]
    n_tiles = vpad // BLOCK
    src_vpad = src_vpad or vpad
    src_rows = min(x.shape[0], src_vpad)
    if group > 1 and nblk % group:
        raise ValueError(
            f"group={group} needs a pad_plan_groups-padded plan; "
            f"got {nblk} blocks")
    if (scale_dst is None) != (scale_src is None):
        raise ValueError("scale_dst and scale_src must be set together")
    xt = jnp.zeros((src_vpad, F), dtype=x.dtype).at[:src_rows].set(
        x[:src_rows]).reshape(src_vpad // BLOCK, BLOCK, F)
    # pad the block list to a chunk multiple; padding scatters zero
    # tiles into a dummy output tile.  Small plans shrink the chunk so
    # padding never exceeds one chunk's worth of zero work.
    group = max(1, group)
    chunk_blocks = max(group, min(chunk_blocks, nblk)
                       // group * group)
    chunks = max(1, -(-nblk // chunk_blocks))
    pad = chunks * chunk_blocks - nblk
    # uint4-packed A (pack_a_u4) is detected from the trailing axis
    a_w = a_blocks.shape[-1]
    packed = a_w == BLOCK // 2
    a_p = jnp.concatenate([
        a_blocks,
        jnp.zeros((pad, BLOCK, a_w), dtype=a_blocks.dtype)]) \
        if pad else a_blocks
    s_p = jnp.concatenate([src_blk,
                           jnp.zeros(pad, dtype=src_blk.dtype)]) \
        if pad else src_blk
    d_p = jnp.concatenate([dst_blk,
                           jnp.full(pad, n_tiles, dtype=dst_blk.dtype)]) \
        if pad else dst_blk
    compute = (jnp.bfloat16 if x.dtype in (jnp.bfloat16,)
               else jnp.float32)
    if scale_src is not None:
        # tiled scale views: [n_src_tiles, 128] / [n_tiles + 1, 128]
        # (the trailing zero row serves padding blocks' dummy dst
        # tile).  Source scaling runs in the compute dtype — exactly
        # where the unfused indegree_norm multiplied; the dst side
        # scales the fp32 accumulator.
        ssrc_t = scale_src.astype(compute).reshape(
            src_vpad // BLOCK, BLOCK)
        sdst_t = jnp.concatenate([
            scale_dst.astype(jnp.float32).reshape(n_tiles, BLOCK),
            jnp.zeros((1, BLOCK), jnp.float32)])
    else:
        ssrc_t = sdst_t = None

    def body(out, ch):
        a_u8, s_ids, d_ids = ch
        if packed:
            # in-register uint4 unpack: byte k holds cols 2k / 2k+1
            a_u8 = jnp.stack([a_u8 & 0xF, a_u8 >> 4],
                             axis=-1).reshape(a_u8.shape[0],
                                              BLOCK, BLOCK)
        gx = xt[s_ids].astype(compute)              # [C, 128, F]
        if ssrc_t is not None:
            gx = gx * ssrc_t[s_ids][:, :, None]
        if group > 1:
            C = s_ids.shape[0]
            y = jnp.einsum("gwij,gwjf->gif",
                           a_u8.astype(compute).reshape(
                               C // group, group, BLOCK, BLOCK),
                           gx.reshape(C // group, group, BLOCK, F),
                           preferred_element_type=jnp.float32)
            d_ids = d_ids.reshape(C // group, group)[:, 0]
        else:
            y = jnp.einsum("bij,bjf->bif", a_u8.astype(compute), gx,
                           preferred_element_type=jnp.float32)
        if sdst_t is not None:
            y = y * sdst_t[d_ids][:, :, None]
        # several blocks/groups can share a dst tile within one chunk
        # -> NOT unique; the plan's dst-major sort keeps them sorted
        return out.at[d_ids].add(y, indices_are_sorted=True), None

    out0 = jnp.zeros((n_tiles + 1, BLOCK, F), dtype=jnp.float32)
    C = chunk_blocks
    out, _ = lax.scan(
        body, out0,
        (a_p.reshape(chunks, C, BLOCK, a_w),
         s_p.reshape(chunks, C), d_p.reshape(chunks, C)))
    return out[:n_tiles].reshape(vpad, F)[:num_rows].astype(out_dtype)
