"""Block-dense MXU aggregation: tiled-adjacency SpMM for community graphs.

The measured sectioned/ELL gather is ROW-RATE bound on v5e (~7 ns per
edge, width-insensitive below F=256 — BASELINE.md "where the epoch
goes"), i.e. the chip's gather unit, not HBM bytes, sets the 98%-of-
epoch aggregation cost.  The MXU escape hatch (VERDICT r4 #1): tile
the adjacency over the vertex id space into ``[128, 128]`` blocks and
aggregate every sufficiently-filled block as one bf16 batched matmul

    out[dst_tile] += A_tile @ x[src_tile]        (A_tile: [128, 128])

leaving the scattered residual edges to the sectioned gather.  Per
dense block the cost is pure bandwidth — A (uint8, cast on device) +
one source tile read + one fp32 output-tile update, ~0.2 us at F=256 —
so a block pays off past roughly

    fill* ~ 0.2us / 7ns ~ 30..64 edges per 128x128 block (<0.4% fill)

while a uniform-random graph at Reddit scale puts only
``E * 128^2 / V^2 ~ 35`` edges in a block (and spreads A over V^2/128^2
tiles, whose reads then dominate).  The path therefore targets graphs
with COMMUNITY structure exposed by the vertex order (real Reddit is
community-generated; ``core/reorder.py`` / the planted-community
generator's oracle order model the ordering quality) — ``plan_blocks``
reports the occupancy stats that decide it, and
``benchmarks/micro_agg.py --impls bdense`` races it.

Reference cost model being attacked: the one-thread-per-edge atomic
CSR kernel ``/root/reference/scattergather_kernel.cu:20-76``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

BLOCK = 128          # MXU-native tile edge
_CHUNK_BLOCKS = 256  # blocks per scan step: bounds the [C,128,F] transient


@dataclass
class BlockPlan:
    """Host-built dense-tile layout + residual CSR (static per graph).

    a_blocks: uint8 [nblk, 128, 128] edge multiplicities (the planted
      generators emit duplicate edges; segment-sum semantics require
      counts, not 0/1).
    src_blk/dst_blk: int32 [nblk] tile ids, sorted by dst_blk (the
      output scatter-add sees sorted indices).
    res_row_ptr/res_col: the residual dst-major CSR (edges in blocks
      under ``min_fill`` + multiplicities over 255), aggregated by the
      caller through the sectioned/ELL path.
    """
    num_rows: int
    vpad: int
    a_blocks: np.ndarray
    src_blk: np.ndarray
    dst_blk: np.ndarray
    res_row_ptr: np.ndarray
    res_col: np.ndarray
    dense_edges: int
    total_edges: int
    # source tile space (== vpad for the square single-device plan;
    # the distributed planner tiles local dst rows x GATHERED source
    # coordinates, so src_vpad covers num_cols instead)
    src_vpad: int = 0

    def __post_init__(self):
        if not self.src_vpad:
            self.src_vpad = self.vpad

    @property
    def n_blocks(self) -> int:
        return int(self.a_blocks.shape[0])

    def occupancy(self) -> dict:
        """The stats that decide whether this path can win (recorded
        with every race row)."""
        nb = self.n_blocks
        return {
            "n_blocks": nb,
            "dense_edges": int(self.dense_edges),
            "dense_frac": round(self.dense_edges
                                / max(self.total_edges, 1), 4),
            "mean_fill": round(self.dense_edges / max(nb, 1), 1),
            "a_bytes": int(nb) * BLOCK * BLOCK,
        }


def _select_dense(counts: np.ndarray, min_fill: int,
                  a_budget_bytes: Optional[int]) -> np.ndarray:
    """Boolean selection over the occupied-tile census: at least
    ``min_fill`` edges, densest-first under the A-table budget.  ONE
    place for the rule — the native and numpy plan paths share it."""
    dense_sel = counts >= min_fill
    if a_budget_bytes is not None:
        max_blocks = int(a_budget_bytes // (BLOCK * BLOCK))
        if int(dense_sel.sum()) > max_blocks:
            cand = np.flatnonzero(dense_sel)
            keep = cand[np.argsort(-counts[cand],
                                   kind="stable")[:max_blocks]]
            dense_sel = np.zeros_like(dense_sel)
            dense_sel[keep] = True
    return dense_sel


def plan_blocks(row_ptr: np.ndarray, col_idx: np.ndarray,
                num_rows: int, min_fill: int = 64,
                a_budget_bytes: Optional[int] = 2 << 30,
                num_cols: Optional[int] = None) -> BlockPlan:
    """Tile the dst-major CSR into [128, 128] blocks; blocks with at
    least ``min_fill`` edges go dense, the rest stay residual CSR.

    ``a_budget_bytes`` caps the total uint8 A-table size (16 KiB per
    block): when more blocks qualify than fit the budget, the DENSEST
    are kept — fill, not count, is what amortizes the per-block cost,
    and an unbounded plan is unusable anyway (at Reddit scale with
    65k-row communities ~930k blocks qualify = a 15 GiB A-table that
    no 16 GiB chip can hold).  ``None`` disables the cap.

    ``num_cols`` sets a RECTANGULAR tile space: dst rows stay
    ``num_rows`` but source ids may range over ``num_cols`` (the
    distributed planner's local-rows x gathered-coordinates case).
    Default: square (``num_rows``)."""
    row_ptr = np.asarray(row_ptr, dtype=np.int64)
    col_i32 = np.ascontiguousarray(col_idx, dtype=np.int32)
    E = col_i32.shape[0]
    vpad = -(-num_rows // BLOCK) * BLOCK
    if num_cols is None:
        num_cols = num_rows
    src_vpad = -(-num_cols // BLOCK) * BLOCK
    n_tiles = src_vpad // BLOCK    # tiles per dst-tile row of keys

    from .. import native
    if native.available():
        # native census + fill: O(E) CSR walks (seconds at Reddit
        # scale vs ~15 min for the numpy argsort/unique pipeline);
        # byte-identical plans (tested).  col stays int32 throughout —
        # Graph.col_idx already is, so no full-E copies happen here
        keys_all, counts_all = native.block_counts(
            row_ptr, col_i32, num_rows, BLOCK, num_cols=num_cols)
        dense_keys = keys_all[_select_dense(counts_all, min_fill,
                                            a_budget_bytes)]
        a, res_ptr, res_col = native.block_fill(
            row_ptr, col_i32, num_rows, BLOCK, dense_keys,
            num_cols=num_cols)
        return BlockPlan(
            num_rows=num_rows, vpad=vpad, a_blocks=a,
            src_blk=(dense_keys % n_tiles).astype(np.int32),
            dst_blk=(dense_keys // n_tiles).astype(np.int32),
            res_row_ptr=res_ptr, res_col=res_col,
            dense_edges=E - res_col.shape[0], total_edges=E,
            src_vpad=src_vpad)

    # numpy fallback works in int64 key space
    col_idx = col_i32.astype(np.int64)
    if E and (col_idx.min() < 0 or col_idx.max() >= num_cols):
        # same hard error as the native path's kErrValue — an
        # out-of-range source would otherwise build a key outside the
        # declared tile space and aggregate silently wrong
        raise ValueError(
            f"col_idx out of range [0, {num_cols}) for the declared "
            f"source space")
    deg = np.diff(row_ptr)
    dst_all = np.repeat(np.arange(num_rows, dtype=np.int64), deg)
    key = (dst_all // BLOCK) * n_tiles + col_idx // BLOCK
    order = np.argsort(key, kind="stable")
    key_s = key[order]
    blocks, starts, counts = np.unique(key_s, return_index=True,
                                       return_counts=True)
    dense_sel = _select_dense(counts, min_fill, a_budget_bytes)
    dense_blocks = blocks[dense_sel]
    nblk = int(dense_blocks.shape[0])
    a = np.zeros((nblk, BLOCK, BLOCK), dtype=np.uint8)
    if nblk:
        pos = np.searchsorted(dense_blocks, key_s)
        pos_c = np.minimum(pos, nblk - 1)
        in_dense = dense_blocks[pos_c] == key_s
    else:
        in_dense = np.zeros(E, dtype=bool)
    e_sel = order[in_dense]
    if nblk:
        flat = (pos_c[in_dense] * BLOCK * BLOCK
                + (dst_all[e_sel] % BLOCK) * BLOCK
                + (col_idx[e_sel] % BLOCK))
        # occupied-slot counting stays O(E_dense), never O(slots):
        # a global bincount over nblk*16384 slots is ~17 GiB of
        # transient int64 at the default A budget (round-5 advisor)
        flat_order = np.argsort(flat, kind="stable")
        flat_sorted = flat[flat_order]
        slots, counts_s = np.unique(flat_sorted, return_counts=True)
        # uint8 multiplicity with saturation: overflowing edges (deep
        # duplicates past 255) fall back to the residual CSR so the
        # semantics stay exact
        kept = np.minimum(counts_s, 255)
        a.reshape(-1)[slots] = kept.astype(np.uint8)
        dense_edges = int(kept.sum())
        overflow_edges = int((counts_s - kept).sum())
    else:
        dense_edges = 0
        overflow_edges = 0
    # residual = all edges not counted densely
    res_mask = np.ones(E, dtype=bool)
    res_mask[e_sel] = False
    if overflow_edges:
        # mark the LAST `excess` duplicates of each saturated slot
        # residual (rare pathological multi-edges)
        over = counts_s > 255
        s1 = np.searchsorted(flat_sorted, slots[over], side="right")
        for hi, ex in zip(s1, (counts_s[over] - 255)):
            res_mask[e_sel[flat_order[hi - ex:hi]]] = True
    res_dst = dst_all[res_mask]
    res_col = col_idx[res_mask]
    res_deg = np.bincount(res_dst, minlength=num_rows)
    res_ptr = np.zeros(num_rows + 1, dtype=np.int64)
    np.cumsum(res_deg, out=res_ptr[1:])
    # residual edges arrive dst-sorted already (dst_all is sorted)
    return BlockPlan(
        num_rows=num_rows, vpad=vpad,
        a_blocks=a,
        src_blk=(dense_blocks % n_tiles).astype(np.int32),
        dst_blk=(dense_blocks // n_tiles).astype(np.int32),
        res_row_ptr=res_ptr, res_col=res_col.astype(np.int32),
        dense_edges=dense_edges, total_edges=E,
        src_vpad=src_vpad)


def aggregate_block_dense(x: jax.Array, a_blocks: jax.Array,
                          src_blk: jax.Array, dst_blk: jax.Array,
                          num_rows: int, vpad: int,
                          out_dtype=jnp.float32,
                          chunk_blocks: int = _CHUNK_BLOCKS,
                          src_vpad: int = 0
                          ) -> jax.Array:
    """Dense-tile partial aggregation (the residual CSR is the
    caller's, via the sectioned/ELL path on the SAME x).

    x: [src_rows, F] source features; ``src_vpad`` (default: ``vpad``)
    is the source tile space — equal to vpad for the square
    single-device plan, the padded GATHERED row count for the
    distributed per-partition plan (x then is the all-gathered
    matrix, dst tiles cover only this partition's local rows).
    Returns [num_rows, F] in ``out_dtype`` — fp32 accumulation over
    tiles (a hub tile receives many sequential adds).
    """
    F = x.shape[1]
    nblk = a_blocks.shape[0]
    n_tiles = vpad // BLOCK
    src_vpad = src_vpad or vpad
    src_rows = min(x.shape[0], src_vpad)
    xt = jnp.zeros((src_vpad, F), dtype=x.dtype).at[:src_rows].set(
        x[:src_rows]).reshape(src_vpad // BLOCK, BLOCK, F)
    # pad the block list to a chunk multiple; padding scatters zero
    # tiles into a dummy output tile.  Small plans shrink the chunk so
    # padding never exceeds one chunk's worth of zero work.
    chunk_blocks = max(1, min(chunk_blocks, nblk))
    chunks = max(1, -(-nblk // chunk_blocks))
    pad = chunks * chunk_blocks - nblk
    a_p = jnp.concatenate([
        a_blocks,
        jnp.zeros((pad, BLOCK, BLOCK), dtype=a_blocks.dtype)]) \
        if pad else a_blocks
    s_p = jnp.concatenate([src_blk,
                           jnp.zeros(pad, dtype=src_blk.dtype)]) \
        if pad else src_blk
    d_p = jnp.concatenate([dst_blk,
                           jnp.full(pad, n_tiles, dtype=dst_blk.dtype)]) \
        if pad else dst_blk
    compute = (jnp.bfloat16 if x.dtype in (jnp.bfloat16,)
               else jnp.float32)

    def body(out, ch):
        a_u8, s_ids, d_ids = ch
        gx = xt[s_ids].astype(compute)              # [C, 128, F]
        y = jnp.einsum("bij,bjf->bif", a_u8.astype(compute), gx,
                       preferred_element_type=jnp.float32)
        # several blocks can share a dst tile within one chunk -> NOT
        # unique; the plan's dst-major sort keeps them sorted
        return out.at[d_ids].add(y, indices_are_sorted=True), None

    out0 = jnp.zeros((n_tiles + 1, BLOCK, F), dtype=jnp.float32)
    C = chunk_blocks
    out, _ = lax.scan(
        body, out0,
        (a_p.reshape(chunks, C, BLOCK, BLOCK),
         s_p.reshape(chunks, C), d_p.reshape(chunks, C)))
    return out[:n_tiles].reshape(vpad, F)[:num_rows].astype(out_dtype)
