"""Neighbor aggregation (the reference's ScatterGather op).

Reference semantics (``scattergather_kernel.cu:20-76``): for a dst-major
CSR, ``out[dst] = sum_{(src,dst) in E} in[src]`` — a CSR-SpMM with an
implicit all-ones sparse matrix.  The reference backward *reuses the
forward kernel* on the same CSR (``scattergather_kernel.cu:160-170``),
which is correct only for symmetric adjacency; we get the exact transpose
for free from JAX autodiff (gather/segment_sum differentiate to the
scatter/gather pair), so our gradients are correct for any graph while
matching the reference bit-for-bit on the symmetric graphs it supports.

Three implementations, one semantics:

- ``segment``: one-shot gather + ``segment_sum``.  Materializes the
  ``[E, F]`` per-edge feature matrix — fine for small graphs and as the
  numerics reference for tests.
- ``blocked``: ``lax.scan`` over edge chunks.  Exploits dst-sortedness:
  because every vertex has a self edge (degree >= 1), the destinations
  inside a chunk of C edges span at most C consecutive rows, so each
  chunk reduces into a C-row window that is added back with a
  dynamic-slice read-modify-write.  The within-chunk reduction is a
  *one-hot selection matmul* (``onehot(dst-r0)^T @ gathered``) — entirely
  scatter-free, so it lands on the MXU instead of XLA's serialized TPU
  scatter path.  Memory is O(C * F) regardless of E.
- ``scan``: ``lax.scan`` over edge chunks with a *cumsum-diff* segmented
  reduction — the direct TPU analog of the reference's cub BlockScan
  kernel (``scattergather_kernel.cu:20-76``).  Within a chunk, row sums
  are prefix-sum differences at precomputed row-end offsets (O(C*F) VPU
  work instead of the one-hot matmul's O(C^2*F) MXU work), the chunk's
  last row travels as a carry record instead of a read-modify-write, and
  each window is *written exactly once* (later windows overwrite the
  provisional zero tail), so HBM traffic drops from 3x to 2x the gather
  bytes.  Carry records are scatter-added after the scan.  (On v5e the
  XLA row-gather dominates all impls — see benchmarks/micro_agg.py —
  so the practical default for big graphs is ``ell``, whose reduce is
  a dense reshape-sum.)
- ``pallas`` (kernels/ell_spmm.py): the ELL layout driven by a
  one-launch-per-bucket Pallas kernel — scalar-readable index blocks in
  SMEM, per-row feature DMA HBM->VMEM with a rotating pipeline, fp32
  VMEM accumulation; dispatched via GraphContext (needs the ELL tables,
  not an edge list).
- ``pallas_csr`` (kernels/spmm.py): the ``scan`` algorithm with the
  per-chunk segmented reduction fused into a Pallas TPU kernel
  (superseded by ``pallas``; kept as the edge-list-contract kernel).

All take per-edge *global* source ids and produce rows for the local
destination range, so they drop into the shard_map step unchanged (the
gathered feature matrix is the all-gathered global one, mirroring the
reference's whole-region input requirement, ``scattergather.cc:70-72``).

**Measured (TPU v5 lite, 2026-07-29, V=50k E=10M F=256 fp32, median of
10; benchmarks/measured_baselines.json has the full rows):** ``ell``
119.1 ms / 86.0 GB/s, ``sectioned`` 131.1 ms, ``scan:4096`` 260.0 ms,
``blocked:1024`` 294.6 ms, Pallas ELL kernel 1006.2 ms — each including
~66 ms constant fetch-barrier overhead.  At REDDIT scale (V=233k,
E=115M — gather table past VMEM) the ranking flips: ``sectioned``
865 ms vs ``ell`` 2006 ms per aggregation, 2708 vs 7920.8 ms per train
epoch (core/ell.py SectionedEll explains the mechanism).  The ``auto``
default picks by table size.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def aggregate_segment(feats: jax.Array, edge_src: jax.Array,
                      edge_dst: jax.Array, num_rows: int) -> jax.Array:
    """Reference implementation: out[d] = sum over edges of feats[src].

    feats: [V(+1), F] source features (last row may be the zero dummy row).
    edge_src/edge_dst: int32 [E].  Returns [num_rows, F].
    """
    gathered = feats[edge_src]
    return jax.ops.segment_sum(gathered, edge_dst, num_segments=num_rows)


@functools.partial(jax.jit, static_argnames=("num_rows", "chunk"))
def aggregate_blocked(feats: jax.Array, edge_src: jax.Array,
                      edge_dst: jax.Array, num_rows: int,
                      chunk: int = 512) -> jax.Array:
    """Chunked CSR aggregation with O(chunk * F) working set.

    Requires edge_dst sorted ascending and every destination row to have
    degree >= 1 over the *full* edge list (self-edge convention,
    ``gnn.cc:756``), which bounds the dst span of any chunk of C edges by
    C rows.  Padding edges must point at a zero source row and the last
    local row (partition.py guarantees both).
    """
    E = edge_src.shape[0]
    F = feats.shape[1]
    assert E % chunk == 0, "pad edges to a chunk multiple"
    n_chunks = E // chunk
    src_c = edge_src.reshape(n_chunks, chunk)
    dst_c = edge_dst.reshape(n_chunks, chunk)
    # Output padded by one window so the dynamic slice never clips.
    out0 = jnp.zeros((num_rows + chunk, F), dtype=feats.dtype)

    def body(out, inputs):
        src, dst = inputs
        r0 = dst[0]
        gathered = feats[src]                       # [C, F]
        local = dst - r0                            # in [0, C)
        # scatter-free segment reduction: sel[e, r] = (local[e] == r);
        # sel^T @ gathered lands on the MXU (fp32 accumulation)
        sel = (local[:, None] ==
               lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
               ).astype(gathered.dtype)
        prec = (lax.Precision.HIGHEST
                if gathered.dtype == jnp.float32 else None)
        seg = lax.dot_general(
            sel, gathered, (((0,), (0,)), ((), ())), precision=prec,
            preferred_element_type=jnp.float32).astype(out.dtype)
        window = lax.dynamic_slice(out, (r0, 0), (chunk, F))
        out = lax.dynamic_update_slice(out, window + seg, (r0, 0))
        return out, None

    out, _ = lax.scan(body, out0, (src_c, dst_c))
    return out[:num_rows]


@functools.partial(jax.jit, static_argnames=("num_rows", "chunk"))
def aggregate_scan(feats: jax.Array, edge_src: jax.Array,
                   edge_dst: jax.Array, num_rows: int,
                   chunk: int = 1024) -> jax.Array:
    """Cumsum-diff segmented reduction — the TPU BlockScan analog.

    Same preconditions as :func:`aggregate_blocked` (dst sorted, degree
    >= 1 over the full edge list, padding to a chunk multiple).  Within
    each chunk of C edges the row sums are differences of the running
    prefix sum at per-row end offsets (O(C*F) VPU work); the chunk's
    last row is emitted as a (row, partial-sum) carry record instead of
    read-modify-writing the output window, and each window is written
    exactly once — rows past the chunk's last destination are written as
    provisional zeros that the next window overwrites.  Carry records
    are scatter-added after the scan (duplicates accumulate, so a row
    spanning many chunks is summed exactly).
    """
    E = edge_src.shape[0]
    F = feats.shape[1]
    assert E % chunk == 0, "pad edges to a chunk multiple"
    C = chunk
    n_chunks = E // C
    src_c = edge_src.reshape(n_chunks, C)
    dst_c = edge_dst.reshape(n_chunks, C)
    # Output padded by one window so dynamic writes never clip.
    out0 = jnp.zeros((num_rows + C, F), dtype=feats.dtype)
    iota = lax.broadcasted_iota(jnp.int32, (C, 1), 0)

    def body(out, inputs):
        src, dst = inputs
        r0 = dst[0]
        pos = dst[C - 1] - r0                       # last row, local
        g = feats[src].astype(jnp.float32)          # [C, F] gather
        S1 = jnp.concatenate(
            [jnp.zeros((1, F), jnp.float32), jnp.cumsum(g, axis=0)])
        local = (dst - r0)[:, None]                 # [C, 1] in [0, C)
        # ends[j] = # edges with local dst <= j  (all dst >= r0 here)
        ends = jnp.sum((local <= iota.T).astype(jnp.int32), axis=0)
        starts = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), ends[:-1]])
        L = jnp.take(S1, ends, axis=0) - jnp.take(S1, starts, axis=0)
        carry = lax.dynamic_slice(L, (pos, 0), (1, F))
        L = jnp.where(iota == pos, 0.0, L).astype(out.dtype)
        out = lax.dynamic_update_slice(out, L, (r0, 0))
        return out, (dst[C - 1], carry[0].astype(out.dtype))

    out, (rows, vecs) = lax.scan(body, out0, (src_c, dst_c))
    out = out.at[rows].add(vecs)
    return out[:num_rows]


def aggregate_ell(feats: jax.Array, ell_idx, ell_row_pos: jax.Array,
                  num_rows: int,
                  budget_elems: int = 1 << 24,
                  ell_w=None) -> jax.Array:
    """Degree-bucketed ELLPACK aggregation (see core/ell.py): per width
    bucket, gather ``feats[idx]`` and sum the width axis; inverse-permute
    the concatenated bucket outputs back to row order.  No scatter, no
    per-edge scan — the TPU-native layout for the reference's CSR hot
    loop (``scattergather_kernel.cu:20-76``).

    feats: [R+1, F] gathered features with trailing zero row.
    ell_idx: tuple of int32 [rows_b, width_b] arrays (dummy = R).
    ell_row_pos: int32 [num_rows] output permutation (zero slot = total
    bucket rows).  Buckets whose gathered block would exceed
    ``budget_elems`` scalars (R * W * F, i.e. bytes/4 in fp32 — default
    64 MiB) are processed in row segments with lax.scan to bound the
    transient.

    ``ell_w`` (optional): per-bucket edge weights shaped like
    ``ell_idx`` (core/ell.py ell_weight_tables — the baked
    ``D^-1/2 A D^-1/2`` scales of the fused aggregation); the gathered
    rows are weighted in-register before the width reduction, so the
    weighted sum costs no extra HBM pass over the features.
    """
    F = feats.shape[1]
    outs = []
    for bi, idx in enumerate(ell_idx):
        w = (ell_w[bi].astype(feats.dtype)
             if ell_w is not None and len(ell_w) else None)
        R, W = idx.shape
        if R * W * F <= budget_elems:
            g = feats[idx]
            if w is not None:
                g = g * w[:, :, None]
            outs.append(g.sum(axis=1))
            continue
        segs = -(-R * W * F // budget_elems)
        seg_rows = -(-R // segs)
        Rp = seg_rows * segs
        pad = jnp.full((Rp - R, W), feats.shape[0] - 1, dtype=idx.dtype)
        idx_p = jnp.concatenate([idx, pad], axis=0)
        xs = (idx_p.reshape(segs, seg_rows, W),)
        if w is not None:
            w_p = jnp.concatenate(
                [w, jnp.zeros((Rp - R, W), dtype=w.dtype)], axis=0)
            xs += (w_p.reshape(segs, seg_rows, W),)

        def body(_, ch):
            g = feats[ch[0]]
            if len(ch) > 1:
                g = g * ch[1][:, :, None]
            return None, g.sum(axis=1)

        _, segs_out = lax.scan(body, None, xs)
        outs.append(segs_out.reshape(Rp, F)[:R])
    zero = jnp.zeros((1, F), dtype=feats.dtype)
    cat = jnp.concatenate(outs + [zero], axis=0)
    return cat[ell_row_pos]


def aggregate_ell_sect(feats: jax.Array, sect_idx, sect_sub_dst,
                       sect_meta, num_rows: int,
                       sect_w=None) -> jax.Array:
    """Source-sectioned width-8 aggregation (core/ell.py SectionedEll —
    the measured numbers and the why live on that dataclass).  Per
    section: slice the <= 64 MiB source block out of ``feats`` (XLA
    keeps it VMEM-resident), ``lax.scan`` over sub-row chunks carrying
    the output — gather-sum ``xsec[idx].sum(1)`` hits the fast gather
    path, then a sorted scatter-add of the ``[seg_rows, F]`` partials.

    feats: [src_rows(+ optional trailing rows), F]; sections read
      ``[start, start+size)`` so an appended global dummy row is fine.
    sect_idx / sect_sub_dst: SectionedEll.idx / .sub_dst as jax arrays.
    sect_meta: static tuple of (start, size) per section.
    sect_w (optional): per-section edge weights shaped like
      ``sect_idx`` (SectionedEll.weight_tables — the baked fused-norm
      scales), applied in-register before the width reduction.
    """
    F = feats.shape[1]
    out = jnp.zeros((num_rows + 1, F), dtype=feats.dtype)
    zero = jnp.zeros((1, F), dtype=feats.dtype)
    weighted = sect_w is not None and len(sect_w) > 0
    for si, ((st, sz), tbl, sdst) in enumerate(
            zip(sect_meta, sect_idx, sect_sub_dst)):
        xsec = jnp.concatenate(
            [lax.slice(feats, (st, 0), (st + sz, F)), zero], axis=0)
        xs = (tbl, sdst)
        if weighted:
            xs += (sect_w[si].astype(feats.dtype),)

        def body(o, ch, xsec=xsec):
            idx_ch, dst_ch = ch[0], ch[1]
            g = xsec[idx_ch]
            if len(ch) > 2:
                g = g * ch[2][:, :, None]
            part = g.sum(axis=1)
            return o.at[dst_ch].add(part, indices_are_sorted=True), None

        out, _ = lax.scan(body, out, xs)
    return out[:num_rows]


def aggregate_ell_sect_split(feats: jax.Array, sect_idx, sect_sub_dst,
                             sect_meta, num_rows: int) -> jax.Array:
    """:func:`aggregate_ell_sect` with the ``[N, W]`` block gather
    replaced by W independent ``[N]``-index row gathers summed as they
    go — a deliberately different XLA gather lowering raced against
    the block form in benchmarks/micro_agg.py (the block gather
    materializes the ``[N, W, F]`` transient before its width
    reduction; the split form keeps a single ``[N, F]`` accumulator)."""
    F = feats.shape[1]
    out = jnp.zeros((num_rows + 1, F), dtype=feats.dtype)
    zero = jnp.zeros((1, F), dtype=feats.dtype)
    for (st, sz), tbl, sdst in zip(sect_meta, sect_idx, sect_sub_dst):
        xsec = jnp.concatenate(
            [lax.slice(feats, (st, 0), (st + sz, F)), zero], axis=0)
        W = tbl.shape[-1]

        def body(o, ch, xsec=xsec, W=W):
            idx_ch, dst_ch = ch
            part = xsec[idx_ch[:, 0]]
            for j in range(1, W):
                part = part + xsec[idx_ch[:, j]]
            return o.at[dst_ch].add(part, indices_are_sorted=True), None

        out, _ = lax.scan(body, out, (tbl, sdst))
    return out[:num_rows]


def aggregate_flat_sum(feats: jax.Array, flat_idx: jax.Array,
                       flat_dst: jax.Array, num_rows: int,
                       flat_w=None) -> jax.Array:
    """Uniform width-8 sub-row SUM — the sum-path twin of the
    attention layout's ``gat_aggregate_flat8`` (ops/attention.py) and
    the compile-wall fix for the per-bucket ELL unroll: every row's
    neighborhood is split into width-8 sub-rows in ONE
    ``[n_chunks, seg_rows, 8]`` table (core/ell.py
    ``flat_sum_from_graph`` — a :class:`SectionedEll` with a single
    section spanning all sources, so ids are global/gathered
    coordinates), and the aggregation is ONE ``lax.scan`` whose body
    shape depends only on (dtype, seg_rows, F) — never on the degree
    distribution.  ``aggregate_ell``'s per-width Python unroll
    compiles one gather+reduce program per degree bucket (doubled by
    autodiff); this path compiles exactly one scan program per
    (dtype, F-quantum), which is what lets the persistent compile
    cache and the prewarm pass (utils/prewarm.py) cover large graphs.

    feats: [G+1, F] gathered features with trailing zero row (== the
      dummy id in ``flat_idx``).
    flat_idx: int32 [n_chunks, seg_rows, 8]; flat_dst: int32
      [n_chunks, seg_rows] output rows, ascending within each chunk
      (chunk padding points at ``num_rows``).
    flat_w (optional): fp32 shaped like ``flat_idx`` — the baked
      ``D^-1/2 A D^-1/2`` fused-normalization entries
      (``SectionedEll.weight_tables`` of the single section), applied
      in-register before the width reduction.
    """
    F = feats.shape[1]
    out = jnp.zeros((num_rows + 1, F), dtype=feats.dtype)
    xs = (flat_idx, flat_dst)
    if flat_w is not None:
        xs += (flat_w.astype(feats.dtype),)

    def body(o, ch):
        g = feats[ch[0]]
        if len(ch) > 2:
            g = g * ch[2][:, :, None]
        part = g.sum(axis=1)
        return o.at[ch[1]].add(part, indices_are_sorted=True), None

    out, _ = lax.scan(body, out, xs)
    return out[:num_rows]


def aggregate_flat_max(feats: jax.Array, flat_idx: jax.Array,
                       flat_dst: jax.Array, num_rows: int) -> jax.Array:
    """Neighbor MAX over the uniform width-8 layout (MIN via negation
    at the call site) — one scan program like
    :func:`aggregate_flat_sum`, with the width reduction a masked max
    and the per-chunk combine a sorted scatter-max (max is
    associative, so a row's sub-rows spanning chunks combine
    exactly).  Dummy/padding sources weigh -inf; rows with no real
    neighbor yield -inf here and the caller maps non-finite rows to 0
    (the sum path's empty-row convention, models/builder.py
    ``_max_fwd``)."""
    F = feats.shape[1]
    dummy = feats.shape[0] - 1
    neg = jnp.asarray(-jnp.inf, dtype=feats.dtype)
    out = jnp.full((num_rows + 1, F), neg, dtype=feats.dtype)

    def body(o, ch):
        idx_ch, dst_ch = ch
        g = feats[idx_ch]
        m = (idx_ch != dummy)[:, :, None]
        part = jnp.max(jnp.where(m, g, neg), axis=1)
        return o.at[dst_ch].max(part, indices_are_sorted=True), None

    out, _ = lax.scan(body, out, (flat_idx, flat_dst))
    return out[:num_rows]


def aggregate_ell_max(feats: jax.Array, ell_idx, ell_row_pos: jax.Array,
                      num_rows: int,
                      budget_elems: int = 1 << 24) -> jax.Array:
    """ELL neighbor MAX (MIN via negation at the call site): per
    bucket, gather and max over the width axis with dummy/padding
    sources masked to -inf.  Large buckets are row-segmented with
    ``lax.scan`` under the same ``budget_elems`` transient bound as
    :func:`aggregate_ell` — a mid-width bucket x wide F must not
    materialize past the budget on the MAX path either (ADVICE r2 /
    VERDICT r2 weak #5).  Rows with no real neighbor yield -inf here;
    the caller maps non-finite rows to 0 (matching the sum path's
    empty-row convention)."""
    F = feats.shape[1]
    dummy = feats.shape[0] - 1
    neg = jnp.asarray(-jnp.inf, dtype=feats.dtype)

    def seg_max(idx_seg):
        g = feats[idx_seg]                           # [r, W, F]
        m = (idx_seg != dummy)[:, :, None]
        return jnp.max(jnp.where(m, g, neg), axis=1)

    outs = []
    for idx in ell_idx:
        R, W = idx.shape
        if R * W * F <= budget_elems:
            outs.append(seg_max(idx))
            continue
        segs = -(-R * W * F // budget_elems)
        seg_rows = -(-R // segs)
        Rp = seg_rows * segs
        pad = jnp.full((Rp - R, W), dummy, dtype=idx.dtype)
        idx_p = jnp.concatenate([idx, pad], axis=0)

        def body(_, ch):
            return None, seg_max(ch)

        _, segs_out = lax.scan(body, None,
                               idx_p.reshape(segs, seg_rows, W))
        outs.append(segs_out.reshape(Rp, F)[:R])
    tail = jnp.full((1, F), neg, dtype=feats.dtype)
    cat = jnp.concatenate(outs + [tail], axis=0)
    return cat[ell_row_pos]


def aggregate(feats: jax.Array, edge_src: jax.Array, edge_dst: jax.Array,
              num_rows: int, impl: str = "segment",
              chunk: int = 512) -> jax.Array:
    """Dispatch over implementations; identical numerics (fp32 addition
    order differs between impls — tests use tolerances accordingly)."""
    if impl == "segment":
        return aggregate_segment(feats, edge_src, edge_dst, num_rows)
    if impl == "blocked":
        return aggregate_blocked(feats, edge_src, edge_dst, num_rows,
                                 chunk=chunk)
    if impl == "scan":
        return aggregate_scan(feats, edge_src, edge_dst, num_rows,
                              chunk=chunk)
    if impl == "pallas":
        raise ValueError(
            "impl='pallas' is the one-launch ELL kernel "
            "(kernels/ell_spmm.py) and needs the ELL tables, not an "
            "edge list — route through GraphContext (aggr_impl='pallas') "
            "or call ell_aggregate_pallas directly")
    if impl == "pallas_csr":
        try:
            from ..kernels.spmm import csr_spmm_pallas
        except ImportError as e:
            raise NotImplementedError(
                "the pallas_csr aggregation kernel is not available in "
                "this build; use impl='blocked'") from e
        return csr_spmm_pallas(feats, edge_src, edge_dst, num_rows,
                               chunk=chunk)
    raise ValueError(f"unknown aggregate impl: {impl}")


def aggregate_mean(feats: jax.Array, edge_src: jax.Array,
                   edge_dst: jax.Array, num_rows: int,
                   in_degree: jax.Array, impl: str = "segment",
                   chunk: int = 512) -> jax.Array:
    """Mean aggregator (AGGR_AVG of the reference's declared-but-unbuilt
    AggrType enum, ``gnn.h:75-80``): sum / real in-degree."""
    s = aggregate(feats, edge_src, edge_dst, num_rows, impl=impl,
                  chunk=chunk)
    deg = jnp.maximum(in_degree.astype(s.dtype), 1.0)
    return s / deg[:, None]
