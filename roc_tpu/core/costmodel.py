"""Per-partition cost model + cost-balanced split search.

The headline contribution of the reference (ROC, MLSys'20) is not the
GNN math — it is the **online-learned cost model that drives graph
partitioning**: balance partitions on *predicted execution time*, not
raw edge counts, and refine the split as measurements arrive.  The
reference fits a per-GPU linear model over graph statistics and moves
partition boundaries between epochs; here the same idea lands TPU-
native:

- :func:`phi_matrix` — per-partition static feature vectors
  ``φ(p) = (1, padded nodes, padded edges, halo-in rows, halo-out
  rows, degree p95, bdense live blocks, streamed blocks)``.  Padded
  (not raw) counts, because on the SPMD layer shapes ARE cost: every
  device runs the max shard's padded program, so the straggler's
  quantized shape gates every step and every ring hop.
- :class:`PartitionCostModel` — ``cost(p) = w · φ(p)`` with weights
  fit by **online ridge regression** (prior-anchored: zero
  observations returns the edge-balance prior exactly) against
  measured per-shard step times.  Under lockstep SPMD only the
  straggler's time is observable, so each measured epoch time is
  attributed to the partition the model currently predicts slowest —
  the reference's "measure, refit, re-split" loop with
  winner-takes-all attribution.
- :func:`cost_balanced_bounds` — contiguous split points minimizing
  ``max_p cost(p)``: binary search on the cost cap with greedy
  maximal packing over the edge prefix sum (feasibility is O(P log V)
  per probe — exact on the prefix-summable features, which is what
  the search weights cover).  Candidate costs are quantized to the
  node/edge padding multiples, so re-splits that cannot change the
  padded shapes tie exactly and repeat shapes hit the compile cache.
  The greedy sweep (``partition.edge_balanced_bounds``) stays as the
  cold-start initializer and the never-worse guard: the returned
  split's modeled max cost is <= the greedy split's by construction.

The epoch-boundary repartitioning that consumes this lives in
``parallel/distributed.DistributedTrainer.maybe_rebalance``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

# Feature order of every φ vector in this module.  ``stream_blocks``
# is the streamed-tier block count (features='host'); the distributed
# trainer never streams, so it carries 0 there — kept so the single-
# device planner can reuse the same vector shape.  ``attn_edges`` is
# the padded edge count AGAIN, but only for attention models: the
# per-edge softmax (exp + segment-max + normalize) is a second O(E)
# pass the plain sum path never pays, and folding it into the shared
# edge weight under-balanced attention workloads.  ``flat8_chunks``
# is the flat8 layout's scan length (8-wide sub-row count) — the
# attn_flat8/flat_sum consolidation walks chunks, not raw edges, so
# its cost quantizes on sub-rows.  Both are 0 for workloads that
# don't run that code, which keeps their fitted weights pinned to
# the prior (zero) there.
PHI = ("intercept", "padded_nodes", "padded_edges", "halo_in",
       "halo_out", "deg_p95", "bd_blocks", "stream_blocks",
       "attn_edges", "flat8_chunks")

# Per-feature scales for ridge conditioning: raw counts span ~6 orders
# of magnitude (intercept 1 vs 1e8 edges) and an unscaled normal
# matrix is numerically useless.  Fixed, documented constants — NOT
# data-derived, so two processes always build the identical model.
_SCALE = np.array([1.0, 1e4, 1e5, 1e3, 1e3, 1e2, 1e2, 1e2, 1e5,
                   1e4])

# Cold-start prior (raw-unit weights): pure padded-edge balance with a
# small padded-node tiebreak — the greedy sweep's objective, solved to
# its minimax optimum instead of first-fit.  The node term keeps a
# degenerate all-the-low-degree-vertices part from blowing up the
# [P, part_nodes, F] feature padding on edge-flat graphs.  Magnitudes
# are realistic ms-per-unit (~1e8 edges/s aggregate rate), NOT just a
# direction: the prior is also the ridge anchor, and an inflated
# anchor would bias the fit against real measurements for many
# observations.  Only the nodes:edges RATIO shapes the search.
_PRIOR_RAW = np.zeros(len(PHI))
_PRIOR_RAW[PHI.index("padded_nodes")] = 2.5e-6
_PRIOR_RAW[PHI.index("padded_edges")] = 1e-5
# attention's per-edge softmax pass costs about half the base
# gather-multiply rate; a flat8 chunk (8 sub-row slots) carries a
# fixed decode+accumulate overhead on top of its edges.  Nonzero
# priors because the cold-start split must already see the extra
# work — the ROADMAP's "--partition cost under-balances attention
# workloads" was exactly the zero-prior cold start.
_PRIOR_RAW[PHI.index("attn_edges")] = 5e-6
_PRIOR_RAW[PHI.index("flat8_chunks")] = 2e-5


def _ceil_mult(x, m: int):
    """Round up to a multiple of ``m`` (elementwise)."""
    return -(-x // m) * m if m > 1 else x


class PartitionCostModel:
    """Online ridge regression ``t ≈ w · φ`` with a prior anchor.

    Bayesian ridge with prior mean ``w0``:
    ``w = (λI + Φ'Φ)^-1 (λ w0 + Φ' t)`` — with zero observations the
    weights ARE the prior (the cold-start split is exactly the
    quantized edge-balance minimax), and every
    :meth:`observe` pulls them toward the measured times.  All state
    is a (d×d) normal matrix + d-vector: O(1) memory, O(d³) per
    solve, deterministic across processes.
    """

    def __init__(self, node_multiple: int = 8, edge_multiple: int = 128,
                 lam: float = 1.0):
        d = len(PHI)
        self.node_multiple = int(node_multiple)
        self.edge_multiple = int(edge_multiple)
        self._lam = float(lam)
        self._w0 = _PRIOR_RAW * _SCALE          # prior in scaled space
        self._A = lam * np.eye(d)
        self._b = lam * self._w0
        self.n_obs = 0

    # ---- fitting ----

    def observe(self, phi_raw: np.ndarray, t_ms: float) -> None:
        """Fold one (features, measured ms) pair into the normal
        equations.  ``phi_raw`` is one raw φ vector (PHI order)."""
        x = np.asarray(phi_raw, dtype=np.float64) / _SCALE
        self._A += np.outer(x, x)
        self._b += x * float(t_ms)
        self.n_obs += 1

    def weights_raw(self) -> np.ndarray:
        """Fitted weights in raw-feature units (ms per node/edge/...)."""
        return np.linalg.solve(self._A, self._b) / _SCALE

    def predict(self, phi_mat_raw: np.ndarray) -> np.ndarray:
        """Predicted per-partition step ms for a [P, d] raw φ matrix."""
        return np.asarray(phi_mat_raw, dtype=np.float64) @ \
            self.weights_raw()

    def search_weights(self, attn_edges: bool = False,
                       flat8: bool = False) -> Tuple[float, float]:
        """(w_nodes, w_edges) for the split search: the fitted weights
        on the prefix-summable features, clamped >= 0 (the packing
        argument needs monotone range costs).  The attention and flat8
        columns are edge-proportional, so for workloads that run that
        code their weights fold into the effective edge rate
        (``flat8_chunks`` is per 8-wide sub-row — /8 per edge).
        Degenerate fits (all ~0, e.g. measurements that
        anti-correlate with size) fall back to the prior rather than
        producing a constant-cost search."""
        w = self.weights_raw()
        wn = max(float(w[PHI.index("padded_nodes")]), 0.0)
        we = max(float(w[PHI.index("padded_edges")]), 0.0)
        if attn_edges:
            we += max(float(w[PHI.index("attn_edges")]), 0.0)
        if flat8:
            we += max(float(w[PHI.index("flat8_chunks")]), 0.0) / 8.0
        if wn + we <= 0.0:
            wn = _PRIOR_RAW[PHI.index("padded_nodes")]
            we = _PRIOR_RAW[PHI.index("padded_edges")]
            if attn_edges:
                we += _PRIOR_RAW[PHI.index("attn_edges")]
            if flat8:
                we += _PRIOR_RAW[PHI.index("flat8_chunks")] / 8.0
        return wn, we


# ------------------------------------------------- split search

def range_cost(row_ptr: np.ndarray, l: int, r1: int,
               w_nodes: float, w_edges: float,
               node_multiple: int, edge_multiple: int) -> float:
    """Modeled cost of the half-open vertex range [l, r1): the
    prefix-summable surrogate ``w_n * pad(nodes) + w_e * pad(edges)``
    with both counts quantized to the padding multiples — the shapes
    the SPMD layer would actually compile for this range."""
    n = _ceil_mult(int(r1 - l), node_multiple)
    e = _ceil_mult(int(row_ptr[r1] - row_ptr[l]), edge_multiple)
    return float(w_nodes * n + w_edges * e)


def bounds_max_cost(row_ptr: np.ndarray,
                    bounds: Sequence[Tuple[int, int]],
                    w_nodes: float, w_edges: float,
                    node_multiple: int, edge_multiple: int) -> float:
    """``max_p cost(p)`` of an inclusive-bounds split under the model."""
    row_ptr = np.asarray(row_ptr, dtype=np.int64)
    return max(range_cost(row_ptr, l, r + 1, w_nodes, w_edges,
                          node_multiple, edge_multiple)
               for l, r in bounds if r >= l)


def _pack(row_ptr: np.ndarray, num_nodes: int, num_parts: int,
          cap: float, w_nodes: float, w_edges: float,
          node_multiple: int, edge_multiple: int
          ) -> Optional[List[Tuple[int, int]]]:
    """Greedy maximal packing under cost cap ``cap``: each part takes
    the longest prefix whose cost stays <= cap (optimal feasibility
    check — range cost is monotone in the right endpoint and
    non-increasing in the left).  Returns inclusive bounds with empty
    ranges only in the tail, or None when infeasible."""
    bounds: List[Tuple[int, int]] = []
    l = 0
    for _ in range(num_parts):
        if l >= num_nodes:
            break
        if range_cost(row_ptr, l, l + 1, w_nodes, w_edges,
                      node_multiple, edge_multiple) > cap:
            return None
        lo, hi = l + 1, num_nodes
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if range_cost(row_ptr, l, mid, w_nodes, w_edges,
                          node_multiple, edge_multiple) <= cap:
                lo = mid
            else:
                hi = mid - 1
        bounds.append((l, lo - 1))
        l = lo
    if l < num_nodes:
        return None
    while len(bounds) < num_parts:
        bounds.append((num_nodes, num_nodes - 1))
    return bounds


def cost_balanced_bounds(row_ptr: np.ndarray, num_parts: int,
                         node_multiple: int = 8,
                         edge_multiple: int = 128,
                         weights: Optional[Tuple[float, float]] = None
                         ) -> List[Tuple[int, int]]:
    """Contiguous split minimizing the max quantized range cost.

    Binary search on the cost cap (each probe is the O(P log V)
    greedy packing above) between the trivial lower bounds (the
    costliest single vertex; the unquantized total divided by P) and
    the one-part cost, down to a quarter of the quantization step —
    past that, caps cannot change which padded shapes are reachable.

    ``weights`` is ``(w_nodes, w_edges)`` from
    :meth:`PartitionCostModel.search_weights`; default = the cold-
    start prior.  Never worse than the greedy sweep under the model:
    the greedy bounds are evaluated too and returned if they tie or
    beat the searched split (also the hard fallback for degenerate
    weight vectors)."""
    from .partition import edge_balanced_bounds
    row_ptr = np.asarray(row_ptr, dtype=np.int64)
    V = row_ptr.shape[0] - 1
    E = int(row_ptr[-1])
    wn, we = weights if weights is not None else (
        _PRIOR_RAW[PHI.index("padded_nodes")],
        _PRIOR_RAW[PHI.index("padded_edges")])
    greedy = edge_balanced_bounds(row_ptr, num_parts)
    if wn <= 0 and we <= 0:
        return greedy
    if V == 0 or num_parts <= 1:
        return greedy
    max_deg = int(np.diff(row_ptr).max())
    lo = max(wn * node_multiple
             + we * _ceil_mult(max_deg, edge_multiple),
             (wn * V + we * E) / num_parts)
    hi = range_cost(row_ptr, 0, V, wn, we, node_multiple,
                    edge_multiple)
    steps = [w * m for w, m in ((wn, node_multiple),
                                (we, edge_multiple)) if w > 0]
    tol = min(steps) / 4.0
    for _ in range(64):
        if hi - lo <= tol:
            break
        mid = (lo + hi) / 2.0
        if _pack(row_ptr, V, num_parts, mid, wn, we,
                 node_multiple, edge_multiple) is None:
            lo = mid
        else:
            hi = mid
    bounds = _pack(row_ptr, V, num_parts, hi, wn, we,
                   node_multiple, edge_multiple)
    if bounds is None:
        return greedy
    if bounds_max_cost(row_ptr, bounds, wn, we, node_multiple,
                       edge_multiple) > \
            bounds_max_cost(row_ptr, greedy, wn, we, node_multiple,
                            edge_multiple):
        return greedy
    return bounds


# ------------------------------------------------- static features

def partition_halo_stats(pg) -> Tuple[np.ndarray, np.ndarray]:
    """(halo_in [P], halo_out [P]): per partition, the distinct
    EXTERNAL source rows its edges gather (halo-in — what the ring /
    gather must deliver to it) and the distinct LOCAL rows other
    partitions reference (halo-out — what it must send).  One
    vectorized O(E) pass over the materialized columns."""
    P = pg.num_parts
    V = pg.num_nodes
    halo_in = np.zeros(P, dtype=np.int64)
    ext: List[np.ndarray] = []
    for p in range(P):
        l, r = pg.bounds[p]
        e = int(pg.real_edges[p])
        col = np.asarray(pg.part_col_idx[p][:e], dtype=np.int64)
        col = col[col < V]          # drop dummy sources
        outside = col[(col < l) | (col > r)] if r >= l else col
        u = np.unique(outside)
        halo_in[p] = u.size
        ext.append(u)
    all_ext = (np.unique(np.concatenate(ext)) if ext
               else np.zeros(0, dtype=np.int64))
    halo_out = np.zeros(P, dtype=np.int64)
    for p in range(P):
        l, r = pg.bounds[p]
        if r >= l:
            halo_out[p] = (np.searchsorted(all_ext, r, side="right")
                           - np.searchsorted(all_ext, l, side="left"))
    return halo_in, halo_out


def phi_matrix(pg, bd_occupancy: Sequence[dict] = (),
               stream_blocks: int = 0, attn_edges: bool = False,
               flat8: bool = False) -> np.ndarray:
    """[P, len(PHI)] raw per-partition feature matrix for a built
    :class:`~roc_tpu.core.partition.PartitionedGraph`.
    ``bd_occupancy`` is ``ShardedData.bd_occupancy`` when the bdense
    planner ran (live dense-block count per part), else zeros.
    ``attn_edges=True`` (the model attends — GAT's per-edge softmax)
    charges the padded edge count a second time in its own column;
    ``flat8=True`` (aggr_impl is the flat8 family) fills the scan-
    length column with the per-part 8-wide sub-row count."""
    P = pg.num_parts
    nm = getattr(pg, "node_multiple", 8)
    em = getattr(pg, "edge_multiple", 128)
    real_n = np.asarray(pg.real_nodes, dtype=np.int64)
    real_e = np.asarray(pg.real_edges, dtype=np.int64)
    halo_in, halo_out = partition_halo_stats(pg)
    p95 = np.zeros(P)
    for p in range(P):
        n = int(real_n[p])
        if n:
            p95[p] = float(np.percentile(
                pg.part_in_degree[p, :n], 95))
    bd = np.zeros(P)
    for p, occ in enumerate(bd_occupancy):
        if p < P:
            bd[p] = float(occ.get("n_blocks", 0))
    padded_e = _ceil_mult(real_e, em).astype(np.float64)
    out = np.stack([
        np.ones(P),
        _ceil_mult(real_n, nm).astype(np.float64),
        padded_e,
        halo_in.astype(np.float64),
        halo_out.astype(np.float64),
        p95,
        bd,
        np.full(P, float(stream_blocks)),
        padded_e if attn_edges else np.zeros(P),
        (_ceil_mult(real_e, 8) // 8).astype(np.float64)
        if flat8 else np.zeros(P),
    ], axis=1)
    return out


def partition_static_stats(pg, bd_occupancy: Sequence[dict] = (),
                           phi: Optional[np.ndarray] = None) -> dict:
    """Split-quality record for the run manifest: per-part padded
    nodes/edges and halo rows plus the ``max/mean`` imbalance ratios
    — every run records the split it actually trained on
    (``python -m roc_tpu.report`` renders the table).  ``phi`` reuses
    an already-computed :func:`phi_matrix` (the halo pass is O(E) —
    callers holding a cache must not pay it twice)."""
    if phi is None:
        phi = phi_matrix(pg, bd_occupancy=bd_occupancy)
    real_e = np.asarray(pg.real_edges, dtype=np.float64)
    real_n = np.asarray(pg.real_nodes, dtype=np.float64)

    def _imb(x):
        m = float(x.mean())
        return round(float(x.max()) / m, 4) if m > 0 else 1.0

    return {
        "num_parts": int(pg.num_parts),
        "part_nodes": int(pg.part_nodes),
        "part_edges": int(pg.part_edges),
        "real_nodes": [int(x) for x in real_n],
        "real_edges": [int(x) for x in real_e],
        "padded_nodes": [int(x) for x in phi[:, PHI.index(
            "padded_nodes")]],
        "padded_edges": [int(x) for x in phi[:, PHI.index(
            "padded_edges")]],
        "halo_in": [int(x) for x in phi[:, PHI.index("halo_in")]],
        "halo_out": [int(x) for x in phi[:, PHI.index("halo_out")]],
        "edge_imbalance": _imb(real_e),
        "node_imbalance": _imb(real_n),
    }
