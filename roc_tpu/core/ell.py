"""Degree-bucketed ELLPACK layout for TPU-friendly CSR aggregation.

The reference's hot loop is an irregular per-edge CSR walk with
shared-memory accumulators and atomics (``scattergather_kernel.cu:20-76``
via cub BlockScan).  TPUs have no atomics and XLA's scatter serializes,
so the rebuild uses a *regularized* layout instead:

- every row is assigned to a power-of-two **width bucket** covering its
  in-degree (min width 8, so padding waste is bounded by 2x plus the
  small-row floor);
- each bucket stores a dense ``[rows, width]`` matrix of source indices
  (padded entries point at the dummy zero-feature row);
- aggregation per bucket = ``feats[idx]`` (a large vectorized gather on
  contiguous feature rows) followed by a sum over the width axis — pure
  gather + reduce, lowering to TPU's native gather units and the VPU,
  with *no* scatter, *no* sequential scan over edge chunks, and *no*
  extra FLOPs;
- a static inverse permutation maps the concatenated bucket outputs back
  to local row order.

Buckets whose gathered block would exceed a memory budget are processed
in row segments via ``lax.scan`` (tens of iterations at Reddit scale, so
serialization is negligible).

For the distributed path, the bucket structure is made *uniform across
partitions* (same widths, same padded row counts) so the stacked arrays
shard over the 1-D parts mesh with identical static shapes per device.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

import numpy as np


@dataclass
class EllTable:
    """Stacked per-partition ELL tables with uniform shapes.

    widths: static tuple of bucket widths (powers of two, ascending).
    idx: one array per bucket, int32 ``[P, rows_b, width_b]`` of source
      indices in *gathered-row coordinates* (dummy row = the appended
      zero row of the gathered feature matrix).
    row_pos: int32 ``[P, part_nodes]`` position of each local row in the
      concatenated bucket output; rows in no bucket (degree 0) point at
      the trailing zero slot (index == total bucket rows).
    """

    widths: Tuple[int, ...]
    idx: Tuple[np.ndarray, ...]
    row_pos: np.ndarray

    @property
    def num_parts(self) -> int:
        return self.row_pos.shape[0]

    def device_view(self, p: int) -> "EllTable":
        """Single-partition slice (keeps the leading axis)."""
        return EllTable(widths=self.widths,
                        idx=tuple(a[p:p + 1] for a in self.idx),
                        row_pos=self.row_pos[p:p + 1])


def row_widths(deg: np.ndarray, min_width: int) -> np.ndarray:
    """Per-row bucket width: smallest power-of-two >= degree (floored at
    ``min_width``); 0 for empty rows.  Widths are unbounded: a hub row
    of any degree gets its own wide bucket (the aggregation kernel
    scan-chunks large buckets, so memory stays bounded) — clamping
    would silently drop edges.  Fully vectorized (exact integer
    comparisons via a power table, no float log2)."""
    deg = np.asarray(deg)
    max_d = int(deg.max()) if deg.size else 1
    powers = [min_width]
    while powers[-1] < max_d:
        powers.append(powers[-1] * 2)
    powers = np.array(powers, dtype=np.int64)
    w = powers[np.searchsorted(powers, deg, side="left")]
    return np.where(deg > 0, w, 0).astype(np.int64)


def build_ell(local_row_ptr: np.ndarray, col_idx: np.ndarray,
              min_width: int = 8) -> dict:
    """Build one partition's bucket assignment from a local CSR.

    local_row_ptr: int [n+1] offsets into ``col_idx`` (callers pass the
    *real* row count so padding rows/edges are excluded).  Returns
    ``{width: (rows, idx)}`` with ``rows`` int64 [R_w] row ids and
    ``idx`` int32 [R_w, w] source indices (-1 padding to be replaced by
    the dummy id at stack time).  Vectorized — no per-row Python.
    """
    row_ptr = np.asarray(local_row_ptr, dtype=np.int64)
    deg = np.diff(row_ptr)
    widths = row_widths(deg, min_width)
    buckets: dict = {}
    for w in np.unique(widths[widths > 0]):
        w = int(w)
        rows = np.flatnonzero(widths == w)
        grid = np.arange(w, dtype=np.int64)[None, :]         # [1, w]
        valid = grid < deg[rows][:, None]                     # [R, w]
        flat = row_ptr[rows][:, None] + grid                  # [R, w]
        idx = np.full((rows.shape[0], w), -1, dtype=np.int32)
        idx[valid] = col_idx[flat[valid]]
        buckets[w] = (rows, idx)
    return buckets


def ell_shape_plan(part_row_ptr: np.ndarray, real_nodes: np.ndarray,
                   min_width: int = 8) -> Tuple[Tuple[int, ...], dict]:
    """Global uniform bucket shapes from row pointers alone (O(V)
    metadata — no column data), so multi-host processes can each build
    only their own partitions' tables (:func:`place_ell_part`) and still
    agree on the SPMD-required identical shapes.

    The plan MUST see the exact degrees :func:`build_ell` will see:
    ``np.diff(part_row_ptr[p, :n + 1])``.  These differ from the real
    in-degrees when ``real_nodes[p] == part_nodes`` — padding edges then
    have no padding row to live on and inflate the last real row's
    degree, so planning from real degrees would omit that row's
    (larger) bucket width and :func:`place_ell_part` would reject the
    table.

    Returns ``(widths, rows_per_width)`` where ``rows_per_width[w]`` is
    the max row count of bucket ``w`` over all partitions (floored at
    1 so shapes always exist)."""
    counts: dict = {}
    for p in range(part_row_ptr.shape[0]):
        n = int(real_nodes[p])
        if n == 0:
            continue
        deg = np.diff(part_row_ptr[p, :n + 1].astype(np.int64))
        w = row_widths(deg, min_width)
        for wv, c in zip(*np.unique(w[w > 0], return_counts=True)):
            counts[int(wv)] = max(counts.get(int(wv), 0), int(c))
    widths = tuple(sorted(counts)) or (min_width,)
    return widths, {w: max(counts.get(w, 0), 1) for w in widths}


def place_ell_part(buckets: dict, widths: Tuple[int, ...],
                   rows_per_width: dict, part_nodes: int,
                   dummy: int) -> Tuple[list, np.ndarray]:
    """Place one partition's buckets (from :func:`build_ell`) into the
    globally planned uniform shapes.  Returns ``(idx_arrays, row_pos)``
    with one int32 [rows_w, w] array per width and int32 [part_nodes]
    output positions (zero slot == total planned rows).  Raises if the
    built buckets contain a width the plan lacks — a plan/build
    disagreement must fail loudly, not silently drop those rows'
    edges."""
    extra = set(buckets) - set(widths)
    if extra:
        raise ValueError(
            f"ELL plan/build mismatch: built bucket widths {sorted(extra)} "
            f"absent from planned widths {list(widths)} — the shape plan "
            "was derived from different degrees than the bucket build")
    idx_arrays = []
    total_rows = sum(rows_per_width[w] for w in widths)
    row_pos = np.full(part_nodes, total_rows, dtype=np.int32)
    offset = 0
    for w in widths:
        R = rows_per_width[w]
        arr = np.full((R, w), dummy, dtype=np.int32)
        if w in buckets:
            rows, idx = buckets[w]
            n = rows.shape[0]
            if n > R:
                raise ValueError(
                    f"ELL plan/build mismatch: bucket width {w} has {n} "
                    f"rows but the plan allows {R}")
            arr[:n] = np.where(idx >= 0, idx, dummy)
            row_pos[rows] = offset + np.arange(n, dtype=np.int32)
        idx_arrays.append(arr)
        offset += R
    return idx_arrays, row_pos


def stack_ell(per_part_buckets: Sequence[dict], part_nodes: int,
              dummy: int) -> EllTable:
    """Unify bucket structure across partitions and stack into the
    equal-shape arrays shard_map needs."""
    P = len(per_part_buckets)
    widths = sorted({w for b in per_part_buckets for w in b})
    rows_per_width = {
        w: max((b[w][0].shape[0] if w in b else 0
                for b in per_part_buckets), default=0)
        for w in widths}
    # drop empty widths, keep at least one so shapes exist
    widths = tuple(w for w in widths if rows_per_width[w] > 0) or (8,)
    rows_per_width = {w: max(rows_per_width.get(w, 0), 1) for w in widths}

    per_part = [place_ell_part(b, widths, rows_per_width, part_nodes,
                               dummy) for b in per_part_buckets]
    idx_arrays = tuple(
        np.stack([per_part[p][0][wi] for p in range(P)])
        for wi in range(len(widths)))
    row_pos = np.stack([per_part[p][1] for p in range(P)])
    return EllTable(widths=widths, idx=idx_arrays, row_pos=row_pos)


def ell_from_padded_parts(part_row_ptr: np.ndarray,
                          part_col_idx: np.ndarray,
                          real_nodes: np.ndarray,
                          part_nodes: int, dummy: int,
                          min_width: int = 8) -> EllTable:
    """EllTable for a PartitionedGraph's local CSRs (col indices already
    remapped to gathered-row coordinates; padding rows/edges excluded by
    slicing to the real row count — the local row_ptr bounds the real
    edge extent)."""
    per_part = []
    for p in range(part_row_ptr.shape[0]):
        n = int(real_nodes[p])
        ptr = part_row_ptr[p, :n + 1].astype(np.int64)
        per_part.append(build_ell(ptr, part_col_idx[p],
                                  min_width=min_width))
    return stack_ell(per_part, part_nodes, dummy)


def ell_from_graph(row_ptr: np.ndarray, col_idx: np.ndarray,
                   num_nodes: int, min_width: int = 8) -> EllTable:
    """Single-device EllTable (P == 1); dummy = num_nodes (the appended
    zero row)."""
    b = build_ell(np.asarray(row_ptr), np.asarray(col_idx),
                  min_width=min_width)
    return stack_ell([b], num_nodes, dummy=num_nodes)
