"""Degree-bucketed ELLPACK layout for TPU-friendly CSR aggregation.

The reference's hot loop is an irregular per-edge CSR walk with
shared-memory accumulators and atomics (``scattergather_kernel.cu:20-76``
via cub BlockScan).  TPUs have no atomics and XLA's scatter serializes,
so the rebuild uses a *regularized* layout instead:

- every row is assigned to a power-of-two **width bucket** covering its
  in-degree (min width 8, so padding waste is bounded by 2x plus the
  small-row floor);
- each bucket stores a dense ``[rows, width]`` matrix of source indices
  (padded entries point at the dummy zero-feature row);
- aggregation per bucket = ``feats[idx]`` (a large vectorized gather on
  contiguous feature rows) followed by a sum over the width axis — pure
  gather + reduce, lowering to TPU's native gather units and the VPU,
  with *no* scatter, *no* sequential scan over edge chunks, and *no*
  extra FLOPs;
- a static inverse permutation maps the concatenated bucket outputs back
  to local row order.

Buckets whose gathered block would exceed a memory budget are processed
in row segments via ``lax.scan`` (tens of iterations at Reddit scale, so
serialization is negligible).

For the distributed path, the bucket structure is made *uniform across
partitions* (same widths, same padded row counts) so the stacked arrays
shard over the 1-D parts mesh with identical static shapes per device.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np


@dataclass
class EllTable:
    """Stacked per-partition ELL tables with uniform shapes.

    widths: static tuple of bucket widths (powers of two, ascending).
    idx: one array per bucket, int32 ``[P, rows_b, width_b]`` of source
      indices in *gathered-row coordinates* (dummy row = the appended
      zero row of the gathered feature matrix).
    row_pos: int32 ``[P, part_nodes]`` position of each local row in the
      concatenated bucket output; rows in no bucket (degree 0) point at
      the trailing zero slot (index == total bucket rows).
    row_id: one array per bucket, int32 ``[P, rows_b]`` — the LOCAL
      output row each bucket row aggregates into (the forward map;
      row_pos is its inverse).  Padding bucket rows carry
      ``part_nodes`` (a dummy slot).  Attention aggregation needs this
      to gather per-destination scores bucket-side (ops/attention.py);
      the plain sum path never reads it.
    """

    widths: Tuple[int, ...]
    idx: Tuple[np.ndarray, ...]
    row_pos: np.ndarray
    row_id: Tuple[np.ndarray, ...] = ()

    @property
    def num_parts(self) -> int:
        return self.row_pos.shape[0]

    def device_view(self, p: int) -> "EllTable":
        """Single-partition slice (keeps the leading axis)."""
        return EllTable(widths=self.widths,
                        idx=tuple(a[p:p + 1] for a in self.idx),
                        row_pos=self.row_pos[p:p + 1],
                        row_id=tuple(a[p:p + 1] for a in self.row_id))


def ell_weight_tables(table: EllTable, d_dst: np.ndarray,
                      d_src: np.ndarray) -> Tuple[np.ndarray, ...]:
    """Baked fused-normalization weights for an :class:`EllTable` —
    one fp32 array per bucket, shaped like ``idx``:
    ``w[p, r, j] = d_dst[p, row_id[p, r]] * d_src[idx[p, r, j]]``
    (the per-edge entries of ``D^-1/2 A D^-1/2`` in ELL layout, so
    the fused aggregation needs ZERO runtime normalization —
    ops/aggregate.py aggregate_ell ``ell_w``).

    d_dst: [P, part_nodes] inv-sqrt in-degrees of local output rows.
    d_src: [gathered_rows] the same in gathered-source coordinates
      (single-device: == d_dst[0]; distributed: the padded global
      layout).  Padding bucket rows (``row_id == part_nodes``) and
      padding entries (``idx == gathered_rows`` dummy) weigh 0.
    """
    d_dst = np.asarray(d_dst, dtype=np.float32)
    P = table.num_parts
    dd = np.concatenate([d_dst, np.zeros((P, 1), np.float32)], axis=1)
    ds = np.concatenate([np.asarray(d_src, dtype=np.float32),
                         np.zeros(1, np.float32)])
    parts = np.arange(P)[:, None]
    return tuple(
        (dd[parts, rid][:, :, None] * ds[idx]).astype(np.float32)
        for idx, rid in zip(table.idx, table.row_id))


def row_widths(deg: np.ndarray, min_width: int) -> np.ndarray:
    """Per-row bucket width: smallest power-of-two >= degree (floored at
    ``min_width``); 0 for empty rows.  Widths are unbounded: a hub row
    of any degree gets its own wide bucket (the aggregation kernel
    scan-chunks large buckets, so memory stays bounded) — clamping
    would silently drop edges.  Fully vectorized (exact integer
    comparisons via a power table, no float log2)."""
    deg = np.asarray(deg)
    max_d = int(deg.max()) if deg.size else 1
    powers = [min_width]
    while powers[-1] < max_d:
        powers.append(powers[-1] * 2)
    powers = np.array(powers, dtype=np.int64)
    w = powers[np.searchsorted(powers, deg, side="left")]
    return np.where(deg > 0, w, 0).astype(np.int64)


def build_ell(local_row_ptr: np.ndarray, col_idx: np.ndarray,
              min_width: int = 8) -> dict:
    """Build one partition's bucket assignment from a local CSR.

    local_row_ptr: int [n+1] offsets into ``col_idx`` (callers pass the
    *real* row count so padding rows/edges are excluded).  Returns
    ``{width: (rows, idx)}`` with ``rows`` int64 [R_w] row ids and
    ``idx`` int32 [R_w, w] source indices (-1 padding to be replaced by
    the dummy id at stack time).  Vectorized — no per-row Python.
    """
    row_ptr = np.asarray(local_row_ptr, dtype=np.int64)
    deg = np.diff(row_ptr)
    widths = row_widths(deg, min_width)
    buckets: dict = {}
    for w in np.unique(widths[widths > 0]):
        w = int(w)
        rows = np.flatnonzero(widths == w)
        grid = np.arange(w, dtype=np.int64)[None, :]         # [1, w]
        valid = grid < deg[rows][:, None]                     # [R, w]
        flat = row_ptr[rows][:, None] + grid                  # [R, w]
        idx = np.full((rows.shape[0], w), -1, dtype=np.int32)
        idx[valid] = col_idx[flat[valid]]
        buckets[w] = (rows, idx)
    return buckets


def ell_shape_plan(part_row_ptr: np.ndarray, real_nodes: np.ndarray,
                   min_width: int = 8) -> Tuple[Tuple[int, ...], dict]:
    """Global uniform bucket shapes from row pointers alone (O(V)
    metadata — no column data), so multi-host processes can each build
    only their own partitions' tables (:func:`place_ell_part`) and still
    agree on the SPMD-required identical shapes.

    The plan MUST see the exact degrees :func:`build_ell` will see:
    ``np.diff(part_row_ptr[p, :n + 1])``.  These differ from the real
    in-degrees when ``real_nodes[p] == part_nodes`` — padding edges then
    have no padding row to live on and inflate the last real row's
    degree, so planning from real degrees would omit that row's
    (larger) bucket width and :func:`place_ell_part` would reject the
    table.

    Returns ``(widths, rows_per_width)`` where ``rows_per_width[w]`` is
    the max row count of bucket ``w`` over all partitions (floored at
    1 so shapes always exist)."""
    counts: dict = {}
    for p in range(part_row_ptr.shape[0]):
        n = int(real_nodes[p])
        if n == 0:
            continue
        deg = np.diff(part_row_ptr[p, :n + 1].astype(np.int64))
        w = row_widths(deg, min_width)
        for wv, c in zip(*np.unique(w[w > 0], return_counts=True)):
            counts[int(wv)] = max(counts.get(int(wv), 0), int(c))
    widths = tuple(sorted(counts)) or (min_width,)
    return widths, {w: max(counts.get(w, 0), 1) for w in widths}


def place_ell_part(buckets: dict, widths: Tuple[int, ...],
                   rows_per_width: dict, part_nodes: int,
                   dummy: int) -> Tuple[list, np.ndarray, list]:
    """Place one partition's buckets (from :func:`build_ell`) into the
    globally planned uniform shapes.  Returns ``(idx_arrays, row_pos,
    rid_arrays)`` with one int32 [rows_w, w] array per width, int32
    [part_nodes] output positions (zero slot == total planned rows),
    and the forward row map per bucket (int32 [rows_w], padding =
    ``part_nodes`` — see ``EllTable.row_id``).  Raises if the built
    buckets contain a width the plan lacks — a plan/build disagreement
    must fail loudly, not silently drop those rows' edges."""
    extra = set(buckets) - set(widths)
    if extra:
        raise ValueError(
            f"ELL plan/build mismatch: built bucket widths {sorted(extra)} "
            f"absent from planned widths {list(widths)} — the shape plan "
            "was derived from different degrees than the bucket build")
    idx_arrays = []
    rid_arrays = []
    total_rows = sum(rows_per_width[w] for w in widths)
    row_pos = np.full(part_nodes, total_rows, dtype=np.int32)
    offset = 0
    for w in widths:
        R = rows_per_width[w]
        arr = np.full((R, w), dummy, dtype=np.int32)
        rid = np.full(R, part_nodes, dtype=np.int32)
        if w in buckets:
            rows, idx = buckets[w]
            n = rows.shape[0]
            if n > R:
                raise ValueError(
                    f"ELL plan/build mismatch: bucket width {w} has {n} "
                    f"rows but the plan allows {R}")
            arr[:n] = np.where(idx >= 0, idx, dummy)
            rid[:n] = rows
            row_pos[rows] = offset + np.arange(n, dtype=np.int32)
        idx_arrays.append(arr)
        rid_arrays.append(rid)
        offset += R
    return idx_arrays, row_pos, rid_arrays


def stack_ell(per_part_buckets: Sequence[dict], part_nodes: int,
              dummy: int) -> EllTable:
    """Unify bucket structure across partitions and stack into the
    equal-shape arrays shard_map needs."""
    P = len(per_part_buckets)
    widths = sorted({w for b in per_part_buckets for w in b})
    rows_per_width = {
        w: max((b[w][0].shape[0] if w in b else 0
                for b in per_part_buckets), default=0)
        for w in widths}
    # drop empty widths, keep at least one so shapes exist
    widths = tuple(w for w in widths if rows_per_width[w] > 0) or (8,)
    rows_per_width = {w: max(rows_per_width.get(w, 0), 1) for w in widths}

    per_part = [place_ell_part(b, widths, rows_per_width, part_nodes,
                               dummy) for b in per_part_buckets]
    idx_arrays = tuple(
        np.stack([per_part[p][0][wi] for p in range(P)])
        for wi in range(len(widths)))
    row_pos = np.stack([per_part[p][1] for p in range(P)])
    row_id = tuple(
        np.stack([per_part[p][2][wi] for p in range(P)])
        for wi in range(len(widths)))
    return EllTable(widths=widths, idx=idx_arrays, row_pos=row_pos,
                    row_id=row_id)


def ell_from_padded_parts(part_row_ptr: np.ndarray,
                          part_col_idx: np.ndarray,
                          real_nodes: np.ndarray,
                          part_nodes: int, dummy: int,
                          min_width: int = 8) -> EllTable:
    """EllTable for a PartitionedGraph's local CSRs (col indices already
    remapped to gathered-row coordinates; padding rows/edges excluded by
    slicing to the real row count — the local row_ptr bounds the real
    edge extent)."""
    per_part = []
    for p in range(part_row_ptr.shape[0]):
        n = int(real_nodes[p])
        ptr = part_row_ptr[p, :n + 1].astype(np.int64)
        per_part.append(build_ell(ptr, part_col_idx[p],
                                  min_width=min_width))
    return stack_ell(per_part, part_nodes, dummy)


def ell_from_graph(row_ptr: np.ndarray, col_idx: np.ndarray,
                   num_nodes: int, min_width: int = 8) -> EllTable:
    """Single-device EllTable (P == 1); dummy = num_nodes (the appended
    zero row)."""
    b = build_ell(np.asarray(row_ptr), np.asarray(col_idx),
                  min_width=min_width)
    return stack_ell([b], num_nodes, dummy=num_nodes)


@dataclass
class SectionedEll:
    """Source-sectioned width-8 sub-row layout — the fast-gather form.

    Measured on TPU v5 lite (2026-07-29, V=233k E=115M F=256 fp32):
    XLA's gather+reduce runs ~9.3 ns/row when the gather TABLE is
    <= ~64 MiB (VMEM-resident) and the index block is shaped ``[N, 8]``
    with large N, vs ~15.7-17.4 ns/row for whole-table gathers — so
    splitting the source rows into <= ``section_rows`` sections and
    rewriting every ELL row as width-8 sub-rows cut the Reddit-scale
    aggregation from 2006 ms to 865 ms (2.3x).  Layout per section:

    - ``idx[s]``: int32 ``[n_chunks, seg_rows, 8]`` section-LOCAL source
      ids (dummy = the section's appended zero row); each original row's
      neighbors-in-section padded to a multiple of 8 and laid out as
      consecutive sub-rows;
    - ``sub_dst[s]``: int32 ``[n_chunks, seg_rows]`` the output row of
      each sub-row, ascending within each chunk (scatter-add with
      ``indices_are_sorted``); chunk padding points at ``num_rows``.

    The aggregation is a ``lax.scan`` over chunks carrying the output:
    gather-sum from the section slice, sorted scatter-add of the
    ``[seg_rows, F]`` partials.  Padding cost: each (row, section) pair
    rounds up to 8 — for avg section-degree d_s the overhead is
    <= 8/d_s + 4/d_s ~ a few percent at Reddit scale, but grows toward
    2x when d_s ~ 8 (many sections or low degree): prefer plain ELL
    for small graphs; this layout targets tables past VMEM size.
    """

    num_rows: int
    src_rows: int
    section_rows: int
    seg_rows: int
    sec_starts: Tuple[int, ...]
    sec_sizes: Tuple[int, ...]
    idx: Tuple[np.ndarray, ...]
    sub_dst: Tuple[np.ndarray, ...]
    sub_w: int = 8

    @property
    def padded_edges(self) -> int:
        return sum(a.size for a in self.idx)

    def as_jax(self):
        """(idx, sub_dst, meta) in the calling convention of
        :func:`roc_tpu.ops.aggregate.aggregate_ell_sect` — the single
        conversion point for every consumer (trainer, benches)."""
        import jax.numpy as jnp
        return (tuple(jnp.asarray(a) for a in self.idx),
                tuple(jnp.asarray(a) for a in self.sub_dst),
                tuple(zip(self.sec_starts, self.sec_sizes)))

    def weight_tables(self, d_dst: np.ndarray,
                      d_src: np.ndarray) -> Tuple[np.ndarray, ...]:
        """Baked fused-normalization weights — one fp32 array per
        section, shaped like ``idx``: ``w = d_dst[sub_dst] *
        d_src[start + idx]`` (the ``D^-1/2 A D^-1/2`` entries in
        sectioned layout; ops/aggregate.py aggregate_ell_sect
        ``sect_w``).

        d_dst: [num_rows] inv-sqrt in-degrees of the output rows, or
          stacked [P, num_rows] for per-part tables built by
          :func:`sectioned_from_padded_parts`.
        d_src: [src_rows] the same over source coordinates (gathered
          layout when they differ).  Chunk-padding sub-rows
          (``sub_dst == num_rows``) and padded entries (section-local
          dummy id == section size) weigh 0.
        """
        d_dst = np.asarray(d_dst, dtype=np.float32)
        d_src = np.asarray(d_src, dtype=np.float32)
        stacked = d_dst.ndim == 2
        zpad = (np.zeros((d_dst.shape[0], 1), np.float32) if stacked
                else np.zeros(1, np.float32))
        dd = np.concatenate([d_dst, zpad], axis=-1)
        out = []
        for st, sz, idx, sdst in zip(self.sec_starts, self.sec_sizes,
                                     self.idx, self.sub_dst):
            ds = np.concatenate([d_src[st:st + sz],
                                 np.zeros(1, np.float32)])
            if stacked:
                parts = np.arange(d_dst.shape[0])[:, None, None]
                wd = dd[parts, sdst]
            else:
                wd = dd[sdst]
            out.append((wd[..., None]
                        * ds[idx.astype(np.int64)]).astype(np.float32))
        return tuple(out)

    def with_idx_dtype(self, dtype) -> "SectionedEll":
        """Same layout with the index tables narrowed to ``dtype``
        (e.g. uint16 when every section's dummy id ``sec_size`` fits —
        section_rows <= 65535).  Halves the index-table HBM traffic;
        the gather semantics are unchanged."""
        info = np.iinfo(dtype)
        hi = max(self.sec_sizes)
        if hi > info.max:
            raise ValueError(
                f"section dummy id {hi} does not fit {np.dtype(dtype)} "
                f"(max {info.max}); build with section_rows <= "
                f"{info.max}")
        from dataclasses import replace
        return replace(
            self, idx=tuple(a.astype(dtype) for a in self.idx))


# Uniform flat-sum layout (aggregate_flat_sum): chunk granularity of
# the single global section.  8192 bounds the per-chunk gathered
# transient [seg, 8, F] at 64 MiB for F=256 fp32 — the same bound the
# attention flat8 tables use (they are the same layout).
FLAT_SEG_ROWS = 8192

# Edge count past which the resolve pass routes an 'ell'-bound auto
# resolution to the uniform 'flat_sum' layout instead: the per-width
# bucket unroll compiles one gather/scan program per degree bucket
# (doubled by autodiff and multiplied by layers), which is what pushed
# products-scale first compiles past 15 min (ROADMAP compile wall);
# the flat layout compiles ONE scan shape per (dtype, F).  Same
# threshold as the attention path's ATTN_FLAT8_MIN_EDGES
# (train/trainer.py) — the two flat routes are the same fix.
FLAT_SUM_MIN_EDGES = 20_000_000


def flat_sum_from_graph(row_ptr: np.ndarray, col_idx: np.ndarray,
                        num_rows: int, src_rows: int = None,
                        seg_rows: int = FLAT_SEG_ROWS) -> SectionedEll:
    """The uniform flat-sum tables: a :class:`SectionedEll` with ONE
    section spanning all ``src_rows`` sources (ids global, dummy ==
    ``src_rows``, sub-rows of a row consecutive/ascending) — the
    layout :func:`roc_tpu.ops.aggregate.aggregate_flat_sum` scans.
    Shared with the attention flat8 build (train/trainer.py
    ``make_graph_context``): one builder, two consumers."""
    if src_rows is None:
        src_rows = num_rows
    return sectioned_from_graph(row_ptr, col_idx, num_rows,
                                src_rows=src_rows,
                                section_rows=src_rows,
                                seg_rows=seg_rows)


def flat_sum_from_padded_parts(part_row_ptr: np.ndarray,
                               part_col: np.ndarray,
                               real_nodes: np.ndarray,
                               part_nodes: int, src_rows: int,
                               seg_rows: int = FLAT_SEG_ROWS
                               ) -> SectionedEll:
    """Stacked per-part flat-sum tables (``[P, n_chunks, seg_rows, 8]``
    — SPMD-uniform shapes like every other stacked layout); the
    distributed twin of :func:`flat_sum_from_graph`, shared by the
    'flat_sum' and 'attn_flat8' branches of
    ``parallel/distributed.shard_dataset``."""
    return sectioned_from_padded_parts(
        part_row_ptr, part_col, real_nodes, part_nodes,
        src_rows=src_rows, section_rows=src_rows, seg_rows=seg_rows)


SECTION_ROWS_DEFAULT = 65_536   # 64 MiB of fp32 rows at F=256
# Swept on-chip at Reddit scale (v5e, F=256 bf16, 2026-07-30):
# section_rows 32768/65536/131072/262144 -> 826/776/808/1747 ms and
# seg_rows 65536/131072/262144/524288 -> 809/776/781/778 ms — the
# defaults sit at the measured optimum for BOTH dtypes (the residency
# window tracks row count, not table bytes: halving the bytes with
# bf16 does NOT move the best section size), and bf16 gains only
# ~11% on the aggregation itself (row-rate-bound gathers, ~7 ns/edge).

# Upper bound of the sectioned layout's winning range (v5e, F=256,
# median of 5, benchmarks/micro_agg.py 2026-07-30):
#   V=233k: sectioned 865 ms vs ell 2006 ms  (2.3x win)
#   V=500k: sectioned 440 ms vs ell 477 ms   (marginal win)
#   V=1M:   sectioned 964 ms vs ell 440 ms   (2.2x LOSS)
#   V=2.45M: sectioned 3784 ms vs ell 1010 ms (3.7x loss)
# Past ~0.6M output rows the carry-scan's scatter-add dominates (the
# [V, F] carry is rewritten every chunk step), so 'auto' hands back to
# the whole-table ELL gather.
SECTIONED_MAX_ROWS = 600_000

# The auto-impl window is a MEASURED property of a device generation,
# not of TPUs in general.  Rows are (section_rows lower bound,
# max out_rows upper bound); only generations with an on-chip sweep
# get a row.  Unknown kinds fall back to the v5e numbers with a
# one-time stderr echo instead of silently mis-picking (VERDICT r3
# weak #5).  To calibrate a new generation: ONE command —
# ``python benchmarks/calibrate.py`` on the chip — races ell vs
# sectioned across a V-sweep and appends the measured row to
# ``benchmarks/calibration.json``, which this resolver merges over
# the builtin table (override path: ``ROC_TPU_CALIBRATION``).
SECTIONED_BOUNDS_BY_KIND = {
    "TPU v5 lite": (SECTION_ROWS_DEFAULT, SECTIONED_MAX_ROWS),
}
_UNCALIBRATED_WARNED: set = set()


def default_section_rows(sect_u16: bool = False) -> int:
    """Default section size for the sectioned layout; uint16
    section-local ids need the dummy id (== section size) to fit in
    the dtype.  The ONE place for that rule — the single-device,
    shard_dataset, and shard_dataset_local builders all call it."""
    return min(SECTION_ROWS_DEFAULT, 65_535) if sect_u16 \
        else SECTION_ROWS_DEFAULT


def calibration_path() -> str:
    """Location of the measured-bounds JSON (calibrate.py writes it,
    sectioned_bounds reads it)."""
    return os.environ.get(
        "ROC_TPU_CALIBRATION",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))),
            "benchmarks", "calibration.json"))


def _calibrated_rows() -> dict:
    """device_kind -> (lo, hi) rows measured by benchmarks/calibrate.py.
    Missing/corrupt file == no extra rows (the builtin table still
    applies); the file is tiny and read per resolve, so a fresh
    calibration takes effect without a restart."""
    try:
        import json
        with open(calibration_path()) as f:
            db = json.load(f)
        return {k: (int(v["lo"]), int(v["hi"]))
                for k, v in db.items()
                if isinstance(v, dict) and "lo" in v and "hi" in v}
    except (OSError, ValueError, TypeError):
        return {}


def sectioned_bounds(device_kind: Optional[str] = None
                     ) -> Tuple[int, int]:
    """(lower num_nodes bound, upper out_rows bound) of the sectioned
    layout's winning window for ``device_kind`` (default: the current
    backend's first device; resolution must never be what first
    claims the single-claim device, so failures fall back silently)."""
    if device_kind is None:
        device_kind = os.environ.get("ROC_TPU_DEVICE_KIND")
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:  # noqa: BLE001 - no backend == use defaults
            device_kind = None
    calibrated = _calibrated_rows()
    if device_kind in calibrated:
        return calibrated[device_kind]
    if device_kind in SECTIONED_BOUNDS_BY_KIND:
        return SECTIONED_BOUNDS_BY_KIND[device_kind]
    if device_kind is not None and device_kind != "cpu" and \
            device_kind not in _UNCALIBRATED_WARNED:
        _UNCALIBRATED_WARNED.add(device_kind)
        from ..obs.events import emit
        emit("resolve", f"sectioned-window bounds not calibrated for "
             f"{device_kind!r}; using v5e-measured defaults "
             f"(core/ell.py SECTIONED_BOUNDS_BY_KIND)",
             device_kind=device_kind)
    return SECTION_ROWS_DEFAULT, SECTIONED_MAX_ROWS


def resolve_auto_impl(num_nodes: int,
                      out_rows: Optional[int] = None,
                      device_kind: Optional[str] = None,
                      num_edges: Optional[int] = None) -> str:
    """The data-driven ``aggr_impl='auto'`` split — ONE place for the
    rule (trainer, distributed, bench, model zoo all call this):
    ``sectioned`` in its measured winning window, ``flat_sum`` for
    ell-bound graphs past :data:`FLAT_SUM_MIN_EDGES` (the compile-wall
    route: one uniform scan program instead of one program per degree
    bucket), ``ell`` otherwise.

    The two sectioned bounds scale with different sizes: the LOWER
    bound is the gathered source-table size (global ``num_nodes`` —
    sectioned's win is VMEM-resident section gathers, and a partition
    gathers from ALL nodes), while the UPPER bound is the scatter-add
    carry ``[out_rows, F]`` rewritten every chunk step — per-partition
    ``out_rows`` in distributed runs (defaults to ``num_nodes``
    single-device).  The bounds are generation-keyed
    (:func:`sectioned_bounds`).  ``num_edges=None`` skips the
    flat_sum route (legacy callers keep the old sectioned/ell
    split)."""
    if out_rows is None:
        out_rows = num_nodes
    lo, hi = sectioned_bounds(device_kind)
    if num_nodes > lo and out_rows <= hi:
        return "sectioned"
    if num_edges is not None and num_edges >= FLAT_SUM_MIN_EDGES:
        # outside sectioned's window the fallback used to be the
        # per-bucket ELL unroll — at this edge count its compile cost
        # (one program per width bucket x autodiff x layers) dominates
        # the first-run wall; the uniform flat layout compiles ONE
        # scan shape and gathers from the same whole table, so the
        # runtime is ell-class while the program space is O(1)
        return "flat_sum"
    return "ell"


def section_sub_counts(row_ptr: np.ndarray, col_idx: np.ndarray,
                       num_rows: int, src_rows: int,
                       section_rows: int = SECTION_ROWS_DEFAULT,
                       sub_w: int = 8) -> np.ndarray:
    """Per-section sub-row totals (the cheap metadata pass used to
    agree on uniform chunk counts across SPMD partitions/hosts).
    Native single-pass when librocio is available; numpy bincounts
    otherwise."""
    from .. import native
    row_ptr = np.asarray(row_ptr)
    col_idx = np.asarray(col_idx)
    n_sec = max(1, -(-src_rows // section_rows))
    if native.available():
        return native.sectioned_counts(row_ptr, col_idx, num_rows,
                                       section_rows, n_sec, sub_w)
    dst_all = np.repeat(np.arange(num_rows, dtype=np.int64),
                        np.diff(row_ptr))
    sec_of = col_idx.astype(np.int64) // section_rows
    out = np.zeros(n_sec, dtype=np.int64)
    for s in range(n_sec):
        cnt = np.bincount(dst_all[sec_of == s], minlength=num_rows)
        out[s] = int((-(-cnt // sub_w)).sum())
    return out


def _resolve_chunks(counts, seg_rows: int, chunks_plan,
                    first_section: int = 0) -> list:
    """Per-section chunk counts from sub-row totals, honoring (and
    validating against) an SPMD plan — the ONE place this logic lives
    (native and numpy builders both call it)."""
    out = []
    for i, c in enumerate(counts):
        s = first_section + i
        n = max(1, -(-int(c) // seg_rows))
        if chunks_plan is not None:
            if n > chunks_plan[s]:
                raise ValueError(
                    f"section {s}: needs {n} chunks > planned "
                    f"{chunks_plan[s]} — the plan must come from "
                    f"section_sub_counts over the same edges")
            n = int(chunks_plan[s])
        out.append(n)
    return out


def sectioned_from_graph(row_ptr: np.ndarray, col_idx: np.ndarray,
                         num_rows: int, src_rows: int = None,
                         section_rows: int = SECTION_ROWS_DEFAULT,
                         seg_rows: int = 131_072,
                         chunks_plan=None, counts=None,
                         sub_w: int = 8) -> SectionedEll:
    """Build the sectioned layout from a dst-major CSR.

    ``src_rows`` is the source-id space (defaults to ``num_rows``;
    the distributed gathered space when they differ).  ``section_rows``
    defaults to 64 MiB worth of fp32 rows at F=256 — pass less for
    wider feature matrices.  ``chunks_plan`` (per-section chunk counts,
    from :func:`section_sub_counts` maxed across partitions) forces
    uniform shapes for SPMD stacking; a section needing more chunks
    than its plan raises.  ``sub_w`` is the sub-row width (neighbors
    gathered per table row; each (row, section) pair pads to a
    multiple of it).  Host-side prep uses the native two-pass builder
    (native/rocio.cc roc_sectioned_counts/_fill: 1.1 s at Reddit
    scale, byte-identical tables — 45x the numpy fallback's ~49 s)
    when librocio is available.
    """
    row_ptr = np.asarray(row_ptr)
    col_idx = np.asarray(col_idx)
    if src_rows is None:
        src_rows = num_rows
    n_sec = max(1, -(-src_rows // section_rows))
    all_sizes = [min(section_rows, src_rows - s * section_rows)
                 for s in range(n_sec)]
    from .. import native
    if native.available():
        # native two-pass fill (counts -> plan -> fill): 45x the numpy
        # path at Reddit scale and byte-identical tables (tested).
        # counts= lets plan-building callers (sectioned_from_padded_
        # parts, shard_dataset_local) skip the second CSR walk.
        if counts is None:
            counts = native.sectioned_counts(row_ptr, col_idx, num_rows,
                                             section_rows, n_sec, sub_w)
        chunks = _resolve_chunks(counts, seg_rows, chunks_plan)
        slots = np.asarray([n * seg_rows for n in chunks],
                           dtype=np.int64)
        idx_flat, sub_flat = native.sectioned_fill(
            row_ptr, col_idx, num_rows, section_rows,
            np.asarray(all_sizes, dtype=np.int64), slots, sub_w)
        idxs, dsts, off = [], [], 0
        for s in range(n_sec):
            n = int(slots[s])
            idxs.append(idx_flat[off:off + n].reshape(
                chunks[s], seg_rows, sub_w))
            dsts.append(sub_flat[off:off + n].reshape(
                chunks[s], seg_rows))
            off += n
        return SectionedEll(
            num_rows=num_rows, src_rows=src_rows,
            section_rows=section_rows, seg_rows=seg_rows,
            sec_starts=tuple(s * section_rows for s in range(n_sec)),
            sec_sizes=tuple(all_sizes),
            idx=tuple(idxs), sub_dst=tuple(dsts), sub_w=sub_w)
    dst_all = np.repeat(np.arange(num_rows, dtype=np.int64),
                        np.diff(row_ptr))
    src_all = col_idx.astype(np.int64)
    sec_of = (src_all // section_rows).astype(np.int8 if n_sec < 128
                                              else np.int32)
    starts, sizes, idxs, dsts = [], [], [], []
    for s in range(n_sec):
        sel = sec_of == s
        srcs = (src_all[sel] - s * section_rows).astype(np.int32)
        dst = dst_all[sel]
        cnt = np.bincount(dst, minlength=num_rows)
        padded = -(-cnt // sub_w) * sub_w
        nz = np.flatnonzero(padded)
        sub_rows = padded[nz] // sub_w
        total_sub = int(sub_rows.sum())
        sec_size = all_sizes[s]
        n_chunks = _resolve_chunks(
            [total_sub], seg_rows, chunks_plan, first_section=s)[0]
        pad = n_chunks * seg_rows - total_sub
        tbl = np.full((n_chunks * seg_rows, sub_w), sec_size,
                      dtype=np.int32)
        start_sub = np.zeros(len(nz) + 1, dtype=np.int64)
        np.cumsum(sub_rows, out=start_sub[1:])
        grp_start = np.zeros(num_rows + 1, dtype=np.int64)
        np.cumsum(cnt, out=grp_start[1:])
        off = np.arange(dst.shape[0], dtype=np.int64) - grp_start[dst]
        act_of = np.zeros(num_rows, dtype=np.int64)
        act_of[nz] = np.arange(len(nz))
        tbl.reshape(-1)[start_sub[act_of[dst]] * sub_w + off] = srcs
        sub_dst = np.concatenate(
            [np.repeat(nz, sub_rows),
             np.full(pad, num_rows, np.int64)]).astype(np.int32)
        starts.append(s * section_rows)
        sizes.append(sec_size)
        idxs.append(tbl.reshape(n_chunks, seg_rows, sub_w))
        dsts.append(sub_dst.reshape(n_chunks, seg_rows))
    return SectionedEll(
        num_rows=num_rows, src_rows=src_rows,
        section_rows=section_rows, seg_rows=seg_rows,
        sec_starts=tuple(starts), sec_sizes=tuple(sizes),
        idx=tuple(idxs), sub_dst=tuple(dsts), sub_w=sub_w)


def sectioned_plan(counts_max: np.ndarray,
                   seg_rows: int = 131_072) -> Tuple[int, list]:
    """(seg_rows, per-section chunk counts) from elementwise-maxed
    per-partition sub-row counts — THE single place the uniform-shape
    agreement math lives (used by the all-parts builder and the
    multi-host partition-local path; a divergence between the two
    would only surface as a chunks_plan error at scale)."""
    max_sub = int(np.max(counts_max)) if np.size(counts_max) else 1
    seg = max(8, min(seg_rows, -(-max_sub // 8) * 8))
    plan = [max(1, -(-int(c) // seg)) for c in np.asarray(counts_max)]
    return seg, plan


def clean_part_ptr(part_row_ptr: np.ndarray, real_nodes: int,
                   part_nodes: int) -> np.ndarray:
    """One partition's row pointers with padding edges dropped: rows
    past ``real_nodes`` become empty instead of carrying the padded
    edge tail."""
    n = int(real_nodes)
    ptr = part_row_ptr[:n + 1].astype(np.int64)
    return np.concatenate(
        [ptr, np.full(part_nodes - n, ptr[n], dtype=np.int64)])


def sectioned_from_padded_parts(part_row_ptr: np.ndarray,
                                part_col: np.ndarray,
                                real_nodes: np.ndarray,
                                part_nodes: int, src_rows: int,
                                section_rows: int = SECTION_ROWS_DEFAULT,
                                seg_rows: int = 131_072,
                                sub_w: int = 8) -> SectionedEll:
    """Uniform stacked per-part sectioned tables for the SPMD step:
    ``idx[s]`` is ``[P, n_chunks_s, seg_rows, sub_w]`` and
    ``sub_dst[s]`` ``[P, n_chunks_s, seg_rows]`` — same static shapes
    on every device.
    ``seg_rows`` shrinks to fit small graphs; per-section chunk counts
    are the max over partitions (metadata pass + plan), so partitions
    with fewer edges carry padding chunks that gather the section's
    zero row into the dummy output row.

    ``part_col`` is ``[P, part_edges]`` in gathered-row coordinates;
    padding edges are excluded via the real row extents."""
    P = part_row_ptr.shape[0]
    ptrs = [clean_part_ptr(part_row_ptr[p], real_nodes[p], part_nodes)
            for p in range(P)]
    cols = [np.asarray(part_col[p][:int(ptrs[p][-1])])
            for p in range(P)]
    counts = np.stack([
        section_sub_counts(ptrs[p], cols[p], part_nodes, src_rows,
                           section_rows, sub_w) for p in range(P)])
    seg_rows, plan = sectioned_plan(counts.max(axis=0), seg_rows)
    per_part = [
        sectioned_from_graph(ptrs[p], cols[p], part_nodes,
                             src_rows=src_rows,
                             section_rows=section_rows,
                             seg_rows=seg_rows, chunks_plan=plan,
                             counts=counts[p], sub_w=sub_w)
        for p in range(P)]
    first = per_part[0]
    return SectionedEll(
        num_rows=part_nodes, src_rows=src_rows,
        section_rows=section_rows, seg_rows=seg_rows,
        sec_starts=first.sec_starts, sec_sizes=first.sec_sizes,
        idx=tuple(np.stack([pp.idx[s] for pp in per_part])
                  for s in range(len(first.idx))),
        sub_dst=tuple(np.stack([pp.sub_dst[s] for pp in per_part])
                      for s in range(len(first.sub_dst))),
        sub_w=sub_w)
