"""Partition-local dataset sources.

The reference never loads whole tensors on every node: each partition's
loader task reads only its ``[rowLeft, rowRight]`` slice of the graph,
features, labels and mask (``load_task.cu:41-51`` skips to rowLeft;
``load_task.cu:201-245`` does per-partition binary reads).  A
:class:`DataSource` is the same contract for this framework: row-sliced
accessors that a multi-host ``shard_dataset_local`` drives so a host
materializes only its own partitions' O(V/P + E/P) data.

Two implementations:

- :class:`ArraySource` — wraps an in-memory :class:`Dataset` (slices are
  views; the degenerate single-host case, and what tests use).
- :class:`FileSource` — reads the reference on-disk layout
  (``.lux``/``.feats.csv|.bin``/``.label``/``.mask``) with seek-based
  slice reads (``core/graph.py`` row-sliced loaders), never touching
  bytes outside the requested rows except the O(V) `.lux` offset
  section every host needs for partition bounds.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

import numpy as np

from . import graph as _graph
from .graph import (Dataset, load_features, load_labels,
                    load_lux_header, load_mask)


class DataSource:
    """Row-sliced access to one dataset.  All ranges are half-open."""

    num_nodes: int
    num_edges: int
    in_dim: int
    num_classes: int

    def row_ptr(self) -> np.ndarray:
        """Global int64 [V+1] CSR row pointers (O(V) — the one global
        structure every host reads, for partition bounds)."""
        raise NotImplementedError

    def col_slice(self, e0: int, e1: int) -> np.ndarray:
        """Global source ids of edges [e0, e1)."""
        raise NotImplementedError

    def features(self, lo: int, hi: int) -> np.ndarray:
        raise NotImplementedError

    def labels(self, lo: int, hi: int) -> np.ndarray:
        raise NotImplementedError

    def mask(self, lo: int, hi: int) -> np.ndarray:
        raise NotImplementedError


@dataclass
class ArraySource(DataSource):
    """In-memory dataset as a row-sliced source (slices are views)."""

    dataset: Dataset

    def __post_init__(self):
        self.num_nodes = self.dataset.graph.num_nodes
        self.num_edges = self.dataset.graph.num_edges
        self.in_dim = self.dataset.in_dim
        self.num_classes = self.dataset.num_classes

    def row_ptr(self) -> np.ndarray:
        return self.dataset.graph.row_ptr

    def col_slice(self, e0: int, e1: int) -> np.ndarray:
        return self.dataset.graph.col_idx[e0:e1]

    def features(self, lo: int, hi: int) -> np.ndarray:
        return self.dataset.features[lo:hi]

    def labels(self, lo: int, hi: int) -> np.ndarray:
        return self.dataset.labels[lo:hi]

    def mask(self, lo: int, hi: int) -> np.ndarray:
        return self.dataset.mask[lo:hi]


class FileSource(DataSource):
    """Reference-layout on-disk dataset with seek-based slice reads.

    ``prefix`` follows ``load_dataset``: ``<prefix>.add_self_edge.lux``
    (or ``<prefix>.lux``), ``.feats.csv``/``.feats.bin``, ``.label``,
    ``.mask``.  The `.lux` must already contain self edges for the
    partition-local path (offline preprocessing, like the reference
    assumes, ``gnn.cc:756``) — in-framework self-edge insertion would
    need the whole graph resident.
    """

    def __init__(self, prefix: str, in_dim: int, num_classes: int):
        self.prefix = prefix
        self.in_dim = in_dim
        self.num_classes = num_classes
        lux = prefix + ".add_self_edge.lux"
        self.lux_path = lux if os.path.exists(lux) else prefix + ".lux"
        self.num_nodes, self.num_edges = load_lux_header(self.lux_path)
        self._row_ptr: Optional[np.ndarray] = None

    def row_ptr(self) -> np.ndarray:
        if self._row_ptr is None:
            with open(self.lux_path, "rb") as f:
                # module-qualified so the loader spy tests can intercept
                ends = _graph._read_slice(f, 12, self.num_nodes, "<u8")
            rp = np.zeros(self.num_nodes + 1, dtype=np.int64)
            rp[1:] = ends.astype(np.int64)
            assert (np.diff(rp) >= 0).all() and rp[-1] == self.num_edges
            self._row_ptr = rp
        return self._row_ptr

    def col_slice(self, e0: int, e1: int) -> np.ndarray:
        base = 12 + self.num_nodes * 8
        with open(self.lux_path, "rb") as f:
            col = _graph._read_slice(f, base + e0 * 4, e1 - e0, "<u4")
        return col.astype(np.int32)

    def features(self, lo: int, hi: int) -> np.ndarray:
        return load_features(self.prefix, self.num_nodes, self.in_dim,
                             rows=(lo, hi))

    def labels(self, lo: int, hi: int) -> np.ndarray:
        return load_labels(self.prefix, self.num_nodes, self.num_classes,
                           rows=(lo, hi))

    def mask(self, lo: int, hi: int) -> np.ndarray:
        return load_mask(self.prefix, self.num_nodes, rows=(lo, hi))


def as_source(data) -> DataSource:
    """Coerce a Dataset (or pass through a DataSource)."""
    if isinstance(data, DataSource):
        return data
    if isinstance(data, Dataset):
        return ArraySource(data)
    raise TypeError(f"not a Dataset or DataSource: {type(data)!r}")
