"""Vertex reordering for gather locality.

The sectioned aggregation's win comes from VMEM-resident source
sections (``core/ell.py SectionedEll``): every (row, section) pair an
edge crosses costs a padded width-8 sub-row, so the layout is cheapest
when each row's neighbors CLUSTER into few sections.  Real-world
graphs have strong community structure but often arbitrary vertex ids;
a locality-preserving relabeling concentrates each neighborhood into a
narrow id range.  This module provides that preprocessing pass:

- :func:`bfs_order` — breadth-first relabeling from a max-degree seed
  (the classic bandwidth-reduction family: neighbors get consecutive
  ids, communities become contiguous id blocks);
- :func:`lpa_order` — label-propagation community detection +
  cluster-major relabeling.  The ordering quality the block-dense MXU
  path (``ops/blockdense.py``) rides on: BFS recovers only ~5% of the
  oracle dense_frac on a shuffled planted-community graph, LPA
  recovers it EXACTLY (measured: oracle 0.813, shuffled 0.003,
  shuffled+bfs 0.045, shuffled+lpa 0.813 at V=65k/E=8M/communities
  4096) because communities become contiguous id blocks regardless of
  where BFS's frontier happens to wander;
- :func:`apply_vertex_order` — permute a whole Dataset (CSR, features,
  labels, masks) so training on the reordered graph is equivalent up
  to the vertex relabeling (logits come back in the NEW order; use the
  returned permutation to map back).

The reference has no analog (its loader keeps file order,
``load_task.cu:201-245``); this is a TPU-era optimization pass.  On
the synthetic *uniform-random* benchmark graphs reordering cannot help
(no structure to recover — measured neutral); the planted-community
test (``tests/test_reorder.py``) demonstrates the mechanism the pass
exists for: cross-section edges drop by >2x on a clustered graph with
shuffled ids.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from .graph import Dataset, Graph


def _undirected_csr(graph: Graph) -> Tuple[np.ndarray, np.ndarray]:
    """(nbr_ptr, nbr int32): symmetrized adjacency — in-edges (CSR
    rows) + out-edges (reverse), duplicates kept (they weight the LPA
    vote like the aggregation weights the sum).  int32 neighbors:
    vertex ids come from an int32 col_idx, and the Reddit-scale
    undirected table is ~230M entries — int64 would double its
    resident gigabyte for nothing."""
    V = graph.num_nodes
    deg_in = np.diff(graph.row_ptr)
    dst_all = np.repeat(np.arange(V, dtype=np.int32), deg_in)
    src_all = np.asarray(graph.col_idx, dtype=np.int32)
    u = np.concatenate([src_all, dst_all])
    v = np.concatenate([dst_all, src_all])
    order = np.argsort(u, kind="stable")
    v = v[order]
    nbr_ptr = np.zeros(V + 1, dtype=np.int64)
    np.cumsum(np.bincount(u, minlength=V), out=nbr_ptr[1:])
    return nbr_ptr, v


def bfs_order(graph: Graph) -> np.ndarray:
    """``perm[new_id] == old_id``: BFS relabeling over the undirected
    view of the CSR, seeded at the max-in-degree vertex of each
    component (processed in decreasing seed degree).  O(V + E)."""
    V = graph.num_nodes
    deg_in = np.diff(graph.row_ptr)
    nbr_ptr, v = _undirected_csr(graph)

    visited = np.zeros(V, dtype=bool)
    out = np.empty(V, dtype=np.int64)
    pos = 0
    for seed in np.argsort(-deg_in, kind="stable"):
        if visited[seed]:
            continue
        frontier = np.array([seed], dtype=np.int64)
        visited[seed] = True
        while frontier.size:
            out[pos:pos + frontier.size] = frontier
            pos += frontier.size
            # frontier's neighbor ids, fully vectorized: flatten the
            # [nbr_ptr[f], nbr_ptr[f+1]) ranges with repeat+cumsum
            # arithmetic (no per-vertex Python)
            starts = nbr_ptr[frontier]
            counts = nbr_ptr[frontier + 1] - starts
            total = int(counts.sum())
            if total == 0:
                break
            offs = np.arange(total, dtype=np.int64)
            row_start = np.repeat(np.cumsum(counts) - counts, counts)
            flat = np.repeat(starts, counts) + (offs - row_start)
            nxt = np.unique(v[flat])
            nxt = nxt[~visited[nxt]]
            visited[nxt] = True
            frontier = nxt
    assert pos == V
    return out


def lpa_labels(graph: Graph, max_iters: int = 16,
               tol_frac: float = 1e-3) -> np.ndarray:
    """int32 [V] community labels via ASYNCHRONOUS label propagation
    over the undirected view: each sweep walks vertices in increasing
    id order, assigning each the most frequent label among its
    neighbors AS ALREADY UPDATED this sweep (ties -> smallest label;
    isolated vertices keep theirs).  Asynchrony is what makes the
    pass terminate: fully-synchronous LPA 2-cycles on bipartite-like
    structures (a star flips center<->leaf labels every sweep, so a
    convergence test never fires and the result depends on sweep
    count), and no fixed vertex bipartition fixes that.  The async
    rule is cycle-free by a lexicographic potential — every change
    strictly raises the vertex's neighbor-agreement count or keeps it
    equal while strictly lowering the label.  Stops when a sweep
    changes fewer than ``tol_frac * V`` labels or after ``max_iters``
    sweeps.  O(E) per sweep on the native path (``roc_lpa_iterate``);
    the numpy fallback replays the identical vertex order (slow
    Python loop — correctness/CI path, the native library is the
    scale path), tested equal."""
    V = graph.num_nodes
    nbr_ptr, nbr = _undirected_csr(graph)
    labels = np.arange(V, dtype=np.int32)
    tol = max(1, int(tol_frac * V))

    from .. import native
    use_native = native.available()
    for _ in range(max_iters):
        if use_native:
            labels, changed = native.lpa_iterate(nbr_ptr, nbr, labels)
        else:
            labels, changed = _lpa_sweep_numpy(nbr_ptr, nbr, labels, V)
        if changed < tol:
            break
    return labels


def _lpa_sweep_numpy(nbr_ptr: np.ndarray, nbr: np.ndarray,
                     labels: np.ndarray, V: int
                     ) -> Tuple[np.ndarray, int]:
    """One asynchronous sweep, id order — the exact semantics of the
    native ``roc_lpa_iterate`` (tested equal).  Per-vertex Python
    loop: the fallback exists for environments without the native
    library, not for Reddit-scale graphs."""
    out = labels.copy()
    for v in range(V):
        lo, hi = nbr_ptr[v], nbr_ptr[v + 1]
        if hi <= lo:
            continue
        votes = out[nbr[lo:hi]]
        vals, cnt = np.unique(votes, return_counts=True)
        # smallest label among the maxima (np.unique sorts vals, so
        # argmax's first-hit rule lands on it)
        out[v] = vals[np.argmax(cnt)]
    return out, int((out != labels).sum())


def lpa_order(graph: Graph, max_iters: int = 16) -> np.ndarray:
    """``perm[new_id] == old_id``: cluster-major relabeling from
    label-propagation communities (original id order within each
    cluster).  The ordering pass that makes ``aggr_impl='bdense'``
    win on community graphs with arbitrary vertex ids — see the
    module docstring for the measured oracle-recovery numbers."""
    labels = lpa_labels(graph, max_iters=max_iters)
    return np.lexsort((np.arange(graph.num_nodes), labels))


# the CLI/benchmark dispatch — ONE place to register an ordering pass
ORDERINGS = {"bfs": bfs_order, "lpa": lpa_order}


def single_key_fits_int64(num_nodes: int) -> bool:
    """True when the ``new_dst * V + new_src`` edge-relabel key stays
    inside int64 — the guard :func:`apply_graph_order` consults before
    taking the single-key fast path (max key value is
    ``(V-1) * V + (V-1) == V^2 - 1``)."""
    v = int(num_nodes)
    return v == 0 or v <= (np.iinfo(np.int64).max // v)


def apply_graph_order(graph: Graph, perm: np.ndarray) -> Graph:
    """CSR with vertices relabeled so ``new_id = rank(old_id)``
    (``perm[new_id] == old_id``); per-row neighbor lists re-sorted
    ascending, preserving the loaders' monotone-CSR convention."""
    V = graph.num_nodes
    perm = np.asarray(perm, dtype=np.int64)
    assert perm.shape == (V,)
    rank = np.empty(V, dtype=np.int64)
    rank[perm] = np.arange(V, dtype=np.int64)
    deg = np.diff(graph.row_ptr)
    new_deg = deg[perm]
    new_row_ptr = np.zeros(V + 1, dtype=np.int64)
    np.cumsum(new_deg, out=new_row_ptr[1:])
    old_dst = np.repeat(np.arange(V, dtype=np.int64), deg)
    if not single_key_fits_int64(V):
        # V^2 past int64 (V > ~3.03e9): the single-key relabel would
        # overflow SILENTLY and corrupt the CSR (round-5 advisor: the
        # limit used to live only in a comment).  No fallback exists
        # that could help — Graph stores int32 columns, which caps
        # representable graphs at V < 2^31 (where V^2 < 2^62 always
        # fits), so reaching this branch means the input was already
        # outside the container's domain: fail LOUDLY.
        raise ValueError(
            f"apply_graph_order: V={V:,} exceeds the single-key int64 "
            f"relabel range (V^2 overflows) — and the int32 col_idx "
            f"Graph layout itself, which caps V below 2^31; relabel "
            f"such graphs with an int64 edge pipeline before loading")
    # vectorized edge relabel: one SINGLE-KEY sort of
    # new_dst * V + new_src (fits int64 up to V ~ 3e9 edges^1/2; the
    # row id recovers by div, the column by mod) — measured ~4x
    # faster than the equivalent two-pass lexsort at Reddit scale,
    # and the sorted VALUES are the answer directly (no 115M-element
    # argsort gather)
    key = rank[old_dst] * V + rank[graph.col_idx.astype(np.int64)]
    key.sort()   # value sort: stability is unobservable in the output
    new_col = (key % V).astype(np.int32)
    return Graph(row_ptr=new_row_ptr, col_idx=new_col)


def apply_vertex_order(dataset: Dataset,
                       perm: np.ndarray,
                       order_name: str
                       ) -> Tuple[Dataset, np.ndarray]:
    """Dataset with vertices relabeled so ``new_id = rank(old_id)``.

    perm: ``perm[new_id] == old_id`` (from :func:`bfs_order` /
    :func:`lpa_order`); ``order_name`` is the provenance suffix
    appended to the dataset name (the config echo and any artifact
    keyed on it record which ordering produced the ids).
    Returns ``(reordered_dataset, perm)``; row ``perm[i]`` of the
    original corresponds to row ``i`` of the result, so original-order
    logits are ``new_logits[inv]`` with ``inv = argsort(perm)``...
    i.e. ``orig_logits = new_logits[rank]`` where ``rank[old] = new``.
    """
    new_graph = apply_graph_order(dataset.graph, perm)
    return Dataset(
        graph=new_graph,
        features=np.ascontiguousarray(dataset.features[perm]),
        labels=np.ascontiguousarray(dataset.labels[perm]),
        mask=np.ascontiguousarray(dataset.mask[perm]),
        num_classes=dataset.num_classes,
        name=dataset.name + "+" + order_name), perm


def cross_section_pairs(graph: Graph, section_rows: int) -> int:
    """Number of distinct (destination row, source section) pairs — the
    sectioned layout's padding driver (each pair costs >= one width-8
    sub-row).  The quantity :func:`bfs_order` exists to reduce."""
    V = graph.num_nodes
    if graph.col_idx.size == 0:
        return 0
    dst = np.repeat(np.arange(V, dtype=np.int64), np.diff(graph.row_ptr))
    sec = graph.col_idx.astype(np.int64) // section_rows
    return int(np.unique(dst * (sec.max() + 1) + sec).shape[0])
