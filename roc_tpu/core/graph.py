"""Graph data layer: CSR graph container, .lux binary reader, feature /
label / mask loaders, and synthetic fixtures.

TPU-native re-design of the reference data layer:

- Reference ``Graph`` (``gnn.h:120-130``) holds Legion regions for row
  pointers (inclusive-end offsets, one per vertex) and column indices.  We
  hold plain numpy arrays host-side with the standard exclusive-start
  ``row_ptr`` of length ``V+1`` (``row_ptr[0] == 0``), converting on load.
- Reference `.lux` format (``gnn.cc:756-801``, ``load_task.cu:229-243``):
  ``u32 numNodes``, ``u64 numEdges``, then ``numNodes`` u64 *inclusive end*
  row offsets, then ``numEdges`` u32 source-vertex ids, rows sorted by
  destination.  Self-edges are pre-added in the file (the driver appends
  ``.add_self_edge.lux`` to the path, ``gnn.cc:756``); we expose
  :func:`add_self_edges` to perform the same conversion in-framework.
- Feature CSV loader with ``.feats.bin`` binary caching mirrors
  ``load_task.cu:41-73``; labels are class indices (one integer per line,
  ``load_task.cu:118-123`` one-hots them — we keep int labels and one-hot
  lazily on device); masks are the strings Train/Val/Test/None
  (``load_task.cu:169-183``).

Row-major node-feature layout ``[num_nodes, dim]`` (the reference uses
``[dim, num_nodes]`` column-major Legion rects — row-major is the
TPU-friendly choice: feature dim lands on the 128-wide lane axis).
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass
from typing import Optional

import numpy as np

# Mask values mirror the reference enum MaskType (gnn.h:98-103).
MASK_NONE = 0
MASK_TRAIN = 1
MASK_VAL = 2
MASK_TEST = 3

_MASK_NAMES = {"Train": MASK_TRAIN, "Val": MASK_VAL, "Test": MASK_TEST,
               "None": MASK_NONE}


@dataclass
class Graph:
    """An in-memory CSR graph, destination-major.

    ``row_ptr`` has length ``num_nodes + 1`` with ``row_ptr[0] == 0``;
    edges for destination vertex ``v`` occupy ``col_idx[row_ptr[v]:row_ptr[v+1]]``
    and store *source* vertex ids.  Aggregation computes
    ``out[v] = sum(in[col_idx[row_ptr[v]:row_ptr[v+1]]])`` exactly like the
    reference hot loop (``scattergather_kernel.cu:20-76``).
    """

    row_ptr: np.ndarray  # int64 [V+1]
    col_idx: np.ndarray  # int32 [E]

    def __post_init__(self):
        self.row_ptr = np.asarray(self.row_ptr, dtype=np.int64)
        self.col_idx = np.asarray(self.col_idx, dtype=np.int32)
        assert self.row_ptr.ndim == 1 and self.col_idx.ndim == 1
        assert self.row_ptr[0] == 0
        assert self.row_ptr[-1] == self.col_idx.shape[0]

    @property
    def num_nodes(self) -> int:
        return int(self.row_ptr.shape[0] - 1)

    @property
    def num_edges(self) -> int:
        return int(self.col_idx.shape[0])

    @property
    def in_degree(self) -> np.ndarray:
        """Per-destination edge counts (int32), the reference's indegree
        (``graphnorm_kernel.cu:45-55`` computes it from CSR row pointers)."""
        return np.diff(self.row_ptr).astype(np.int32)

    def edge_dst(self) -> np.ndarray:
        """Expand row_ptr to a per-edge destination id array (int32 [E])."""
        return np.repeat(
            np.arange(self.num_nodes, dtype=np.int32), self.in_degree
        )

    def has_all_self_edges(self) -> bool:
        deg = self.in_degree
        if (deg == 0).any():
            return False
        dst = self.edge_dst()
        # binary check: does each row contain its own id?
        out = np.zeros(self.num_nodes, dtype=bool)
        out[dst[self.col_idx == dst]] = True
        return bool(out.all())

    def is_symmetric(self) -> bool:
        """True iff the adjacency matrix equals its transpose.  The
        reference backward pass reuses the forward CSR
        (``scattergather_kernel.cu:160-170``) which is only correct for
        symmetric graphs; callers can verify with this."""
        return check_symmetric(self)

    def transpose(self) -> "Graph":
        """CSC <-> CSR flip: returns the graph with edge directions
        reversed (sorted by the old source)."""
        dst = self.edge_dst()
        src = self.col_idx
        order = np.argsort(src, kind="stable")
        new_dst = src[order]
        new_col = dst[order]
        counts = np.bincount(new_dst, minlength=self.num_nodes)
        row_ptr = np.zeros(self.num_nodes + 1, dtype=np.int64)
        np.cumsum(counts, out=row_ptr[1:])
        return Graph(row_ptr=row_ptr, col_idx=new_col.astype(np.int32))


def check_symmetric(graph: Graph) -> bool:
    """Exact symmetry check via sorted edge-list comparison."""
    dst = graph.edge_dst().astype(np.int64)
    src = graph.col_idx.astype(np.int64)
    fwd = dst * graph.num_nodes + src
    bwd = src * graph.num_nodes + dst
    return bool(np.array_equal(np.sort(fwd), np.sort(bwd)))


# ---------------------------------------------------------------------------
# .lux binary format
# ---------------------------------------------------------------------------

def _read_slice(f, offset: int, count: int, dtype: str) -> np.ndarray:
    """Seek + read a typed slice.  All partition-local binary reads go
    through here so tests can spy on exactly which byte ranges a host
    touches (the reference's per-partition loader contract,
    ``load_task.cu:41-51,201-245``)."""
    f.seek(offset)
    out = np.fromfile(f, dtype=dtype, count=count)
    if out.size != count:
        raise IOError(f"truncated read at {offset} (+{count}): "
                      f"got {out.size} items")
    return out


def load_lux_header(path: str) -> tuple:
    """(num_nodes, num_edges) from a `.lux` header without reading the
    body."""
    with open(path, "rb") as f:
        return struct.unpack("<IQ", f.read(12))


def load_lux_rows(path: str, row_lo: int, row_hi: int) -> tuple:
    """Partition-local `.lux` read: only rows ``[row_lo, row_hi)``.

    Reads the (row_hi - row_lo + 1)-entry offset slice and exactly the
    partition's column-index bytes — the reference loader's skip-to-
    rowLeft behavior (``load_task.cu:41-51,201-245``) — instead of the
    whole file.  Returns ``(local_row_ptr, col_idx)`` with
    ``local_row_ptr`` int64 [n+1] rebased to 0.
    """
    num_nodes, num_edges = load_lux_header(path)
    if not 0 <= row_lo <= row_hi <= num_nodes:
        raise ValueError(f"bad row range [{row_lo}, {row_hi}) for "
                         f"{num_nodes} nodes")
    n = row_hi - row_lo
    header = 12
    with open(path, "rb") as f:
        # offsets are u64 *inclusive ends*; row v's edges end at off[v]
        # and start at off[v-1] (0 for v == 0)
        lo_off = 0 if row_lo == 0 else int(_read_slice(
            f, header + (row_lo - 1) * 8, 1, "<u8")[0])
        if n == 0:
            return np.zeros(1, dtype=np.int64), np.zeros(0, dtype=np.int32)
        ends = _read_slice(f, header + row_lo * 8, n, "<u8").astype(
            np.int64)
        if not ((np.diff(ends) >= 0).all() and ends[0] >= lo_off):
            raise ValueError(f"{path}: non-monotone row offsets in "
                             f"rows [{row_lo}, {row_hi})")
        col_base = header + num_nodes * 8
        e0, e1 = lo_off, int(ends[-1])
        col = _read_slice(f, col_base + e0 * 4, e1 - e0, "<u4")
    local_ptr = np.zeros(n + 1, dtype=np.int64)
    local_ptr[1:] = ends - lo_off
    return local_ptr, col.astype(np.int32)


def load_lux(path: str) -> Graph:
    """Read a `.lux` binary graph (reference format, ``gnn.cc:756-801``):
    u32 num_nodes, u64 num_edges, num_nodes x u64 inclusive-end row
    offsets, num_edges x u32 source ids.

    Uses the native C++ reader (native/rocio.cc) when built; numpy
    fallback otherwise."""
    from .. import native
    if native.available():
        row_ptr, col_idx = native.load_lux(path)
        return Graph(row_ptr=row_ptr, col_idx=col_idx)
    with open(path, "rb") as f:
        header = f.read(12)
        num_nodes, num_edges = struct.unpack("<IQ", header)
        raw_rows = np.fromfile(f, dtype="<u8", count=num_nodes)
        col_idx = np.fromfile(f, dtype="<u4", count=num_edges)
    if raw_rows.shape[0] != num_nodes:
        raise IOError(f"{path}: truncated .lux row offsets")
    if col_idx.shape[0] != num_edges:
        raise IOError(f"{path}: truncated .lux col indices")
    # Monotonicity checks mirror gnn.cc:798-800 (ValueError, not assert:
    # data validation must survive python -O).
    if not (np.diff(raw_rows.astype(np.int64)) >= 0).all():
        raise ValueError(f"{path}: non-monotone row offsets")
    if raw_rows[-1] != num_edges:
        raise ValueError(f"{path}: row offsets end at {raw_rows[-1]}, "
                         f"expected {num_edges}")
    row_ptr = np.zeros(num_nodes + 1, dtype=np.int64)
    row_ptr[1:] = raw_rows.astype(np.int64)
    return Graph(row_ptr=row_ptr, col_idx=col_idx.astype(np.int32))


def save_lux(graph: Graph, path: str) -> None:
    """Write the reference `.lux` binary format (inverse of load_lux)."""
    with open(path, "wb") as f:
        f.write(struct.pack("<IQ", graph.num_nodes, graph.num_edges))
        graph.row_ptr[1:].astype("<u8").tofile(f)
        graph.col_idx.astype("<u4").tofile(f)


def add_self_edges(graph: Graph) -> Graph:
    """Ensure every vertex has a self edge (the `.add_self_edge.lux`
    preprocessing the reference assumes was done offline, ``gnn.cc:756``).
    Existing self edges are kept; missing ones are inserted."""
    from .. import native
    if native.available():
        row_ptr, col_idx = native.add_self_edges(graph.row_ptr,
                                                 graph.col_idx)
        return Graph(row_ptr=row_ptr, col_idx=col_idx)
    V = graph.num_nodes
    dst = graph.edge_dst()
    has_self = np.zeros(V, dtype=bool)
    self_rows = dst[graph.col_idx == dst]
    has_self[self_rows] = True
    missing = np.flatnonzero(~has_self).astype(np.int32)
    if missing.size == 0:
        return graph
    dst_all = np.concatenate([dst, missing])
    col_all = np.concatenate([graph.col_idx, missing])
    order = np.argsort(dst_all, kind="stable")
    dst_all = dst_all[order]
    col_all = col_all[order]
    counts = np.bincount(dst_all, minlength=V)
    row_ptr = np.zeros(V + 1, dtype=np.int64)
    np.cumsum(counts, out=row_ptr[1:])
    return Graph(row_ptr=row_ptr, col_idx=col_all.astype(np.int32))


def from_edge_list(src: np.ndarray, dst: np.ndarray, num_nodes: int,
                   symmetrize: bool = False) -> Graph:
    """Build a dst-major CSR graph from a COO edge list."""
    src = np.asarray(src, dtype=np.int64)
    dst = np.asarray(dst, dtype=np.int64)
    if symmetrize:
        src, dst = np.concatenate([src, dst]), np.concatenate([dst, src])
        # dedupe
        key = dst * num_nodes + src
        key = np.unique(key)
        dst, src = key // num_nodes, key % num_nodes
    order = np.argsort(dst, kind="stable")
    src, dst = src[order], dst[order]
    counts = np.bincount(dst, minlength=num_nodes)
    row_ptr = np.zeros(num_nodes + 1, dtype=np.int64)
    np.cumsum(counts, out=row_ptr[1:])
    return Graph(row_ptr=row_ptr, col_idx=src.astype(np.int32))


# ---------------------------------------------------------------------------
# Feature / label / mask loaders (reference load_task.cu:25-199)
# ---------------------------------------------------------------------------

def load_features(prefix: str, num_nodes: int, in_dim: int,
                  rows: Optional[tuple] = None) -> np.ndarray:
    """Load ``<prefix>.feats.csv`` (one comma-separated row per vertex),
    caching a ``.feats.bin`` float32 binary alongside exactly like
    ``load_task.cu:41-73``.  Returns float32 ``[num_nodes, in_dim]``.

    ``rows=(lo, hi)`` reads only that half-open row range — from the
    ``.bin`` cache it is an exact byte-range read (the reference's
    per-partition skip-to-rowLeft, ``load_task.cu:41-51``); from the CSV
    the native parser line-skips to ``lo``, and the numpy fallback
    parses only the needed lines."""
    from .. import native
    bin_path = prefix + ".feats.bin"
    csv_path = prefix + ".feats.csv"
    if rows is not None:
        lo, hi = rows
        if not 0 <= lo <= hi <= num_nodes:
            raise ValueError(f"bad row range [{lo}, {hi}) for "
                             f"{num_nodes} nodes")
        if os.path.exists(bin_path):
            with open(bin_path, "rb") as f:
                data = _read_slice(f, lo * in_dim * 4, (hi - lo) * in_dim,
                                   np.float32)
            return data.reshape(hi - lo, in_dim)
        if native.available():
            return native.load_features_csv_rows(csv_path, lo, hi, in_dim)
        data = np.loadtxt(_iter_lines(csv_path, lo, hi), delimiter=",",
                          dtype=np.float32, ndmin=2)
        if data.shape != (hi - lo, in_dim):
            raise ValueError(f"{csv_path}: rows [{lo}, {hi}) parsed to "
                             f"{data.shape}, expected {(hi - lo, in_dim)}")
        return data
    if os.path.exists(bin_path):
        data = np.fromfile(bin_path, dtype=np.float32,
                           count=num_nodes * in_dim)
        if data.size != num_nodes * in_dim:
            raise IOError(f"{bin_path}: truncated .feats.bin "
                          f"({data.size} of {num_nodes * in_dim} floats)")
        return data.reshape(num_nodes, in_dim)
    if native.available():
        data = native.load_features_csv(csv_path, num_nodes, in_dim)
    else:
        data = np.loadtxt(csv_path, delimiter=",", dtype=np.float32)
        data = data.reshape(num_nodes, in_dim)
    data.tofile(bin_path)
    return data


def _iter_lines(path: str, lo: int, hi: int):
    """Yield lines [lo, hi) of a text file (the numpy-fallback line
    skip for partition-local CSV/label/mask reads)."""
    import itertools
    with open(path) as f:
        yield from itertools.islice(f, lo, hi)


def load_labels(prefix: str, num_nodes: int, num_classes: int,
                rows: Optional[tuple] = None) -> np.ndarray:
    """Load ``<prefix>.label`` (one class index per line,
    ``load_task.cu:118-123``).  Returns int32 ``[num_nodes]`` (or the
    ``rows=(lo, hi)`` slice); one-hot is formed on device by the loss."""
    if rows is not None:
        lo, hi = rows
        labels = np.loadtxt(_iter_lines(prefix + ".label", lo, hi),
                            dtype=np.int64, ndmin=1)
        n = hi - lo
    else:
        labels = np.loadtxt(prefix + ".label", dtype=np.int64,
                            ndmin=1)[:num_nodes]
        n = num_nodes
    if labels.shape[0] != n:
        raise ValueError(f"{prefix}.label: got {labels.shape[0]} rows, "
                         f"expected {n}")
    if not ((labels >= 0) & (labels < num_classes)).all():
        raise ValueError(f"{prefix}.label: class index outside "
                         f"[0, {num_classes})")
    return labels.astype(np.int32)


def load_mask(prefix: str, num_nodes: int,
              rows: Optional[tuple] = None) -> np.ndarray:
    """Load ``<prefix>.mask`` ("Train"/"Val"/"Test"/"None" per line,
    ``load_task.cu:169-183``).  Returns int32 ``[num_nodes]`` (or the
    ``rows=(lo, hi)`` slice) with MASK_* values."""
    from .. import native
    if rows is None and native.available():
        return native.load_mask(prefix + ".mask", num_nodes)
    lo, hi = rows if rows is not None else (0, num_nodes)
    out = np.empty(hi - lo, dtype=np.int32)
    if hi == lo:
        return out
    count = 0
    for i, line in enumerate(_iter_lines(prefix + ".mask", lo, hi)):
        line = line.strip()
        if line not in _MASK_NAMES:
            raise ValueError(f"Unrecognized mask: {line!r}")
        out[i] = _MASK_NAMES[line]
        count = i + 1
    if count != hi - lo:
        raise ValueError(
            f"truncated .mask: wanted rows [{lo}, {hi}), got {count}")
    return out


@dataclass
class Dataset:
    """A fully-loaded full-graph node-classification problem."""

    graph: Graph
    features: np.ndarray  # float32 [V, in_dim]
    labels: np.ndarray    # int32 [V]
    mask: np.ndarray      # int32 [V] of MASK_* values
    num_classes: int
    name: str = "dataset"

    @property
    def in_dim(self) -> int:
        return int(self.features.shape[1])


def save_dataset(ds: "Dataset", prefix: str, csv: bool = True,
                 feats_bin: bool = True) -> None:
    """Write a dataset in the reference on-disk layout (the format
    ``load_task.cu:25-199`` consumes): ``<prefix>.add_self_edge.lux``,
    ``.feats.csv`` and/or ``.feats.bin``, ``.label``, ``.mask``.  The
    graph is written as-is — callers ensure self edges are present
    (``add_self_edges``) to honor the filename's contract."""
    save_lux(ds.graph, prefix + ".add_self_edge.lux")
    if csv:
        np.savetxt(prefix + ".feats.csv", ds.features, delimiter=",",
                   fmt="%.7g")
    if feats_bin:
        ds.features.astype(np.float32).tofile(prefix + ".feats.bin")
    np.savetxt(prefix + ".label", ds.labels, fmt="%d")
    names = {v: k for k, v in _MASK_NAMES.items()}
    with open(prefix + ".mask", "w") as f:
        for m in ds.mask:
            f.write(names[int(m)] + "\n")


def load_dataset(prefix: str, in_dim: int, num_classes: int,
                 name: Optional[str] = None) -> Dataset:
    """Load a reference-layout dataset directory: ``<prefix>.add_self_edge.lux``
    (falling back to ``<prefix>.lux`` + in-framework self-edge insertion),
    ``.feats.csv``/``.feats.bin``, ``.label``, ``.mask``."""
    lux = prefix + ".add_self_edge.lux"
    if os.path.exists(lux):
        graph = load_lux(lux)
    else:
        graph = add_self_edges(load_lux(prefix + ".lux"))
    feats = load_features(prefix, graph.num_nodes, in_dim)
    labels = load_labels(prefix, graph.num_nodes, num_classes)
    mask = load_mask(prefix, graph.num_nodes)
    return Dataset(graph=graph, features=feats, labels=labels, mask=mask,
                   num_classes=num_classes,
                   name=name or os.path.basename(prefix))


# ---------------------------------------------------------------------------
# Synthetic fixtures (the reference ships none; needed for tests + bench)
# ---------------------------------------------------------------------------

def random_csr(num_nodes: int, num_edges: int, seed: int = 0,
               power_law: bool = True) -> Graph:
    """Fast benchmark-scale CSR generator: draws a degree sequence
    (lognormal when ``power_law``, else near-uniform) summing to
    ``num_edges`` with every degree >= 1 (self-edge convention), and
    uniform random sources.  Not symmetric — use for timing, not for
    gradient-parity tests."""
    assert num_edges >= num_nodes, "need >= 1 edge per node (self edges)"
    rng = np.random.RandomState(seed)
    if power_law:
        deg = _lognormal_degree_sequence(num_nodes, num_edges, rng)
    else:
        raw = np.ones(num_nodes) + rng.rand(num_nodes) * 0.1
        deg = _degree_sequence(raw, num_edges, rng)
    row_ptr = np.zeros(num_nodes + 1, dtype=np.int64)
    np.cumsum(deg, out=row_ptr[1:])
    col_idx = rng.randint(0, num_nodes, size=num_edges, dtype=np.int64)
    return Graph(row_ptr=row_ptr, col_idx=col_idx.astype(np.int32))


def _degree_sequence(raw: np.ndarray, num_edges: int,
                     rng) -> np.ndarray:
    """Degree sequence proportional to ``raw`` summing to
    ``num_edges`` with every degree >= 1 (self-edge convention);
    rounding remainder distributed over random vertices."""
    num_nodes = raw.shape[0]
    extra = num_edges - num_nodes
    deg = 1 + np.floor(raw / raw.sum() * extra).astype(np.int64)
    short = num_edges - int(deg.sum())
    if short > 0:
        np.add.at(deg, rng.randint(0, num_nodes, size=short), 1)
    return deg


def _lognormal_degree_sequence(num_nodes: int, num_edges: int,
                               rng) -> np.ndarray:
    """In-degree sequence lognormal-skewed like real social graphs —
    shared by the benchmark-scale generators."""
    raw = rng.lognormal(mean=0.0, sigma=1.25, size=num_nodes)
    return _degree_sequence(raw, num_edges, rng)


def zipf_csr(num_nodes: int, num_edges: int, a: float = 1.0,
             seed: int = 0, shuffle: bool = True) -> Graph:
    """Benchmark-scale CSR with **Zipf in-degrees**: the vertex ranked
    k gets degree ∝ k^-a — a heavier hub tail than the lognormal
    draw, the stress case for edge-balanced partitioning (a handful
    of hubs can hold a whole partition cap's worth of edges).
    ``shuffle=True`` scatters the ranks over random vertex ids so the
    hubs are not id-contiguous.  Uniform random sources; not
    symmetric — timing/partitioning use only."""
    assert num_edges >= num_nodes, "need >= 1 edge per node"
    rng = np.random.RandomState(seed)
    raw = np.arange(1, num_nodes + 1, dtype=np.float64) ** (-a)
    if shuffle:
        rng.shuffle(raw)
    deg = _degree_sequence(raw, num_edges, rng)
    row_ptr = np.zeros(num_nodes + 1, dtype=np.int64)
    np.cumsum(deg, out=row_ptr[1:])
    col_idx = rng.randint(0, num_nodes, size=num_edges, dtype=np.int64)
    return Graph(row_ptr=row_ptr, col_idx=col_idx.astype(np.int32))


def planted_community_csr(num_nodes: int, num_edges: int,
                          community_rows: int = 65_536,
                          intra_frac: float = 0.8, seed: int = 0,
                          shuffle: bool = True,
                          src_skew: float = 0.0) -> Graph:
    """Benchmark-scale dst-major CSR with PLANTED community structure:
    each edge's source lands in its destination's community block with
    probability ``intra_frac``, uniformly elsewhere otherwise.  With
    ``shuffle=True`` vertex ids are randomly relabeled afterwards —
    the worst case for locality, which a reordering pass
    (core/reorder.py bfs_order) should be able to recover.
    ``src_skew`` > 0 additionally skews WHICH community member is
    picked (u**(1+src_skew) mapping), modelling hub sources.  Same
    lognormal in-degree sequence as :func:`random_csr`.  Not
    symmetric — timing use only."""
    assert num_edges >= num_nodes
    rng = np.random.RandomState(seed)
    deg = _lognormal_degree_sequence(num_nodes, num_edges, rng)
    row_ptr = np.zeros(num_nodes + 1, dtype=np.int64)
    np.cumsum(deg, out=row_ptr[1:])
    dst_all = np.repeat(np.arange(num_nodes, dtype=np.int64), deg)
    com_of = dst_all // community_rows
    com_lo = com_of * community_rows
    com_hi = np.minimum(com_lo + community_rows, num_nodes)
    u = rng.rand(num_edges)
    if src_skew > 0.0:
        u = u ** (1.0 + src_skew)
    local = com_lo + np.floor(u * (com_hi - com_lo)).astype(np.int64)
    anywhere = rng.randint(0, num_nodes, size=num_edges)
    intra = rng.rand(num_edges) < intra_frac
    col = np.where(intra, local, anywhere)
    if shuffle:
        relabel = rng.permutation(num_nodes).astype(np.int64)
        col = relabel[col]
        # destinations relabel too: re-sort edges by new dst
        new_dst = relabel[dst_all]
        order = np.argsort(new_dst, kind="stable")
        col = col[order]
        new_deg = np.bincount(new_dst, minlength=num_nodes)
        row_ptr = np.zeros(num_nodes + 1, dtype=np.int64)
        np.cumsum(new_deg, out=row_ptr[1:])
    del anywhere, local, u, com_of, com_lo, com_hi, dst_all
    return Graph(row_ptr=row_ptr, col_idx=col.astype(np.int32))


def synthetic_graph(num_nodes: int, avg_degree: int, seed: int = 0,
                    power_law: bool = False) -> Graph:
    """Random symmetric graph with self edges.  ``power_law=True`` skews
    degrees like real social graphs (Reddit-ish) to stress edge-balanced
    partitioning."""
    rng = np.random.RandomState(seed)
    n_rand = num_nodes * max(avg_degree - 1, 0) // 2
    if power_law and n_rand > 0:
        # preferential-attachment-flavored endpoints
        p = 1.0 / (np.arange(num_nodes) + 10.0)
        p /= p.sum()
        src = rng.choice(num_nodes, size=n_rand, p=p).astype(np.int64)
        dst = rng.randint(0, num_nodes, size=n_rand).astype(np.int64)
    else:
        src = rng.randint(0, num_nodes, size=n_rand).astype(np.int64)
        dst = rng.randint(0, num_nodes, size=n_rand).astype(np.int64)
    g = from_edge_list(src, dst, num_nodes, symmetrize=True)
    return add_self_edges(g)


def synthetic_dataset(num_nodes: int = 128, avg_degree: int = 8,
                      in_dim: int = 16, num_classes: int = 4,
                      seed: int = 0, homophily: float = 0.8,
                      name: str = "synthetic") -> Dataset:
    """Deterministic learnable fixture: a homophilous graph (edges mostly
    intra-class, like Cora/Reddit) with class-informative features
    (cluster means + noise), so a GCN converges quickly — the stand-in
    for the reference's convergence-as-test strategy (SURVEY §4)."""
    rng = np.random.RandomState(seed + 1)
    labels = rng.randint(0, num_classes, size=num_nodes).astype(np.int32)
    # homophilous edges: src random; dst same-class with prob
    # `homophily`.  Fully vectorized — same-class picks index into the
    # label-sorted id list via per-class offsets — so the generator
    # reaches benchmark scale (57M draws for Reddit-shaped E; the old
    # per-edge Python loop capped it at toy sizes).
    n_rand = num_nodes * max(avg_degree - 1, 0) // 2
    src = rng.randint(0, num_nodes, size=n_rand).astype(np.int64)
    order = np.argsort(labels, kind="stable")
    class_start = np.zeros(num_classes + 1, dtype=np.int64)
    np.cumsum(np.bincount(labels, minlength=num_classes),
              out=class_start[1:])
    src_lab = labels[src]
    sizes = np.maximum(class_start[src_lab + 1] - class_start[src_lab],
                       1)
    pick = class_start[src_lab] + np.minimum(
        np.floor(rng.rand(n_rand) * sizes).astype(np.int64), sizes - 1)
    same = rng.rand(n_rand) < homophily
    dst = np.where(same, order[pick],
                   rng.randint(0, num_nodes, size=n_rand))
    graph = add_self_edges(from_edge_list(src, dst, num_nodes,
                                          symmetrize=True))
    means = rng.randn(num_classes, in_dim).astype(np.float32) * 2.0
    feats = means[labels] + rng.randn(num_nodes, in_dim).astype(np.float32)
    mask = np.full(num_nodes, MASK_NONE, dtype=np.int32)
    split = rng.rand(num_nodes)
    mask[split < 0.5] = MASK_TRAIN
    mask[(split >= 0.5) & (split < 0.75)] = MASK_VAL
    mask[split >= 0.75] = MASK_TEST
    return Dataset(graph=graph, features=feats.astype(np.float32),
                   labels=labels, mask=mask, num_classes=num_classes,
                   name=name)
