"""Edge-balanced contiguous vertex-range graph partitioner.

Re-implements the reference's greedy sweep (``gnn.cc:806-829``): walk
vertices in order accumulating in-edge counts; whenever the running count
exceeds ``cap = ceil(E / num_parts)`` close the current range at this
vertex (inclusive) and reset the counter.  The reference then *asserts*
that exactly ``num_parts`` ranges were produced (``gnn.cc:829``) — which
can fail on skewed graphs.  We keep the same greedy semantics but make the
result total: if the sweep closes fewer than ``num_parts`` ranges, the
tail ranges are empty; it can never produce more because the cap
guarantees at least one vertex per closed range.

On top of the ranges we add what the TPU SPMD layer needs and Legion
provided implicitly (``gnn_mapper.cc`` + region partitions): *padded,
equal-sized* shards so every device holds identical static shapes.
Node counts pad to ``max_part_nodes`` rounded up to ``node_multiple``
(sublane-friendly), edge counts to ``max_part_edges`` rounded to
``edge_multiple``.  Padding edges point at a dummy source (node index
``V``, whose feature row is zero) and a dummy destination (the last padded
row), so they aggregate zeros and touch no real output row.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from .graph import Graph


def edge_balanced_bounds(row_ptr: np.ndarray, num_parts: int
                         ) -> List[Tuple[int, int]]:
    """Greedy edge-balanced split into ``num_parts`` contiguous inclusive
    vertex ranges ``[left, right]`` (reference ``gnn.cc:806-829``).
    Ranges may be empty (``left > right``) only in the padded tail.

    The Python fallback is vectorized: the greedy sweep closes a range
    at the first vertex whose running edge count exceeds the cap, i.e.
    at ``searchsorted(row_ptr, row_ptr[left] + cap, 'right') - 1`` —
    O(P log V) instead of the former O(V) degree loop, bit-identical
    to the native sweep (tests/test_native.py test_bounds_parity)."""
    from .. import native
    row_ptr = np.asarray(row_ptr, dtype=np.int64)
    num_nodes = row_ptr.shape[0] - 1
    if native.available():
        return [tuple(b) for b in
                native.edge_balanced_bounds(row_ptr, num_parts)]
    num_edges = int(row_ptr[-1])
    cap = (num_edges + num_parts - 1) // num_parts
    bounds: List[Tuple[int, int]] = []
    left = 0
    for _ in range(num_parts - 1):
        if left >= num_nodes:
            break
        # first v with row_ptr[v+1] - row_ptr[left] > cap closes the
        # range at v; v+1 is the first index whose prefix exceeds the
        # target, which searchsorted finds in O(log V)
        v1 = int(np.searchsorted(row_ptr, row_ptr[left] + cap,
                                 side="right"))
        if v1 > num_nodes:
            break  # remaining edges fit under the cap: no more closes
        bounds.append((left, v1 - 1))
        left = v1
    bounds.append((left, num_nodes - 1))
    # pad with empty tail ranges so len(bounds) == num_parts always
    while len(bounds) < num_parts:
        bounds.append((num_nodes, num_nodes - 1))
    return bounds


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


# The default shape-quantization multiples: per-part padded node rows
# snap to NODE_MULTIPLE, padded edge slots to EDGE_MULTIPLE.  Named so
# every consumer of the quantization grid — the splitter below, the
# rebalance path, and the program-space auditor's cache-key-drift
# snapping (analysis/programspace.py) — reads the SAME values.
NODE_MULTIPLE = 8
EDGE_MULTIPLE = 128


def quantize_plan_shapes(real_nodes, real_edges,
                         node_multiple: int = NODE_MULTIPLE,
                         edge_multiple: int = EDGE_MULTIPLE
                         ) -> Tuple[int, int]:
    """``(part_nodes, part_edges)`` — the padded per-part shapes a
    plan over these per-part real counts compiles to.  This is THE
    quantized program-shape derivation: :func:`plan_from_bounds` (the
    splitter), the rebalance path, and the program-space auditor
    (``analysis/programspace.py``) all call it, so the shapes the
    trainer actually builds and the shapes the auditor statically
    enumerates can never disagree.

    Includes the full-part padding-edge correction: a part whose real
    rows exactly fill ``part_nodes`` while carrying padding edges
    would absorb dummy-source edges into its last REAL row (the
    sectioned/bdense planners then see out-of-range gathered
    coordinates), so one extra row-multiple is added whenever that
    configuration occurs."""
    real_nodes = np.asarray(real_nodes, dtype=np.int64)
    real_edges = np.asarray(real_edges, dtype=np.int64)
    part_nodes = _round_up(max(int(real_nodes.max()), 1), node_multiple)
    part_edges = _round_up(max(int(real_edges.max()), 1), edge_multiple)
    if any(int(real_nodes[p]) == part_nodes
           and int(real_edges[p]) < part_edges
           for p in range(real_nodes.shape[0])):
        part_nodes += node_multiple
    return part_nodes, part_edges


@dataclass
class PartitionPlan:
    """Partition metadata computable from ``row_ptr`` alone — O(V), no
    edge data.  Each host derives the full plan cheaply (the offsets
    section of a `.lux` is ~8 bytes/vertex) and then loads/builds ONLY
    its own partitions' O(E/P) column data (:func:`partition_col`),
    matching the reference's per-partition loader tasks
    (``load_task.cu:201-245``).

    Conventions:
      - ``part_row_ptr[p]`` is a *local* CSR over the part's padded rows:
        length ``part_nodes + 1``, offsets into the part's padded edge
        slice.  Padding edges attach to the *first padded row* (or the
        last real row when the part has no padded rows) so that edge
        destinations stay contiguous — the blocked/pallas aggregators
        rely on "a chunk of C sorted edges spans <= C rows".  Padding
        edges point at the dummy zero-feature source, so a real last row
        absorbing them just adds zeros.
      - ``node_offset[p]`` is the global id of the part's first row;
        global row ``g`` lives at part ``p``, local row ``g - node_offset[p]``.
    """

    num_nodes: int
    num_edges: int
    num_parts: int
    part_nodes: int              # padded rows per part
    part_edges: int              # padded edges per part
    bounds: List[Tuple[int, int]]
    node_offset: np.ndarray      # int32 [P]
    real_nodes: np.ndarray       # int32 [P] un-padded row counts
    real_edges: np.ndarray       # int64 [P]
    part_row_ptr: np.ndarray     # int32 [P, part_nodes+1] local offsets
    part_in_degree: np.ndarray   # int32 [P, part_nodes] real in-degrees
    # the padding multiples the plan was built with — recorded so a
    # repartition (core/costmodel.py + DistributedTrainer rebalance)
    # re-quantizes to the SAME multiples and repeat shapes hit the
    # compile cache
    node_multiple: int = NODE_MULTIPLE
    edge_multiple: int = EDGE_MULTIPLE

    @property
    def padded_num_nodes(self) -> int:
        """Total rows across all parts (== part_nodes * num_parts)."""
        return self.part_nodes * self.num_parts

    @property
    def dummy_src(self) -> int:
        """Global source id used by padding edges; its feature row must be
        zero."""
        return self.num_nodes

    def edge_range(self, p: int) -> Tuple[int, int]:
        """Global [e0, e1) edge extent of partition ``p``'s real edges
        (parts cover contiguous vertex ranges in order, so their edges
        are consecutive in global CSR order)."""
        e0 = int(self.real_edges[:p].sum())
        return e0, e0 + int(self.real_edges[p])

    def local_to_global(self) -> np.ndarray:
        """int32 [P, part_nodes] map of padded local rows to global node
        ids; padded rows map to ``num_nodes`` (the dummy row)."""
        out = np.full((self.num_parts, self.part_nodes), self.num_nodes,
                      dtype=np.int32)
        for p in range(self.num_parts):
            n = int(self.real_nodes[p])
            out[p, :n] = np.arange(self.node_offset[p],
                                   self.node_offset[p] + n, dtype=np.int32)
        return out

    def global_pad_map(self) -> np.ndarray:
        """int32 [padded_num_nodes] map from concatenated padded rows back
        to global node ids (num_nodes for padding rows).  Used to scatter
        padded-part outputs back to the compact global order."""
        return self.local_to_global().reshape(-1)


@dataclass
class PartitionedGraph(PartitionPlan):
    """A :class:`PartitionPlan` plus every partition's column data —
    the fully materialized form used single-process (multi-host code
    keeps only local parts' columns via :func:`partition_col`).

    ``part_col_idx[p]`` holds *global* source ids; padding edges point
    at the dummy source id ``num_nodes`` (a zero feature row appended
    by the training layer).
    """

    # dataclass default only because the base plan's multiples have
    # defaults; __post_init__ restores the required-field contract
    part_col_idx: np.ndarray = None  # int32 [P, part_edges] global src

    def __post_init__(self):
        if self.part_col_idx is None:
            raise TypeError(
                "PartitionedGraph requires part_col_idx "
                "(materialize_plan attaches it to a plan)")


def padded_edge_list(graph: Graph, multiple: int = 1024
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Single-device analog of the partition padding: return
    ``(edge_src, edge_dst)`` int32 arrays padded to a multiple of
    ``multiple``.  Padding edges use the dummy source ``num_nodes`` (zero
    feature row) and the last real destination row, preserving both the
    aggregation result and the blocked aggregator's sorted-contiguity
    invariant."""
    E = graph.num_edges
    Ep = _round_up(max(E, 1), multiple)
    src = np.full(Ep, graph.num_nodes, dtype=np.int32)
    dst = np.full(Ep, graph.num_nodes - 1, dtype=np.int32)
    src[:E] = graph.col_idx
    dst[:E] = graph.edge_dst()
    return src, dst


def partition_bounds(row_ptr: np.ndarray, num_parts: int,
                     method: str = "greedy",
                     node_multiple: int = NODE_MULTIPLE,
                     edge_multiple: int = EDGE_MULTIPLE,
                     cost_weights=None) -> List[Tuple[int, int]]:
    """Split-point selection — the ONE dispatch between the
    reference's greedy edge sweep (``method='greedy'``) and the
    cost-balanced minimax search (``method='cost'``,
    core/costmodel.py; ``cost_weights`` = the model's
    ``search_weights()``, default the edge-balance prior).  Unknown
    methods raise — a typo must not silently change the split."""
    if method == "greedy":
        return edge_balanced_bounds(row_ptr, num_parts)
    if method == "cost":
        from .costmodel import cost_balanced_bounds
        return cost_balanced_bounds(row_ptr, num_parts,
                                    node_multiple=node_multiple,
                                    edge_multiple=edge_multiple,
                                    weights=cost_weights)
    raise ValueError(f"unknown partition method {method!r}; expected "
                     "'greedy' or 'cost'")


def partition_plan(row_ptr: np.ndarray, num_parts: int,
                   node_multiple: int = NODE_MULTIPLE,
                   edge_multiple: int = EDGE_MULTIPLE,
                   method: str = "greedy",
                   cost_weights=None) -> PartitionPlan:
    """Everything about the partitioning derivable from the global row
    pointers alone (bounds, padded shapes, local row CSRs, degrees) —
    the O(V) metadata every host computes; column data is loaded
    per-partition afterwards (:func:`partition_col`)."""
    row_ptr = np.asarray(row_ptr, dtype=np.int64)
    bounds = partition_bounds(row_ptr, num_parts, method=method,
                              node_multiple=node_multiple,
                              edge_multiple=edge_multiple,
                              cost_weights=cost_weights)
    return plan_from_bounds(row_ptr, bounds, num_parts,
                            node_multiple=node_multiple,
                            edge_multiple=edge_multiple)


def plan_from_bounds(row_ptr: np.ndarray, bounds: List[Tuple[int, int]],
                     num_parts: int, node_multiple: int = NODE_MULTIPLE,
                     edge_multiple: int = EDGE_MULTIPLE) -> PartitionPlan:
    """Materialize the plan metadata for explicit ``bounds`` — the
    shared tail of :func:`partition_plan` and the repartitioning path
    (DistributedTrainer.maybe_rebalance hands searched bounds here)."""
    row_ptr = np.asarray(row_ptr, dtype=np.int64)
    V = row_ptr.shape[0] - 1
    E = int(row_ptr[-1])
    real_nodes = np.array([max(r - l + 1, 0) for l, r in bounds],
                          dtype=np.int32)
    real_edges = np.array(
        [int(row_ptr[r + 1] - row_ptr[l]) if r >= l else 0
         for l, r in bounds], dtype=np.int64)
    # Padded shapes + the full-part padding-edge correction live in
    # quantize_plan_shapes — the ONE quantized program-shape
    # derivation, shared with the rebalance path and the program-space
    # auditor (analysis/programspace.py).  Latent-bug history of the
    # correction is documented there.
    part_nodes, part_edges = quantize_plan_shapes(
        real_nodes, real_edges, node_multiple, edge_multiple)

    node_offset = np.array([l for l, _ in bounds], dtype=np.int32)
    node_offset = np.minimum(node_offset, V)  # empty tail parts
    part_row_ptr = np.zeros((num_parts, part_nodes + 1), dtype=np.int32)
    part_in_degree = np.zeros((num_parts, part_nodes), dtype=np.int32)
    for p, (l, r) in enumerate(bounds):
        if r < l:
            # empty part: every edge is padding; row 0 absorbs them all.
            part_row_ptr[p, 1:] = part_edges
            continue
        n = r - l + 1
        e0 = int(row_ptr[l])
        local_ptr = (row_ptr[l:r + 2] - e0).astype(np.int32)
        part_row_ptr[p, :n + 1] = local_ptr
        # Padding edges attach immediately after the real edges, on the
        # first padded row (local row n) — or, when n == part_nodes, on
        # the last real row, where they harmlessly add the dummy source's
        # zero feature row.  Every row after that has zero edges, so
        # part_row_ptr[-1] == part_edges always holds.
        part_row_ptr[p, min(n, part_nodes - 1) + 1:] = part_edges
        part_in_degree[p, :n] = np.diff(row_ptr[l:r + 2])
    return PartitionPlan(
        num_nodes=V, num_edges=E, num_parts=num_parts,
        part_nodes=part_nodes, part_edges=part_edges, bounds=bounds,
        node_offset=node_offset, real_nodes=real_nodes,
        real_edges=real_edges, part_row_ptr=part_row_ptr,
        part_in_degree=part_in_degree,
        node_multiple=node_multiple, edge_multiple=edge_multiple)


def partition_col(plan: PartitionPlan, col_slice, p: int) -> np.ndarray:
    """One partition's padded column array (int32 [part_edges], global
    source ids, padding == num_nodes).  ``col_slice(e0, e1)`` returns
    the global ``col_idx[e0:e1]`` — a memory view single-process, a
    seek+read for file-backed hosts — so a host materializes only its
    own partitions' O(E/P) edges (reference ``load_task.cu:201-245``)."""
    out = np.full(plan.part_edges, plan.num_nodes, dtype=np.int32)
    e0, e1 = plan.edge_range(p)
    if e1 > e0:
        out[:e1 - e0] = col_slice(e0, e1)
    return out


def partition_graph(graph: Graph, num_parts: int,
                    node_multiple: int = NODE_MULTIPLE,
                    edge_multiple: int = EDGE_MULTIPLE,
                    method: str = "greedy",
                    cost_weights=None) -> PartitionedGraph:
    """Partition ``graph`` into ``num_parts`` equal-shaped padded
    shards — the fully materialized single-process form (plan + every
    part's columns).  ``method='greedy'`` (default) is the reference's
    edge-balanced sweep; ``method='cost'`` the cost-balanced minimax
    search (core/costmodel.py, ``cost_weights`` as there)."""
    plan = partition_plan(graph.row_ptr, num_parts,
                          node_multiple=node_multiple,
                          edge_multiple=edge_multiple,
                          method=method, cost_weights=cost_weights)
    return materialize_plan(graph, plan)


def materialize_plan(graph: Graph, plan: PartitionPlan
                     ) -> PartitionedGraph:
    """Attach every partition's column data to a plan (single-process;
    the repartitioning path reuses this with searched bounds)."""
    col_slice = lambda e0, e1: graph.col_idx[e0:e1]
    part_col_idx = np.stack([partition_col(plan, col_slice, p)
                             for p in range(plan.num_parts)])
    return PartitionedGraph(**vars(plan), part_col_idx=part_col_idx)
