"""Memory placement policy: estimate per-device HBM and choose a plan.

The reference actively manages device-memory residency: a 4-slot
framebuffer cache sized from ``maxHidden`` with best-fit slot
assignment (``resourcemanager.cc:29-57``, ``load_task.cu:365-374``),
backed by zero-copy host memory for everything that doesn't fit
(``types.cu:22-32``).  The TPU analog is a *plan*, not a cache: XLA
owns HBM, so the policy's job is to pick, before compilation, which
combination of mechanisms keeps the step's peak footprint inside the
budget:

- ``halo``: one-shot ``all_gather`` (fast, materializes the global
  [V, H] feature matrix per device) vs the ``ppermute`` ring (O(V/P)
  peak, parallel/ring.py);
- ``features``: HBM-resident input features vs host-resident features
  streamed through the first layer (core/streaming.py — the direct
  analog of the reference's ZC->FB staging);
- ``remat``: recompute activations in backward instead of saving them
  (``jax.checkpoint``).

:func:`choose_memory_plan` estimates the footprint of each viable
combination (cheapest-first) and returns the first that fits, so a
graph sized past the gather budget trains via ring or streaming with
no user flags — the reference needs no flags for its cache either.
The decision is echoed at trainer setup like the reference's config
print (``gnn.cc:48-60``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

# Activation-liveness factors: a GCN-family layer keeps roughly this
# many [V_p, H] intermediates alive for backward (dropout out, linear
# out, two norms, aggregation out, relu out) without remat; with
# jax.checkpoint the layer boundaries survive plus the saved
# aggregation outputs (the default save_aggregates policy,
# train/trainer.py remat_policy — recomputing the halo gather + CSR
# sum would dominate the remat overhead).
_ACT_FACTOR_SAVED = 6
_ACT_FACTOR_REMAT_SAVE_AGG = 3   # layer boundaries + saved aggregates
_ACT_FACTOR_REMAT_FULL = 2       # layer boundaries only
# Default usable fraction of physical HBM (XLA reserves workspace,
# and the estimate is deliberately coarse).
_USABLE = 0.85
_DEFAULT_HBM = 16 * 1024**3  # v5e physical per chip


def charged_table_bytes(aggr_impl: str, uses_attention: bool,
                        uses_max_aggregation: bool,
                        a_budget_bytes: Optional[int]) -> int:
    """The impl-specific resident-table bytes the memory plan must
    charge on top of the generic ``E*4`` term — today the bdense
    A-table, whose worst case is exactly the planner's device-byte cap
    (``bdense_a_budget``).  ONE home for the rule (it used to live
    duplicated in ``modeled_step_bytes`` and the autopilot, round-5
    advisor): attention/MAX models never keep the table — their impl
    is rewritten away from bdense by ``resolve_attention_impl`` — and
    an uncapped budget is unmodelable (0 here; the occupancy echo is
    the warning there)."""
    keeps_bdense = (aggr_impl == "bdense"
                    and not uses_attention
                    and not uses_max_aggregation)
    return (a_budget_bytes or 0) if keeps_bdense else 0


def detect_hbm_bytes(default: int = _DEFAULT_HBM) -> int:
    """Per-device HBM budget: ``memory_stats()['bytes_limit']`` when the
    backend exposes it (the axon relay may not), else the v5e default;
    scaled by the usable fraction either way."""
    try:
        import jax
        stats = jax.devices()[0].memory_stats()
        limit = int(stats.get("bytes_limit", 0))
        if limit > 0:
            return int(limit * _USABLE)
    except Exception:  # noqa: BLE001 - any backend without stats
        pass
    return int(_DEFAULT_HBM * _USABLE)


@dataclass
class MemoryPlan:
    """A chosen residency/exchange configuration + its evidence."""
    halo: str            # "gather" | "ring"
    features: str        # "hbm" | "host"
    remat: bool
    fits: bool           # False = even the last-resort plan over budget
    est_bytes: int       # estimate for the chosen plan
    budget_bytes: int
    candidates: Dict[str, int]  # plan-name -> estimated bytes
    reason: str

    @property
    def name(self) -> str:
        return (f"halo={self.halo} features={self.features} "
                f"remat={self.remat}")

    def echo(self) -> str:
        """Human-readable decision line (the ``# `` console prefix is
        added by the event log's console sink)."""
        gib = 1024**3
        return (f"memory plan: {self.name} — est "
                f"{self.est_bytes / gib:.2f} GiB of "
                f"{self.budget_bytes / gib:.2f} GiB budget; {self.reason}")


def estimate_plan_bytes(num_nodes: int, num_edges: int,
                        layer_dims: Sequence[int], num_parts: int = 1,
                        dtype_bytes: int = 4, halo: str = "gather",
                        features: str = "hbm", remat: bool = False,
                        ring_padding: float = 1.7,
                        remat_policy: str = "save_aggregates",
                        extra_table_bytes: int = 0) -> int:
    """Coarse per-device peak-HBM estimate for one train step.

    ``layer_dims`` is the CLI layer spec (in-dim, hidden..., classes).
    Deliberately simple and slightly pessimistic — the policy needs
    ordering between plans, not byte-exact numbers.

    ``extra_table_bytes`` covers impl-specific resident tables the
    generic ``E*4`` term misses — today the bdense A-table, whose
    worst case is exactly ``bdense_a_budget`` (the planner's device-
    byte cap)."""
    V_p = -(-num_nodes // num_parts)
    E_p = -(-num_edges // num_parts)
    b = dtype_bytes
    F = layer_dims[0]
    hiddens = list(layer_dims[1:])
    h_max = max(hiddens + [F])

    # replicated params + Adam m/v
    w = sum(layer_dims[i] * layer_dims[i + 1]
            for i in range(len(layer_dims) - 1))
    total = 3 * w * b

    # input features
    if features == "hbm":
        total += V_p * F * b
    else:
        total += 65536 * F * b  # one streamed block + dY reuse

    # edge tables: ELL idx ~ E_p int32 (+ row positions)
    total += E_p * 4 + V_p * 4 + extra_table_bytes
    if halo == "ring":
        total += int(2 * E_p * 4 * ring_padding)  # src+dst flat tables

    # live activations
    if remat:
        act = (_ACT_FACTOR_REMAT_FULL if remat_policy == "full"
               else _ACT_FACTOR_REMAT_SAVE_AGG)
    else:
        act = _ACT_FACTOR_SAVED
    act_bytes = sum(V_p * h * b * act for h in hiddens)
    if features == "hbm":
        # first dropout output is [V_p, F]
        act_bytes += V_p * F * b * (1 if remat else 2)
    total += act_bytes

    # halo transient: the gathered global matrix vs two ring buffers
    if halo == "gather":
        total += num_parts * V_p * h_max * b
    else:
        total += 2 * V_p * h_max * b
    return total


def per_axis_plan_bytes(num_nodes: int, num_edges: int,
                        layer_dims: Sequence[int], parts: int = 1,
                        model: int = 1, dtype_bytes: int = 4,
                        halo: str = "gather", features: str = "hbm",
                        remat: bool = False,
                        remat_policy: str = "save_aggregates",
                        ring_padding: float = 1.7
                        ) -> Dict[str, Dict[str, int]]:
    """Per-component, per-mesh-axis byte attribution of one train
    step on an abstract ``(parts, model)`` mesh — the planner-side
    half of the sharding auditor's replication ledger
    (analysis/sharding_lint.py) and the "modeled per-device HBM"
    column of the mesh-portability report.

    Same coarse accounting as :func:`estimate_plan_bytes` (whose
    ``parts``-only totals this reproduces at ``model=1``), but each
    component reports WHICH axes divide it: params/opt-state and
    activations split over ``model`` on their feature axis (the 2-D
    design's pjit'd dense ops), vertex-scale tensors split over
    ``parts``, edge/halo index tables split over ``parts`` only —
    they carry no feature axis, so the model axis REPLICATES them,
    and the ledger must say so rather than divide by the whole mesh.

    Returns ``{component: {"bytes": total, "parts_div": p,
    "model_div": m, "per_device": total // (p*m)}}`` plus a
    ``"total"`` row; ``replicated`` in a component marks the axes
    (divisor 1 while the mesh axis is >1) it is replicated over."""
    V_p = -(-num_nodes // max(parts, 1))
    E_p = -(-num_edges // max(parts, 1))
    b = dtype_bytes
    F = layer_dims[0]
    hiddens = list(layer_dims[1:])
    h_max = max(hiddens + [F])
    w = sum(layer_dims[i] * layer_dims[i + 1]
            for i in range(len(layer_dims) - 1))

    def comp(total: int, parts_div: int, model_div: int
             ) -> Dict[str, int]:
        per_dev = int(total) // max(parts_div * model_div, 1)
        rep = []
        if parts > 1 and parts_div == 1:
            rep.append("parts")
        if model > 1 and model_div == 1:
            rep.append("model")
        return {"bytes": int(total), "parts_div": parts_div,
                "model_div": model_div, "per_device": per_dev,
                "replicated": rep}

    out: Dict[str, Dict[str, int]] = {}
    # params + Adam m/v: feature-axis (model) sharded on the 2-D
    # mesh, replicated over parts either way (the reference reads
    # weights whole in every task)
    out["params"] = comp(w * b, 1, model)
    out["opt_state"] = comp(2 * w * b, 1, model)
    if features == "hbm":
        out["features"] = comp(num_nodes * F * b, parts, model)
    else:
        out["features"] = comp(65536 * F * b * parts, parts, model)
    # edge/halo index tables: int32 per edge + row positions — no
    # feature axis, so the model axis replicates them
    tab = E_p * 4 * parts + V_p * 4 * parts
    if halo == "ring":
        tab += int(2 * E_p * 4 * ring_padding) * parts
    out["tables"] = comp(tab, parts, 1)
    if remat:
        act = (_ACT_FACTOR_REMAT_FULL if remat_policy == "full"
               else _ACT_FACTOR_REMAT_SAVE_AGG)
    else:
        act = _ACT_FACTOR_SAVED
    act_bytes = sum(num_nodes * h * b * act for h in hiddens)
    if features == "hbm":
        act_bytes += num_nodes * F * b * (1 if remat else 2)
    out["activations"] = comp(act_bytes, parts, model)
    # halo transient: the gathered whole-region matrix is per-device
    # [P * V_p, h] — replicated over parts BY DESIGN (that is what a
    # gather is), feature-sharded over model; the ring keeps two
    # block buffers instead
    if halo == "gather":
        out["halo"] = comp(parts * V_p * h_max * b * parts, parts,
                           model)
    else:
        out["halo"] = comp(2 * V_p * h_max * b * parts, parts, model)
    total = sum(c["bytes"] for c in out.values())
    per_dev = sum(c["per_device"] for c in out.values())
    out["total"] = {"bytes": int(total), "per_device": int(per_dev),
                    "replicated": sorted({a for c in out.values()
                                          for a in c.get("replicated",
                                                         [])})}
    return out


def choose_memory_plan(num_nodes: int, num_edges: int,
                       layer_dims: Sequence[int], num_parts: int = 1,
                       dtype_bytes: int = 4,
                       hbm_bytes: Optional[int] = None,
                       head_streamable: bool = True,
                       remat_policy: str = "save_aggregates",
                       extra_table_bytes: int = 0
                       ) -> MemoryPlan:
    """First-fit over plans ordered cheapest-compute-first.

    Order: gather/hbm -> gather/hbm+remat -> ring (P>1, +-remat) ->
    host-streamed features (P==1, head_streamable models).  The ring is
    the distributed answer to >HBM (SURVEY §5), host streaming the
    single-device one (the reference's ZC tier, ``types.cu:22-32``).
    If nothing fits, the last candidate is returned with
    ``fits=False`` — the caller proceeds (estimates are pessimistic)
    with the warning in the echo."""
    budget = hbm_bytes if hbm_bytes is not None else detect_hbm_bytes()
    cands: List = [("gather/hbm", "gather", "hbm", False),
                   ("gather/hbm/remat", "gather", "hbm", True)]
    if num_parts > 1:
        cands += [("ring/hbm", "ring", "hbm", False),
                  ("ring/hbm/remat", "ring", "hbm", True)]
    elif head_streamable:
        cands += [("gather/host", "gather", "host", False),
                  ("gather/host/remat", "gather", "host", True)]
    est = {}
    for name, halo, feats, remat in cands:
        est[name] = estimate_plan_bytes(
            num_nodes, num_edges, layer_dims, num_parts, dtype_bytes,
            halo=halo, features=feats, remat=remat,
            remat_policy=remat_policy,
            # ring runs never build the bdense A-table (the ring
            # tables fully describe the aggregation) — charging them
            # would push ring plans into remat for phantom bytes
            extra_table_bytes=(extra_table_bytes
                               if halo == "gather" else 0))
    for name, halo, feats, remat in cands:
        if est[name] <= budget:
            return MemoryPlan(
                halo=halo, features=feats, remat=remat, fits=True,
                est_bytes=est[name], budget_bytes=budget,
                candidates=est,
                reason=f"first fit of {len(cands)} candidates")
    name, halo, feats, remat = cands[-1]
    return MemoryPlan(
        halo=halo, features=feats, remat=remat, fits=False,
        est_bytes=est[name], budget_bytes=budget, candidates=est,
        reason="NO plan fits the budget — proceeding with the smallest "
               "(estimates are pessimistic); expect allocator pressure")
