"""Host-resident tensor streaming: train graphs larger than HBM.

The reference's scaling-beyond-framebuffer mechanism is host residency:
every tensor lives in zero-copy host memory and each GPU task stages
its working set through a 4-slot framebuffer cache
(``types.cu:22-32``, ``load_task.cu:365-374``, ``resourcemanager.cc:
29-57``) — a graph only has to fit in host RAM.  The TPU-native analog
keeps the *input features* (the dominant tensor: ``[V, in_dim]``) in
host RAM and streams row blocks through HBM:

- :func:`streamed_linear` — the first-layer projection ``X @ W``
  computed block-by-block (device_put of block k+1 overlaps the matmul
  of block k through JAX's async dispatch).  The projected ``[V,
  hidden]`` activations are HBM-resident from then on, so the rest of
  the model runs the normal fast path.  This covers the common
  out-of-core case (huge raw features, modest hidden width).
- :class:`StreamingAggregator` — full out-of-core neighbor aggregation
  for when even per-layer activations exceed HBM: edges are statically
  grouped by *source block* (host-side, once); per block, the block's
  feature rows are staged to HBM, gathered locally, and scatter-added
  into the output by destination.  Exactly the reference's
  stage-compute-writeback loop, with the FB cache slot replaced by a
  device-resident block buffer.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .graph import Graph


def streamed_linear(feats_host: np.ndarray, weight: jax.Array,
                    block_rows: int = 65536,
                    dtype=jnp.float32) -> jax.Array:
    """``feats @ weight`` with ``feats`` in host RAM, streamed through
    HBM in ``block_rows``-row blocks.  Returns the device-resident
    ``[V, out_dim]`` result.  Peak HBM: one block + the output."""
    V = feats_host.shape[0]
    outs = []
    for lo in range(0, V, block_rows):
        block = jax.device_put(
            np.ascontiguousarray(feats_host[lo:lo + block_rows]))
        outs.append(jnp.asarray(block, dtype=dtype) @ weight)
    return jnp.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]


@dataclass
class _SrcBlockPlan:
    """Static per-source-block edge layout (host-side, built once)."""
    lo: int                 # first global source row of the block
    hi: int                 # one past the last
    src_local: np.ndarray   # int32 [E_b] source ids relative to lo
    dst: np.ndarray         # int32 [E_b] destination rows (sorted)


class StreamingAggregator:
    """Out-of-core CSR sum-aggregation: ``out[dst] = sum feats[src]``
    with ``feats`` in host RAM.

    Edges are grouped by source block at construction (static for the
    life of the graph, like the reference's partition-time layout);
    each ``__call__`` stages one block of feature rows at a time and
    accumulates with a sorted segment scatter-add.  Memory on device:
    one feature block + the ``[num_rows, F]`` output + an edge-chunk
    transient.  This is the capability tier — the in-HBM impls in
    ``ops/aggregate.py`` are strictly faster when features fit.
    """

    def __init__(self, graph: Graph, block_rows: int = 65536,
                 edge_chunk: int = 1 << 20):
        self.num_rows = graph.num_nodes
        self.block_rows = block_rows
        self.edge_chunk = edge_chunk
        dst_all = graph.edge_dst()
        src_all = graph.col_idx
        # group edges by source block; within a block keep dst order
        # (stable sort) so the scatter-add sees sorted segment ids
        block_of = src_all // block_rows
        order = np.argsort(block_of, kind="stable")
        src_s, dst_s = src_all[order], dst_all[order]
        blocks_present = np.unique(block_of)
        self.plans: List[_SrcBlockPlan] = []
        starts = np.searchsorted(block_of[order], blocks_present,
                                 side="left")
        ends = np.searchsorted(block_of[order], blocks_present,
                               side="right")
        for b, lo_e, hi_e in zip(blocks_present, starts, ends):
            lo = int(b) * block_rows
            hi = min(lo + block_rows, self.num_rows)
            sl = src_s[lo_e:hi_e] - lo
            dl = dst_s[lo_e:hi_e]
            o = np.argsort(dl, kind="stable")
            self.plans.append(_SrcBlockPlan(
                lo=lo, hi=hi, src_local=sl[o].astype(np.int32),
                dst=dl[o].astype(np.int32)))

    def __call__(self, feats_host: np.ndarray,
                 out_dtype=jnp.float32) -> jax.Array:
        F = feats_host.shape[1]
        out = jnp.zeros((self.num_rows, F), dtype=out_dtype)
        add = _block_scatter_add_jit
        for plan in self.plans:
            block = jax.device_put(np.ascontiguousarray(
                feats_host[plan.lo:plan.hi])).astype(out_dtype)
            # chunk the block's edges to bound the [E, F] transient
            for e0 in range(0, plan.src_local.shape[0], self.edge_chunk):
                sl = jnp.asarray(plan.src_local[e0:e0 + self.edge_chunk])
                dl = jnp.asarray(plan.dst[e0:e0 + self.edge_chunk])
                out = add(out, block, sl, dl)
        return out


def _block_scatter_add(out, block, src_local, dst):
    g = block[src_local]
    return out.at[dst].add(g, indices_are_sorted=True,
                           unique_indices=False)


# module-level jit: the dispatch cache survives across aggregator calls
_block_scatter_add_jit = jax.jit(_block_scatter_add, donate_argnums=(0,))


@dataclass
class _TilePlan:
    """Edges of one (dst block, src block) adjacency tile."""
    src_lo: int
    src_local: np.ndarray   # int32 [E_t] source ids relative to src_lo
    dst_local: np.ndarray   # int32 [E_t] dest ids relative to the dst
    #                         block start (sorted)


def build_tile_plans(graph: Graph, block_rows: int):
    """dst-block -> list of per-src-block edge tiles (host-side, once).
    The fully-out-of-core grouping: BOTH operands of each tile fit in
    one block, so neither the feature matrix nor the output ever has to
    be device-resident whole."""
    dst_all = graph.edge_dst()
    src_all = graph.col_idx
    if not src_all.size:
        return {}
    db = dst_all // block_rows
    sb = src_all // block_rows
    order = np.lexsort((sb, db))
    dst_s, src_s, db_s, sb_s = (dst_all[order], src_all[order],
                                db[order], sb[order])
    # tile boundaries in the lexsorted edge list
    key = db_s.astype(np.int64) * (sb.max() + 1 if sb.size else 1) + sb_s
    cut = np.flatnonzero(np.diff(key)) + 1
    starts = np.concatenate([[0], cut])
    ends = np.concatenate([cut, [key.shape[0]]])
    tiles: dict = {}
    for lo_e, hi_e in zip(starts, ends):
        d, s = int(db_s[lo_e]), int(sb_s[lo_e])
        sl = (src_s[lo_e:hi_e] - s * block_rows).astype(np.int32)
        dl = (dst_s[lo_e:hi_e] - d * block_rows).astype(np.int32)
        o = np.argsort(dl, kind="stable")
        tiles.setdefault(d, []).append(_TilePlan(
            src_lo=s * block_rows, src_local=sl[o], dst_local=dl[o]))
    return tiles


def aggregate_to_host(graph: Graph, feats_host: np.ndarray,
                      block_rows: int = 65536,
                      edge_chunk: int = 1 << 20,
                      tiles=None) -> np.ndarray:
    """Fully out-of-core CSR sum-aggregation: both the feature matrix
    AND the result live in host RAM; the device holds one destination
    accumulator block + one source feature block + an edge-chunk
    transient.  This is the complete form of the reference's
    stage-compute-writeback residency design (``types.cu:22-32``,
    ``load_task.cu:365-374``): *every* [V, F] tensor is host-resident.
    :class:`StreamingAggregator` (device-resident output) is the
    faster tier when the output fits."""
    V = graph.num_nodes
    F = feats_host.shape[1]
    if tiles is None:
        tiles = build_tile_plans(graph, block_rows)
    out = np.zeros((V, F), dtype=np.float32)
    for d in sorted(tiles):
        d_lo = d * block_rows
        rows = min(block_rows, V - d_lo)
        acc = jnp.zeros((rows, F), dtype=jnp.float32)
        for t in tiles[d]:
            block = jax.device_put(np.ascontiguousarray(
                feats_host[t.src_lo:t.src_lo + block_rows])
            ).astype(jnp.float32)
            for e0 in range(0, t.src_local.shape[0], edge_chunk):
                sl = jnp.asarray(t.src_local[e0:e0 + edge_chunk])
                dl = jnp.asarray(t.dst_local[e0:e0 + edge_chunk])
                acc = _block_scatter_add_jit(acc, block, sl, dl)
        out[d_lo:d_lo + rows] = np.asarray(acc)
    return out


def stream_prefix_to_host(graph: Graph, prefix_ops,
                          feats_host: np.ndarray,
                          block_rows: int = 65536) -> np.ndarray:
    """Evaluate a parameter-free norm/aggregation prefix (the op list
    returned by ``Model.streamable_agg_head``) with every [V, F]
    intermediate host-resident: ``indegree_norm`` is a host row
    scaling, ``scatter_gather`` (SUM/AVG) runs through
    :func:`aggregate_to_host`.  Returns fp32; runs ONCE per training
    session — this is the SGC-style precompute (A_hat^k X), after which
    epochs touch only the streamed head."""
    from ..models.builder import AGGR_AVG, AGGR_SUM
    from ..ops.norm import inv_sqrt_degree_np
    x = np.asarray(feats_host, dtype=np.float32)
    deg = np.asarray(graph.in_degree, dtype=np.float32)
    inv_sqrt = inv_sqrt_degree_np(graph.in_degree)[:, None]
    tiles = None
    for op in prefix_ops:
        if op.kind == "indegree_norm":
            x = x * inv_sqrt
        elif op.kind == "scatter_gather":
            if tiles is None:
                tiles = build_tile_plans(graph, block_rows)
            x = aggregate_to_host(graph, x, block_rows, tiles=tiles)
            if op.attrs.get("aggr", AGGR_SUM) == AGGR_AVG:
                x = x / np.maximum(deg, 1.0)[:, None]
        elif op.kind == "fused_aggregate":
            # the fused norm -> sum -> norm [-> relu] op
            # (models/builder.py fuse_norm_aggregate), unrolled
            # host-side — this precompute runs once, so fusion buys
            # nothing here and exactness is what matters
            if tiles is None:
                tiles = build_tile_plans(graph, block_rows)
            x = aggregate_to_host(graph, x * inv_sqrt, block_rows,
                                  tiles=tiles) * inv_sqrt
            if op.attrs.get("activation", "none") != "none":
                np.maximum(x, 0.0, out=x)
        else:  # pragma: no cover - guarded by streamable_agg_head
            raise NotImplementedError(op.kind)
    return x


class StreamedHead:
    """First model layer (``dropout -> linear``) computed from
    host-resident features, with the matching streamed weight gradient.

    This is the *integrated* form of :func:`streamed_linear` — the
    piece that makes ``TrainConfig(features="host")`` a training path,
    not just a forward helper.  Forward: per 65536-row block, stage the
    block to HBM, apply inverted dropout (key folded per block), matmul
    into the ``[V, H]`` output; JAX's async dispatch overlaps block
    k+1's transfer with block k's compute.  Backward: given the
    cotangent ``dY`` of the projected activations (from autodiff of the
    device-resident tail), ``dW = sum_b dropout(X_b)^T @ dY_b`` with
    the SAME per-block keys, so the recomputed masks match the forward
    exactly.  The raw ``[V, F]`` feature matrix never resides on device
    — the reference's ZC->FB staging loop (``types.cu:22-32``) with the
    FB cache slot replaced by the block transient.

    Note the RNG stream differs from the in-HBM path (one key per
    block instead of one for the whole matrix): both are valid
    inverted-dropout samplings; numerics match exactly in eval mode.
    """

    def __init__(self, rate: float, block_rows: int = 65536):
        self.rate = float(rate)
        self.block_rows = block_rows

    def _keys(self, key, n_blocks: int):
        if key is None:
            return [None] * n_blocks
        return [jax.random.fold_in(key, b) for b in range(n_blocks)]

    def _blocks(self, V: int):
        return [(lo, min(lo + self.block_rows, V))
                for lo in range(0, V, self.block_rows)]

    def forward(self, weight: jax.Array, feats_host: np.ndarray,
                key: Optional[jax.Array], train: bool) -> jax.Array:
        """[V, H] projected activations, device-resident."""
        blocks = self._blocks(feats_host.shape[0])
        keys = self._keys(key, len(blocks))
        outs = []
        for (lo, hi), k in zip(blocks, keys):
            x = jax.device_put(np.ascontiguousarray(feats_host[lo:hi]))
            x = x.astype(weight.dtype)
            outs.append(_head_fwd_block(x, weight, self.rate, k,
                                        train and key is not None))
        return jnp.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]

    def wgrad(self, feats_host: np.ndarray, dY: jax.Array,
              key: Optional[jax.Array], train: bool) -> jax.Array:
        """dL/dW for the head linear, streamed: recomputes each block's
        dropout with the same folded key as :meth:`forward`."""
        blocks = self._blocks(feats_host.shape[0])
        keys = self._keys(key, len(blocks))
        # accumulate across blocks in fp32 regardless of the compute
        # dtype (many-block bf16 accumulation would round away small
        # contributions); the caller casts to the master param dtype
        dW = jnp.zeros((feats_host.shape[1], dY.shape[1]),
                       dtype=jnp.float32)
        for (lo, hi), k in zip(blocks, keys):
            x = jax.device_put(np.ascontiguousarray(feats_host[lo:hi]))
            x = x.astype(dY.dtype)
            dW = _head_wgrad_block(dW, x, dY[lo:hi], self.rate, k,
                                   train and key is not None)
        return dW


@functools.partial(jax.jit, static_argnames=("rate", "use_mask"))
def _head_fwd_block(x, weight, rate, key, use_mask):
    # dense.linear, not a bare @: the in-HBM path accumulates fp32 at
    # HIGHEST precision and the streamed path must match bit-for-bit
    # semantics (Model.streamable_head guarantees activation == NONE)
    from ..ops.dense import AC_MODE_NONE, dropout, linear
    d = dropout(x, rate if use_mask else 0.0, key, use_mask)
    return linear(d, weight, AC_MODE_NONE)


@functools.partial(jax.jit, static_argnames=("rate", "use_mask"),
                   donate_argnums=(0,))
def _head_wgrad_block(dW, x, dy, rate, key, use_mask):
    from ..ops.dense import dropout
    d = dropout(x, rate if use_mask else 0.0, key, use_mask)
    prec = (jax.lax.Precision.HIGHEST if d.dtype == jnp.float32
            else None)
    return dW + jax.lax.dot_general(
        d, dy, (((0,), (0,)), ((), ())), precision=prec,
        preferred_element_type=jnp.float32).astype(dW.dtype)
