"""Host-resident tensor streaming: train graphs larger than HBM.

The reference's scaling-beyond-framebuffer mechanism is host residency:
every tensor lives in zero-copy host memory and each GPU task stages
its working set through a 4-slot framebuffer cache
(``types.cu:22-32``, ``load_task.cu:365-374``, ``resourcemanager.cc:
29-57``) — a graph only has to fit in host RAM.  The TPU-native analog
keeps the *input features* (the dominant tensor: ``[V, in_dim]``) in
host RAM and streams row blocks through HBM:

- :func:`streamed_linear` — the first-layer projection ``X @ W``
  computed block-by-block.  The projected ``[V, hidden]`` activations
  are HBM-resident from then on, so the rest of the model runs the
  normal fast path.  This covers the common out-of-core case (huge raw
  features, modest hidden width).
- :class:`StreamingAggregator` — full out-of-core neighbor aggregation
  for when even per-layer activations exceed HBM: edges are statically
  grouped by *source block* (host-side, once); per block, the block's
  feature rows are staged to HBM, gathered locally, and scatter-added
  into the output by destination.  Exactly the reference's
  stage-compute-writeback loop, with the FB cache slot replaced by a
  device-resident block buffer.

Every path stages through :class:`StagingPool` — the piece that makes
the tier *latency-hiding* instead of latency-serial: the reference's
ZC→FB loop overlaps the DRAM→GPU copy of the next task's working set
with the current task's kernel, and the pool reproduces that overlap
by running block k+1's host copy + H2D issue on a background thread
while block k's compute is dispatched.  ``prefetch=0`` degrades to the
synchronous form (bit-identical results — the parity reference).
"""

from __future__ import annotations

import functools
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .graph import Graph
# import-light (os/signal/dataclasses + the jax-free event bus): the
# fault-drill hook below sits on the per-block staging path, so the
# lookup must not repeat per block
from ..resilience.inject import maybe_staging_error


class _StageError:
    """Worker-side exception carrier (re-raised on the consumer)."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class StagingPool:
    """Reusable double-buffered host→device staging pipeline.

    ``stream(fns)`` yields each stage function's result in order.  With
    ``depth >= 1`` a daemon worker thread runs up to ``depth`` stage
    calls ahead of the consumer, so the blocking host work of block
    k+1 (``np.ascontiguousarray`` copy + ``device_put`` issue) executes
    under block k's compute — the reference's ZC→FB overlap
    (``load_task.cu:365-374``) with the FB slot replaced by a staged
    device buffer.  ``depth == 0`` stages inline (synchronous): the
    bit-identical parity reference and the honest baseline the
    ``overlap_frac`` metric compares against.

    Live-buffer bound: the worker acquires one of ``depth`` credits
    before each stage call and the consumer returns the credit when it
    dequeues, so at most ``depth + 1`` staged blocks exist at any time
    (the one the consumer holds plus the prefetched ones) — with the
    default ``depth=1`` the pool is exactly a 2-slot double buffer,
    regardless of how many blocks V splits into.

    Stats (reset by :meth:`take_stats`): per-block consumer-side
    ``h2d_wait_ms`` (time blocked waiting for a staged block — the
    un-hidden part of the transfer) and worker-side ``stage_ms`` (host
    copy + H2D issue wall time).  ``1 - wait/stage`` is the fraction
    of staging latency hidden under compute (``overlap_frac``).
    """

    def __init__(self, depth: int = 1):
        self.depth = int(depth)
        if self.depth < 0:
            raise ValueError(f"prefetch depth must be >= 0, got {depth}")
        self.h2d_wait_ms: List[float] = []
        self.stage_ms: List[float] = []
        # monotonic start times parallel to the two series — the
        # cross-process timeline (obs/timeline.py) places each block's
        # wait/stage on the merged time axis
        self.h2d_wait_t0: List[float] = []
        self.stage_t0: List[float] = []
        self.max_live = 0
        self._live = 0
        self._lock = threading.Lock()

    def _note_live(self, delta: int) -> None:
        with self._lock:
            self._live += delta
            if self._live > self.max_live:
                self.max_live = self._live

    def take_stats(self) -> Dict[str, object]:
        """Return accumulated per-block stats and reset the series
        (``max_live`` is a lifetime high-water mark and persists).
        The derived summary — ``wait_p50_ms``, ``stage_p50_ms``,
        ``overlap_frac`` (clamped ``1 - wait_total/stage_total``;
        None when nothing was staged) — is computed HERE, once, so
        every consumer (trainer epoch records, bench rows,
        micro_stream) reports identical semantics."""
        with self._lock:
            wait, stage = self.h2d_wait_ms, self.stage_ms
            wait_t0, stage_t0 = self.h2d_wait_t0, self.stage_t0
            self.h2d_wait_ms, self.stage_ms = [], []
            self.h2d_wait_t0, self.stage_t0 = [], []
            # under the lock: the worker bumps it via _note_live
            # concurrently (roc-lint unguarded-shared-state)
            max_live = self.max_live
        out: Dict[str, object] = {
            "n": len(wait), "wait_ms": wait, "stage_ms": stage,
            "wait_t0": wait_t0, "stage_t0": stage_t0,
            "max_live": max_live, "depth": self.depth,
            "wait_p50_ms": None, "stage_p50_ms": None,
            "overlap_frac": None}
        # these float()s reduce host-side python lists of wall-clock
        # ms — no device array is ever fetched here
        if wait:
            # host stats: roc-lint: ok=host-sync-hot-path
            out["wait_p50_ms"] = round(float(np.median(wait)), 3)
        if stage:
            # host stats: roc-lint: ok=host-sync-hot-path
            out["stage_p50_ms"] = round(float(np.median(stage)), 3)
            total = float(sum(stage))   # host stats: roc-lint: ok=host-sync-hot-path
            if total > 0:
                out["overlap_frac"] = round(min(1.0, max(
                    # host stats: roc-lint: ok=host-sync-hot-path
                    0.0, 1.0 - float(sum(wait)) / total)), 4)
        return out

    def stream(self, stage_fns: Sequence[Callable[[], object]]
               ) -> Iterator[object]:
        """Yield ``fn()`` for each staging function, in order, staging
        up to ``depth`` calls ahead on a worker thread."""
        fns = list(stage_fns)
        # live accounting is per-pass: a consumer that stops pulling
        # (zip with a shorter iterator) leaves the generator suspended
        # mid-yield, so decrements happen at the NEXT dequeue (when the
        # consumer's loop variable has provably been rebound), and the
        # counter resets here
        with self._lock:
            self._live = 0
        if self.depth == 0:
            first = True
            for fn in fns:
                if not first:
                    self._note_live(-1)  # previous block superseded
                first = False
                mono0 = time.monotonic()
                t0 = time.perf_counter()
                val = fn()
                ms = (time.perf_counter() - t0) * 1e3
                with self._lock:
                    self.stage_ms.append(ms)
                    self.stage_t0.append(mono0)
                    # synchronous: the whole stage sits on the critical
                    # path, so the wait IS the stage time
                    self.h2d_wait_ms.append(ms)
                    self.h2d_wait_t0.append(mono0)
                self._note_live(+1)
                yield val
            return

        q: "queue.Queue" = queue.Queue()
        credits = threading.Semaphore(self.depth)
        cancel = threading.Event()

        def work():
            try:
                for fn in fns:
                    while not credits.acquire(timeout=0.1):
                        if cancel.is_set():
                            return
                    if cancel.is_set():
                        return
                    mono0 = time.monotonic()
                    t0 = time.perf_counter()
                    val = fn()
                    with self._lock:
                        self.stage_ms.append(
                            (time.perf_counter() - t0) * 1e3)
                        self.stage_t0.append(mono0)
                    self._note_live(+1)
                    q.put(val)
                    val = None  # the queue owns the only worker ref
            except BaseException as e:  # noqa: BLE001 - re-raised below
                q.put(_StageError(e))

        worker = threading.Thread(target=work, daemon=True,
                                  name="roc-tpu-staging")
        worker.start()
        try:
            for i in range(len(fns)):
                mono0 = time.monotonic()
                t0 = time.perf_counter()
                item = q.get()
                with self._lock:
                    self.h2d_wait_ms.append(
                        (time.perf_counter() - t0) * 1e3)
                    self.h2d_wait_t0.append(mono0)
                if isinstance(item, _StageError):
                    raise item.exc
                if i > 0:
                    # asking for block i means the consumer's loop
                    # rebound its variable: block i-1 is released
                    self._note_live(-1)
                # credit back BEFORE the yield: the worker stages the
                # next block while the consumer computes on this one —
                # that concurrency is the entire point of the pool
                credits.release()
                yield item
        finally:
            cancel.set()


def _stage_block(feats_host: np.ndarray, lo: int, hi: int) -> jax.Array:
    """The ONE sanctioned synchronous host→device staging call site:
    contiguous host copy + async ``device_put`` of one row block.
    Loops never call this directly — they route through
    :meth:`StagingPool.stream` (enforced by roc-lint
    ``sync-h2d-in-loop``).  Also the streamed tier's fault-drill
    site: an armed ``staging_io`` fault raises OSError here once, and
    the recovery loop must restore-and-retry (tests/test_drills.py)."""
    maybe_staging_error()
    return jax.device_put(np.ascontiguousarray(feats_host[lo:hi]))


def streamed_linear(feats_host: np.ndarray, weight: jax.Array,
                    block_rows: int = 65536,
                    dtype=jnp.float32, prefetch: int = 1) -> jax.Array:
    """``feats @ weight`` with ``feats`` in host RAM, streamed through
    HBM in ``block_rows``-row blocks (block k+1 staged under block k's
    matmul).  Returns the device-resident ``[V, out_dim]`` result.
    Peak HBM: two blocks (the double buffer) + the output."""
    V = feats_host.shape[0]
    pool = StagingPool(depth=prefetch)
    stage = [functools.partial(_stage_block, feats_host, lo,
                               lo + block_rows)
             for lo in range(0, V, block_rows)]
    outs = [jnp.asarray(block, dtype=dtype) @ weight
            for block in pool.stream(stage)]
    return jnp.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]


# Device-residency budget for cached index tables: plans whose total
# int32 (src, dst) bytes fit keep them device-resident for their
# lifetime (uploaded once at plan build — they used to be re-uploaded
# by ``jnp.asarray`` on every aggregator call); plans past the budget
# fall back to transient per-call uploads, because pinning O(E) index
# bytes on device would defeat the out-of-core tier on exactly the
# >HBM graphs it exists for (one edge_chunk of a transient upload is
# ~8 MB; a billion-edge resident table would be ~8 GB).
TABLE_CACHE_BYTES = 1 << 30


def _iter_chunks(src: np.ndarray, dst: np.ndarray, edge_chunk: int):
    for e0 in range(0, src.shape[0], edge_chunk):
        yield (jnp.asarray(src[e0:e0 + edge_chunk]),
               jnp.asarray(dst[e0:e0 + edge_chunk]))


def _dev_chunks(src: np.ndarray, dst: np.ndarray, edge_chunk: int,
                cache: Optional[dict]):
    """Chunked device-resident (src, dst) index pairs.  ``cache`` is
    the plan's memo dict (upload once, keep for the plan's lifetime)
    or None — the over-:data:`TABLE_CACHE_BYTES` fallback, which
    yields LAZILY so only one edge_chunk of transient index upload is
    live at a time (eagerly materializing the list would re-pin the
    whole O(E) table the budget exists to keep off the device)."""
    if cache is None:
        return _iter_chunks(src, dst, edge_chunk)
    chunks = cache.get(edge_chunk)
    if chunks is None:
        chunks = list(_iter_chunks(src, dst, edge_chunk))
        cache[edge_chunk] = chunks
    return chunks


@dataclass
class _SrcBlockPlan:
    """Static per-source-block edge layout (host-side, built once)."""
    lo: int                 # first global source row of the block
    hi: int                 # one past the last
    src_local: np.ndarray   # int32 [E_b] source ids relative to lo
    dst: np.ndarray         # int32 [E_b] destination rows (sorted)
    _dev: dict = field(default_factory=dict, repr=False, compare=False)

    def dev_chunks(self, edge_chunk: int, cache: bool = True):
        return _dev_chunks(self.src_local, self.dst, edge_chunk,
                           self._dev if cache else None)


class StreamingAggregator:
    """Out-of-core CSR sum-aggregation: ``out[dst] = sum feats[src]``
    with ``feats`` in host RAM.

    Edges are grouped by source block at construction (static for the
    life of the graph, like the reference's partition-time layout) and
    the per-block index tables are uploaded to the device HERE, once —
    while their total bytes fit ``table_cache_bytes``; past that they
    upload transiently per call (O(E) resident index bytes would
    defeat the out-of-core tier at the scales it exists for).  Each
    ``__call__`` streams the feature blocks through the staging
    pool (block k+1's host copy + H2D under block k's scatter-add) and
    accumulates with a sorted segment scatter-add.  Memory on device:
    two feature blocks (the double buffer) + the ``[num_rows, F]``
    output + an edge-chunk transient.  This is the capability tier —
    the in-HBM impls in ``ops/aggregate.py`` are strictly faster when
    features fit.
    """

    def __init__(self, graph: Graph, block_rows: int = 65536,
                 edge_chunk: int = 1 << 20, prefetch: int = 1,
                 table_cache_bytes: int = TABLE_CACHE_BYTES):
        self.num_rows = graph.num_nodes
        self.block_rows = block_rows
        self.edge_chunk = edge_chunk
        self.pool = StagingPool(depth=prefetch)
        dst_all = graph.edge_dst()
        src_all = graph.col_idx
        # group edges by source block; within a block keep dst order
        # (stable sort) so the scatter-add sees sorted segment ids
        block_of = src_all // block_rows
        order = np.argsort(block_of, kind="stable")
        src_s, dst_s = src_all[order], dst_all[order]
        blocks_present = np.unique(block_of)
        self.plans: List[_SrcBlockPlan] = []
        starts = np.searchsorted(block_of[order], blocks_present,
                                 side="left")
        ends = np.searchsorted(block_of[order], blocks_present,
                               side="right")
        for b, lo_e, hi_e in zip(blocks_present, starts, ends):
            lo = int(b) * block_rows
            hi = min(lo + block_rows, self.num_rows)
            sl = src_s[lo_e:hi_e] - lo
            dl = dst_s[lo_e:hi_e]
            o = np.argsort(dl, kind="stable")
            self.plans.append(_SrcBlockPlan(
                lo=lo, hi=hi, src_local=sl[o].astype(np.int32),
                dst=dl[o].astype(np.int32)))
        # device-resident index tables, uploaded once at plan build —
        # but only when their total bytes fit the residency budget:
        # past it, calls fall back to transient per-chunk uploads
        # (this tier exists for graphs that do NOT fit on device)
        idx_bytes = sum(p.src_local.nbytes + p.dst.nbytes
                        for p in self.plans)
        self.cache_tables = idx_bytes <= table_cache_bytes
        if self.cache_tables:
            for plan in self.plans:
                plan.dev_chunks(edge_chunk)

    def __call__(self, feats_host: np.ndarray,
                 out_dtype=jnp.float32) -> jax.Array:
        F = feats_host.shape[1]
        out = jnp.zeros((self.num_rows, F), dtype=out_dtype)
        add = _block_scatter_add_jit
        stage = [functools.partial(_stage_block, feats_host,
                                   plan.lo, plan.hi)
                 for plan in self.plans]
        for plan, block in zip(self.plans, self.pool.stream(stage)):
            # chunk the block's edges to bound the [E, F] transient
            for sl, dl in plan.dev_chunks(self.edge_chunk,
                                          cache=self.cache_tables):
                out = add(out, block, sl, dl)
        return out


def _block_scatter_add(out, block, src_local, dst):
    g = block[src_local].astype(out.dtype)
    return out.at[dst].add(g, indices_are_sorted=True,
                           unique_indices=False)


# module-level jit: the dispatch cache survives across aggregator calls
_block_scatter_add_jit = jax.jit(_block_scatter_add, donate_argnums=(0,))


@dataclass
class _TilePlan:
    """Edges of one (dst block, src block) adjacency tile."""
    src_lo: int
    src_local: np.ndarray   # int32 [E_t] source ids relative to src_lo
    dst_local: np.ndarray   # int32 [E_t] dest ids relative to the dst
    #                         block start (sorted)
    _dev: dict = field(default_factory=dict, repr=False, compare=False)

    def dev_chunks(self, edge_chunk: int, cache: bool = True):
        return _dev_chunks(self.src_local, self.dst_local, edge_chunk,
                           self._dev if cache else None)


def build_tile_plans(graph: Graph, block_rows: int):
    """dst-block -> list of per-src-block edge tiles (host-side, once).
    The fully-out-of-core grouping: BOTH operands of each tile fit in
    one block, so neither the feature matrix nor the output ever has to
    be device-resident whole."""
    dst_all = graph.edge_dst()
    src_all = graph.col_idx
    if not src_all.size:
        return {}
    db = dst_all // block_rows
    sb = src_all // block_rows
    order = np.lexsort((sb, db))
    dst_s, src_s, db_s, sb_s = (dst_all[order], src_all[order],
                                db[order], sb[order])
    # tile boundaries in the lexsorted edge list
    key = db_s.astype(np.int64) * (sb.max() + 1 if sb.size else 1) + sb_s
    cut = np.flatnonzero(np.diff(key)) + 1
    starts = np.concatenate([[0], cut])
    ends = np.concatenate([cut, [key.shape[0]]])
    tiles: dict = {}
    for lo_e, hi_e in zip(starts, ends):
        d, s = int(db_s[lo_e]), int(sb_s[lo_e])
        sl = (src_s[lo_e:hi_e] - s * block_rows).astype(np.int32)
        dl = (dst_s[lo_e:hi_e] - d * block_rows).astype(np.int32)
        o = np.argsort(dl, kind="stable")
        tiles.setdefault(d, []).append(_TilePlan(
            src_lo=s * block_rows, src_local=sl[o], dst_local=dl[o]))
    return tiles


def aggregate_to_host(graph: Graph, feats_host: np.ndarray,
                      block_rows: int = 65536,
                      edge_chunk: int = 1 << 20,
                      tiles=None, prefetch: int = 1,
                      pool: Optional[StagingPool] = None) -> np.ndarray:
    """Fully out-of-core CSR sum-aggregation: both the feature matrix
    AND the result live in host RAM; the device holds one destination
    accumulator block + the double-buffered source feature blocks + an
    edge-chunk transient.  This is the complete form of the reference's
    stage-compute-writeback residency design (``types.cu:22-32``,
    ``load_task.cu:365-374``): *every* [V, F] tensor is host-resident,
    and the next tile's source block stages under the current tile's
    scatter-add.  :class:`StreamingAggregator` (device-resident output)
    is the faster tier when the output fits."""
    V = graph.num_nodes
    F = feats_host.shape[1]
    if tiles is None:
        tiles = build_tile_plans(graph, block_rows)
    if pool is None:
        pool = StagingPool(depth=prefetch)
    out = np.zeros((V, F), dtype=np.float32)
    work = [(d, t) for d in sorted(tiles) for t in tiles[d]]
    # index tables stay device-resident across calls only while they
    # fit the residency budget (stream_prefix_to_host reuses the same
    # tiles across its whole chain); past it they upload transiently —
    # this is the fully-out-of-core tier, where pinning O(E) index
    # bytes on device would defeat the point
    idx_bytes = sum(t.src_local.nbytes + t.dst_local.nbytes
                    for _, t in work)
    cache_tables = idx_bytes <= TABLE_CACHE_BYTES
    stage = [functools.partial(_stage_block, feats_host, t.src_lo,
                               t.src_lo + block_rows)
             for _, t in work]
    acc = None
    cur_d = None
    for (d, t), block in zip(work, pool.stream(stage)):
        if d != cur_d:
            if acc is not None:
                d_lo = cur_d * block_rows
                out[d_lo:d_lo + acc.shape[0]] = np.asarray(acc)
            cur_d = d
            rows = min(block_rows, V - d * block_rows)
            acc = jnp.zeros((rows, F), dtype=jnp.float32)
        for sl, dl in t.dev_chunks(edge_chunk, cache=cache_tables):
            acc = _block_scatter_add_jit(acc, block, sl, dl)
    if acc is not None:
        d_lo = cur_d * block_rows
        out[d_lo:d_lo + acc.shape[0]] = np.asarray(acc)
    return out


def _prefix_op_view(op) -> tuple:
    """``(kind, attrs)`` of a prefix op — accepts both the builder's
    ``_Op`` objects (the trainer's streamable_agg_head path) and the
    plain-dict descriptors the serve manifest persists
    (``roc_tpu/serve/propagation.py``), so BOTH consumers walk the
    identical numeric path below."""
    if isinstance(op, dict):
        return op["kind"], op
    return op.kind, op.attrs


def stream_prefix_to_host(graph: Graph, prefix_ops,
                          feats_host: np.ndarray,
                          block_rows: int = 65536,
                          prefetch: int = 1,
                          capture=None) -> np.ndarray:
    """Evaluate a parameter-free norm/aggregation prefix (the op list
    returned by ``Model.streamable_agg_head``, or its serialized dict
    form) with every [V, F] intermediate host-resident:
    ``indegree_norm`` is a host row scaling, ``scatter_gather``
    (SUM/AVG) runs through :func:`aggregate_to_host` (one staging pool
    reused across the whole chain).  Returns fp32; runs ONCE per
    training session — this is the SGC-style precompute (A_hat^k X),
    after which epochs touch only the streamed head.

    ``capture`` receives each post-op stage table: a plain list (or
    anything with ``.append``) keeps the fp32 arrays — the per-stage
    tables the serve tier's incremental invalidation needs
    (``serve/propagation.PropagationCache``) — while a CALLABLE is
    invoked with each stage instead, which is the quantized-export
    hook (``serve/quant.QuantizingCapture`` encodes each stage as it
    streams, so the >RAM export's host peak holds ONE fp32 stage, not
    all k).  Either way the sink receives an exclusively-owned array
    (see the no-defensive-copy note below).  ONE walk for the
    trainer's precompute and the serving table, so the two can never
    diverge numerically."""
    from ..models.builder import AGGR_AVG, AGGR_SUM
    from ..ops.norm import inv_sqrt_degree_np
    x = np.asarray(feats_host, dtype=np.float32)
    deg = np.asarray(graph.in_degree, dtype=np.float32)
    inv_sqrt = inv_sqrt_degree_np(graph.in_degree)[:, None]
    tiles = None
    pool = StagingPool(depth=prefetch)
    for op in prefix_ops:
        kind, attrs = _prefix_op_view(op)
        if kind == "indegree_norm":
            x = x * inv_sqrt
        elif kind == "scatter_gather":
            if tiles is None:
                tiles = build_tile_plans(graph, block_rows)
            x = aggregate_to_host(graph, x, block_rows, tiles=tiles,
                                  pool=pool)
            if attrs.get("aggr", AGGR_SUM) == AGGR_AVG:
                x = x / np.maximum(deg, 1.0)[:, None]
        elif kind == "fused_aggregate":
            # the fused norm -> sum -> norm [-> relu] op
            # (models/builder.py fuse_norm_aggregate), unrolled
            # host-side — this precompute runs once, so fusion buys
            # nothing here and exactness is what matters
            if tiles is None:
                tiles = build_tile_plans(graph, block_rows)
            x = aggregate_to_host(graph, x * inv_sqrt, block_rows,
                                  tiles=tiles, pool=pool) * inv_sqrt
            if attrs.get("activation", "none") != "none":
                np.maximum(x, 0.0, out=x)
        else:  # pragma: no cover - guarded by streamable_agg_head
            raise NotImplementedError(kind)
        if capture is not None:
            # no defensive copy: every branch above REBINDS x to a
            # fresh array (the fused relu's in-place np.maximum runs
            # before this append), so each captured stage is
            # exclusively owned — a copy would double the host peak
            # of the >HBM export this path exists for
            if callable(capture):
                capture(x)
            else:
                capture.append(x)
    return x


class StreamedHead:
    """First model layer (``dropout -> linear``) computed from
    host-resident features, with the matching streamed weight gradient.

    This is the *integrated* form of :func:`streamed_linear` — the
    piece that makes ``TrainConfig(features="host")`` a training path,
    not just a forward helper.  Forward: per 65536-row block, stage the
    block to HBM through the staging pool (block k+1's host copy + H2D
    issued under block k's compute), apply inverted dropout (key folded
    per block), matmul into the ``[V, H]`` output.  Backward: given the
    cotangent ``dY`` of the projected activations (from autodiff of the
    device-resident tail), ``dW = sum_b dropout(X_b)^T @ dY_b`` with
    the SAME per-block keys, so the recomputed masks match the forward
    exactly; the per-block ``dY`` slice happens INSIDE the jitted block
    fn (a dynamic-slice on the device-resident cotangent — no per-block
    host dispatch or copy).  The raw ``[V, F]`` feature matrix never
    resides on device, and each staged block's last reference drops as
    its block fn consumes it (the running ``dW`` is donated — the one
    buffer here that can alias), so the pool holds at most 2 block
    buffers regardless of V — the reference's ZC->FB staging loop
    (``types.cu:22-32``) with the FB cache slots replaced by the
    double-buffered block transients.

    ``prefetch`` is the pool depth: 0 = synchronous (bit-identical —
    the per-block ``fold_in`` keys do not depend on staging order).

    Note the RNG stream differs from the in-HBM path (one key per
    block instead of one for the whole matrix): both are valid
    inverted-dropout samplings; numerics match exactly in eval mode.
    """

    def __init__(self, rate: float, block_rows: int = 65536,
                 prefetch: int = 1):
        self.rate = float(rate)
        self.block_rows = block_rows
        self.pool = StagingPool(depth=prefetch)

    def _keys(self, key, n_blocks: int):
        if key is None:
            return [None] * n_blocks
        return [jax.random.fold_in(key, b) for b in range(n_blocks)]

    def _blocks(self, V: int):
        return [(lo, min(lo + self.block_rows, V))
                for lo in range(0, V, self.block_rows)]

    def _stage_fns(self, feats_host, blocks):
        return [functools.partial(_stage_block, feats_host, lo, hi)
                for lo, hi in blocks]

    def forward(self, weight: jax.Array, feats_host: np.ndarray,
                key: Optional[jax.Array], train: bool) -> jax.Array:
        """[V, H] projected activations, device-resident."""
        blocks = self._blocks(feats_host.shape[0])
        keys = self._keys(key, len(blocks))
        outs = []
        for k, x in zip(keys, self.pool.stream(
                self._stage_fns(feats_host, blocks))):
            outs.append(_head_fwd_block(x, weight, self.rate, k,
                                        train and key is not None))
        return jnp.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]

    def wgrad(self, feats_host: np.ndarray, dY: jax.Array,
              key: Optional[jax.Array], train: bool) -> jax.Array:
        """dL/dW for the head linear, streamed: recomputes each block's
        dropout with the same folded key as :meth:`forward`."""
        blocks = self._blocks(feats_host.shape[0])
        keys = self._keys(key, len(blocks))
        # accumulate across blocks in fp32 regardless of the compute
        # dtype (many-block bf16 accumulation would round away small
        # contributions); the caller casts to the master param dtype
        dW = jnp.zeros((feats_host.shape[1], dY.shape[1]),
                       dtype=jnp.float32)
        for (lo, hi), k, x in zip(blocks, keys, self.pool.stream(
                self._stage_fns(feats_host, blocks))):
            dW = _head_wgrad_block(dW, x, dY, lo, hi - lo, self.rate,
                                   k, train and key is not None)
        return dW


@functools.partial(jax.jit, static_argnames=("rate", "use_mask"))
def _head_fwd_block(x, weight, rate, key, use_mask):
    # dense.linear, not a bare @: the in-HBM path accumulates fp32 at
    # HIGHEST precision and the streamed path must match bit-for-bit
    # semantics (Model.streamable_head guarantees activation == NONE).
    # x (the staged [B, F] block) is deliberately NOT donated: no
    # output shares its shape, so donation could never alias — it
    # would only emit per-compile "donated buffers were not usable"
    # warnings; the buffer frees by refcount once this block fn
    # consumes it, which is what keeps the pool at 2 slots.
    from ..ops.dense import AC_MODE_NONE, dropout, linear
    x = x.astype(weight.dtype)
    d = dropout(x, rate if use_mask else 0.0, key, use_mask)
    return linear(d, weight, AC_MODE_NONE)


@functools.partial(jax.jit,
                   static_argnames=("rows", "rate", "use_mask"),
                   donate_argnums=(0,))
def _head_wgrad_block(dW, x, dY, lo, rows, rate, key, use_mask):
    # dY stays whole and device-resident; the per-block slice is a
    # dynamic-slice INSIDE the jit (one compile for the uniform blocks
    # + one for the tail — no per-block host-side slice dispatch).
    # dW (the running accumulator) is donated — it aliases the output
    # exactly; x cannot alias anything (see _head_fwd_block) and dY is
    # read by every block, so neither is.
    from ..ops.dense import dropout
    x = x.astype(dY.dtype)
    dy = jax.lax.dynamic_slice_in_dim(dY, lo, rows, axis=0)
    d = dropout(x, rate if use_mask else 0.0, key, use_mask)
    prec = (jax.lax.Precision.HIGHEST if d.dtype == jnp.float32
            else None)
    return dW + jax.lax.dot_general(
        d, dy, (((0,), (0,)), ((), ())), precision=prec,
        preferred_element_type=jnp.float32).astype(dW.dtype)
