"""Command-line driver: the reference's flag surface on the new stack.

Mirrors ``parse_input_args`` (``gnn.cc:114-179``) flag for flag —
``-lr``, ``-e/-epoch``, ``-dropout/-dr``, ``-decay/-wd``,
``-decay-rate``, ``-decay-step/-ds``, ``-file``, ``-seed``,
``-verbose/-v`` and the dash-separated ``-layers 602-256-41`` spec
(layers[0] = input dim, layers[-1] = classes) — plus the TPU-side knobs
the Legion low-level flags (``-ll:gpu`` etc.) used to carry: ``--parts``
(graph partitions = mesh size), ``--model`` (gcn/sage/gin), ``--impl``
(aggregation backend), ``--dtype``, ``--checkpoint``/``--resume``.

Run: ``python -m roc_tpu.train.cli -file data/reddit -layers 602-256-41
-lr 0.01 -decay 0.0001 -decay-rate 0.97 -dropout 0.5 -e 3000``
(cf. ``test.sh:8`` / ``example_run.sh:1``).  Without ``-file`` a
synthetic dataset is used (smoke-test mode).
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import List, Optional


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(
        prog="roc_tpu", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    # reference flags (gnn.cc:114-179); defaults from gnn.cc:30-41
    ap.add_argument("-lr", type=float, default=0.01, dest="lr")
    ap.add_argument("-e", "-epoch", type=int, default=200, dest="epochs")
    ap.add_argument("-dropout", "-dr", type=float, default=0.5,
                    dest="dropout")
    ap.add_argument("-decay", "-wd", type=float, default=0.05,
                    dest="weight_decay")
    ap.add_argument("-decay-rate", type=float, default=1.0,
                    dest="decay_rate")
    ap.add_argument("-decay-step", "-ds", type=int, default=100,
                    dest="decay_steps")
    ap.add_argument("-file", type=str, default=None, dest="file",
                    help="dataset prefix (<prefix>.lux / .feats.csv / "
                         ".label / .mask)")
    ap.add_argument("-layers", type=str, default="16-16-4",
                    help="dash-separated dims, e.g. 602-256-41")
    ap.add_argument("-seed", type=int, default=1)
    ap.add_argument("-verbose", "-v", action="store_true")
    # TPU-era flags
    ap.add_argument("--model",
                    choices=["gcn", "sage", "gin", "gat", "sgc",
                             "appnp", "gcn2"],
                    default="gcn")
    ap.add_argument("--heads", type=int, default=1,
                    help="attention heads for --model gat (hidden "
                         "dims must divide by it; output layer stays "
                         "single-head)")
    ap.add_argument("--hops", type=int, default=None,
                    help="for --model sgc/appnp: propagation depth k "
                         "(sgc: logits = softmax(S^k X W), default 2; "
                         "appnp: k teleport-anchored hops after the "
                         "MLP, default 10 — the papers' classic "
                         "settings)")
    ap.add_argument("--alpha", type=float, default=None,
                    help="for --model appnp/gcn2: teleport / initial-"
                         "residual strength (default 0.1)")
    ap.add_argument("--lam", type=float, default=None,
                    help="for --model gcn2: identity-mapping decay "
                         "(beta_l = log(lam/l + 1); default 0.5)")
    ap.add_argument("--learn-eps", action="store_true",
                    help="for --model gin: learnable per-layer "
                         "epsilon self-weight (zero-init GIN-0) "
                         "instead of the fixed self-add")
    ap.add_argument("--parts", type=int, default=1,
                    help="graph partitions == mesh devices (the "
                         "reference's numMachines*numGPUs)")
    ap.add_argument("--mesh", type=str, default="auto",
                    help="device mesh shape PxM (parts x model), "
                         "e.g. 2x4: P must equal --parts and M > 1 "
                         "feature-shards the params and Adam moments "
                         "over the model axis of the (parts, model) "
                         "2-D mesh (needs P*M devices); 'auto' "
                         "(default) = every device on the parts axis "
                         "— today's exact 1-D behavior")
    ap.add_argument("--impl", default="auto",
                    choices=["auto", "segment", "blocked", "scan", "ell",
                             "sectioned", "pallas", "bdense",
                             "flat_sum"],
                    help="aggregation backend; auto = 'sectioned' (the "
                         "source-sectioned fast-gather layout, measured "
                         "2.3x over 'ell' at Reddit scale) for graphs "
                         "past VMEM table size, 'flat_sum' (the uniform "
                         "width-8 single-scan layout — ONE compiled "
                         "scan program per feature width instead of "
                         "one per degree bucket) past the sectioned "
                         "window at >=20M edges, else 'ell'")
    ap.add_argument("--allow-slow-impl", action="store_true",
                    help="permit --impl pallas, the one-launch DMA ELL "
                         "kernel measured 8.4x SLOWER than the XLA "
                         "'ell' path on v5e (kernels/ell_spmm.py keeps "
                         "it as evidence); without this flag the "
                         "selection is rejected up front")
    ap.add_argument("--fuse", default="auto",
                    choices=["auto", "on", "off"],
                    help="fold norm -> aggregate -> norm [-> relu] "
                         "chains into one fused aggregation op with "
                         "table-baked D^-1/2 scales (exact linear "
                         "algebra; default auto = fuse whenever the "
                         "model has the chain)")
    ap.add_argument("--partition", default="auto",
                    choices=["greedy", "cost", "auto"],
                    help="distributed split-point selection: 'greedy' "
                         "= the reference's edge-count sweep "
                         "(gnn.cc:806-829), 'cost' = cost-balanced "
                         "minimax search over the partition cost "
                         "model's padded-shape surrogate "
                         "(core/costmodel.py), 'auto' (default) = "
                         "cost — never worse than greedy under the "
                         "model, strictly better on skewed graphs")
    ap.add_argument("--rebalance", action="store_true",
                    help="online load rebalancing (--parts > 1): fit "
                         "the per-partition cost model against "
                         "measured step times and repartition at "
                         "epoch boundaries when the predicted "
                         "max-shard gain exceeds 10%% (at most 2 "
                         "repartitions per run; numerics-preserving "
                         "under full-batch training)")
    ap.add_argument("--halo", default="gather",
                    choices=["gather", "ring"],
                    help="distributed halo exchange: one-shot "
                         "all_gather or ppermute ring (O(V/P) memory)")
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16", "mixed"],
                    help="float32 = the reference's pure-fp32 "
                         "semantics; bfloat16 = everything (incl. "
                         "params/Adam) in bf16; mixed = fp32 master "
                         "params + bf16 features/activations (halves "
                         "aggregation HBM traffic, MXU-native matmuls)")
    ap.add_argument("--memory", default="auto",
                    choices=["auto", "manual"],
                    help="auto (default): estimate per-device HBM and "
                         "pick halo/features/remat (core/memory.py), "
                         "echoing the decision; explicit --halo/"
                         "--features flags switch back to manual")
    ap.add_argument("--features", default="hbm",
                    choices=["hbm", "host"],
                    help="input-feature residency: device HBM, or host "
                         "RAM streamed through the first layer "
                         "(>HBM graphs, single device)")
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize activations in backward")
    ap.add_argument("--prefetch", default="auto",
                    help="streamed-tier staging-pool depth "
                         "(--features host): blocks the background "
                         "stager runs ahead of compute; 'auto' = 1 "
                         "(double-buffered — block k+1's host copy + "
                         "H2D transfer hide under block k's compute), "
                         "0 = synchronous (the parity/debug "
                         "reference).  Epoch records then carry "
                         "overlap_frac / h2d_wait_p50_ms "
                         "(python -m roc_tpu.report)")
    ap.add_argument("--head-chunk", default="auto",
                    help="chunked output head: evaluate the "
                         "classification-head linear as a scan over "
                         "this many vertex rows per block so its "
                         "compiled matmul is [block, C] instead of "
                         "[V_p, C] (bit-identical forward values; "
                         "dW matches to fp32 roundoff); 'auto' "
                         "(default) chunks at 65536 rows once the "
                         "local row count reaches 262144, 0 disables")
    ap.add_argument("--cache-min-secs", type=float, default=None,
                    help="persistent compile cache write threshold "
                         "(seconds): programs compiling faster are "
                         "not persisted.  Default: "
                         "$ROC_TPU_CACHE_MIN_SECS or 1.0; pass 0 to "
                         "persist every program (what `python -m "
                         "roc_tpu.prewarm` and the bench children do "
                         "— the 1.0 s default silently skips the "
                         "small per-block streamed-head programs)")
    ap.add_argument("--eval-every", type=int, default=5)
    ap.add_argument("--checkpoint", type=str, default=None,
                    help="save params+opt state here after training")
    ap.add_argument("--checkpoint-every", type=int, default=0,
                    help="also save every N epochs")
    ap.add_argument("--resume", type=str, default=None,
                    help="restore a checkpoint before training")
    ap.add_argument("--recovery", action="store_true",
                    help="checkpoint-restart recovery "
                         "(roc_tpu/resilience): train in checkpointed "
                         "rounds under a keep-last-3 rotation at the "
                         "--checkpoint PREFIX (v3 checkpoint "
                         "directories <prefix>.<epoch>/ with "
                         "per-process shard files and a committed "
                         "MANIFEST.json; legacy .npz checkpoints "
                         "still restore), resume from the "
                         "newest intact checkpoint on start — "
                         "re-invoking the identical command after ANY "
                         "crash continues the run, including onto a "
                         "different --parts (elastic restart) — and "
                         "retry numeric failures / watchdog stalls / "
                         "transient I/O errors from the last good "
                         "checkpoint (bounded by --max-retries).  "
                         "Arms the SIGTERM/SIGINT preemption handler; "
                         "exits 75 (restartable) on preemption")
    ap.add_argument("--max-retries", type=int, default=3,
                    help="recovery retry budget per failure streak "
                         "(--recovery; default 3)")
    ap.add_argument("--preempt-grace", type=float, default=None,
                    dest="preempt_grace",
                    help="arm the SIGTERM/SIGINT preemption handler "
                         "with this grace window in seconds (also "
                         "armed by --recovery, default 30): the first "
                         "signal finishes the in-flight epoch step, "
                         "writes an emergency checkpoint, and exits "
                         "75 (restartable); a second signal kills "
                         "immediately")
    ap.add_argument("--async-save", default="auto",
                    choices=["auto", "on", "off"],
                    dest="async_save",
                    help="asynchronous checkpointing (resilience/"
                         "async_save.py): the recovery rotation's "
                         "saves run CRC+write+commit on a background "
                         "saver thread (bounded queue depth 1, newer "
                         "snapshot supersedes a queued one) — the "
                         "step path pays only the finite guard + "
                         "host snapshot.  'auto' (default) = on when "
                         "single-process, off under multi-process "
                         "SPMD; emergency/preemption saves are "
                         "always flushed before exit")
    ap.add_argument("--fault", type=str, default=None,
                    help="fault-injection drill (resilience/"
                         "inject.py): arm ONE fault as "
                         "site:epoch[:proc] — sites nan_grads, "
                         "sigkill, sigterm, kill_in_save, "
                         "kill_in_async_save, shard_corrupt, "
                         "saver_stall, bitflip_checkpoint, "
                         "staging_io, stall_compile.  Equivalent "
                         "env: ROC_TPU_FAULT")
    ap.add_argument("--eval-only", action="store_true",
                    help="run one inference pass (the reference's "
                         "every-5th-epoch infer, gnn.cc:107-110, as a "
                         "standalone step — typically with --resume) "
                         "and exit")
    ap.add_argument("--save-logits", type=str, default=None,
                    help="write the [V, C] inference logits here "
                         "(.npy, float32, ORIGINAL vertex order even "
                         "under --reorder) after training/eval")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend")
    ap.add_argument("--no-compile-cache", action="store_true",
                    help="disable the persistent XLA compilation "
                         "cache (utils/compile_cache.py; default on — "
                         "repeat runs skip the 1-2 min Reddit-scale "
                         "compile)")
    ap.add_argument("--profile-dir", type=str, default=None,
                    help="write a jax.profiler trace of one epoch here")
    ap.add_argument("--metrics", type=str, default=None,
                    help="training-metrics JSONL path (one record per "
                         "eval: loss/accuracies, epoch_ms, eval_ms, "
                         "compile_ms, edges_per_s, tflops_per_s, mfu)")
    ap.add_argument("--events", type=str, default=None,
                    help="structured event-log JSONL path (roc_tpu/"
                         "obs): run manifest, resolve/plan decisions, "
                         "compile cost + modeled-vs-actual HBM, "
                         "per-phase epoch spans, stall heartbeats; "
                         "summarize with `python -m roc_tpu.report`. "
                         "Also settable via ROC_TPU_EVENTS")
    ap.add_argument("--reorder", default="none",
                    choices=["none", "bfs", "lpa"],
                    help="vertex relabeling for gather locality "
                         "(core/reorder.py): clusters neighborhoods "
                         "into narrow id ranges so the sectioned "
                         "layout pads less on community-structured "
                         "graphs ('lpa' = label-propagation "
                         "communities, the ordering --impl bdense "
                         "rides on); metrics are relabeling-invariant")
    return ap.parse_args(argv)


def main(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    from ..obs.events import emit, install_excepthook
    # crash flight recorder: an unhandled exception dumps the last
    # telemetry window (obs/events.py ring buffer) before the
    # traceback — dead runs stop taking their evidence with them
    install_excepthook()
    if args.events:
        # env too, so worker/child processes join the same artifact
        import os
        os.environ["ROC_TPU_EVENTS"] = args.events
        from ..obs.events import configure
        configure(jsonl_path=args.events)
    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    if not args.no_compile_cache:
        from ..utils.compile_cache import enable_compile_cache
        enable_compile_cache(min_compile_secs=args.cache_min_secs)
    from ..core.graph import load_dataset, synthetic_dataset
    from .trainer import TrainConfig, Trainer, resolve_dtypes
    from ..parallel.distributed import DistributedTrainer
    from ..utils.checkpoint import checkpoint_trainer, restore_trainer

    layers = [int(x) for x in args.layers.split("-")]
    if len(layers) < 2:
        print("error: -layers needs at least in-dim and classes",
              file=sys.stderr)
        return 2
    # flag validation BEFORE the (possibly minutes-long) dataset load
    if args.impl == "pallas" and not args.allow_slow_impl:
        # close the user-selectable footgun (VERDICT weakness #5): the
        # DMA ELL kernel is measured 8.4x slower than --impl ell on
        # v5e and exists as checked-in evidence, not a training path
        print("error: --impl pallas is the hand-written DMA ELL "
              "kernel, measured 8.4x SLOWER than --impl ell on v5e "
              "(kernels/ell_spmm.py records why); pass "
              "--allow-slow-impl to run it anyway", file=sys.stderr)
        return 2
    # ONE validator (train/trainer.py resolve_prefetch) so the CLI and
    # the trainer can never accept different --prefetch vocabularies
    from .trainer import resolve_head_chunk, resolve_prefetch
    try:
        resolve_prefetch(TrainConfig(prefetch=args.prefetch))
    except ValueError as e:
        print(f"error: --prefetch: {e}", file=sys.stderr)
        return 2
    # ONE validator (train/trainer.py resolve_head_chunk), same policy
    # as --prefetch: the CLI and the trainer share the vocabulary
    try:
        resolve_head_chunk(TrainConfig(head_chunk=args.head_chunk),
                           1 << 30)
    except ValueError as e:
        print(f"error: --head-chunk: {e}", file=sys.stderr)
        return 2
    # ONE validator (train/trainer.py resolve_mesh) again: the CLI,
    # both trainers, multihost, and the rigs share the PxM vocabulary
    from .trainer import resolve_mesh
    try:
        resolve_mesh(TrainConfig(mesh=args.mesh),
                     num_parts=max(args.parts, 1))
    except ValueError as e:
        print(f"error: --mesh: {e}", file=sys.stderr)
        return 2
    if args.rebalance and args.parts <= 1:
        print("error: --rebalance requires --parts > 1 (rebalancing "
              "moves partition boundaries over a device mesh)",
              file=sys.stderr)
        return 2
    if args.recovery and not args.checkpoint:
        print("error: --recovery needs --checkpoint PREFIX (the "
              "rotation writes <prefix>.<epoch>/ checkpoint "
              "directories there)", file=sys.stderr)
        return 2
    if args.max_retries < 0:
        print("error: --max-retries must be >= 0", file=sys.stderr)
        return 2
    if args.fault:
        # fail fast on a typo'd drill spec, before the dataset load
        from ..resilience import inject
        try:
            inject.parse(args.fault)
        except ValueError as e:
            print(f"error: --fault: {e}", file=sys.stderr)
            return 2
    if args.model != "gat" and args.heads != 1:
        print("error: --heads applies to --model gat only",
              file=sys.stderr)
        return 2
    if args.learn_eps and args.model != "gin":
        print("error: --learn-eps applies to --model gin only",
              file=sys.stderr)
        return 2
    if args.alpha is not None and args.model not in ("appnp", "gcn2"):
        # None sentinel: ANY explicit --alpha on a model without the
        # knob is the misuse this guard exists for, the default value
        # included
        print("error: --alpha applies to --model appnp/gcn2 only",
              file=sys.stderr)
        return 2
    if args.lam is not None and args.model != "gcn2":
        print("error: --lam applies to --model gcn2 only",
              file=sys.stderr)
        return 2
    if args.hops is not None and args.model not in ("sgc", "appnp"):
        # same sentinel policy as --alpha/--heads/--learn-eps: a
        # propagation depth on a fixed-depth model must fail, not be
        # silently discarded
        print("error: --hops applies to --model sgc/appnp only",
              file=sys.stderr)
        return 2
    if args.model in ("sgc", "appnp"):
        if args.hops is None:
            args.hops = 2 if args.model == "sgc" else 10
        if args.hops < 1:
            print("error: --hops must be >= 1", file=sys.stderr)
            return 2
    if args.model in ("appnp", "gcn2"):
        if args.alpha is None:
            args.alpha = 0.1
        if not 0.0 <= args.alpha <= 1.0:
            print("error: --alpha must be in [0, 1]", file=sys.stderr)
            return 2
    if args.model == "gcn2":
        if args.lam is None:
            args.lam = 0.5
        if args.lam <= 0.0:
            print("error: --lam must be > 0", file=sys.stderr)
            return 2
        # structural -layers checks up front (same policy as gat's
        # heads divisibility: fail BEFORE the dataset load, with the
        # clean exit-2 contract, not a build_gcn2 traceback after it)
        if len(layers) < 3:
            print("error: gcn2 needs at least one hidden layer "
                  "(F-H-C)", file=sys.stderr)
            return 2
        if any(h != layers[1] for h in layers[1:-1]):
            print(f"error: gcn2 hidden widths must all match (the "
                  f"initial residual adds H_0 into every layer), got "
                  f"{layers[1:-1]}", file=sys.stderr)
            return 2
    if args.model == "gat":
        if args.heads < 1:
            print("error: --heads must be >= 1", file=sys.stderr)
            return 2
        bad = [d for d in layers[1:-1] if d % args.heads]
        if bad:
            print(f"error: hidden dims {bad} not divisible by "
                  f"--heads {args.heads}", file=sys.stderr)
            return 2

    if args.file:
        ds = load_dataset(args.file, in_dim=layers[0],
                          num_classes=layers[-1])
    else:
        ds = synthetic_dataset(512, 8, in_dim=layers[0],
                               num_classes=layers[-1], seed=args.seed)
    perm = None
    if args.reorder != "none":
        from ..core.reorder import ORDERINGS, apply_vertex_order
        t0 = time.time()
        ds, perm = apply_vertex_order(
            ds, ORDERINGS[args.reorder](ds.graph),
            order_name=args.reorder)
        emit("plan", f"reorder={args.reorder} applied in "
             f"{time.time() - t0:.1f}s", reorder=args.reorder,
             reorder_s=round(time.time() - t0, 2))
    # config echo, like gnn.cc:48-60 (the structured run manifest is
    # emitted by the trainer once the config is RESOLVED)
    emit("run", f"dataset={ds.name} V={ds.graph.num_nodes} "
         f"E={ds.graph.num_edges} layers={layers} model={args.model} "
         f"lr={args.lr} wd={args.weight_decay} dropout={args.dropout} "
         f"decay={args.decay_rate}/{args.decay_steps} parts={args.parts} "
         f"mesh={args.mesh} impl={args.impl}")

    from ..models import model_builders
    build = model_builders()
    kwargs = {"heads": args.heads} if args.model == "gat" else {}
    if args.model == "gin" and args.learn_eps:
        kwargs["learn_eps"] = True
    if args.model in ("sgc", "appnp"):
        kwargs["k"] = args.hops
    if args.model in ("appnp", "gcn2"):
        kwargs["alpha"] = args.alpha
    if args.model == "gcn2":
        kwargs["lam"] = args.lam
    model = build[args.model](layers, dropout_rate=args.dropout,
                              **kwargs)
    dt, cdt = resolve_dtypes(args.dtype)
    memory = args.memory
    if memory == "auto" and (args.halo != "gather"
                             or args.features != "hbm" or args.remat):
        # explicit residency flags win over the autopilot
        memory = "manual"
    cfg = TrainConfig(
        learning_rate=args.lr, weight_decay=args.weight_decay,
        dropout_rate=args.dropout, decay_rate=args.decay_rate,
        decay_steps=args.decay_steps, epochs=args.epochs,
        seed=args.seed, eval_every=args.eval_every, verbose=True,
        aggr_impl=args.impl, aggr_fuse=args.fuse, halo=args.halo,
        memory=memory, features=args.features, remat=args.remat,
        prefetch=args.prefetch, partition=args.partition,
        rebalance=args.rebalance, head_chunk=args.head_chunk,
        cache_min_compile_secs=args.cache_min_secs,
        async_save=args.async_save, fault=args.fault, mesh=args.mesh,
        dtype=dt, compute_dtype=cdt, metrics_path=args.metrics)

    from ..obs.heartbeat import StallFailure
    from ..resilience import preempt
    from ..resilience.preempt import Preempted, RESTARTABLE_EXIT_CODE
    if args.recovery or args.preempt_grace is not None:
        preempt.install(args.preempt_grace
                        if args.preempt_grace is not None
                        else preempt.DEFAULT_GRACE_S)

    if args.halo == "ring" and args.parts <= 1:
        print("error: --halo ring requires --parts > 1 (the ring "
              "rotates shards over a device mesh)", file=sys.stderr)
        return 2
    try:
        if args.parts > 1:
            trainer = DistributedTrainer(model, ds, args.parts, cfg)
        else:
            trainer = Trainer(model, ds, cfg)
    except StallFailure as e:
        # a watchdog-promoted setup hang (dead multihost peer at the
        # DCN rendezvous, wedged first table build) is restartable —
        # a fresh process against a recovered fleet IS the retry
        emit("resilience", f"{e} during trainer setup — exiting "
             f"{RESTARTABLE_EXIT_CODE} (restartable)",
             kind="restartable_exit")
        return RESTARTABLE_EXIT_CODE

    if args.resume:
        restore_trainer(trainer, args.resume)
        emit("run", f"resumed from {args.resume} at epoch "
             f"{trainer.epoch}", epoch=trainer.epoch)

    def save_logits():
        if not args.save_logits:
            return
        import numpy as np
        logits = np.asarray(trainer.predict(), dtype=np.float32)
        if perm is not None:
            # rows are in reordered coordinates; new row i holds old
            # vertex perm[i] — scatter back to original order
            out = np.empty_like(logits)
            out[perm] = logits
            logits = out
        np.save(args.save_logits, logits)
        emit("run", f"logits [{logits.shape[0]}, {logits.shape[1]}] "
             f"saved to {args.save_logits}", path=args.save_logits)

    if args.eval_only:
        from .trainer import format_metrics
        m = trainer.evaluate()
        print(format_metrics(trainer.epoch, m))
        save_logits()
        return 0

    if args.profile_dir:
        trainer.train(epochs=1)  # compile outside the trace
        # phase spans route through jax.profiler.TraceAnnotation for
        # the traced epoch (utils/profiling.py EpochTimer.annotate),
        # so the XLA device trace carries the same named phases as
        # the host timeline lanes.  The CLI owns the toggle here: it
        # never sets TrainConfig.profile_dir (run_epoch_loop would
        # start a SECOND nested profiler trace), so the constructor's
        # annotate-arming path does not apply and the flag is scoped
        # to exactly the traced epoch
        trainer.timer.annotate = True
        try:
            with jax.profiler.trace(args.profile_dir):
                trainer.train(epochs=1)
        finally:
            trainer.timer.annotate = False
        emit("run", f"profile written to {args.profile_dir}",
             path=args.profile_dir)

    t0 = time.time()
    remaining = args.epochs - trainer.epoch
    try:
        if args.recovery:
            from ..resilience.recovery import (CheckpointRotation,
                                               train_with_recovery)
            from .trainer import resolve_async_save
            rotation = CheckpointRotation(
                args.checkpoint, keep=3,
                async_save=resolve_async_save(cfg))
            every = (args.checkpoint_every if args.checkpoint_every > 0
                     else max(args.eval_every, 1))
            train_with_recovery(trainer, args.epochs, rotation,
                                checkpoint_every=every,
                                max_retries=args.max_retries)
        elif args.checkpoint and args.checkpoint_every > 0:
            while trainer.epoch < args.epochs:
                n = min(args.checkpoint_every,
                        args.epochs - trainer.epoch)
                trainer.train(epochs=n)
                checkpoint_trainer(trainer, args.checkpoint)
        else:
            trainer.train(epochs=max(remaining, 0))
    except Preempted as e:
        # --recovery already wrote the emergency checkpoint through
        # its rotation; the plain path persists --checkpoint here
        # (the finite guard may refuse a poisoned state — still exit
        # restartable, the restart simply starts from whatever good
        # checkpoint exists)
        if not args.recovery and args.checkpoint:
            from ..resilience.recovery import NumericFailure
            try:
                checkpoint_trainer(trainer, args.checkpoint)
            except (NumericFailure, OSError) as nf:
                # a refused (poisoned) or unwritable emergency save
                # must not cost the restartable exit code — the
                # restart resumes from whatever good checkpoint exists
                emit("resilience", f"emergency checkpoint failed: "
                     f"{nf}", kind="preempt", epoch=trainer.epoch)
        emit("resilience", f"preempted at epoch {trainer.epoch} "
             f"({e}) — exiting {RESTARTABLE_EXIT_CODE} (restartable)",
             kind="restartable_exit", epoch=trainer.epoch)
        return RESTARTABLE_EXIT_CODE
    except StallFailure as e:
        # watchdog-promoted hang with nothing restored to retry from:
        # a fresh process (same command) IS the retry
        emit("resilience", f"{e} — exiting {RESTARTABLE_EXIT_CODE} "
             f"(restartable)", kind="restartable_exit",
             epoch=trainer.epoch)
        return RESTARTABLE_EXIT_CODE
    except OSError as e:
        if not args.recovery:
            raise
        emit("resilience", f"I/O failure {e!r} — exiting "
             f"{RESTARTABLE_EXIT_CODE} (restartable)",
             kind="restartable_exit", epoch=trainer.epoch)
        return RESTARTABLE_EXIT_CODE
    dt = time.time() - t0
    if remaining > 0:
        emit("run", f"{remaining} epochs in {dt:.1f}s "
             f"({1000.0 * dt / max(remaining, 1):.1f} ms/epoch)",
             epochs=remaining, wall_s=round(dt, 2))
    if args.checkpoint and not args.recovery:
        # under --recovery the rotation already holds the final state
        # (and --checkpoint is a prefix there, not a file)
        checkpoint_trainer(trainer, args.checkpoint)
        emit("run", f"checkpoint saved to {args.checkpoint}",
             path=args.checkpoint)
    save_logits()
    return 0


if __name__ == "__main__":
    sys.exit(main())
