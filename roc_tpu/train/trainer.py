"""Single-device training loop (the reference's ``top_level_task`` epoch
loop, ``gnn.cc:99-111``): per epoch — staircase lr decay, zero grads
(implicit: JAX recomputes), forward, backward, Adam update; every 5th
epoch an inference pass printing train loss + train/val/test accuracy in
the reference's format (``softmax_kernel.cu:141-152``).

The distributed loop lives in ``parallel/distributed.py``; this module is
the minimum end-to-end slice (BASELINE.md config 1/2 path).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, replace as dc_replace
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.graph import Dataset
from ..core.partition import padded_edge_list
from ..models.builder import GraphContext, Model
from ..obs.events import emit
from ..obs.metrics_registry import MetricsRegistry
from ..ops.loss import perf_metrics, summarize_metrics
from .optimizer import AdamConfig, adam_init, adam_update, decayed_lr


@dataclass
class TrainConfig:
    """Mirrors the reference ``Config`` struct + CLI defaults
    (``gnn.h:105-113``, ``gnn.cc:30-41``)."""
    learning_rate: float = 0.01
    weight_decay: float = 0.05
    dropout_rate: float = 0.5
    decay_rate: float = 1.0
    decay_steps: int = 100
    epochs: int = 200
    seed: int = 1
    eval_every: int = 5
    verbose: bool = True
    # segment|blocked|scan|ell|sectioned|pallas|auto ("auto" picks
    # sectioned in its measured node-count window, ell outside —
    # core/ell.py resolve_auto_impl)
    aggr_impl: str = "segment"
    chunk: int = 512
    # Aggregation fusion (auto|on|off): rewrite every norm ->
    # sum-aggregate -> norm [-> relu] chain into ONE fused op
    # (models/builder.py fuse_norm_aggregate) with the symmetric
    # D^-1/2 scales baked into the host-built tables where the layout
    # allows (ell/sectioned/bdense/ring — core/ell.py weight tables)
    # and fused pre/post scaling elsewhere.  Exact linear algebra:
    # forward and gradients match the unfused chain to fp32 tolerance
    # (tests/test_fused_agg.py).  "auto" fuses whenever the model has
    # a matching chain; "on" additionally echoes when nothing fused;
    # "off" keeps the reference's separate-op semantics.
    aggr_fuse: str = "auto"
    dtype: Any = jnp.float32
    # Mixed precision: when set (e.g. jnp.bfloat16), params + Adam
    # state stay in ``dtype`` (fp32 master weights) while features,
    # activations, and the aggregation run in ``compute_dtype`` —
    # halving HBM traffic on the bandwidth-bound aggregation and using
    # the MXU's native bf16 multiply path.  Params are cast inside the
    # step; gradients flow back through the cast as fp32 (bf16 shares
    # fp32's exponent range, so no loss scaling is needed; the loss
    # itself is always reduced in fp32, ops/loss.py).  None = compute
    # in ``dtype`` (the reference's pure-fp32 semantics,
    # linear_kernel.cu:76-80).
    compute_dtype: Optional[Any] = None
    # Halo exchange for the distributed step: "gather" (one-shot
    # all_gather, the reference's whole-region semantics) or "ring"
    # (ppermute rotation, O(V/P) peak memory; parallel/ring.py)
    halo: str = "gather"
    # Ring hop schedule (halo='ring'): True (default) issues each
    # hop's ppermute BEFORE the scatter-accumulate of the current
    # buffer — double-buffered, so XLA can overlap the collective
    # with compute.  False keeps the strictly sequential
    # compute-then-permute order (identical numerics; the
    # measurement/debug reference).
    ring_overlap: bool = True
    # Streamed-tier prefetch (features='host'): staging-pool depth —
    # how many feature blocks the background stager runs ahead of
    # compute (core/streaming.py StagingPool).  "auto" resolves to 1
    # (double-buffered: block k+1's host copy + H2D transfer run
    # under block k's compute, peak 2 live block buffers); 0 stages
    # synchronously (bit-identical results — the parity reference
    # the overlap_frac epoch metric compares against).
    prefetch: Any = "auto"
    # Symmetric-adjacency assumption for the aggregation backward (the
    # reference requires it, scattergather_kernel.cu:160-170).
    # None = verify host-side at setup (O(E log E)); True = trust the
    # caller (skip the check, e.g. huge graphs); False = force exact
    # autodiff gradients (directed graphs; slow for the blocked impl).
    symmetric: Optional[bool] = None
    # Observability (utils/profiling.py): profiler trace directory
    # (TensorBoard format; None = off) and metrics JSONL path.
    profile_dir: Optional[str] = None
    metrics_path: Optional[str] = None
    # Memory policy (the TPU analog of the reference's FB-cache +
    # zero-copy residency design, resourcemanager.h:30, types.cu:22-32):
    # - remat: rematerialize the forward pass in backward instead of
    #   saving activations — one extra forward of FLOPs for O(layers)
    #   less activation memory.
    # - features: "hbm" keeps the input features device-resident;
    #   "host" keeps them in host RAM and streams the first layer
    #   (dropout -> linear) through HBM in row blocks, forward AND
    #   weight-gradient (core/streaming.py StreamedHead).  Requires a
    #   streamable model head (Model.streamable_head).
    # - memory: "manual" uses halo/features/remat as given; "auto" runs
    #   core/memory.choose_memory_plan over the dataset/model shapes
    #   and overrides them with the first plan that fits hbm_bytes
    #   (None = detect), echoing the decision at setup.
    # - remat_policy: "full" recomputes everything; "save_aggregates"
    #   saves the scatter_gather outputs (the halo gather + CSR sum is
    #   by far the most expensive recompute: at products scale a full
    #   remat spends ~2/3 of its overhead re-aggregating) and
    #   recomputes only the cheap dense/elementwise ops.
    remat: bool = False
    remat_policy: str = "save_aggregates"
    features: str = "hbm"
    memory: str = "manual"
    hbm_bytes: Optional[int] = None
    # Sectioned-layout tuning (core/ell.py SectionedEll; raced by
    # benchmarks/micro_agg.py sectw:/sectu16 specs):
    # - sect_sub_w: neighbors per sub-row (each (row, section) pair
    #   pads to a multiple of it).
    # - sect_u16: uint16 section-local index tables (halves index
    #   bytes; caps section_rows at 65,535 so the dummy id fits).
    sect_sub_w: int = 8
    sect_u16: bool = False
    # - bdense_min_fill: edges per [128,128] tile below which the tile
    #   stays in the sectioned residual (aggr_impl='bdense')
    # - bdense_a_budget: uint8 A-table byte cap (densest blocks kept);
    #   the 2 GiB default was measured BINDING on the community
    #   substrate at Reddit scale — min_fill=32 with a 6 GiB budget
    #   lifts dense_frac 0.52 -> 0.81 (blockdense_occupancy.json
    #   planted16384_lpa_f32_b6g).  None disables the cap.
    bdense_min_fill: int = 64
    bdense_a_budget: Optional[int] = 2 << 30
    # - bdense_group: dense blocks reduced per output-tile update
    #   (pad_plan_groups).  >1 cuts the dominant [128, F] fp32 output
    #   read-modify-write traffic group-x for <= (group-1) zero-A
    #   padding blocks per occupied dst tile.
    bdense_group: int = 1
    # Graph partitioning (distributed only; core/costmodel.py):
    # - partition: "greedy" = the reference's edge-count sweep
    #   (gnn.cc:806-829 semantics), "cost" = the cost-balanced minimax
    #   split over the model's padded-shape surrogate, "auto" = cost
    #   (the cold-start weights ARE quantized edge balance, solved
    #   optimally — never worse than greedy under the model).
    # - rebalance: refit the per-partition cost model against measured
    #   step times at every eval boundary and repartition when the
    #   predicted max-shard gain exceeds rebalance_gain (hysteresis),
    #   at most rebalance_max times per run.  Full-batch training
    #   makes a repartition numerics-preserving; unchanged quantized
    #   shapes reuse the compiled step (no recompile).
    partition: str = "auto"
    rebalance: bool = False
    rebalance_gain: float = 0.10
    rebalance_max: int = 2
    # Chunked output head (the compile-wall fix for the classification
    # head): "auto" chunks the loss-op linear on the vertex axis in
    # HEAD_CHUNK_ROWS blocks once the local row count reaches
    # HEAD_CHUNK_AUTO_MIN_ROWS (below that the full-width matmul is
    # already small), an int >= 0 is a literal block size (0 = off).
    # Values and dX bit-identical either way; dW matches to fp32
    # roundoff (blockwise row-sum order, ops/dense.py linear_chunked);
    # the chunked head's compiled matmul is [block, C] instead of
    # [V_p, C], shape-stable across graph sizes.
    head_chunk: Any = "auto"
    # Persistent compile cache write threshold (utils/compile_cache.py
    # enable_compile_cache min_compile_secs): None defers to the
    # harness default (ROC_TPU_CACHE_MIN_SECS env, else 1.0 s).  The
    # 1.0 s default silently skips caching the many small per-block
    # streamed-head programs; the prewarm driver (utils/prewarm.py)
    # and the bench children pass 0.0 so EVERY program lands in the
    # cache.  Recorded in the run manifest; consumed by the harnesses
    # (CLI/bench) that enable the cache — trainers never touch the
    # cache themselves.
    cache_min_compile_secs: Optional[float] = None
    # Async checkpointing (resilience/async_save.py): 'auto' (default)
    # saves asynchronously when this job is single-process — the step
    # path pays only the finite guard + host snapshot while CRC +
    # shard write + manifest commit overlap the next epochs on the
    # saver thread; 'on'/'off' force it.  Multi-process 'auto'
    # resolves OFF: async coalescing decisions are timing-dependent
    # and cannot be assumed identical across SPMD processes (the
    # sharded save's commit barrier needs lockstep), so shared
    # rotations save synchronously unless forced.
    async_save: Any = "auto"
    # Fault injection (resilience/inject.py): arm ONE drill fault for
    # this process as "site:epoch[:proc]" (sites: nan_grads, sigkill,
    # sigterm, kill_in_save, bitflip_checkpoint, staging_io,
    # stall_compile).  None = no fault; the ROC_TPU_FAULT env var is
    # the equivalent out-of-band switch.  Each fault fires at most
    # once per process — the drill harness (tests/test_drills.py)
    # injects, restarts, and asserts the run still finishes.
    fault: Optional[str] = None
    # Device mesh shape "PxM" (parts x model) or "auto" (= all
    # devices on the parts axis — today's exact 1-D behavior; a
    # single-device Trainer resolves to 1x1).  model > 1 builds the
    # (parts, model) 2-D mesh: params + Adam moments live
    # model-sharded at rest (parallel.model_shard_spec picks the
    # feature dim), the streamed-head [V, H] handoff is pinned
    # model-sharded, and the 1-D shard_map step bodies are reused
    # unchanged with MODEL_AXIS as a GSPMD auto axis.  Validated by
    # resolve_mesh (the CLI's --mesh routes through it too).
    mesh: Any = "auto"


def resolve_dtypes(name: str):
    """CLI/benchmark dtype-mode string -> ``(dtype, compute_dtype)`` —
    the ONE place the mode names map to TrainConfig fields, so the CLI
    and the benchmarks can never train with different semantics for
    the same flag value."""
    if name == "float32":
        return jnp.float32, None
    if name == "bfloat16":
        return jnp.bfloat16, None
    if name == "mixed":
        return jnp.float32, jnp.bfloat16
    raise ValueError(f"unknown dtype mode {name!r}; expected "
                     "'float32', 'bfloat16', or 'mixed'")


def resolve_prefetch(config: TrainConfig) -> int:
    """``TrainConfig.prefetch`` -> staging-pool depth: 'auto' = 1 (the
    double-buffered default — one block ahead is enough to hide the
    host copy + H2D issue, and deeper pools only add live buffers);
    an int >= 0 is taken literally (0 = synchronous)."""
    p = config.prefetch
    if p == "auto":
        return 1
    try:
        depth = int(p)
    except (TypeError, ValueError):
        raise ValueError(f"unknown prefetch {p!r}; expected 'auto' or "
                         "an int >= 0") from None
    if depth < 0:
        raise ValueError(f"prefetch must be >= 0, got {depth}")
    return depth


# Chunked-head resolution constants: the block matches the streamed
# head's staging granularity (core/streaming.py StreamedHead
# block_rows — the machinery linear_chunked is the in-jit twin of);
# the auto threshold keeps small graphs on the plain matmul (a
# [262k, C] head is the scale where the full-width program starts
# mattering to compile size and the scan adds nothing below it).
HEAD_CHUNK_ROWS = 65_536
HEAD_CHUNK_AUTO_MIN_ROWS = 262_144


def resolve_head_chunk(config: TrainConfig, num_rows: int) -> int:
    """``TrainConfig.head_chunk`` -> the concrete block size the
    GraphContext carries (0 = unchunked).  ONE validator — the CLI
    routes --head-chunk through this same function.  'auto' chunks at
    :data:`HEAD_CHUNK_ROWS` once ``num_rows`` reaches
    :data:`HEAD_CHUNK_AUTO_MIN_ROWS`; an explicit block >= the row
    count degenerates to 0 (a single block would only add scan
    overhead)."""
    hc = config.head_chunk
    if hc == "auto":
        return (HEAD_CHUNK_ROWS
                if num_rows >= HEAD_CHUNK_AUTO_MIN_ROWS else 0)
    try:
        block = int(hc)
    except (TypeError, ValueError):
        raise ValueError(f"unknown head_chunk {hc!r}; expected 'auto' "
                         "or an int >= 0") from None
    if block < 0:
        raise ValueError(f"head_chunk must be >= 0, got {block}")
    return 0 if block >= num_rows else block


def resolve_async_save(config: TrainConfig) -> bool:
    """``TrainConfig.async_save`` -> the concrete saver mode the
    rotation is constructed with.  ONE validator — the CLI routes
    --async-save through this same function.  'auto' enables the
    async saver exactly when the job is single-process (see the
    config field's comment for why multi-process resolves off);
    'on'/'off' (or bools) are literal."""
    v = config.async_save
    if isinstance(v, bool):
        return v
    if v == "on":
        return True
    if v == "off":
        return False
    if v == "auto":
        import jax
        return jax.process_count() == 1
    raise ValueError(f"unknown async_save {v!r}; expected 'auto', "
                     "'on', or 'off'")


def resolve_partition(config: TrainConfig) -> str:
    """``TrainConfig.partition`` -> the concrete split method:
    'auto' resolves to 'cost' (cold-start weights are the quantized
    edge-balance prior, so the searched split is never worse than the
    greedy sweep under the model and usually strictly better on
    skewed graphs).  Unknown values raise — the CLI validates through
    this same function so the vocabularies can never diverge."""
    p = config.partition
    if p == "auto":
        return "cost"
    if p in ("greedy", "cost"):
        return p
    raise ValueError(f"unknown partition {p!r}; expected 'greedy', "
                     "'cost', or 'auto'")


def resolve_mesh(config: TrainConfig,
                 num_parts: Optional[int] = None,
                 num_devices: Optional[int] = None):
    """``TrainConfig.mesh`` -> the concrete ``(parts, model)`` shape.

    'auto' = ``(num_parts or 1, 1)`` — exactly today's 1-D layout (the
    degenerate all-parts shape of ``parallel.candidate_mesh_shapes``).
    A "PxM" string names both axes explicitly; a (p, m) tuple is taken
    literally.  ONE validator — the CLI routes --mesh through this
    same function, and both trainer constructors resolve through it,
    so the vocabularies can never diverge.  When ``num_parts`` is
    given (the DistributedTrainer's positional parts count), an
    explicit P must match it; when ``num_devices`` is given, p*m must
    fit."""
    v = config.mesh
    if v in (None, "auto"):
        p, m = (int(num_parts) if num_parts else 1), 1
    else:
        if isinstance(v, str):
            try:
                ps, ms = v.lower().split("x")
                p, m = int(ps), int(ms)
            except ValueError:
                raise ValueError(
                    f"unknown mesh {v!r}; expected 'auto' or 'PxM' "
                    "(e.g. '2x4')") from None
        else:
            try:
                p, m = (int(v[0]), int(v[1]))
            except (TypeError, ValueError, IndexError):
                raise ValueError(
                    f"unknown mesh {v!r}; expected 'auto', 'PxM', or "
                    "a (parts, model) pair") from None
        if p < 1 or m < 1:
            raise ValueError(f"mesh axes must be >= 1, got {p}x{m}")
        if num_parts is not None and p != int(num_parts):
            raise ValueError(
                f"mesh {p}x{m} names {p} parts but the trainer was "
                f"built with {num_parts} partitions — the parts axis "
                "IS the partition count")
    if num_devices is not None and p * m > int(num_devices):
        raise ValueError(
            f"mesh {p}x{m} needs {p * m} devices, have {num_devices}")
    return p, m


def compute_dtype_of(config: TrainConfig):
    """The activation/feature dtype: ``compute_dtype`` when set (mixed
    precision), else ``dtype``."""
    return (config.compute_dtype if config.compute_dtype is not None
            else config.dtype)


def cast_floats(tree, dtype):
    """Cast floating-point leaves to ``dtype``; integer leaves (masks,
    labels, index tables) pass through.  A no-op cast is left to XLA
    to elide."""
    return jax.tree_util.tree_map(
        lambda a: a.astype(dtype)
        if jnp.issubdtype(a.dtype, jnp.floating) else a, tree)


def remat_policy(config: TrainConfig):
    """jax.checkpoint policy for ``config.remat_policy``: None (full
    recompute) or save-named-aggregates (models/builder.py tags every
    scatter_gather output with checkpoint_name 'aggregate').  An
    unknown name raises — a typo must not silently change the memory
    footprint."""
    if config.remat_policy == "full":
        return None
    if config.remat_policy != "save_aggregates":
        raise ValueError(
            f"unknown remat_policy {config.remat_policy!r}; expected "
            "'save_aggregates' or 'full'")
    return jax.checkpoint_policies.save_only_these_names("aggregate")


# Attention models switch from the per-width bucket layout to the
# uniform flat8 layout past this edge count: the bucket path's
# Python-unrolled checkpointed scans (one per large width bucket,
# doubled by autodiff) pushed ogbn-products-scale remote compile past
# 40 min (VERDICT r3 missing #3); the flat8 path has ONE scan shape.
ATTN_FLAT8_MIN_EDGES = 20_000_000


def resolve_attention_impl(model, config: TrainConfig,
                           dataset=None) -> TrainConfig:
    """The ONE model-driven impl policy both trainers apply: models
    whose ops need the ELL tables — attention (edge softmax over one
    bucket row, ops/attention.py) and MAX/MIN aggregation (no
    sectioned/blocked/scan form) — get aggr_impl overridden to 'ell'
    with a startup echo, and halo='ring' rejected up front (the ring
    accumulator is additive; failing at jit-trace time would waste
    the whole ring-table build first).  Attention models on graphs
    past ``ATTN_FLAT8_MIN_EDGES`` route to the uniform 'attn_flat8'
    layout instead (compile size at scale; pass ``dataset`` to enable
    the scale check)."""
    why = ("attention" if model.uses_attention()
           else "MAX/MIN aggregation" if model.uses_max_aggregation()
           else None)
    if config.aggr_impl == "attn_flat8":
        # validate BEFORE the no-op return: a sum-only model with this
        # impl must fail here, not after the (expensive at 100M+
        # edges) table build inside a jit trace
        if why != "attention":
            raise NotImplementedError(
                "aggr_impl='attn_flat8' is the attention-only layout; "
                f"this model uses {why or 'sum aggregation'}")
    if why is None:
        return config
    if config.halo == "ring":
        raise NotImplementedError(
            f"{why} models are not supported with halo='ring' (the "
            "ring accumulator is additive; the whole neighborhood is "
            "needed per row); use halo='gather'")
    if config.aggr_impl == "attn_flat8":
        return config
    if why == "attention" and dataset is not None and \
            config.aggr_impl not in ("ell", "pallas") and \
            dataset.graph.num_edges >= ATTN_FLAT8_MIN_EDGES:
        import dataclasses
        emit("resolve",
             f"aggr_impl={config.aggr_impl!r} -> 'attn_flat8' "
             f"(attention at E={dataset.graph.num_edges:,}: uniform "
             "layout keeps the compile small)",
             requested=config.aggr_impl, resolved="attn_flat8")
        return dataclasses.replace(config, aggr_impl="attn_flat8")
    if config.aggr_impl in ("ell", "pallas"):
        return config
    if why == "MAX/MIN aggregation":
        if config.aggr_impl == "segment":
            # _max_fwd has a real segment path (jax.ops.segment_max) —
            # an explicitly requested 'segment' must not be silently
            # overridden (ADVICE r3); only the chunked-sum impls
            # (blocked/scan/pallas_csr/sectioned) lack a MAX form
            return config
        if config.aggr_impl == "flat_sum":
            # the uniform flat layout has a MAX twin
            # (ops/aggregate.py aggregate_flat_max) — an explicit
            # flat_sum stands
            return config
        from ..core.ell import FLAT_SUM_MIN_EDGES
        if dataset is not None and \
                dataset.graph.num_edges >= FLAT_SUM_MIN_EDGES:
            # large MAX graphs get the same uniform-scan consolidation
            # as the sum path: the ELL fallback's per-bucket unroll is
            # exactly the compile wall the flat layout removes
            import dataclasses
            emit("resolve",
                 f"aggr_impl={config.aggr_impl!r} -> 'flat_sum' "
                 f"(MAX/MIN at E={dataset.graph.num_edges:,}: uniform "
                 "layout keeps the compile small)",
                 requested=config.aggr_impl, resolved="flat_sum")
            return dataclasses.replace(config, aggr_impl="flat_sum")
    # echo unconditionally: this changes user-selected behavior, so it
    # must never be silent (ADVICE r3)
    emit("resolve", f"aggr_impl={config.aggr_impl!r} -> 'ell' "
         f"({why} model needs the ELL tables)",
         requested=config.aggr_impl, resolved="ell", why=why)
    import dataclasses
    return dataclasses.replace(config, aggr_impl="ell")


def resolve_fuse(model: Model, config: TrainConfig) -> Model:
    """``aggr_fuse`` resolution — ONE place for the rule (both
    trainers): 'off' leaves the model alone; 'auto'/'on' rewrite the
    fusable ``norm -> aggregate -> norm [-> relu]`` chains into fused
    ops (models/builder.py fuse_norm_aggregate).  Returns the model to
    train — the ORIGINAL object when nothing fused, so callers can
    compare identity.  Parameter names are untouched either way."""
    if config.aggr_fuse == "off":
        return model
    if config.aggr_fuse not in ("auto", "on"):
        raise ValueError(
            f"unknown aggr_fuse {config.aggr_fuse!r}; expected "
            "'auto', 'on', or 'off'")
    fused = model.fuse_norm_aggregate()
    # count NEWLY fused chains: an already-fused model re-entering the
    # resolve pass (resolve_config is idempotent — the program-space
    # auditor asserts it) has fused_aggregate ops but nothing left to
    # rewrite, and must come back as the SAME object with no re-echo
    n = fused.num_fused_aggregates() - model.num_fused_aggregates()
    if n <= 0:
        if config.aggr_fuse == "on" and not model.num_fused_aggregates():
            # an explicit request that changes nothing must say so
            emit("resolve", "aggr_fuse='on': no fusable "
                 "norm->aggregate->norm chain in this model — running "
                 "unfused", fuse=0)
        return model
    emit("resolve", f"aggr_fuse: {n} norm->aggregate->norm chain(s) "
         f"folded into the aggregation", console=config.verbose,
         fuse=n)
    return fused


def model_layer_dims(model: Model) -> List[int]:
    """The CLI-style layer spec (in-dim, linear out-dims...) recovered
    from the built model — the shape vocabulary core/memory.py's
    estimator speaks."""
    return [model._ops[0].dim] + [op.dim for op in model._ops
                                  if op.kind == "linear"]


def modeled_step_bytes(model: Model, dataset: Dataset,
                       config: TrainConfig,
                       num_parts: int = 1) -> int:
    """The memory model's peak-HBM estimate for the RESOLVED config —
    the number the compile observer (obs/compile_watch.py) holds
    against XLA's actual ``memory_analysis()`` so the planner and the
    residency can never silently disagree again (round-5 advisor).
    Computed for manual configs too: the autopilot only runs under
    ``memory='auto'``, but the modeled-vs-actual delta is evidence on
    every run."""
    from ..core.memory import charged_table_bytes, estimate_plan_bytes
    a_tab = charged_table_bytes(
        config.aggr_impl, model.uses_attention(),
        model.uses_max_aggregation(), config.bdense_a_budget)
    return estimate_plan_bytes(
        dataset.graph.num_nodes, dataset.graph.num_edges,
        model_layer_dims(model), num_parts=num_parts,
        dtype_bytes=jnp.dtype(compute_dtype_of(config)).itemsize,
        halo=config.halo if num_parts > 1 else "gather",
        features=config.features, remat=config.remat,
        remat_policy=config.remat_policy,
        extra_table_bytes=a_tab)


def resolve_symmetric(dataset: Dataset,
                      symmetric: Optional[bool]) -> bool:
    if symmetric is None:
        from ..core.graph import check_symmetric
        return check_symmetric(dataset.graph)
    return symmetric


def apply_memory_autopilot(model: Model, dataset: Dataset,
                           config: TrainConfig,
                           num_parts: int = 1) -> TrainConfig:
    """Resolve ``memory='auto'`` into concrete halo/features/remat via
    core/memory.choose_memory_plan, echoing the decision like the
    reference's startup config print (``gnn.cc:48-60``).  No-op for
    ``memory='manual'``."""
    if config.memory != "auto":
        return config
    import dataclasses
    from ..core.memory import charged_table_bytes, choose_memory_plan
    dims = model_layer_dims(model)
    # bdense keeps an A-table resident next to the model; the resolve
    # pass (resolve_config) runs aggr_impl='auto' (incl. the bdense
    # structure probe) BEFORE this autopilot, so a probe-selected
    # bdense is charged exactly like an explicit one — the planner and
    # the actual residency can no longer disagree by up to the A
    # budget (round-5 advisor).  Attention/MAX models never keep the
    # table: resolve_attention_impl (which runs AFTER the autopilot,
    # because it must see the chosen halo) rewrites their impl away
    # from bdense.  charged_table_bytes (core/memory.py) is the ONE
    # home for the charge rule.
    a_tab = charged_table_bytes(
        config.aggr_impl, model.uses_attention(),
        model.uses_max_aggregation(), config.bdense_a_budget)
    plan = choose_memory_plan(
        dataset.graph.num_nodes, dataset.graph.num_edges, dims,
        num_parts=num_parts,
        dtype_bytes=jnp.dtype(compute_dtype_of(config)).itemsize,
        hbm_bytes=config.hbm_bytes,
        head_streamable=(model.streamable_head() is not None
                         or model.streamable_agg_head() is not None),
        remat_policy=config.remat_policy,
        extra_table_bytes=a_tab)
    # a plan that doesn't fit echoes even with verbose off — running
    # anyway is a deliberate gamble the operator must see
    emit("plan", plan.echo(), console=config.verbose or not plan.fits,
         halo=plan.halo, features=plan.features, remat=plan.remat,
         fits=plan.fits, est_bytes=plan.est_bytes,
         budget_bytes=plan.budget_bytes, candidates=plan.candidates)
    return dataclasses.replace(
        config, memory="manual", features=plan.features,
        remat=plan.remat,
        halo=plan.halo if num_parts > 1 else config.halo)


def resolve_auto_impl_probed(graph, out_rows: Optional[int] = None, *,
                             bdense_min_fill: int = 64,
                             bdense_a_budget: Optional[int] = 2 << 30,
                             bdense_group: int = 1,
                             verbose: bool = False,
                             multiprocess: bool = False):
    """ONE home for the full ``aggr_impl='auto'`` rule: the measured
    sectioned/ell node-count window (core/ell.py resolve_auto_impl)
    plus the bdense STRUCTURE probe — when the vertex order
    concentrates enough edges into [128,128] tiles (community graphs
    after ``--reorder lpa``), the MXU block-dense path beats the
    row-rate-bound gather (measured 1.64-2.49x, BASELINE.md).  The
    probe is census-only (~a second at Reddit scale) and native-gated.

    Returns ``(impl, census)``; ``census`` is the reusable
    ``(keys, counts)`` when the probe selected 'bdense' over the SAME
    square tile space plan_blocks will use, else None.

    ``multiprocess=True`` skips the probe entirely: its outcome
    depends on per-host native availability, and every SPMD process
    must resolve the SAME impl — multi-process resolution stays pure
    arithmetic (set aggr_impl explicitly to use bdense there)."""
    from ..core.ell import resolve_auto_impl
    from ..ops import blockdense as _BD
    impl = resolve_auto_impl(graph.num_nodes, out_rows=out_rows,
                             num_edges=graph.num_edges)
    if impl == "flat_sum":
        # the compile-wall route (core/ell.py FLAT_SUM_MIN_EDGES):
        # outside sectioned's measured window at this edge count the
        # per-bucket ELL unroll would compile one program per degree
        # bucket — changes the execution path, so it echoes
        # unconditionally.  Pure arithmetic: multi-process safe.
        emit("resolve", f"aggr_impl='auto' -> 'flat_sum' "
             f"(E={graph.num_edges:,} past the sectioned window: ONE "
             f"uniform scan program instead of one per degree bucket)",
             resolved="flat_sum", num_edges=int(graph.num_edges))
        return impl, None
    if (impl != "sectioned" or multiprocess
            or graph.num_edges < _BD.BDENSE_AUTO_MIN_EDGES):
        return impl, None
    probe = _BD.probe_dense_frac(
        graph.row_ptr, graph.col_idx, graph.num_nodes,
        min_fill=bdense_min_fill, a_budget_bytes=bdense_a_budget,
        group=bdense_group, return_census=True)
    if probe is None:
        return impl, None
    frac, census = probe
    if frac >= _BD.BDENSE_AUTO_MIN_FRAC:
        # changes the execution path — echoes unconditionally
        emit("resolve", f"aggr_impl='auto' -> 'bdense' (census: "
             f"{frac:.0%} of edges on dense tiles >= "
             f"{_BD.BDENSE_AUTO_MIN_FRAC:.0%})",
             resolved="bdense", dense_frac=round(float(frac), 4))
        return "bdense", census
    emit("resolve", f"auto bdense probe: dense_frac {frac:.1%} < "
         f"{_BD.BDENSE_AUTO_MIN_FRAC:.0%} — staying sectioned",
         console=verbose, resolved=impl,
         dense_frac=round(float(frac), 4))
    return impl, None


def resolve_auto_impl_early(model: Model, config: TrainConfig, graph,
                            out_rows: Optional[int] = None,
                            multiprocess: bool = False):
    """``aggr_impl='auto'`` resolution shared by BOTH trainer
    constructors — ONE home for the rule: the measured window split +
    bdense structure probe run BEFORE the memory autopilot, so a
    probe-selected bdense A-table is charged into the memory plan and
    the remat downgrade applies (round-5 advisor).  Attention/MAX
    models skip (resolve_attention_impl rewrites their impl anyway
    and they never keep the A-table); ``features='host'`` skips (its
    graph tables may never be built — the placeholder/late path
    resolves lazily, and paying the ~1 s census for it would be pure
    startup cost).  Returns ``(config, census)``."""
    if config.aggr_impl != "auto" or config.features == "host" \
            or model.uses_attention() or model.uses_max_aggregation():
        return config, None
    impl, census = resolve_auto_impl_probed(
        graph, out_rows=out_rows,
        bdense_min_fill=config.bdense_min_fill,
        bdense_a_budget=config.bdense_a_budget,
        bdense_group=config.bdense_group,
        verbose=config.verbose,
        multiprocess=multiprocess)
    return dc_replace(config, aggr_impl=impl), census


def resolve_config(model: Model, dataset: Dataset, config: TrainConfig,
                   num_parts: int = 1, multiprocess: bool = False):
    """THE config resolve pass — fuse rewrite, ``aggr_impl='auto'``
    (incl. the bdense structure probe), memory autopilot, attention
    impl — in the ONE order that makes the memory plan honest: the
    probe runs first so an auto→bdense outcome re-enters
    ``choose_memory_plan`` with the A-budget charged
    (``core/memory.charged_table_bytes``), and the attention rewrite
    runs last because it must see the chosen halo.  Shared by BOTH
    trainer constructors and the program-space auditor
    (``analysis/programspace.py``) so the statically enumerated
    program space and the programs the trainers actually build can
    never diverge at the resolve layer.

    Idempotent by construction: a resolved config re-entering this
    pass is unchanged (fuse finds no new chains on a fused model,
    ``memory`` is already 'manual', ``aggr_impl`` concrete), so
    re-resolving yields the identical program-key set — the auditor
    asserts exactly that (tests/test_programspace.py).

    Returns ``(model, config, bd_census)``."""
    model = resolve_fuse(model, config)
    out_rows = (-(-dataset.graph.num_nodes // num_parts)
                if num_parts > 1 else None)
    config, bd_census = resolve_auto_impl_early(
        model, config, dataset.graph, out_rows=out_rows,
        multiprocess=multiprocess)
    config = apply_memory_autopilot(model, dataset, config,
                                    num_parts=num_parts)
    config = resolve_attention_impl(model, config, dataset)
    return model, config, bd_census


def make_graph_context(dataset: Dataset, aggr_impl: str = "segment",
                       chunk: int = 512,
                       symmetric: Optional[bool] = None,
                       sect_sub_w: int = 8,
                       sect_u16: bool = False,
                       bdense_min_fill: int = 64,
                       bdense_a_budget: Optional[int] = 2 << 30,
                       bdense_group: int = 1,
                       verbose: bool = False,
                       fuse: bool = False,
                       bd_census=None,
                       head_chunk: int = 0) -> GraphContext:
    """Single-device GraphContext: edges padded to the chunk multiple,
    dummy source id == num_nodes (the appended zero row).
    ``sect_sub_w``/``sect_u16`` tune the sectioned layout and
    ``bdense_min_fill`` the block-dense split (TrainConfig fields of
    the same names); ``verbose`` gates the informational echoes (the
    impl-override ones stay unconditional).

    ``fuse=True`` additionally bakes the symmetric ``D^-1/2`` scales
    into the tables (fused-aggregation weight tables / bdense tile
    scales) for models rewritten by ``Model.fuse_norm_aggregate``;
    ``bd_census`` reuses a probe census from an earlier
    :func:`resolve_auto_impl_probed` call (the trainers resolve
    'auto' before the memory autopilot and pass it through)."""
    g = dataset.graph
    if aggr_impl == "auto":
        aggr_impl, bd_census = resolve_auto_impl_probed(
            g, bdense_min_fill=bdense_min_fill,
            bdense_a_budget=bdense_a_budget,
            bdense_group=bdense_group, verbose=verbose)
    d_np = None
    if fuse:
        from ..ops.norm import inv_sqrt_degree_np
        d_np = inv_sqrt_degree_np(g.in_degree)
    ell_idx: tuple = ()
    ell_row_pos = None
    sect_idx: tuple = ()
    sect_sub_dst: tuple = ()
    sect_meta: tuple = ()
    flat8_idx = flat8_dst = flat8_w = None
    bd_a = bd_src = bd_dst = None
    bd_vpad = 0
    ell_w: tuple = ()
    sect_w: tuple = ()
    bd_scale: tuple = ()
    if aggr_impl in ("ell", "pallas", "sectioned", "attn_flat8",
                     "flat_sum", "bdense"):
        # these paths never read the flat edge arrays — don't upload
        # two [E] int32 tensors (~920 MB at Reddit scale) they'd ignore
        edge_src = np.zeros(1, dtype=np.int32)
        edge_dst = np.zeros(1, dtype=np.int32)
    else:
        edge_src, edge_dst = padded_edge_list(g, multiple=chunk)
    ell_row_id: tuple = ()
    if aggr_impl in ("ell", "pallas"):
        # both consume the degree-bucketed ELL layout; "pallas" runs it
        # through the one-launch DMA kernel (kernels/ell_spmm.py)
        from ..core.ell import ell_from_graph
        table = ell_from_graph(g.row_ptr, g.col_idx, g.num_nodes)
        ell_idx = tuple(jnp.asarray(a[0]) for a in table.idx)
        ell_row_pos = jnp.asarray(table.row_pos[0])
        ell_row_id = tuple(jnp.asarray(a[0]) for a in table.row_id)
        if fuse and aggr_impl == "ell":
            # 'pallas' derives d in-trace instead (the fused kernel
            # route scales rows, not table entries)
            from ..core.ell import ell_weight_tables
            ell_w = tuple(
                jnp.asarray(w[0]) for w in ell_weight_tables(
                    table, d_np[None, :], d_np))
    elif aggr_impl == "sectioned":
        from ..core.ell import default_section_rows, sectioned_from_graph
        sect = sectioned_from_graph(
            g.row_ptr, g.col_idx, g.num_nodes,
            section_rows=default_section_rows(sect_u16),
            sub_w=sect_sub_w)
        if sect_u16:
            sect = sect.with_idx_dtype(np.uint16)
        sect_idx, sect_sub_dst, sect_meta = sect.as_jax()
        if fuse:
            sect_w = tuple(jnp.asarray(w)
                           for w in sect.weight_tables(d_np, d_np))
    elif aggr_impl == "bdense":
        # block-dense MXU aggregation: dense [128,128] adjacency tiles
        # as uint8 multiplicity tables, scattered residual through the
        # sectioned gather (ops/blockdense.py — wins when the vertex
        # order concentrates edges into tiles; the occupancy echo
        # makes a mis-fit choice visible)
        from ..core.ell import default_section_rows, sectioned_from_graph
        from ..ops.blockdense import BLOCK, plan_blocks_packed
        plan = plan_blocks_packed(g.row_ptr, g.col_idx, g.num_nodes,
                                  min_fill=bdense_min_fill,
                                  a_budget_bytes=bdense_a_budget,
                                  group=bdense_group,
                                  census=bd_census)
        packed = plan.a_blocks.shape[-1] == BLOCK // 2
        occ = plan.occupancy()
        if plan.n_blocks:
            emit("plan", f"bdense plan: {occ['n_blocks']} blocks, "
                 f"fill {occ['mean_fill']}, dense "
                 f"{occ['dense_frac']:.0%} (residual "
                 f"{1 - occ['dense_frac']:.0%} via sectioned"
                 f"{', A u4-packed' if packed else ''})",
                 console=verbose, packed=packed, **occ)
            bd_a = jnp.asarray(plan.a_blocks)
            bd_src = jnp.asarray(plan.src_blk)
            bd_dst = jnp.asarray(plan.dst_blk)
            bd_vpad = plan.vpad
        else:
            # no tile qualifies: running the zero-block kernel every
            # step would be pure overhead — this changes the effective
            # execution path, so it echoes unconditionally
            emit("plan", f"bdense: no [128,128] tile reaches min_fill="
                 f"{bdense_min_fill} on this graph/order — running "
                 f"the sectioned residual only", **occ)
        if fuse:
            # in-register tile scales (ops/blockdense.py scale_dst/
            # scale_src) — the integer A-table stays intact
            dd = np.zeros(plan.vpad, np.float32)
            dd[:g.num_nodes] = d_np
            ds = np.zeros(plan.src_vpad, np.float32)
            ds[:g.num_nodes] = d_np
            bd_scale = (jnp.asarray(dd), jnp.asarray(ds))
        if plan.res_col.shape[0]:
            # same tuning knobs as the 'sectioned' branch — bdense's
            # residual must not silently drop user-selected config
            sect = sectioned_from_graph(
                plan.res_row_ptr, plan.res_col, g.num_nodes,
                section_rows=default_section_rows(sect_u16),
                sub_w=sect_sub_w)
            if sect_u16:
                sect = sect.with_idx_dtype(np.uint16)
            sect_idx, sect_sub_dst, sect_meta = sect.as_jax()
            if fuse:
                sect_w = tuple(jnp.asarray(w)
                               for w in sect.weight_tables(d_np, d_np))
    elif aggr_impl in ("attn_flat8", "flat_sum"):
        # the uniform flat layout: ONE section spanning all sources
        # (global ids, dummy == num_nodes == the appended zero row),
        # sub-rows of a row consecutive/ascending — compile size
        # independent of the degree distribution.  Two consumers of
        # the same tables: gat_aggregate_flat8 (attention) and
        # aggregate_flat_sum/_max (the sum/MAX consolidation).
        # FLAT_SEG_ROWS bounds the per-chunk transient [seg, 8, F] at
        # 64 MiB for F=256 fp32.
        from ..core.ell import flat_sum_from_graph
        sect = flat_sum_from_graph(g.row_ptr, g.col_idx, g.num_nodes)
        flat8_idx = jnp.asarray(sect.idx[0])
        flat8_dst = jnp.asarray(sect.sub_dst[0])
        if fuse and aggr_impl == "flat_sum":
            # baked D^-1/2 A D^-1/2 entries of the single section —
            # zero runtime normalization on the fused flat path
            flat8_w = jnp.asarray(
                sect.weight_tables(d_np, d_np)[0])
    return GraphContext(
        edge_src=jnp.asarray(edge_src),
        edge_dst=jnp.asarray(edge_dst),
        in_degree=jnp.asarray(g.in_degree),
        num_rows=g.num_nodes,
        gathered_rows=g.num_nodes,
        aggr_impl=aggr_impl,
        chunk=chunk,
        symmetric=resolve_symmetric(dataset, symmetric),
        ell_idx=ell_idx,
        ell_row_pos=ell_row_pos,
        ell_row_id=ell_row_id,
        sect_idx=sect_idx,
        sect_sub_dst=sect_sub_dst,
        sect_meta=sect_meta,
        flat8_idx=flat8_idx,
        flat8_dst=flat8_dst,
        flat8_w=flat8_w,
        head_chunk=head_chunk,
        bd_a=bd_a,
        bd_src=bd_src,
        bd_dst=bd_dst,
        bd_vpad=bd_vpad,
        bd_group=bdense_group if bd_a is not None else 1,
        ell_w=ell_w,
        sect_w=sect_w,
        bd_scale=bd_scale,
    )


class Trainer:
    """Owns params + optimizer state and the jitted step functions."""

    def __init__(self, model: Model, dataset: Dataset,
                 config: TrainConfig = TrainConfig()):
        model, config, bd_census = resolve_config(model, dataset,
                                                  config)
        self.model = model
        self.config = config
        self.compute = compute_dtype_of(config)
        self.epoch = 0
        # observability: edge count for edges/sec and the memory
        # model's estimate the compile observer checks XLA against
        self._obs_edges = int(dataset.graph.num_edges)
        self._modeled_bytes = modeled_step_bytes(model, dataset, config)
        # dataset identity for the checkpoint config fingerprint
        # (utils/checkpoint.trainer_fingerprint strict half)
        self._fp_dataset = {"V": int(dataset.graph.num_nodes),
                            "E": int(dataset.graph.num_edges)}
        self.labels = jnp.asarray(dataset.labels)
        self.mask = jnp.asarray(dataset.mask)
        key = jax.random.PRNGKey(config.seed)
        self.key, init_key = jax.random.split(key)
        self.params = model.init_params(init_key, dtype=config.dtype)
        self.opt_state = adam_init(self.params)
        self.adam_cfg = AdamConfig(weight_decay=config.weight_decay)
        # (parts, model) mesh knob: a single-device Trainer hosts only
        # the model axis (parts is always 1 here — partitioning is the
        # DistributedTrainer's job).  model > 1 places params + Adam
        # moments model-sharded at rest (put_replicated picks the dim
        # via parallel.model_shard_spec); the plain jitted steps then
        # inherit the layout through GSPMD (computation follows data),
        # and the streamed-head [V, H] handoff is pinned via
        # _pin_stream.
        _, self._mesh_model = resolve_mesh(
            config, num_parts=1, num_devices=len(jax.devices()))
        self.mesh = None
        if self._mesh_model > 1:
            from ..parallel.distributed import make_mesh, put_replicated
            self.mesh = make_mesh(1, model=self._mesh_model)
            self.params = put_replicated(self.params, self.mesh)
            self.opt_state = put_replicated(self.opt_state, self.mesh)
        self._head = None
        self._head_chunk = resolve_head_chunk(
            config, dataset.graph.num_nodes)
        if config.features == "host":
            # host-resident features streamed through the first layer
            # (the reference's ZC tier, types.cu:22-32)
            head = model.streamable_head()
            prefix_ops = None
            if head is None:
                # second shape the tier serves: a parameter-free
                # aggregation prefix (SGC family) evaluated ONCE fully
                # out-of-core, then the same streamed dropout/linear
                agg = model.streamable_agg_head()
                if agg is None:
                    raise NotImplementedError(
                        "features='host' needs a streamable model head "
                        "(input -> dropout -> linear, Model."
                        "streamable_head) or an aggregation-prefix "
                        "head (norm/aggregate chain -> dropout -> "
                        "linear, Model.streamable_agg_head).  This "
                        "model's first layer consumes raw features "
                        "elsewhere — use features='hbm', or partition "
                        "with --parts/halo='ring' to shrink per-device "
                        "residency")
                (prefix_ops, rate, self._head_param,
                 self._tail_model) = agg
            else:
                rate, self._head_param, self._tail_model = head
            from ..core.streaming import StreamedHead
            depth = resolve_prefetch(config)
            self._head = StreamedHead(rate, prefetch=depth)
            feats_np = np.asarray(dataset.features)
            if prefix_ops is not None:
                from ..core.streaming import stream_prefix_to_host
                feats_np = stream_prefix_to_host(
                    dataset.graph, prefix_ops, feats_np,
                    prefetch=depth)
            # host copy in the COMPUTE dtype (ml_dtypes bf16 under
            # mixed): device_put then ships 2-byte blocks — the
            # host-link transfer is this tier's dominant per-epoch
            # cost, so staging fp32 and casting on device would
            # forfeit half the mode's bandwidth win
            self.feats_host = np.ascontiguousarray(
                feats_np.astype(jnp.dtype(self.compute), copy=False))
            self.feats = None
            from ..obs.compile_watch import ObservedJit
            # y (arg 1) is donated: the projected [V, H] activation is
            # rebuilt by the streamed head every step and never read
            # after this call — undonated it doubled its residency
            # across the tail (found by roc-lint jaxpr-non-donated)
            self._tail_grad = ObservedJit(
                self._tail_grad_impl, name="tail_grad",
                donate_argnums=(1,),
                modeled_bytes=self._modeled_bytes,
                verbose=config.verbose)
            self._tail_eval = ObservedJit(self._tail_eval_impl,
                                          name="tail_eval",
                                          verbose=config.verbose)
            # grads (arg 2) are donated too: they are rebuilt every
            # step and never read after the update — undonated they'd
            # hold a param-sized buffer alive across the whole apply
            # (found by roc-lint jaxpr-non-donated)
            self._apply_update = ObservedJit(self._apply_update_impl,
                                             name="apply_update",
                                             donate_argnums=(0, 1, 2),
                                             verbose=config.verbose)
        else:
            self.feats = jnp.asarray(dataset.features,
                                     dtype=self.compute)
        if self._head is not None and not any(
                op.kind in ("scatter_gather", "gat", "fused_aggregate")
                for op in self._tail_model._ops):
            # the model's whole graph part ran in the host-side
            # precompute (SGC): don't build O(E) tables nobody reads
            from ..models.builder import GraphContext
            g = dataset.graph
            self.gctx = GraphContext(
                edge_src=jnp.zeros(1, jnp.int32),
                edge_dst=jnp.zeros(1, jnp.int32),
                in_degree=jnp.asarray(g.in_degree),
                num_rows=g.num_nodes, gathered_rows=g.num_nodes,
                aggr_impl="segment", chunk=config.chunk,
                head_chunk=self._head_chunk,
                # only the scatter_gather VJP reads symmetric, and this
                # branch is taken only when the tail has none — a
                # constant avoids check_symmetric's O(E log E) sort
                symmetric=True)
        else:
            self.gctx = make_graph_context(
                dataset, config.aggr_impl, config.chunk,
                symmetric=config.symmetric,
                sect_sub_w=config.sect_sub_w,
                sect_u16=config.sect_u16,
                bdense_min_fill=config.bdense_min_fill,
                bdense_a_budget=config.bdense_a_budget,
                bdense_group=config.bdense_group,
                verbose=config.verbose,
                fuse=model.num_fused_aggregates() > 0,
                bd_census=bd_census,
                head_chunk=self._head_chunk)
            if config.aggr_impl == "auto":
                # attention/MAX models reach here with 'auto' already
                # rewritten by resolve_attention_impl; any other
                # residue resolves inside make_graph_context — reflect
                # it so artifacts record what actually runs
                self.config = dc_replace(self.config,
                                         aggr_impl=self.gctx.aggr_impl)
        # Dataset tensors are jitted *arguments*, not closure captures:
        # capturing them would embed a second copy of the feature matrix
        # as an executable constant and recompile per Trainer instance
        # (the Reddit feature matrix alone is ~560 MB).  Only params +
        # opt state are donated — the data args are reused every step.
        # ObservedJit records lower/compile wall time + XLA cost/memory
        # introspection on the first call (obs/compile_watch.py).
        from ..obs.compile_watch import ObservedJit
        self._train_step = ObservedJit(self._train_step_impl,
                                       name="train_step",
                                       donate_argnums=(0, 1),
                                       modeled_bytes=self._modeled_bytes,
                                       verbose=config.verbose)
        # eval and predict share ONE compiled program: the eval step
        # returns (metrics, logits) — the logits already exist inside
        # the step, so outputting them costs one [V, C] buffer write
        # per eval while removing a whole compiled program from every
        # config's space (program-space consolidation, ISSUE 7;
        # evaluate() fetches only the metrics leaf)
        self._eval_step = ObservedJit(self._eval_step_impl,
                                      name="eval_step",
                                      verbose=config.verbose)
        from ..obs.manifest import run_manifest
        run_manifest(config=self.config, dataset=dataset, model=model,
                     extra={"modeled_step_bytes": self._modeled_bytes},
                     console=config.verbose)
        from ..utils.profiling import EpochTimer, MetricsLog
        # annotate=True routes every phase span through
        # jax.profiler.TraceAnnotation so --profile-dir device
        # traces carry the same named phases as the timeline lanes
        self.timer = EpochTimer(
            annotate=bool(config.profile_dir))
        self.metrics_log = MetricsLog(config.metrics_path)

    def _train_step_impl(self, params, opt_state, key, lr, feats,
                         labels, mask, gctx):
        # gctx arrives as a jit ARGUMENT (GraphContext is a pytree):
        # closure-capturing it would embed the edge/ELL tables as HLO
        # constants — see the register_pytree_node note in builder.py
        def objective(p):
            # mixed precision: compute in self.compute; the astype vjp
            # returns fp32 cotangents, so grads/Adam stay in dtype
            loss, _ = self.model.loss_fn(cast_floats(p, self.compute),
                                         feats, labels, mask,
                                         gctx, key=key, train=True)
            return loss
        if self.config.remat:
            objective = jax.checkpoint(
                objective, policy=remat_policy(self.config))
        loss, grads = jax.value_and_grad(objective)(params)
        params, opt_state = adam_update(params, grads, opt_state, lr,
                                        self.adam_cfg)
        return params, opt_state, loss

    def _eval_step_impl(self, params, feats, labels, mask, gctx):
        logits = self.model.apply(cast_floats(params, self.compute),
                                  feats, gctx, key=None, train=False)
        return perf_metrics(logits, labels, mask), logits

    # ---- host-feature streaming path (config.features == "host") ----

    def _tail_grad_impl(self, params, y, key, labels, mask, gctx):
        """Loss + grads of the device-resident tail w.r.t. (params, Y);
        dY feeds the streamed head weight gradient."""
        def objective(p, yy):
            loss, _ = self._tail_model.loss_fn(
                cast_floats(p, self.compute), yy, labels, mask,
                gctx, key=key, train=True)
            return loss
        if self.config.remat:
            objective = jax.checkpoint(
                objective, policy=remat_policy(self.config))
        loss, (gp, gy) = jax.value_and_grad(objective, argnums=(0, 1))(
            params, y)
        return loss, gp, gy

    def _tail_eval_impl(self, params, y, labels, mask, gctx):
        # (metrics, logits) like _eval_step_impl: the streamed tier's
        # predict reuses this one compiled program (no tail_predict)
        logits = self._tail_model.apply(cast_floats(params, self.compute),
                                        y, gctx, key=None, train=False)
        return perf_metrics(logits, labels, mask), logits

    def _apply_update_impl(self, params, opt_state, grads, lr):
        return adam_update(params, grads, opt_state, lr, self.adam_cfg)

    def _pin_stream(self, y):
        """Model-shard the streamed-head [V, H] handoff: under a
        model mesh the block-assembled Y would otherwise land fully
        replicated (it is built by per-block device_puts outside any
        jit) and sit at the top of the replication ledger.  One
        device_put re-lays it out H-sharded; the tail programs then
        consume it sharded (GSPMD).  No-op on the 1-D mesh or when H
        does not divide."""
        if self.mesh is None:
            return y
        from jax.sharding import NamedSharding, PartitionSpec
        from ..parallel import model_shard_spec
        spec = model_shard_spec(y.shape, self._mesh_model)
        if spec is None:
            return y
        return jax.device_put(
            y, NamedSharding(self.mesh, PartitionSpec(*spec)))

    def _streamed_step(self, step_key, lr):
        head_key, tail_key = jax.random.split(step_key)
        # cast the master weight to the compute dtype so the streamed
        # blocks (and Y, hence the whole tail) run in compute precision
        # — the footprint the memory autopilot sized the plan with.
        # The phase spans record host wall time per sub-phase WITHOUT
        # extra barriers (the streamed head is already host-paced per
        # block; a per-phase fetch would serialize the tail dispatch).
        timer = self.timer
        w0 = self.params[self._head_param].astype(self.compute)
        with timer.span("head_forward"):
            y = self._pin_stream(
                self._head.forward(w0, self.feats_host, head_key, True))
        with timer.span("tail_grad"):
            _, grads, gy = self._tail_grad(self.params, y, tail_key,
                                           self.labels, self.mask,
                                           self.gctx)
        with timer.span("head_wgrad"):
            grads[self._head_param] = self._head.wgrad(
                self.feats_host, gy, head_key, True
            ).astype(self.params[self._head_param].dtype)
        with timer.span("update"):
            self.params, self.opt_state = self._apply_update(
                self.params, self.opt_state, grads, lr)

    def pipeline_fields(self) -> Dict[str, float]:
        """Streaming-pipeline metrics accumulated since the last call
        (the staging pool's per-block series), folded into the epoch
        record by ``run_epoch_loop``: ``overlap_frac`` = fraction of
        staging latency hidden under compute (0 for the synchronous
        ``prefetch=0`` path by construction), ``h2d_wait_p50_ms`` =
        median consumer-side stall per block, ``prefetch_depth`` = the
        resolved pool depth.  The per-block waits also land in the
        ``h2d_wait``/``h2d_stage`` timer spans so the report's phase
        table shows them next to the epoch phases."""
        if self._head is None:
            return {}
        stats = self._head.pool.take_stats()
        if not stats["n"]:
            return {}
        self.timer.spans_ms.setdefault("h2d_wait", []).extend(
            stats["wait_ms"])
        self.timer.spans_ms.setdefault("h2d_stage", []).extend(
            stats["stage_ms"])
        # per-block records for the timeline merger's h2d lane (the
        # pool stamps monotonic starts alongside each series)
        self.timer.timeline.extend(
            ("h2d_wait", t0, ms) for t0, ms in
            zip(stats["wait_t0"], stats["wait_ms"]))
        self.timer.timeline.extend(
            ("h2d_stage", t0, ms) for t0, ms in
            zip(stats["stage_t0"], stats["stage_ms"]))
        out: Dict[str, float] = {
            "prefetch_depth": int(stats["depth"]),
            "h2d_wait_p50_ms": stats["wait_p50_ms"],
            "h2d_stage_p50_ms": stats["stage_p50_ms"],
        }
        if stats["overlap_frac"] is not None:
            out["overlap_frac"] = stats["overlap_frac"]
        emit("pipeline", f"h2d: {stats['n']} blocks, wait p50 "
             f"{out['h2d_wait_p50_ms']:.2f} ms, overlap_frac "
             f"{out.get('overlap_frac', 0.0)}", console=False, **out)
        return out

    # ---- loop ----

    def train(self, epochs: Optional[int] = None) -> List[Dict[str, float]]:
        """Run ``epochs`` more epochs; the epoch counter persists across
        calls so lr decay and the eval cadence continue correctly."""
        def do_step(step_key, lr):
            if self._head is not None:
                self._streamed_step(step_key, lr)
                return
            self.params, self.opt_state, _ = self._train_step(
                self.params, self.opt_state, step_key, lr, self.feats,
                self.labels, self.mask, self.gctx)

        return run_epoch_loop(self, epochs, do_step, self.evaluate)

    def sync(self) -> None:
        """Block until all dispatched train steps have finished.  Uses
        the fetch-based barrier: ``block_until_ready`` does not reliably
        synchronize under the axon TPU relay (utils/profiling.py)."""
        from ..utils.profiling import sync
        sync(self.params)

    def predict(self, node_ids=None) -> jax.Array:
        """[V, C] inference-mode logits (the tensor the reference only
        ever reduces to metrics, softmax_kernel.cu:41-79 — exposed so
        a user can export predictions).  Runs the EVAL program and
        takes its logits output — predict compiles nothing of its own
        (program-space consolidation: one compiled program serves
        evaluate and predict; still jitted, so the eager interpreter
        never holds every intermediate activation alive).

        ``node_ids`` gathers a ``[len(ids), C]`` row subset ON DEVICE
        — the full ``[V, C]`` tensor never crosses device→host, which
        is the transfer the serve tier's gather path exists to avoid
        (the eager ``take`` is a tiny per-shape program outside the
        audited step set, same class as the epoch loop's scalar
        ops)."""
        if self._head is not None:
            w0 = self.params[self._head_param].astype(self.compute)
            y = self._pin_stream(
                self._head.forward(w0, self.feats_host, None, False))
            _, logits = self._tail_eval(self.params, y, self.labels,
                                        self.mask, self.gctx)
        else:
            _, logits = self._eval_step(self.params, self.feats,
                                        self.labels, self.mask,
                                        self.gctx)
        if node_ids is None:
            return logits
        ids = np.asarray(node_ids, dtype=np.int32).ravel()
        V = int(logits.shape[0])
        if ids.size and (ids.min() < 0 or ids.max() >= V):
            # jnp.take's out-of-bounds mode is 'fill' (silent NaN
            # rows) — raise like DistributedTrainer/Predictor do, one
            # contract across the serve gather paths
            raise ValueError(f"node ids out of range [0, {V})")
        return jnp.take(logits, jnp.asarray(ids), axis=0)

    def evaluate(self) -> Dict[str, float]:
        # fetch ONLY the metrics leaf: the shared eval/predict program
        # also outputs the [V, C] logits, which must stay on device
        # during training evals
        if self._head is not None:
            w0 = self.params[self._head_param].astype(self.compute)
            y = self._pin_stream(
                self._head.forward(w0, self.feats_host, None, False))
            m, _ = self._tail_eval(self.params, y, self.labels,
                                   self.mask, self.gctx)
            return summarize_metrics(jax.device_get(m))
        m, _ = self._eval_step(self.params, self.feats, self.labels,
                               self.mask, self.gctx)
        return summarize_metrics(jax.device_get(m))


def run_epoch_loop(tr, epochs: Optional[int], do_step,
                   do_eval) -> List[Dict[str, float]]:
    """The reference epoch loop (``gnn.cc:99-111``), shared by the
    single-device and distributed trainers: staircase lr decay,
    async-dispatched train step, every-``eval_every``-epoch eval with
    metrics logging and honest timing.

    ``tr`` provides config/epoch/key/timer/metrics_log/sync state;
    ``do_step(step_key, lr)`` runs one training step (async);
    ``do_eval()`` returns the summarized metrics dict.

    Timing: train steps dispatch asynchronously; before each eval the
    loop blocks on ``tr.sync()`` so ``epoch_ms`` is pure train-step
    wall clock divided by the steps in the burst, and ``eval_ms`` is
    the eval pass (device fetch included) timed separately — eval and
    host overhead no longer fold into the per-epoch number.  The very
    first step of a fresh trainer is the compile step: it is barriered
    and recorded on its own (``m["compile_ms"]`` of the first eval /
    the timer's warmup lap) so every reported ``epoch_ms`` is a steady
    lap — no counter surgery needed downstream.  Evals land on
    ``epoch % eval_every == eval_every - 1`` so each covers a full
    burst of steady steps (the reference prints every 5th epoch,
    ``gnn.cc:107-110``; same cadence, phase-shifted off the compile
    epoch)."""
    from ..obs.heartbeat import Heartbeat
    from ..resilience import inject, preempt
    from ..utils.profiling import trace
    cfg = tr.config
    if cfg.fault:
        inject.arm(cfg.fault)
    epochs = epochs if epochs is not None else cfg.epochs
    history: List[Dict[str, float]] = []
    # the loop's live registry view (PR 17): step-time EWMA, epoch-lap
    # histogram, straggler ratio, h2d wait — the same numbers the
    # metrics rows log, but windowed/current for dashboards and the
    # roc-lint metric-adhoc contract (no hand-rolled accumulators in
    # the hot loop)
    reg = getattr(tr, "reg", None)
    if reg is None:
        reg = tr.reg = MetricsRegistry("train")
    g_step = reg.gauge("step_ewma_ms", ewma_alpha=0.2)
    g_strag = reg.gauge("straggler_ratio")
    g_h2d = reg.gauge("h2d_wait_p50_ms")
    h_epoch = reg.histogram("epoch_ms")
    t_last = time.perf_counter()
    e_last = tr.epoch
    compile_ms: Optional[float] = None
    # per-trainer flag, NOT tr.epoch > 0: a checkpoint-restored trainer
    # in a fresh process has epoch > 0 but still compiles on step one
    compiled = getattr(tr, "_loop_compiled", False)
    try:
        with trace(cfg.profile_dir):
            for _ in range(epochs):
                epoch = tr.epoch
                inject.note_epoch(epoch)
                lr = decayed_lr(cfg.learning_rate, jnp.asarray(epoch),
                                cfg.decay_rate, cfg.decay_steps)
                tr.key, step_key = jax.random.split(tr.key)
                do_step(step_key, lr)
                if not compiled:
                    # barrier the compile step out of the steady laps;
                    # the heartbeat turns the historical blank
                    # "claiming backend" hang into dated stall events —
                    # and, with ROC_TPU_STALL_TIMEOUT_S armed, into a
                    # StallFailure the recovery loop can restart
                    with Heartbeat("first_compile"):
                        inject.maybe_stall()
                        tr.sync()
                    now = time.perf_counter()
                    compile_ms = (now - t_last) * 1e3
                    # timer laps are the timeline span buffer
                    # (flushed per eval), not a quantile store; the
                    # registry histogram records the same lap below
                    # roc-lint: ok=metric-adhoc
                    tr.timer.laps_ms.append(compile_ms)
                    tr.timer.note_span("compile", compile_ms)
                    # clock-sync handshake, piggybacked on the barrier
                    # just crossed: every SPMD process passes the first
                    # step's collective within one step of each other,
                    # so the merger (obs/timeline.py) aligns the
                    # per-process monotonic clocks on this event's
                    # (wall, mono) pair — N per-process JSONL streams
                    # become one time axis
                    emit("timeline",
                         f"clock_sync: first-step barrier crossed "
                         f"(epoch {epoch})", console=False,
                         kind="clock_sync", epoch=epoch,
                         compile_ms=round(compile_ms, 1))
                    t_last, e_last = now, tr.epoch + 1
                    compiled = tr._loop_compiled = True
                if epoch % cfg.eval_every == cfg.eval_every - 1:
                    tr.sync()
                    now = time.perf_counter()
                    mono_now = time.monotonic()
                    m = do_eval()
                    t_eval_end = time.perf_counter()
                    m["epoch"] = epoch
                    span = tr.epoch + 1 - e_last
                    if span <= 0:
                        # no steady steps since the compile barrier
                        # (only possible on the first eval with
                        # eval_every == 1): the compile lap is the only
                        # honest number we have
                        m["epoch_ms"] = compile_ms
                    else:
                        m["epoch_ms"] = (now - t_last) * 1e3 / span
                        # span buffer, see the compile lap above
                        # roc-lint: ok=metric-adhoc
                        tr.timer.laps_ms.append(m["epoch_ms"])
                        tr.timer.spans_ms.setdefault(
                            "train", []).append(m["epoch_ms"])
                        # timeline lane: the whole steady burst as ONE
                        # span (per-epoch steps dispatch async and
                        # have no individual host-visible boundaries)
                        burst_ms = (now - t_last) * 1e3
                        tr.timer.timeline.append(
                            ("train", mono_now - burst_ms / 1e3,
                             burst_ms))
                    m["eval_ms"] = (t_eval_end - now) * 1e3
                    tr.timer.note_span("eval", m["eval_ms"])
                    if compile_ms is not None:
                        m["compile_ms"] = compile_ms
                        compile_ms = None
                    if span > 0:
                        # throughput from honest steady laps only
                        m.update(throughput_fields(tr, m["epoch_ms"]))
                    # streamed-tier pipeline metrics (overlap_frac,
                    # h2d_wait p50) accumulated over the burst
                    pipe = getattr(tr, "pipeline_fields", None)
                    if pipe is not None:
                        m.update(pipe() or {})
                    # per-epoch straggler attribution (distributed
                    # trainers): which shard the cost model predicts
                    # slowest for the measured lap, by how much — the
                    # SAME record maybe_rebalance's ridge observation
                    # consumes, now on every eval'd record and in the
                    # merged timeline
                    sf = getattr(tr, "straggler_fields", None)
                    if sf is not None:
                        m.update(sf(m) or {})
                    # registry recording + the row's EWMA field: only
                    # steady laps feed the EWMA (the compile lap would
                    # drag it for ~1/alpha evals)
                    if span > 0 and m.get("epoch_ms"):
                        h_epoch.record(m["epoch_ms"])
                        g_step.set(m["epoch_ms"])
                        ew = g_step.ewma
                        if ew is not None:
                            m["step_ewma_ms"] = round(ew, 2)
                    if m.get("straggler_ratio") is not None:
                        g_strag.set(m["straggler_ratio"])
                    if m.get("h2d_wait_p50_ms") is not None:
                        g_h2d.set(m["h2d_wait_p50_ms"])
                    t_last, e_last = t_eval_end, tr.epoch + 1
                    history.append(m)
                    tr.metrics_log.log(m)
                    # flush span laps for the timeline merger: one
                    # compact event per eval instead of one per span
                    tl = tr.timer.take_timeline()
                    if tl:
                        emit("timeline",
                             f"spans: {len(tl)} laps to epoch {epoch}",
                             console=False, kind="spans", epoch=epoch,
                             spans=[[n, round(t0, 6), round(ms, 3)]
                                    for n, t0, ms in tl])
                    # epoch-boundary load rebalancing (distributed
                    # trainers with config.rebalance): feed the
                    # measured lap to the partition cost model and
                    # repartition when the predicted max-shard gain
                    # clears the hysteresis threshold.  After a
                    # shape-changing repartition the trainer resets
                    # _loop_compiled so the recompile lap is barriered
                    # out of the steady timing like the first one.
                    rb = getattr(tr, "maybe_rebalance", None)
                    if rb is not None:
                        rb(m)
                        compiled = getattr(tr, "_loop_compiled",
                                           compiled)
                    emit("epoch",
                         f"epoch {epoch}: {m['epoch_ms']:.1f} ms/epoch "
                         f"eval {m['eval_ms']:.1f} ms",
                         console=False, **m)
                    if cfg.verbose:
                        print(format_metrics(epoch, m))
                tr.epoch += 1
                # epoch-boundary fault sites (nan_grads / sigkill /
                # sigterm drills) and the preemption grace check: the
                # in-flight step has been dispatched, so a graceful
                # stop here "finishes the epoch step" by construction
                inject.epoch_hooks(tr, epoch)
                preempt.raise_if_preempted(epoch)
    finally:
        # bound fds across many trainers — on exceptions too; the log
        # lazily reopens in append mode if train() is called again
        tr.metrics_log.close()
        tl = tr.timer.take_timeline()
        if tl:
            # span laps accumulated since the last eval flush (a run
            # dying between evals must not take them along)
            emit("timeline", f"spans: {len(tl)} laps (final)",
                 console=False, kind="spans",
                 spans=[[n, round(t0, 6), round(ms, 3)]
                        for n, t0, ms in tl])
        if tr.timer.spans_ms:
            emit("epoch", "phase spans "
                 + " ".join(f"{k}:n={v['n']},p50={v['p50_ms']:.1f}ms"
                            for k, v in
                            tr.timer.span_summary().items()),
                 console=False, spans=tr.timer.span_summary(),
                 laps=tr.timer.summary())
    return history


def throughput_fields(tr, epoch_ms: Optional[float]) -> Dict[str, float]:
    """edges/sec and MFU-style utilization for one steady epoch lap.
    FLOPs come from the compile observer's ``cost_analysis()`` capture
    (per-device under SPMD — matching the per-chip peak the MFU ratio
    divides by); missing introspection just drops the fields."""
    out: Dict[str, float] = {}
    if not epoch_ms or epoch_ms <= 0:
        return out
    s = epoch_ms / 1e3
    edges = getattr(tr, "_obs_edges", None)
    if edges:
        out["edges_per_s"] = round(edges / s, 1)
    cost = getattr(getattr(tr, "_train_step", None), "cost", None)
    if not cost:
        # features='host' streaming never calls _train_step — the
        # observed step there is the device-resident tail
        cost = getattr(getattr(tr, "_tail_grad", None), "cost", None)
    flops = (cost or {}).get("flops")
    if flops:
        out["tflops_per_s"] = round(flops / s / 1e12, 4)
        from ..obs.compile_watch import peak_flops_per_s
        peak = peak_flops_per_s()
        if peak:
            out["mfu"] = round(flops / s / peak, 4)
    return out


def format_metrics(epoch: int, m: Dict[str, float]) -> str:
    """The reference's infer-mode print line (``softmax_kernel.cu:146``)."""
    return ("[INFER][%d] train_loss: %.4f  train_accuracy: %.2f%%(%d/%d)  "
            "val_accuracy: %.2f%%(%d/%d)  test_accuracy: %.2f%%(%d/%d)"
            % (epoch, m["train_loss"],
               m["train_acc"] * 100.0, m["train_correct"], m["train_cnt"],
               m["val_acc"] * 100.0, m["val_correct"], m["val_cnt"],
               m["test_acc"] * 100.0, m["test_correct"], m["test_cnt"]))
