"""Adam optimizer with reference-parity semantics.

Reference (``optimizer.h:34-50``, ``optimizer.cc:79-85``,
``optimizer_kernel.cu:43-103``):

- ``next()`` is called before each update step:
  ``beta1_t *= beta1; beta2_t *= beta2;
  alpha_t = alpha * sqrt(1 - beta2_t) / (1 - beta1_t)``.
- Per-parameter update: ``gt = grad + weight_decay * W`` (L2-coupled,
  fast.ai-style, ``optimizer_kernel.cu:56``), ``m/v`` EMA, then
  ``W -= alpha_t * mt / (sqrt(vt) + eps)``.
- The gradient "allreduce" sums the per-partition replicas on one GPU
  (``optimizer_kernel.cu:88-94``); in the TPU framework the replicas never
  materialize — each shard contributes its local gradient and a ``psum``
  over the mesh produces the identical sum (fp32 addition order aside).

Implemented as pure pytree functions (optax-style) so the whole step jits
and the m/v state shards with the params.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class AdamState(NamedTuple):
    step: jax.Array      # int32 scalar
    beta1_t: jax.Array   # float32 scalar, beta1^step
    beta2_t: jax.Array   # float32 scalar
    m: Any               # pytree like params
    v: Any               # pytree like params


@dataclass(frozen=True)
class AdamConfig:
    # defaults mirror AdamOptimizer ctor defaults (optimizer.h:36-38)
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    weight_decay: float = 0.0


def adam_init(params: Any) -> AdamState:
    zeros = jax.tree_util.tree_map(
        lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
    return AdamState(step=jnp.zeros((), jnp.int32),
                     beta1_t=jnp.ones((), jnp.float32),
                     beta2_t=jnp.ones((), jnp.float32),
                     m=zeros,
                     v=jax.tree_util.tree_map(jnp.copy, zeros))


def adam_update(params: Any, grads: Any, state: AdamState, lr: jax.Array,
                cfg: AdamConfig) -> Tuple[Any, AdamState]:
    """One optimizer step.  ``lr`` is the (possibly decayed) base alpha;
    bias correction is applied inside, matching ``next()`` +
    ``adam_update``."""
    beta1_t = state.beta1_t * cfg.beta1
    beta2_t = state.beta2_t * cfg.beta2
    alpha_t = lr * jnp.sqrt(1.0 - beta2_t) / (1.0 - beta1_t)

    def upd(w, g, m, v):
        w32 = w.astype(jnp.float32)
        # L2-coupled decay on weight MATRICES only (the reference's
        # params are all matrices, optimizer_kernel.cu:52-62); scalar
        # params (GIN's learnable eps) are excluded — decaying them
        # would regularize eps back to GIN-0 against the paper's
        # free epsilon
        wd = cfg.weight_decay if w.ndim > 0 else 0.0
        gt = g.astype(jnp.float32) + wd * w32
        mt = cfg.beta1 * m + (1.0 - cfg.beta1) * gt
        vt = cfg.beta2 * v + (1.0 - cfg.beta2) * gt * gt
        new_w = w32 - alpha_t * mt / (jnp.sqrt(vt) + cfg.epsilon)
        return new_w.astype(w.dtype), mt, vt

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(state.m)
    flat_v = treedef.flatten_up_to(state.v)
    out = [upd(w, g, m, v) for w, g, m, v in
           zip(flat_p, flat_g, flat_m, flat_v)]
    new_p = treedef.unflatten([o[0] for o in out])
    new_m = treedef.unflatten([o[1] for o in out])
    new_v = treedef.unflatten([o[2] for o in out])
    return new_p, AdamState(step=state.step + 1, beta1_t=beta1_t,
                            beta2_t=beta2_t, m=new_m, v=new_v)


def decayed_lr(base_lr: float, epoch: jax.Array, decay_rate: float,
               decay_steps: int) -> jax.Array:
    """Staircase lr decay: the reference multiplies ``alpha`` by
    ``decay_rate`` every ``decay_steps`` epochs (``gnn.cc:100-101``)."""
    k = (epoch // jnp.maximum(decay_steps, 1)).astype(jnp.float32)
    return base_lr * jnp.power(decay_rate, k)
