"""``python -m roc_tpu.prewarm`` — pre-pay the compile wall.

Feeds the program-space auditor's exact static enumeration
(``analysis/programspace.py`` — keyed by the quantized plan shapes the
rebalancer preserves) into AOT ``lower().compile()`` against the
persistent compile cache, so rebalance / resume / serving / the bench
probe all start warm.  Compile-only: nothing executes on a device.

Usage:
    python -m roc_tpu.prewarm                      # every hosted rig
    python -m roc_tpu.prewarm --config gin_flat8   # one rig
    python -m roc_tpu.prewarm --jobs 2             # parallel procs
    python -m roc_tpu.prewarm --cpu                # force CPU backend

Writes the warm-state artifact (``programspace_warm.json`` next to the
bench artifacts) recording each warmed config's program-key set — the
bench probe preflight diffs ``python -m roc_tpu.analysis --json``
against it and refuses to burn chip deadline on a config whose program
set grew since the cache was warmed.  Stdout gets one JSON line per
warmed config (machine-readable; `# ...` diagnostics go to stderr).
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from typing import List, Optional


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(
        prog="python -m roc_tpu.prewarm", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--config", default="all",
                    help="rig config name (analysis/programspace.py "
                         "rig_configs) or 'all' (default)")
    ap.add_argument("--cache-dir", default=None,
                    help="persistent cache directory (default: "
                         "$ROC_TPU_CACHE_DIR or ~/.cache/roc_tpu/xla)")
    ap.add_argument("--state", default=None,
                    help="warm-state artifact path (default: "
                         "benchmarks/programspace_warm.json, honoring "
                         "ROC_TPU_BENCH_ARTIFACTS)")
    ap.add_argument("--no-state", action="store_true",
                    help="do not write the warm-state artifact")
    ap.add_argument("--jobs", type=int, default=1,
                    help="warm configs in N parallel child processes. "
                         "The cache itself is file-based and multi-"
                         "process safe, but (a) on a TPU host keep "
                         "the default 1 — libtpu owns the accelerator "
                         "exclusively, so a second concurrent child "
                         "fails backend init — and (b) concurrent "
                         "children sharing one cache dir make the "
                         "warm-vs-cold attribution best-effort (a "
                         "sibling's write inside a candidate's "
                         "before/after window counts as cold); the "
                         "warm-state KEY sets stay exact either way")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (CI / cache priming "
                         "for CPU-rig tests)")
    ap.add_argument("-v", "--verbose", action="store_true")
    return ap.parse_args(argv)


def _parallel(names: List[str], args) -> int:
    """One child process per config, ``--jobs`` at a time.  Children
    print their JSON report line; the parent relays it and merges the
    warm state (children run --no-state so the artifact is written
    once, by the parent)."""
    base = [sys.executable, "-m", "roc_tpu.prewarm", "--no-state",
            "--jobs", "1"]
    for flag, val in (("--cache-dir", args.cache_dir),):
        if val:
            base += [flag, val]
    if args.cpu:
        base.append("--cpu")
    if args.verbose:
        base.append("-v")
    reports, rc = [], 0
    pending = list(names)
    running: List = []
    while pending or running:
        while pending and len(running) < max(1, args.jobs):
            name = pending.pop(0)
            running.append((name, subprocess.Popen(
                base + ["--config", name], stdout=subprocess.PIPE,
                stderr=sys.stderr, text=True)))
        name, proc = running.pop(0)
        out, _ = proc.communicate()
        for line in out.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    reports.append(json.loads(line))
                except ValueError:
                    pass
            if line:
                print(line)
        if proc.returncode != 0:
            print(f"# prewarm child {name} exited "
                  f"{proc.returncode}", file=sys.stderr)
            rc = 1
    if reports and not args.no_state:
        from .utils.prewarm import write_warm_state
        # keep keys=[] reports: an all-failed config must be RECORDED
        # as warmed-nothing so the preflight sees its whole program
        # set as growth and refuses — dropping it would skip the
        # guard entirely (same semantics as the sequential path)
        path = write_warm_state(
            [r for r in reports if "config" in r], args.state)
        print(f"# warm state -> {path}", file=sys.stderr)
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    if args.cpu:
        # before any backend init; children inherit the env too.  The
        # 8-virtual-device flag must land before CPU-client init or
        # the multi-device rigs (gin_flat8 parts=2) are SILENTLY
        # skipped and never warmed — the exact masked cold-compile
        # the warm state exists to surface
        from .analysis import force_cpu_rig
        force_cpu_rig()
    from .analysis.programspace import rig_configs
    names = (sorted(rig_configs()) if args.config == "all"
             else [args.config])
    unknown = [n for n in names if n not in rig_configs()]
    if unknown:
        print(f"error: unknown config(s) {unknown}; known: "
              f"{sorted(rig_configs())}", file=sys.stderr)
        return 2
    if args.jobs > 1 and len(names) > 1:
        return _parallel(names, args)

    from .utils.prewarm import prewarm_config, write_warm_state
    reports = []
    for name in names:
        rep = prewarm_config(name, cache_dir=args.cache_dir,
                             verbose=args.verbose)
        if rep is not None:
            reports.append(rep)
            print(json.dumps({k: v for k, v in rep.items()
                              if k != "slots"}))
        else:
            print(f"# prewarm {name}: skipped — backend cannot host "
                  f"the rig mesh (with --cpu the 8-virtual-device "
                  f"flag is set automatically)", file=sys.stderr)
    if reports and not args.no_state:
        path = write_warm_state(reports, args.state)
        print(f"# warm state -> {path}", file=sys.stderr)
    # a failed candidate was NOT warmed, and an unavailable cache dir
    # means NOTHING was warmed (keys withheld either way, so the
    # preflight sees growth) — surface both in the exit code so
    # round6_chain.sh step 0 can't report success over them
    if any(r.get("failed") or r.get("cache_unavailable")
           for r in reports):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
