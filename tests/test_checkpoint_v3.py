"""Checkpoint format v3 + async saver tests (ISSUE 15): sharded
saves with the two-phase commit protocol, gather-on-restore across
mesh layouts, and the async saver's contract — coalescing queue,
flush barrier, bounded stalls, bit-identical results, and the
step-path-blocked-time acceptance pin.  The crash drills (SIGKILL in
every commit window, corrupt shards, wedged saver, DCN variants)
live in tests/test_drills.py."""

import contextlib
import os
import time

import numpy as np
import pytest

from roc_tpu.utils.checkpoint import (CheckpointCorrupt, _load_v3,
                                      read_manifest, save_checkpoint,
                                      snapshot_state, write_snapshot)


@pytest.fixture(scope="module", autouse=True)
def _shed_native_jit_state():
    yield
    import jax
    jax.clear_caches()


@contextlib.contextmanager
def _capture_events():
    from roc_tpu.obs.events import get_bus

    class _Cap:
        def __init__(self):
            self.records = []

        def write(self, rec):
            self.records.append(dict(rec))

        def close(self):
            pass

    bus = get_bus()
    cap = _Cap()
    bus.add_sink(cap)
    try:
        yield cap.records
    finally:
        bus.sinks.remove(cap)


def _tree(scale=1, seed=0):
    """A params-like host tree (flat name → array, the shape every
    model's init_params produces)."""
    rng = np.random.RandomState(seed)
    return {f"w{i}": rng.rand(64 * scale, 32).astype(np.float32)
            for i in range(3)}


class _FakeTrainer:
    """The minimal surface CheckpointRotation.save/restore touch —
    lets the saver tests run without paying a model compile."""

    def __init__(self, scale=1, seed=0, epoch=0):
        import jax
        import jax.numpy as jnp
        from roc_tpu.train.optimizer import adam_init
        self.params = {k: jnp.asarray(v)
                       for k, v in _tree(scale, seed).items()}
        self.opt_state = adam_init(self.params)
        self.epoch = epoch
        self.key = jax.random.PRNGKey(seed)


# ------------------------------------------------ sharded save/restore

def test_sharded_save_gathers_on_restore(tmp_path):
    """A P('parts')-sharded tree saved at parts=2 reassembles to the
    full host arrays on load (gather-on-restore), and re-places onto
    a DIFFERENT parts=4 mesh bit-exactly — the elastic cross-P
    restore at the array level, P in {2, 4}."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from roc_tpu.parallel import multihost as mh

    x = np.arange(64 * 8, dtype=np.float32).reshape(64, 8)
    y = np.arange(32, dtype=np.float32)
    mesh2 = mh.make_parts_mesh(2)
    sharded = {
        "w": jax.device_put(jnp.asarray(x),
                            NamedSharding(mesh2, P("parts"))),
        "b": jax.device_put(jnp.asarray(y), NamedSharding(mesh2, P())),
    }
    snap = snapshot_state(sharded, {"m": sharded["w"]}, epoch=5)
    p = str(tmp_path / "ck.5")
    write_snapshot(p, snap)
    data, doc = _load_v3(p)
    assert doc["epoch"] == 5
    np.testing.assert_array_equal(data["params['w']"], x)
    np.testing.assert_array_equal(data["params['b']"], y)
    np.testing.assert_array_equal(data["opt['m']"], x)
    # elastic: the gathered array re-places onto a parts=4 layout
    mesh4 = mh.make_parts_mesh(4)
    w4 = jax.device_put(jnp.asarray(data["params['w']"]),
                        NamedSharding(mesh4, P("parts")))
    np.testing.assert_array_equal(np.asarray(w4), x)


def test_sharded_shard_header_carries_spec_and_indices(tmp_path):
    """Per-shard headers speak the PR-14 sharding-spec vocabulary:
    the 'parts' axis name on the sharded dim, per-piece [lo, hi)
    index ranges that tile the global shape."""
    import json
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from roc_tpu.parallel import multihost as mh

    mesh = mh.make_parts_mesh(4)
    x = np.arange(64 * 4, dtype=np.float32).reshape(64, 4)
    xs = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("parts")))
    snap = snapshot_state({"w": xs}, {}, epoch=1)
    p = str(tmp_path / "ck.1")
    write_snapshot(p, snap)
    with np.load(os.path.join(p, "shard_00000.npz")) as z:
        header = json.loads(bytes(
            np.asarray(z["__header__"], dtype=np.uint8)).decode())
    meta = header["arrays"]["params['w']"]
    assert meta["spec"] == ["parts", None]
    assert meta["shape"] == [64, 4]
    pieces = [pm for pm in header["pieces"].values()
              if pm["key"] == "params['w']"]
    assert len(pieces) == 4  # one canonical piece per mesh slot
    rows = sorted(tuple(pm["index"][0]) for pm in pieces)
    assert rows == [(0, 16), (16, 32), (32, 48), (48, 64)]
    assert all(tuple(pm["index"][1]) == (0, 4) for pm in pieces)


def test_v3_parity_across_mesh_shapes(tmp_path):
    """The 2-D mesh satellite: trainer state model-sharded on the
    (2, 4) mesh saves via the v3 multi-writer path (one canonical
    piece per model slot, 'model' in the header spec vocabulary) and
    restores bit-identically onto the transposed (4, 2) mesh and onto
    a 1-D parts=2 mesh — the elastic restore across every mesh
    reshape of the 8-device rig, with the restored leaves landing in
    the NEW mesh's at-rest layout."""
    import json
    import jax
    from jax.sharding import PartitionSpec as P
    from roc_tpu.parallel import MODEL_AXIS, model_shard_spec
    from roc_tpu.parallel import multihost as mh
    from roc_tpu.parallel.distributed import put_replicated
    from roc_tpu.utils.checkpoint import (checkpoint_trainer,
                                          restore_trainer)

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device rig")

    def mesh_trainer(parts, model, seed, epoch):
        tr = _FakeTrainer(seed=seed, epoch=epoch)
        tr.mesh = mh.make_parts_mesh(parts, model=model)
        tr.params, tr.opt_state = put_replicated(
            (tr.params, tr.opt_state), tr.mesh)
        return tr

    src = mesh_trainer(2, 4, seed=3, epoch=7)
    assert src.params["w0"].sharding.spec == P(None, MODEL_AXIS)
    want = {k: np.asarray(v) for k, v in src.params.items()}
    p = str(tmp_path / "ck.7")
    checkpoint_trainer(src, p)
    # the shard header speaks the model axis: [64, 32] params carry
    # it on the feature dim, one canonical piece per model slot
    with np.load(os.path.join(p, "shard_00000.npz")) as z:
        header = json.loads(bytes(
            np.asarray(z["__header__"], dtype=np.uint8)).decode())
    meta = header["arrays"]["params['w0']"]
    assert meta["spec"] == [None, "model"]
    pieces = [pm for pm in header["pieces"].values()
              if pm["key"] == "params['w0']"]
    assert sorted(tuple(pm["index"][1]) for pm in pieces) == \
        [(0, 8), (8, 16), (16, 24), (24, 32)]
    for parts, model in ((4, 2), (2, 1)):
        dst = mesh_trainer(parts, model, seed=99, epoch=0)
        restore_trainer(dst, p)
        assert dst.epoch == 7
        mspec = model_shard_spec((64, 32), model)
        assert dst.params["w0"].sharding.spec == \
            (P(*mspec) if mspec else P())
        for k, ref in want.items():
            np.testing.assert_array_equal(np.asarray(dst.params[k]),
                                          ref)
        np.testing.assert_array_equal(
            np.asarray(dst.opt_state.m["w0"]),
            np.asarray(src.opt_state.m["w0"]))


def test_incomplete_sharded_coverage_is_corrupt(tmp_path):
    """A save whose pieces do not tile an array (a lost shard piece)
    must fail the coverage proof, not silently zero-fill."""
    snap = snapshot_state({"w": np.ones((8, 2), np.float32)}, {},
                          epoch=0)
    # drop rows [4, 8): simulate a piece that never landed
    keep = snap.pieces[0]
    keep.index = [[0, 4], [0, 2]]
    keep.data = keep.data[:4]
    keep.member = "params['w']@0"
    p = str(tmp_path / "ck.0")
    write_snapshot(p, snap)
    with pytest.raises(CheckpointCorrupt, match="gathered"):
        _load_v3(p)


def test_recommit_uncommits_first(tmp_path):
    """Re-saving an epoch (a replayed recovery round) removes the old
    manifest BEFORE rewriting shards: a crash mid-rewrite leaves an
    invisible directory, never a manifest pointing at half-replaced
    shards."""
    tree = _tree()
    p = str(tmp_path / "ck.3")
    save_checkpoint(p, tree, {"m": tree["w0"]}, epoch=3)
    man1 = read_manifest(p)
    save_checkpoint(p, {k: v + 1 for k, v in tree.items()},
                    {"m": tree["w0"]}, epoch=3)
    man2 = read_manifest(p)
    assert man2["shards"][0]["crc32"] != man1["shards"][0]["crc32"]
    data, _ = _load_v3(p)
    np.testing.assert_array_equal(data["params['w0']"],
                                  tree["w0"] + 1)


# ------------------------------------------------------- async saver

def test_async_vs_sync_bit_identical(tmp_path):
    """The satellite pin: async save -> restore yields byte-identical
    state to the synchronous save of the same trainer."""
    from roc_tpu.resilience.recovery import CheckpointRotation
    tr = _FakeTrainer(epoch=4)
    sync_p = str(tmp_path / "sync" / "ck.4")
    save_checkpoint(sync_p, tr.params, tr.opt_state, tr.epoch, tr.key)
    rot = CheckpointRotation(str(tmp_path / "async" / "ck"), keep=2,
                             async_save=True)
    async_p = rot.save(tr)
    rot.drain()
    a, _ = _load_v3(sync_p)
    b, _ = _load_v3(async_p)
    assert sorted(a) == sorted(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k])


def test_coalescing_drops_superseded_snapshot(tmp_path):
    """Queue depth 1: with the saver wedged on save N, submitting
    N+1 then N+2 drops N+1 (dated ``superseded`` event) and commits
    N+2 — asserted via events, per the satellite."""
    from roc_tpu.resilience.async_save import AsyncSaver
    import threading
    from roc_tpu.utils import checkpoint as ck

    gate = threading.Event()
    orig = ck.write_snapshot

    def slow_write(path, snap):
        if snap.epoch == 0:
            gate.wait(timeout=30.0)
        return orig(path, snap)

    saver = AsyncSaver()
    tree = _tree()
    snaps = [snapshot_state(tree, {}, epoch=e) for e in range(3)]
    # the saver imports write_snapshot lazily from utils.checkpoint
    # per save — patching at the source module intercepts it
    ck.write_snapshot = slow_write
    try:
        with _capture_events() as recs:
            saver.submit(snaps[0], str(tmp_path / "ck.0"))
            # wait until save 0 is actually in flight, so 1 and 2
            # both land in the (depth-1) queue slot
            deadline = time.monotonic() + 10.0
            while not saver.stats()["busy"]:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            saver.submit(snaps[1], str(tmp_path / "ck.1"))
            saver.submit(snaps[2], str(tmp_path / "ck.2"))
            gate.set()
            saver.drain()
    finally:
        ck.write_snapshot = orig
    sup = [r for r in recs if r.get("cat") == "checkpoint"
           and r.get("kind") == "superseded"]
    assert len(sup) == 1 and sup[0]["epoch"] == 1 and sup[0]["by"] == 2
    assert os.path.isdir(str(tmp_path / "ck.0"))
    assert not os.path.exists(str(tmp_path / "ck.1"))
    assert os.path.isdir(str(tmp_path / "ck.2"))
    st = saver.stats()
    assert st["saved"] == 2 and st["superseded"] == 1


def test_flush_bounds_wedged_saver(tmp_path):
    """flush() is deadline-bounded: a wedged saver surfaces as
    StallFailure within the bound — the emergency-save latency
    guarantee — and drain() abandons the daemon thread."""
    from roc_tpu.obs.heartbeat import StallFailure
    from roc_tpu.resilience.async_save import AsyncSaver
    from roc_tpu.utils import checkpoint as ck
    import threading

    gate = threading.Event()
    orig = ck.write_snapshot
    ck.write_snapshot = lambda path, snap: gate.wait(timeout=60.0)
    saver = AsyncSaver()
    try:
        saver.submit(snapshot_state(_tree(), {}, epoch=0),
                     str(tmp_path / "ck.0"))
        t0 = time.monotonic()
        with pytest.raises(StallFailure):
            saver.flush(timeout_s=0.5)
        assert time.monotonic() - t0 < 5.0
        with pytest.raises(StallFailure):
            saver.drain(timeout_s=0.2)
    finally:
        gate.set()
        ck.write_snapshot = orig


def test_background_failure_surfaces_on_next_flush(tmp_path):
    """An async save that fails in the background is stored and
    re-raised on the next flush — never silent."""
    from roc_tpu.resilience.async_save import AsyncSaver
    from roc_tpu.utils import checkpoint as ck

    orig = ck.write_snapshot

    def boom(path, snap):
        raise OSError("injected background write failure")

    ck.write_snapshot = boom
    saver = AsyncSaver()
    try:
        with _capture_events() as recs:
            saver.submit(snapshot_state(_tree(), {}, epoch=0),
                         str(tmp_path / "ck.0"))
            with pytest.raises(OSError, match="injected"):
                saver.flush(timeout_s=10.0)
        assert any(r.get("kind") == "saver_error" for r in recs)
    finally:
        ck.write_snapshot = orig
        saver.drain(timeout_s=5.0)


def test_async_block_under_quarter_of_sync_wall(tmp_path):
    """The acceptance pin: the async save's step-path blocked time
    (finite guard + host snapshot, CheckpointRotation.last_block_ms)
    measures < 25% of the synchronous save's wall on the CPU rig,
    evidenced by the new ``checkpoint`` events' block/save timings."""
    import shutil
    from roc_tpu.resilience.recovery import CheckpointRotation
    from roc_tpu.utils.checkpoint import checkpoint_trainer
    tr = _FakeTrainer(scale=64, epoch=1)   # ~2.3 MB params, 3x opt
    rot = CheckpointRotation(str(tmp_path / "a" / "ck"), keep=2,
                             async_save=True)
    best_ratio = np.inf
    for attempt in range(3):   # best-of-3: CI hosts stall arbitrarily
        sync_p = str(tmp_path / f"s{attempt}" / "ck.1")
        t0 = time.perf_counter()
        checkpoint_trainer(tr, sync_p)
        sync_ms = (time.perf_counter() - t0) * 1e3
        shutil.rmtree(os.path.dirname(sync_p), ignore_errors=True)
        with _capture_events() as recs:
            rot.save(tr)
            rot.flush()
        saved = [r for r in recs if r.get("cat") == "checkpoint"
                 and r.get("kind") == "saved"]
        assert saved, recs
        block_ms = saved[-1]["block_ms"]
        assert saved[-1]["save_ms"] >= saved[-1]["write_ms"]
        best_ratio = min(best_ratio, block_ms / max(sync_ms, 1e-6))
        if best_ratio < 0.25:
            break
    rot.drain()
    assert best_ratio < 0.25, \
        f"async save blocked the step path {best_ratio:.0%} of the " \
        f"sync wall (acceptance: < 25%)"


def test_ckpt_spans_render_in_timeline(tmp_path):
    """The saver's ckpt_write/ckpt_commit span laps merge into the
    Perfetto trace on the process lane — the save visibly overlaps
    the training bursts."""
    from roc_tpu.obs.timeline import merge_timeline
    from roc_tpu.resilience.recovery import CheckpointRotation
    tr = _FakeTrainer(epoch=2)
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=2,
                             async_save=True)
    with _capture_events() as recs:
        rot.save(tr)
        rot.flush()
    rot.drain()
    spans = [r for r in recs if r.get("cat") == "timeline"
             and r.get("kind") == "spans"]
    names = {lap[0] for r in spans for lap in r.get("spans", [])}
    assert {"ckpt_write", "ckpt_commit"} <= names
    trace = merge_timeline(recs, [])
    tnames = {ev.get("name") for ev in trace["traceEvents"]}
    assert {"ckpt_write", "ckpt_commit"} <= tnames


def test_async_rotation_prunes_after_commit(tmp_path):
    """The keep window holds under async saves, and pruning runs on
    the saver thread strictly after the commit (the newest save can
    never orphan the rotation)."""
    from roc_tpu.resilience.recovery import CheckpointRotation
    tr = _FakeTrainer()
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=2,
                             async_save=True)
    for ep in (1, 2, 3, 4):
        tr.epoch = ep
        rot.save(tr)
        rot.flush()
    rot.drain()
    assert rot.existing() == [3, 4]


def test_async_save_adds_zero_compile_events(tmp_path):
    """The async path compiles nothing: a full save+flush cycle emits
    zero compile-observer events (program budgets stay at delta +0 —
    the programspace gate pins the budgets themselves)."""
    from roc_tpu.resilience.recovery import CheckpointRotation
    tr = _FakeTrainer(epoch=1)
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=2,
                             async_save=True)
    rot.save(tr)
    rot.flush()   # warm the (pre-existing) finite-guard jit
    with _capture_events() as recs:
        tr.epoch = 2
        rot.save(tr)
        rot.flush()
    rot.drain()
    compiles = [r for r in recs
                if r.get("cat") == "compile" and "lower_s" in r]
    assert not compiles, compiles
