"""GAT attention aggregation + model family.

The reference has no attention model (sum-only aggregation,
``scattergather_kernel.cu:20-76``); GAT is the framework extension.
Tests: the ELL edge softmax against a dense numpy reference, padding /
zero-degree handling, the budget-segmented path, convergence (SURVEY
§4's correctness-by-convergence standard), the SPMD step, and the
trainer's forced-ell override.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from roc_tpu.core.ell import ell_from_graph
from roc_tpu.core.graph import synthetic_dataset
from roc_tpu.models.gat import build_gat
from roc_tpu.ops.attention import gat_aggregate_ell
from roc_tpu.train.trainer import TrainConfig, Trainer


@pytest.fixture(scope="module")
def dataset():
    return synthetic_dataset(128, 6, in_dim=16, num_classes=4, seed=0)


def dense_gat_reference(adj, h, a_src, a_dst, neg_slope=0.2):
    """O(V^2) numpy reference: exact additive-attention aggregation."""
    V, F = h.shape
    s = h @ a_src
    d = h @ a_dst
    out = np.zeros_like(h)
    for i in range(V):
        nbrs = np.flatnonzero(adj[:, i])  # adj[src, dst]
        if nbrs.size == 0:
            continue
        e = s[nbrs] + d[i]
        e = np.where(e > 0, e, neg_slope * e)
        e = e - e.max()
        w = np.exp(e)
        alpha = w / w.sum()
        out[i] = (alpha[:, None] * h[nbrs]).sum(axis=0)
    return out


def _adj_from_graph(g):
    V = g.num_nodes
    adj = np.zeros((V, V), dtype=bool)
    dst = np.repeat(np.arange(V), np.diff(g.row_ptr))
    adj[g.col_idx, dst] = True
    return adj


@pytest.mark.parametrize("budget", [1 << 24, 512])
def test_gat_aggregate_matches_dense_reference(dataset, budget):
    """ELL edge softmax == the dense O(V^2) computation, including
    with the scan-segmented path forced via a tiny budget."""
    g = dataset.graph
    V, F = g.num_nodes, 8
    rng = np.random.RandomState(0)
    h = rng.randn(V, F).astype(np.float32)
    a_src = rng.randn(F).astype(np.float32) * 0.3
    a_dst = rng.randn(F).astype(np.float32) * 0.3

    table = ell_from_graph(g.row_ptr, g.col_idx, V)
    idx = tuple(jnp.asarray(a[0]) for a in table.idx)
    rid = tuple(jnp.asarray(a[0]) for a in table.row_id)
    pos = jnp.asarray(table.row_pos[0])

    full = jnp.concatenate(
        [jnp.asarray(h), jnp.zeros((1, F), jnp.float32)])
    s_full = (full @ jnp.asarray(a_src))[:, None]
    d_local = jnp.concatenate(
        [jnp.asarray(h @ a_dst), jnp.zeros((1,), jnp.float32)])[:, None]
    out = gat_aggregate_ell(full, s_full, d_local, idx, rid, pos, V,
                            budget_elems=budget)
    ref = dense_gat_reference(_adj_from_graph(g), h, a_src, a_dst)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                               atol=2e-5)


def test_gat_zero_degree_rows_are_zero():
    """A row with no in-edges aggregates to exactly 0 (the sum path's
    convention), not NaN from an empty softmax."""
    from roc_tpu.core.graph import from_edge_list
    # node 2 has no in-edges
    g = from_edge_list(np.array([0, 1]), np.array([1, 0]), 3)
    table = ell_from_graph(g.row_ptr, g.col_idx, 3)
    idx = tuple(jnp.asarray(a[0]) for a in table.idx)
    rid = tuple(jnp.asarray(a[0]) for a in table.row_id)
    pos = jnp.asarray(table.row_pos[0])
    h = jnp.asarray(np.random.RandomState(0).randn(3, 4),
                    dtype=jnp.float32)
    full = jnp.concatenate([h, jnp.zeros((1, 4), jnp.float32)])
    s_full = (jnp.ones((4,), jnp.float32) @ full.T)[:, None]
    d_local = jnp.zeros((4, 1), jnp.float32)
    out = gat_aggregate_ell(full, s_full, d_local, idx, rid, pos, 3)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_array_equal(np.asarray(out)[2], 0.0)


def test_multihead_equals_per_slice_single_head(dataset):
    """K-head attention == K independent single-head attentions on the
    K feature slices, concatenated — the defining property of the
    concat form."""
    g = dataset.graph
    V, K, dh = g.num_nodes, 4, 5
    F = K * dh
    rng = np.random.RandomState(1)
    h = rng.randn(V, F).astype(np.float32)
    a_src = rng.randn(K, dh).astype(np.float32) * 0.3
    a_dst = rng.randn(K, dh).astype(np.float32) * 0.3

    table = ell_from_graph(g.row_ptr, g.col_idx, V)
    idx = tuple(jnp.asarray(a[0]) for a in table.idx)
    rid = tuple(jnp.asarray(a[0]) for a in table.row_id)
    pos = jnp.asarray(table.row_pos[0])

    def run(hh, asrc, adst):
        k = asrc.shape[0]
        full = jnp.concatenate(
            [jnp.asarray(hh),
             jnp.zeros((1, hh.shape[1]), jnp.float32)])
        fr = full.reshape(full.shape[0], k, -1)
        s = jnp.einsum("gkd,kd->gk", fr, jnp.asarray(asrc))
        d = jnp.einsum("vkd,kd->vk",
                       jnp.asarray(hh).reshape(V, k, -1),
                       jnp.asarray(adst))
        dl = jnp.concatenate([d, jnp.zeros((1, k), jnp.float32)])
        return np.asarray(gat_aggregate_ell(full, s, dl, idx, rid,
                                            pos, V))

    multi = run(h, a_src, a_dst)
    for k in range(K):
        sl = slice(k * dh, (k + 1) * dh)
        single = run(h[:, sl], a_src[k:k + 1], a_dst[k:k + 1])
        np.testing.assert_allclose(multi[:, sl], single, rtol=1e-5,
                                   atol=1e-6)


def test_multihead_model_converges(dataset):
    model = build_gat([dataset.in_dim, 16, dataset.num_classes],
                      dropout_rate=0.0, heads=4)
    assert model.init_params(
        jax.random.PRNGKey(0))["gat_0_src"].shape == (4, 4)
    cfg = TrainConfig(aggr_impl="ell", verbose=False,
                      eval_every=1 << 30)
    tr = Trainer(model, dataset, cfg)
    tr.train(epochs=60)
    assert tr.evaluate()["train_acc"] > 0.9


def test_gat_heads_must_divide_dim():
    from roc_tpu.models.builder import Model
    m = Model(in_dim=8)
    t = m.input()
    t = m.linear(t, 10)
    with pytest.raises(ValueError, match="divisible"):
        m.gat_attention(t, heads=4)


def test_gat_model_converges(dataset):
    """Correctness by convergence on the synthetic fixture; also pins
    the trainer's attention override (segment -> ell) and that grads
    reach the attention vectors."""
    model = build_gat([dataset.in_dim, 16, dataset.num_classes],
                      dropout_rate=0.0)
    assert model.uses_attention()
    cfg = TrainConfig(aggr_impl="segment", verbose=False,
                      eval_every=1 << 30, learning_rate=0.01)
    tr = Trainer(model, dataset, cfg)
    assert tr.config.aggr_impl == "ell"       # forced for attention
    p0 = np.asarray(tr.params["gat_0_src"]).copy()
    tr.train(epochs=60)
    m = tr.evaluate()
    assert m["train_acc"] > 0.9, m
    assert not np.allclose(np.asarray(tr.params["gat_0_src"]), p0)


def test_gat_distributed_matches_single(dataset):
    """SPMD GAT: 4-part shard_map step converges and its eval agrees
    with a single-device trainer given the same params."""
    from roc_tpu.parallel.distributed import DistributedTrainer
    # heads=4: the multi-head reshape/einsum must agree with the
    # padded-part row order under shard_map, not just single-device
    model = build_gat([dataset.in_dim, 16, dataset.num_classes],
                      dropout_rate=0.0, heads=4)
    cfg = TrainConfig(aggr_impl="ell", verbose=False, chunk=64,
                      eval_every=1 << 30)
    dt = DistributedTrainer(model, dataset, 4, cfg)
    tr = Trainer(model, dataset, cfg)
    tr.params = jax.device_get(dt.params)
    md = dt.evaluate()
    ms = tr.evaluate()
    assert md["train_loss"] == pytest.approx(ms["train_loss"],
                                             rel=1e-4)
    dt.train(epochs=60)
    assert dt.evaluate()["train_acc"] > 0.9


def test_gat_mixed_precision(dataset):
    """Mixed mode: bf16 compute with the fp32 softmax inside the
    attention op — finite, converging."""
    model = build_gat([dataset.in_dim, 16, dataset.num_classes],
                      dropout_rate=0.0)
    cfg = TrainConfig(aggr_impl="ell", verbose=False,
                      eval_every=1 << 30,
                      compute_dtype=jnp.bfloat16)
    tr = Trainer(model, dataset, cfg)
    tr.train(epochs=60)
    m = tr.evaluate()
    assert np.isfinite(m["train_loss"])
    assert m["train_acc"] > 0.85, m


def test_gat_streamable_head(dataset):
    """GAT's first layer (input -> dropout -> linear) qualifies for
    the host-feature streaming tier; training must work with the
    features never device-resident."""
    model = build_gat([dataset.in_dim, 16, dataset.num_classes],
                      dropout_rate=0.5)
    assert model.streamable_head() is not None
    tr = Trainer(model, dataset,
                 TrainConfig(aggr_impl="ell", verbose=False,
                             eval_every=1 << 30, features="host"))
    assert tr.feats is None          # never uploaded whole
    tr.train(epochs=3)
    m = tr.evaluate()
    assert np.isfinite(m["train_loss"])


def test_gat_ring_rejected_at_setup(dataset):
    """halo='ring' + attention fails fast at trainer construction,
    before any ring-table build."""
    from roc_tpu.parallel.distributed import DistributedTrainer
    model = build_gat([dataset.in_dim, 16, dataset.num_classes])
    cfg = TrainConfig(aggr_impl="ell", halo="ring", verbose=False)
    with pytest.raises(NotImplementedError, match="ring"):
        DistributedTrainer(model, dataset, 4, cfg)


def test_gat_rejects_sectioned_tables():
    """A GraphContext without ELL tables raises the actionable error
    rather than silently mis-aggregating."""
    from roc_tpu.models.builder import GraphContext
    gctx = GraphContext(edge_src=jnp.zeros(1, jnp.int32),
                        edge_dst=jnp.zeros(1, jnp.int32),
                        in_degree=jnp.zeros(4, jnp.int32),
                        num_rows=4, gathered_rows=4,
                        aggr_impl="sectioned")
    with pytest.raises(NotImplementedError, match="ELL"):
        gctx.gat_attention(jnp.zeros((4, 2)), jnp.zeros(2),
                           jnp.zeros(2))


# ---------------------------------------------------------------- flat8

def _flat8_tables(g, seg_rows=64):
    from roc_tpu.core.ell import sectioned_from_graph
    sect = sectioned_from_graph(g.row_ptr, g.col_idx, g.num_nodes,
                                src_rows=g.num_nodes,
                                section_rows=g.num_nodes,
                                seg_rows=seg_rows)
    assert len(sect.idx) == 1
    return jnp.asarray(sect.idx[0]), jnp.asarray(sect.sub_dst[0])


def test_flat8_matches_dense_reference(dataset):
    """The uniform width-8 attention layout (the large-graph compile
    path) == the dense O(V^2) computation, with several scan chunks
    forced via a small seg_rows."""
    from roc_tpu.ops.attention import gat_aggregate_flat8
    g = dataset.graph
    V, F = g.num_nodes, 8
    rng = np.random.RandomState(0)
    h = rng.randn(V, F).astype(np.float32)
    a_src = rng.randn(F).astype(np.float32) * 0.3
    a_dst = rng.randn(F).astype(np.float32) * 0.3
    f8i, f8d = _flat8_tables(g, seg_rows=64)
    assert f8i.shape[0] > 1, "need multiple chunks to test the scan"
    full = jnp.concatenate(
        [jnp.asarray(h), jnp.zeros((1, F), jnp.float32)])
    s_full = (full @ jnp.asarray(a_src))[:, None]
    d_local = jnp.concatenate(
        [jnp.asarray(h @ a_dst), jnp.zeros((1,), jnp.float32)])[:, None]
    out = gat_aggregate_flat8(full, s_full, d_local, f8i, f8d, V)
    ref = dense_gat_reference(_adj_from_graph(g), h, a_src, a_dst)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                               atol=2e-5)


def test_flat8_multihead_matches_bucket_path(dataset):
    """flat8 == the bucket path on multi-head inputs (same numerics,
    different reduction structure), and its gradients match too."""
    from roc_tpu.ops.attention import (gat_aggregate_ell,
                                       gat_aggregate_flat8)
    g = dataset.graph
    V, K, dh = g.num_nodes, 4, 5
    F = K * dh
    rng = np.random.RandomState(3)
    h = rng.randn(V, F).astype(np.float32)
    a_src = rng.randn(K, dh).astype(np.float32) * 0.3
    a_dst = rng.randn(K, dh).astype(np.float32) * 0.3
    table = ell_from_graph(g.row_ptr, g.col_idx, V)
    idx = tuple(jnp.asarray(a[0]) for a in table.idx)
    rid = tuple(jnp.asarray(a[0]) for a in table.row_id)
    pos = jnp.asarray(table.row_pos[0])
    f8i, f8d = _flat8_tables(g, seg_rows=64)

    def prep(hh):
        full = jnp.concatenate(
            [hh, jnp.zeros((1, F), jnp.float32)])
        fr = full.reshape(full.shape[0], K, dh)
        s = jnp.einsum("gkd,kd->gk", fr, jnp.asarray(a_src))
        d = jnp.einsum("vkd,kd->vk", hh.reshape(V, K, dh),
                       jnp.asarray(a_dst))
        dl = jnp.concatenate([d, jnp.zeros((1, K), jnp.float32)])
        return full, s, dl

    def via_ell(hh):
        full, s, dl = prep(hh)
        return gat_aggregate_ell(full, s, dl, idx, rid, pos, V)

    def via_flat8(hh):
        full, s, dl = prep(hh)
        return gat_aggregate_flat8(full, s, dl, f8i, f8d, V)

    hj = jnp.asarray(h)
    np.testing.assert_allclose(np.asarray(via_flat8(hj)),
                               np.asarray(via_ell(hj)),
                               rtol=2e-4, atol=2e-5)
    g_ell = jax.grad(lambda x: jnp.sum(via_ell(x) ** 2))(hj)
    g_f8 = jax.grad(lambda x: jnp.sum(via_flat8(x) ** 2))(hj)
    np.testing.assert_allclose(np.asarray(g_f8), np.asarray(g_ell),
                               rtol=2e-3, atol=2e-4)


def test_flat8_dh_chunked_matches_fused(dataset):
    """The dh-chunked numerator (the products-scale OOM fix:
    resolve_dh_chunk) is element-for-element the SAME math as the
    fused pass2 — identical w, identical per-slice einsum reduction
    order, identical scatter-add order — so values match exactly and
    gradients match to fp32 tolerance.  (Values are NOT asserted
    bit-exact: XLA lowers the per-slice einsum differently for
    non-dividing widths — measured <=3e-7 drift.)"""
    from roc_tpu.ops.attention import (gat_aggregate_flat8,
                                       resolve_dh_chunk)
    g = dataset.graph
    V, K, dh = g.num_nodes, 2, 6
    F = K * dh
    rng = np.random.RandomState(7)
    h = rng.randn(V, F).astype(np.float32)
    a_src = rng.randn(K, dh).astype(np.float32) * 0.3
    a_dst = rng.randn(K, dh).astype(np.float32) * 0.3
    f8i, f8d = _flat8_tables(g, seg_rows=64)

    def run(hh, dh_chunk):
        full = jnp.concatenate([hh, jnp.zeros((1, F), jnp.float32)])
        fr = full.reshape(full.shape[0], K, dh)
        s = jnp.einsum("gkd,kd->gk", fr, jnp.asarray(a_src))
        d = jnp.einsum("vkd,kd->vk", hh.reshape(V, K, dh),
                       jnp.asarray(a_dst))
        dl = jnp.concatenate([d, jnp.zeros((1, K), jnp.float32)])
        return gat_aggregate_flat8(full, s, dl, f8i, f8d, V,
                                   dh_chunk=dh_chunk)

    hj = jnp.asarray(h)
    fused = run(hj, None)
    for dc in (1, 4, 5, dh):  # incl. a non-dividing width and ==dh
        np.testing.assert_allclose(np.asarray(run(hj, dc)),
                                   np.asarray(fused),
                                   rtol=1e-6, atol=1e-6)
    g_fused = jax.grad(lambda x: jnp.sum(run(x, None) ** 2))(hj)
    g_chunk = jax.grad(lambda x: jnp.sum(run(x, 4) ** 2))(hj)
    np.testing.assert_allclose(np.asarray(g_chunk),
                               np.asarray(g_fused),
                               rtol=1e-6, atol=1e-6)
    # the resolver: small graphs stay fused; at products scale the
    # per-chunk carry must actually fit the budget (not just split)
    assert resolve_dh_chunk(1000, 1, 256) is None
    dc = resolve_dh_chunk(2_449_029, 1, 256)
    assert dc is not None and dc < 256
    assert (2_449_030 * 1 * dc * 4) <= (768 << 20)


def test_flat8_zero_degree_rows_are_zero():
    from roc_tpu.core.graph import from_edge_list
    from roc_tpu.ops.attention import gat_aggregate_flat8
    g = from_edge_list(np.array([0, 1]), np.array([1, 0]), 3)
    f8i, f8d = _flat8_tables(g, seg_rows=8)
    h = jnp.asarray(np.random.RandomState(0).randn(3, 4),
                    dtype=jnp.float32)
    full = jnp.concatenate([h, jnp.zeros((1, 4), jnp.float32)])
    s_full = (jnp.ones((4,), jnp.float32) @ full.T)[:, None]
    d_local = jnp.zeros((4, 1), jnp.float32)
    out = gat_aggregate_flat8(full, s_full, d_local, f8i, f8d, 3)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_array_equal(np.asarray(out)[2], 0.0)


def test_flat8_end_to_end_and_resolver(dataset):
    """aggr_impl='attn_flat8' trains a GAT end to end to the same
    params as 'ell' (dropout 0 => identical RNG-free paths), and the
    resolver routes big-E attention configs to it automatically."""
    from roc_tpu.train.trainer import (ATTN_FLAT8_MIN_EDGES,
                                       resolve_attention_impl)
    params = {}
    for impl in ("ell", "attn_flat8"):
        model = build_gat([dataset.in_dim, 8, dataset.num_classes],
                          dropout_rate=0.0)
        cfg = TrainConfig(learning_rate=0.02, aggr_impl=impl,
                          verbose=False, eval_every=1 << 30)
        tr = Trainer(model, dataset, cfg)
        tr.train(epochs=3)
        params[impl] = tr.params
    for k in params["ell"]:
        np.testing.assert_allclose(np.asarray(params["ell"][k]),
                                   np.asarray(params["attn_flat8"][k]),
                                   rtol=2e-3, atol=2e-4)

    model = build_gat([dataset.in_dim, 8, dataset.num_classes])
    # small graph: stays on the bucket path
    cfg = resolve_attention_impl(
        model, TrainConfig(aggr_impl="auto", verbose=False), dataset)
    assert cfg.aggr_impl == "ell"
    # big-E graph: routed to flat8 (threshold patched to the fixture)
    import roc_tpu.train.trainer as trmod
    orig = trmod.ATTN_FLAT8_MIN_EDGES
    try:
        trmod.ATTN_FLAT8_MIN_EDGES = dataset.graph.num_edges
        cfg = resolve_attention_impl(
            model, TrainConfig(aggr_impl="auto", verbose=False),
            dataset)
        assert cfg.aggr_impl == "attn_flat8"
    finally:
        trmod.ATTN_FLAT8_MIN_EDGES = orig
    # MAX/MIN models must refuse the attention-only layout
    from roc_tpu.models.sage import build_sage
    pool = build_sage([dataset.in_dim, 8, dataset.num_classes],
                      aggregator="pool")
    with pytest.raises(NotImplementedError, match="attention-only"):
        resolve_attention_impl(
            pool, TrainConfig(aggr_impl="attn_flat8"), dataset)


def test_attn_flat8_rejected_for_sum_models(dataset):
    """A sum-only model with aggr_impl='attn_flat8' fails at resolve
    time, before any table build."""
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import resolve_attention_impl
    gcn = build_gcn([dataset.in_dim, 8, dataset.num_classes])
    with pytest.raises(NotImplementedError, match="attention-only"):
        resolve_attention_impl(
            gcn, TrainConfig(aggr_impl="attn_flat8"), dataset)


def test_gat_distributed_flat8_matches_ell(dataset):
    """Distributed attn_flat8 (single-section uniform tables over
    gathered coordinates, VERDICT r4 weak #3) must reproduce the
    distributed ELL-bucket attention exactly — same model, same seed,
    table layout is the only difference."""
    from roc_tpu.parallel.distributed import DistributedTrainer
    model = build_gat([dataset.in_dim, 16, dataset.num_classes],
                      dropout_rate=0.0, heads=2)
    kw = dict(verbose=False, chunk=64, eval_every=1 << 30,
              learning_rate=0.05)
    te = DistributedTrainer(model, dataset, 4,
                            TrainConfig(aggr_impl="ell", **kw))
    tf = DistributedTrainer(model, dataset, 4,
                            TrainConfig(aggr_impl="attn_flat8", **kw))
    me, mf = te.evaluate(), tf.evaluate()
    assert mf["train_loss"] == pytest.approx(me["train_loss"],
                                             rel=1e-5)
    te.train(epochs=5)
    tf.train(epochs=5)
    for k in te.params:
        np.testing.assert_allclose(np.asarray(tf.params[k]),
                                   np.asarray(te.params[k]),
                                   rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(tf.predict(), te.predict(),
                               rtol=2e-4, atol=2e-4)
