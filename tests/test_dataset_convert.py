"""Dataset converter tests (scripts/convert_dataset.py): parse the real
public raw formats from generated fixture files (no network), round-trip
through the reference on-disk layout, and gate converged accuracy — the
reference's one correctness standard (SURVEY §4)."""

import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

_SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")
sys.path.insert(0, _SCRIPTS)

from convert_dataset import (  # noqa: E402
    convert_dgl_reddit, convert_planetoid, synthetic_cora)
from roc_tpu.core.graph import (  # noqa: E402
    MASK_NONE, MASK_TEST, MASK_TRAIN, MASK_VAL, load_dataset,
    save_dataset)


def _write_planetoid_fixture(raw_dir, name="cora", n_train=8, n_val=6,
                             n_test=5, n_other=4, F=12, C=3):
    """Generate a tiny but format-faithful Planetoid raw set: pickled
    scipy matrices, one-hot label arrays, adjacency dict, test.index."""
    import scipy.sparse as sp
    rng = np.random.RandomState(0)
    V = n_train + n_val + n_other + n_test
    labels = rng.randint(0, C, size=V)
    feats = sp.csr_matrix(
        (rng.rand(V, F) < 0.3).astype(np.float32))
    onehot = np.eye(C, dtype=np.int32)[labels]
    n_all = V - n_test  # allx/ally cover everything but the test tail
    # the real distribution stores tx/ty rows in the PERMUTED order of
    # test.index (the converter re-sorts them); mirror that exactly
    test_idx = n_all + rng.permutation(n_test)
    x, y = feats[:n_train], onehot[:n_train]
    allx, ally = feats[:n_all], onehot[:n_all]
    tx, ty = feats[test_idx], onehot[test_idx]
    graph = {v: [int(u) for u in
                 rng.choice(V, size=rng.randint(1, 4), replace=False)]
             for v in range(V)}
    objs = {"x": x, "y": y, "tx": tx, "ty": ty, "allx": allx,
            "ally": ally, "graph": graph}
    for ext, obj in objs.items():
        with open(os.path.join(raw_dir, f"ind.{name}.{ext}"), "wb") as f:
            pickle.dump(obj, f)
    np.savetxt(os.path.join(raw_dir, f"ind.{name}.test.index"),
               test_idx, fmt="%d")
    return V, F, C, n_train, n_test, labels


def test_planetoid_parser(tmp_path):
    raw = str(tmp_path)
    V, F, C, n_train, n_test, labels = _write_planetoid_fixture(raw)
    ds = convert_planetoid(raw, "cora")
    assert ds.graph.num_nodes == V
    assert ds.in_dim == F and ds.num_classes == C
    assert (ds.mask == MASK_TRAIN).sum() == n_train
    assert (ds.mask == MASK_TEST).sum() == n_test
    np.testing.assert_array_equal(ds.labels, labels)
    assert ds.graph.is_symmetric() and ds.graph.has_all_self_edges()


def test_planetoid_citeseer_gaps_and_permutation(tmp_path):
    """Citeseer's test.index is permuted AND has gaps (isolated nodes
    absent from the raw tx/ty): converted labels/features must land on
    the right nodes, and gap nodes must get zero features and NO test
    mask."""
    import scipy.sparse as sp
    rng = np.random.RandomState(3)
    V, F, C, n_train, n_all = 20, 10, 3, 4, 14
    dense = (rng.rand(V, F) < 0.4).astype(np.float32)
    labels = rng.randint(0, C, size=V)
    onehot = np.eye(C, dtype=np.int32)[labels]
    gap = 17
    test_real = np.array([14, 15, 16, 18, 19])
    test_reorder = test_real[rng.permutation(len(test_real))]
    dense[gap] = 0          # isolated node: no raw features anywhere
    objs = {
        "x": sp.csr_matrix(dense[:n_train]), "y": onehot[:n_train],
        "allx": sp.csr_matrix(dense[:n_all]), "ally": onehot[:n_all],
        "tx": sp.csr_matrix(dense[test_reorder]),
        "ty": onehot[test_reorder],
        "graph": {v: [int((v + 1) % V)] for v in range(V)},
    }
    for ext, obj in objs.items():
        with open(os.path.join(tmp_path, f"ind.citeseer.{ext}"),
                  "wb") as f:
            pickle.dump(obj, f)
    np.savetxt(os.path.join(tmp_path, "ind.citeseer.test.index"),
               test_reorder, fmt="%d")
    ds = convert_planetoid(str(tmp_path), "citeseer")
    assert ds.graph.num_nodes == V
    np.testing.assert_array_equal(ds.labels[test_real],
                                  labels[test_real])
    np.testing.assert_allclose(ds.features[test_real],
                               dense[test_real])
    assert (ds.features[gap] == 0).all()
    assert ds.mask[gap] == MASK_NONE
    assert (ds.mask == MASK_TEST).sum() == len(test_real)


def test_dgl_reddit_parser(tmp_path):
    import scipy.sparse as sp
    rng = np.random.RandomState(1)
    V, F = 40, 6
    feats = rng.rand(V, F).astype(np.float32)
    labels = rng.randint(0, 4, size=V).astype(np.int64)
    types = rng.choice([0, 1, 2, 3], size=V)
    np.savez(os.path.join(tmp_path, "reddit_data.npz"),
             feature=feats, label=labels, node_types=types)
    adj = sp.random(V, V, density=0.1, random_state=2, format="coo")
    sp.save_npz(os.path.join(tmp_path, "reddit_graph.npz"), adj)
    ds = convert_dgl_reddit(str(tmp_path))
    assert ds.graph.num_nodes == V and ds.in_dim == F
    assert (ds.mask == MASK_TRAIN).sum() == (types == 1).sum()
    assert (ds.mask == MASK_VAL).sum() == (types == 2).sum()
    assert ds.graph.is_symmetric() and ds.graph.has_all_self_edges()


def test_missing_raw_files_error(tmp_path):
    with pytest.raises(FileNotFoundError, match="Planetoid"):
        convert_planetoid(str(tmp_path), "cora")
    with pytest.raises(FileNotFoundError, match="Reddit"):
        convert_dgl_reddit(str(tmp_path))


def test_synthetic_cora_shape_and_roundtrip(tmp_path):
    ds = synthetic_cora()
    assert (ds.graph.num_nodes, ds.in_dim, ds.num_classes) == \
        (2708, 1433, 7)
    assert (ds.mask == MASK_TRAIN).sum() == 140
    assert (ds.mask == MASK_VAL).sum() == 500
    assert (ds.mask == MASK_TEST).sum() == 1000
    assert ds.graph.is_symmetric() and ds.graph.has_all_self_edges()
    # determinism: the offline gate must be reproducible
    ds2 = synthetic_cora()
    np.testing.assert_array_equal(ds.graph.col_idx, ds2.graph.col_idx)
    np.testing.assert_array_equal(ds.features, ds2.features)
    # reference on-disk layout round trip (the path the CLI consumes)
    prefix = os.path.join(tmp_path, "cora")
    save_dataset(ds, prefix, csv=False)
    back = load_dataset(prefix, in_dim=1433, num_classes=7)
    np.testing.assert_array_equal(back.graph.row_ptr, ds.graph.row_ptr)
    np.testing.assert_array_equal(back.labels, ds.labels)
    np.testing.assert_array_equal(back.mask, ds.mask)
    np.testing.assert_allclose(back.features, ds.features)


def test_converter_cli_end_to_end(tmp_path):
    """The script's own CLI writes a trainable layout."""
    out = os.path.join(tmp_path, "d", "cora")
    r = subprocess.run(
        [sys.executable, os.path.join(_SCRIPTS, "convert_dataset.py"),
         "--dataset", "cora-synth", "--out", out, "--no-csv"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    assert os.path.exists(out + ".add_self_edge.lux")
    assert os.path.exists(out + ".feats.bin")
    ds = load_dataset(out, in_dim=1433, num_classes=7)
    assert ds.graph.num_nodes == 2708


@pytest.mark.slow
@pytest.mark.parametrize("dtype_mode", ["float32", "mixed"])
def test_cora_accuracy_gate(dtype_mode):
    """BASELINE.md config-1 gate: the 2-layer GCN on the Cora-shaped
    dataset must converge to high semi-supervised test accuracy from
    140 labels (converged value ~93%; asserted with margin).  This is
    the reference's convergence-as-correctness standard
    (softmax_kernel.cu:141-152) on the canonical small config.  The
    'mixed' variant gates that bf16 compute with fp32 master params
    costs no accuracy (measured parity: 93.1% both modes,
    2026-07-30)."""
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import (TrainConfig, Trainer,
                                       resolve_dtypes)
    ds = synthetic_cora()
    model = build_gcn([1433, 16, 7], dropout_rate=0.5)
    dt, cdt = resolve_dtypes(dtype_mode)
    cfg = TrainConfig(learning_rate=0.01, weight_decay=5e-4,
                      epochs=120, eval_every=1 << 30, verbose=False,
                      symmetric=True, dtype=dt, compute_dtype=cdt)
    tr = Trainer(model, ds, cfg)
    tr.train()
    m = tr.evaluate()
    assert m["test_acc"] >= 0.85, m
    assert m["val_acc"] >= 0.85, m


def test_karate_club_is_the_real_graph():
    """The vendored Zachary karate club must be the canonical dataset:
    34 members, 78 undirected friendships, the documented 17/17
    faction split, leaders on opposite sides."""
    from convert_dataset import karate_club
    ds = karate_club()
    assert ds.graph.num_nodes == 34
    # 78 undirected edges -> 156 arcs + 34 self edges
    assert ds.graph.num_edges == 2 * 78 + 34
    assert ds.graph.is_symmetric() and ds.graph.has_all_self_edges()
    assert int(ds.labels.sum()) == 17 and ds.labels.shape == (34,)
    assert ds.labels[0] == 0 and ds.labels[33] == 1
    assert (ds.mask == MASK_TRAIN).sum() == 2
    assert (ds.mask == MASK_TEST).sum() == 30
    # well-known structural facts of the real graph: the two leaders
    # are the highest-degree members
    deg = np.diff(ds.graph.row_ptr)
    top2 = set(np.argsort(-deg)[:2].tolist())
    assert top2 == {0, 33}, deg


def test_karate_real_data_cli_convergence_gate(tmp_path, capsys):
    """A REAL (non-synthetic) graph through the full product path:
    convert CLI -> reference on-disk layout -> train CLI -> accuracy
    floor (VERDICT r3 next-round #5).  The GCN must recover the real
    club fission from 2 labeled leaders at >= 80% test accuracy
    (typical converged value: >= 90%)."""
    out = os.path.join(tmp_path, "d", "karate")
    r = subprocess.run(
        [sys.executable, os.path.join(_SCRIPTS, "convert_dataset.py"),
         "--dataset", "karate", "--out", out],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    assert os.path.exists(out + ".add_self_edge.lux")
    from roc_tpu.train import cli
    rc = cli.main(["--cpu", "--no-compile-cache", "-file", out,
                   "-layers", "34-16-2", "-lr", "0.02", "-decay",
                   "5e-4", "-dropout", "0.0", "-e", "150",
                   "--eval-every", "150", "--impl", "ell"])
    assert rc == 0
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("[INFER]")]
    assert lines, "no INFER output"
    import re
    accs = re.findall(r"test_accuracy:\s*([0-9.]+)%", lines[-1])
    assert accs, lines[-1]
    assert float(accs[0]) >= 80.0, lines[-1]


def test_karate_real_data_new_families_converge(tmp_path, capsys):
    """The beyond-reference families recover the real club fission
    too: REAL data through APPNP (teleport propagation from 2 labeled
    leaders is exactly personalized PageRank's home turf) and GCNII
    (deep stack on a 34-node graph — the oversmoothing stress case)."""
    out = os.path.join(tmp_path, "d", "karate")
    r = subprocess.run(
        [sys.executable, os.path.join(_SCRIPTS, "convert_dataset.py"),
         "--dataset", "karate", "--out", out],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    from roc_tpu.train import cli
    import re
    for extra in (["--model", "appnp", "--hops", "10",
                   "--alpha", "0.1", "-layers", "34-16-2"],
                  ["--model", "gcn2",
                   "-layers", "34-16-16-16-16-2"]):
        rc = cli.main(["--cpu", "--no-compile-cache", "-file", out,
                       "-lr", "0.02", "-decay", "5e-4", "-dropout",
                       "0.0", "-e", "150", "--eval-every", "150",
                       "--impl", "ell"] + extra)
        assert rc == 0, extra
        lines = [ln for ln in capsys.readouterr().out.splitlines()
                 if ln.startswith("[INFER]")]
        accs = re.findall(r"test_accuracy:\s*([0-9.]+)%", lines[-1])
        assert accs and float(accs[0]) >= 80.0, (extra, lines[-1])
