"""Worker process for the serve warm-start tests (ISSUE 11).

Simulates a COLD server deployment: loads a predictor from an exported
artifact (no trainer, no dataset for the precomputed backend), warms
its program set against the persistent cache a previous export process
populated (asserting every program is a warm hit), starts the
microbatch server, and answers queries.  The parent asserts, from the
events artifact and the cache directory, that this process compiled
ZERO new serve programs and that its compile events' program_key set
matches the artifact manifest exactly.

Usage: python serve_worker.py <artifact_dir>
Env:   ROC_TPU_CACHE_DIR (cache), ROC_TPU_EVENTS (events JSONL),
       ROC_TPU_CACHE_MIN_SECS=0 (persist everything).
"""

import json
import sys


def main() -> None:
    art = sys.argv[1]
    from roc_tpu.analysis import force_cpu_rig
    force_cpu_rig()

    from roc_tpu.utils.compile_cache import enable_compile_cache
    d = enable_compile_cache()   # dir + min-secs from env
    assert d, "cache dir must be usable in the worker"

    from roc_tpu.serve.export import load_predictor
    from roc_tpu.serve.server import Server
    pred = load_predictor(art)
    # first-query readiness check: the artifact's programs must all be
    # warm hits against the cache the export populated
    warm = pred.warm(name="serve_worker")
    assert warm["compile_cold"] == 0, warm
    assert warm["compile_warm_hits"] == warm["programs"], warm
    with Server(pred, max_wait_ms=2.0) as srv:
        futs = [srv.submit([i, i + 1]) for i in range(0, 40, 2)]
        rows = [f.result() for f in futs]
        assert all(r.shape[0] == 2 for r in rows)
        stats = srv.stats()
    man = json.load(open(f"{art}/serve_manifest.json"))
    print("WORKER_OK "
          + json.dumps({"n_batches": stats["n_batches"],
                        "programs": len(man["program_keys"])}),
          flush=True)


if __name__ == "__main__":
    main()
