"""Cross-implementation equivalence sweep over randomized graphs.

The aggregation impls (segment / blocked / scan / ell / sectioned /
bdense incl. grouped+u4-packed) must agree on ANY graph — including
the structures that historically broke layouts: zero-degree rows,
hub rows (bucket width >> mean), single-node components, and
empty-ish partitions.  The fixed fixtures
elsewhere pin one shape each; this sweep randomizes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from roc_tpu.core.graph import Dataset, Graph, from_edge_list
from roc_tpu.models.gcn import build_gcn
from roc_tpu.train.trainer import TrainConfig, Trainer, make_graph_context

IMPLS = ("segment", "blocked", "scan", "ell", "sectioned")


def _random_stress_graph(seed: int) -> Graph:
    """Graphs with planted pathologies: hubs, isolated rows, skew."""
    rng = np.random.RandomState(seed)
    V = int(rng.randint(40, 200))
    E = int(rng.randint(V, V * 12))
    src = rng.randint(0, V, size=E)
    dst = rng.randint(0, V, size=E)
    # plant a hub: one destination absorbs 25% of edges
    hub = int(rng.randint(V))
    dst[: E // 4] = hub
    # plant isolated rows by construction: never target the last rows
    iso = max(1, V // 10)
    dst = np.where(dst >= V - iso, (dst - iso) % max(V - iso, 1), dst)
    return from_edge_list(src, dst, V)


@pytest.mark.parametrize("seed", range(6))
def test_aggregation_impls_agree_on_stress_graphs(seed):
    g = _random_stress_graph(seed)
    rng = np.random.RandomState(seed + 100)
    ds = Dataset(graph=g,
                 features=rng.randn(g.num_nodes, 16).astype(np.float32),
                 labels=rng.randint(0, 3, g.num_nodes).astype(np.int32),
                 mask=np.ones(g.num_nodes, np.int32), num_classes=3)
    feats = jnp.asarray(ds.features)
    model = build_gcn([16, 8, 3], dropout_rate=0.0)
    params = model.init_params(jax.random.PRNGKey(seed))
    outs = {}
    for impl in IMPLS:
        gctx = make_graph_context(ds, aggr_impl=impl, chunk=64)
        outs[impl] = np.asarray(
            model.apply(params, feats, gctx, train=False))
    # block-dense variants: min_fill=1 forces tiles on any graph, the
    # planted hub's duplicate edges exercise uint8/u4 multiplicity
    # saturation and the packing fallback, group=4 the padded-run
    # reduction
    for label, kw in (("bdense", {}), ("bdense_g4",
                                       {"bdense_group": 4})):
        gctx = make_graph_context(ds, aggr_impl="bdense", chunk=64,
                                  bdense_min_fill=1, **kw)
        assert gctx.bd_a is not None, label
        outs[label] = np.asarray(
            model.apply(params, feats, gctx, train=False))
    ref = outs["segment"]
    for impl in list(IMPLS[1:]) + ["bdense", "bdense_g4"]:
        np.testing.assert_allclose(outs[impl], ref, rtol=2e-4,
                                   atol=2e-5, err_msg=impl)


@pytest.mark.parametrize("seed", range(3))
def test_distributed_matches_single_on_stress_graphs(seed):
    """4-part SPMD loss == single-device loss on the same stress
    graph with identical params (partition-count invariance under
    hubs/isolated rows)."""
    from roc_tpu.parallel.distributed import DistributedTrainer
    g = _random_stress_graph(seed + 50)
    rng = np.random.RandomState(seed)
    ds = Dataset(graph=g,
                 features=rng.randn(g.num_nodes, 12).astype(np.float32),
                 labels=rng.randint(0, 3, g.num_nodes).astype(np.int32),
                 mask=rng.choice([1, 2, 3], g.num_nodes).astype(np.int32),
                 num_classes=3)
    model = build_gcn([12, 8, 3], dropout_rate=0.0)
    cfg = TrainConfig(aggr_impl="ell", verbose=False, chunk=64,
                      eval_every=1 << 30, symmetric=None)
    dt = DistributedTrainer(model, ds, 4, cfg)
    tr = Trainer(model, ds, cfg)
    tr.params = jax.device_get(dt.params)
    md, ms = dt.evaluate(), tr.evaluate()
    assert md["train_loss"] == pytest.approx(ms["train_loss"],
                                             rel=1e-4)
    assert md["test_correct"] == ms["test_correct"]
