"""Fused-normalization aggregation (ISSUE 1): the fusion pass over the
recorded-op graph, fused-vs-unfused forward/gradient equivalence in
fp32 (<= 1e-5 rel) across impl x halo x model, the TrainConfig knob
plumbing, and the round-5 advisor regressions that ride this PR."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from roc_tpu.core.graph import synthetic_dataset
from roc_tpu.models.builder import Model
from roc_tpu.models.gcn import build_gcn
from roc_tpu.models.gcn2 import build_gcn2
from roc_tpu.models.gin import build_gin
from roc_tpu.models.sgc import build_sgc
from roc_tpu.train.trainer import (TrainConfig, Trainer,
                                   make_graph_context, resolve_fuse)

REL = 1e-5


@pytest.fixture(scope="module")
def dataset():
    return synthetic_dataset(96, 5, in_dim=12, num_classes=4, seed=7)


def _rel_err(a, b):
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    return np.max(np.abs(a - b)) / (np.max(np.abs(a)) + 1e-30)


def _logits_and_grads(model, params, ds, gctx):
    feats = jnp.asarray(ds.features)
    labels = jnp.asarray(ds.labels)
    mask = jnp.asarray(ds.mask)
    logits = model.apply(params, feats, gctx, train=False)

    def loss(p):
        l, _ = model.loss_fn(p, feats, labels, mask, gctx,
                             train=False)
        return l

    return logits, jax.grad(loss)(params)


# ---- the fusion pass itself ----

def test_fuse_rewrites_gcn_chains():
    m = build_gcn([12, 16, 4])
    f = m.fuse_norm_aggregate()
    assert f.num_fused_aggregates() == 2
    kinds = [op.kind for op in f._ops]
    assert "indegree_norm" not in kinds
    assert "scatter_gather" not in kinds
    # the hidden layer's relu folded into the fused op; the output
    # layer's (loss-marked, no relu) did not gain one
    acts = [op.attrs["activation"] for op in f._ops
            if op.kind == "fused_aggregate"]
    assert acts == ["relu", "none"]
    # parameter-name compatibility: the chain is parameter-free
    k0 = set(m.init_params(jax.random.PRNGKey(0)))
    k1 = set(f.init_params(jax.random.PRNGKey(0)))
    assert k0 == k1


def test_fuse_deep_gcn_keeps_residual_consumers():
    # n > 3 adds a dense residual consuming the relu output — the
    # chain (incl. relu) still fuses because only INTERMEDIATES need
    # a single consumer
    m = build_gcn([12, 16, 16, 4])
    f = m.fuse_norm_aggregate()
    assert f.num_fused_aggregates() == 3
    assert any(op.kind == "add" for op in f._ops)


def test_fuse_gcn2_and_sgc():
    assert build_gcn2([12, 16, 16, 4]).fuse_norm_aggregate() \
        .num_fused_aggregates() == 2
    # SGC: k norm->agg->norm hops on raw features, no relus between
    f = build_sgc([12, 4], k=3).fuse_norm_aggregate()
    assert f.num_fused_aggregates() == 3
    assert all(op.attrs["activation"] == "none" for op in f._ops
               if op.kind == "fused_aggregate")


def test_fuse_leaves_models_without_chains_alone():
    m = build_gin([12, 16, 4])
    f = m.fuse_norm_aggregate()
    assert f.num_fused_aggregates() == 0
    assert [op.kind for op in f._ops] == [op.kind for op in m._ops]


def test_fuse_respects_loss_marker_on_intermediate():
    # loss marked on the POST-AGGREGATE norm output is fine (it maps
    # to the fused op's output), but a relu past it must NOT fold
    m = Model(in_dim=8)
    t = m.input()
    t = m.indegree_norm(t)
    t = m.scatter_gather(t)
    t = m.indegree_norm(t)
    m.softmax_cross_entropy(t)
    t = m.relu(t)
    f = m.fuse_norm_aggregate()
    assert f.num_fused_aggregates() == 1
    fa = next(op for op in f._ops if op.kind == "fused_aggregate")
    assert fa.attrs["activation"] == "none"
    assert [op.kind for op in f._ops].count("activation") == 1


def test_fuse_skips_multi_consumer_intermediates():
    # the aggregate output feeds BOTH the post-norm and an add — the
    # chain must not fuse (the intermediate would disappear)
    m = Model(in_dim=8)
    t = m.input()
    n = m.indegree_norm(t)
    s = m.scatter_gather(n)
    p = m.indegree_norm(s)
    q = m.add(p, s)
    m.softmax_cross_entropy(q)
    f = m.fuse_norm_aggregate()
    assert f.num_fused_aggregates() == 0


def test_streamable_agg_head_accepts_fused_prefix():
    f = build_sgc([12, 4], k=2).fuse_norm_aggregate()
    head = f.streamable_agg_head()
    assert head is not None
    prefix_ops, rate, param, tail = head
    assert all(op.kind == "fused_aggregate" for op in prefix_ops)


# ---- fused vs unfused equivalence (forward + grads, fp32) ----

@pytest.mark.parametrize("impl", ["segment", "blocked", "scan", "ell",
                                  "sectioned", "bdense", "pallas"])
@pytest.mark.parametrize("build", [
    lambda: build_gcn([12, 16, 4]),
    lambda: build_gcn([12, 16, 16, 4]),      # deep: dense residual
    lambda: build_gcn2([12, 16, 16, 4]),
    lambda: build_sgc([12, 4], k=2),
], ids=["gcn", "gcn-residual", "gcn2", "sgc"])
def test_fused_matches_unfused_single_device(dataset, impl, build):
    m = build()
    f = m.fuse_norm_aggregate()
    assert f.num_fused_aggregates() > 0
    params = m.init_params(jax.random.PRNGKey(3))
    g0 = make_graph_context(dataset, impl, chunk=8, bdense_min_fill=1)
    g1 = make_graph_context(dataset, impl, chunk=8, bdense_min_fill=1,
                            fuse=True)
    out0, gr0 = _logits_and_grads(m, params, dataset, g0)
    out1, gr1 = _logits_and_grads(f, params, dataset, g1)
    assert _rel_err(out0, out1) < REL
    for k in gr0:
        assert _rel_err(gr0[k], gr1[k]) < REL, k


def test_fused_weight_tables_present(dataset):
    # the table-baked forms actually engage (not the scaling fallback)
    g = make_graph_context(dataset, "ell", fuse=True)
    assert g.ell_w and len(g.ell_w) == len(g.ell_idx)
    g = make_graph_context(dataset, "sectioned", fuse=True)
    assert g.sect_w and len(g.sect_w) == len(g.sect_idx)
    g = make_graph_context(dataset, "bdense", bdense_min_fill=1,
                           fuse=True)
    assert len(g.bd_scale) == 2


@pytest.mark.parametrize("halo", ["gather", "ring"])
def test_fused_matches_unfused_distributed(dataset, halo):
    from roc_tpu.parallel.distributed import DistributedTrainer
    cfg = TrainConfig(aggr_impl="ell", halo=halo, memory="manual",
                      dropout_rate=0.0, verbose=False, epochs=2,
                      eval_every=1 << 30)
    t0 = DistributedTrainer(build_gcn([12, 16, 4], dropout_rate=0.0),
                            dataset, 2,
                            dataclasses.replace(cfg, aggr_fuse="off"))
    t1 = DistributedTrainer(build_gcn([12, 16, 4], dropout_rate=0.0),
                            dataset, 2,
                            dataclasses.replace(cfg, aggr_fuse="on"))
    assert t1.model.num_fused_aggregates() == 2
    assert _rel_err(t0.predict(), t1.predict()) < REL
    # gradients: two full training epochs must keep params aligned
    t0.train(2)
    t1.train(2)
    for k in t0.params:
        assert _rel_err(t0.params[k], t1.params[k]) < 1e-4, k


@pytest.mark.parametrize("halo", ["gather", "ring"])
def test_fused_ring_weight_tables_bake(dataset, halo):
    # shard_dataset actually bakes the weights for the fused model
    from roc_tpu.parallel.distributed import DistributedTrainer
    cfg = TrainConfig(aggr_impl="sectioned", halo=halo,
                      memory="manual", aggr_fuse="on",
                      verbose=False)
    t = DistributedTrainer(build_gcn([12, 16, 4]), dataset, 2, cfg)
    if halo == "ring":
        assert t.data.ring_w
    else:
        assert t.data.sect_w


def test_trainer_fuse_knob_and_equivalence(dataset):
    base = dict(aggr_impl="ell", dropout_rate=0.0, verbose=False,
                memory="manual")
    t_off = Trainer(build_gcn([12, 16, 4], dropout_rate=0.0), dataset,
                    TrainConfig(aggr_fuse="off", **base))
    t_on = Trainer(build_gcn([12, 16, 4], dropout_rate=0.0), dataset,
                   TrainConfig(aggr_fuse="auto", **base))
    assert t_off.model.num_fused_aggregates() == 0
    assert t_on.model.num_fused_aggregates() == 2
    assert _rel_err(np.asarray(t_off.predict()),
                    np.asarray(t_on.predict())) < REL
    with pytest.raises(ValueError, match="aggr_fuse"):
        resolve_fuse(build_gcn([12, 16, 4]),
                     TrainConfig(aggr_fuse="sometimes"))


def test_fused_sgc_host_streaming_matches(dataset):
    # features='host' + fused model: the parameter-free fused prefix
    # streams through stream_prefix_to_host exactly
    base = dict(aggr_impl="segment", dropout_rate=0.0, verbose=False,
                memory="manual", features="host")
    t_off = Trainer(build_sgc([12, 4], k=2), dataset,
                    TrainConfig(aggr_fuse="off", **base))
    t_on = Trainer(build_sgc([12, 4], k=2), dataset,
                   TrainConfig(aggr_fuse="on", **base))
    assert _rel_err(np.asarray(t_off.predict()),
                    np.asarray(t_on.predict())) < REL


# ---- round-5 advisor regressions ----

def test_autopilot_charges_probed_bdense(dataset, monkeypatch):
    """ADVICE r5: when aggr_impl='auto' probe-resolves to bdense, the
    memory autopilot must see the concrete impl and charge the
    A-table budget (extra_table_bytes > 0)."""
    import roc_tpu.train.trainer as tr
    seen = {}
    real_plan = tr.__dict__["apply_memory_autopilot"]

    def fake_probe(graph, out_rows=None, **kw):
        return "bdense", None

    from roc_tpu.core import memory as mem
    real_choose = mem.choose_memory_plan

    def spy_choose(*a, **kw):
        seen["extra"] = kw.get("extra_table_bytes", 0)
        return real_choose(*a, **kw)

    monkeypatch.setattr(tr, "resolve_auto_impl_probed", fake_probe)
    monkeypatch.setattr(mem, "choose_memory_plan", spy_choose)
    cfg = TrainConfig(aggr_impl="auto", memory="auto", verbose=False,
                      bdense_min_fill=1, aggr_fuse="off")
    Trainer(build_gcn([12, 16, 4]), dataset, cfg)
    assert seen["extra"] == cfg.bdense_a_budget > 0


def test_resolve_dh_chunk_sizes_training_carry():
    """ADVICE r5: the flat8 dh chunk is sized against the TRAINING
    carry (forward + cotangent = 2x), not the forward alone."""
    from roc_tpu.ops.attention import resolve_dh_chunk
    budget = 1 << 20
    heads, dh = 1, 64
    # rows chosen so the forward carry fits the budget but 2x does NOT
    rows = (budget * 3 // 4) // (heads * 4 * dh) - 1
    fwd_bytes = (rows + 1) * heads * 4 * dh
    assert fwd_bytes <= budget < 2 * fwd_bytes
    chunk = resolve_dh_chunk(rows, heads, dh, carry_budget=budget)
    assert chunk is not None
    # the chunk's DOUBLED carry fits the stated budget
    assert 2 * (rows + 1) * heads * 4 * chunk <= budget


def test_reorder_overflow_guard_fails_loudly(monkeypatch):
    """ADVICE r5: past the int64 single-key range the relabel raises
    instead of corrupting the CSR (no fallback CAN help: Graph's
    int32 col_idx already caps V below 2^31, where the single key
    always fits — so the guard marks an unrepresentable input)."""
    import roc_tpu.core.reorder as ro
    from roc_tpu.core.graph import add_self_edges, synthetic_graph
    g = add_self_edges(synthetic_graph(60, 4, seed=2))
    perm = np.random.RandomState(0).permutation(60)
    assert ro.apply_graph_order(g, perm).num_edges == g.num_edges
    assert ro.single_key_fits_int64(60)
    assert ro.single_key_fits_int64((1 << 31) - 1)
    assert not ro.single_key_fits_int64(4_000_000_000)
    monkeypatch.setattr(ro, "single_key_fits_int64", lambda v: False)
    with pytest.raises(ValueError, match="single-key int64"):
        ro.apply_graph_order(g, perm)


def test_cli_fences_slow_pallas_impl(capsys):
    """The known-8.4x-slower --impl pallas is rejected without
    --allow-slow-impl (VERDICT weakness #5)."""
    from roc_tpu.train import cli
    rc = cli.main(["--cpu", "--impl", "pallas", "-layers", "8-8-3"])
    assert rc == 2
    assert "--allow-slow-impl" in capsys.readouterr().err
    # with the flag, validation passes the fence (a later, unrelated
    # check rejects this argv — proving the fence stood down)
    rc = cli.main(["--cpu", "--impl", "pallas", "--allow-slow-impl",
                   "--heads", "2", "-layers", "8-8-3"])
    assert rc == 2
    assert "--heads applies" in capsys.readouterr().err
