"""Worker process for the REAL 2-process DCN test.

Spawned by ``tests/test_multihost.py::test_two_process_dcn_parity`` as
two actual OS processes, each with 4 virtual CPU devices, meeting
through ``jax.distributed.initialize`` (Gloo collectives over
loopback) — the first genuine multi-address-space exercise of
``roc_tpu.parallel.multihost.init_distributed`` (the reference's
GASNet/NCCL bootstrap analog; its own multi-rank init is dead-coded,
``gnn.cc:630-642``).

Each process builds ONLY its own partitions' shards via
``shard_dataset_local``, trains 2 epochs through ``DistributedTrainer``
(gradients psum across the 8-device mesh spanning both processes),
evaluates, and predicts.  Process 0 writes metrics + final params +
logits to ``<outdir>/result.npz`` for the parent to compare against a
single-process run of the identical workload.

Usage: python multihost_worker.py <coordinator> <nproc> <pid> <outdir>
       [aggr_impl]
"""

import os
import sys


def main() -> None:
    coordinator, nproc, pid, outdir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    impl = sys.argv[5] if len(sys.argv) > 5 else "ell"
    # 4 virtual CPU devices per process; force CPU via jax.config (the
    # env var alone is overridden by the axon sitecustomize)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4")
    import jax
    jax.config.update("jax_platforms", "cpu")

    from roc_tpu.parallel import multihost as mh
    mh.init_distributed(coordinator, nproc, pid)
    assert jax.process_count() == nproc, jax.process_count()
    assert len(jax.local_devices()) == 4

    import numpy as np
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.core.partition import partition_graph
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.train.trainer import TrainConfig

    n_parts = 4 * nproc
    ds = synthetic_dataset(16 * n_parts, 6, in_dim=12, num_classes=3,
                           seed=0)
    mesh = mh.make_parts_mesh(n_parts)
    local = mh.process_local_parts(mesh)
    # locality layout: this process owns a contiguous block of 4 parts
    assert len(local) == 4, local
    # min_fill=8 for bdense: the tiny fixture must actually yield
    # dense tiles so the cross-process block-count agreement is real
    cfg = TrainConfig(epochs=2, verbose=False, aggr_impl=impl,
                      bdense_min_fill=8,
                      symmetric=True, dropout_rate=0.0,
                      eval_every=1 << 30)
    pg = partition_graph(ds.graph, n_parts, node_multiple=8,
                         edge_multiple=cfg.chunk)
    data = mh.shard_dataset_local(ds, pg, mesh, aggr_impl=impl,
                                  bdense_min_fill=8)
    tr = DistributedTrainer(build_gcn([12, 8, 3], dropout_rate=0.0),
                            ds, n_parts, cfg, mesh=mesh, data=data,
                            pg=pg)
    tr.train(epochs=2)
    m = tr.evaluate()
    logits = tr.predict()
    if pid == 0:
        out = {f"param_{k}": np.asarray(v) for k, v in tr.params.items()}
        out["logits"] = logits
        out["train_loss"] = np.float64(m["train_loss"])
        out["train_acc"] = np.float64(m["train_acc"])
        np.savez(os.path.join(outdir, "result.npz"), **out)
    print(f"WORKER_OK pid={pid}", flush=True)


if __name__ == "__main__":
    main()
