"""Parity tests: native C++ data layer (native/rocio.cc via ctypes)
vs the pure-numpy reference implementations."""

import os
import tempfile

import numpy as np
import pytest

from roc_tpu import native
from roc_tpu.core import graph as G
from roc_tpu.core import partition as P

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native librocio.so not built")


@pytest.fixture(scope="module")
def graph():
    return G.synthetic_graph(500, 12, seed=3, power_law=True)


def test_lux_roundtrip(graph, tmp_path):
    p = str(tmp_path / "t.lux")
    G.save_lux(graph, p)
    row_ptr, col_idx = native.load_lux(p)
    assert np.array_equal(row_ptr, graph.row_ptr)
    assert np.array_equal(col_idx, graph.col_idx)
    p2 = str(tmp_path / "t2.lux")
    native.save_lux(p2, graph.row_ptr, graph.col_idx)
    g2 = G.load_lux(p2)
    assert np.array_equal(g2.row_ptr, graph.row_ptr)
    assert np.array_equal(g2.col_idx, graph.col_idx)


def test_lux_read_rejects_corrupt(tmp_path):
    p = str(tmp_path / "bad.lux")
    with open(p, "wb") as f:
        f.write(b"\x05\x00\x00\x00")  # header truncated
    with pytest.raises(IOError):
        native.load_lux(p)


def test_features_csv(tmp_path):
    feats = np.random.RandomState(0).randn(50, 7).astype(np.float32)
    p = str(tmp_path / "x.feats.csv")
    np.savetxt(p, feats, delimiter=",", fmt="%.6e")
    got = native.load_features_csv(p, 50, 7)
    np.testing.assert_allclose(got, feats, atol=1e-5)


def test_features_csv_shape_mismatch_raises(tmp_path):
    """A wrong column count must raise, not silently mis-align rows
    (parity with the numpy fallback's reshape error)."""
    feats = np.arange(16, dtype=np.float32).reshape(4, 4)
    p = str(tmp_path / "x.feats.csv")
    np.savetxt(p, feats, delimiter=",", fmt="%.1f")
    with pytest.raises(IOError):
        native.load_features_csv(p, 4, 2)   # under-declared cols
    with pytest.raises(IOError):
        native.load_features_csv(p, 4, 8)   # over-declared cols


def test_mask_parser(tmp_path):
    names = ["Train", "Val", "Test", "None"]
    vals = np.random.RandomState(1).randint(0, 4, size=200)
    p = str(tmp_path / "m.mask")
    with open(p, "w") as f:
        f.write("\n".join(names[v] for v in vals) + "\n")
    got = native.load_mask(p, 200)
    want = np.array([[1, 2, 3, 0][v] for v in vals], dtype=np.int32)
    assert np.array_equal(got, want)


@pytest.mark.parametrize("num_parts", [1, 2, 4, 7])
def test_bounds_parity(graph, num_parts, monkeypatch):
    nb = [tuple(b) for b in
          native.edge_balanced_bounds(graph.row_ptr, num_parts)]
    # force the pure-python sweep for comparison
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)
    pb = P.edge_balanced_bounds(graph.row_ptr, num_parts)
    assert nb == pb


def test_add_self_edges_parity(monkeypatch):
    base = G.from_edge_list(np.array([0, 1, 2, 4, 2]),
                            np.array([1, 2, 3, 4, 2]), 6)
    row_ptr, col_idx = native.add_self_edges(base.row_ptr, base.col_idx)
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)
    ref = G.add_self_edges(base)
    assert np.array_equal(row_ptr, ref.row_ptr)
    assert np.array_equal(col_idx, ref.col_idx)


def test_ell_widths(graph):
    w = native.ell_widths(graph.row_ptr, 8)
    deg = np.diff(graph.row_ptr)
    for d, got in zip(deg, w):
        if d == 0:
            assert got == 0
        else:
            want = 8
            while want < d:
                want *= 2
            assert got == want


def test_sectioned_native_matches_numpy():
    """The native sectioned prep (counts + fill) must produce
    byte-identical tables to the numpy builder across multi-section,
    multi-chunk, plan-forced shapes."""
    import roc_tpu.core.ell as ell_mod
    from roc_tpu import native
    from roc_tpu.core.graph import add_self_edges, synthetic_graph
    if not native.available():
        pytest.skip("native library unavailable")
    g = add_self_edges(synthetic_graph(400, 9, seed=13, power_law=True))

    def build():
        return ell_mod.sectioned_from_graph(
            g.row_ptr, g.col_idx, g.num_nodes, section_rows=64,
            seg_rows=32)

    got = build()
    # force the numpy fallback
    orig = native.available
    try:
        native.available = lambda: False
        want = build()
    finally:
        native.available = orig
    assert got.sec_sizes == want.sec_sizes
    assert len(got.idx) == len(want.idx)
    for a, b in zip(got.idx, want.idx):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(got.sub_dst, want.sub_dst):
        np.testing.assert_array_equal(a, b)
    # counts pass parity too
    nc = native.sectioned_counts(g.row_ptr, g.col_idx, g.num_nodes,
                                 64, -(-g.num_nodes // 64))
    try:
        native.available = lambda: False
        pc = ell_mod.section_sub_counts(g.row_ptr, g.col_idx,
                                        g.num_nodes, g.num_nodes, 64)
    finally:
        native.available = orig
    np.testing.assert_array_equal(nc, pc)


def test_sectioned_native_rejects_out_of_range_cols():
    """Out-of-range columns must be a clean error, not a silent heap
    write (other native entry points validate the same way)."""
    from roc_tpu import native
    if not native.available():
        pytest.skip("native library unavailable")
    row_ptr = np.array([0, 2], dtype=np.int64)
    col_bad = np.array([0, 64], dtype=np.int32)  # 64 == src_rows: OOB
    with pytest.raises(ValueError, match="roc_sectioned_counts"):
        native.sectioned_counts(row_ptr, col_bad, 1, 64, 1)
    with pytest.raises(ValueError, match="roc_sectioned_fill"):
        native.sectioned_fill(row_ptr, col_bad, 1, 64,
                              np.array([64], dtype=np.int64),
                              np.array([8], dtype=np.int64))


def test_block_plan_native_matches_numpy():
    """Native census+fill must produce a byte-identical BlockPlan to
    the numpy pipeline (dense tables, key order, residual CSR,
    saturation behavior)."""
    if not native.available():
        pytest.skip("librocio not built")
    import roc_tpu.native as native_mod
    from roc_tpu.core.graph import Graph, planted_community_csr
    from roc_tpu.ops import blockdense as bd

    g = planted_community_csr(700, 10_000, community_rows=128,
                              intra_frac=0.85, shuffle=False, seed=9)
    # inject heavy duplicates to exercise the saturation path
    row_ptr = np.concatenate([[0], g.row_ptr[1:] + 300])
    col = np.concatenate([np.full(300, 5, dtype=np.int32), g.col_idx])
    g2 = Graph(row_ptr=row_ptr.astype(np.int64), col_idx=col)
    for min_fill, budget in ((8, None), (16, 3 * 128 * 128), (1, None)):
        pn = bd.plan_blocks(g2.row_ptr, g2.col_idx, g2.num_nodes,
                            min_fill=min_fill, a_budget_bytes=budget)
        avail = native_mod.available
        native_mod.available = lambda: False
        try:
            pp = bd.plan_blocks(g2.row_ptr, g2.col_idx, g2.num_nodes,
                                min_fill=min_fill,
                                a_budget_bytes=budget)
        finally:
            native_mod.available = avail
        np.testing.assert_array_equal(pn.a_blocks, pp.a_blocks)
        np.testing.assert_array_equal(pn.src_blk, pp.src_blk)
        np.testing.assert_array_equal(pn.dst_blk, pp.dst_blk)
        np.testing.assert_array_equal(pn.res_row_ptr, pp.res_row_ptr)
        np.testing.assert_array_equal(pn.res_col, pp.res_col)
        assert pn.dense_edges == pp.dense_edges


def test_block_plan_rectangular_native_matches_numpy():
    """num_cols > num_rows (the distributed local-rows x gathered-
    coords plan): native and numpy paths agree byte-for-byte, and
    src tiles index the WIDE space."""
    if not native.available():
        pytest.skip("librocio not built")
    import roc_tpu.native as native_mod
    from roc_tpu.ops import blockdense as bd

    rng = np.random.RandomState(7)
    num_rows, num_cols, E = 200, 900, 4000
    # concentrate sources high so src tiles beyond the square range
    # are exercised
    col = np.sort(rng.randint(500, num_cols, size=E)).astype(np.int32)
    rng.shuffle(col)
    deg = rng.multinomial(E, np.ones(num_rows) / num_rows)
    row_ptr = np.zeros(num_rows + 1, dtype=np.int64)
    np.cumsum(deg, out=row_ptr[1:])
    col.sort()  # per-row order irrelevant; global sort is fine
    pn = bd.plan_blocks(row_ptr, col, num_rows, min_fill=8,
                        num_cols=num_cols)
    avail = native_mod.available
    native_mod.available = lambda: False
    try:
        pp = bd.plan_blocks(row_ptr, col, num_rows, min_fill=8,
                            num_cols=num_cols)
    finally:
        native_mod.available = avail
    assert pn.src_vpad == -(-num_cols // bd.BLOCK) * bd.BLOCK
    assert pn.src_blk.max() >= num_rows // bd.BLOCK  # wide space hit
    for a, b in ((pn.a_blocks, pp.a_blocks), (pn.src_blk, pp.src_blk),
                 (pn.dst_blk, pp.dst_blk),
                 (pn.res_row_ptr, pp.res_row_ptr),
                 (pn.res_col, pp.res_col)):
        np.testing.assert_array_equal(a, b)
    assert pn.dense_edges == pp.dense_edges
