"""Mixed precision (fp32 master params + bf16 compute).

The reference is pure fp32 (``linear_kernel.cu:76-80``); the TPU
rebuild adds ``TrainConfig.compute_dtype=bfloat16`` as the
hardware-native mode: features/activations in bf16 (halving HBM
traffic on the bandwidth-bound aggregation), params + Adam state in
fp32 so the optimizer's small updates don't round away.  These tests
pin the contract: master params stay fp32, grads arrive fp32,
convergence matches the fp32 run, and the distributed step agrees.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from roc_tpu.core.graph import synthetic_dataset
from roc_tpu.models.gcn import build_gcn
from roc_tpu.train.trainer import (TrainConfig, Trainer, cast_floats,
                                   compute_dtype_of)


@pytest.fixture(scope="module", autouse=True)
def _fresh_executables():
    """Long single-process suite runs on this host intermittently
    corrupt params mid-module (sign-flips / denormal garbage in the
    exact-equality roundtrip below; reproduced on unmodified seed
    trees, never in isolation) — shed the ~200 prior tests'
    accumulated native JIT state before the knife-edge bf16 module
    runs.  Assertions are untouched: a real checkpoint-field
    regression still fails deterministically."""
    jax.clear_caches()
    yield
    jax.clear_caches()


@pytest.fixture(scope="module")
def dataset():
    return synthetic_dataset(256, 8, in_dim=16, num_classes=4, seed=0)


def _cfg(**kw):
    kw.setdefault("verbose", False)
    kw.setdefault("aggr_impl", "ell")
    kw.setdefault("eval_every", 1 << 30)
    return TrainConfig(**kw)


def test_compute_dtype_resolution():
    assert compute_dtype_of(_cfg()) == jnp.float32
    assert compute_dtype_of(_cfg(dtype=jnp.bfloat16)) == jnp.bfloat16
    assert compute_dtype_of(
        _cfg(compute_dtype=jnp.bfloat16)) == jnp.bfloat16


def test_cast_floats_leaves_ints_alone():
    tree = {"w": jnp.ones((2, 2), jnp.float32),
            "idx": jnp.zeros((3,), jnp.int32)}
    out = cast_floats(tree, jnp.bfloat16)
    assert out["w"].dtype == jnp.bfloat16
    assert out["idx"].dtype == jnp.int32


def test_mixed_master_params_stay_fp32(dataset):
    model = build_gcn([dataset.in_dim, 32, dataset.num_classes],
                      dropout_rate=0.5)
    tr = Trainer(model, dataset, _cfg(compute_dtype=jnp.bfloat16))
    tr.train(epochs=3)
    for leaf in jax.tree_util.tree_leaves(tr.params):
        assert leaf.dtype == jnp.float32
    for leaf in jax.tree_util.tree_leaves(tr.opt_state.m):
        assert leaf.dtype == jnp.float32
    # features/activations really are bf16 on the compute path
    assert tr.feats.dtype == jnp.bfloat16


@pytest.mark.parametrize("impl", ["ell", "sectioned", "segment"])
def test_mixed_converges_like_fp32(dataset, impl):
    """The correctness-by-convergence gate (SURVEY §4) must hold in
    mixed mode: same synthetic task, accuracy within a few points of
    the fp32 run."""
    model = build_gcn([dataset.in_dim, 32, dataset.num_classes],
                      dropout_rate=0.0)
    accs = {}
    for name, cfg in (
            ("fp32", _cfg(aggr_impl=impl)),
            ("mixed", _cfg(aggr_impl=impl,
                           compute_dtype=jnp.bfloat16))):
        tr = Trainer(model, dataset, cfg)
        tr.train(epochs=40)
        accs[name] = tr.evaluate()["train_acc"]
    assert accs["fp32"] > 0.9
    assert accs["mixed"] > accs["fp32"] - 0.05, accs


def test_mixed_first_loss_close_to_fp32(dataset):
    """Before any updates the two modes see the same params; the bf16
    forward may only differ by rounding, not by orders of magnitude."""
    model = build_gcn([dataset.in_dim, 32, dataset.num_classes],
                      dropout_rate=0.0)
    losses = {}
    for name, cfg in (("fp32", _cfg()),
                      ("mixed", _cfg(compute_dtype=jnp.bfloat16))):
        tr = Trainer(model, dataset, cfg)
        losses[name] = tr.evaluate()["train_loss"]
    assert losses["mixed"] == pytest.approx(losses["fp32"], rel=0.05)


def test_distributed_mixed(dataset):
    """SPMD mixed step: fp32 master params replicated, bf16 sharded
    features, finite psum'd loss, accuracy comparable to the
    single-device mixed run."""
    from roc_tpu.parallel.distributed import DistributedTrainer
    model = build_gcn([dataset.in_dim, 32, dataset.num_classes],
                      dropout_rate=0.0)
    cfg = _cfg(compute_dtype=jnp.bfloat16, chunk=64)
    tr = DistributedTrainer(model, dataset, 4, cfg)
    tr.train(epochs=40)
    for leaf in jax.tree_util.tree_leaves(tr.params):
        assert leaf.dtype == jnp.float32
    assert tr.data.feats.dtype == jnp.bfloat16
    m = tr.evaluate()
    assert np.isfinite(m["train_loss"])
    assert m["train_acc"] > 0.85


def test_resolve_dtypes_mapping():
    from roc_tpu.train.trainer import resolve_dtypes
    assert resolve_dtypes("float32") == (jnp.float32, None)
    assert resolve_dtypes("bfloat16") == (jnp.bfloat16, None)
    assert resolve_dtypes("mixed") == (jnp.float32, jnp.bfloat16)
    with pytest.raises(ValueError):
        resolve_dtypes("fp16")


def test_mixed_streamed_head(dataset):
    """features='host' under mixed precision: the streamed head must
    run the blocks (and hence Y and the tail) in bf16 — the footprint
    the autopilot sized — while dW comes back fp32 for the master
    param."""
    model = build_gcn([dataset.in_dim, 32, dataset.num_classes],
                      dropout_rate=0.5)
    tr = Trainer(model, dataset,
                 _cfg(compute_dtype=jnp.bfloat16, features="host"))
    # the host copy itself is bf16 so device_put ships 2-byte blocks
    assert tr.feats_host.dtype == jnp.dtype(jnp.bfloat16)
    w0 = tr.params[tr._head_param].astype(tr.compute)
    y = tr._head.forward(w0, tr.feats_host, None, False)
    assert y.dtype == jnp.bfloat16
    tr.train(epochs=3)
    for leaf in jax.tree_util.tree_leaves(tr.params):
        assert leaf.dtype == jnp.float32
    m = tr.evaluate()
    assert np.isfinite(m["train_loss"])


def test_mixed_checkpoint_roundtrip(tmp_path, dataset):
    """Checkpoint/resume under mixed precision: the restored trainer
    keeps fp32 master params (the template's dtype wins) and training
    continues from the same state.

    The config deliberately sits OFF the numeric knife edge: the old
    TrainConfig-default ``weight_decay=0.05`` with bf16 compute NaN'd
    under CPU thread-pool load on slow full-suite runs (load-
    correlated flake, CHANGES PR 4) — the roundtrip contract under
    test is dtype/state preservation, not survival at an extreme
    hyperparameter, so wd is pinned small here."""
    from roc_tpu.utils.checkpoint import (checkpoint_trainer,
                                          restore_trainer)
    model = build_gcn([dataset.in_dim, 32, dataset.num_classes],
                      dropout_rate=0.5)
    cfg = _cfg(compute_dtype=jnp.bfloat16, weight_decay=1e-3)
    tr = Trainer(model, dataset, cfg)
    tr.train(epochs=3)
    path = str(tmp_path / "ckpt.npz")
    checkpoint_trainer(tr, path)
    tr2 = Trainer(model, dataset, cfg)
    restore_trainer(tr2, path)
    assert tr2.epoch == tr.epoch
    for a, b in zip(jax.tree_util.tree_leaves(tr.params),
                    jax.tree_util.tree_leaves(tr2.params)):
        assert b.dtype == jnp.float32
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    tr2.train(epochs=1)
    assert np.isfinite(tr2.evaluate()["train_loss"])


def test_mixed_checkpoint_roundtrip_deterministic(tmp_path, dataset):
    """Fast deterministic regression variant of the roundtrip: no
    dropout, one epoch, and the restored trainer's next step must
    reproduce the original trainer's next step EXACTLY (same key
    stream, same params, full-batch training — any divergence is a
    checkpoint field gone missing, not noise)."""
    from roc_tpu.utils.checkpoint import (checkpoint_trainer,
                                          restore_trainer)
    model = build_gcn([dataset.in_dim, 16, dataset.num_classes],
                      dropout_rate=0.0)
    cfg = _cfg(compute_dtype=jnp.bfloat16, weight_decay=1e-3,
               dropout_rate=0.0)
    tr = Trainer(model, dataset, cfg)
    tr.train(epochs=1)
    path = str(tmp_path / "ckpt.npz")
    checkpoint_trainer(tr, path)
    tr2 = Trainer(model, dataset, cfg)
    restore_trainer(tr2, path)
    tr.train(epochs=1)
    tr2.train(epochs=1)
    for a, b in zip(jax.tree_util.tree_leaves(tr.params),
                    jax.tree_util.tree_leaves(tr2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pure_bf16_unchanged(dataset):
    """dtype=bf16 without compute_dtype keeps the old all-bf16
    semantics (params included) — the knob is additive."""
    model = build_gcn([dataset.in_dim, 32, dataset.num_classes])
    tr = Trainer(model, dataset, _cfg(dtype=jnp.bfloat16))
    tr.train(epochs=2)
    for leaf in jax.tree_util.tree_leaves(tr.params):
        assert leaf.dtype == jnp.bfloat16
