"""Unified distributed timeline (roc_tpu/obs/timeline.py) + crash
flight recorder (roc_tpu/obs/events.py): cross-process trace merge,
clock-sync alignment, Perfetto export, and the dumps fatal paths
leave behind."""

import glob
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from roc_tpu.obs.timeline import (clock_offsets, merge_timeline,
                                  straggler_records)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def _shed_native_jit_state():
    """The flight-recorder / straggler tests below compile trainer
    steps into the pytest process; shed the accumulated native JIT
    state when the module ends (the PR-7/8 mitigation for the known
    jaxlib-0.4.x XLA:CPU corruption flake under per-process compile
    churn — test_flat_sum / test_mixed_precision / test_drills carry
    the same fixture)."""
    yield
    import jax
    jax.clear_caches()


def _ev(cat, t, mono, proc, host="hostA", **fields):
    return {"t": t, "mono": mono, "host": host, "proc": proc,
            "cat": cat, "msg": f"{cat} event", **fields}


def _stream(proc, mono_base, sync_wall=1000.0, host="hostA"):
    """One synthetic per-process stream: manifest, clock_sync at
    ``sync_wall`` (all procs' walls agree; monotonic bases do NOT),
    and a spans batch with a lap starting 0.5 s after the sync."""
    return [
        _ev("manifest", sync_wall - 2.0, mono_base - 2.0, proc,
            host=host),
        _ev("timeline", sync_wall, mono_base, proc, host=host,
            kind="clock_sync", epoch=0),
        _ev("timeline", sync_wall + 1.0, mono_base + 1.0, proc,
            host=host, kind="spans",
            spans=[["train", mono_base + 0.5, 400.0]]),
    ]


# ------------------------------------------------- merge (synthetic)

def test_clock_offsets_align_on_sync():
    """Four processes whose monotonic bases differ by hundreds of
    seconds must land their sync points on one instant, so the lap
    each started 0.5 s after its own sync renders simultaneous."""
    events = []
    for p in range(4):
        events += _stream(p, mono_base=100.0 + 500.0 * p)
    offs = clock_offsets(events)
    assert len(offs) == 4
    aligned = {(h, p): off + (100.0 + 500.0 * p)
               for (h, p), off in offs.items()}
    vals = list(aligned.values())
    assert max(vals) - min(vals) < 1e-6   # sync points coincide

    doc = merge_timeline(events)
    meta = doc["roc_tpu"]
    assert len(meta["processes"]) == 4            # lane per process
    assert all(pr["aligned"] for pr in meta["processes"])
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(spans) == 4
    assert len({e["pid"] for e in spans}) == 4
    # the four train laps start within float noise of each other
    ts = [e["ts"] for e in spans]
    assert max(ts) - min(ts) < 1.0                # us


def test_merge_unsynced_stream_falls_back_to_wall():
    """A stream without a clock_sync handshake (legacy artifact) wall-
    aligns on its first stamped record instead of being dropped."""
    events = _stream(0, mono_base=100.0)
    events += [
        _ev("manifest", 1000.5, 7.0, 1, host="hostB"),
        _ev("timeline", 1001.0, 7.5, 1, host="hostB", kind="spans",
            spans=[["train", 7.2, 100.0]]),
    ]
    doc = merge_timeline(events)
    assert len(doc["roc_tpu"]["processes"]) == 2
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in spans} == {1, 2}


def test_merge_legacy_records_without_clock_tuple():
    """Pre-clock-tuple records (no mono/host/proc) collapse into one
    lane placed by wall time — never an error."""
    events = [{"t": 10.0, "cat": "stall", "msg": "x", "stage": "s",
               "elapsed_s": 5.0},
              {"t": 11.0, "cat": "compile", "msg": "c",
               "name": "train_step", "lower_s": 0.5, "compile_s": 1.0}]
    doc = merge_timeline(events)
    assert len(doc["roc_tpu"]["processes"]) == 1
    names = {e["name"] for e in doc["traceEvents"]}
    assert "stall:s" in names and "compile:train_step" in names


def test_span_nesting_h2d_lane():
    """h2d block waits render on their own thread lane, nested inside
    the phase span that staged them."""
    events = [
        _ev("timeline", 1000.0, 50.0, 0, kind="clock_sync"),
        _ev("timeline", 1002.0, 52.0, 0, kind="spans",
            spans=[["head_forward", 50.5, 1000.0],
                   ["h2d_wait", 50.6, 20.0],
                   ["h2d_wait", 50.9, 15.0]]),
    ]
    doc = merge_timeline(events)
    phase = next(e for e in doc["traceEvents"]
                 if e["ph"] == "X" and e["name"] == "head_forward")
    h2d = [e for e in doc["traceEvents"]
           if e["ph"] == "X" and e["name"] == "h2d_wait"]
    assert len(h2d) == 2
    assert all(e["tid"] != phase["tid"] for e in h2d)
    for e in h2d:   # nesting: wait intervals inside the phase span
        assert phase["ts"] <= e["ts"]
        assert e["ts"] + e["dur"] <= phase["ts"] + phase["dur"]


def test_straggler_records_and_markers():
    events = [
        _ev("timeline", 1000.0, 50.0, 0, kind="clock_sync"),
        _ev("costmodel", 1001.0, 51.0, 0, kind="straggler", epoch=3,
            straggler_part=2, straggler_ratio=1.4, measured_ms=120.0,
            num_parts=4),
        _ev("resilience", 1002.0, 52.0, 0, kind="fault",
            site="sigkill", epoch=4),
    ]
    recs = straggler_records(events)
    assert recs == [{"epoch": 3, "part": 2, "ratio": 1.4,
                     "measured_ms": 120.0, "proc": 0, "num_parts": 4}]
    doc = merge_timeline(events)
    assert doc["roc_tpu"]["straggler"] == recs
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "i"}
    assert "straggler:part2" in names
    assert "fault:sigkill" in names


# ----------------------------- per-request distributed trace (PR 17)

RID = "abc1-7"


def _request_run(hedge=False, failover=False, stray_s=None):
    """Synthetic 3-process serve run: the router (proc 0) routes RID
    end to end, replica procs 1/2 run microbatch spans carrying it in
    their rids args.  Wall syncs agree at 1000.0; monotonic bases are
    hundreds of seconds apart, so a connected trace PROVES the merge
    aligned the lanes."""
    events = []
    for p in range(3):
        events.append(_ev("timeline", 1000.0, 100.0 + 500.0 * p, p,
                          kind="clock_sync", epoch=0))
    # router: the end-to-end route span, 0.2 s after sync, 600 ms
    events.append(_ev("timeline", 1001.0, 101.0, 0, kind="spans",
                      spans=[["route_request", 100.2, 600.0,
                              {"rid": RID, "version": 3}]]))
    # replica 1: RID's primary microbatch inside the route interval,
    # plus an unrelated request's microbatch that must stay OUT
    events.append(_ev("timeline", 1001.0, 601.0, 1, kind="spans",
                      spans=[["microbatch", 600.25, 120.0,
                              {"batch": 1, "rows": 4, "version": 3,
                               "rids": [RID]}],
                            ["microbatch", 600.05, 80.0,
                             {"batch": 0, "rows": 2, "version": 3,
                              "rids": ["other-1"]}]]))
    if hedge:
        events.append(_ev("serve", 1000.45, 100.45, 0, kind="hedge",
                          replica=2, rid=RID))
        events.append(_ev("timeline", 1001.0, 1101.0, 2, kind="spans",
                          spans=[["microbatch", 1100.5, 100.0,
                                  {"batch": 0, "rows": 4, "version": 3,
                                   "rids": [RID]}]]))
    if failover:
        events.append(_ev("serve", 1000.4, 100.4, 0, kind="failover",
                          replica=1, requeued=1, rids=[RID]))
        events.append(_ev("timeline", 1001.0, 1101.0, 2, kind="spans",
                          spans=[["microbatch", 1100.45, 150.0,
                                  {"batch": 0, "rows": 4, "version": 3,
                                   "rids": [RID]}]]))
    if stray_s is not None:
        # a RID-tagged span far outside the route interval — an
        # orphaned fragment the connectivity check must flag
        events.append(_ev("timeline", 1003.0, 1103.0, 2, kind="spans",
                          spans=[["microbatch", 1100.0 + stray_s, 90.0,
                                  {"rids": [RID]}]]))
    return events


def test_request_trace_hedged_single_connected():
    """A hedged request — primary microbatch on replica 1, hedge
    marker on the router, hedge microbatch on replica 2 — renders as
    ONE connected trace spanning all three lanes, with the unrelated
    request's microbatch excluded."""
    from roc_tpu.timeline import request_trace
    doc = merge_timeline(_request_run(hedge=True))
    tr = request_trace(doc, RID)
    assert tr["connected"] is True
    assert tr["n_events"] == 4
    assert len(tr["lanes"]) == 3
    names = [e["name"] for e in tr["events"]]
    assert "route_request" in names
    assert "serve:hedge" in names
    assert names.count("microbatch") == 2
    for e in tr["events"]:
        assert "other-1" not in (e["args"].get("rids") or [])


def test_request_trace_failover_requeue_single_connected():
    """A failover-requeued request — replica 1's batch orphaned, the
    router's failover marker carrying the rid, the requeued batch on
    replica 2 — still merges into one connected trace."""
    from roc_tpu.obs.timeline import request_trace
    doc = merge_timeline(_request_run(failover=True))
    tr = request_trace(doc, RID)
    assert tr["connected"] is True
    assert len(tr["lanes"]) == 3
    names = [e["name"] for e in tr["events"]]
    assert "serve:failover" in names
    marker = next(e for e in tr["events"]
                  if e["name"] == "serve:failover")
    assert marker["args"]["replica"] == 1


def test_request_trace_orphan_fragment_not_connected():
    """A rid-tagged span far outside the route_request interval is an
    orphaned fragment: the trace still collects it, but connectivity
    goes False instead of papering over the gap."""
    from roc_tpu.obs.timeline import request_trace
    doc = merge_timeline(_request_run(stray_s=1.5))
    tr = request_trace(doc, RID)
    assert tr["n_events"] == 3
    assert tr["connected"] is False


def test_request_trace_unknown_rid_empty():
    from roc_tpu.obs.timeline import request_trace
    doc = merge_timeline(_request_run())
    tr = request_trace(doc, "nope-0")
    assert tr["n_events"] == 0
    assert tr["connected"] is False


def test_span_lap_args_roundtrip_and_legacy():
    """4-element span laps carry their args dict onto the merged X
    event; legacy 3-element laps still parse with empty args."""
    events = [
        _ev("timeline", 1000.0, 50.0, 0, kind="clock_sync"),
        _ev("timeline", 1001.0, 51.0, 0, kind="spans",
            spans=[["microbatch", 50.5, 10.0,
                    {"rids": [RID], "rows": 4}],
                   ["train", 50.7, 10.0]]),
    ]
    doc = merge_timeline(events)
    mb = next(e for e in doc["traceEvents"]
              if e.get("name") == "microbatch")
    assert mb["args"]["rids"] == [RID]
    assert mb["args"]["rows"] == 4
    tr = next(e for e in doc["traceEvents"]
              if e.get("name") == "train")
    assert tr["ph"] == "X" and tr["args"] == {}


# --------------------------------------------- live P=4 rig (2 procs)

@pytest.fixture(scope="module")
def p4_run(tmp_path_factory):
    """One REAL 2-process x 2-device (P=4) distributed run, each
    process writing its own event/metrics JSONL streams."""
    import socket
    tmp = tmp_path_factory.mktemp("p4_timeline")
    worker = os.path.join(os.path.dirname(__file__),
                          "timeline_worker.py")
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    env.pop("ROC_TPU_EVENTS", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, worker, f"localhost:{port}", "2", str(i),
         str(tmp)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for i in (0, 1)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-3000:]
        assert "WORKER_OK" in out
    return tmp


def test_p4_merged_trace_golden(p4_run):
    """The acceptance artifact: a P=4 distributed CPU-rig run yields
    ONE Perfetto-loadable merged trace with a lane per process,
    aligned phase spans, and per-epoch straggler attribution."""
    ev_paths = sorted(glob.glob(str(p4_run / "ev_p*.jsonl")))
    assert len(ev_paths) == 2
    events = []
    for p in ev_paths:
        events.extend(json.loads(l) for l in open(p) if l.strip())
    # both processes performed the clock-sync handshake
    syncs = [e for e in events if e.get("kind") == "clock_sync"]
    assert {e["proc"] for e in syncs} == {0, 1}

    doc = merge_timeline(events)
    meta = doc["roc_tpu"]
    assert len(meta["processes"]) == 2          # lane per process
    assert all(pr["aligned"] for pr in meta["processes"])
    by_pid = {}
    for e in doc["traceEvents"]:
        if e["ph"] == "X":
            by_pid.setdefault(e["pid"], set()).add(e["name"])
    assert len(by_pid) == 2
    for names in by_pid.values():                # aligned phase spans
        assert {"compile", "train", "eval"} <= names, names
    # phase spans of the two processes overlap on the merged axis
    # (lockstep SPMD: both trained simultaneously)
    trains = {}
    for e in doc["traceEvents"]:
        if e["ph"] == "X" and e["name"] == "train":
            trains.setdefault(e["pid"], []).append(
                (e["ts"], e["ts"] + e["dur"]))
    (a0, a1), (b0, b1) = trains[1][0], trains[2][0]
    assert a0 < b1 and b0 < a1, (trains[1][0], trains[2][0])

    # per-epoch straggler attribution (P=4), the PR-5 cost-model record
    recs = [r for r in meta["straggler"] if r["num_parts"] == 4]
    assert recs and all(0 <= r["part"] < 4 for r in recs)
    assert all(r["ratio"] is None or r["ratio"] >= 1.0 for r in recs)

    # the whole document is valid Chrome-trace JSON
    s = json.dumps(doc)
    assert json.loads(s)["traceEvents"]


def test_p4_timeline_cli_glob(p4_run, tmp_path):
    """`python -m roc_tpu.timeline 'ev_p*.jsonl' --metrics ...` merges
    the per-process streams and reports the lanes."""
    out = str(tmp_path / "trace.json")
    r = subprocess.run(
        [sys.executable, "-m", "roc_tpu.timeline",
         str(p4_run / "ev_p*.jsonl"),
         "--metrics", str(p4_run / "m_p*.jsonl"), "-o", out],
        capture_output=True, text=True, cwd=_REPO, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stderr
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["streams"] == 2
    assert summary["processes"] == 2
    assert summary["straggler"]
    doc = json.load(open(out))
    assert doc["traceEvents"]
    # metrics records joined as per-eval epoch markers
    assert any(e["ph"] == "i" and e["name"].startswith("epoch ")
               for e in doc["traceEvents"])


def test_report_accepts_multiple_event_files(p4_run, tmp_path):
    """Satellite: roc_tpu.report renders merged multi-process runs
    instead of silently assuming one stream."""
    ev_paths = sorted(glob.glob(str(p4_run / "ev_p*.jsonl")))
    r = subprocess.run(
        [sys.executable, "-m", "roc_tpu.report"] + ev_paths,
        capture_output=True, text=True, cwd=_REPO, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stderr
    assert "processes (merged event streams)" in r.stdout
    assert "proc0@" in r.stdout and "proc1@" in r.stdout
    assert "run manifest" in r.stdout


# ------------------------------------------------ crash flight recorder

def _cli(tmp_path, args, fdir, timeout=240):
    env = {k: v for k, v in os.environ.items()
           if k not in ("ROC_TPU_FAULT",)}
    env["ROC_TPU_FLIGHT_DIR"] = str(fdir)
    env["ROC_TPU_EVENTS"] = str(tmp_path / "events.jsonl")
    return subprocess.run(
        [sys.executable, "-m", "roc_tpu.train.cli"] + args,
        cwd=_REPO, env=env, capture_output=True, text=True,
        timeout=timeout)


BASE = ["-e", "4", "-layers", "8-8-3", "-dropout", "0.0",
        "--eval-every", "2", "--impl", "ell", "--no-compile-cache",
        "--cpu"]


def _load_dumps(fdir, needle):
    paths = sorted(glob.glob(os.path.join(str(fdir),
                                          "flightrecord_*.json")))
    hits = [p for p in paths if needle in os.path.basename(p)]
    return [json.load(open(p)) for p in hits]


def test_flight_record_on_sigkill(tmp_path):
    """A SIGKILLed process leaves a dump whose LAST event is the
    injected fault site — the acceptance criterion's drill."""
    fdir = tmp_path / "fr"
    r = _cli(tmp_path, BASE + ["--fault", "sigkill:2"], fdir)
    assert r.returncode == -signal.SIGKILL, r.stderr[-2000:]
    dumps = _load_dumps(fdir, "fault-sigkill")
    assert dumps, os.listdir(str(fdir))
    d = dumps[-1]
    assert d["reason"] == "fault:sigkill"
    last = d["events"][-1]
    assert last["cat"] == "resilience" and last["site"] == "sigkill"
    # the ring carried the run's recent telemetry, clock-stamped
    assert len(d["events"]) > 1
    assert all("t" in e and "mono" in e and "proc" in e
               for e in d["events"])


def test_flight_record_on_sigterm_preemption(tmp_path):
    """The preemption path (SIGTERM -> grace -> epoch boundary) dumps
    before exiting restartable; the dump contains the injected
    sigterm fault event."""
    fdir = tmp_path / "fr"
    r = _cli(tmp_path,
             BASE + ["--fault", "sigterm:2", "--preempt-grace", "30"],
             fdir)
    assert r.returncode == 75, (r.returncode, r.stderr[-2000:])
    dumps = _load_dumps(fdir, "preempted")
    assert dumps, os.listdir(str(fdir))
    events = dumps[-1]["events"]
    assert any(e.get("site") == "sigterm" for e in events)


def test_flight_record_on_stall_deadline(tmp_path, monkeypatch):
    """The stall watchdog dumps the telemetry window BEFORE trying to
    interrupt the hung region (a terminally wedged C call would never
    let anything later run)."""
    from roc_tpu.obs.heartbeat import Heartbeat, StallFailure
    fdir = tmp_path / "fr"
    monkeypatch.setenv("ROC_TPU_FLIGHT_DIR", str(fdir))
    from roc_tpu.obs.events import emit
    emit("run", "pre-stall breadcrumb", console=False, crumb=1)
    with pytest.raises(StallFailure):
        with Heartbeat("wedge_test", interval_s=0.05, deadline_s=0.3):
            time.sleep(30.0)
    dumps = _load_dumps(fdir, "stall-wedge-test")
    assert dumps, os.listdir(str(fdir)) if fdir.exists() else "no dir"
    events = dumps[-1]["events"]
    assert any(e.get("crumb") == 1 for e in events)
    assert any(e.get("cat") == "stall" for e in events)


def test_clock_tuple_on_every_event(tmp_path):
    """Tentpole invariant: the bus stamps (t, mono, host, proc) on
    every record; JSONL artifacts carry the full tuple."""
    from roc_tpu.obs.events import EventLog, JsonlSink
    p = str(tmp_path / "e.jsonl")
    bus = EventLog([JsonlSink(p)])
    bus.emit("run", "x")
    bus.emit("epoch", "y", console=False, epoch_ms=1.5)
    bus.close()
    recs = [json.loads(l) for l in open(p)]
    for r in recs:
        assert set(("t", "mono", "host", "proc")) <= set(r)
        assert isinstance(r["proc"], int)
    assert recs[1]["mono"] >= recs[0]["mono"]
