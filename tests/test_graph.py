"""Graph container, .lux round-trip, self edges, partitioner."""

import numpy as np
import pytest

from roc_tpu.core.graph import (Graph, add_self_edges, check_symmetric,
                                from_edge_list, load_lux, save_lux,
                                synthetic_dataset, synthetic_graph)
from roc_tpu.core.partition import (edge_balanced_bounds, padded_edge_list,
                                    partition_graph)


def tiny_graph():
    # 0->1 means edge dst=1 src=0 in our dst-major CSR
    src = [0, 1, 2, 3, 0]
    dst = [1, 2, 3, 0, 2]
    return from_edge_list(src, dst, 4, symmetrize=True)


def test_from_edge_list_csr():
    g = tiny_graph()
    assert g.num_nodes == 4
    assert check_symmetric(g)
    # row of dst=1 must contain src 0
    row1 = g.col_idx[g.row_ptr[1]:g.row_ptr[2]]
    assert 0 in row1


def test_add_self_edges():
    g = add_self_edges(tiny_graph())
    assert g.has_all_self_edges()
    deg = g.in_degree
    assert (deg >= 1).all()
    # idempotent
    g2 = add_self_edges(g)
    assert g2.num_edges == g.num_edges


def test_lux_roundtrip(tmp_path):
    g = add_self_edges(synthetic_graph(50, 4, seed=3))
    path = str(tmp_path / "g.lux")
    save_lux(g, path)
    g2 = load_lux(path)
    np.testing.assert_array_equal(g.row_ptr, g2.row_ptr)
    np.testing.assert_array_equal(g.col_idx, g2.col_idx)


def test_transpose_symmetric_identity():
    g = add_self_edges(synthetic_graph(30, 5, seed=1))
    t = g.transpose()
    assert check_symmetric(g)
    assert t.num_edges == g.num_edges
    # symmetric graph: transpose has identical row degrees
    np.testing.assert_array_equal(g.in_degree, t.in_degree)


def test_edge_balanced_bounds_cover_all_vertices():
    g = synthetic_graph(100, 6, seed=0, power_law=True)
    for P in (1, 2, 4, 8):
        bounds = edge_balanced_bounds(g.row_ptr, P)
        assert len(bounds) == P
        covered = []
        for (l, r) in bounds:
            if r >= l:
                covered.extend(range(l, r + 1))
        assert covered == list(range(g.num_nodes))


def test_edge_balance_quality():
    g = synthetic_graph(1000, 16, seed=0)
    P = 8
    bounds = edge_balanced_bounds(g.row_ptr, P)
    edges = [int(g.row_ptr[r + 1] - g.row_ptr[l]) if r >= l else 0
             for (l, r) in bounds]
    cap = (g.num_edges + P - 1) // P
    # greedy closes a range only after exceeding cap; each range holds at
    # most cap + max_degree edges
    max_deg = int(g.in_degree.max())
    assert max(edges) <= cap + max_deg + 1


def test_partition_graph_shapes_and_content():
    g = add_self_edges(synthetic_graph(100, 6, seed=2))
    P = 4
    pg = partition_graph(g, P, node_multiple=8, edge_multiple=32)
    assert pg.part_row_ptr.shape == (P, pg.part_nodes + 1)
    assert pg.part_col_idx.shape == (P, pg.part_edges)
    assert (pg.part_row_ptr[:, -1] == pg.part_edges).all()
    # real edges reproduce the global CSR
    for p in range(P):
        l, r = pg.bounds[p]
        if r < l:
            continue
        e = int(pg.real_edges[p])
        got = pg.part_col_idx[p, :e]
        want = g.col_idx[g.row_ptr[l]:g.row_ptr[r + 1]]
        np.testing.assert_array_equal(got, want)
        # degrees match
        np.testing.assert_array_equal(
            pg.part_in_degree[p, :int(pg.real_nodes[p])],
            g.in_degree[l:r + 1])
    # padding edges all point at the dummy source
    for p in range(P):
        e = int(pg.real_edges[p])
        assert (pg.part_col_idx[p, e:] == pg.dummy_src).all()


def test_partition_chunk_span_invariant():
    """A run of C consecutive local edges must span <= C local rows —
    required by the blocked aggregator."""
    g = add_self_edges(synthetic_graph(200, 5, seed=4, power_law=True))
    for P in (1, 3, 8):
        pg = partition_graph(g, P, node_multiple=8, edge_multiple=64)
        for p in range(P):
            ptr = pg.part_row_ptr[p]
            dst = np.repeat(np.arange(pg.part_nodes), np.diff(ptr))
            assert dst.shape[0] == pg.part_edges
            C = 64
            for c0 in range(0, pg.part_edges, C):
                span = dst[c0:c0 + C]
                assert span[-1] - span[0] < C


def test_global_pad_map():
    g = add_self_edges(synthetic_graph(50, 4, seed=5))
    pg = partition_graph(g, 4, node_multiple=8)
    m = pg.global_pad_map()
    assert m.shape == (pg.padded_num_nodes,)
    real = m[m < g.num_nodes]
    np.testing.assert_array_equal(np.sort(real), np.arange(g.num_nodes))


def test_padded_edge_list():
    g = add_self_edges(synthetic_graph(33, 3, seed=6))
    src, dst = padded_edge_list(g, multiple=64)
    assert src.shape[0] % 64 == 0
    E = g.num_edges
    np.testing.assert_array_equal(src[:E], g.col_idx)
    assert (src[E:] == g.num_nodes).all()
    assert (dst[E:] == g.num_nodes - 1).all()
    assert (np.diff(dst) >= 0).all()


def test_synthetic_dataset_deterministic():
    d1 = synthetic_dataset(64, 6, seed=7)
    d2 = synthetic_dataset(64, 6, seed=7)
    np.testing.assert_array_equal(d1.features, d2.features)
    np.testing.assert_array_equal(d1.graph.col_idx, d2.graph.col_idx)
    assert d1.graph.has_all_self_edges()
