"""Pallas kernel parity tests (interpreter mode on CPU; the real-chip
path is exercised by benchmarks/micro_agg.py --impls pallas)."""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from roc_tpu.core.graph import add_self_edges, synthetic_graph
from roc_tpu.core.partition import padded_edge_list
from roc_tpu.ops.aggregate import aggregate_segment
from roc_tpu.ops.norm import indegree_norm


def test_graphnorm_pallas_matches_xla():
    from roc_tpu.kernels.graphnorm import indegree_norm_pallas
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(100, 12).astype(np.float32))
    deg = jnp.asarray(np.concatenate(
        [np.zeros(5, np.int32),  # padding rows -> zero output
         rng.randint(1, 50, size=95).astype(np.int32)]))
    want = indegree_norm(x, deg)
    got = indegree_norm_pallas(x, deg, block=32, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_graphnorm_pallas_unaligned_rows():
    from roc_tpu.kernels.graphnorm import indegree_norm_pallas
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(37, 8).astype(np.float32))
    deg = jnp.asarray(rng.randint(1, 9, size=37).astype(np.int32))
    want = indegree_norm(x, deg)
    got = indegree_norm_pallas(x, deg, block=16, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_ell_spmm_pallas_interpret():
    """Interpreter-mode numerics of the one-launch ELL kernel
    (kernels/ell_spmm.py) against the XLA ELL reduction, on a
    power-law graph exercising several width buckets + row/width
    padding inside the kernel launcher."""
    from roc_tpu.core.ell import ell_from_graph
    from roc_tpu.kernels.ell_spmm import ell_aggregate_pallas
    from roc_tpu.ops.aggregate import aggregate_ell
    g = synthetic_graph(300, 9, seed=3, power_law=True)
    V = g.num_nodes
    t = ell_from_graph(g.row_ptr, g.col_idx, V)
    idx = tuple(jnp.asarray(a[0]) for a in t.idx)
    pos = jnp.asarray(t.row_pos[0])
    rng = np.random.RandomState(0)
    feats = np.zeros((V + 1, 24), dtype=np.float32)
    feats[:V] = rng.rand(V, 24)
    feats = jnp.asarray(feats)
    want = aggregate_ell(feats, idx, pos, V)
    got = ell_aggregate_pallas(feats, idx, pos, V, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_ell_spmm_pallas_in_model():
    """aggr_impl='pallas' end to end through GraphContext (interpret
    mode auto-selected on CPU)."""
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import TrainConfig, Trainer
    ds = synthetic_dataset(96, 6, in_dim=8, num_classes=3, seed=0)
    model = build_gcn([8, 8, 3], dropout_rate=0.0)
    cfgs = [TrainConfig(aggr_impl=i, verbose=False, symmetric=True,
                        epochs=1) for i in ("ell", "pallas")]
    outs = []
    for cfg in cfgs:
        tr = Trainer(model, ds, cfg)
        tr.train(epochs=2)
        tr.sync()
        outs.append(np.asarray(tr.params["linear_0"]))
    np.testing.assert_allclose(outs[0], outs[1], rtol=2e-4, atol=1e-5)


@pytest.mark.slow
def test_spmm_pallas_interpret_small():
    """Interpreter-mode numerics check of the fused segmented-reduce
    kernel on a small graph (slow: one pallas interpret per chunk)."""
    from roc_tpu.kernels.spmm import csr_spmm_pallas
    g = add_self_edges(synthetic_graph(80, 5, seed=1))
    V = g.num_nodes
    rng = np.random.RandomState(0)
    feats = np.zeros((V + 1, 6), dtype=np.float32)
    feats[:V] = rng.randn(V, 6)
    src, dst = padded_edge_list(g, multiple=64)
    want = aggregate_segment(jnp.asarray(feats), jnp.asarray(src),
                             jnp.asarray(dst), V)
    got = csr_spmm_pallas(jnp.asarray(feats), jnp.asarray(src),
                          jnp.asarray(dst), V, chunk=64,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_resolve_auto_impl_generation_keyed():
    """The sectioned window is keyed on device_kind: calibrated kinds
    use their measured bounds, unknown kinds fall back to v5e values
    (loudly, once) instead of silently mis-picking (VERDICT r3)."""
    from roc_tpu.core import ell
    assert ell.resolve_auto_impl(233_000,
                                 device_kind="TPU v5 lite") == "sectioned"
    assert ell.resolve_auto_impl(50_000,
                                 device_kind="TPU v5 lite") == "ell"
    assert ell.resolve_auto_impl(2_450_000,
                                 device_kind="TPU v5 lite") == "ell"
    # unknown generation: same defaults, plus a one-time echo
    assert ell.resolve_auto_impl(233_000, device_kind="TPU v9") == \
        ell.resolve_auto_impl(233_000, device_kind="TPU v5 lite")
    assert "TPU v9" in ell._UNCALIBRATED_WARNED
    assert ell.sectioned_bounds("TPU v5 lite") == \
        (ell.SECTION_ROWS_DEFAULT, ell.SECTIONED_MAX_ROWS)


def test_calibration_json_overrides_builtin(tmp_path, monkeypatch):
    """A row written by benchmarks/calibrate.py takes effect through
    sectioned_bounds/resolve_auto_impl without a code edit or restart
    (VERDICT r4 weak #4)."""
    from roc_tpu.core import ell
    path = tmp_path / "calibration.json"
    path.write_text(json.dumps({
        "TPU v6e": {"lo": 100_000, "hi": 900_000,
                    "provenance": "benchmarks/calibrate.py"}}))
    monkeypatch.setenv("ROC_TPU_CALIBRATION", str(path))
    assert ell.sectioned_bounds("TPU v6e") == (100_000, 900_000)
    assert ell.resolve_auto_impl(150_000, device_kind="TPU v6e") == \
        "sectioned"
    assert ell.resolve_auto_impl(150_000,
                                 device_kind="TPU v5 lite") == "sectioned"
    # a calibrated row for an already-builtin kind wins over the table
    path.write_text(json.dumps({
        "TPU v5 lite": {"lo": 65_536, "hi": 200_000}}))
    assert ell.sectioned_bounds("TPU v5 lite") == (65_536, 200_000)
    assert ell.resolve_auto_impl(233_000,
                                 device_kind="TPU v5 lite") == "ell"
    # corrupt file: builtin table still applies
    path.write_text("{nope")
    assert ell.sectioned_bounds("TPU v5 lite") == \
        (ell.SECTION_ROWS_DEFAULT, ell.SECTIONED_MAX_ROWS)


def test_calibrate_bounds_from_points():
    """Crossover placement: geometric mean of the win/loss bracket;
    all-win extrapolates, all-loss collapses the window."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "calibrate", os.path.join(os.path.dirname(__file__), "..",
                                  "benchmarks", "calibrate.py"))
    cal = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cal)
    lo = 65_536
    pts = [{"V": 233_000, "winner": "sectioned"},
           {"V": 500_000, "winner": "sectioned"},
           {"V": 1_000_000, "winner": "ell"}]
    got = cal.bounds_from_points(pts, lo)
    assert got[0] == lo
    assert got[1] == int((500_000 * 1_000_000) ** 0.5)
    assert cal.bounds_from_points(
        [{"V": 233_000, "winner": "sectioned"}], lo) == (lo, 466_000)
    assert cal.bounds_from_points(
        [{"V": 233_000, "winner": "ell"}], lo) == (lo, lo)
    # a loss BELOW a later win must not clip the window
    pts = [{"V": 100_000, "winner": "ell"},
           {"V": 500_000, "winner": "sectioned"}]
    assert cal.bounds_from_points(pts, lo) == (lo, 1_000_000)
