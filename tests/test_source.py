"""Partition-local loading tests: row-sliced loaders, FileSource, and
the spy asserting a host touches only its partitions' byte ranges
(VERDICT r1 #3; reference contract load_task.cu:41-51,201-245)."""

import numpy as np
import pytest

import jax.numpy as jnp

from roc_tpu.core import graph as G
from roc_tpu.core.graph import (Dataset, load_features, load_labels,
                                load_lux_rows, load_mask, save_dataset,
                                synthetic_dataset)
from roc_tpu.core.partition import (partition_col, partition_graph,
                                    partition_plan)
from roc_tpu.core.source import ArraySource, FileSource, as_source


@pytest.fixture(scope="module")
def disk_ds(tmp_path_factory):
    ds = synthetic_dataset(96, 6, in_dim=10, num_classes=3, seed=7)
    prefix = str(tmp_path_factory.mktemp("data") / "synth")
    save_dataset(ds, prefix, csv=True, feats_bin=False)
    return ds, prefix


def test_load_lux_rows_slices(disk_ds):
    ds, prefix = disk_ds
    g = ds.graph
    for lo, hi in [(0, 10), (5, 40), (90, 96), (0, 96), (7, 7)]:
        ptr, col = load_lux_rows(prefix + ".add_self_edge.lux", lo, hi)
        want_ptr = (g.row_ptr[lo:hi + 1] - g.row_ptr[lo])
        np.testing.assert_array_equal(ptr, want_ptr)
        np.testing.assert_array_equal(
            col, g.col_idx[g.row_ptr[lo]:g.row_ptr[hi]])


def test_row_sliced_loaders_match_full(disk_ds):
    ds, prefix = disk_ds
    V, F = ds.graph.num_nodes, ds.in_dim
    for lo, hi in [(0, 17), (31, 64), (64, 96)]:
        np.testing.assert_allclose(
            load_features(prefix, V, F, rows=(lo, hi)),
            ds.features[lo:hi], rtol=1e-5)
        np.testing.assert_array_equal(
            load_labels(prefix, V, ds.num_classes, rows=(lo, hi)),
            ds.labels[lo:hi])
        np.testing.assert_array_equal(
            load_mask(prefix, V, rows=(lo, hi)), ds.mask[lo:hi])


def test_feats_bin_rows_slice(tmp_path):
    ds = synthetic_dataset(40, 4, in_dim=6, num_classes=2, seed=1)
    prefix = str(tmp_path / "binonly")
    save_dataset(ds, prefix, csv=False, feats_bin=True)
    got = load_features(prefix, 40, 6, rows=(13, 29))
    np.testing.assert_allclose(got, ds.features[13:29], rtol=1e-6)


def test_file_source_matches_array_source(disk_ds):
    ds, prefix = disk_ds
    fs = FileSource(prefix, ds.in_dim, ds.num_classes)
    ars = as_source(ds)
    assert fs.num_nodes == ars.num_nodes
    assert fs.num_edges == ars.num_edges
    np.testing.assert_array_equal(fs.row_ptr(), ds.graph.row_ptr)
    np.testing.assert_array_equal(fs.col_slice(5, 50),
                                  ds.graph.col_idx[5:50])
    np.testing.assert_allclose(fs.features(10, 30), ds.features[10:30],
                               rtol=1e-5)
    np.testing.assert_array_equal(fs.labels(0, 96), ds.labels)
    np.testing.assert_array_equal(fs.mask(50, 96), ds.mask[50:])


def test_partition_local_reads_touch_only_local_rows(disk_ds,
                                                     monkeypatch):
    """The spy: partition p's column + feature reads must stay inside
    p's byte ranges (the O(V) row-pointer/offsets section is the one
    allowed global read)."""
    ds, prefix = disk_ds
    # use the binary feature cache so feature reads are seek-based
    save_dataset(ds, prefix, csv=False, feats_bin=True)
    fs = FileSource(prefix, ds.in_dim, ds.num_classes)
    plan = partition_plan(fs.row_ptr(), 4)
    reads = []
    real_read = G._read_slice

    def spy(f, offset, count, dtype):
        reads.append((f.name, offset, np.dtype(dtype).itemsize * count))
        return real_read(f, offset, count, dtype)

    monkeypatch.setattr(G, "_read_slice", spy)
    p = 1
    l, r = plan.bounds[p]
    e0, e1 = plan.edge_range(p)
    col = partition_col(plan, fs.col_slice, p)
    feats = fs.features(l, r + 1)
    col_base = 12 + plan.num_nodes * 8
    for name, off, nbytes in reads:
        if name.endswith(".lux"):
            lo_b, hi_b = col_base + e0 * 4, col_base + e1 * 4
        elif name.endswith(".feats.bin"):
            lo_b = l * ds.in_dim * 4
            hi_b = (r + 1) * ds.in_dim * 4
        else:
            raise AssertionError(f"unexpected read from {name}")
        assert lo_b <= off and off + nbytes <= hi_b, (
            f"{name}: read [{off}, {off+nbytes}) outside partition "
            f"range [{lo_b}, {hi_b})")
    assert len(reads) >= 2  # both the col slice and the feature slice
    # and the data is right
    np.testing.assert_array_equal(
        col[:e1 - e0], ds.graph.col_idx[e0:e1])
    np.testing.assert_allclose(feats, ds.features[l:r + 1], rtol=1e-6)


@pytest.mark.parametrize("aggr_impl", ["segment", "ell"])
def test_shard_dataset_local_matches_global(aggr_impl):
    """shard_dataset_local (per-part local builds) must produce the
    same device contents as the all-parts shard_dataset."""
    from roc_tpu.parallel import multihost as mh
    from roc_tpu.parallel.distributed import shard_dataset

    ds = synthetic_dataset(64, 6, in_dim=8, num_classes=3, seed=0)
    mesh = mh.make_parts_mesh(4)
    pg = partition_graph(ds.graph, 4, edge_multiple=64)
    want = shard_dataset(ds, pg, mesh, aggr_impl=aggr_impl)
    got = mh.shard_dataset_local(ds, pg, mesh, aggr_impl=aggr_impl)
    np.testing.assert_allclose(np.asarray(got.feats),
                               np.asarray(want.feats), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(got.labels),
                                  np.asarray(want.labels))
    np.testing.assert_array_equal(np.asarray(got.mask),
                                  np.asarray(want.mask))
    np.testing.assert_array_equal(np.asarray(got.edge_src),
                                  np.asarray(want.edge_src))
    np.testing.assert_array_equal(np.asarray(got.edge_dst),
                                  np.asarray(want.edge_dst))
    assert len(got.ell_idx) == len(want.ell_idx)
    for a, b in zip(got.ell_idx, want.ell_idx):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(got.ell_row_pos),
                                  np.asarray(want.ell_row_pos))


def test_trainer_on_file_source_local_shards(disk_ds):
    """End to end: DistributedTrainer on shards built from FileSource
    row-sliced reads."""
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.parallel import multihost as mh
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.train.trainer import TrainConfig

    ds, prefix = disk_ds
    fs = FileSource(prefix, ds.in_dim, ds.num_classes)
    mesh = mh.make_parts_mesh(4)
    cfg = TrainConfig(epochs=2, verbose=False, aggr_impl="ell",
                      symmetric=True)
    tr = DistributedTrainer(build_gcn([ds.in_dim, 8, 3]), ds, 4, cfg,
                            mesh=mesh)
    tr.data = mh.shard_dataset_local(fs, tr.pg, mesh, aggr_impl="ell")
    tr.train(epochs=2)
    assert np.isfinite(tr.evaluate()["train_loss"])


def test_shard_dataset_local_ring_matches_global():
    """Partition-local ring prep (pair lists from local column reads +
    O(P) width agreement) must produce byte-identical ring tables to
    the global build_ring_tables path."""
    from roc_tpu.parallel import multihost as mh
    from roc_tpu.parallel.distributed import shard_dataset

    ds = synthetic_dataset(96, 7, in_dim=8, num_classes=3, seed=3)
    mesh = mh.make_parts_mesh(4)
    pg = partition_graph(ds.graph, 4, edge_multiple=64)
    want = shard_dataset(ds, pg, mesh, halo="ring")
    got = mh.shard_dataset_local(ds, pg, mesh, halo="ring")
    np.testing.assert_array_equal(np.asarray(got.ring_idx[0]),
                                  np.asarray(want.ring_idx[0]))
    np.testing.assert_array_equal(np.asarray(got.ring_idx[1]),
                                  np.asarray(want.ring_idx[1]))
    np.testing.assert_allclose(got.ring_padding_ratio,
                               want.ring_padding_ratio)
    np.testing.assert_allclose(np.asarray(got.feats),
                               np.asarray(want.feats), rtol=1e-6)


def test_ring_prep_reads_stay_partition_local(disk_ds, monkeypatch):
    """The ring prep's column reads must stay inside each partition's
    own .lux byte range — no host-side whole-graph pass (VERDICT r2
    weak #8)."""
    from roc_tpu.parallel import multihost as mh

    ds, prefix = disk_ds
    fs = FileSource(prefix, ds.in_dim, ds.num_classes)
    mesh = mh.make_parts_mesh(4)
    plan = partition_plan(fs.row_ptr(), 4)
    reads = []
    real_read = G._read_slice

    def spy(f, offset, count, dtype):
        reads.append((f.name, offset, np.dtype(dtype).itemsize * count))
        return real_read(f, offset, count, dtype)

    monkeypatch.setattr(G, "_read_slice", spy)
    mh.shard_dataset_local(fs, plan, mesh, halo="ring")
    col_base = 12 + plan.num_nodes * 8
    ranges = [tuple(col_base + e * 4 for e in plan.edge_range(p))
              for p in range(4)]
    lux_reads = [r for r in reads if r[0].endswith(".lux")]
    assert lux_reads, "expected column reads through the source"
    for name, off, nbytes in lux_reads:
        assert any(lo <= off and off + nbytes <= hi
                   for lo, hi in ranges), (
            f"column read [{off}, {off + nbytes}) spans beyond any "
            f"single partition's range {ranges}")


def test_trainer_ring_on_file_source_local_shards(disk_ds):
    """End to end: ring-halo DistributedTrainer on shards built from
    FileSource partition-local reads (previously NotImplementedError)."""
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.parallel import multihost as mh
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.train.trainer import TrainConfig

    ds, prefix = disk_ds
    fs = FileSource(prefix, ds.in_dim, ds.num_classes)
    mesh = mh.make_parts_mesh(4)
    cfg = TrainConfig(epochs=2, verbose=False, halo="ring",
                      symmetric=True)
    tr = DistributedTrainer(build_gcn([ds.in_dim, 8, 3]), ds, 4, cfg,
                            mesh=mesh)
    tr.data = mh.shard_dataset_local(fs, tr.pg, mesh, halo="ring")
    tr.train(epochs=2)
    assert np.isfinite(tr.evaluate()["train_loss"])


def test_shard_dataset_local_sectioned_matches_global():
    """Partition-local sectioned prep (per-part counts + O(P*n_sec)
    max collective for the uniform chunk plan) must produce the same
    tables as the global build."""
    from roc_tpu.parallel import multihost as mh
    from roc_tpu.parallel.distributed import shard_dataset

    ds = synthetic_dataset(96, 7, in_dim=8, num_classes=3, seed=6)
    mesh = mh.make_parts_mesh(4)
    pg = partition_graph(ds.graph, 4, edge_multiple=64)
    want = shard_dataset(ds, pg, mesh, aggr_impl="sectioned")
    got = mh.shard_dataset_local(ds, pg, mesh, aggr_impl="sectioned")
    assert got.sect_meta == want.sect_meta
    assert len(got.sect_idx) == len(want.sect_idx)
    for a, b in zip(got.sect_idx, want.sect_idx):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(got.sect_sub_dst, want.sect_sub_dst):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
