"""Per-op unit tests vs dense numpy references + gradient checks
(the test strategy SURVEY.md §4 says the reference lacks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from roc_tpu.core.graph import add_self_edges, synthetic_graph
from roc_tpu.core.partition import padded_edge_list
from roc_tpu.ops.aggregate import (aggregate_blocked, aggregate_mean,
                                   aggregate_scan, aggregate_segment)
from roc_tpu.ops.dense import (AC_MODE_NONE, AC_MODE_RELU, dropout, linear)
from roc_tpu.ops.loss import (masked_softmax_cross_entropy, perf_metrics,
                              summarize_metrics)
from roc_tpu.ops.norm import indegree_norm
from roc_tpu.core.graph import MASK_NONE, MASK_TRAIN, MASK_VAL, MASK_TEST


def dense_adjacency(g):
    A = np.zeros((g.num_nodes, g.num_nodes), dtype=np.float32)
    dst = g.edge_dst()
    for d, s in zip(dst, g.col_idx):
        A[d, s] += 1.0
    return A


@pytest.fixture(scope="module")
def graph():
    return add_self_edges(synthetic_graph(60, 5, seed=0, power_law=True))


@pytest.fixture(scope="module")
def feats(graph):
    rng = np.random.RandomState(0)
    return rng.randn(graph.num_nodes, 12).astype(np.float32)


def _padded(graph, chunk=64):
    src, dst = padded_edge_list(graph, multiple=chunk)
    return jnp.asarray(src), jnp.asarray(dst)


def test_aggregate_segment_matches_dense(graph, feats):
    A = dense_adjacency(graph)
    want = A @ feats
    src, dst = _padded(graph)
    x = jnp.concatenate([jnp.asarray(feats),
                         jnp.zeros((1, feats.shape[1]))], axis=0)
    got = aggregate_segment(x, src, dst, graph.num_nodes)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_aggregate_blocked_matches_segment(graph, feats):
    src, dst = _padded(graph, chunk=64)
    x = jnp.concatenate([jnp.asarray(feats),
                         jnp.zeros((1, feats.shape[1]))], axis=0)
    a = aggregate_segment(x, src, dst, graph.num_nodes)
    b = aggregate_blocked(x, src, dst, graph.num_nodes, chunk=64)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("chunk", [32, 64, 256])
def test_aggregate_scan_matches_segment(graph, feats, chunk):
    src, dst = _padded(graph, chunk=chunk)
    x = jnp.concatenate([jnp.asarray(feats),
                         jnp.zeros((1, feats.shape[1]))], axis=0)
    a = aggregate_segment(x, src, dst, graph.num_nodes)
    b = aggregate_scan(x, src, dst, graph.num_nodes, chunk=chunk)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-5)


def test_aggregate_scan_hub_row_spans_chunks():
    """A row whose degree is many times the chunk size exercises the
    carry-record path (partials scatter-added across chunks)."""
    V, hub_deg, chunk = 16, 300, 32
    rng = np.random.RandomState(0)
    dst = np.concatenate([np.arange(V), np.full(hub_deg, 7)])
    src = np.concatenate([np.arange(V), rng.randint(0, V, hub_deg)])
    from roc_tpu.core.graph import from_edge_list
    g = from_edge_list(src, dst, V)
    psrc, pdst = padded_edge_list(g, multiple=chunk)
    x = np.zeros((V + 1, 5), dtype=np.float32)
    x[:V] = rng.randn(V, 5)
    a = aggregate_segment(jnp.asarray(x), jnp.asarray(psrc),
                          jnp.asarray(pdst), V)
    b = aggregate_scan(jnp.asarray(x), jnp.asarray(psrc),
                       jnp.asarray(pdst), V, chunk=chunk)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-4, atol=1e-4)


def test_aggregate_grad_is_transpose(graph, feats):
    """d(sum(A@X * G))/dX == A^T @ G — JAX must produce the exact
    transpose (the reference reuses A, valid only because A == A^T;
    our symmetric fixture satisfies both)."""
    A = dense_adjacency(graph)
    rng = np.random.RandomState(1)
    G = rng.randn(*feats.shape).astype(np.float32)
    src, dst = _padded(graph)

    def f(x):
        x_ext = jnp.concatenate([x, jnp.zeros((1, x.shape[1]))], axis=0)
        out = aggregate_segment(x_ext, src, dst, graph.num_nodes)
        return jnp.sum(out * G)

    got = jax.grad(f)(jnp.asarray(feats))
    want = A.T @ G
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)


def test_aggregate_mean(graph, feats):
    A = dense_adjacency(graph)
    deg = A.sum(axis=1, keepdims=True)
    want = (A @ feats) / np.maximum(deg, 1.0)
    src, dst = _padded(graph)
    x = jnp.concatenate([jnp.asarray(feats),
                         jnp.zeros((1, feats.shape[1]))], axis=0)
    got = aggregate_mean(x, src, dst, graph.num_nodes,
                         jnp.asarray(graph.in_degree))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_indegree_norm(graph, feats):
    deg = graph.in_degree.astype(np.float32)
    want = feats / np.sqrt(deg)[:, None]
    got = indegree_norm(jnp.asarray(feats), jnp.asarray(graph.in_degree))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


def test_indegree_norm_zero_degree():
    x = jnp.ones((4, 3))
    deg = jnp.array([1, 4, 0, 9], dtype=jnp.int32)
    out = indegree_norm(x, deg)
    np.testing.assert_allclose(np.asarray(out[2]), 0.0)
    np.testing.assert_allclose(np.asarray(out[3]), 1.0 / 3.0, rtol=1e-6)


def test_linear_fused_relu():
    rng = np.random.RandomState(0)
    x = rng.randn(10, 8).astype(np.float32)
    w = rng.randn(8, 6).astype(np.float32)
    want = np.maximum(x @ w, 0.0)
    got = linear(jnp.asarray(x), jnp.asarray(w), AC_MODE_RELU)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_dropout_train_and_infer():
    key = jax.random.PRNGKey(0)
    x = jnp.ones((1000, 4))
    y = dropout(x, 0.5, key, train=True)
    # inverted dropout: survivors scaled by 2, mean preserved
    kept = np.asarray(y) > 0
    assert 0.4 < kept.mean() < 0.6
    np.testing.assert_allclose(np.asarray(y)[kept], 2.0)
    y_inf = dropout(x, 0.5, None, train=False)
    np.testing.assert_array_equal(np.asarray(y_inf), np.asarray(x))


def test_loss_grad_is_masked_softmax_minus_onehot():
    """The defining parity property (softmax_kernel.cu:19-33)."""
    rng = np.random.RandomState(0)
    V, C = 20, 5
    logits = rng.randn(V, C).astype(np.float32)
    labels = rng.randint(0, C, size=V).astype(np.int32)
    mask = rng.choice([MASK_NONE, MASK_TRAIN, MASK_VAL, MASK_TEST],
                      size=V).astype(np.int32)

    g = jax.grad(lambda l: masked_softmax_cross_entropy(
        l, jnp.asarray(labels), jnp.asarray(mask)))(jnp.asarray(logits))

    p = np.exp(logits - logits.max(axis=1, keepdims=True))
    p /= p.sum(axis=1, keepdims=True)
    onehot = np.eye(C, dtype=np.float32)[labels]
    want = (p - onehot) * (mask == MASK_TRAIN)[:, None]
    np.testing.assert_allclose(np.asarray(g), want, rtol=1e-5, atol=1e-6)


def test_perf_metrics_definitions():
    logits = jnp.asarray(np.array([[2.0, 1.0], [0.0, 3.0], [5.0, 0.0],
                                   [0.0, 1.0]], dtype=np.float32))
    labels = jnp.asarray(np.array([0, 1, 1, 1], dtype=np.int32))
    mask = jnp.asarray(np.array([MASK_TRAIN, MASK_TRAIN, MASK_VAL,
                                 MASK_TEST], dtype=np.int32))
    m = summarize_metrics(jax.device_get(perf_metrics(logits, labels, mask)))
    assert m["train_cnt"] == 2 and m["train_correct"] == 2
    assert m["val_cnt"] == 1 and m["val_correct"] == 0
    assert m["test_cnt"] == 1 and m["test_correct"] == 1
    # train_loss = sum over train of (1 - p_true)
    p0 = np.exp(2.0) / (np.exp(2.0) + np.exp(1.0))
    p1 = np.exp(3.0) / (np.exp(0.0) + np.exp(3.0))
    np.testing.assert_allclose(m["train_loss"], (1 - p0) + (1 - p1),
                               rtol=1e-5)


def test_aggregate_ell_matches_dense(graph, feats):
    from roc_tpu.core.ell import ell_from_graph
    from roc_tpu.ops.aggregate import aggregate_ell
    A = dense_adjacency(graph)
    want = A @ feats
    table = ell_from_graph(graph.row_ptr, graph.col_idx, graph.num_nodes)
    x = jnp.concatenate([jnp.asarray(feats),
                         jnp.zeros((1, feats.shape[1]))], axis=0)
    got = aggregate_ell(x, tuple(jnp.asarray(a[0]) for a in table.idx),
                        jnp.asarray(table.row_pos[0]), graph.num_nodes)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_aggregate_ell_chunked_budget(graph, feats):
    """Tiny budget forces the segmented-scan path; results identical."""
    from roc_tpu.core.ell import ell_from_graph
    from roc_tpu.ops.aggregate import aggregate_ell
    table = ell_from_graph(graph.row_ptr, graph.col_idx, graph.num_nodes)
    x = jnp.concatenate([jnp.asarray(feats),
                         jnp.zeros((1, feats.shape[1]))], axis=0)
    idx = tuple(jnp.asarray(a[0]) for a in table.idx)
    pos = jnp.asarray(table.row_pos[0])
    a = aggregate_ell(x, idx, pos, graph.num_nodes)
    b = aggregate_ell(x, idx, pos, graph.num_nodes, budget_elems=256)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-5)


def test_aggregate_ell_hub_node():
    """A hub row far above the old width clamp must aggregate exactly
    (regression: widths are unbounded powers of two, never clamped)."""
    from roc_tpu.core.graph import from_edge_list, add_self_edges
    from roc_tpu.core.ell import ell_from_graph, row_widths
    from roc_tpu.ops.aggregate import aggregate_ell
    assert row_widths(np.array([70_000]), 8)[0] == 131072
    V = 300
    hub_src = np.arange(V, dtype=np.int64)
    hub_dst = np.zeros(V, dtype=np.int64)
    g = add_self_edges(from_edge_list(hub_src, hub_dst, V))
    rng = np.random.RandomState(0)
    feats = rng.randn(V, 5).astype(np.float32)
    table = ell_from_graph(g.row_ptr, g.col_idx, V)
    x = jnp.concatenate([jnp.asarray(feats), jnp.zeros((1, 5))], axis=0)
    got = aggregate_ell(x, tuple(jnp.asarray(a[0]) for a in table.idx),
                        jnp.asarray(table.row_pos[0]), V)
    # row 0 sums every node's features (+ its self edge already counted)
    np.testing.assert_allclose(np.asarray(got)[0], feats.sum(axis=0),
                               rtol=1e-4, atol=1e-4)


# ---- sectioned aggregation (core/ell.py SectionedEll) ----

def test_sectioned_matches_segment():
    """The fast-gather sectioned layout must be exact vs segment-sum,
    across section boundaries and with multi-section tables."""
    import jax.numpy as jnp
    from roc_tpu.core.graph import add_self_edges, synthetic_graph
    from roc_tpu.core.ell import sectioned_from_graph
    from roc_tpu.core.partition import padded_edge_list
    from roc_tpu.ops.aggregate import aggregate_ell_sect, aggregate_segment
    g = add_self_edges(synthetic_graph(500, 9, seed=11, power_law=True))
    F = 12
    feats = np.random.RandomState(0).rand(g.num_nodes + 1, F).astype(
        np.float32)
    feats[-1] = 0
    x = jnp.asarray(feats)
    src, dst = padded_edge_list(g, multiple=64)
    want = aggregate_segment(x, jnp.asarray(src), jnp.asarray(dst),
                             g.num_nodes)
    # force several sections and several chunks
    sect = sectioned_from_graph(g.row_ptr, g.col_idx, g.num_nodes,
                                section_rows=128, seg_rows=64)
    got = aggregate_ell_sect(
        x, tuple(jnp.asarray(a) for a in sect.idx),
        tuple(jnp.asarray(a) for a in sect.sub_dst),
        tuple(zip(sect.sec_starts, sect.sec_sizes)), g.num_nodes)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_sectioned_end_to_end_training():
    """aggr_impl='sectioned' trains the GCN to the same result as
    'segment' (rate-0 dropout => identical RNG-free paths)."""
    import jax
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import TrainConfig, Trainer
    ds = synthetic_dataset(300, 6, in_dim=12, num_classes=3, seed=5)
    params = {}
    for impl in ("segment", "sectioned"):
        model = build_gcn([12, 8, 3], dropout_rate=0.0)
        cfg = TrainConfig(learning_rate=0.05, epochs=3, aggr_impl=impl,
                          eval_every=1 << 30, verbose=False,
                          symmetric=True)
        tr = Trainer(model, ds, cfg)
        tr.train()
        params[impl] = tr.params
    for k in params["segment"]:
        np.testing.assert_allclose(np.asarray(params["segment"][k]),
                                   np.asarray(params["sectioned"][k]),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("sub_w", [8, 16, 32])
def test_sectioned_width_variants_match_segment(sub_w):
    """Width-parameterized sub-rows (VERDICT r4 gather levers): any
    sub_w must be exact vs segment-sum, native and numpy builders
    agreeing."""
    import jax.numpy as jnp
    from roc_tpu.core.graph import add_self_edges, synthetic_graph
    from roc_tpu.core.ell import sectioned_from_graph
    from roc_tpu.core.partition import padded_edge_list
    from roc_tpu.ops.aggregate import (aggregate_ell_sect,
                                       aggregate_segment)
    g = add_self_edges(synthetic_graph(500, 9, seed=7, power_law=True))
    F = 12
    feats = np.random.RandomState(1).rand(g.num_nodes + 1, F).astype(
        np.float32)
    feats[-1] = 0
    x = jnp.asarray(feats)
    src, dst = padded_edge_list(g, multiple=64)
    want = aggregate_segment(x, jnp.asarray(src), jnp.asarray(dst),
                             g.num_nodes)
    sect = sectioned_from_graph(g.row_ptr, g.col_idx, g.num_nodes,
                                section_rows=128, seg_rows=64,
                                sub_w=sub_w)
    assert sect.idx[0].shape[-1] == sub_w
    sidx, sdst, meta = sect.as_jax()
    got = aggregate_ell_sect(x, sidx, sdst, meta, g.num_nodes)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_sectioned_uint16_and_split_gather_match():
    """uint16 section-local indices and the split-gather lowering are
    numerics-identical to the block-gather int32 form."""
    import jax.numpy as jnp
    from roc_tpu.core.graph import add_self_edges, synthetic_graph
    from roc_tpu.core.ell import sectioned_from_graph
    from roc_tpu.ops.aggregate import (aggregate_ell_sect,
                                       aggregate_ell_sect_split)
    g = add_self_edges(synthetic_graph(400, 7, seed=3, power_law=True))
    F = 9
    feats = np.random.RandomState(2).rand(g.num_nodes + 1, F).astype(
        np.float32)
    feats[-1] = 0
    x = jnp.asarray(feats)
    sect = sectioned_from_graph(g.row_ptr, g.col_idx, g.num_nodes,
                                section_rows=128, seg_rows=64)
    sidx, sdst, meta = sect.as_jax()
    want = np.asarray(aggregate_ell_sect(x, sidx, sdst, meta,
                                         g.num_nodes))
    u16 = sect.with_idx_dtype(np.uint16)
    assert all(a.dtype == np.uint16 for a in u16.idx)
    uidx, udst, umeta = u16.as_jax()
    got16 = np.asarray(aggregate_ell_sect(x, uidx, udst, umeta,
                                          g.num_nodes))
    np.testing.assert_array_equal(got16, want)
    gots = np.asarray(aggregate_ell_sect_split(x, sidx, sdst, meta,
                                               g.num_nodes))
    np.testing.assert_allclose(gots, want, rtol=1e-5, atol=1e-6)
    # a section size past the dtype's range must refuse loudly
    import pytest as _pytest
    big = sectioned_from_graph(g.row_ptr, g.col_idx, g.num_nodes,
                               section_rows=4096, seg_rows=64)
    if max(big.sec_sizes) <= 255:
        _pytest.skip("graph too small to overflow uint8 sections")
    with _pytest.raises(ValueError, match="does not fit"):
        big.with_idx_dtype(np.uint8)
