"""Worker for the merged-timeline rig test (tests/test_timeline.py):
a REAL multi-process P=4 distributed run whose per-process event and
metrics JSONL streams the timeline merger must fuse into one
Perfetto trace.

Each of ``nproc`` processes owns ``4 // nproc`` virtual CPU devices
(2 x 2 in the test — P=4 on the rig), meets the others through
``jax.distributed.initialize`` (Gloo loopback), writes its OWN
``ev_p<pid>.jsonl`` / ``m_p<pid>.jsonl`` (the per-process streams the
ISSUE's merge exists for), trains through enough evals that phase
spans, the clock-sync handshake, and per-epoch straggler attribution
all land in the artifacts.

Usage: python timeline_worker.py <coordinator> <nproc> <pid> <outdir>
"""

import os
import sys


def main() -> None:
    coordinator, nproc, pid, outdir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    n_parts = 4
    local_dev = n_parts // nproc
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={local_dev}")
    # per-process event stream BEFORE any roc_tpu import emits
    ev_path = os.path.join(outdir, f"ev_p{pid}.jsonl")
    os.environ["ROC_TPU_EVENTS"] = ev_path
    import jax
    jax.config.update("jax_platforms", "cpu")

    from roc_tpu.parallel import multihost as mh
    mh.init_distributed(coordinator, nproc, pid)
    assert jax.process_count() == nproc, jax.process_count()

    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.core.partition import partition_graph
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.train.trainer import TrainConfig

    ds = synthetic_dataset(32 * n_parts, 6, in_dim=12, num_classes=3,
                           seed=0)
    mesh = mh.make_parts_mesh(n_parts)
    cfg = TrainConfig(
        epochs=6, verbose=False, aggr_impl="ell", symmetric=True,
        dropout_rate=0.0, eval_every=2,
        metrics_path=os.path.join(outdir, f"m_p{pid}.jsonl"))
    pg = partition_graph(ds.graph, n_parts, node_multiple=8,
                         edge_multiple=cfg.chunk)
    data = mh.shard_dataset_local(ds, pg, mesh, aggr_impl="ell")
    tr = DistributedTrainer(build_gcn([12, 8, 3], dropout_rate=0.0),
                            ds, n_parts, cfg, mesh=mesh, data=data,
                            pg=pg)
    tr.train()
    print(f"WORKER_OK pid={pid}", flush=True)


if __name__ == "__main__":
    main()
