"""Perf-regression sentinel (roc_tpu/obs/sentinel.py): median+MAD
gate over the BENCH_*.json trajectory, small-sample rules, metrics-
JSONL mode, and the bench.py headline verdict."""

import json
import os
import shutil
import subprocess
import sys

from roc_tpu.obs.sentinel import (bench_history, bench_verdict,
                                  check_run, detect, metrics_summary)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------- detect()

def test_detect_no_data_and_no_history():
    assert detect([], None)["verdict"] == "no_data"
    assert detect([], 100.0)["verdict"] == "no_history"
    assert detect([None, None], 100.0)["verdict"] == "no_history"


def test_detect_median_mad_lower_better():
    hist = [100.0, 102.0, 98.0, 101.0]
    # rel floor dominates the tiny MAD: bound = 100.5 * 1.25
    assert detect(hist, 110.0)["verdict"] == "ok"
    v = detect(hist, 140.0)
    assert v["verdict"] == "regression"
    assert v["rule"].startswith("median_mad")
    assert v["n"] == 4 and v["median"] == 100.5


def test_detect_mad_scales_with_noise():
    """A noisy history widens the bound: the same excursion that bites
    on a tight history passes on a loose one."""
    tight = [100.0, 101.0, 99.0, 100.0, 100.5]
    loose = [100.0, 160.0, 60.0, 140.0, 80.0]
    assert detect(tight, 140.0)["verdict"] == "regression"
    assert detect(loose, 140.0)["verdict"] == "ok"


def test_detect_small_sample_rule():
    """n < 3: only a gross excursion (> 1.5x the median) flags — a
    synthetic 2x step-time regression bites, round noise does not."""
    v = detect([2362.64], 2362.64 * 2)
    assert v["verdict"] == "regression"
    assert v["rule"].startswith("small_sample")
    assert detect([2362.64], 2362.64 * 1.3)["verdict"] == "ok"
    assert detect([100.0, 104.0], 300.0)["verdict"] == "regression"


def test_detect_higher_is_better():
    hist = [0.60, 0.59, 0.61]
    assert detect(hist, 0.55, higher_is_better=True)["verdict"] == "ok"
    assert detect(hist, 0.20,
                  higher_is_better=True)["verdict"] == "regression"
    v = detect([0.6], 0.2, higher_is_better=True)
    assert v["verdict"] == "regression"   # small-sample, higher-better


# -------------------------------------------------- BENCH round loading

def test_bench_history_loads_checked_in_rounds():
    rounds = bench_history(os.path.join(_REPO, "BENCH_r*.json"))
    assert len(rounds) >= 5
    by_name = {r["path"]: r for r in rounds}
    # r01-r04 are legitimate all-null history; r05 carries the headline
    assert by_name["BENCH_r01.json"]["step_ms"] is None
    assert by_name["BENCH_r05.json"]["step_ms"] == 2362.64
    assert by_name["BENCH_r05.json"]["dtype"] == "mixed"


def test_bench_round_extracts_overlap_frac(tmp_path):
    """The overlap_frac gate has real history to work with: the micro
    stage's stream:prefetch row is extracted from each round, and a
    collapsed overlap regresses."""
    from roc_tpu.obs.sentinel import load_bench_round
    doc = {"parsed": {"value": 100.0, "unit": "ms", "stages": {
        "micro": {"impls": {
            "ell": {"ms": 5.0},
            "stream:prefetch": {"ms": 7.0, "overlap_frac": 0.59},
        }}}}}
    p = tmp_path / "BENCH_r10.json"
    p.write_text(json.dumps(doc))
    r = load_bench_round(str(p))
    assert r["overlap_frac"] == 0.59
    rounds = [dict(r, path=f"r{i}") for i in range(3)]
    res = check_run(rounds, {"overlap_frac": 0.1})
    assert "overlap_frac" in res["regressed"]
    assert check_run(rounds, {"overlap_frac": 0.6})["ok"]


def test_bench_round_extracts_mesh_ratio(tmp_path):
    """ISSUE-16 satellite: the micro stage's mesh:2d row carries the
    best-2-D-over-1-D epoch ratio; load_bench_round mines it and the
    gate bites when the model-sharded step slows relative to 1-D."""
    from roc_tpu.obs.sentinel import load_bench_round
    doc = {"parsed": {"value": 100.0, "unit": "ms", "stages": {
        "micro": {"impls": {
            "mesh:1d": {"epoch_ms": 50.0, "shape": "8x1"},
            "mesh:2d": {"epoch_ms": 46.0, "shape": "2x4",
                        "mesh_epoch_ratio": 0.92},
        }}}}}
    p = tmp_path / "BENCH_r10.json"
    p.write_text(json.dumps(doc))
    r = load_bench_round(str(p))
    assert r["mesh_epoch_ratio"] == 0.92
    rounds = [dict(r, path=f"r{i}") for i in range(3)]
    res = check_run(rounds, {"mesh_epoch_ratio": 1.9})
    assert "mesh_epoch_ratio" in res["regressed"]
    assert check_run(rounds, {"mesh_epoch_ratio": 0.95})["ok"]


def test_serve_availability_checks_bite():
    """ISSUE-13 satellite: the availability triple gates the serve
    trajectory — a healthy all-zero shed history still bites on a
    synthetic shed storm (allow_zero + the absolute floor), and an
    availability collapse regresses while normal jitter passes."""
    rounds = [{"path": f"r{i}", "serve_shed_rate": 0.0,
               "serve_error_rate": 0.0, "serve_availability": 1.0}
              for i in range(4)]
    res = check_run(rounds, {"serve_shed_rate": 0.3,
                             "serve_error_rate": 0.2,
                             "serve_availability": 0.5})
    assert set(res["regressed"]) == {"serve_shed_rate",
                                     "serve_error_rate",
                                     "serve_availability"}
    # jitter inside the absolute floor passes on the same history
    ok = check_run(rounds, {"serve_shed_rate": 0.01,
                            "serve_error_rate": 0.02,
                            "serve_availability": 0.97})
    assert ok["ok"], ok


def test_serve_availability_loaded_from_round(tmp_path):
    """bench.py's headline carries the triple; load_bench_round reads
    it back like serve_p50_ms."""
    from roc_tpu.obs.sentinel import load_bench_round
    doc = {"parsed": {"value": 100.0, "unit": "ms",
                      "serve_p50_ms": 0.5, "serve_qps": 1000.0,
                      "serve_shed_rate": 0.0,
                      "serve_error_rate": 0.01,
                      "serve_availability": 0.99}}
    p = tmp_path / "BENCH_r20.json"
    p.write_text(json.dumps(doc))
    r = load_bench_round(str(p))
    assert r["serve_shed_rate"] == 0.0
    assert r["serve_error_rate"] == 0.01
    assert r["serve_availability"] == 0.99
    rounds = [dict(r, path=f"r{i}") for i in range(3)]
    bad = check_run(rounds, {"serve_availability": 0.4})
    assert bad["regressed"] == ["serve_availability"]


def test_serve_p99_and_slo_ok_bite(tmp_path):
    """PR-17 satellite: the windowed tail latency and the SLO-smoke
    verdict gate the serve trajectory — a synthetic p99 blowup bites
    lower-better, a health-red smoke (0.0 after a 1.0 history) bites
    higher-better, normal jitter passes, and load_bench_round reads
    both columns back like serve_p50_ms."""
    from roc_tpu.obs.sentinel import load_bench_round
    doc = {"parsed": {"value": 100.0, "unit": "ms",
                      "serve_p50_ms": 0.5, "serve_p99_ms": 1.2,
                      "serve_slo_ok": 1.0}}
    p = tmp_path / "BENCH_r22.json"
    p.write_text(json.dumps(doc))
    r = load_bench_round(str(p))
    assert r["serve_p99_ms"] == 1.2
    assert r["serve_slo_ok"] == 1.0
    rounds = [dict(r, path=f"r{i}") for i in range(4)]
    bad = check_run(rounds, {"serve_p99_ms": 6.0,
                             "serve_slo_ok": 0.0})
    assert set(bad["regressed"]) == {"serve_p99_ms", "serve_slo_ok"}
    ok = check_run(rounds, {"serve_p99_ms": 1.3,
                            "serve_slo_ok": 1.0})
    assert ok["ok"], ok


def test_serve_obs_columns_tolerate_old_rounds():
    """Rounds recorded before PR 17 lack serve_p99_ms/serve_slo_ok
    entirely: the loader leaves them None, history shrinks to
    nothing, and the verdicts are no_history / no_data — never an
    error, never a false regression."""
    old = [{"path": f"r{i}", "serve_p50_ms": 0.5, "serve_qps": 900.0}
           for i in range(3)]
    res = check_run(old, {"serve_p50_ms": 0.51, "serve_qps": 880.0,
                          "serve_p99_ms": 1.4, "serve_slo_ok": 1.0})
    assert res["ok"], res
    assert res["checks"]["serve_p99_ms"]["verdict"] == "no_history"
    assert res["checks"]["serve_slo_ok"]["verdict"] == "no_history"
    # and a current run WITHOUT the new columns against any history
    res2 = check_run(old, {"serve_p50_ms": 0.5})
    assert res2["checks"]["serve_p99_ms"]["verdict"] == "no_data"
    assert res2["checks"]["serve_slo_ok"]["verdict"] == "no_data"
    assert res2["ok"], res2


def test_ckpt_columns_gate_and_load(tmp_path):
    """ISSUE-15 satellite: the checkpoint-cost pair rides the headline
    and gates lower-better — a synthetic 10x re-synchronized save
    regresses ckpt_block_ms/ckpt_save_ms, normal jitter passes, and
    load_bench_round reads the columns back like serve_p50_ms."""
    from roc_tpu.obs.sentinel import load_bench_round
    doc = {"parsed": {"value": 100.0, "unit": "ms",
                      "ckpt_save_ms": 40.0, "ckpt_block_ms": 2.0}}
    p = tmp_path / "BENCH_r21.json"
    p.write_text(json.dumps(doc))
    r = load_bench_round(str(p))
    assert r["ckpt_save_ms"] == 40.0
    assert r["ckpt_block_ms"] == 2.0
    rounds = [dict(r, path=f"r{i}") for i in range(4)]
    bad = check_run(rounds, {"ckpt_save_ms": 400.0,
                             "ckpt_block_ms": 20.0})
    assert set(bad["regressed"]) == {"ckpt_save_ms", "ckpt_block_ms"}
    ok = check_run(rounds, {"ckpt_save_ms": 42.0,
                            "ckpt_block_ms": 2.1})
    assert ok["ok"], ok


def test_serve_quant_columns_bite(tmp_path):
    """PR-19 satellite: the quantized-serving pair gates the
    trajectory — a synthetic bad round (table bytes back at fp32
    size → the shrink was lost; drift past the gate's floor) bites
    lower-better on BOTH columns, healthy jitter passes, and
    load_bench_round reads the columns back like serve_p50_ms."""
    from roc_tpu.obs.sentinel import load_bench_round
    doc = {"parsed": {"value": 100.0, "unit": "ms",
                      "serve_table_bytes": 5280000.0,
                      "serve_quant_drift": 0.011}}
    p = tmp_path / "BENCH_r23.json"
    p.write_text(json.dumps(doc))
    r = load_bench_round(str(p))
    assert r["serve_table_bytes"] == 5280000.0
    assert r["serve_quant_drift"] == 0.011
    rounds = [dict(r, path=f"r{i}") for i in range(4)]
    bad = check_run(rounds, {"serve_table_bytes": 20480000.0,
                             "serve_quant_drift": 0.3})
    assert set(bad["regressed"]) == {"serve_table_bytes",
                                     "serve_quant_drift"}
    ok = check_run(rounds, {"serve_table_bytes": 5280000.0,
                            "serve_quant_drift": 0.012})
    assert ok["ok"], ok
    # pre-PR-19 rounds lack the columns entirely: never an error
    old = [{"path": f"r{i}", "serve_p50_ms": 0.5} for i in range(3)]
    res = check_run(old, {"serve_p50_ms": 0.51,
                          "serve_table_bytes": 5280000.0,
                          "serve_quant_drift": 0.011})
    assert res["ok"], res
    assert res["checks"]["serve_table_bytes"]["verdict"] == \
        "no_history"


def test_serve_shard_columns_bite(tmp_path):
    """PR-20 satellite: the sharded-serving pair gates the
    trajectory — a synthetic bad round (per-replica slice back at
    full-table size → the slicing was lost; gather p50 blown up)
    bites lower-better on BOTH columns, healthy jitter passes, and
    load_bench_round reads the columns back like serve_p50_ms."""
    from roc_tpu.obs.sentinel import load_bench_round
    doc = {"parsed": {"value": 100.0, "unit": "ms",
                      "serve_shard_table_bytes": 1388772.0,
                      "serve_gather_p50_ms": 450.0}}
    p = tmp_path / "BENCH_r24.json"
    p.write_text(json.dumps(doc))
    r = load_bench_round(str(p))
    assert r["serve_shard_table_bytes"] == 1388772.0
    assert r["serve_gather_p50_ms"] == 450.0
    rounds = [dict(r, path=f"r{i}") for i in range(4)]
    bad = check_run(rounds, {"serve_shard_table_bytes": 2640132.0,
                             "serve_gather_p50_ms": 4500.0})
    assert set(bad["regressed"]) == {"serve_shard_table_bytes",
                                     "serve_gather_p50_ms"}
    ok = check_run(rounds, {"serve_shard_table_bytes": 1388772.0,
                            "serve_gather_p50_ms": 470.0})
    assert ok["ok"], ok
    # pre-PR-20 rounds lack the columns entirely: never an error
    old = [{"path": f"r{i}", "serve_p50_ms": 0.5} for i in range(3)]
    res = check_run(old, {"serve_p50_ms": 0.51,
                          "serve_shard_table_bytes": 1388772.0,
                          "serve_gather_p50_ms": 450.0})
    assert res["ok"], res
    assert res["checks"]["serve_shard_table_bytes"]["verdict"] == \
        "no_history"


def test_check_run_filters_step_history_by_dtype():
    rounds = [{"path": "a", "step_ms": 7920.0, "compile_s": None,
               "overlap_frac": None, "dtype": "float32"},
              {"path": "b", "step_ms": 2400.0, "compile_s": None,
               "overlap_frac": None, "dtype": "mixed"}]
    # a mixed 2500 ms run is fine next to the mixed 2400 round; the
    # fp32 7920 round must NOT widen the comparison
    res = check_run(rounds, {"step_ms": 2500.0, "dtype": "mixed"})
    assert res["ok"], res
    assert res["checks"]["step_time_ms"]["n"] == 1
    res2 = check_run(rounds, {"step_ms": 2400.0 * 2, "dtype": "mixed"})
    assert res2["regressed"] == ["step_time_ms"]


# -------------------------------------------------------- CLI contract

def _sentinel(args, cwd=_REPO):
    return subprocess.run(
        [sys.executable, "-m", "roc_tpu.sentinel"] + args,
        capture_output=True, text=True, cwd=cwd, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))


def test_cli_green_on_real_trajectory():
    """Acceptance: exit 0 on the checked-in r01-r05 history."""
    r = _sentinel(["--json"])
    assert r.returncode == 0, r.stdout + r.stderr
    payload = json.loads(r.stdout)
    assert payload["ok"] is True
    assert payload["current"]["round"] == "BENCH_r05.json"


def test_cli_bites_on_synthetic_2x_regression(tmp_path):
    """Acceptance: a 2x step-time regression injected into a COPY of
    the BENCH history exits nonzero."""
    for p in sorted(os.listdir(_REPO)):
        if p.startswith("BENCH_r") and p.endswith(".json"):
            shutil.copy(os.path.join(_REPO, p), tmp_path / p)
    with open(tmp_path / "BENCH_r06.json", "w") as f:
        json.dump({"parsed": {"value": 2362.64 * 2, "unit": "ms",
                              "stage": "full", "dtype": "mixed"}}, f)
    r = _sentinel(["--json", "--bench-glob",
                   str(tmp_path / "BENCH_r*.json")])
    assert r.returncode == 1, r.stdout + r.stderr
    payload = json.loads(r.stdout)
    assert payload["regressed"] == ["step_time_ms"]
    v = payload["checks"]["step_time_ms"]
    assert v["verdict"] == "regression" and v["n"] == 1


def test_cli_metrics_mode(tmp_path):
    """--metrics: a live run's steady epoch_ms checked against the
    whole round history."""
    hist_dir = tmp_path / "h"
    hist_dir.mkdir()
    for i, ms in enumerate((100.0, 104.0, 98.0)):
        with open(hist_dir / f"BENCH_r{i:02d}.json", "w") as f:
            json.dump({"parsed": {"value": ms, "unit": "ms",
                                  "stage": "full"}}, f)
    m = tmp_path / "m.jsonl"
    with open(m, "w") as f:
        f.write(json.dumps({"epoch": 1, "epoch_ms": 300.0,
                            "compile_ms": 900.0}) + "\n")
        for e in (3, 5):
            f.write(json.dumps({"epoch": e, "epoch_ms": 310.0}) + "\n")
    r = _sentinel(["--json", "--metrics", str(m), "--bench-glob",
                   str(hist_dir / "BENCH_r*.json")])
    assert r.returncode == 1, r.stdout + r.stderr
    payload = json.loads(r.stdout)
    assert payload["mode"] == "metrics"
    # the compile-lap record was excluded from the steady median
    assert payload["current"]["step_ms"] == 310.0
    assert payload["regressed"] == ["step_time_ms"]

    ok = tmp_path / "ok.jsonl"
    with open(ok, "w") as f:
        f.write(json.dumps({"epoch": 3, "epoch_ms": 101.0}) + "\n")
    r2 = _sentinel(["--json", "--metrics", str(ok), "--bench-glob",
                    str(hist_dir / "BENCH_r*.json")])
    assert r2.returncode == 0, r2.stdout + r2.stderr


def test_metrics_summary_fields():
    recs = [{"epoch": 1, "epoch_ms": 50.0, "compile_ms": 2000.0,
             "overlap_frac": 0.5},
            {"epoch": 3, "epoch_ms": 52.0, "overlap_frac": 0.7},
            {"epoch": 5, "epoch_ms": 48.0}]
    s = metrics_summary(recs)
    assert s["step_ms"] == 50.0       # median of the steady laps only
    assert s["compile_s"] == 2.0
    assert s["overlap_frac"] == 0.6


def test_bench_verdict_shape(tmp_path):
    """bench.py records this into the headline line: compact, never
    raises, honest about missing history."""
    v = bench_verdict(2400.0, dtype="mixed", bench_dir=str(tmp_path))
    assert v == {"verdict": "no_history", "n_history": 0}
    for i, ms in enumerate((2400.0, 2500.0, 2350.0)):
        with open(tmp_path / f"BENCH_r{i:02d}.json", "w") as f:
            json.dump({"parsed": {"value": ms, "unit": "ms",
                                  "dtype": "mixed"}}, f)
    good = bench_verdict(2450.0, dtype="mixed",
                         bench_dir=str(tmp_path))
    assert good["verdict"] == "ok" and good["n_history"] == 3
    bad = bench_verdict(2400.0 * 2, dtype="mixed",
                        bench_dir=str(tmp_path))
    assert bad["verdict"] == "regression"


def test_bench_verdict_filters_by_stage(tmp_path):
    """A small-stage headline is never scored against full-scale
    history (and vice versa)."""
    with open(tmp_path / "BENCH_r00.json", "w") as f:
        json.dump({"parsed": {"value": 2400.0, "unit": "ms",
                              "stage": "full", "dtype": "mixed"}}, f)
    v = bench_verdict(240.0, dtype="mixed", bench_dir=str(tmp_path),
                      stage="small")
    assert v == {"verdict": "no_history", "n_history": 0}
    v_full = bench_verdict(2400.0 * 2, dtype="mixed",
                           bench_dir=str(tmp_path), stage="full")
    assert v_full["verdict"] == "regression"
