"""Multi-host runtime tests on the 8-virtual-device CPU rig.

Single-process is the degenerate case of every multihost helper, so
these validate the mesh layout, local-part selection, per-shard array
assembly, and that DistributedTrainer runs unchanged on
``shard_dataset_local`` output."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from roc_tpu.core.graph import synthetic_dataset
from roc_tpu.core.partition import partition_graph
from roc_tpu.models.gcn import build_gcn
from roc_tpu.parallel import multihost as mh


def test_init_distributed_noop_without_coordinator(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    mh.init_distributed()  # must not raise or initialize anything


def test_make_parts_mesh_defaults():
    mesh = mh.make_parts_mesh()
    assert mesh.axis_names == ("parts",)
    assert mesh.devices.size == len(jax.devices())
    small = mh.make_parts_mesh(4)
    assert small.devices.size == 4


def test_process_local_parts_single_process():
    mesh = mh.make_parts_mesh(8)
    assert mh.process_local_parts(mesh) == list(range(8))


def test_make_sharded_array_roundtrip():
    mesh = mh.make_parts_mesh(4)
    data = np.arange(4 * 3 * 2, dtype=np.float32).reshape(4, 3, 2)
    local = mh.process_local_parts(mesh)
    arr = mh.make_sharded_array(mesh, local,
                                [data[p:p + 1] for p in local],
                                data.shape)
    assert arr.shape == data.shape
    np.testing.assert_array_equal(np.asarray(arr), data)
    # each shard actually lives on its mesh device
    shards = {s.device: np.asarray(s.data) for s in arr.addressable_shards}
    for i, d in enumerate(mesh.devices.reshape(-1)):
        np.testing.assert_array_equal(shards[d], data[i:i + 1])


def test_local_ell_plan_matches_global_on_full_part():
    """Regression (round-2 advisor, high): when real_nodes[p] ==
    part_nodes, padding edges inflate the last real row's local-CSR
    degree; the shape plan must be derived from those SAME degrees or
    the local ELL tables silently drop that row's edges and diverge
    from shard_dataset's.

    Since the cost-partitioning PR the plan layer PREVENTS the
    hazardous fixture outright: a part whose real rows exactly fill
    part_nodes while carrying padding edges gets one extra
    row-multiple (core/partition.plan_from_bounds), because the
    sectioned/bdense planners — unlike the ELL builder this test
    originally pinned — cannot tolerate dummy sources inside real
    rows.  The test now asserts that invariant AND keeps the
    local-vs-global ELL equality on the same node_multiple=1
    fixture."""
    from roc_tpu.parallel.distributed import shard_dataset

    ds = synthetic_dataset(96, 7, in_dim=12, num_classes=3, seed=11)
    # node_multiple=1: the largest partition WOULD be exactly full —
    # the plan layer must have padded it by one extra row-multiple
    # instead of letting its last real row absorb the padding edges
    pg = partition_graph(ds.graph, 4, node_multiple=1, edge_multiple=128)
    full = np.flatnonzero(pg.real_nodes == pg.part_nodes)
    assert not full.size, (
        "plan_from_bounds must keep padding edges on padded rows — a "
        "full partition with padding edges leaks dummy sources into "
        "real rows")
    assert pg.part_nodes == int(pg.real_nodes.max()) + 1

    mesh = mh.make_parts_mesh(4)
    loc = mh.shard_dataset_local(ds, pg, mesh, aggr_impl="ell")
    glo = shard_dataset(ds, pg, mesh, aggr_impl="ell")
    assert len(loc.ell_idx) == len(glo.ell_idx)
    for a, b in zip(loc.ell_idx, glo.ell_idx):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(loc.ell_row_pos),
                                  np.asarray(glo.ell_row_pos))
    # the attention row map must agree too (EllTable.row_id)
    assert len(loc.ell_row_id) == len(glo.ell_row_id)
    for a, b in zip(loc.ell_row_id, glo.ell_row_id):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_predict_on_local_shards():
    """predict() (replicated all_gather output) returns the same
    original-order logits from partition-local shards as from the
    global build."""
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.train.trainer import TrainConfig

    ds = synthetic_dataset(96, 7, in_dim=12, num_classes=3, seed=7)
    mesh = mh.make_parts_mesh(4)
    cfg = TrainConfig(verbose=False, aggr_impl="ell", symmetric=True)
    tr = DistributedTrainer(build_gcn([12, 8, 3], dropout_rate=0.0),
                            ds, 4, cfg, mesh=mesh)
    want = tr.predict()
    assert want.shape == (96, 3)
    tr.data = mh.shard_dataset_local(ds, tr.pg, mesh, aggr_impl="ell")
    # atol: the global build carries baked fused-norm weight tables,
    # the local-shards build scales in-op (same operator, different
    # fp32 association) — near-zero logits need an absolute floor
    np.testing.assert_allclose(tr.predict(), want, rtol=1e-5,
                               atol=1e-6)


def test_gat_trains_on_local_shards():
    """Attention over partition-local ELL tables: the multihost
    row_id upload must feed the edge softmax identically to the
    global path."""
    from roc_tpu.models.gat import build_gat
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.train.trainer import TrainConfig

    ds = synthetic_dataset(96, 7, in_dim=12, num_classes=3, seed=5)
    mesh = mh.make_parts_mesh(4)
    cfg = TrainConfig(epochs=2, verbose=False, aggr_impl="ell",
                      symmetric=True, dropout_rate=0.0)
    tr = DistributedTrainer(build_gat([12, 8, 3], dropout_rate=0.0),
                            ds, 4, cfg, mesh=mesh)
    want = tr.evaluate()["train_loss"]
    tr.data = mh.shard_dataset_local(ds, tr.pg, mesh, aggr_impl="ell")
    got = tr.evaluate()["train_loss"]
    np.testing.assert_allclose(got, want, rtol=1e-5)
    tr.train(epochs=2)
    assert np.isfinite(tr.evaluate()["train_loss"])


@pytest.mark.parametrize("halo", ["gather", "ring"])
def test_distributed_trainer_on_local_shards(halo):
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.train.trainer import TrainConfig

    n_dev = 4
    ds = synthetic_dataset(16 * n_dev, 6, in_dim=12, num_classes=3,
                           seed=0)
    mesh = mh.make_parts_mesh(n_dev)
    cfg = TrainConfig(epochs=2, verbose=False, aggr_impl="blocked",
                      chunk=64, halo=halo)
    tr = DistributedTrainer(build_gcn([12, 8, 3]), ds, n_dev, cfg,
                            mesh=mesh)
    pg = partition_graph(ds.graph, n_dev)
    tr.data = mh.shard_dataset_local(ds, tr.pg, mesh,
                                     dtype=jnp.float32,
                                     aggr_impl="blocked", halo=halo)
    tr.train(epochs=2)
    m = tr.evaluate()
    assert np.isfinite(m["train_loss"])


@pytest.mark.parametrize("impl", ["ell", "bdense"])
def test_two_process_dcn_parity(tmp_path, impl):
    """REAL 2-process execution (VERDICT r4 missing #3): two OS
    processes x 4 CPU devices meet via jax.distributed.initialize,
    each builds only its own partitions with shard_dataset_local,
    trains 2 epochs with cross-process psum, and the result must match
    a single-process run of the identical 8-part workload bit-for-bit
    up to float tolerance.  The bdense variant exercises the REAL
    cross-process block-count/chunk-plan agreement collectives."""
    import socket
    import subprocess
    import sys as _sys
    import os as _os

    worker = _os.path.join(_os.path.dirname(__file__),
                           "multihost_worker.py")
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    env = dict(_os.environ)
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    repo = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + _os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [_sys.executable, worker, f"localhost:{port}", "2", str(i),
         str(tmp_path), impl],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for i in (0, 1)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-3000:]
        assert "WORKER_OK" in out
    got = np.load(tmp_path / "result.npz")

    # identical workload, single process on the in-test 8-device rig
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.train.trainer import TrainConfig
    ds = synthetic_dataset(16 * 8, 6, in_dim=12, num_classes=3, seed=0)
    mesh = mh.make_parts_mesh(8)
    cfg = TrainConfig(epochs=2, verbose=False, aggr_impl=impl,
                      bdense_min_fill=8,
                      symmetric=True, dropout_rate=0.0,
                      eval_every=1 << 30)
    tr = DistributedTrainer(build_gcn([12, 8, 3], dropout_rate=0.0),
                            ds, 8, cfg, mesh=mesh)
    tr.train(epochs=2)
    want_m = tr.evaluate()
    np.testing.assert_allclose(got["train_loss"], want_m["train_loss"],
                               rtol=1e-5)
    np.testing.assert_allclose(got["train_acc"], want_m["train_acc"],
                               rtol=1e-6)
    for k, v in tr.params.items():
        np.testing.assert_allclose(got[f"param_{k}"], np.asarray(v),
                                   rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(got["logits"], tr.predict(),
                               rtol=2e-4, atol=2e-4)


def test_local_sectioned_honors_sub_w_and_u16():
    """shard_dataset_local must honor sect_sub_w/sect_u16 exactly like
    shard_dataset (the advisor's silently-dropped-config class, fixed
    at BOTH levels)."""
    from roc_tpu.parallel.distributed import shard_dataset

    ds = synthetic_dataset(96, 7, in_dim=12, num_classes=3, seed=11)
    pg = partition_graph(ds.graph, 4, node_multiple=8, edge_multiple=64)
    mesh = mh.make_parts_mesh(4)
    loc = mh.shard_dataset_local(ds, pg, mesh, aggr_impl="sectioned",
                                 sect_sub_w=16, sect_u16=True)
    glo = shard_dataset(ds, pg, mesh, aggr_impl="sectioned",
                        sect_sub_w=16, sect_u16=True)
    assert len(loc.sect_idx) == len(glo.sect_idx)
    for a, b in zip(loc.sect_idx, glo.sect_idx):
        assert a.dtype == jnp.uint16
        assert a.shape[-1] == 16
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(loc.sect_sub_dst, glo.sect_sub_dst):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert loc.sect_meta == glo.sect_meta


def test_local_flat8_matches_global_and_trains():
    """shard_dataset_local's attn_flat8 tables must equal
    shard_dataset's, and the injected-data path must run the GAT
    through them (the multi-host large-attention entry point)."""
    from roc_tpu.models.gat import build_gat
    from roc_tpu.parallel.distributed import (DistributedTrainer,
                                              shard_dataset)
    from roc_tpu.train.trainer import TrainConfig

    ds = synthetic_dataset(96, 7, in_dim=12, num_classes=3, seed=11)
    pg = partition_graph(ds.graph, 4, node_multiple=8, edge_multiple=64)
    mesh = mh.make_parts_mesh(4)
    loc = mh.shard_dataset_local(ds, pg, mesh, aggr_impl="attn_flat8")
    glo = shard_dataset(ds, pg, mesh, aggr_impl="attn_flat8")
    assert len(loc.sect_idx) == 1 == len(glo.sect_idx)
    np.testing.assert_array_equal(np.asarray(loc.sect_idx[0]),
                                  np.asarray(glo.sect_idx[0]))
    np.testing.assert_array_equal(np.asarray(loc.sect_sub_dst[0]),
                                  np.asarray(glo.sect_sub_dst[0]))
    # the flat edge arrays are stubs, not [P, E_p] uploads
    assert loc.edge_src.shape[-1] == 1
    cfg = TrainConfig(epochs=2, verbose=False, aggr_impl="attn_flat8",
                      dropout_rate=0.0, eval_every=1 << 30)
    tr = DistributedTrainer(build_gat([12, 8, 3], dropout_rate=0.0),
                            ds, 4, cfg, mesh=mesh, data=loc, pg=pg)
    tr.train(epochs=2)
    assert np.isfinite(tr.evaluate()["train_loss"])


def test_local_flat_sum_matches_global_and_trains():
    """shard_dataset_local's flat_sum tables must equal
    shard_dataset's and train through the injected-data path — the
    resolve pass auto-routes multi-process >=20M-edge configs to
    flat_sum, so the multihost builder must host it (parity vs the
    single-device segment reference <= 1e-5)."""
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.parallel.distributed import (DistributedTrainer,
                                              shard_dataset)
    from roc_tpu.train.trainer import Trainer, TrainConfig

    ds = synthetic_dataset(96, 7, in_dim=12, num_classes=3, seed=11)
    pg = partition_graph(ds.graph, 4, node_multiple=8, edge_multiple=64)
    mesh = mh.make_parts_mesh(4)
    loc = mh.shard_dataset_local(ds, pg, mesh, aggr_impl="flat_sum")
    glo = shard_dataset(ds, pg, mesh, aggr_impl="flat_sum")
    assert len(loc.sect_idx) == 1 == len(glo.sect_idx)
    np.testing.assert_array_equal(np.asarray(loc.sect_idx[0]),
                                  np.asarray(glo.sect_idx[0]))
    np.testing.assert_array_equal(np.asarray(loc.sect_sub_dst[0]),
                                  np.asarray(glo.sect_sub_dst[0]))
    # the flat edge arrays are stubs, not [P, E_p] uploads
    assert loc.edge_src.shape[-1] == 1
    cfg = TrainConfig(epochs=3, verbose=False, aggr_impl="flat_sum",
                      symmetric=True, dropout_rate=0.0,
                      eval_every=1 << 30)
    tr = DistributedTrainer(build_gcn([12, 8, 3], dropout_rate=0.0),
                            ds, 4, cfg, mesh=mesh, data=loc, pg=pg)
    tr.train(epochs=3)
    ref = Trainer(build_gcn([12, 8, 3], dropout_rate=0.0), ds,
                  TrainConfig(epochs=3, verbose=False,
                              aggr_impl="segment", symmetric=True,
                              dropout_rate=0.0, eval_every=1 << 30))
    ref.train(epochs=3)
    p0 = np.asarray(ref.predict(), np.float64)
    p1 = np.asarray(tr.predict(), np.float64)
    err = np.max(np.abs(p1 - p0)) / max(1.0, np.max(np.abs(p0)))
    assert err < 1e-5


def test_injected_data_without_flat8_tables_fails_fast():
    """Resolved attn_flat8 + injected data lacking the tables must be
    a construction-time ValueError, not a mid-trace IndexError."""
    from roc_tpu.models.gat import build_gat
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.train.trainer import TrainConfig

    ds = synthetic_dataset(96, 7, in_dim=12, num_classes=3, seed=11)
    pg = partition_graph(ds.graph, 4, node_multiple=8, edge_multiple=64)
    mesh = mh.make_parts_mesh(4)
    ell_data = mh.shard_dataset_local(ds, pg, mesh, aggr_impl="ell")
    cfg = TrainConfig(verbose=False, aggr_impl="attn_flat8",
                      dropout_rate=0.0)
    with pytest.raises(ValueError, match="flat8"):
        DistributedTrainer(build_gat([12, 8, 3], dropout_rate=0.0),
                           ds, 4, cfg, mesh=mesh, data=ell_data, pg=pg)


def test_injected_sectioned_data_with_bdense_impl_fails_fast():
    """Sectioned-built data passes the sect_idx/sect_meta checks but
    carries no block plan; resolved aggr_impl='bdense' must raise at
    construction, not silently run the pure sectioned residual."""
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.train.trainer import TrainConfig

    ds = synthetic_dataset(96, 7, in_dim=12, num_classes=3, seed=11)
    pg = partition_graph(ds.graph, 4, node_multiple=8, edge_multiple=64)
    mesh = mh.make_parts_mesh(4)
    sect_data = mh.shard_dataset_local(ds, pg, mesh,
                                       aggr_impl="sectioned")
    cfg = TrainConfig(verbose=False, aggr_impl="bdense",
                      dropout_rate=0.0)
    with pytest.raises(ValueError, match="block-dense"):
        DistributedTrainer(build_gcn([12, 8, 3], dropout_rate=0.0),
                           ds, 4, cfg, mesh=mesh, data=sect_data,
                           pg=pg)
