"""(parts, model) 2-D mesh training parity (ISSUE 16): the tentpole's
end-to-end guarantee.  Training on EVERY (parts, model) factorization
of the 8-virtual-device rig produces the same learning trajectory as
today's 1-D all-parts mesh at the same partition count — fwd + grad +
update within 1e-5 after multiple epochs — including the fused
flat_sum aggregate and the ring halo schedule, with parameters
model-SHARDED at rest whenever model > 1 (the replication-ledger
ratchet's live counterpart; the modeled side is tests/
test_sharding_lint.py)."""

import numpy as np
import pytest

import jax

from roc_tpu.core.graph import MASK_NONE, Dataset, random_csr
from roc_tpu.models.gcn import build_gcn
from roc_tpu.parallel import (MODEL_AXIS, candidate_mesh_shapes,
                              model_shard_spec)
from roc_tpu.parallel.distributed import DistributedTrainer
from roc_tpu.train.trainer import TrainConfig

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-virtual-device rig")

V, F, C = 192, 48, 6


@pytest.fixture(scope="module")
def dataset():
    g = random_csr(V, 6 * V, seed=0)
    rng = np.random.RandomState(1)
    ds = Dataset(graph=g, features=rng.rand(V, F).astype(np.float32),
                 labels=rng.randint(0, C, size=V).astype(np.int32),
                 mask=np.full(V, MASK_NONE, dtype=np.int32),
                 num_classes=C, name="mesh2d")
    ds.mask[rng.rand(V) < 0.5] = 1
    return ds


def _train(ds, parts, mesh, epochs=3, **kw):
    cfg = TrainConfig(verbose=False, symmetric=True, dropout_rate=0.0,
                      eval_every=1 << 30, mesh=mesh, **kw)
    tr = DistributedTrainer(build_gcn([F, 24, C], dropout_rate=0.0),
                            ds, parts, cfg)
    tr.train(epochs=epochs)
    tr.sync()
    return tr


def _assert_parity(ref, got, tol=1e-5):
    """Identical trajectory: every parameter leaf within tol after the
    full fwd+grad+update loop, and the evaluated loss agrees."""
    pr = jax.device_get(ref.params)
    pg = jax.device_get(got.params)
    assert sorted(pr) == sorted(pg)
    for k in pr:
        d = float(np.max(np.abs(np.asarray(pr[k], np.float64)
                                - np.asarray(pg[k], np.float64))))
        assert d <= tol, (k, d)
    assert got.evaluate()["train_loss"] == pytest.approx(
        ref.evaluate()["train_loss"], abs=1e-5)


def _assert_model_sharded_at_rest(tr, model):
    """Params AND Adam moments whose shape carries a model-divisible
    dim actually live split over MODEL_AXIS (not just modeled so)."""
    sharded = 0
    for tree in (tr.params, tr.opt_state.m, tr.opt_state.v):
        for k, leaf in tree.items():
            spec = model_shard_spec(np.shape(leaf), model)
            if spec is None:
                continue
            sharded += 1
            assert tuple(leaf.sharding.spec) == spec, \
                (k, leaf.sharding.spec, spec)
    assert sharded > 0, "no leaf left the replicated layout"


@pytest.mark.parametrize(
    "shape", candidate_mesh_shapes(8),
    ids=lambda s: f"{s[0]}x{s[1]}")
def test_training_parity_every_mesh_shape(dataset, shape):
    """1-D vs 2-D parity on every factorization of the rig, reference
    rebuilt at the SAME partition count (the parts axis is the
    partition count; only the model axis is new)."""
    parts, model = shape
    ref = _train(dataset, parts, "auto")
    two = _train(dataset, parts, f"{parts}x{model}")
    if model > 1:
        _assert_model_sharded_at_rest(two, model)
    _assert_parity(ref, two)


def test_training_parity_flat_sum_fused_aggregate(dataset):
    """The fused aggregate keeps parity on the 2-D mesh (the flat8
    scan runs inside the partial-auto shard_map body)."""
    ref = _train(dataset, 2, "auto", aggr_impl="flat_sum")
    two = _train(dataset, 2, "2x4", aggr_impl="flat_sum")
    _assert_model_sharded_at_rest(two, 4)
    _assert_parity(ref, two)


def test_training_parity_ring_halo(dataset):
    """halo='ring' on the 2-D mesh runs the step fully manual over
    both axes (ppermute cannot cross a partial-auto boundary) — the
    trajectory still matches, and params still rest model-sharded
    between steps."""
    ref = _train(dataset, 2, "auto", halo="ring")
    two = _train(dataset, 2, "2x4", halo="ring")
    _assert_model_sharded_at_rest(two, 4)
    _assert_parity(ref, two)
