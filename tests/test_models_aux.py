"""SAGE/GIN model families, max aggregator, checkpoint/resume, CLI."""

import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from roc_tpu.core.graph import synthetic_dataset
from roc_tpu.models.builder import AGGR_AVG, AGGR_MAX, AGGR_SUM
from roc_tpu.models.gcn import build_gcn
from roc_tpu.models.gin import build_gin
from roc_tpu.models.sage import build_sage
from roc_tpu.train.trainer import TrainConfig, Trainer, make_graph_context


@pytest.fixture(scope="module")
def dataset():
    return synthetic_dataset(96, 7, in_dim=12, num_classes=3, seed=2)


# GIN's un-normalized sum aggregation amplifies dropout noise on the
# tiny fixture, so it trains without dropout (and needs more epochs).
@pytest.mark.parametrize("build,dropout,epochs",
                         [(build_sage, 0.1, 60), (build_gin, 0.0, 120)])
def test_model_families_converge(dataset, build, dropout, epochs):
    model = build([dataset.in_dim, 24, dataset.num_classes],
                  dropout_rate=dropout)
    cfg = TrainConfig(learning_rate=0.01, weight_decay=1e-4,
                      epochs=epochs, verbose=False)
    t = Trainer(model, dataset, cfg)
    t.train()
    m = t.evaluate()
    assert m["train_acc"] > 0.9, m


@pytest.mark.parametrize("build", [build_sage, build_gin])
def test_model_families_impl_invariance(dataset, build):
    model = build([dataset.in_dim, 16, dataset.num_classes],
                  dropout_rate=0.0)
    params = model.init_params(jax.random.PRNGKey(0))
    feats = jnp.asarray(dataset.features)
    outs = {}
    for impl in ("segment", "ell"):
        gctx = make_graph_context(dataset, aggr_impl=impl)
        outs[impl] = np.asarray(model.apply(params, feats, gctx,
                                            train=False))
    np.testing.assert_allclose(outs["segment"], outs["ell"],
                               rtol=1e-4, atol=1e-4)


def test_appnp_matches_manual_propagation(dataset):
    """build_appnp == the hand-written APPNP recurrence
    Z_{k+1} = (1-a) * S Z_k + a * H computed directly from the CSR
    (S = D^-1/2 A D^-1/2, self edges pre-added by the fixture)."""
    from roc_tpu.models.appnp import build_appnp
    k, alpha = 3, 0.2
    model = build_appnp([dataset.in_dim, 16, dataset.num_classes],
                        k=k, alpha=alpha, dropout_rate=0.0)
    params = model.init_params(jax.random.PRNGKey(0))
    feats = jnp.asarray(dataset.features)
    gctx = make_graph_context(dataset, aggr_impl="segment")
    got = np.asarray(model.apply(params, feats, gctx, train=False))

    # manual: MLP then the propagation recurrence
    g = dataset.graph
    h = np.maximum(
        dataset.features @ np.asarray(params["linear_0"]), 0.0)
    h = h @ np.asarray(params["linear_1"])
    deg = np.asarray(g.in_degree, dtype=np.float64)
    dinv = 1.0 / np.sqrt(np.maximum(deg, 1.0))
    dst = np.repeat(np.arange(g.num_nodes), np.diff(g.row_ptr))
    z = h.astype(np.float64)
    for _ in range(k):
        s = np.zeros_like(z)
        np.add.at(s, dst, (z * dinv[:, None])[g.col_idx])
        z = (1 - alpha) * s * dinv[:, None] + alpha * h
    np.testing.assert_allclose(got, z, rtol=2e-4, atol=2e-4)


def test_appnp_converges_and_cli_validates(dataset):
    """APPNP trains to high accuracy on the homophilous fixture, the
    parameter count is propagation-depth-independent (decoupled
    predict-then-propagate), and bad --alpha values fail fast."""
    from roc_tpu.models.appnp import build_appnp
    m10 = build_appnp([dataset.in_dim, 24, dataset.num_classes],
                      k=10, alpha=0.1, dropout_rate=0.1)
    m2 = build_appnp([dataset.in_dim, 24, dataset.num_classes],
                     k=2, alpha=0.1, dropout_rate=0.1)
    p10 = m10.init_params(jax.random.PRNGKey(0))
    p2 = m2.init_params(jax.random.PRNGKey(0))
    assert {k_: v.shape for k_, v in p10.items()} == \
        {k_: v.shape for k_, v in p2.items()}
    t = Trainer(m10, dataset,
                TrainConfig(learning_rate=0.02, weight_decay=1e-4,
                            epochs=80, verbose=False))
    t.train()
    assert t.evaluate()["train_acc"] > 0.9
    with pytest.raises(ValueError, match="alpha"):
        build_appnp([12, 4], alpha=1.5)


def test_gcn2_deep_stack_converges(dataset):
    """GCNII's raison d'etre: an 8-propagation-layer stack still
    trains to high accuracy (initial residual + identity mapping
    prevent the oversmoothing a plain deep GCN suffers), and
    validation rejects mismatched hidden widths / degenerate knobs."""
    from roc_tpu.models.gcn2 import build_gcn2
    layers = [dataset.in_dim] + [24] * 8 + [dataset.num_classes]
    model = build_gcn2(layers, alpha=0.1, lam=0.5, dropout_rate=0.1)
    t = Trainer(model, dataset,
                TrainConfig(learning_rate=0.02, weight_decay=1e-4,
                            epochs=80, verbose=False))
    t.train()
    assert t.evaluate()["train_acc"] > 0.9
    with pytest.raises(ValueError, match="hidden widths"):
        build_gcn2([12, 16, 24, 3])
    with pytest.raises(ValueError, match="alpha"):
        build_gcn2([12, 16, 3], alpha=-0.1)
    with pytest.raises(ValueError, match="lam"):
        build_gcn2([12, 16, 3], lam=0.0)
    with pytest.raises(ValueError, match="hidden"):
        build_gcn2([12, 3])


def test_gcn2_matches_manual_recurrence(dataset):
    """build_gcn2 == the hand-written GCNII layer math on the CSR."""
    import math as _math
    from roc_tpu.models.gcn2 import build_gcn2
    alpha, lam = 0.2, 0.6
    model = build_gcn2([dataset.in_dim, 16, 16, dataset.num_classes],
                       alpha=alpha, lam=lam, dropout_rate=0.0)
    params = model.init_params(jax.random.PRNGKey(1))
    feats = jnp.asarray(dataset.features)
    gctx = make_graph_context(dataset, aggr_impl="segment")
    got = np.asarray(model.apply(params, feats, gctx, train=False))

    g = dataset.graph
    deg = np.asarray(g.in_degree, dtype=np.float64)
    dinv = 1.0 / np.sqrt(np.maximum(deg, 1.0))
    dst = np.repeat(np.arange(g.num_nodes), np.diff(g.row_ptr))

    def prop(z):
        s = np.zeros_like(z)
        np.add.at(s, dst, (z * dinv[:, None])[g.col_idx])
        return s * dinv[:, None]

    h0 = np.maximum(
        dataset.features @ np.asarray(params["linear_0"]), 0.0)
    t = h0
    for l in (1, 2):
        beta = _math.log(lam / l + 1.0)
        m = (1 - alpha) * prop(t) + alpha * h0
        t = np.maximum(
            (1 - beta) * m
            + beta * (m @ np.asarray(params[f"linear_{l}"])), 0.0)
    z = t @ np.asarray(params["linear_3"])
    np.testing.assert_allclose(got, z, rtol=2e-4, atol=2e-4)


def test_gin_learnable_eps(dataset):
    """learn_eps=True: zero-init scalar (GIN-0), updated by training,
    and at eps == 0 the forward equals plain aggregation (no self
    doubling)."""
    model = build_gin([dataset.in_dim, 16, dataset.num_classes],
                      dropout_rate=0.0, learn_eps=True)
    params = model.init_params(jax.random.PRNGKey(0))
    assert params["eps_0"].shape == ()
    assert float(params["eps_0"]) == 0.0
    # the algebra the docstring claims: at eps == 0 the layer output
    # is EXACTLY the aggregation (no self term) — pin the forward
    # against a hand-built model with the eps layer removed, sharing
    # the same linear params (scale_add consumes no PRNG key, so the
    # param names and values line up)
    from roc_tpu.models.builder import AGGR_SUM, Model
    from roc_tpu.ops.dense import AC_MODE_NONE, AC_MODE_RELU
    ref_model = Model(in_dim=dataset.in_dim)
    rt = ref_model.input()
    for dim in (16, dataset.num_classes):
        rt = ref_model.dropout(rt, 0.0)
        rt = ref_model.scatter_gather(rt, aggr=AGGR_SUM)
        rt = ref_model.linear(rt, dim, AC_MODE_RELU)
        rt = ref_model.linear(rt, dim, AC_MODE_NONE)
        if dim != dataset.num_classes:
            rt = ref_model.relu(rt)
    ref_model.softmax_cross_entropy(rt)
    gctx = make_graph_context(dataset, aggr_impl="ell")
    feats = jnp.asarray(dataset.features)
    got = model.apply(params, feats, gctx, train=False)
    ref = ref_model.apply(params, feats, gctx, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6)
    cfg = TrainConfig(learning_rate=0.01, aggr_impl="ell",
                      verbose=False, eval_every=1 << 30)
    t = Trainer(model, dataset, cfg)
    loss0 = t.evaluate()["train_loss"]
    t.train(epochs=60)
    m = t.evaluate()
    # mechanics, not a convergence bar: GIN-0's zero-init self weight
    # is a much weaker inductive bias than the fixed eps=1 form on
    # this tiny fixture (which test_model_families_converge gates);
    # here we pin that the objective moves and eps is actually trained
    assert m["train_loss"] < 0.75 * loss0, (loss0, m["train_loss"])
    assert float(t.params["eps_0"]) != 0.0  # actually learned


def test_sage_pool_converges_and_validates(dataset):
    """Hamilton et al.'s max-pool aggregator: learned ReLU pre-pool
    transform + neighborhood MAX (the AGGR_MAX path's first real
    model consumer); bad option combos error up front."""
    model = build_sage([dataset.in_dim, 24, dataset.num_classes],
                       dropout_rate=0.0, aggregator="pool")
    # 'auto' must resolve to 'ell' via the shared model-driven impl
    # policy (sectioned/blocked/scan have no MAX form)
    cfg = TrainConfig(learning_rate=0.01, weight_decay=1e-4,
                      aggr_impl="auto", verbose=False,
                      eval_every=1 << 30)
    t = Trainer(model, dataset, cfg)
    assert t.config.aggr_impl == "ell"
    t.train(epochs=80)
    m = t.evaluate()
    assert m["train_acc"] > 0.9, m
    with pytest.raises(ValueError, match="aggregator"):
        build_sage([4, 8, 2], aggregator="median")
    with pytest.raises(ValueError, match="use_norm"):
        build_sage([4, 8, 2], aggregator="pool", use_norm=True)
    # ring + MAX fails fast at trainer setup, before any table build
    from roc_tpu.parallel.distributed import DistributedTrainer
    with pytest.raises(NotImplementedError, match="ring"):
        DistributedTrainer(model, dataset, 4,
                           TrainConfig(aggr_impl="ell", halo="ring",
                                       verbose=False))


def test_max_aggregator_matches_numpy(dataset):
    g = dataset.graph
    feats = dataset.features
    # numpy reference
    want = np.zeros_like(feats)
    for v in range(g.num_nodes):
        srcs = g.col_idx[g.row_ptr[v]:g.row_ptr[v + 1]]
        if len(srcs):
            want[v] = feats[srcs].max(axis=0)
    for impl in ("segment", "ell"):
        gctx = make_graph_context(dataset, aggr_impl=impl)
        got = np.asarray(gctx.aggregate(jnp.asarray(feats), AGGR_MAX))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6,
                                   err_msg=impl)


def test_min_aggregator_matches_numpy(dataset):
    from roc_tpu.models.builder import AGGR_MIN
    g = dataset.graph
    feats = dataset.features
    want = np.zeros_like(feats)
    for v in range(g.num_nodes):
        srcs = g.col_idx[g.row_ptr[v]:g.row_ptr[v + 1]]
        if len(srcs):
            want[v] = feats[srcs].min(axis=0)
    for impl in ("segment", "ell"):
        gctx = make_graph_context(dataset, aggr_impl=impl)
        got = np.asarray(gctx.aggregate(jnp.asarray(feats), AGGR_MIN))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6,
                                   err_msg=impl)


def test_checkpoint_roundtrip(dataset, tmp_path):
    from roc_tpu.utils.checkpoint import (checkpoint_trainer,
                                          restore_trainer)
    model = build_gcn([dataset.in_dim, 16, dataset.num_classes],
                      dropout_rate=0.0)
    cfg = TrainConfig(epochs=10, verbose=False, weight_decay=1e-4)
    t1 = Trainer(model, dataset, cfg)
    t1.train(epochs=6)
    path = str(tmp_path / "ckpt.npz")
    checkpoint_trainer(t1, path)
    t1.train(epochs=4)

    t2 = Trainer(model, dataset, cfg)
    restore_trainer(t2, path)
    assert t2.epoch == 6
    t2.train(epochs=4)
    # identical continuation (same PRNG key restored)
    for k in t1.params:
        np.testing.assert_allclose(np.asarray(t1.params[k]),
                                   np.asarray(t2.params[k]),
                                   rtol=1e-6, atol=1e-7)


def test_checkpoint_shape_mismatch_rejected(dataset, tmp_path):
    # a mismatched model raises the DISTINCT CheckpointCorrupt error
    # (resilience PR: the strict config fingerprint catches it before
    # any leaf is even compared)
    from roc_tpu.utils.checkpoint import (CheckpointCorrupt,
                                          checkpoint_trainer,
                                          restore_trainer)
    cfg = TrainConfig(epochs=1, verbose=False)
    t1 = Trainer(build_gcn([dataset.in_dim, 16, dataset.num_classes]),
                 dataset, cfg)
    path = str(tmp_path / "ckpt.npz")
    checkpoint_trainer(t1, path)
    t2 = Trainer(build_gcn([dataset.in_dim, 32, dataset.num_classes]),
                 dataset, cfg)
    with pytest.raises(CheckpointCorrupt, match="mismatch"):
        restore_trainer(t2, path)


def test_cli_smoke(tmp_path):
    """End-to-end CLI run on a synthetic dataset (CPU)."""
    ckpt = str(tmp_path / "cli_ckpt.npz")
    res = subprocess.run(
        [sys.executable, "-m", "roc_tpu.train.cli", "--cpu",
         "-layers", "12-8-3", "-e", "6", "-lr", "0.01", "-dropout", "0.2",
         "-decay", "0.0001", "--impl", "ell", "--checkpoint", ckpt],
        capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stderr
    assert "[INFER]" in res.stdout
    assert "checkpoint saved" in res.stderr
    # resume from the checkpoint
    res2 = subprocess.run(
        [sys.executable, "-m", "roc_tpu.train.cli", "--cpu",
         "-layers", "12-8-3", "-e", "10", "--resume", ckpt],
        capture_output=True, text=True, timeout=300)
    assert res2.returncode == 0, res2.stderr
    assert "resumed" in res2.stderr


def test_cli_bad_layers():
    res = subprocess.run(
        [sys.executable, "-m", "roc_tpu.train.cli", "--cpu",
         "-layers", "602"],
        capture_output=True, text=True, timeout=120)
    assert res.returncode == 2
    assert "layers" in res.stderr


def test_ell_max_budget_segmenting_exact(dataset):
    """aggregate_ell_max under a tiny transient budget (forcing the
    lax.scan row-segmented path on every bucket) must be exact — the
    MAX path honors the same memory bound as the sum path."""
    from roc_tpu.core.ell import ell_from_graph
    from roc_tpu.ops.aggregate import aggregate_ell_max
    g = dataset.graph
    feats = dataset.features
    table = ell_from_graph(g.row_ptr, g.col_idx, g.num_nodes)
    idx = tuple(jnp.asarray(a[0]) for a in table.idx)
    pos = jnp.asarray(table.row_pos[0])
    full = jnp.concatenate(
        [jnp.asarray(feats), jnp.zeros((1, feats.shape[1]))], axis=0)
    want = np.asarray(aggregate_ell_max(full, idx, pos, g.num_nodes))
    got = np.asarray(aggregate_ell_max(full, idx, pos, g.num_nodes,
                                       budget_elems=64))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_explicit_segment_survives_max_model_resolution(dataset):
    """resolve_attention_impl must not override an explicitly requested
    aggr_impl='segment' for MAX/MIN models — _max_fwd has a real
    segment path (jax.ops.segment_max); only the chunked-sum impls are
    rerouted (ADVICE r3)."""
    from roc_tpu.train.trainer import resolve_attention_impl
    model = build_sage([dataset.in_dim, 8, dataset.num_classes],
                       dropout_rate=0.0, aggregator="pool")
    cfg = resolve_attention_impl(
        model, TrainConfig(aggr_impl="segment", verbose=False))
    assert cfg.aggr_impl == "segment"
    # the chunked-sum impls still reroute (they have no MAX form) and
    # the override is echoed even with verbose=False
    cfg = resolve_attention_impl(
        model, TrainConfig(aggr_impl="sectioned", verbose=False))
    assert cfg.aggr_impl == "ell"
    # and the segment path actually trains end to end
    t = Trainer(model, dataset,
                TrainConfig(aggr_impl="segment", verbose=False,
                            eval_every=1 << 30))
    assert t.config.aggr_impl == "segment"
    t.train(epochs=2)
