"""Fault-injection drill matrix: kill this run anywhere, any way, and
it finishes anyway.

Each drill runs the REAL CLI in a subprocess with one armed fault
(``--fault site:epoch``), restarts it the way a supervisor would —
re-invoking the identical command after a crash (SIGKILL leaves
rc=-9; preemption/stall exit the restartable code 75) — and asserts
the run completes to the target epoch with the *uninterrupted* run's
final loss (relative 1e-5; the drills train with dropout 0 so the
retry key perturbation cannot change the trajectory).

Sites: nan_grads, sigkill, kill_in_save (shard tmp write), the
checkpoint-v3 commit-protocol sites — kill_in_async_save (between
shard rename and manifest publish), shard_corrupt (bitflipped shard
under an intact manifest), saver_stall (wedged async saver thread) —
bitflip_checkpoint (corrupted commit record), sigterm (preemption;
the emergency save is FLUSHED before the restartable exit),
staging_io (streamed tier), stall_compile (watchdog deadline);
distributed variants at P in {2, 4} on the 8-virtual-device CPU rig,
including one elastic restore onto a DIFFERENT P and the 2-process
gloo DCN arms.  Kill-at-any-point coverage of the two-phase commit:
before (kill_in_save), during (kill_in_async_save), and after
(bitflip/shard_corrupt + SIGKILL) the manifest publish — every
restart resumes from the last COMMITTED checkpoint, zero torn
restores.

References are computed in-process (same code, same platform — CPU
runs are deterministic) and cached per config for the module.
"""

import json
import os
import signal
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# 6 epochs, eval/checkpoint cadence 2: checkpoints land at epochs 2/4/6
# and the final metrics record is epoch 5.  dropout 0.0 keeps the
# trajectory key-independent (see module docstring).
ELL = ["-e", "6", "-layers", "8-8-3", "-dropout", "0.0",
       "--eval-every", "2", "--impl", "ell", "--no-compile-cache",
       "--cpu"]
STREAM = ["-e", "6", "-layers", "16-16-4", "-dropout", "0.0",
          "--eval-every", "2", "--features", "host",
          "--no-compile-cache", "--cpu"]


def _run(tmp_path, args, env_extra=None, timeout=240):
    env = {k: v for k, v in os.environ.items() if k != "ROC_TPU_FAULT"}
    env["ROC_TPU_EVENTS"] = str(tmp_path / "events.jsonl")
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-m", "roc_tpu.train.cli"] + args,
        cwd=_REPO, env=env, capture_output=True, text=True,
        timeout=timeout)


def _recovery_args(tmp_path, base):
    return base + ["--recovery", "--checkpoint",
                   str(tmp_path / "ck"),
                   "--metrics", str(tmp_path / "m.jsonl")]


def _final_loss(path) -> float:
    recs = [json.loads(l) for l in open(path)]
    assert recs, f"no metrics in {path}"
    last = recs[-1]
    # the run reached the target: final eval lands on epoch 5
    assert last["epoch"] == 5.0, last
    return float(last["train_loss"])


def _resilience_events(tmp_path, kind=None):
    p = tmp_path / "events.jsonl"
    if not p.exists():
        return []
    es = [json.loads(l) for l in p.read_text().splitlines()
          if l.strip()]
    es = [e for e in es if e.get("cat") == "resilience"]
    return [e for e in es
            if kind is None or e.get("kind") == kind]


def _committed(tmp_path, epoch) -> bool:
    """A v3 checkpoint directory with a published MANIFEST.json —
    the ONLY thing restore_latest will look at."""
    return (tmp_path / f"ck.{epoch}" / "MANIFEST.json").exists()


def _assert_parity(got: float, want: float) -> None:
    assert abs(got - want) <= 1e-5 * max(1.0, abs(want)), \
        f"final loss {got} != uninterrupted {want}"


@pytest.fixture(scope="module", autouse=True)
def _shed_native_jit_state():
    """The in-process reference runs below compile several trainers
    into the pytest process; shed the accumulated native JIT state
    when the module ends (the PR-7 mitigation for the known
    jaxlib-0.4.x XLA:CPU corruption flake under per-process compile
    churn on this sandbox — test_flat_sum/test_mixed_precision carry
    the same fixture)."""
    yield
    import jax
    jax.clear_caches()


@pytest.fixture(scope="module")
def ref(tmp_path_factory):
    """Uninterrupted final loss per drill config, computed once
    in-process (cheap: shares the pytest process's jit caches)."""
    cache = {}

    def get(key, args):
        if key not in cache:
            from roc_tpu.train import cli
            d = tmp_path_factory.mktemp(f"ref_{key}")
            m = str(d / "m.jsonl")
            rc = cli.main(list(args) + ["--metrics", m])
            assert rc == 0
            cache[key] = _final_loss(m)
        return cache[key]

    return get


# ------------------------------------------------- single-process sites

def test_drill_nan_grads(tmp_path, ref):
    """NaN-poisoned params at epoch 3: the round boundary's finite
    guard refuses the checkpoint, recovery restores and replays —
    one invocation, same final loss."""
    args = _recovery_args(tmp_path, ELL) + ["--fault", "nan_grads:3"]
    r = _run(tmp_path, args)
    assert r.returncode == 0, r.stderr[-2000:]
    assert _resilience_events(tmp_path, "fault")
    assert _resilience_events(tmp_path, "recovery")
    _assert_parity(_final_loss(tmp_path / "m.jsonl"),
                   ref("ell", ELL))


def test_drill_sigkill_mid_epoch(tmp_path, ref):
    """SIGKILL at epoch 3; re-invoking the identical command resumes
    from the committed ck.2 and finishes with the uninterrupted
    loss."""
    base = _recovery_args(tmp_path, ELL)
    r1 = _run(tmp_path, base + ["--fault", "sigkill:3"])
    assert r1.returncode == -signal.SIGKILL, (r1.returncode, r1.stderr)
    assert _committed(tmp_path, 2)
    r2 = _run(tmp_path, base)
    assert r2.returncode == 0, r2.stderr[-2000:]
    _assert_parity(_final_loss(tmp_path / "m.jsonl"),
                   ref("ell", ELL))


def test_drill_kill_mid_checkpoint_write(tmp_path, ref):
    """kill -9 INSIDE the shard write (after the tmp write, before the
    atomic rename): the ``.npz.tmp`` must never be picked up by
    restore_latest, the directory stays uncommitted (no manifest),
    and the previous checkpoint restores cleanly — the 'before the
    commit' arm of kill-at-any-point."""
    base = _recovery_args(tmp_path, ELL)
    r1 = _run(tmp_path, base + ["--fault", "kill_in_save:4"])
    assert r1.returncode == -signal.SIGKILL, (r1.returncode, r1.stderr)
    tmps = list(tmp_path.glob("ck.4/*.npz.tmp"))
    assert tmps, "the killed writer should leave its .npz.tmp behind"
    assert not _committed(tmp_path, 4)
    assert _committed(tmp_path, 2)
    r2 = _run(tmp_path, base)
    assert r2.returncode == 0, r2.stderr[-2000:]
    _assert_parity(_final_loss(tmp_path / "m.jsonl"),
                   ref("ell", ELL))


def test_drill_kill_in_async_save(tmp_path, ref):
    """kill -9 in the two-phase-commit WINDOW (shard renamed into
    place, manifest not yet published) — the 'during the commit' arm:
    the shard-complete-but-uncommitted ck.4 must stay invisible and
    the restart resumes from the committed ck.2."""
    base = _recovery_args(tmp_path, ELL)
    r1 = _run(tmp_path, base + ["--fault", "kill_in_async_save:4"])
    assert r1.returncode == -signal.SIGKILL, (r1.returncode, r1.stderr)
    # the shard landed; the commit record did not
    assert list(tmp_path.glob("ck.4/shard_*.npz"))
    assert not _committed(tmp_path, 4)
    assert _committed(tmp_path, 2)
    r2 = _run(tmp_path, base)
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert not _resilience_events(tmp_path, "corrupt_fallback"), \
        "an uncommitted save must be invisible, not a corrupt restore"
    _assert_parity(_final_loss(tmp_path / "m.jsonl"),
                   ref("ell", ELL))


def test_drill_bitflip_checkpoint(tmp_path, ref):
    """The newest checkpoint's COMMIT RECORD corrupted (manifest
    bitflip, then SIGKILL): the restart must detect CheckpointCorrupt
    and fall back to the previous checkpoint instead of training on
    garbage — the 'after the commit' arm."""
    base = _recovery_args(tmp_path, ELL)
    r1 = _run(tmp_path, base + ["--fault", "bitflip_checkpoint:4"])
    assert r1.returncode == -signal.SIGKILL, (r1.returncode, r1.stderr)
    assert _committed(tmp_path, 4)  # committed, but corrupt on disk
    r2 = _run(tmp_path, base)
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert _resilience_events(tmp_path, "corrupt_fallback")
    _assert_parity(_final_loss(tmp_path / "m.jsonl"),
                   ref("ell", ELL))


def test_drill_shard_corrupt(tmp_path, ref):
    """One byte of a committed checkpoint's SHARD file flipped (the
    manifest stays intact, then SIGKILL): the restore scan's
    manifest-vs-shard CRC validation must reject the candidate before
    selection and fall back to the previous checkpoint."""
    base = _recovery_args(tmp_path, ELL)
    r1 = _run(tmp_path, base + ["--fault", "shard_corrupt:4"])
    assert r1.returncode == -signal.SIGKILL, (r1.returncode, r1.stderr)
    assert _committed(tmp_path, 4)
    r2 = _run(tmp_path, base)
    assert r2.returncode == 0, r2.stderr[-2000:]
    falls = _resilience_events(tmp_path, "corrupt_fallback")
    assert falls and any("CRC32" in e["msg"] or "manifest" in e["msg"]
                         for e in falls)
    _assert_parity(_final_loss(tmp_path / "m.jsonl"),
                   ref("ell", ELL))


@pytest.mark.slow
def test_drill_saver_stall(tmp_path, ref):
    """A wedged async saver thread: the flush deadline converts the
    silent hang into StallFailure and the process exits restartable
    (75) with the last COMMITTED checkpoint intact; the restart
    completes at the uninterrupted loss."""
    base = _recovery_args(tmp_path, ELL)
    r1 = _run(tmp_path, base + ["--fault", "saver_stall:4"],
              env_extra={"ROC_TPU_CKPT_FLUSH_TIMEOUT_S": "3"})
    assert r1.returncode == 75, (r1.returncode, r1.stderr[-2000:])
    assert _resilience_events(tmp_path, "fault")
    assert _resilience_events(tmp_path, "restartable_exit")
    assert _committed(tmp_path, 2)
    assert not _committed(tmp_path, 4)  # the wedged save never landed
    r2 = _run(tmp_path, base)
    assert r2.returncode == 0, r2.stderr[-2000:]
    _assert_parity(_final_loss(tmp_path / "m.jsonl"),
                   ref("ell", ELL))


def test_drill_sigterm_preemption(tmp_path, ref):
    """SIGTERM mid-run: the grace handler finishes the in-flight
    epoch step, writes an emergency checkpoint through the rotation
    (FLUSHED — committed before the exit code), and exits the
    distinct restartable code; the re-invoked command resumes from
    it."""
    base = _recovery_args(tmp_path, ELL)
    r1 = _run(tmp_path, base + ["--fault", "sigterm:3",
                                "--preempt-grace", "30"])
    assert r1.returncode == 75, (r1.returncode, r1.stderr[-2000:])
    assert _resilience_events(tmp_path, "preempt")
    # the emergency checkpoint covers the in-flight epoch (3 done -> 4)
    # and is COMMITTED (the preemption path flushes the async saver)
    assert _committed(tmp_path, 4)
    r2 = _run(tmp_path, base)
    assert r2.returncode == 0, r2.stderr[-2000:]
    _assert_parity(_final_loss(tmp_path / "m.jsonl"),
                   ref("ell", ELL))


@pytest.mark.slow
def test_drill_staging_io_error(tmp_path, ref):
    """Injected OSError from the StagingPool staging site (streamed
    tier): recovery restores the last checkpoint and retries in
    process — one invocation, same final loss as the uninterrupted
    streamed run."""
    args = _recovery_args(tmp_path, STREAM) + ["--fault",
                                               "staging_io:3"]
    r = _run(tmp_path, args)
    assert r.returncode == 0, r.stderr[-2000:]
    assert _resilience_events(tmp_path, "recovery")
    _assert_parity(_final_loss(tmp_path / "m.jsonl"),
                   ref("stream", STREAM))


@pytest.mark.slow
def test_drill_stalled_first_compile(tmp_path, ref):
    """A silent hang in the first-compile barrier: the watchdog
    deadline (ROC_TPU_STALL_TIMEOUT_S) converts it into StallFailure
    and the process exits restartable instead of burning a blank
    bench timeout; the restart completes."""
    base = _recovery_args(tmp_path, ELL)
    r1 = _run(tmp_path, base + ["--fault", "stall_compile:0"],
              env_extra={"ROC_TPU_STALL_TIMEOUT_S": "3",
                         "ROC_TPU_HEARTBEAT_S": "1"})
    assert r1.returncode == 75, (r1.returncode, r1.stderr[-2000:])
    exits = _resilience_events(tmp_path, "restartable_exit")
    assert exits and "stalled in first_compile" in exits[-1]["msg"]
    r2 = _run(tmp_path, base)
    assert r2.returncode == 0, r2.stderr[-2000:]
    _assert_parity(_final_loss(tmp_path / "m.jsonl"),
                   ref("ell", ELL))


# --------------------------------------- distributed sites (CPU rig)

def test_drill_distributed_sigkill_p2(tmp_path, ref):
    """SIGKILL mid-run at P=2: restart at P=2 resumes the replicated
    state and matches the uninterrupted distributed run."""
    base = _recovery_args(tmp_path, ELL + ["--parts", "2"])
    r1 = _run(tmp_path, base + ["--fault", "sigkill:3"])
    assert r1.returncode == -signal.SIGKILL, (r1.returncode, r1.stderr)
    r2 = _run(tmp_path, base)
    assert r2.returncode == 0, r2.stderr[-2000:]
    _assert_parity(_final_loss(tmp_path / "m.jsonl"),
                   ref("p2", ELL + ["--parts", "2"]))


def test_drill_kill_in_async_save_p2(tmp_path, ref):
    """The commit-window kill at P=2: SIGKILL between shard rename
    and manifest publish on the distributed trainer — the restart
    resumes from the committed ck.2 and matches the uninterrupted
    distributed run (with nan_grads_p4 and the DCN arms this covers
    kill-at-any-point at P in {2, 4})."""
    base = _recovery_args(tmp_path, ELL + ["--parts", "2"])
    r1 = _run(tmp_path, base + ["--fault", "kill_in_async_save:4"])
    assert r1.returncode == -signal.SIGKILL, (r1.returncode, r1.stderr)
    assert list(tmp_path.glob("ck.4/shard_*.npz"))
    assert not _committed(tmp_path, 4)
    assert _committed(tmp_path, 2)
    r2 = _run(tmp_path, base)
    assert r2.returncode == 0, r2.stderr[-2000:]
    _assert_parity(_final_loss(tmp_path / "m.jsonl"),
                   ref("p2", ELL + ["--parts", "2"]))


def test_drill_nan_grads_p4(tmp_path, ref):
    """NaN poisoning at P=4 recovers in process.  Full-batch training
    is partition-count-invariant to fp roundoff, so the P=2 reference
    bounds the P=4 run at the same 1e-5."""
    base = _recovery_args(tmp_path, ELL + ["--parts", "4"])
    r = _run(tmp_path, base + ["--fault", "nan_grads:3"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert _resilience_events(tmp_path, "recovery")
    _assert_parity(_final_loss(tmp_path / "m.jsonl"),
                   ref("p2", ELL + ["--parts", "2"]))


def _spawn_dcn_workers(tmp_path, fault=None, timeout=240):
    """Two REAL OS processes over gloo loopback (the timeline_worker
    spawn pattern), through the resilience stack; returns the
    completed Popen objects + outputs."""
    import socket
    worker = os.path.join(_REPO, "tests", "dcn_drill_worker.py")
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items()
           if k not in ("ROC_TPU_FAULT", "JAX_COORDINATOR_ADDRESS")}
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    argv = lambda i: ([sys.executable, worker, f"localhost:{port}",
                       "2", str(i), str(tmp_path)]
                      + ([fault] if fault else []))
    procs = [subprocess.Popen(argv(i), env=env,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for i in (0, 1)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    while len(outs) < len(procs):
        outs.append("<killed: peer-death collective wedge>")
    return procs, outs


@pytest.fixture(scope="module")
def dcn_ref():
    """Uninterrupted reference for the DCN drills: the IDENTICAL P=4
    workload in-process on the 8-virtual-device rig (the worker's
    exact dataset / partition / config, minus the fault and the
    process boundary).  Computed once for the module."""
    cache = {}

    def get():
        if "loss" not in cache:
            from roc_tpu.core.graph import synthetic_dataset
            from roc_tpu.core.partition import partition_graph
            from roc_tpu.models.gcn import build_gcn
            from roc_tpu.parallel import multihost as mh
            from roc_tpu.parallel.distributed import DistributedTrainer
            from roc_tpu.train.trainer import TrainConfig
            ds = synthetic_dataset(32 * 4, 6, in_dim=12, num_classes=3,
                                   seed=0)
            cfg = TrainConfig(epochs=6, verbose=False, aggr_impl="ell",
                              symmetric=True, dropout_rate=0.0,
                              eval_every=2)
            pg = partition_graph(ds.graph, 4, node_multiple=8,
                                 edge_multiple=cfg.chunk)
            tr = DistributedTrainer(
                build_gcn([12, 8, 3], dropout_rate=0.0),
                ds, 4, cfg, mesh=mh.make_parts_mesh(4), pg=pg)
            tr.train(6)
            cache["loss"] = float(tr.evaluate()["train_loss"])
        return cache["loss"]

    return get


@pytest.mark.slow
def test_drill_dcn_two_process_sigkill_recovery(tmp_path, dcn_ref):
    """The drill matrix's REAL multi-process DCN arm (advertised since
    PR 8): 2 gloo-loopback processes x 2 devices (P=4), a
    ``sigkill:3:1`` fault killing ONLY process 1 mid-run — the
    ``site:epoch:proc`` arm finally drilled across real process
    boundaries.  Re-spawning the pair resumes both processes from the
    shared rotation's newest committed checkpoint (process 0 wrote
    it, both restore) and the run finishes at the uninterrupted
    reference loss — recovery parity across a real DCN restart."""
    procs, outs = _spawn_dcn_workers(tmp_path, fault="sigkill:3:1")
    assert procs[1].returncode == -signal.SIGKILL, \
        (procs[1].returncode, outs[1][-2000:])
    # proc 0 loses its peer mid-collective: anything but success is
    # acceptable (wedge-killed, gloo error, restartable exit) — the
    # drill only requires that it did NOT claim completion
    assert "WORKER_OK" not in outs[0], outs[0][-2000:]
    # the checkpoint round before the fault landed on shared storage
    assert _committed(tmp_path, 2), sorted(os.listdir(tmp_path))
    # supervisor restart: identical command, no fault
    procs2, outs2 = _spawn_dcn_workers(tmp_path)
    for p, out in zip(procs2, outs2):
        assert p.returncode == 0, out[-3000:]
        assert "WORKER_OK" in out
    _assert_parity(_final_loss(tmp_path / "m_p0.jsonl"), dcn_ref())


@pytest.mark.slow
def test_drill_dcn_kill_in_commit(tmp_path, dcn_ref):
    """The 2-process gloo DCN variant of the commit-window kill
    (ISSUE 15 satellite): ``kill_in_async_save:4:0`` SIGKILLs ONLY
    process 0 — the manifest WRITER — after its shard rename and
    before the manifest publish.  ck.4 is left shard-complete but
    uncommitted on the shared rotation; the re-spawned pair must
    resume from the committed ck.2 (zero torn restores) and finish at
    the uninterrupted reference loss."""
    procs, outs = _spawn_dcn_workers(tmp_path,
                                     fault="kill_in_async_save:4:0")
    assert procs[0].returncode == -signal.SIGKILL, \
        (procs[0].returncode, outs[0][-2000:])
    assert "WORKER_OK" not in outs[1], outs[1][-2000:]
    assert list(tmp_path.glob("ck.4/shard_*.npz"))
    assert not _committed(tmp_path, 4)
    assert _committed(tmp_path, 2), sorted(os.listdir(tmp_path))
    procs2, outs2 = _spawn_dcn_workers(tmp_path)
    for p, out in zip(procs2, outs2):
        assert p.returncode == 0, out[-3000:]
        assert "WORKER_OK" in out
    _assert_parity(_final_loss(tmp_path / "m_p0.jsonl"), dcn_ref())


def test_drill_elastic_restart_p2_to_p4(tmp_path, ref):
    """Preempted at P=2, restarted at P=4: the checkpointed replicated
    params ride through while the partition (and its quantized plan
    shapes) is rebuilt — the elastic restore leaves a dated event and
    the final loss matches the uninterrupted run."""
    p2 = _recovery_args(tmp_path, ELL + ["--parts", "2"])
    p4 = _recovery_args(tmp_path, ELL + ["--parts", "4"])
    r1 = _run(tmp_path, p2 + ["--fault", "sigterm:3",
                              "--preempt-grace", "30"])
    assert r1.returncode == 75, (r1.returncode, r1.stderr[-2000:])
    r2 = _run(tmp_path, p4)
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert _resilience_events(tmp_path, "elastic_restore")
    _assert_parity(_final_loss(tmp_path / "m.jsonl"),
                   ref("p2", ELL + ["--parts", "2"]))
