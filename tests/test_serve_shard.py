"""Sharded serving tables (PR 20, ``serve/export.py --shards`` +
``serve/predictor.py`` ShardSlice + the router gather leg):

- the shard plan: edge-balanced contiguous ranges covering [0, V)
  exactly, one fleet-uniform padded slice shape (max owned rounded to
  NODE_MULTIPLE + halo + pad row), per-slice npz files on disk, and
  per-replica bytes strictly below the full table once V clears the
  halo;
- cold slice load: ``load_predictor(shard=k)`` rebuilds from ONE
  slice with program keys equal to the manifest's export-time shard
  warm set (the zero-new-compiles parity), and answers owned ids
  bit-exactly with no gather path;
- cross-shard parity: two in-process shard predictors wired
  gather_fn→read_rows serve GLOBAL ids bit-exactly vs the export
  predictor, fp32 and int8 (quantized gathers ship stored codes +
  per-row scales verbatim), including batches that straddle the
  boundary;
- the version pin: a gather answered from the wrong version is
  retried once, then refused typed (GatherError); the owner side
  refuses stale pins and foreign ids outright;
- ``add_edges`` across the boundary: the full-cache originator ships
  (rows, fp32 values) to every shard; owners apply exactly their
  rows, non-owners bump an epoch-only version, and the fleet stays
  bit-exact vs the mutated full table at lockstep version counters.
"""

import numpy as np
import pytest


def _dataset(V=2000, seed=0):
    from roc_tpu.core.graph import synthetic_dataset
    return synthetic_dataset(num_nodes=V, avg_degree=6, in_dim=24,
                             num_classes=5, seed=seed)


def _sgc_model():
    from roc_tpu.models.sgc import build_sgc
    return build_sgc([24, 5], k=2, dropout_rate=0.5)


def _config(**kw):
    from roc_tpu.train.trainer import TrainConfig
    kw.setdefault("verbose", False)
    kw.setdefault("symmetric", True)
    return TrainConfig(**kw)


@pytest.fixture(scope="module")
def rig():
    import jax
    from roc_tpu.train.trainer import Trainer
    ds = _dataset()
    tr = Trainer(_sgc_model(), ds, _config())
    tr.train(2)
    return ds, tr, np.asarray(jax.device_get(tr.predict()))


def _export_sharded(rig, out_dir, quant="off", n=2):
    from roc_tpu.serve.export import build_predictor, export_predictor
    ds, tr, _ = rig
    pred = build_predictor(_sgc_model(), ds, _config(),
                           params=tr.params, backend="precomputed",
                           quant=quant)
    manifest = export_predictor(
        pred, out_dir, dataset_meta={"V": ds.graph.num_nodes},
        shards=n)
    return pred, manifest


def _wire(a, b):
    """gather_fn → the other shard's read_rows, with the owner's
    typed refusal mapped to the wire client's sentinel answer."""
    from roc_tpu.serve.errors import GatherError

    def mk(owner, me):
        def gather(ids, version):
            try:
                return owner.read_rows(ids, version)
            except GatherError:
                return None, None, -1, me.quant
        return gather
    a.gather_fn = mk(b, a)
    b.gather_fn = mk(a, b)


def _load_pair(art, wire=True):
    from roc_tpu.serve.export import load_predictor
    s0 = load_predictor(art, shard=0)
    s1 = load_predictor(art, shard=1)
    if wire:
        _wire(s0, s1)
    return s0, s1


# ---------------------------------------------------------- the plan

def test_shard_manifest_plan_and_bytes(rig, tmp_path):
    import os

    from roc_tpu.core.partition import NODE_MULTIPLE
    from roc_tpu.serve.export import SHARD_FILE
    from roc_tpu.serve.quant import table_bytes
    ds = rig[0]
    V = ds.graph.num_nodes
    art = str(tmp_path / "art")
    pred, manifest = _export_sharded(rig, art, quant="int8")
    sb = manifest["shards"]
    assert sb["n"] == 2
    plan = [tuple(p) for p in sb["plan"]]
    # contiguous, exactly covering [0, V)
    assert plan[0][0] == 0 and plan[-1][1] == V
    for (_, a_hi), (b_lo, _) in zip(plan, plan[1:]):
        assert a_hi == b_lo
    # one fleet-uniform slice shape: max owned, node-aligned, + halo
    owned_max = max(hi - lo for lo, hi in plan)
    assert sb["rows_padded"] >= owned_max
    assert sb["rows_padded"] % NODE_MULTIPLE == 0
    assert sb["halo"] == max(manifest["buckets"])
    F = int(pred.cache.table.shape[1])
    shape = (sb["rows_padded"] + sb["halo"] + 1, F)
    assert sb["bytes_per_replica"] == table_bytes(shape, "int8")
    # the capacity point: a slice is strictly smaller than the table
    assert sb["bytes_per_replica"] < sb["bytes_full"]
    for k in range(2):
        assert os.path.exists(
            os.path.join(art, SHARD_FILE.format(k=k)))
    assert sb["program_keys"], "shard warm set must be recorded"


def test_cold_slice_load_parity_and_programs(rig, tmp_path):
    art = str(tmp_path / "art")
    pred, manifest = _export_sharded(rig, art)
    s0, s1 = _load_pair(art, wire=False)
    for s in (s0, s1):
        # zero-new-compiles: keys equal the export-time shard warm
        # set (load_predictor raises on mismatch; pin it here too)
        assert s.program_keys() == sorted(
            manifest["shards"]["program_keys"])
        lo, hi = s.shard
        own = np.arange(lo, min(hi, lo + 16), dtype=np.int32)
        assert np.array_equal(np.asarray(s.query(own)),
                              np.asarray(pred.query(own)))
        assert s.last_gather_ms is None, \
            "owned-only queries must not touch the gather leg"


# ------------------------------------------------- cross-shard parity

@pytest.mark.parametrize("quant", ["off", "int8"])
def test_cross_shard_gather_parity(rig, tmp_path, quant):
    ds = rig[0]
    V = ds.graph.num_nodes
    art = str(tmp_path / "art")
    pred, manifest = _export_sharded(rig, art, quant=quant)
    s0, s1 = _load_pair(art)
    b = manifest["shards"]["plan"][0][1]
    rng = np.random.RandomState(0)
    batches = [rng.randint(0, V, size=12).astype(np.int32)
               for _ in range(6)]
    batches.append(np.asarray([b - 1, b, b + 1, 0, V - 1],
                              dtype=np.int32))   # straddle the seam
    for ids in batches:
        want = np.asarray(pred.query(ids))
        for s in (s0, s1):
            got = np.asarray(s.query(ids))
            assert np.array_equal(got, want), (
                f"quant={quant} shard {s.shard} drifted by "
                f"{np.abs(got - want).max()}")
    # the straddling batch crossed at least one foreign fetch
    assert s0.last_gather_ms is not None


def test_gather_version_pin_retry_then_refusal(rig, tmp_path):
    from roc_tpu.serve.errors import GatherError
    art = str(tmp_path / "art")
    pred, manifest = _export_sharded(rig, art)
    s0, s1 = _load_pair(art, wire=False)
    foreign = np.asarray([s0.shard[1] + 1], dtype=np.int32)
    # no gather leg at all → typed refusal
    with pytest.raises(GatherError):
        s0.query(foreign)
    # stale once, fresh on the retry → served (the owner mid-publish)
    calls = {"n": 0}

    def flaky(ids, version):
        calls["n"] += 1
        if calls["n"] == 1:
            return None, None, -1, s0.quant
        return s1.read_rows(ids, version)
    s0.gather_fn = flaky
    want = np.asarray(pred.query(foreign))
    assert np.array_equal(np.asarray(s0.query(foreign)), want)
    assert calls["n"] == 2
    # stale twice → GatherError, never a mixed-version batch
    s0.gather_fn = lambda ids, version: (None, None, -1, s0.quant)
    with pytest.raises(GatherError):
        s0.query(foreign)


def test_read_rows_owner_refusals(rig, tmp_path):
    from roc_tpu.serve.errors import GatherError
    art = str(tmp_path / "art")
    _, manifest = _export_sharded(rig, art)
    s0, s1 = _load_pair(art, wire=False)
    lo1, hi1 = s1.shard
    owned = np.asarray([lo1], dtype=np.int64)
    live = s1.published().version
    # stale pin refused — the REQUESTER decides what to do
    with pytest.raises(GatherError):
        s1.read_rows(owned, live + 1)
    # foreign ids refused — a gather never silently crosses owners
    with pytest.raises(GatherError):
        s1.read_rows(np.asarray([lo1 - 1]), live)
    vals, scales, ver, qmode = s1.read_rows(owned, live)
    assert ver == live and qmode == "off" and scales is None
    assert vals.shape[0] == 1


# -------------------------------------------- add_edges invalidation

def test_add_edges_invalidation_crosses_shard_boundary(rig, tmp_path):
    """The sharded half of the invalidation fan-out: the originator
    (full PropagationCache) recomputes the k-hop rows centrally and
    ships (rows, values) to every shard.  An edge appended ACROSS the
    boundary must refresh owned rows on both sides, keep the fleet
    bit-exact vs the mutated full table, and advance every shard's
    version in lockstep (epoch-only on shards that own none)."""
    ds = rig[0]
    art = str(tmp_path / "art")
    pred, manifest = _export_sharded(rig, art)
    s0, s1 = _load_pair(art)
    b = manifest["shards"]["plan"][0][1]
    v0 = (s0.published().version, s1.published().version)
    # an edge across the seam: src owned by shard 0, dst by shard 1
    src = np.asarray([b - 2], dtype=np.int32)
    dst = np.asarray([b + 2], dtype=np.int32)
    with pred._pub_lock:
        rows = pred.cache.add_edges(src, dst)
        version = pred._publish_rows_locked(rows)
    pred._emit_publish(version, rows)
    assert rows.size > 0
    values = np.asarray(pred.cache.table[rows], dtype=np.float32)
    applied = [s.apply_refresh(rows, values) for s in (s0, s1)]
    # the k-hop set of a seam edge lands rows on BOTH owners here
    assert applied[0] > 0 and applied[1] > 0
    assert sum(applied) == rows.size, "each row on exactly one owner"
    # lockstep version counters (the pinnable-mid-rollout property)
    assert s0.published().version == v0[0] + 1
    assert s1.published().version == v0[1] + 1
    ids = np.unique(np.concatenate(
        [rows[:8], np.asarray([b - 1, b, 0], dtype=np.int64)]
    )).astype(np.int32)
    want = np.asarray(pred.query(ids))
    for s in (s0, s1):
        assert np.array_equal(np.asarray(s.query(ids)), want)


def test_add_edges_epoch_only_bump_off_owner(rig, tmp_path):
    """Rows entirely inside shard 0: shard 1 applies nothing but its
    version still advances — fleet-comparable counters are what keep
    a cross-shard gather pinnable right after a refresh."""
    art = str(tmp_path / "art")
    pred, manifest = _export_sharded(rig, art)
    s0, s1 = _load_pair(art)
    rows = np.arange(4, dtype=np.int64)          # owned by shard 0
    values = np.asarray(pred.cache.table[rows], dtype=np.float32)
    v1 = s1.published().version
    assert s1.apply_refresh(rows, values) == 0
    assert s1.published().version == v1 + 1
    assert s0.apply_refresh(rows, values) == rows.size
    # and the gather leg still pins bit-exact across the new versions
    want = np.asarray(pred.query(rows.astype(np.int32)))
    assert np.array_equal(
        np.asarray(s1.query(rows.astype(np.int32))), want)


def test_refresh_guards_are_typed(rig, tmp_path):
    """The two halves refuse each other's refresh API: sharded
    predictors have no full host cache (refresh_rows), full-table
    ones never see the fan-out (apply_refresh)."""
    art = str(tmp_path / "art")
    pred, _ = _export_sharded(rig, art)
    s0, _ = _load_pair(art)
    with pytest.raises(NotImplementedError):
        s0.refresh_rows(np.arange(2))
    with pytest.raises(NotImplementedError):
        pred.apply_refresh(np.arange(2), np.zeros((2, 24), np.float32))
