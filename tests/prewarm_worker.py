"""Worker process for the prewarm correctness tests (ISSUE 7).

Runs ONE live rig lifecycle (train 1 epoch + evaluate + predict) in a
fresh process against a persistent compile cache a previous prewarm
process populated.  The parent asserts, from the events artifact and
the cache directory, that the warm process compiled ZERO new step
programs: its ``compile`` events' program_key set equals the auditor's
enumeration, and no new step-program entry appeared in the cache.

Usage: python prewarm_worker.py <rig_name>
Env:   ROC_TPU_CACHE_DIR (cache), ROC_TPU_EVENTS (events JSONL),
       ROC_TPU_CACHE_MIN_SECS=0 (persist everything).
"""

import sys


def main() -> None:
    name = sys.argv[1]
    from roc_tpu.analysis import force_cpu_rig
    force_cpu_rig()

    from roc_tpu.utils.compile_cache import enable_compile_cache
    d = enable_compile_cache()   # dir + min-secs from env
    assert d, "cache dir must be usable in the worker"

    from roc_tpu.analysis.programspace import (build_rig_dataset,
                                               build_rig_trainer,
                                               rig_configs)
    spec = rig_configs()[name]
    tr = build_rig_trainer(spec, build_rig_dataset())
    tr.train(1)
    m = tr.evaluate()
    logits = tr.predict()
    assert logits.shape[0] == 256, logits.shape
    print(f"WORKER_OK loss={m['train_loss']:.4f}", flush=True)


if __name__ == "__main__":
    main()
