"""Block-dense MXU aggregation (ops/blockdense.py): plan + kernel
correctness against the segment-sum reference, occupancy accounting,
and the residual split's exactness."""

import numpy as np
import pytest

import jax.numpy as jnp

from roc_tpu.core.graph import planted_community_csr, random_csr
from roc_tpu.ops.aggregate import aggregate_segment
from roc_tpu.ops.blockdense import (BLOCK, aggregate_block_dense,
                                    plan_blocks)


def _reference(g, x):
    deg = np.diff(g.row_ptr)
    dst = np.repeat(np.arange(g.num_nodes, dtype=np.int64), deg)
    src, dstj = jnp.asarray(g.col_idx), jnp.asarray(dst)
    xp = jnp.concatenate([x, jnp.zeros((1, x.shape[1]), x.dtype)])
    return np.asarray(aggregate_segment(xp, src, dstj, g.num_nodes))


def _dense_plus_residual(g, x, plan):
    out = np.asarray(aggregate_block_dense(
        x, jnp.asarray(plan.a_blocks), jnp.asarray(plan.src_blk),
        jnp.asarray(plan.dst_blk), g.num_nodes, plan.vpad,
        chunk_blocks=4))
    # residual through the plain segment path
    res_deg = np.diff(plan.res_row_ptr)
    rdst = np.repeat(np.arange(g.num_nodes, dtype=np.int64), res_deg)
    if rdst.size:
        xp = jnp.concatenate([x, jnp.zeros((1, x.shape[1]), x.dtype)])
        out = out + np.asarray(aggregate_segment(
            xp, jnp.asarray(plan.res_col), jnp.asarray(rdst),
            g.num_nodes))
    return out


@pytest.mark.parametrize("min_fill", [1, 8, 10**9])
def test_block_dense_plus_residual_matches_reference(min_fill):
    """dense tiles + residual CSR == the plain segment sum, at every
    split point (all-dense, mixed, all-residual)."""
    g = planted_community_csr(500, 6000, community_rows=BLOCK,
                              shuffle=False, seed=3)
    plan = plan_blocks(g.row_ptr, g.col_idx, g.num_nodes,
                       min_fill=min_fill)
    assert plan.dense_edges + plan.res_col.shape[0] == g.num_edges
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(g.num_nodes, 24).astype(np.float32))
    got = _dense_plus_residual(g, x, plan)
    np.testing.assert_allclose(got, _reference(g, x), rtol=1e-4,
                               atol=1e-4)


def test_plan_occupancy_reflects_structure():
    """Oracle-ordered community graph concentrates edges into few
    blocks; uniform random at the same V/E does not — the stat that
    decides whether the MXU path can win."""
    V, E = 2048, 60_000
    comm = planted_community_csr(V, E, community_rows=512,
                                 intra_frac=0.9, shuffle=False, seed=1)
    unif = random_csr(V, E, seed=1)
    po = plan_blocks(comm.row_ptr, comm.col_idx, V, min_fill=64)
    pu = plan_blocks(unif.row_ptr, unif.col_idx, V, min_fill=64)
    occ_o, occ_u = po.occupancy(), pu.occupancy()
    assert occ_o["dense_frac"] > 0.5
    # community order CONCENTRATES: fewer blocks, much higher fill
    assert occ_o["mean_fill"] > 2 * occ_u["mean_fill"]
    assert occ_o["n_blocks"] < occ_u["n_blocks"]
    # at large V a uniform graph scatters below any useful fill
    # (E * 128^2 / V^2 ~ 4 edges/block here)
    big = random_csr(20_000, 100_000, seed=2)
    pb = plan_blocks(big.row_ptr, big.col_idx, 20_000, min_fill=64)
    assert pb.occupancy()["dense_frac"] < 0.05


def test_duplicate_saturation_stays_exact():
    """Edges past uint8 multiplicity overflow to the residual CSR —
    total semantics stay exact."""
    # 400 copies of the same edge (0 <- 1) + a spread of others
    row_ptr = np.array([0, 400, 401, 402], dtype=np.int64)
    col_idx = np.array([1] * 400 + [2, 0], dtype=np.int64)
    from roc_tpu.core.graph import Graph
    g = Graph(row_ptr=row_ptr, col_idx=col_idx.astype(np.int32))
    plan = plan_blocks(g.row_ptr, g.col_idx, g.num_nodes, min_fill=1)
    assert plan.res_col.shape[0] == 400 - 255  # saturated tail
    x = jnp.asarray(np.eye(3, 5, dtype=np.float32))
    got = _dense_plus_residual(g, x, plan)
    np.testing.assert_allclose(got, _reference(g, x), rtol=1e-5)


def test_empty_dense_plan():
    g = random_csr(300, 900, seed=0)
    plan = plan_blocks(g.row_ptr, g.col_idx, g.num_nodes,
                       min_fill=10**9)
    assert plan.n_blocks == 0
    assert plan.res_col.shape[0] == g.num_edges


def test_a_budget_keeps_densest_blocks():
    """The A-table byte budget keeps the DENSEST qualifying blocks and
    exactness survives (the dropped blocks fall to the residual)."""
    g = planted_community_csr(600, 9000, community_rows=BLOCK,
                              shuffle=False, seed=5)
    full = plan_blocks(g.row_ptr, g.col_idx, g.num_nodes, min_fill=1,
                       a_budget_bytes=None)
    budget = 2 * BLOCK * BLOCK  # room for exactly two blocks
    capped = plan_blocks(g.row_ptr, g.col_idx, g.num_nodes, min_fill=1,
                         a_budget_bytes=budget)
    assert capped.n_blocks == 2 < full.n_blocks
    # the two kept blocks are the densest ones
    per_block_full = full.a_blocks.reshape(full.n_blocks, -1).sum(1)
    kept = np.sort(capped.a_blocks.reshape(2, -1).sum(1))
    assert (kept == np.sort(per_block_full)[-2:]).all()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(g.num_nodes, 8).astype(np.float32))
    np.testing.assert_allclose(_dense_plus_residual(g, x, capped),
                               _reference(g, x), rtol=1e-4, atol=1e-4)
