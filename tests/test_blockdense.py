"""Block-dense MXU aggregation (ops/blockdense.py): plan + kernel
correctness against the segment-sum reference, occupancy accounting,
and the residual split's exactness."""

import numpy as np
import pytest

import jax.numpy as jnp

from roc_tpu.core.graph import planted_community_csr, random_csr
from roc_tpu.ops.aggregate import aggregate_segment
from roc_tpu.ops.blockdense import (BLOCK, aggregate_block_dense,
                                    plan_blocks)


def _reference(g, x):
    deg = np.diff(g.row_ptr)
    dst = np.repeat(np.arange(g.num_nodes, dtype=np.int64), deg)
    src, dstj = jnp.asarray(g.col_idx), jnp.asarray(dst)
    xp = jnp.concatenate([x, jnp.zeros((1, x.shape[1]), x.dtype)])
    return np.asarray(aggregate_segment(xp, src, dstj, g.num_nodes))


def _dense_plus_residual(g, x, plan):
    out = np.asarray(aggregate_block_dense(
        x, jnp.asarray(plan.a_blocks), jnp.asarray(plan.src_blk),
        jnp.asarray(plan.dst_blk), g.num_nodes, plan.vpad,
        chunk_blocks=4))
    # residual through the plain segment path
    res_deg = np.diff(plan.res_row_ptr)
    rdst = np.repeat(np.arange(g.num_nodes, dtype=np.int64), res_deg)
    if rdst.size:
        xp = jnp.concatenate([x, jnp.zeros((1, x.shape[1]), x.dtype)])
        out = out + np.asarray(aggregate_segment(
            xp, jnp.asarray(plan.res_col), jnp.asarray(rdst),
            g.num_nodes))
    return out


@pytest.mark.parametrize("min_fill", [1, 8, 10**9])
def test_block_dense_plus_residual_matches_reference(min_fill):
    """dense tiles + residual CSR == the plain segment sum, at every
    split point (all-dense, mixed, all-residual)."""
    g = planted_community_csr(500, 6000, community_rows=BLOCK,
                              shuffle=False, seed=3)
    plan = plan_blocks(g.row_ptr, g.col_idx, g.num_nodes,
                       min_fill=min_fill)
    assert plan.dense_edges + plan.res_col.shape[0] == g.num_edges
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(g.num_nodes, 24).astype(np.float32))
    got = _dense_plus_residual(g, x, plan)
    np.testing.assert_allclose(got, _reference(g, x), rtol=1e-4,
                               atol=1e-4)


@pytest.mark.parametrize("group", [2, 4, 7])
def test_grouped_reduction_matches_ungrouped(group):
    """pad_plan_groups + group>1 kernel == the group=1 result exactly
    in structure (same dense/residual split) and numerically (the
    padding blocks are zero-A): the output-RMW-traffic optimization
    must not change a single value."""
    from roc_tpu.ops.blockdense import pad_plan_groups
    g = planted_community_csr(500, 6000, community_rows=BLOCK,
                              shuffle=False, seed=3)
    plan = plan_blocks(g.row_ptr, g.col_idx, g.num_nodes, min_fill=4)
    assert plan.n_blocks > 2
    padded = pad_plan_groups(plan, group)
    # group alignment, per-dst-tile padding only
    assert padded.n_blocks % group == 0
    assert padded.n_blocks < plan.n_blocks + group * len(
        np.unique(plan.dst_blk))
    # padding blocks are inert: zero A
    assert padded.a_blocks.sum() == plan.a_blocks.sum()
    # every group shares one dst tile
    dgrp = padded.dst_blk.reshape(-1, group)
    assert (dgrp == dgrp[:, :1]).all()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(g.num_nodes, 24).astype(np.float32))
    base = np.asarray(aggregate_block_dense(
        x, jnp.asarray(plan.a_blocks), jnp.asarray(plan.src_blk),
        jnp.asarray(plan.dst_blk), g.num_nodes, plan.vpad,
        chunk_blocks=4))
    got = np.asarray(aggregate_block_dense(
        x, jnp.asarray(padded.a_blocks), jnp.asarray(padded.src_blk),
        jnp.asarray(padded.dst_blk), g.num_nodes, padded.vpad,
        chunk_blocks=4 * group, group=group))
    np.testing.assert_allclose(got, base, rtol=1e-5, atol=1e-5)
    # an unpadded plan with group>1 must fail fast, not mis-aggregate
    if plan.n_blocks % group:
        with pytest.raises(ValueError, match="pad_plan_groups"):
            aggregate_block_dense(
                x, jnp.asarray(plan.a_blocks),
                jnp.asarray(plan.src_blk), jnp.asarray(plan.dst_blk),
                g.num_nodes, plan.vpad, group=group)


def test_trainer_bdense_group_matches_segment():
    """TrainConfig.bdense_group end-to-end through the Trainer:
    grouped bdense == ungrouped == segment (same trained params), with
    a real dense+residual split and real group padding exercised."""
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import TrainConfig, Trainer

    ds = synthetic_dataset(300, 9, in_dim=12, num_classes=3, seed=4)
    kw = dict(learning_rate=0.05, epochs=5, eval_every=1 << 30,
              verbose=False, dropout_rate=0.0, symmetric=True)
    trainers = {}
    for label, impl, grp in (("segment", "segment", 1),
                             ("bdense", "bdense", 1),
                             ("bdense_g4", "bdense", 4)):
        tr = Trainer(build_gcn([12, 8, 3], dropout_rate=0.0), ds,
                     TrainConfig(aggr_impl=impl, bdense_min_fill=40,
                                 bdense_group=grp, **kw))
        tr.train()
        trainers[label] = tr
    tg = trainers["bdense_g4"]
    assert tg.gctx.bd_group == 4
    assert tg.gctx.bd_a.shape[0] % 4 == 0
    # padding actually happened (the fixture's tile widths are odd)
    assert tg.gctx.bd_a.shape[0] > trainers["bdense"].gctx.bd_a.shape[0]
    for ref in ("bdense", "segment"):
        for k in trainers[ref].params:
            np.testing.assert_allclose(
                np.asarray(tg.params[k]),
                np.asarray(trainers[ref].params[k]),
                rtol=2e-4, atol=2e-4)


def test_plan_occupancy_reflects_structure():
    """Oracle-ordered community graph concentrates edges into few
    blocks; uniform random at the same V/E does not — the stat that
    decides whether the MXU path can win."""
    V, E = 2048, 60_000
    comm = planted_community_csr(V, E, community_rows=512,
                                 intra_frac=0.9, shuffle=False, seed=1)
    unif = random_csr(V, E, seed=1)
    po = plan_blocks(comm.row_ptr, comm.col_idx, V, min_fill=64)
    pu = plan_blocks(unif.row_ptr, unif.col_idx, V, min_fill=64)
    occ_o, occ_u = po.occupancy(), pu.occupancy()
    assert occ_o["dense_frac"] > 0.5
    # community order CONCENTRATES: fewer blocks, much higher fill
    assert occ_o["mean_fill"] > 2 * occ_u["mean_fill"]
    assert occ_o["n_blocks"] < occ_u["n_blocks"]
    # at large V a uniform graph scatters below any useful fill
    # (E * 128^2 / V^2 ~ 4 edges/block here)
    big = random_csr(20_000, 100_000, seed=2)
    pb = plan_blocks(big.row_ptr, big.col_idx, 20_000, min_fill=64)
    assert pb.occupancy()["dense_frac"] < 0.05


def test_duplicate_saturation_stays_exact():
    """Edges past uint8 multiplicity overflow to the residual CSR —
    total semantics stay exact."""
    # 400 copies of the same edge (0 <- 1) + a spread of others
    row_ptr = np.array([0, 400, 401, 402], dtype=np.int64)
    col_idx = np.array([1] * 400 + [2, 0], dtype=np.int64)
    from roc_tpu.core.graph import Graph
    g = Graph(row_ptr=row_ptr, col_idx=col_idx.astype(np.int32))
    plan = plan_blocks(g.row_ptr, g.col_idx, g.num_nodes, min_fill=1)
    assert plan.res_col.shape[0] == 400 - 255  # saturated tail
    x = jnp.asarray(np.eye(3, 5, dtype=np.float32))
    got = _dense_plus_residual(g, x, plan)
    np.testing.assert_allclose(got, _reference(g, x), rtol=1e-5)


def test_numpy_fallback_rejects_out_of_range_cols():
    """The numpy plan path must hard-error on sources outside the
    declared tile space exactly like the native kErrValue path — a
    clamped gather would aggregate silently wrong."""
    import roc_tpu.native as native_mod
    ptr = np.array([0, 1, 2], dtype=np.int64)
    col = np.array([0, 300], dtype=np.int32)
    avail = native_mod.available
    native_mod.available = lambda: False
    try:
        with pytest.raises(ValueError, match="out of range"):
            plan_blocks(ptr, col, 2, min_fill=1, num_cols=200)
        # in-range passes
        plan_blocks(ptr, col, 2, min_fill=1, num_cols=400)
    finally:
        native_mod.available = avail


def test_empty_dense_plan():
    g = random_csr(300, 900, seed=0)
    plan = plan_blocks(g.row_ptr, g.col_idx, g.num_nodes,
                       min_fill=10**9)
    assert plan.n_blocks == 0
    assert plan.res_col.shape[0] == g.num_edges


def test_a_budget_keeps_densest_blocks():
    """The A-table byte budget keeps the DENSEST qualifying blocks and
    exactness survives (the dropped blocks fall to the residual)."""
    g = planted_community_csr(600, 9000, community_rows=BLOCK,
                              shuffle=False, seed=5)
    full = plan_blocks(g.row_ptr, g.col_idx, g.num_nodes, min_fill=1,
                       a_budget_bytes=None)
    budget = 2 * BLOCK * BLOCK  # room for exactly two blocks
    capped = plan_blocks(g.row_ptr, g.col_idx, g.num_nodes, min_fill=1,
                         a_budget_bytes=budget)
    assert capped.n_blocks == 2 < full.n_blocks
    # the two kept blocks are the densest ones
    per_block_full = full.a_blocks.reshape(full.n_blocks, -1).sum(1)
    kept = np.sort(capped.a_blocks.reshape(2, -1).sum(1))
    assert (kept == np.sort(per_block_full)[-2:]).all()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(g.num_nodes, 8).astype(np.float32))
    np.testing.assert_allclose(_dense_plus_residual(g, x, capped),
                               _reference(g, x), rtol=1e-4, atol=1e-4)


def test_u4_packed_a_matches_uint8():
    """pack_a_u4 halves the A bytes and the kernel's in-register
    unpack reproduces the uint8 result exactly — grouped and
    ungrouped; plans with multiplicities past 4 bits must refuse to
    pack rather than saturate."""
    from roc_tpu.ops.blockdense import pack_a_u4
    g = planted_community_csr(500, 6000, community_rows=BLOCK,
                              shuffle=False, seed=3)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(g.num_nodes, 24).astype(np.float32))
    for group in (1, 4):
        plan = plan_blocks(g.row_ptr, g.col_idx, g.num_nodes,
                           min_fill=4, group=group)
        assert plan.a_blocks.max() <= 15, "fixture must be packable"
        packed = pack_a_u4(plan)
        assert packed is not None
        assert packed.a_blocks.nbytes * 2 == plan.a_blocks.nbytes
        assert packed.occupancy()["a_bytes"] * 2 == \
            plan.occupancy()["a_bytes"]
        base = np.asarray(aggregate_block_dense(
            x, jnp.asarray(plan.a_blocks), jnp.asarray(plan.src_blk),
            jnp.asarray(plan.dst_blk), g.num_nodes, plan.vpad,
            chunk_blocks=4 * group, group=group))
        got = np.asarray(aggregate_block_dense(
            x, jnp.asarray(packed.a_blocks),
            jnp.asarray(packed.src_blk), jnp.asarray(packed.dst_blk),
            g.num_nodes, packed.vpad,
            chunk_blocks=4 * group, group=group))
        np.testing.assert_array_equal(got, base)
    # >15 multiplicity: refuse to pack (the 400-duplicate fixture)
    from roc_tpu.core.graph import Graph
    row_ptr = np.array([0, 400, 401, 402], dtype=np.int64)
    col_idx = np.array([1] * 400 + [2, 0], dtype=np.int32)
    gd = Graph(row_ptr=row_ptr, col_idx=col_idx)
    pd = plan_blocks(gd.row_ptr, gd.col_idx, gd.num_nodes, min_fill=1)
    assert pd.a_blocks.max() > 15
    assert pack_a_u4(pd) is None


def test_plan_blocks_packed_budget_policy():
    """plan_blocks_packed spends the stated budget in DEVICE bytes:
    a packable graph keeps ~2x the blocks a uint8 plan could (packed
    bytes still <= budget); an unpackable graph re-plans to the uint8
    cap rather than exceeding it."""
    from roc_tpu.ops.blockdense import plan_blocks_packed
    g = planted_community_csr(600, 9000, community_rows=BLOCK,
                              shuffle=False, seed=5)
    budget = 2 * BLOCK * BLOCK  # two uint8 blocks / four packed
    p8 = plan_blocks(g.row_ptr, g.col_idx, g.num_nodes, min_fill=1,
                     a_budget_bytes=budget)
    pp = plan_blocks_packed(g.row_ptr, g.col_idx, g.num_nodes,
                            min_fill=1, a_budget_bytes=budget)
    assert pp.a_blocks.shape[-1] == BLOCK // 2, "fixture packable"
    assert pp.a_blocks.nbytes <= budget
    assert pp.n_blocks == 2 * p8.n_blocks
    # unpackable: the 400-duplicate fixture must land at uint8 <= cap
    from roc_tpu.core.graph import Graph
    row_ptr = np.array([0, 400, 401, 402], dtype=np.int64)
    col_idx = np.array([1] * 400 + [2, 0], dtype=np.int32)
    gd = Graph(row_ptr=row_ptr, col_idx=col_idx)
    pu = plan_blocks_packed(gd.row_ptr, gd.col_idx, gd.num_nodes,
                            min_fill=1,
                            a_budget_bytes=BLOCK * BLOCK)
    assert pu.a_blocks.shape[-1] == BLOCK  # uint8
    assert pu.a_blocks.nbytes <= BLOCK * BLOCK


def test_probe_dense_frac_matches_plan():
    """The census-only auto probe must agree with the full plan's
    dense_frac (same census + same selection, minus the A fill)."""
    import roc_tpu.native as native_mod
    if not native_mod.available():
        pytest.skip("probe is native-gated")
    from roc_tpu.ops.blockdense import probe_dense_frac
    comm = planted_community_csr(2048, 60_000, community_rows=512,
                                 intra_frac=0.9, shuffle=False, seed=1)
    unif = random_csr(20_000, 100_000, seed=2)
    for g, v in ((comm, 2048), (unif, 20_000)):
        frac = probe_dense_frac(g.row_ptr, g.col_idx, v, min_fill=64)
        plan = plan_blocks(g.row_ptr, g.col_idx, v, min_fill=64)
        assert frac == pytest.approx(plan.occupancy()["dense_frac"],
                                     abs=1e-3)
    # grouped probe respects the padded-budget selection
    budget = 4 * BLOCK * BLOCK
    frac_b = probe_dense_frac(comm.row_ptr, comm.col_idx, 2048,
                              min_fill=1, a_budget_bytes=budget,
                              group=4)
    plan_b = plan_blocks(comm.row_ptr, comm.col_idx, 2048, min_fill=1,
                         a_budget_bytes=budget, group=4)
    assert frac_b == pytest.approx(plan_b.occupancy()["dense_frac"],
                                   abs=1e-3)


def test_auto_impl_probes_structure(monkeypatch):
    """aggr_impl='auto' switches to bdense when the census finds
    enough dense-tile structure, and stays sectioned on a uniform
    graph — the flagship path must be reachable without naming it."""
    import roc_tpu.native as native_mod
    if not native_mod.available():
        pytest.skip("probe is native-gated")
    from roc_tpu.core import ell as ell_mod
    from roc_tpu.core.graph import Dataset
    from roc_tpu.ops import blockdense as bd
    from roc_tpu.train.trainer import make_graph_context

    # shrink the gate sizes so the fixture stays test-sized; the
    # trainer reads both dynamically
    monkeypatch.setattr(bd, "BDENSE_AUTO_MIN_EDGES", 10_000)
    monkeypatch.setattr(ell_mod, "SECTIONED_BOUNDS_DEFAULT",
                        (1_000, 10**9), raising=False)
    monkeypatch.setattr(ell_mod, "sectioned_bounds",
                        lambda device_kind=None: (1_000, 10**9))

    def mk(g):
        rng = np.random.RandomState(0)
        return Dataset(graph=g,
                       features=rng.rand(g.num_nodes, 8).astype(
                           np.float32),
                       labels=np.zeros(g.num_nodes, np.int32),
                       mask=np.ones(g.num_nodes, np.int32),
                       num_classes=2, name="probe")

    comm = planted_community_csr(2048, 60_000, community_rows=512,
                                 intra_frac=0.9, shuffle=False, seed=1)
    gc = make_graph_context(mk(comm), "auto", bdense_min_fill=64)
    assert gc.aggr_impl == "bdense"
    assert gc.bd_a is not None
    unif = random_csr(20_000, 100_000, seed=2)
    gu = make_graph_context(mk(unif), "auto", bdense_min_fill=64)
    assert gu.aggr_impl == "sectioned"

    # the shared resolver: census returned on the bdense path is
    # byte-identical to a fresh plan's walk; multiprocess runs skip
    # the probe (per-host native availability must not desync SPMD)
    from roc_tpu.train.trainer import resolve_auto_impl_probed
    impl, census = resolve_auto_impl_probed(comm, bdense_min_fill=64)
    assert impl == "bdense" and census is not None
    p_census = plan_blocks(comm.row_ptr, comm.col_idx, 2048,
                           min_fill=64, census=census)
    p_fresh = plan_blocks(comm.row_ptr, comm.col_idx, 2048,
                          min_fill=64)
    np.testing.assert_array_equal(p_census.a_blocks, p_fresh.a_blocks)
    np.testing.assert_array_equal(p_census.res_col, p_fresh.res_col)
    impl_mp, cen_mp = resolve_auto_impl_probed(
        comm, bdense_min_fill=64, multiprocess=True)
    assert impl_mp == "sectioned" and cen_mp is None


def test_auto_probe_without_native_stays_sectioned(monkeypatch):
    """No librocio -> the probe declines (None) and 'auto' keeps the
    arithmetic resolution — never the minutes-long numpy census."""
    import roc_tpu.native as native_mod
    from roc_tpu.core import ell as ell_mod
    from roc_tpu.ops import blockdense as bd
    from roc_tpu.train.trainer import resolve_auto_impl_probed
    monkeypatch.setattr(native_mod, "available", lambda: False)
    monkeypatch.setattr(bd, "BDENSE_AUTO_MIN_EDGES", 10_000)
    monkeypatch.setattr(ell_mod, "sectioned_bounds",
                        lambda device_kind=None: (1_000, 10**9))
    comm = planted_community_csr(2048, 60_000, community_rows=512,
                                 intra_frac=0.9, shuffle=False, seed=1)
    impl, census = resolve_auto_impl_probed(comm, bdense_min_fill=64)
    assert impl == "sectioned" and census is None


def test_group_padding_respects_a_budget():
    """With group>1 the budget caps the PADDED table: the selection
    must account for alignment blocks up front, never exceed the byte
    cap after padding, and exactness must survive (dropped blocks fall
    to the residual)."""
    g = planted_community_csr(600, 9000, community_rows=BLOCK,
                              shuffle=False, seed=5)
    budget = 4 * BLOCK * BLOCK  # room for four PADDED blocks
    plan = plan_blocks(g.row_ptr, g.col_idx, g.num_nodes, min_fill=1,
                       a_budget_bytes=budget, group=4)
    assert plan.n_blocks * BLOCK * BLOCK <= budget
    assert plan.n_blocks % 4 == 0
    # group=1 at the same budget keeps 4 raw blocks; grouping must
    # not keep MORE raw blocks than that
    raw = plan.n_blocks - plan.pad_blocks
    assert 0 < raw <= 4
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(g.num_nodes, 8).astype(np.float32))
    out = np.asarray(aggregate_block_dense(
        x, jnp.asarray(plan.a_blocks), jnp.asarray(plan.src_blk),
        jnp.asarray(plan.dst_blk), g.num_nodes, plan.vpad,
        chunk_blocks=4, group=4))
    res_deg = np.diff(plan.res_row_ptr)
    rdst = np.repeat(np.arange(g.num_nodes, dtype=np.int64), res_deg)
    if rdst.size:
        xp = jnp.concatenate([x, jnp.zeros((1, x.shape[1]), x.dtype)])
        out = out + np.asarray(aggregate_segment(
            xp, jnp.asarray(plan.res_col), jnp.asarray(rdst),
            g.num_nodes))
    np.testing.assert_allclose(out, _reference(g, x), rtol=1e-4,
                               atol=1e-4)


def test_trainer_bdense_matches_segment():
    """aggr_impl='bdense' end-to-end through the Trainer: identical
    training trajectory to the segment reference.  bdense_min_fill=250
    forces a REAL dense+residual split (4 dense tiles, 718 residual
    edges on this fixture) so the trainer's sectioned-residual glue is
    exercised, not just the all-dense fast case."""
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import TrainConfig, Trainer

    ds = synthetic_dataset(300, 9, in_dim=12, num_classes=3, seed=4)
    kw = dict(learning_rate=0.05, epochs=5, eval_every=1 << 30,
              verbose=False, dropout_rate=0.0, symmetric=True)
    tb = Trainer(build_gcn([12, 8, 3], dropout_rate=0.0), ds,
                 TrainConfig(aggr_impl="bdense", bdense_min_fill=250,
                             **kw))
    # the plan actually split: dense tiles AND a sectioned residual
    assert tb.gctx.bd_a is not None and tb.gctx.bd_a.shape[0] > 0
    assert tb.gctx.sect_idx, "fixture must leave residual edges"
    ts = Trainer(build_gcn([12, 8, 3], dropout_rate=0.0), ds,
                 TrainConfig(aggr_impl="segment", **kw))
    tb.train()
    ts.train()
    for k in ts.params:
        np.testing.assert_allclose(np.asarray(tb.params[k]),
                                   np.asarray(ts.params[k]),
                                   rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(tb.evaluate()["train_loss"],
                               ts.evaluate()["train_loss"], rtol=1e-4)


def test_trainer_bdense_no_dense_tiles_falls_back():
    """A graph/order with no qualifying tile runs the pure sectioned
    residual (no zero-block kernel in the step) and still matches the
    segment reference."""
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import TrainConfig, Trainer

    ds = synthetic_dataset(300, 9, in_dim=12, num_classes=3, seed=4)
    kw = dict(learning_rate=0.05, epochs=3, eval_every=1 << 30,
              verbose=False, dropout_rate=0.0, symmetric=True)
    tb = Trainer(build_gcn([12, 8, 3], dropout_rate=0.0), ds,
                 TrainConfig(aggr_impl="bdense",
                             bdense_min_fill=10**9, **kw))
    assert tb.gctx.bd_a is None
    assert tb.gctx.sect_idx
    ts = Trainer(build_gcn([12, 8, 3], dropout_rate=0.0), ds,
                 TrainConfig(aggr_impl="segment", **kw))
    tb.train()
    ts.train()
    for k in ts.params:
        np.testing.assert_allclose(np.asarray(tb.params[k]),
                                   np.asarray(ts.params[k]),
                                   rtol=2e-4, atol=2e-4)


def test_trainer_bdense_mixed_precision_converges():
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import TrainConfig, Trainer

    ds = synthetic_dataset(300, 9, in_dim=12, num_classes=3, seed=4)
    tr = Trainer(build_gcn([12, 16, 3], dropout_rate=0.0), ds,
                 TrainConfig(aggr_impl="bdense", learning_rate=0.05,
                             epochs=60, eval_every=1 << 30,
                             verbose=False, symmetric=True,
                             compute_dtype=jnp.bfloat16))
    tr.train()
    m = tr.evaluate()
    assert np.isfinite(m["train_loss"])
    assert m["train_acc"] > 0.9


def test_bdense_distributed_matches_segment():
    """aggr_impl='bdense' through the DistributedTrainer (per-partition
    rectangular plans: local dst rows x gathered source coords): same
    training trajectory as the distributed segment reference, with a
    REAL dense+residual split on at least one partition."""
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.train.trainer import TrainConfig

    ds = synthetic_dataset(384, 9, in_dim=12, num_classes=3, seed=4)
    kw = dict(learning_rate=0.05, epochs=5, eval_every=1 << 30,
              verbose=False, dropout_rate=0.0, symmetric=True)
    tb = DistributedTrainer(build_gcn([12, 8, 3], dropout_rate=0.0),
                            ds, 4,
                            TrainConfig(aggr_impl="bdense",
                                        bdense_min_fill=64, **kw))
    # the per-part plans actually split: dense tiles AND residuals
    assert tb.data.bd_tabs, "fixture must yield dense tiles"
    dense_total = sum(o["dense_edges"] for o in tb.data.bd_occupancy)
    # a REAL residual remains (sect_idx alone is vacuous: the bdense
    # branch builds the stacked tables even for an all-dense plan)
    assert 0 < dense_total < ds.graph.num_edges, dense_total
    assert tb.data.sect_idx
    assert tb.data.bd_src_vpad >= 4 * tb.pg.part_nodes
    ts = DistributedTrainer(build_gcn([12, 8, 3], dropout_rate=0.0),
                            ds, 4, TrainConfig(aggr_impl="segment",
                                               **kw))
    tb.train()
    ts.train()
    for k in ts.params:
        np.testing.assert_allclose(np.asarray(tb.params[k]),
                                   np.asarray(ts.params[k]),
                                   rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(tb.evaluate()["train_loss"],
                               ts.evaluate()["train_loss"], rtol=1e-4)
    # predict rides the same tables
    np.testing.assert_allclose(tb.predict(), ts.predict(),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("group", [1, 4])
def test_bdense_distributed_matches_single_device(group):
    """1-vs-N invariance for the bdense path: the 4-part distributed
    run reproduces the single-device bdense trajectory — with and
    without the grouped output-tile reduction (whose per-part
    alignment + whole-group stacked tail padding is the subtle SPMD
    invariant)."""
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.train.trainer import TrainConfig, Trainer

    ds = synthetic_dataset(384, 9, in_dim=12, num_classes=3, seed=4)
    kw = dict(learning_rate=0.05, epochs=4, eval_every=1 << 30,
              verbose=False, dropout_rate=0.0, symmetric=True,
              bdense_group=group)
    td = DistributedTrainer(build_gcn([12, 8, 3], dropout_rate=0.0),
                            ds, 4,
                            TrainConfig(aggr_impl="bdense",
                                        bdense_min_fill=64, **kw))
    t1 = Trainer(build_gcn([12, 8, 3], dropout_rate=0.0), ds,
                 TrainConfig(aggr_impl="bdense", bdense_min_fill=64,
                             **kw))
    if group > 1:
        assert td.data.bd_group == group
        assert td.data.bd_tabs[0].shape[1] % group == 0
    td.train()
    t1.train()
    for k in t1.params:
        np.testing.assert_allclose(np.asarray(td.params[k]),
                                   np.asarray(t1.params[k]),
                                   rtol=2e-4, atol=2e-4)


def test_bdense_distributed_packs_with_zero_block_parts():
    """A packable graph where some partitions plan ZERO dense tiles
    must still stack the u4 table (a zero-block part's empty A packs
    to the uniform trailing width instead of forcing uint8 or
    crashing the stack) and train exactly."""
    from roc_tpu.core.graph import Dataset, from_edge_list
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.train.trainer import TrainConfig

    rng = np.random.RandomState(5)
    V = 256
    # one CONCENTRATED community (rows 0-63: ~1500 edges in a single
    # tile, far past min_fill) + SCATTERED edges over the rest (fill
    # per tile ~50, under min_fill): the edge-balanced partitioner
    # gives every part similar edge counts, but only the parts
    # holding community rows plan dense tiles
    dense_s = rng.randint(0, 64, 4000)
    dense_d = rng.randint(0, 64, 4000)
    scat_s = rng.randint(0, V, 300)
    scat_d = rng.randint(64, V, 300)
    src = np.concatenate([dense_s, scat_s, np.arange(V)])
    dst = np.concatenate([dense_d, scat_d, np.arange(V)])
    g = from_edge_list(src, dst, V)
    ds = Dataset(graph=g,
                 features=rng.rand(V, 8).astype(np.float32),
                 labels=rng.randint(0, 3, V).astype(np.int32),
                 mask=np.ones(V, np.int32), num_classes=3)
    kw = dict(verbose=False, eval_every=1 << 30, dropout_rate=0.0,
              symmetric=False, epochs=2, learning_rate=0.05,
              chunk=64)   # partition geometry the fixture's split
    td = DistributedTrainer(build_gcn([8, 8, 3], dropout_rate=0.0),
                            ds, 4,
                            TrainConfig(aggr_impl="bdense",
                                        bdense_min_fill=300, **kw))
    occ = td.data.bd_occupancy
    assert any(o["n_blocks"] == 0 for o in occ), \
        "fixture must leave some partition without dense tiles"
    assert any(o["n_blocks"] > 0 for o in occ)
    assert td.data.bd_tabs[0].shape[-1] == 64  # u4 despite empties
    ts = DistributedTrainer(build_gcn([8, 8, 3], dropout_rate=0.0),
                            ds, 4, TrainConfig(aggr_impl="segment",
                                               **kw))
    td.train()
    ts.train()
    for k in ts.params:
        np.testing.assert_allclose(np.asarray(td.params[k]),
                                   np.asarray(ts.params[k]),
                                   rtol=2e-4, atol=2e-4)


def test_bdense_distributed_unpackable_stays_uint8_and_exact():
    """A >15-multiplicity graph must stack uint8 tables (no silent
    saturation) and still train to the segment reference."""
    from roc_tpu.core.graph import Dataset, Graph
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.train.trainer import TrainConfig

    rng = np.random.RandomState(3)
    V = 64
    # a hub destination with 40 copies of one source edge (mult > 15)
    src = np.concatenate([np.full(40, 7), rng.randint(0, V, 400),
                          np.arange(V)]).astype(np.int64)
    dst = np.concatenate([np.full(40, 3), rng.randint(0, V, 400),
                          np.arange(V)]).astype(np.int64)
    from roc_tpu.core.graph import from_edge_list
    g = from_edge_list(src, dst, V)
    ds = Dataset(graph=g,
                 features=rng.rand(V, 8).astype(np.float32),
                 labels=rng.randint(0, 3, V).astype(np.int32),
                 mask=np.ones(V, np.int32), num_classes=3)
    cfg = TrainConfig(aggr_impl="bdense", bdense_min_fill=1,
                      verbose=False, eval_every=1 << 30,
                      dropout_rate=0.0, symmetric=False, epochs=2,
                      learning_rate=0.05)
    td = DistributedTrainer(build_gcn([8, 8, 3], dropout_rate=0.0),
                            ds, 4, cfg)
    assert td.data.bd_tabs[0].shape[-1] == 128  # uint8, not packed
    ts = DistributedTrainer(build_gcn([8, 8, 3], dropout_rate=0.0),
                            ds, 4,
                            TrainConfig(aggr_impl="segment",
                                        verbose=False,
                                        eval_every=1 << 30,
                                        dropout_rate=0.0,
                                        symmetric=False, epochs=2,
                                        learning_rate=0.05))
    td.train()
    ts.train()
    for k in ts.params:
        np.testing.assert_allclose(np.asarray(td.params[k]),
                                   np.asarray(ts.params[k]),
                                   rtol=2e-4, atol=2e-4)


def test_bdense_distributed_group_mismatch_fails_fast():
    """Injected data built with one bdense_group must be rejected by a
    config wanting another — a silent mismatch would reduce across
    dst-tile boundaries without any shape error."""
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.core.partition import partition_graph
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.parallel import multihost as mh
    from roc_tpu.parallel.distributed import (DistributedTrainer,
                                              shard_dataset)
    from roc_tpu.train.trainer import TrainConfig

    ds = synthetic_dataset(96, 7, in_dim=12, num_classes=3, seed=2)
    pg = partition_graph(ds.graph, 4, node_multiple=8, edge_multiple=64)
    mesh = mh.make_parts_mesh(4)
    data = shard_dataset(ds, pg, mesh, aggr_impl="bdense",
                         bdense_min_fill=8)  # group=1 tables
    assert data.bd_tabs and data.bd_group == 1
    with pytest.raises(ValueError, match="bdense_group"):
        DistributedTrainer(
            build_gcn([12, 8, 3], dropout_rate=0.0), ds, 4,
            TrainConfig(aggr_impl="bdense", bdense_min_fill=8,
                        bdense_group=4, verbose=False),
            mesh=mesh, data=data, pg=pg)


def test_bdense_distributed_no_dense_tiles_falls_back():
    """min_fill too high for any partition: pure sectioned residual,
    no zero-block kernel in the step."""
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.train.trainer import TrainConfig

    ds = synthetic_dataset(384, 9, in_dim=12, num_classes=3, seed=4)
    kw = dict(learning_rate=0.05, epochs=2, eval_every=1 << 30,
              verbose=False, dropout_rate=0.0, symmetric=True)
    tb = DistributedTrainer(build_gcn([12, 8, 3], dropout_rate=0.0),
                            ds, 4,
                            TrainConfig(aggr_impl="bdense",
                                        bdense_min_fill=10**9, **kw))
    assert not tb.data.bd_tabs
    assert tb.data.sect_idx
    ts = DistributedTrainer(build_gcn([12, 8, 3], dropout_rate=0.0),
                            ds, 4, TrainConfig(aggr_impl="segment",
                                               **kw))
    tb.train()
    ts.train()
    for k in ts.params:
        np.testing.assert_allclose(np.asarray(tb.params[k]),
                                   np.asarray(ts.params[k]),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("group", [1, 3])
def test_bdense_multihost_local_build_matches_global_and_trains(group):
    """shard_dataset_local's bdense tables (block-count + residual
    chunk plan agreed via the O(P) collectives) must equal
    shard_dataset's single-controller build — including the group
    alignment, whose uniform stacked tail relies on every host's
    count being a group multiple — and the injected-data path must
    train through them."""
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.core.partition import partition_graph
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.parallel import multihost as mh
    from roc_tpu.parallel.distributed import (DistributedTrainer,
                                              shard_dataset)
    from roc_tpu.train.trainer import TrainConfig

    ds = synthetic_dataset(96, 7, in_dim=12, num_classes=3, seed=2)
    pg = partition_graph(ds.graph, 4, node_multiple=8, edge_multiple=64)
    mesh = mh.make_parts_mesh(4)
    kw = dict(aggr_impl="bdense", bdense_min_fill=8,
              bdense_group=group)
    loc = mh.shard_dataset_local(ds, pg, mesh, **kw)
    glo = shard_dataset(ds, pg, mesh, **kw)
    assert len(loc.bd_tabs) == 3 == len(glo.bd_tabs), \
        "fixture must yield dense tiles in both builders"
    assert loc.bd_group == group == glo.bd_group
    # the packable fixture stacks u4 tables in BOTH builders (the
    # multihost packing decision rides the max-multiplicity
    # collective; a width mismatch here means the agreement broke)
    assert loc.bd_tabs[0].shape[-1] == 64 == glo.bd_tabs[0].shape[-1]
    for a, b in zip(loc.bd_tabs, glo.bd_tabs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert (loc.bd_vpad, loc.bd_src_vpad) == (glo.bd_vpad,
                                              glo.bd_src_vpad)
    for a, b in zip(loc.sect_idx, glo.sect_idx):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(loc.sect_sub_dst, glo.sect_sub_dst):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert loc.sect_meta == glo.sect_meta
    assert loc.edge_src.shape[-1] == 1
    cfg = TrainConfig(epochs=2, verbose=False, aggr_impl="bdense",
                      bdense_min_fill=8, bdense_group=group,
                      dropout_rate=0.0, eval_every=1 << 30)
    tr = DistributedTrainer(build_gcn([12, 8, 3], dropout_rate=0.0),
                            ds, 4, cfg, mesh=mesh, data=loc, pg=pg)
    tr.train(epochs=2)
    assert np.isfinite(tr.evaluate()["train_loss"])


def test_trainer_bdense_a_budget_caps_plan_and_stays_exact():
    """TrainConfig.bdense_a_budget reaches the planner and caps
    DEVICE bytes: a one-uint8-block budget holds TWO u4-packed blocks
    on this (packable) fixture, shrinks the plan vs uncapped, pushes
    the dropped blocks into the sectioned residual, and the capped
    trainer still matches the segment reference exactly."""
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import TrainConfig, Trainer

    ds = synthetic_dataset(300, 9, in_dim=12, num_classes=3, seed=4)
    kw = dict(learning_rate=0.05, epochs=4, eval_every=1 << 30,
              verbose=False, dropout_rate=0.0, symmetric=True)
    uncapped = Trainer(
        build_gcn([12, 8, 3], dropout_rate=0.0), ds,
        TrainConfig(aggr_impl="bdense", bdense_min_fill=250,
                    bdense_a_budget=None, **kw))
    capped = Trainer(
        build_gcn([12, 8, 3], dropout_rate=0.0), ds,
        TrainConfig(aggr_impl="bdense", bdense_min_fill=250,
                    bdense_a_budget=128 * 128, **kw))
    n_unc = int(uncapped.gctx.bd_a.shape[0])
    assert n_unc > 2, "fixture must yield multiple dense tiles"
    assert int(capped.gctx.bd_a.shape[0]) == 2
    assert capped.gctx.bd_a.shape[-1] == 64  # u4-packed
    assert capped.gctx.bd_a.size <= 128 * 128  # device bytes <= cap
    ref = Trainer(build_gcn([12, 8, 3], dropout_rate=0.0), ds,
                  TrainConfig(aggr_impl="segment", **kw))
    capped.train()
    ref.train()
    for k in ref.params:
        np.testing.assert_allclose(np.asarray(capped.params[k]),
                                   np.asarray(ref.params[k]),
                                   rtol=2e-4, atol=2e-4)
