"""Worker for the multi-process DCN fault drill (ISSUE 13 satellite —
the drill matrix's real-DCN arm, advertised since PR 8).

Same spawn pattern as ``timeline_worker.py``: each of ``nproc``
processes owns ``4 // nproc`` virtual CPU devices, meets the others
through ``jax.distributed.initialize`` (Gloo loopback), and trains the
P=4 workload — but THROUGH the resilience stack: preemption guard
installed, a shared checkpoint rotation (multihost: process 0 writes,
everyone restores), ``train_with_recovery`` rounds, and an optional
armed fault (the ``site:epoch:proc`` grammar — ``sigkill:3:1`` kills
ONLY process 1 mid-run, the drill the test re-spawns around).

Exit codes follow the CLI contract: 0 = reached the target epoch,
75 = restartable (preempted / stalled), anything else = a real bug.

Usage: python dcn_drill_worker.py <coordinator> <nproc> <pid> <outdir>
       [fault]
"""

import os
import sys


def main() -> None:
    coordinator, nproc, pid, outdir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    fault = sys.argv[5] if len(sys.argv) > 5 else None
    n_parts = 4
    local_dev = n_parts // nproc
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={local_dev}")
    os.environ["ROC_TPU_EVENTS"] = os.path.join(
        outdir, f"ev_p{pid}.jsonl")
    import jax
    jax.config.update("jax_platforms", "cpu")

    from roc_tpu.parallel import multihost as mh
    mh.init_distributed(coordinator, nproc, pid)
    assert jax.process_count() == nproc, jax.process_count()

    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.core.partition import partition_graph
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.obs.heartbeat import StallFailure
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.resilience import inject, preempt
    from roc_tpu.resilience.preempt import (Preempted,
                                            RESTARTABLE_EXIT_CODE)
    from roc_tpu.resilience.recovery import (CheckpointRotation,
                                             train_with_recovery)
    from roc_tpu.train.trainer import TrainConfig

    preempt.install()
    if fault:
        inject.arm(fault)

    ds = synthetic_dataset(32 * n_parts, 6, in_dim=12, num_classes=3,
                           seed=0)
    mesh = mh.make_parts_mesh(n_parts)
    cfg = TrainConfig(
        epochs=6, verbose=False, aggr_impl="ell", symmetric=True,
        dropout_rate=0.0, eval_every=2,
        metrics_path=os.path.join(outdir, f"m_p{pid}.jsonl"))
    pg = partition_graph(ds.graph, n_parts, node_multiple=8,
                         edge_multiple=cfg.chunk)
    data = mh.shard_dataset_local(ds, pg, mesh, aggr_impl="ell")
    tr = DistributedTrainer(build_gcn([12, 8, 3], dropout_rate=0.0),
                            ds, n_parts, cfg, mesh=mesh, data=data,
                            pg=pg)
    rotation = CheckpointRotation(os.path.join(outdir, "ck"), keep=3)
    try:
        # max_retries=0: in a multi-process run an in-process retry
        # cannot work once a PEER is gone (the first collective wedges
        # again) — the restartable-exit + re-spawn path IS the drill
        train_with_recovery(tr, cfg.epochs, rotation,
                            checkpoint_every=2, max_retries=0)
    except (Preempted, StallFailure):
        sys.exit(RESTARTABLE_EXIT_CODE)
    m = tr.evaluate()
    print(f"WORKER_OK pid={pid} loss={m['train_loss']:.8f}",
          flush=True)


if __name__ == "__main__":
    main()
