"""Persistent XLA compilation cache (utils/compile_cache.py)."""

import os

import jax
import jax.numpy as jnp

from roc_tpu.utils.compile_cache import enable_compile_cache


def test_cache_populates_and_is_honored(tmp_path):
    d = str(tmp_path / "xla")
    got = enable_compile_cache(d, min_compile_secs=0.0)
    assert got == d and os.path.isdir(d)
    f = jax.jit(lambda a: jnp.tanh(a @ a).sum() + 41.0)
    f(jnp.ones((256, 256))).block_until_ready()
    assert os.listdir(d), "compilation cache stayed empty"


def test_env_var_dir(tmp_path, monkeypatch):
    d = str(tmp_path / "envcache")
    monkeypatch.setenv("ROC_TPU_CACHE_DIR", d)
    assert enable_compile_cache() == d


def test_uncreatable_dir_degrades_gracefully(tmp_path):
    # a path under a regular FILE can never be created (works even as
    # root, unlike a permissions-based setup)
    f = tmp_path / "plainfile"
    f.write_text("x")
    assert enable_compile_cache(str(f / "sub")) is None
