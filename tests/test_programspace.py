"""Program-space auditor + SPMD collective verifier (ISSUE 6): every
new rule fires on a synthetic violation, the statically enumerated
program-key set matches what ObservedJit actually records compiling in
a live rig run (the acceptance criterion — no under- or
over-enumeration), the program budget ratchets shrink-only, and the
CLI's --json output is machine-readable."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from roc_tpu.analysis.collective_lint import (CollectiveUnit,
                                              check_axis_names,
                                              check_conditional_collective,
                                              check_ppermute_cycle,
                                              check_ring_halo,
                                              ring_table_halo_counts)
from roc_tpu.analysis.programspace import (ProgramEntry, ProgramSpace,
                                           _check_distinct,
                                           build_rig_dataset,
                                           build_rig_trainer,
                                           check_cache_key_drift,
                                           check_compile_explosion,
                                           enumerate_programs,
                                           rig_configs)
from roc_tpu.obs.events import get_bus

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_AX = {"parts": 4}


def _cunit(fn, *args, axis_env=(("parts", 4),), axes=_AX):
    return CollectiveUnit(
        "fix", jax.make_jaxpr(fn, axis_env=list(axis_env))(*args), axes)


# -------------------------------------- collective verifier fixtures

def test_ppermute_two_cycle_fires():
    """A permutation made of two disjoint sub-rings rotates each half
    of the mesh among itself — every shard silently sees only half the
    graph.  The cycle rule must name the defect."""
    u = _cunit(lambda x: lax.ppermute(
        x, "parts", [(0, 1), (1, 0), (2, 3), (3, 2)]), jnp.ones(3))
    got = check_ppermute_cycle(u)
    assert [f.rule for f in got] == ["collective-ppermute-cycle"]
    assert "2 disjoint cycles" in got[0].msg


def test_ppermute_partial_cover_fires():
    """A permutation covering a strict subset of the axis leaves the
    uncovered shards waiting on sends that never come — a hang, not an
    error, at P>=2."""
    u = _cunit(lambda x: lax.ppermute(
        x, "parts", [(0, 1), (1, 0)]), jnp.ones(3))
    got = check_ppermute_cycle(u)
    assert len(got) == 1
    assert "covers 2/4" in got[0].msg and "missing [2, 3]" in got[0].msg


def test_ppermute_named_schedule_clean():
    """ring_hop_perm — THE schedule ring_aggregate issues — is a
    single full cycle at every width, and so is its reversal (any
    single cycle is deadlock-free; the canonical one is the ring's)."""
    from roc_tpu.parallel.ring import ring_hop_perm
    for s in (2, 3, 4, 8):
        perm = ring_hop_perm(s)
        u = _cunit(lambda x: lax.ppermute(x, "parts", perm),
                   jnp.ones(3), axis_env=(("parts", s),),
                   axes={"parts": s})
        assert not check_ppermute_cycle(u), f"S={s}"
    rev = [(d, s) for s, d in ring_hop_perm(4)]
    u = _cunit(lambda x: lax.ppermute(x, "parts", rev), jnp.ones(3))
    assert not check_ppermute_cycle(u)


def test_axis_name_fires_on_unknown_axis():
    """A collective over an axis the rig mesh does not define binds
    only on a larger mesh, or never."""
    u = _cunit(lambda x: lax.psum(x, "model"), jnp.ones(3),
               axis_env=(("model", 2),))
    got = check_axis_names(u)
    assert [f.key for f in got] == ["axis|psum|model"]
    # the mesh's own axis is of course clean
    assert not check_axis_names(
        _cunit(lambda x: lax.psum(x, "parts"), jnp.ones(3)))


def test_conditional_collective_fires():
    """A psum issued in one cond branch but not the other is an
    instant P>=2 hang when shards disagree on the predicate."""
    u = _cunit(lambda p, x: lax.cond(
        p, lambda v: lax.psum(v, "parts"), lambda v: v * 2.0, x),
        True, jnp.ones(3))
    got = check_conditional_collective(u)
    assert [f.rule for f in got] == ["collective-conditional"]
    assert "deadlock" in got[0].msg
    # branches issuing the SAME collective sequence are lockstep-safe
    u2 = _cunit(lambda p, x: lax.cond(
        p, lambda v: lax.psum(v, "parts") + 1.0,
        lambda v: lax.psum(v, "parts") * 2.0, x), True, jnp.ones(3))
    assert not check_conditional_collective(u2)


def test_conditional_ppermute_perm_mismatch_fires():
    """Same primitive/axis/shape in both branches but DIFFERENT
    permutations is just as deadlock-prone — device A sends along one
    schedule while B waits on the other — so the perm is part of the
    sequence identity."""
    from roc_tpu.parallel.ring import ring_hop_perm
    fwd = ring_hop_perm(4)
    rev = [(d, s) for s, d in fwd]
    u = _cunit(lambda p, x: lax.cond(
        p, lambda v: lax.ppermute(v, "parts", fwd),
        lambda v: lax.ppermute(v, "parts", rev), x),
        True, jnp.ones(3))
    got = check_conditional_collective(u)
    assert [f.rule for f in got] == ["collective-conditional"]
    # identical perms in both branches stay clean
    u2 = _cunit(lambda p, x: lax.cond(
        p, lambda v: lax.ppermute(v, "parts", fwd) + 1.0,
        lambda v: lax.ppermute(v, "parts", fwd) * 2.0, x),
        True, jnp.ones(3))
    assert not check_conditional_collective(u2)


def test_ring_halo_parity_and_violation():
    """The ring tables and the partition plan are two independent
    derivations of the same halo exchange: the real build ties
    exactly, and a tampered table (rows collapsed onto one source)
    fires on both sides of the drifted pair."""
    from roc_tpu.core.costmodel import partition_halo_stats
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.core.partition import partition_graph
    from roc_tpu.parallel.ring import build_ring_tables
    ds = synthetic_dataset(num_nodes=96, avg_degree=5, in_dim=8,
                           num_classes=4, seed=3)
    pg = partition_graph(ds.graph, 3, node_multiple=8)
    rt = build_ring_tables(pg)
    assert not check_ring_halo("collective:fix", pg, rt)
    recv, send = ring_table_halo_counts(pg, rt)
    hi, ho = partition_halo_stats(pg)
    assert np.array_equal(recv, hi) and np.array_equal(send, ho)
    src = rt.src.copy()
    ext = np.where(src[0, 1] < pg.part_nodes)[0]
    assert len(ext) > 1, "fixture graph must have a real halo"
    src[0, 1, ext] = src[0, 1, ext[0]]
    rt2 = type(rt)(src=src, dst=rt.dst,
                   padding_ratio=rt.padding_ratio)
    keys = sorted(f.key for f in check_ring_halo("collective:fix",
                                                 pg, rt2))
    assert keys == ["halo-in|part=0", "halo-out|part=1"]


# ------------------------------------------ program-space rule fixtures

def _entry(slot, dims, dtype="float32", spec="-", eqns=10,
           observed=True):
    leaves = tuple(("{}".format(dtype), tuple(d), spec) for d in dims)
    sig = ";".join(f"{dtype}[{','.join(map(str, d))}]@{spec}"
                   for d in dims)
    return ProgramEntry(slot=slot, key=f"{slot}|{sig}|donate=",
                        leaves=leaves, observed=observed, eqns=eqns)


def test_cache_key_drift_fires_on_unquantized_pair():
    """Two program keys differing ONLY by dims that snap to the same
    node multiple are a guaranteed persistent-compile-cache miss — the
    shapes would have tied had the quantization been applied."""
    space = ProgramSpace(config="fix", entries=[
        _entry("a", [(250, 48)]), _entry("b", [(252, 48)])],
        node_multiple=8, edge_multiple=128)
    got = check_cache_key_drift(space)
    assert [f.rule for f in got] == ["cache-key-drift"]
    assert "250 vs 252" in got[0].msg


def test_cache_key_drift_quiet_on_real_differences():
    # dims that snap to DIFFERENT multiples: distinct programs for
    # real reasons
    s1 = ProgramSpace(config="fix", entries=[
        _entry("a", [(250, 48)]), _entry("b", [(260, 48)])])
    assert not check_cache_key_drift(s1)
    # dtype difference: structural, never drift
    s2 = ProgramSpace(config="fix", entries=[
        _entry("a", [(250, 48)]),
        _entry("b", [(252, 48)], dtype="bfloat16")])
    assert not check_cache_key_drift(s2)
    # sharding-spec difference likewise
    s3 = ProgramSpace(config="fix", entries=[
        _entry("a", [(250, 48)]),
        _entry("b", [(252, 48)], spec="parts")])
    assert not check_cache_key_drift(s3)


def test_cache_key_drift_quiet_on_node_quantized_pairs():
    """Dims that are ALREADY exact node multiples (quantized shapes,
    or widths that happen to sit on the 8-grid) landing in the same
    128-edge-window are not drift — nothing leaked, there is nothing
    left to quantize, and flagging the pair would be an unclearable
    finding."""
    # 8 vs 120: both on the node grid, same edge window
    s1 = ProgramSpace(config="fix", entries=[
        _entry("a", [(8, 48)]), _entry("b", [(120, 48)])])
    assert not check_cache_key_drift(s1)
    # 136 vs 240: same, in the second edge window
    s2 = ProgramSpace(config="fix", entries=[
        _entry("a", [(136, 48)]), _entry("b", [(240, 48)])])
    assert not check_cache_key_drift(s2)
    # but a pair with one dim OFF the node grid in the same edge
    # window is still a leak (244 = 4 mod 8)
    s3 = ProgramSpace(config="fix", entries=[
        _entry("a", [(256, 48)]), _entry("b", [(244, 48)])])
    assert check_cache_key_drift(s3)


def test_cache_key_drift_exempts_aux_block_programs():
    """The streamed head's per-block jit variants (observed=False)
    legitimately differ by a row count — a ragged tail block is not a
    quantization failure, and block sizes are not partition shapes, so
    the drift rule must not flag a pair the gate could never clear."""
    a = _entry("head_fwd_block:256:train", [(256, 48)], observed=False)
    b = _entry("head_fwd_block:244:train", [(244, 48)], observed=False)
    space = ProgramSpace(config="fix", entries=[a, b])
    assert not check_cache_key_drift(space)
    # the same shapes on OBSERVED slots are a real drift
    space2 = ProgramSpace(config="fix", entries=[
        _entry("a", [(256, 48)]), _entry("b", [(244, 48)])])
    assert check_cache_key_drift(space2)


def test_compile_explosion_fires_past_budget():
    space = ProgramSpace(config="fix", entries=[
        _entry("a", [(8, 8)]), _entry("b", [(16, 8)]),
        _entry("c", [(24, 8)])])
    got = check_compile_explosion(space, 2)
    assert [f.rule for f in got] == ["compile-explosion"]
    assert got[0].detail["programs"] == 3
    assert got[0].detail["budget"] == 2
    # at or under the bound, or with no bound recorded yet: quiet
    assert not check_compile_explosion(space, 3)
    assert not check_compile_explosion(space, None)


def test_enumeration_rejects_duplicate_keys():
    e = _entry("a", [(8, 8)])
    dup = ProgramEntry(slot="b", key=e.key, leaves=e.leaves,
                       observed=True, eqns=1)
    with pytest.raises(AssertionError, match="duplicate keys"):
        _check_distinct(ProgramSpace(config="fix", entries=[e, dup]))


def test_quantize_plan_shapes_is_the_shared_derivation():
    """plan_from_bounds' padded shapes must come from the SAME
    function the auditor calls — including the full-part padding-edge
    correction (a part whose real rows exactly fill part_nodes while
    carrying padding edges gets one extra row-multiple)."""
    from roc_tpu.core.partition import quantize_plan_shapes
    assert quantize_plan_shapes([5, 7], [100, 120]) == (8, 128)
    # part 0 exactly fills the 8-row multiple AND carries padding
    # edges (100 < 128): the correction adds one row-multiple
    assert quantize_plan_shapes([8, 7], [100, 120]) == (16, 128)
    # a full part with FULL edges needs no padding edges: uncorrected
    assert quantize_plan_shapes([8, 7], [128, 120]) == (8, 128)


# -------------------------------- enumeration + live parity (rig runs)

@pytest.fixture(scope="module")
def rig_dataset():
    return build_rig_dataset()


def test_enumeration_counts_and_structure(rig_dataset):
    """The enumerated spaces of both rig configs: counts match the
    committed program budget (the compile-explosion baseline), keys
    are distinct, and the streamed config's space is strictly larger
    than its ObservedJit slots (the per-block head jits)."""
    from roc_tpu.analysis.findings import load_program_budget
    budget = load_program_budget(
        os.path.join(_REPO, "scripts", "lint_baseline.json"))
    spaces = {name: enumerate_programs(spec, dataset=rig_dataset)
              for name, spec in rig_configs().items()}
    for name, space in spaces.items():
        assert space.program_count == budget[name], name
        assert len({e.key for e in space.entries}) == \
            space.program_count
        assert space.modeled_compile_ms() > 0
    # gin_flat8: every program is an ObservedJit slot, and the rig
    # runs the uniform flat-sum consolidation
    g = spaces["gin_flat8"]
    assert all(e.observed for e in g.entries)
    assert g.resolved["parts"] == 2
    assert g.resolved["aggr_impl"] == "flat_sum"
    # sgc_stream: the aux head-block programs exceed the observed set
    s = spaces["sgc_stream"]
    assert len(s.observed_keys()) < s.program_count
    assert any(e.slot.startswith("head_fwd_block") for e in s.entries)


def test_resolve_idempotency_asserted(rig_dataset, monkeypatch):
    """The auditor refuses to enumerate through a non-idempotent
    resolve pass — re-resolving a resolved config must be a fixpoint,
    or the static program space silently forks from the trainers."""
    import roc_tpu.train.trainer as T
    real = T.resolve_config
    calls = {"n": 0}

    def flappy(model, dataset, config, **kw):
        model, config, census = real(model, dataset, config, **kw)
        calls["n"] += 1
        if calls["n"] > 1:     # second resolve: mutate the config
            import dataclasses
            config = dataclasses.replace(config, chunk=config.chunk + 1)
        return model, config, census

    monkeypatch.setattr(T, "resolve_config", flappy)
    spec = rig_configs()["gin_flat8"]
    with pytest.raises(AssertionError, match="not idempotent"):
        enumerate_programs(spec, dataset=rig_dataset)


class _Recorder:
    def __init__(self):
        self.records = []

    def write(self, record):
        self.records.append(dict(record))

    def close(self):
        pass


@pytest.mark.parametrize("name", ["gin_flat8", "sgc_stream",
                                  "gin_mesh2d"])
def test_program_key_parity_static_vs_live(rig_dataset, name):
    """THE acceptance criterion: for these rig configs the auditor's
    statically enumerated program-key set exactly matches the set of
    programs ObservedJit records compiling in a live
    train+eval+predict run — no under- or over-enumeration.  The 2-D
    rig bounds the mesh PR's program growth to exactly its declared
    new step variants (sharded-in/out train + eval keys)."""
    from roc_tpu.analysis.programspace import rig_required_devices
    spec = rig_configs()[name]
    need = rig_required_devices(spec)
    if need > len(jax.devices()):
        pytest.skip(f"needs {need} devices")
    space = enumerate_programs(spec, dataset=rig_dataset)
    static = space.observed_keys()
    rec = _Recorder()
    bus = get_bus()
    bus.add_sink(rec)
    try:
        tr = build_rig_trainer(spec, dataset=rig_dataset)
        tr.train(1)
        tr.evaluate()
        tr.predict()
    finally:
        bus.sinks.remove(rec)
    live = {r["program_key"] for r in rec.records
            if r.get("cat") == "compile" and "program_key" in r}
    assert live == static, (
        f"{name}: static-only={sorted(static - live)} "
        f"live-only={sorted(live - static)}")


def test_enumeration_follows_dataset_scale():
    """The streamed branch must size the [V,H] activation and the
    head blocks from the AUDITED dataset, not the rig constant — an
    enumeration over a 320-node dataset whose keys carried 256-row
    shapes would under- and over-enumerate at once."""
    from roc_tpu.core.graph import synthetic_dataset
    ds = synthetic_dataset(num_nodes=320, avg_degree=6, in_dim=48,
                           num_classes=6, seed=1)
    spec = rig_configs()["sgc_stream"]
    space = enumerate_programs(spec, dataset=ds)
    tg = next(e for e in space.entries if e.slot == "tail_grad")
    # leaf 0+ are the param leaves; the streamed activation y is the
    # one [V, H] leaf — its row count must be the dataset's V
    assert any(dims[:1] == (320,) for _, dims, _ in tg.leaves), \
        tg.leaves
    assert not any(dims[:1] == (256,) for _, dims, _ in tg.leaves), \
        "rig-constant rows leaked into a non-rig dataset's keys"
    blocks = {int(s.rsplit(":", 2)[1]) for s in
              (e.slot for e in space.entries)
              if s.startswith("head_fwd_block")}
    tr = build_rig_trainer(spec, dataset=ds)
    assert blocks == {hi - lo for lo, hi in tr._head._blocks(320)}


def test_program_key_parity_plain_single_device(rig_dataset):
    """The single-device NON-streamed enumeration branch (plain
    train/eval/predict ObservedJit slots) is not reachable from either
    registered rig config — gin_flat8 is distributed, sgc_stream is
    streamed — so an ad-hoc rig pins its static-vs-live parity too:
    a drifted donate tuple or arg order in that branch must fail here,
    not the day a third rig config is registered."""
    from roc_tpu.analysis.programspace import _C, _F, _H, RigSpec
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import TrainConfig
    spec = RigSpec(
        name="gcn_plain",
        model=lambda: build_gcn([_F, _H, _C], dropout_rate=0.5),
        config=lambda: TrainConfig(verbose=False, symmetric=True,
                                   aggr_impl="segment",
                                   dtype=jnp.float32,
                                   compute_dtype=jnp.bfloat16),
        parts=1)
    space = enumerate_programs(spec, dataset=rig_dataset)
    # predict compiles NOTHING of its own — it reuses the eval
    # program's logits output (the eval/predict consolidation)
    assert {e.slot for e in space.entries} == \
        {"train_step", "eval_step"}
    assert all(e.observed for e in space.entries)
    rec = _Recorder()
    bus = get_bus()
    bus.add_sink(rec)
    try:
        tr = build_rig_trainer(spec, dataset=rig_dataset)
        tr.train(1)
        tr.evaluate()
        tr.predict()
    finally:
        bus.sinks.remove(rec)
    live = {r["program_key"] for r in rec.records
            if r.get("cat") == "compile" and "program_key" in r}
    assert live == space.observed_keys(), (
        f"static-only={sorted(space.observed_keys() - live)} "
        f"live-only={sorted(live - space.observed_keys())}")


# --------------------------------- uniform-scan consolidation (pins)

def _scan_shapes(closed_jaxpr):
    """Distinct scan-body signatures in a jaxpr (recursing through
    pjit/custom_vjp/etc. via iter_eqns) — each distinct signature is
    one scan program XLA compiles."""
    from roc_tpu.analysis.jaxpr_lint import iter_eqns
    shapes = set()
    for eqn in iter_eqns(closed_jaxpr):
        if eqn.primitive.name == "scan":
            shapes.add(tuple(str(v.aval) for v in eqn.invars))
    return shapes


def test_flat_sum_single_scan_program(rig_dataset):
    """THE consolidation pin: a flat_sum config with ONE aggregation
    width compiles exactly ONE scan program into its train step —
    forward and symmetric-vjp backward share the shape, and the shape
    is independent of the degree distribution (a skewed dataset
    enumerates the identical scan set; the per-bucket ELL unroll
    would have compiled one program per width bucket)."""
    from roc_tpu.analysis.programspace import _C, _F
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.sgc import build_sgc
    from roc_tpu.train.trainer import TrainConfig, Trainer

    def shapes_for(ds):
        tr = Trainer(build_sgc([_F, _C], k=2, dropout_rate=0.5), ds,
                     TrainConfig(verbose=False, symmetric=True,
                                 aggr_impl="flat_sum",
                                 dtype=jnp.float32,
                                 compute_dtype=jnp.bfloat16))
        lr = jnp.asarray(0.01, jnp.float32)
        jaxpr = jax.make_jaxpr(tr._train_step._jit)(
            tr.params, tr.opt_state, tr.key, lr, tr.feats,
            tr.labels, tr.mask, tr.gctx)
        return _scan_shapes(jaxpr)

    shapes = shapes_for(rig_dataset)
    assert len(shapes) == 1, shapes
    # degree-distribution independence: a much more skewed graph of
    # the same size yields the same single scan shape
    skew = synthetic_dataset(num_nodes=256, avg_degree=12, in_dim=_F,
                             num_classes=_C, seed=7)
    assert shapes_for(skew) == shapes


def test_flat_sum_rig_one_scan_per_width(rig_dataset):
    """The flat-sum rig (gin_flat8, two aggregation widths F and H):
    the distributed train step's distinct scan programs == one per
    (dtype, F-quantum) — the tentpole claim, pinned."""
    spec = rig_configs()["gin_flat8"]
    if spec.parts > len(jax.devices()):
        pytest.skip(f"needs {spec.parts} devices")
    tr = build_rig_trainer(spec, rig_dataset)
    assert tr.config.aggr_impl == "flat_sum"
    d = tr.data
    lr = jnp.asarray(0.01, jnp.float32)
    jaxpr = jax.make_jaxpr(tr._train_step._jit)(
        tr.params, tr.opt_state, d.feats, d.labels, d.mask,
        d.edge_src, d.edge_dst, d.in_degree, d.ell_idx,
        d.ell_row_pos, d.ell_row_id, d.ring_idx, d.sect_idx,
        d.sect_sub_dst, d.bd_tabs,
        (d.ell_w, d.sect_w, d.ring_w, d.bd_scale), tr.key, lr)
    shapes = _scan_shapes(jaxpr)
    widths = {op.dim for op in tr.model._ops
              if op.kind == "scatter_gather"}
    assert len(widths) == 2          # GIN aggregates at F and H
    assert len(shapes) == len(widths), shapes


# -------------------------------------------- program budget ratchet

def test_program_budget_shrink_only(tmp_path):
    """min(stored, measured): a bound initializes and shrinks, never
    grows; unmeasured configs keep their stored bounds; the findings
    list rides through untouched."""
    from roc_tpu.analysis.findings import (load_baseline,
                                           load_program_budget,
                                           save_baseline,
                                           shrink_program_budget)
    bp = str(tmp_path / "baseline.json")
    save_baseline(bp, ["r|u|k"], program_budget={"a": 5, "keep": 9})
    got = shrink_program_budget(bp, {"a": 7, "b": 4})
    # a: 7 > 5 stored -> stays 5; b: initialized at 4; keep: untouched
    assert got == {"a": 5, "b": 4, "keep": 9}
    assert load_program_budget(bp) == got
    assert load_baseline(bp) == {"r|u|k"}
    # shrink: measured 3 < stored 5
    assert shrink_program_budget(bp, {"a": 3})["a"] == 3
    # saving findings with program_budget=None preserves the section
    save_baseline(bp, [])
    assert load_program_budget(bp)["a"] == 3
    # known= drops bounds for configs that no longer exist (renamed
    # rigs) while keeping known-but-unmeasured ones
    got = shrink_program_budget(bp, {"a": 3}, known={"a", "keep"})
    assert got == {"a": 3, "keep": 9}


# --------------------------------------------------- CLI + registration

def test_new_rules_registered():
    from roc_tpu.analysis.driver import all_rule_names, is_trace_rule
    names = set(all_rule_names())
    for r in ("collective-ppermute-cycle", "collective-axis-name",
              "collective-conditional", "collective-ring-halo",
              "compile-explosion", "cache-key-drift"):
        assert r in names, r
        assert is_trace_rule(r), r


def test_cli_json_update_baseline_reports_post_state(tmp_path):
    """--json --update-baseline: the payload describes the state the
    run LEAVES (stale entries it just removed are gone from the
    output, and the file is rewritten) — a CI consumer must not
    re-flag a ratchet the same invocation already cleared."""
    bp = tmp_path / "scripts" / "lint_baseline.json"
    bp.parent.mkdir()
    bp.write_text(json.dumps(
        {"version": 1, "findings": ["stdout-print|gone|x"]}))
    (tmp_path / "roc_tpu").mkdir()
    (tmp_path / "roc_tpu" / "clean.py").write_text("x = 1\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "roc_tpu.analysis", "--json",
         "--update-baseline", "--root", str(tmp_path),
         "--select", "stdout-print"],
        capture_output=True, text=True, timeout=60, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    payload = json.loads(r.stdout)
    assert payload["stale"] == []
    assert payload["summary"]["stale"] == 0
    assert json.loads(bp.read_text())["findings"] == []


def test_cli_baseline_override_governs_program_budget(tmp_path):
    """--baseline points the compile-explosion bound AND the ratchet
    at the same file: an override with a tighter budget must fire the
    rule (the check and the shrink can't operate on different
    files)."""
    bp = tmp_path / "baseline.json"
    bp.write_text(json.dumps(
        {"version": 1, "findings": [],
         "program_budget": {"gin_flat8": 1, "sgc_stream": 99}}))
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "roc_tpu.analysis",
         "--baseline", str(bp), "--select", "compile-explosion"],
        cwd=_REPO, capture_output=True, text=True, timeout=180,
        env=env)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "compile-explosion" in r.stdout
    assert "baseline 1, delta +1" in r.stdout


def test_cli_strict_fails_on_budget_slack(tmp_path):
    """Same ratchet semantics as stale findings: a measured program
    count BELOW the recorded bound must be committed via
    --update-baseline under --strict — a later program-count
    regression would otherwise hide inside the slack and the
    compile-wall tripwire would never fire."""
    bp = tmp_path / "baseline.json"
    bp.write_text(json.dumps(
        {"version": 1, "findings": [],
         "program_budget": {"gin_flat8": 9, "sgc_stream": 7}}))
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    args = [sys.executable, "-m", "roc_tpu.analysis",
            "--baseline", str(bp), "--select", "compile-explosion"]
    r = subprocess.run(args + ["--strict"], cwd=_REPO,
                       capture_output=True, text=True, timeout=180,
                       env=env)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "2 measured < 9 baselined" in r.stdout
    # non-strict: a note, not a failure
    r2 = subprocess.run(args, cwd=_REPO, capture_output=True,
                        text=True, timeout=180, env=env)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "note:" in r2.stdout
    # --update-baseline ratchets the bound down and clears the slack
    r3 = subprocess.run(args + ["--strict", "--update-baseline"],
                        cwd=_REPO, capture_output=True, text=True,
                        timeout=180, env=env)
    assert r3.returncode == 0, r3.stdout + r3.stderr
    assert json.loads(bp.read_text())["program_budget"] == \
        {"gin_flat8": 2, "sgc_stream": 6, "sgc_serve": 4,
         "sgc_serve_q8": 4, "gin_mesh2d": 2}


def test_cli_json_reports_program_space():
    """--json: one machine-readable object on stdout with the
    compile-budget reports and full program-key sets, so CI can diff
    program counts across commits without parsing text.  A
    programspace-only --select skips the jaxpr/HLO trace stage."""
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "roc_tpu.analysis", "--json",
         "--select", "compile-explosion,cache-key-drift"],
        cwd=_REPO, capture_output=True, text=True, timeout=180,
        env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    payload = json.loads(r.stdout)
    assert payload["summary"]["new"] == 0
    reports = {p["config"]: p for p in payload["program_space"]}
    assert set(reports) == {"gin_flat8", "sgc_stream", "sgc_serve",
                            "sgc_serve_q8", "gin_mesh2d"}
    for rep in reports.values():
        assert rep["programs"] == len(rep["keys"])
        assert rep["budget"] is not None
        assert rep["delta"] == 0
