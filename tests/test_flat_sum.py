"""Uniform-scan flat_sum consolidation + chunked output head
(ISSUE 7): forward+grad parity of the single-scan layout against the
ell/sectioned references across impl x halo rig configs, the MAX and
fused-weight variants, the resolve pass's edge-count auto-route (and
its idempotency), and the chunked classification head (values and dX
bit-identical; dW to fp32 roundoff).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from roc_tpu.core.graph import synthetic_dataset
from roc_tpu.models.builder import AGGR_MAX, Model
from roc_tpu.models.gcn import build_gcn
from roc_tpu.models.gin import build_gin
from roc_tpu.parallel.distributed import DistributedTrainer
from roc_tpu.train.trainer import (HEAD_CHUNK_ROWS, TrainConfig,
                                   Trainer, resolve_config,
                                   resolve_head_chunk)

REL = 1e-5


@pytest.fixture(scope="module")
def ds():
    return synthetic_dataset(num_nodes=256, avg_degree=6, in_dim=24,
                             num_classes=5, seed=3)


@pytest.fixture(scope="module", autouse=True)
def _release_executables():
    """This module compiles ~60 distinct trainer programs (parity
    matrices across impl x halo x parts); release the in-process
    executable/trace caches afterwards so the accumulated native JIT
    state doesn't destabilize the rest of a long single-process
    suite run."""
    yield
    jax.clear_caches()


def _rel_err(a, b):
    a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
    return float(np.max(np.abs(a - b))
                 / max(1.0, np.max(np.abs(b))))


def _train(ds, model_fn, impl, parts=1, epochs=3, **cfg_kw):
    cfg = TrainConfig(verbose=False, symmetric=True, aggr_impl=impl,
                      dropout_rate=0.0, **cfg_kw)
    if parts > 1:
        tr = DistributedTrainer(model_fn(), ds, parts, cfg)
    else:
        tr = Trainer(model_fn(), ds, cfg)
    tr.train(epochs)
    m = tr.evaluate()
    return tr, m, np.asarray(tr.predict())


# ----------------------------------------------------- fwd+grad parity

@pytest.mark.parametrize("ref_impl", ["segment", "ell", "sectioned"])
def test_flat_sum_parity_single_device(ds, ref_impl):
    """3 trained epochs (forward AND gradients compound into the
    params) + logits: flat_sum vs each reference impl <= 1e-5."""
    mk = lambda: build_gcn([24, 16, 5], dropout_rate=0.0)
    t0, m0, p0 = _train(ds, mk, ref_impl)
    t1, m1, p1 = _train(ds, mk, "flat_sum")
    assert _rel_err(p1, p0) < REL
    for k in t0.params:
        assert _rel_err(t1.params[k], t0.params[k]) < REL, k
    assert abs(m1["train_loss"] - m0["train_loss"]) < 1e-3


@pytest.mark.parametrize("parts,halo", [(2, "gather"), (4, "gather"),
                                        (2, "ring")])
def test_flat_sum_parity_distributed(ds, parts, halo):
    """Across the halo axis: gather shards the flat tables; ring
    uploads empty sect stubs and the flat8 fields must stay None so
    the builder routes to ring_aggregate.  Either way P-part flat_sum
    training matches the single-device segment reference <= 1e-5 —
    params and original-order logits."""
    mk = lambda: build_gcn([24, 16, 5], dropout_rate=0.0)
    t0, _, p0 = _train(ds, mk, "segment")
    t1, _, p1 = _train(ds, mk, "flat_sum", parts=parts, halo=halo)
    assert _rel_err(p1, p0) < REL
    for k in t0.params:
        assert _rel_err(t1.params[k], t0.params[k]) < REL, k


def test_flat_sum_fused_weight_parity(ds):
    """aggr_fuse='on' bakes the D^-1/2 A D^-1/2 entries into the flat
    tables (flat8_w): fused flat_sum == fused sectioned == UNfused
    flat_sum (exact linear algebra), single-device and P=2."""
    mk = lambda: build_gcn([24, 16, 5], dropout_rate=0.0)
    _, _, p_sect = _train(ds, mk, "sectioned", aggr_fuse="on")
    t_f, _, p_f = _train(ds, mk, "flat_sum", aggr_fuse="on")
    _, _, p_off = _train(ds, mk, "flat_sum", aggr_fuse="off")
    # the fused model really did fuse, and the tables really exist
    assert t_f.model.num_fused_aggregates() > 0
    assert t_f.gctx.flat8_w is not None
    assert _rel_err(p_f, p_sect) < REL
    assert _rel_err(p_f, p_off) < REL
    _, _, p_d = _train(ds, mk, "flat_sum", parts=2, aggr_fuse="on")
    assert _rel_err(p_d, p_f) < REL


def _build_max(dims):
    m = Model(dims[0])
    t = m.input()
    t = m.scatter_gather(t, AGGR_MAX)
    t = m.linear(t, dims[1])
    m.softmax_cross_entropy(t)
    return m


def test_flat_max_parity(ds):
    """The MAX variant (aggregate_flat_max: masked width-max + sorted
    scatter-max): matches the ELL MAX reference through training."""
    t0, _, p0 = _train(ds, lambda: _build_max([24, 5]), "ell")
    t1, _, p1 = _train(ds, lambda: _build_max([24, 5]), "flat_sum")
    assert _rel_err(p1, p0) < REL
    for k in t0.params:
        assert _rel_err(t1.params[k], t0.params[k]) < REL, k


def test_flat_sum_op_grad_parity(ds):
    """Direct op-level vjp: cotangents through aggregate_flat_sum ==
    through aggregate_segment (the exact-autodiff reference,
    symmetric=False so the custom vjp is NOT in play)."""
    mk = lambda: build_gcn([24, 16, 5], dropout_rate=0.0)
    outs = {}
    for impl in ("segment", "flat_sum"):
        cfg = TrainConfig(verbose=False, symmetric=False,
                          aggr_impl=impl, dropout_rate=0.0)
        tr = Trainer(mk(), ds, cfg)
        x = jnp.asarray(np.random.RandomState(0).rand(256, 24),
                        jnp.float32)
        g = jax.grad(lambda v: tr.gctx.aggregate_sum(v).sum() ** 2)(x)
        outs[impl] = np.asarray(g)
    assert _rel_err(outs["flat_sum"], outs["segment"]) < REL


# ------------------------------------------------- resolve auto-route

def test_auto_route_past_sectioned_window(monkeypatch):
    """resolve_auto_impl: sectioned keeps its measured window; the
    ell-bound region routes to flat_sum once num_edges crosses
    FLAT_SUM_MIN_EDGES (and never without edge information)."""
    from roc_tpu.core import ell as E
    lo, hi = E.SECTION_ROWS_DEFAULT, E.SECTIONED_MAX_ROWS
    monkeypatch.setenv("ROC_TPU_DEVICE_KIND", "TPU v5 lite")
    # inside the sectioned window: unchanged
    assert E.resolve_auto_impl(233_000, num_edges=10 ** 9) == \
        "sectioned"
    # past the window's out_rows bound with huge E: flat_sum
    assert E.resolve_auto_impl(2_450_000,
                               num_edges=E.FLAT_SUM_MIN_EDGES) == \
        "flat_sum"
    # past the window, small E: the per-bucket unroll is cheap — ell
    assert E.resolve_auto_impl(2_450_000, num_edges=10 ** 6) == "ell"
    # no edge info (legacy callers): the old sectioned/ell split
    assert E.resolve_auto_impl(2_450_000) == "ell"
    assert lo < hi  # window sanity (the constants the cases rely on)


def test_auto_route_resolves_in_config_and_is_idempotent(
        ds, monkeypatch):
    """With the threshold lowered to rig scale, aggr_impl='auto'
    resolves to flat_sum through THE resolve pass, and re-resolving
    the resolved config is a fixpoint (the auditor's idempotency
    contract holds with the new route)."""
    from roc_tpu.core import ell as E
    monkeypatch.setattr(E, "FLAT_SUM_MIN_EDGES", 100)
    cfg = TrainConfig(verbose=False, symmetric=True,
                      aggr_impl="auto", dropout_rate=0.0)
    model = build_gin([24, 16, 5], dropout_rate=0.0)
    m1, c1, _ = resolve_config(model, ds, cfg)
    assert c1.aggr_impl == "flat_sum"
    m2, c2, _ = resolve_config(m1, ds, c1)
    assert c2 == c1 and m2 is m1
    # MAX models route through resolve_attention_impl to flat_sum too
    cfg_max = TrainConfig(verbose=False, symmetric=True,
                          aggr_impl="auto", dropout_rate=0.0)
    _, c3, _ = resolve_config(_build_max([24, 5]), ds, cfg_max)
    assert c3.aggr_impl == "flat_sum"
    _, c4, _ = resolve_config(_build_max([24, 5]), ds, c3)
    assert c4 == c3


# ----------------------------------------------- chunked output head

def test_resolve_head_chunk():
    c = lambda v: TrainConfig(head_chunk=v)
    # auto: off below the threshold, HEAD_CHUNK_ROWS past it
    assert resolve_head_chunk(c("auto"), 1000) == 0
    assert resolve_head_chunk(c("auto"), 1 << 22) == HEAD_CHUNK_ROWS
    # explicit: literal, 0 = off, >= rows degenerates to off
    assert resolve_head_chunk(c(4096), 1 << 20) == 4096
    assert resolve_head_chunk(c(0), 1 << 20) == 0
    assert resolve_head_chunk(c(1 << 21), 1 << 20) == 0
    with pytest.raises(ValueError):
        resolve_head_chunk(c(-1), 1 << 20)
    with pytest.raises(ValueError):
        resolve_head_chunk(c("banana"), 1 << 20)


def test_linear_chunked_bit_identical():
    """ops/dense.linear_chunked == linear exactly (each output row's
    dot product is unchanged), including a ragged tail block and the
    fused activation, values AND gradients."""
    from roc_tpu.ops.dense import linear, linear_chunked
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(300, 24), jnp.float32)
    w = jnp.asarray(rng.randn(24, 7), jnp.float32)
    for act in ("none", "relu"):
        y0 = linear(x, w, act)
        y1 = linear_chunked(x, w, act, block=128)
        np.testing.assert_array_equal(np.asarray(y0), np.asarray(y1))
    # dW sums the row axis blockwise — a different (but equally
    # valid) fp reduction order than the one-matmul reference
    g0 = jax.grad(lambda ww: linear(x, ww, "none").sum())(w)
    g1 = jax.grad(lambda ww: linear_chunked(
        x, ww, "none", block=128).sum())(w)
    np.testing.assert_allclose(np.asarray(g0), np.asarray(g1),
                               rtol=1e-5)
    # block >= rows short-circuits to the plain matmul
    y2 = linear_chunked(x, w, "none", block=512)
    np.testing.assert_array_equal(np.asarray(y2),
                                  np.asarray(linear(x, w, "none")))


def test_head_chunk_training_parity(ds):
    """End-to-end: a forced head_chunk trains to the SAME params and
    logits as the unchunked head (dropout on — the RNG stream is
    untouched because chunking only rewrites the loss-op linear)."""
    def run(hc):
        cfg = TrainConfig(verbose=False, symmetric=True,
                          aggr_impl="segment", dropout_rate=0.5,
                          head_chunk=hc)
        tr = Trainer(build_gcn([24, 16, 5], dropout_rate=0.5), ds,
                     cfg)
        tr.train(3)
        return tr, np.asarray(tr.predict())
    t0, p0 = run(0)
    t1, p1 = run(64)
    assert t1.gctx.head_chunk == 64
    np.testing.assert_allclose(p1, p0, rtol=0, atol=1e-5)
    for k in t0.params:
        np.testing.assert_allclose(np.asarray(t1.params[k]),
                                   np.asarray(t0.params[k]),
                                   rtol=0, atol=1e-6, err_msg=k)


def test_head_chunk_distributed_parity(ds):
    """The distributed step carries head_chunk through _gctx: a P=2
    run with a forced chunk matches the unchunked P=2 run exactly."""
    def run(hc):
        cfg = TrainConfig(verbose=False, symmetric=True,
                          aggr_impl="flat_sum", dropout_rate=0.0,
                          head_chunk=hc)
        tr = DistributedTrainer(
            build_gcn([24, 16, 5], dropout_rate=0.0), ds, 2, cfg)
        tr.train(2)
        return np.asarray(tr.predict())
    p0 = run(0)
    p1 = run(32)
    np.testing.assert_allclose(p1, p0, rtol=0, atol=1e-5)


def test_head_chunk_compiles_scan_program(ds):
    """The chunked head really is a scan in the step: the chunked
    config's train-step jaxpr gains exactly the head's forward scan
    (the [block, H] @ [H, C] body) plus its grad-transpose scan
    (value_and_grad differentiates through lax.scan), while the
    unchunked segment-impl step contains no scans at all."""
    from test_programspace import _scan_shapes

    def shapes(hc):
        cfg = TrainConfig(verbose=False, symmetric=True,
                          aggr_impl="segment", dropout_rate=0.0,
                          head_chunk=hc)
        tr = Trainer(build_gcn([24, 16, 5], dropout_rate=0.0), ds,
                     cfg)
        lr = jnp.asarray(0.01, jnp.float32)
        return _scan_shapes(jax.make_jaxpr(tr._train_step._jit)(
            tr.params, tr.opt_state, tr.key, lr, tr.feats,
            tr.labels, tr.mask, tr.gctx))
    assert not shapes(0)
    assert len(shapes(64)) == 2
