"""Test configuration: force an 8-virtual-device CPU platform BEFORE jax
initializes, so sharding tests run anywhere (SURVEY.md §4 test plan)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test (interpret-mode kernels)")

# The axon sitecustomize can override JAX_PLATFORMS after env setup;
# force the CPU platform explicitly so the 8 virtual devices exist.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)
