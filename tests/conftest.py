"""Test configuration: force an 8-virtual-device CPU platform BEFORE jax
initializes, so sharding tests run anywhere (SURVEY.md §4 test plan)."""

import os
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"
# crash-flight-recorder dumps (obs/events.py) from in-process tests
# must never land in the repo root: pin the dump dir to a scratch
# location unless a test overrides it
os.environ.setdefault(
    "ROC_TPU_FLIGHT_DIR", tempfile.mkdtemp(prefix="roc_flight_"))
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test (interpret-mode kernels)")

# The axon sitecustomize can override JAX_PLATFORMS after env setup;
# force the CPU platform explicitly so the 8 virtual devices exist.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)
