"""Sharding & replication auditor — roc-lint level seven (ISSUE 14):
every rule fires on a synthetic violation, the propagation engine
keeps/loses splits where GSPMD would, the REAL tree audits clean
(findings baseline stays EMPTY), the replication budget ratchets
shrink-only through the CLI, and the mesh-portability report pins the
known full-width sites of both registered rigs."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from roc_tpu.analysis.programspace import Candidate
from roc_tpu.analysis.sharding_lint import (CANONICAL_SHAPE,
                                            Propagator, RigDims,
                                            SHARDING_RULES,
                                            audit_sharding,
                                            check_donation,
                                            check_plan_excess,
                                            check_replication_budget,
                                            findings_from_sites,
                                            ledger_entries,
                                            replicated_bytes,
                                            seed_leaf, union_ledger)
from roc_tpu.parallel import MODEL_AXIS, PARTS_AXIS

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_AX = {PARTS_AXIS: 2, MODEL_AXIS: 4}


def _prop(fn, in_specs, *args, scale=1):
    """Propagate one traced fn with explicit input specs; returns
    (out_specs, propagator)."""
    p = Propagator(_AX, scale)
    out = p.run(jax.make_jaxpr(fn)(*args), [tuple(s)
                                            for s in in_specs])
    return out, p


# ------------------------------------------------ propagation engine

def test_elementwise_and_dot_keep_model_split():
    """The dense path is mesh-agnostic: elementwise ops join specs,
    dot_general carries the rhs free-dim split to the output and
    consumes contracted splits without a site."""
    x = jnp.zeros((64, 48))
    w = jnp.zeros((48, 24))

    def fn(x, w):
        return jnp.tanh(x) @ w + 1.0

    out, p = _prop(fn, [(None, MODEL_AXIS), (None, MODEL_AXIS)],
                   x, w)
    # lhs contraction split consumed, rhs free dim keeps model
    assert out[0] == (None, MODEL_AXIS)
    assert p.sites == []


def test_unconstrained_op_is_caught():
    """THE acceptance fixture: a deliberately-unconstrained synthetic
    op (one the propagation model has no transfer rule for) kills the
    split — the exact GSPMD silent-re-gather failure mode — and the
    full-width-materialization rule reports it with op and bytes."""
    x = jnp.zeros((256, 48))

    def fn(x):
        return jnp.fft.fft(x).real.astype(jnp.float32)

    out, p = _prop(fn, [(PARTS_AXIS, MODEL_AXIS)], x,
                   scale=256 * 48 // 8)
    kinds = {(s.kind, s.op) for s in p.sites}
    assert ("unknown-op", "fft") in kinds, p.sites
    findings = findings_from_sites("rig", "step", p.sites)
    rules = {f.rule for f in findings}
    assert "full-width-materialization" in rules
    f = [x for x in findings
         if x.rule == "full-width-materialization"][0]
    assert "fft" in f.msg and f.unit == "sharding:rig:step"


def test_below_scale_sites_not_reported():
    x = jnp.zeros((8, 8))
    _, p = _prop(lambda x: jnp.fft.fft(x).real,
                 [(PARTS_AXIS, None)], x, scale=1 << 20)
    assert p.sites == []


def test_slice_and_gather_across_split_dim_fire():
    """Slicing a window of a split dim (the streamed-head block
    pattern) and gathering rows across a split dim both re-gather
    the operand."""
    x = jnp.zeros((256, 48))
    _, p = _prop(lambda x: x[:100], [(PARTS_AXIS, None)], x)
    assert any(s.kind == "full-width" and s.op == "slice"
               for s in p.sites), p.sites
    idx = jnp.zeros((7,), jnp.int32)
    _, p = _prop(lambda x, i: jnp.take(x, i, axis=0),
                 [(PARTS_AXIS, None), (None,)], x, idx)
    assert any(s.kind == "full-width" and s.op == "gather"
               for s in p.sites), p.sites
    # gather along an UNsplit dim inherits the operand's other splits
    out, p = _prop(lambda x, i: jnp.take(x, i, axis=0),
                   [(None, MODEL_AXIS), (None,)], x, idx)
    assert out[0] == (None, MODEL_AXIS)
    assert p.sites == []


def test_scatter_add_keeps_window_split():
    """The aggregation pattern: scatter-add of [E, F]-shaped updates
    into [V, F] zeros along V — the F split must survive (the window
    dims join), or every aggregation would be a false positive."""
    upd = jnp.zeros((512, 48))
    idx = jnp.zeros((512,), jnp.int32)

    def fn(upd, idx):
        return jnp.zeros((256, 48)).at[idx].add(upd)

    out, p = _prop(fn, [(None, MODEL_AXIS), (None,)], upd, idx)
    assert out[0] == (None, MODEL_AXIS)
    assert not any(s.kind == "full-width" for s in p.sites), p.sites


def test_reduce_and_transpose_and_reshape():
    x = jnp.zeros((256, 48))
    out, _ = _prop(lambda x: x.sum(axis=0),
                   [(PARTS_AXIS, MODEL_AXIS)], x)
    assert out[0] == (MODEL_AXIS,)
    out, _ = _prop(lambda x: x.T, [(PARTS_AXIS, MODEL_AXIS)], x)
    assert out[0] == (MODEL_AXIS, PARTS_AXIS)
    # merge keeps an outer-dim split on the merged dim; unmerging a
    # split dim loses it (and reports)
    y = jnp.zeros((2, 128, 48))
    out, p = _prop(lambda y: y.reshape(256, 48),
                   [(PARTS_AXIS, None, None)], y)
    assert out[0] == (PARTS_AXIS, None)
    assert p.sites == []


def test_scan_carries_specs_through_fixpoint():
    xs = jnp.zeros((8, 64, 48))

    def fn(xs):
        def body(c, x):
            return c + x, x.sum()
        return jax.lax.scan(body, jnp.zeros((64, 48)), xs)

    out, p = _prop(fn, [(None, None, MODEL_AXIS)], xs)
    assert out[0] == (None, MODEL_AXIS)     # carry keeps the split
    assert not any(s.kind == "full-width" for s in p.sites)


def test_sharding_constraint_seeds_and_conflict_fires():
    """with_sharding_constraint introduces live specs mid-graph (how
    the rules arm once the 2-D mesh work starts), and a constraint
    that contradicts the propagated spec is a sharding-mismatch."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    if len(jax.devices()) < 2:
        pytest.skip("needs the 8-virtual-device rig")
    mesh = Mesh(np.asarray(jax.devices()[:2]), (PARTS_AXIS,))
    sh = NamedSharding(mesh, P(PARTS_AXIS, None))
    sh2 = NamedSharding(mesh, P(None, PARTS_AXIS))
    x = jnp.zeros((256, 48))

    def fn(x):
        a = jax.lax.with_sharding_constraint(x, sh)
        return jax.lax.with_sharding_constraint(a, sh2)

    out, p = _prop(fn, [(None, None)], x)
    assert out[0] == (None, PARTS_AXIS)
    assert any(s.kind == "reshard" for s in p.sites), p.sites
    findings = findings_from_sites("rig", "s", p.sites)
    assert any(f.rule == "sharding-mismatch" for f in findings)


def test_shard_map_boundary_pins_are_sites():
    """An outer split the shard_map in_names don't name is an
    implicit all-gather at the boundary — the dist rigs' F-axis
    story."""
    from jax.sharding import Mesh, PartitionSpec as P
    if len(jax.devices()) < 2:
        pytest.skip("needs the 8-virtual-device rig")
    mesh = Mesh(np.asarray(jax.devices()[:2]), (PARTS_AXIS,))
    from roc_tpu.parallel.distributed import _shard_map
    x = jnp.zeros((2, 128, 48))
    fn = _shard_map(lambda x: x * 2.0, mesh, (P(PARTS_AXIS),),
                    P(PARTS_AXIS))
    # parts consumed by in_names: clean
    _, p = _prop(fn, [(PARTS_AXIS, None, None)], x)
    assert not p.sites
    # a model split the in_names don't know: boundary site
    _, p = _prop(fn, [(PARTS_AXIS, None, MODEL_AXIS)], x)
    assert any(s.kind == "boundary" for s in p.sites), p.sites


# -------------------------------------------------- rules (directly)

def test_replication_budget_rule():
    assert check_replication_budget("cfg", 100, None) == []
    assert check_replication_budget("cfg", 100, 100) == []
    got = check_replication_budget("cfg", 101, 100)
    assert len(got) == 1 and got[0].rule == "replication-budget"
    assert got[0].key == "over-budget"


def test_plan_excess_rule():
    assert check_plan_excess("cfg", 100, None) == []
    assert check_plan_excess("cfg", 100, 50) == []      # 2x < 4x
    got = check_plan_excess("cfg", 1000, 100)
    assert len(got) == 1 and got[0].key == "plan-excess"


def test_donation_under_sharding_fires_on_spec_mismatch():
    """A donated buffer whose only aval-matching output carries a
    different propagated sharding: the aliasing silently degrades to
    a copy."""
    x = jnp.zeros((256, 48))
    cand = Candidate(slot="s", fn=lambda x: x * 1.0, args=(x,),
                     donate=(0,), roles=("data",))
    jaxpr = jax.make_jaxpr(cand.fn)(x)
    got = check_donation("rig", cand, [(PARTS_AXIS, None)],
                         [(None, None)], jaxpr)
    assert len(got) == 1
    assert got[0].rule == "donation-under-sharding"
    # identical specs: clean
    assert check_donation("rig", cand, [(PARTS_AXIS, None)],
                          [(PARTS_AXIS, None)], jaxpr) == []
    # undonated candidate: out of scope
    cand2 = Candidate(slot="s", fn=lambda x: x * 1.0, args=(x,),
                      donate=(), roles=("data",))
    assert check_donation("rig", cand2, [(PARTS_AXIS, None)],
                          [(None, None)], jaxpr) == []


# ---------------------------------------------- seeding + the ledger

def test_seed_leaf_live_vs_simulation():
    dims = RigDims(vertex_sizes={256}, feat_sizes={48, 24},
                   parts_traced=2)
    # live: only the dist stacked dim carries parts
    assert seed_leaf((2, 136, 48), "data", dims, False) == \
        (PARTS_AXIS, None, None)
    assert seed_leaf((48, 24), "params", dims, False) == (None, None)
    # simulation: last feature dim gains model, one dim per axis
    assert seed_leaf((48, 24), "params", dims, True) == \
        (None, MODEL_AXIS)
    assert seed_leaf((2, 136, 48), "data", dims, True) == \
        (PARTS_AXIS, None, MODEL_AXIS)
    # params never take the stacked seed
    assert seed_leaf((2, 24), "params", dims, False) == (None, None)


def test_ledger_and_replicated_bytes():
    dims = RigDims(vertex_sizes={256}, feat_sizes={48},
                   parts_traced=1)
    x = jnp.zeros((256, 48), jnp.float32)     # vertex data
    w = jnp.zeros((48, 48), jnp.float32)      # params
    cand = Candidate(slot="s", fn=lambda a, b: a @ b, args=(x, w),
                     roles=("data", "params"))
    entries = ledger_entries(cand, dims, (2, 4))
    by_role = {e["role"]: e for e in entries}
    assert by_role["data"]["split"] == [PARTS_AXIS]
    assert by_role["data"]["replicated"] == [MODEL_AXIS]
    assert by_role["data"]["per_device_bytes"] == 256 * 48 * 4 // 2
    # params F-shard over model at rest (put_replicated); still
    # replicated over parts
    assert by_role["params"]["split"] == [MODEL_AXIS]
    assert by_role["params"]["replicated"] == [PARTS_AXIS]
    assert by_role["params"]["per_device_bytes"] == 48 * 48 * 4 // 4
    # every row is still replicated over SOME >1 axis here (data over
    # model, params over parts) -> all per-device bytes count
    assert replicated_bytes(entries) == sum(
        e["per_device_bytes"] for e in entries)
    # trivial mesh: nothing is "replicated" on one device
    assert replicated_bytes(ledger_entries(cand, dims, (1, 1))) == 0
    # union dedups the shared buffer across candidates
    assert len(union_ledger([entries, entries])) == len(entries)


# --------------------------------------- the real tree + portability

@pytest.fixture(scope="module")
def tree_audit():
    extras = {}
    findings = audit_sharding(extras=extras)
    return findings, {r["config"]: r for r in extras["sharding"]}


def test_tree_is_clean(tree_audit):
    """The live-semantics audit of the real tree: ZERO findings — the
    PR 3/6/12 convention, the baseline stays EMPTY."""
    findings, _ = tree_audit
    assert findings == [], [f.render() for f in findings]


def test_mesh_portability_golden_gin_flat8(tree_audit):
    """The migration worklist for the dist rig is exactly the
    shard_map boundary pinning params and features replicated over
    model — the F axis dies at the 1-D mesh's in-specs, nowhere
    inside the step body (the dense path is already mesh-agnostic)."""
    _, reports = tree_audit
    rep = reports["gin_flat8"]
    assert rep["parts"] == 2
    sites = [s for slot in rep["slots"] for s in slot["sites"]]
    assert {(s["kind"], s["op"]) for s in sites} == \
        {("boundary", "shard_map")}
    assert {tuple(s["lost"]) for s in sites} == {("model",)}
    shapes = {tuple(s["shape"]) for s in sites}
    assert shapes == {(48, 48), (2, 136, 48)}, shapes
    # modeled per-device bytes: the stacked feature block divides by
    # parts, and the report covers the three candidate 2-D shapes
    feat = [s for s in sites if tuple(s["shape"]) == (2, 136, 48)][0]
    for mesh in ("1x8", "2x4", "4x2"):
        assert mesh in feat["per_device_bytes"]
    assert feat["per_device_bytes"]["2x4"] == \
        feat["bytes"] // 2
    # every op INSIDE the step body kept its splits
    for slot in rep["slots"]:
        assert slot["mesh_agnostic_ops"] == slot["ops"], slot


def test_mesh_portability_golden_sgc_stream(tree_audit):
    """The streamed-head rig's traced programs are mesh-agnostic (no
    full-width sites — the [V, H] handoff is a ledger fact, not an op
    defect).  The [V, H] handoff (role ``stream``) now F-shards over
    model — the top reclaimed ledger row — while the [V, F] graph
    data stays model-replicated."""
    _, reports = tree_audit
    rep = reports["sgc_stream"]
    assert [s for slot in rep["slots"] for s in slot["sites"]] == []
    big = [e for e in rep["ledger"]
           if e["shape"] and e["shape"][0] == 256]
    assert big, rep["ledger"]
    stream = [e for e in big if e["role"] == "stream"]
    assert stream, big
    assert all(MODEL_AXIS in e["split"] and
               MODEL_AXIS not in e["replicated"] for e in stream)
    rest = [e for e in big if e["role"] != "stream"]
    assert all(MODEL_AXIS in e["replicated"] for e in rest)
    # modeled per-device HBM shrinks as the model axis widens — the
    # quantitative case for feature sharding
    per_dev = {(m["parts"], m["model"]): m["per_device_bytes"]
               for m in rep["mesh_shapes"]}
    assert per_dev[(1, 8)] < per_dev[(2, 4)] < per_dev[(8, 1)]


def test_reports_cover_all_rigs_and_budget(tree_audit):
    _, reports = tree_audit
    assert set(reports) == {"gin_flat8", "sgc_stream", "sgc_serve",
                            "sgc_serve_q8", "gin_mesh2d"}
    from roc_tpu.analysis.findings import load_budget
    budget = load_budget(os.path.join(_REPO, "scripts",
                                      "lint_baseline.json"),
                         "replication_budget")
    for name, rep in reports.items():
        assert rep["replicated_bytes"] > 0
        assert rep["canonical_shape"] == list(CANONICAL_SHAPE)
        # the checked-in ratchet matches the measurement exactly
        # (delta 0): a drift here means replication grew (fix it) or
        # shrank (commit the shrink via --update-baseline)
        assert budget[name] == rep["replicated_bytes"], name


def test_rules_registered():
    from roc_tpu.analysis.driver import all_rule_names, is_trace_rule
    names = set(all_rule_names())
    for r in SHARDING_RULES:
        assert r in names, r
        assert is_trace_rule(r), r


def test_sharding_events_emitted():
    from roc_tpu.obs.events import CATEGORIES, get_bus

    class _Cap:
        def __init__(self):
            self.recs = []

        def write(self, rec):
            self.recs.append(rec)

        def close(self):
            pass

    assert "sharding" in CATEGORIES
    cap = _Cap()
    bus = get_bus()
    bus.add_sink(cap)
    try:
        audit_sharding()
    finally:
        bus.sinks.remove(cap)
    got = [r for r in cap.recs if r.get("cat") == "sharding"]
    assert {r["config"] for r in got} == \
        {"gin_flat8", "sgc_stream", "sgc_serve", "sgc_serve_q8",
         "gin_mesh2d"}
    for r in got:
        assert "replicated_bytes" in r and "mesh_shapes" in r


# ------------------------------------------------- CLI ratchet + JSON

def _run_cli(args, timeout=240):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "roc_tpu.analysis"] + args,
        cwd=_REPO, capture_output=True, text=True, timeout=timeout,
        env=env)


def test_cli_ratchet_bites_and_never_absorbs(tmp_path):
    """A replication_budget below the measurement fires the rule
    (exit 1), and --update-baseline does NOT absorb the finding —
    min(stored, measured) can only shrink; clearing the finding means
    fixing the replication or hand-editing the JSON."""
    bp = tmp_path / "baseline.json"
    bp.write_text(json.dumps(
        {"version": 1, "findings": [],
         "replication_budget": {"gin_flat8": 1, "sgc_stream": 1,
                                "sgc_serve": 1}}))
    r = _run_cli(["--baseline", str(bp), "--select",
                  "replication-budget"])
    assert r.returncode == 1, r.stdout + r.stderr
    assert "replication-budget" in r.stdout
    assert "exceed the baselined bound 1" in r.stdout
    # the ratchet can only shrink: --update-baseline keeps the bound
    # at 1 and the findings stay un-absorbed (still exit 1)
    r2 = _run_cli(["--baseline", str(bp), "--select",
                   "replication-budget", "--update-baseline"])
    assert r2.returncode == 1, r2.stdout + r2.stderr
    data = json.loads(bp.read_text())
    assert data["replication_budget"]["gin_flat8"] == 1
    assert data["findings"] == []


def test_cli_strict_fails_on_replication_slack_and_unbounded(tmp_path):
    """Slack (measured < bound) and a missing bound both fail
    --strict until --update-baseline commits the shrink /
    initializes — the program_budget semantics, verbatim."""
    bp = tmp_path / "baseline.json"
    bp.write_text(json.dumps({"version": 1, "findings": []}))
    args = ["--baseline", str(bp), "--select", "sharding"]
    r = _run_cli(args + ["--strict"])
    assert r.returncode == 1, r.stdout + r.stderr
    assert "no replication_budget bound" in r.stdout
    r2 = _run_cli(args)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    r3 = _run_cli(args + ["--strict", "--update-baseline"])
    assert r3.returncode == 0, r3.stdout + r3.stderr
    budget = json.loads(bp.read_text())["replication_budget"]
    assert set(budget) == {"gin_flat8", "sgc_stream", "sgc_serve",
                           "sgc_serve_q8", "gin_mesh2d"}
    # slack now: inflate one bound by hand
    budget2 = dict(budget, gin_flat8=budget["gin_flat8"] + 5)
    bp.write_text(json.dumps({"version": 1, "findings": [],
                              "replication_budget": budget2}))
    r4 = _run_cli(args + ["--strict"])
    assert r4.returncode == 1, r4.stdout + r4.stderr
    assert "above the measured bytes" in r4.stdout
    # an orphan bound (renamed rig) fails strict and drops on update
    budget3 = dict(budget, ghost_rig=123)
    bp.write_text(json.dumps({"version": 1, "findings": [],
                              "replication_budget": budget3}))
    r5 = _run_cli(args + ["--strict"])
    assert r5.returncode == 1, r5.stdout + r5.stderr
    assert "unknown rig config" in r5.stdout
    r6 = _run_cli(args + ["--strict", "--update-baseline"])
    assert r6.returncode == 0, r6.stdout + r6.stderr
    assert "ghost_rig" not in \
        json.loads(bp.read_text())["replication_budget"]


def test_cli_json_carries_ledger_and_sites():
    """--json: the sharding reports ride the payload — findings,
    ledger, sites, mesh shapes — so CI and the report renderer share
    one machine-readable artifact."""
    r = _run_cli(["--json", "--select", "sharding"])
    assert r.returncode == 0, r.stdout + r.stderr
    payload = json.loads(r.stdout)
    reports = {p["config"]: p for p in payload["sharding"]}
    assert set(reports) == {"gin_flat8", "sgc_stream", "sgc_serve",
                            "sgc_serve_q8", "gin_mesh2d"}
    rep = reports["gin_flat8"]
    assert rep["delta"] == 0
    assert rep["ledger"] and rep["mesh_shapes"]
    assert all("per_device_bytes" in e for e in rep["ledger"])
    assert payload["summary"]["replication_unbounded"] == 0


def test_report_sharding_renders():
    """`python -m roc_tpu.report --sharding <file>` renders the
    mesh-portability tables from the --json payload (the acceptance
    path; the no-arg live mode runs the same renderer)."""
    r = _run_cli(["--json", "--select", "sharding"])
    assert r.returncode == 0, r.stderr
    payload_path = os.path.join(_REPO, "benchmarks",
                                "_test_shard_payload.json")
    with open(payload_path, "w") as f:
        f.write(r.stdout)
    try:
        env = dict(os.environ)
        env["PYTHONPATH"] = (_REPO + os.pathsep
                             + env.get("PYTHONPATH", ""))
        r2 = subprocess.run(
            [sys.executable, "-m", "roc_tpu.report", "--sharding",
             payload_path],
            cwd=_REPO, capture_output=True, text=True, timeout=120,
            env=env)
        assert r2.returncode == 0, r2.stdout + r2.stderr
        for needle in ("== sharding gin_flat8", "== sharding gin_mesh2d",
                       "1x8", "2x4", "4x2",
                       "full-width-materialization sites",
                       "replication ledger", "shard_map"):
            assert needle in r2.stdout, (needle, r2.stdout[-2000:])
        # the 2-D-mesh golden: params / opt-state / the streamed-head
        # handoff have LEFT the model-replicated ledger — split over
        # 'model', replicated only over 'parts' — in the payload, and
        # the stream row renders that way in the ledger table
        payload = json.loads(r.stdout)
        stream_rep = next(p for p in payload["sharding"]
                          if p["config"] == "sgc_stream")
        moved = {e["role"] for e in stream_rep["ledger"]
                 if "model" in e["split"]
                 and "model" not in e["replicated"]}
        assert {"params", "opt_state", "stream"} <= moved, moved
        assert any(ln.strip().startswith("stream ") and "model" in ln
                   for ln in r2.stdout.splitlines()), \
            r2.stdout[-2000:]
        # an explicitly-passed payload renders even when event files
        # are ALSO given (after the event summary)
        ev_path = os.path.join(_REPO, "benchmarks",
                               "_test_shard_ev.jsonl")
        with open(ev_path, "w") as f:
            f.write(json.dumps({"t": 1.0, "cat": "run",
                                "msg": "x"}) + "\n")
        try:
            r3 = subprocess.run(
                [sys.executable, "-m", "roc_tpu.report", ev_path,
                 "--sharding", payload_path],
                cwd=_REPO, capture_output=True, text=True,
                timeout=120, env=env)
            assert r3.returncode == 0, r3.stdout + r3.stderr
            assert "run manifest" in r3.stdout
            assert "== sharding gin_flat8" in r3.stdout
        finally:
            os.remove(ev_path)
    finally:
        os.remove(payload_path)
