"""Out-of-core streaming tests (core/streaming.py) vs in-memory paths."""

import numpy as np
import pytest

import jax.numpy as jnp

from roc_tpu.core.graph import add_self_edges, synthetic_graph
from roc_tpu.core.partition import padded_edge_list
from roc_tpu.core.streaming import StreamingAggregator, streamed_linear
from roc_tpu.ops.aggregate import aggregate_segment


@pytest.fixture(scope="module")
def graph():
    return add_self_edges(synthetic_graph(300, 7, seed=5, power_law=True))


def test_streamed_linear_matches_dense():
    rng = np.random.RandomState(0)
    X = rng.randn(1000, 24).astype(np.float32)
    W = jnp.asarray(rng.randn(24, 8).astype(np.float32))
    got = streamed_linear(X, W, block_rows=128)
    np.testing.assert_allclose(np.asarray(got), X @ np.asarray(W),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("block_rows,edge_chunk", [(64, 128), (97, 1 << 20)])
def test_streaming_aggregator_matches_segment(graph, block_rows,
                                              edge_chunk):
    rng = np.random.RandomState(1)
    feats = rng.randn(graph.num_nodes, 9).astype(np.float32)
    agg = StreamingAggregator(graph, block_rows=block_rows,
                              edge_chunk=edge_chunk)
    got = agg(feats)
    src, dst = padded_edge_list(graph, multiple=64)
    x = jnp.concatenate([jnp.asarray(feats), jnp.zeros((1, 9))], axis=0)
    want = aggregate_segment(x, jnp.asarray(src), jnp.asarray(dst),
                             graph.num_nodes)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_streaming_aggregator_static_plan_reuse(graph):
    """The edge plan is static: two calls with different features must
    both be exact (no state corruption across calls)."""
    rng = np.random.RandomState(2)
    agg = StreamingAggregator(graph, block_rows=50)
    for seed in (0, 1):
        feats = np.random.RandomState(seed).randn(
            graph.num_nodes, 4).astype(np.float32)
        got = agg(feats)
        src, dst = padded_edge_list(graph, multiple=64)
        x = jnp.concatenate([jnp.asarray(feats), jnp.zeros((1, 4))],
                            axis=0)
        want = aggregate_segment(x, jnp.asarray(src), jnp.asarray(dst),
                                 graph.num_nodes)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)


# ---- StreamedHead: the integrated features="host" training tier ----

import jax

from roc_tpu.core.graph import synthetic_dataset
from roc_tpu.core.memory import choose_memory_plan, estimate_plan_bytes
from roc_tpu.core.streaming import StreamedHead
from roc_tpu.models.gcn import build_gcn
from roc_tpu.models.gin import build_gin
from roc_tpu.train.trainer import TrainConfig, Trainer


def test_streamed_head_eval_matches_dense():
    """Eval mode (no dropout) must match X @ W exactly, across the
    block boundary."""
    rng = np.random.RandomState(0)
    X = rng.randn(300, 24).astype(np.float32)
    W = jnp.asarray(rng.randn(24, 8).astype(np.float32))
    head = StreamedHead(rate=0.5, block_rows=128)
    got = head.forward(W, X, key=None, train=False)
    np.testing.assert_allclose(np.asarray(got), X @ np.asarray(W),
                               rtol=1e-5, atol=1e-5)


def test_streamed_head_wgrad_matches_autodiff():
    """wgrad must equal jax.grad of the identical streamed forward
    (same per-block dropout keys)."""
    rng = np.random.RandomState(1)
    X = rng.randn(200, 12).astype(np.float32)
    W = jnp.asarray(rng.randn(12, 6).astype(np.float32))
    dY = jnp.asarray(rng.randn(200, 6).astype(np.float32))
    head = StreamedHead(rate=0.4, block_rows=64)
    key = jax.random.PRNGKey(3)

    def scalar(w):
        return jnp.sum(head.forward(w, X, key, True) * dY)

    want = jax.grad(scalar)(W)
    got = head.wgrad(X, dY, key, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_streamable_head_detection():
    assert build_gcn([16, 8, 4]).streamable_head() is not None
    # GIN aggregates raw features -> dropout output has two consumers
    assert build_gin([16, 8, 4]).streamable_head() is None
    # deep GCN residual consumes the first dropout output twice
    assert build_gcn([16, 8, 8, 8, 4]).streamable_head() is None
    # a fused activation on the head linear would be silently dropped
    # by the streamed projection -> must be rejected
    from roc_tpu.models.builder import Model
    from roc_tpu.ops.dense import AC_MODE_RELU
    m = Model(in_dim=16)
    t = m.input()
    t = m.dropout(t, 0.5)
    t = m.linear(t, 8, AC_MODE_RELU)
    t = m.scatter_gather(t)
    m.softmax_cross_entropy(t)
    assert m.streamable_head() is None


def test_streamable_head_tail_matches_full_apply():
    """head.forward + tail.apply == model.apply (rate irrelevant in
    eval mode)."""
    from roc_tpu.train.trainer import make_graph_context
    ds = synthetic_dataset(120, 5, in_dim=16, num_classes=4, seed=0)
    model = build_gcn([16, 8, 4], dropout_rate=0.5)
    rate, pname, tail = model.streamable_head()
    assert rate == 0.5 and pname == "linear_0"
    gctx = make_graph_context(ds, "segment")
    params = model.init_params(jax.random.PRNGKey(0))
    feats = jnp.asarray(ds.features)
    want = model.apply(params, feats, gctx, key=None, train=False)
    head = StreamedHead(rate, block_rows=50)
    y = head.forward(params[pname], np.asarray(ds.features), None, False)
    got = tail.apply(params, y, gctx, key=None, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_host_features_training_matches_hbm_when_no_dropout():
    """With rate=0 the host-streamed path has no RNG divergence from
    the in-HBM path: parameters must match after several steps."""
    ds = synthetic_dataset(150, 5, in_dim=12, num_classes=3, seed=1)
    kw = dict(learning_rate=0.05, eval_every=1 << 30, verbose=False,
              epochs=3, symmetric=True)
    m1 = build_gcn([12, 8, 3], dropout_rate=0.0)
    t1 = Trainer(m1, ds, TrainConfig(features="hbm", **kw))
    t1.train()
    m2 = build_gcn([12, 8, 3], dropout_rate=0.0)
    t2 = Trainer(m2, ds, TrainConfig(features="host", **kw))
    t2.train()
    for k in t1.params:
        np.testing.assert_allclose(np.asarray(t1.params[k]),
                                   np.asarray(t2.params[k]),
                                   rtol=2e-4, atol=2e-4)


def test_host_features_converges_with_dropout():
    """The streamed path is a real training path: accuracy on an easy
    synthetic dataset must clear chance by a wide margin."""
    ds = synthetic_dataset(200, 6, in_dim=16, num_classes=4, seed=2)
    model = build_gcn([16, 16, 4], dropout_rate=0.3)
    cfg = TrainConfig(learning_rate=0.05, features="host", epochs=60,
                      eval_every=1 << 30, verbose=False, symmetric=True)
    tr = Trainer(model, ds, cfg)
    tr.train()
    m = tr.evaluate()
    assert m["train_acc"] > 0.6, m


# ---- pipelined execution: staging pool + prefetch parity ----

import functools

from roc_tpu.core.streaming import StagingPool


def test_staging_pool_order_stats_and_errors():
    pool = StagingPool(depth=2)
    got = list(pool.stream([(lambda i=i: i * 10) for i in range(7)]))
    assert got == [0, 10, 20, 30, 40, 50, 60]
    s = pool.take_stats()
    assert s["n"] == 7 and len(s["stage_ms"]) == 7
    # a second take sees only new work
    assert pool.take_stats()["n"] == 0

    def boom():
        raise RuntimeError("stage died")
    with pytest.raises(RuntimeError, match="stage died"):
        list(StagingPool(depth=1).stream([boom]))


def test_staging_pool_caps_live_buffers_at_depth_plus_one():
    """The 2-slot invariant: however many blocks V splits into (and
    across reuse passes), a depth-1 pool never holds more than 2 live
    staged buffers — and the worker never runs more than depth stages
    ahead of the consumer."""
    pool = StagingPool(depth=1)
    for _ in range(3):          # reused pool: the bound must not leak
        staged, taken = [], []

        def mk(i):
            def f():
                staged.append(i)
                return i
            return f
        for v in pool.stream([mk(i) for i in range(16)]):
            taken.append(v)
            # credits bound the run-ahead: staged <= taken + depth
            assert len(staged) <= len(taken) + pool.depth
    assert pool.max_live <= 2
    # synchronous pools hold exactly one
    p0 = StagingPool(depth=0)
    assert list(p0.stream([lambda: 1, lambda: 2])) == [1, 2]
    assert p0.max_live == 1


def test_streamed_head_pool_live_bound_many_blocks():
    """End-to-end: fwd + wgrad over many blocks and repeated epochs
    keep peak live block buffers <= 2 (the ISSUE's staging-pool
    acceptance), independent of V."""
    rng = np.random.RandomState(0)
    X = rng.randn(640, 12).astype(np.float32)   # 10 blocks of 64
    W = jnp.asarray(rng.randn(12, 6).astype(np.float32))
    dY = jnp.asarray(rng.randn(640, 6).astype(np.float32))
    head = StreamedHead(0.3, block_rows=64, prefetch=1)
    key = jax.random.PRNGKey(1)
    for _ in range(3):
        head.forward(W, X, key, True)
        head.wgrad(X, dY, key, True)
    assert head.pool.max_live <= 2


@pytest.mark.parametrize("key_mode", ["none", "dropout"])
def test_prefetched_streaming_bitexact_vs_synchronous(key_mode):
    """The parity gate: prefetch=0 (synchronous) and prefetch>=1
    (background staging) produce BIT-IDENTICAL fwd + wgrad — the
    per-block fold_in keys are position-derived, never order-derived,
    and staging moves bytes, not math."""
    rng = np.random.RandomState(2)
    X = rng.randn(330, 12).astype(np.float32)   # uneven tail block
    W = jnp.asarray(rng.randn(12, 6).astype(np.float32))
    dY = jnp.asarray(rng.randn(330, 6).astype(np.float32))
    key = None if key_mode == "none" else jax.random.PRNGKey(3)
    train = key is not None
    outs = {}
    for depth in (0, 1, 2):
        head = StreamedHead(0.4, block_rows=64, prefetch=depth)
        outs[depth] = (np.asarray(head.forward(W, X, key, train)),
                       np.asarray(head.wgrad(X, dY, key, train)))
    for depth in (1, 2):
        np.testing.assert_array_equal(outs[0][0], outs[depth][0])
        np.testing.assert_array_equal(outs[0][1], outs[depth][1])


def test_streaming_aggregator_prefetch_bitexact(graph):
    rng = np.random.RandomState(4)
    feats = rng.randn(graph.num_nodes, 6).astype(np.float32)
    a0 = StreamingAggregator(graph, block_rows=50, prefetch=0)
    a1 = StreamingAggregator(graph, block_rows=50, prefetch=1)
    np.testing.assert_array_equal(np.asarray(a0(feats)),
                                  np.asarray(a1(feats)))


def test_streaming_aggregator_index_tables_device_resident(graph):
    """The per-plan int32 tables are uploaded ONCE at plan build (the
    satellite fix for jnp.asarray re-uploading them in the hot loop):
    the cached device chunks must be the same objects across calls."""
    agg = StreamingAggregator(graph, block_rows=64, edge_chunk=128)
    before = [id(c[0]) for p in agg.plans
              for c in p.dev_chunks(agg.edge_chunk)]
    feats = np.random.RandomState(5).randn(
        graph.num_nodes, 4).astype(np.float32)
    agg(feats)
    agg(feats)
    after = [id(c[0]) for p in agg.plans
              for c in p.dev_chunks(agg.edge_chunk)]
    assert before == after and len(before) > 0


def test_streaming_aggregator_table_budget_falls_back_transient(graph):
    """Past the table residency budget the aggregator must NOT pin
    O(E) index bytes on device (that would defeat the out-of-core
    tier): uploads become transient per call, results identical."""
    rng = np.random.RandomState(8)
    feats = rng.randn(graph.num_nodes, 5).astype(np.float32)
    cached = StreamingAggregator(graph, block_rows=64)
    assert cached.cache_tables
    tight = StreamingAggregator(graph, block_rows=64,
                                table_cache_bytes=16)
    assert not tight.cache_tables
    got = tight(feats)
    assert all(not p._dev for p in tight.plans)   # nothing pinned
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(cached(feats)))


def test_aggregate_to_host_prefetch_matches_sync():
    from roc_tpu.core.streaming import aggregate_to_host
    ds = synthetic_dataset(200, 7, in_dim=9, num_classes=3, seed=3)
    x = np.random.RandomState(6).randn(
        ds.graph.num_nodes, 9).astype(np.float32)
    got0 = aggregate_to_host(ds.graph, x, block_rows=32,
                             edge_chunk=64, prefetch=0)
    got1 = aggregate_to_host(ds.graph, x, block_rows=32,
                             edge_chunk=64, prefetch=1)
    np.testing.assert_array_equal(got0, got1)


def test_streamed_tier_epoch_records_carry_pipeline_fields():
    """Epoch records on the streamed tier report overlap_frac,
    h2d_wait_p50_ms and prefetch_depth; the synchronous path reports
    overlap_frac == 0 by construction."""
    ds = synthetic_dataset(200, 5, in_dim=12, num_classes=3, seed=4)
    recs = {}
    for depth in (0, 1):
        model = build_gcn([12, 8, 3], dropout_rate=0.2)
        cfg = TrainConfig(learning_rate=0.05, features="host",
                          prefetch=depth, epochs=2, eval_every=2,
                          verbose=False, symmetric=True)
        recs[depth] = Trainer(model, ds, cfg).train()
    for depth, hist in recs.items():
        assert hist, hist
        m = hist[-1]
        assert m["prefetch_depth"] == depth
        assert "h2d_wait_p50_ms" in m and "overlap_frac" in m
    assert recs[0][-1]["overlap_frac"] == 0.0


def test_resolve_prefetch():
    from roc_tpu.train.trainer import resolve_prefetch
    assert resolve_prefetch(TrainConfig()) == 1            # auto
    assert resolve_prefetch(TrainConfig(prefetch=0)) == 0
    assert resolve_prefetch(TrainConfig(prefetch="3")) == 3
    with pytest.raises(ValueError):
        resolve_prefetch(TrainConfig(prefetch=-1))
    with pytest.raises(ValueError):
        resolve_prefetch(TrainConfig(prefetch="fast"))


# ---- memory autopilot ----

def test_choose_memory_plan_tiers():
    dims = [602, 256, 41]
    # small graph, generous budget -> plain gather/hbm
    p = choose_memory_plan(10_000, 100_000, dims, num_parts=1,
                           hbm_bytes=1 << 34)
    assert (p.halo, p.features, p.remat) == ("gather", "hbm", False)
    assert p.fits
    # single device, tiny budget -> host streaming
    p = choose_memory_plan(500_000, 10_000_000, dims, num_parts=1,
                           hbm_bytes=200 << 20)
    assert p.features == "host"
    # multi-device, budget that kills the gathered matrix -> ring
    p = choose_memory_plan(4_000_000, 60_000_000, dims, num_parts=8,
                           hbm_bytes=1 << 30)
    assert p.halo == "ring"
    # estimates are monotone in the obvious ways
    assert (estimate_plan_bytes(10**6, 10**7, dims, remat=True)
            < estimate_plan_bytes(10**6, 10**7, dims, remat=False))
    assert (estimate_plan_bytes(10**6, 10**7, dims, num_parts=8,
                                halo="ring")
            < estimate_plan_bytes(10**6, 10**7, dims, num_parts=8,
                                  halo="gather"))
    # impl-resident tables (the bdense A-budget) are charged: the same
    # config that fits plain flips to remat once the A-table bytes
    # are on the books
    base = estimate_plan_bytes(10**6, 10**7, dims)
    assert estimate_plan_bytes(
        10**6, 10**7, dims, extra_table_bytes=2 << 30) \
        == base + (2 << 30)
    p_no = choose_memory_plan(232_965, 114_848_857, dims,
                              hbm_bytes=6 << 30)
    p_bd = choose_memory_plan(232_965, 114_848_857, dims,
                              hbm_bytes=6 << 30,
                              extra_table_bytes=4 << 30)
    assert not p_no.remat and p_bd.remat
    # ring candidates are never charged (ring runs build no A-table):
    # same A-charge, multi-part, budget that only ring can meet
    p_ring = choose_memory_plan(4_000_000, 60_000_000, dims,
                                num_parts=8, hbm_bytes=1 << 30,
                                extra_table_bytes=4 << 30)
    assert p_ring.halo == "ring"
    assert p_ring.candidates["ring/hbm"] == \
        choose_memory_plan(4_000_000, 60_000_000, dims, num_parts=8,
                           hbm_bytes=1 << 30).candidates["ring/hbm"]


def test_autopilot_trains_oversized_graph_without_flags():
    """VERDICT r2 task 3 'done' criterion: a graph sized past the
    gather budget trains via streaming with no user flags beyond
    memory='auto' (tiny synthetic budget stands in for a huge graph)."""
    ds = synthetic_dataset(300, 5, in_dim=16, num_classes=4, seed=3)
    model = build_gcn([16, 8, 4], dropout_rate=0.2)
    cfg = TrainConfig(learning_rate=0.05, memory="auto",
                      hbm_bytes=40_000,  # far below the gather footprint
                      epochs=3, eval_every=1 << 30, verbose=False,
                      symmetric=True)
    tr = Trainer(model, ds, cfg)
    assert tr.config.features == "host"  # the plan, not the user, chose
    assert tr._head is not None
    tr.train()
    assert np.isfinite(tr.evaluate()["train_loss"])


def test_autopilot_picks_ring_for_distributed():
    """A budget the gathered global matrix busts (even with remat) but
    the ring fits: the plan must choose ring with no user flags."""
    from roc_tpu.parallel.distributed import DistributedTrainer
    ds = synthetic_dataset(64 * 64, 5, in_dim=8, num_classes=3, seed=4)
    model = build_gcn([8, 64, 3], dropout_rate=0.0)
    cfg = TrainConfig(memory="auto", hbm_bytes=1_500_000, epochs=1,
                      eval_every=1 << 30, verbose=False, symmetric=True,
                      aggr_impl="blocked", chunk=64)
    tr = DistributedTrainer(model, ds, 4, cfg)
    assert tr.config.halo == "ring"
    tr.train(epochs=1)
    assert np.isfinite(tr.evaluate()["train_loss"])


# ------------------------------------------------- full out-of-core tier

def test_aggregate_to_host_matches_device(  ):
    """The fully-host-resident block SpMM == the in-HBM segment sum."""
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.core.partition import padded_edge_list
    from roc_tpu.core.streaming import aggregate_to_host
    from roc_tpu.ops.aggregate import aggregate_segment

    ds = synthetic_dataset(200, 7, in_dim=9, num_classes=3, seed=3)
    g = ds.graph
    rng = np.random.RandomState(0)
    x = rng.randn(g.num_nodes, 9).astype(np.float32)
    # tiny blocks: many (dst, src) tiles, several per dst block
    got = aggregate_to_host(g, x, block_rows=32, edge_chunk=64)
    xp = np.concatenate([x, np.zeros((1, 9), np.float32)])
    src, dst = padded_edge_list(g, multiple=16)
    want = np.asarray(aggregate_segment(
        jnp.asarray(xp), jnp.asarray(src), jnp.asarray(dst),
        g.num_nodes))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_sgc_streamable_agg_head_detected():
    from roc_tpu.models.sgc import build_sgc
    m = build_sgc([9, 3], k=2, dropout_rate=0.3)
    assert m.streamable_head() is None        # head aggregates first
    got = m.streamable_agg_head()
    assert got is not None
    prefix, rate, param, tail = got
    assert [op.kind for op in prefix] == [
        "indegree_norm", "scatter_gather", "indegree_norm"] * 2
    assert rate == 0.3 and param == "linear_0"
    # classic SGC: the head linear IS the classifier; tail is loss-only
    assert all(op.kind == "input" for op in tail._ops)
    # GCN's head is linear-first: the agg-head detector must decline
    from roc_tpu.models.gcn import build_gcn
    assert build_gcn([9, 8, 3]).streamable_agg_head() is None


def test_sgc_host_tier_matches_in_hbm():
    """features='host' SGC (out-of-core S^k X precompute + streamed
    head) must match the in-HBM SGC trainer: exact eval parity at
    init, numerically-close training."""
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.sgc import build_sgc
    from roc_tpu.train.trainer import TrainConfig, Trainer

    ds = synthetic_dataset(300, 6, in_dim=12, num_classes=4, seed=1)
    kw = dict(verbose=False, eval_every=1 << 30, learning_rate=0.2,
              symmetric=True)
    model = build_sgc([12, 4], k=2, dropout_rate=0.0)
    th = Trainer(model, ds, TrainConfig(features="host", **kw))
    td = Trainer(model, ds, TrainConfig(**kw))
    assert th.feats is None                  # never device-resident
    mh_, md_ = th.evaluate(), td.evaluate()
    np.testing.assert_allclose(mh_["train_loss"], md_["train_loss"],
                               rtol=1e-4)
    th.train(epochs=30)
    td.train(epochs=30)
    # same convergence; dropout=0 keeps the paths numerically aligned
    np.testing.assert_allclose(
        th.evaluate()["train_acc"], td.evaluate()["train_acc"],
        atol=0.05)
    assert th.evaluate()["train_acc"] > 0.9


def test_autopilot_selects_host_tier_for_sgc_over_budget():
    """A budget smaller than the feature matrix must route an SGC
    model to the host tier (VERDICT r4 weak #7: the out-of-core
    aggregator is now a plan the autopilot can SELECT, not shelf-ware)."""
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.sgc import build_sgc
    from roc_tpu.train.trainer import TrainConfig, Trainer

    ds = synthetic_dataset(4096, 6, in_dim=64, num_classes=4, seed=2)
    model = build_sgc([64, 4], k=1, dropout_rate=0.0)
    # 3 MB budget: [4096, 64] fp32 feats alone exceed 1 MB + tables
    tr = Trainer(model, ds, TrainConfig(
        verbose=False, eval_every=1 << 30, memory="auto",
        hbm_bytes=3 << 20))
    assert tr.config.features == "host"
    assert tr.feats is None
    tr.train(epochs=2)
    assert np.isfinite(tr.evaluate()["train_loss"])
