"""Out-of-core streaming tests (core/streaming.py) vs in-memory paths."""

import numpy as np
import pytest

import jax.numpy as jnp

from roc_tpu.core.graph import add_self_edges, synthetic_graph
from roc_tpu.core.partition import padded_edge_list
from roc_tpu.core.streaming import StreamingAggregator, streamed_linear
from roc_tpu.ops.aggregate import aggregate_segment


@pytest.fixture(scope="module")
def graph():
    return add_self_edges(synthetic_graph(300, 7, seed=5, power_law=True))


def test_streamed_linear_matches_dense():
    rng = np.random.RandomState(0)
    X = rng.randn(1000, 24).astype(np.float32)
    W = jnp.asarray(rng.randn(24, 8).astype(np.float32))
    got = streamed_linear(X, W, block_rows=128)
    np.testing.assert_allclose(np.asarray(got), X @ np.asarray(W),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("block_rows,edge_chunk", [(64, 128), (97, 1 << 20)])
def test_streaming_aggregator_matches_segment(graph, block_rows,
                                              edge_chunk):
    rng = np.random.RandomState(1)
    feats = rng.randn(graph.num_nodes, 9).astype(np.float32)
    agg = StreamingAggregator(graph, block_rows=block_rows,
                              edge_chunk=edge_chunk)
    got = agg(feats)
    src, dst = padded_edge_list(graph, multiple=64)
    x = jnp.concatenate([jnp.asarray(feats), jnp.zeros((1, 9))], axis=0)
    want = aggregate_segment(x, jnp.asarray(src), jnp.asarray(dst),
                             graph.num_nodes)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_streaming_aggregator_static_plan_reuse(graph):
    """The edge plan is static: two calls with different features must
    both be exact (no state corruption across calls)."""
    rng = np.random.RandomState(2)
    agg = StreamingAggregator(graph, block_rows=50)
    for seed in (0, 1):
        feats = np.random.RandomState(seed).randn(
            graph.num_nodes, 4).astype(np.float32)
        got = agg(feats)
        src, dst = padded_edge_list(graph, multiple=64)
        x = jnp.concatenate([jnp.asarray(feats), jnp.zeros((1, 4))],
                            axis=0)
        want = aggregate_segment(x, jnp.asarray(src), jnp.asarray(dst),
                                 graph.num_nodes)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)
