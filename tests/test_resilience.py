"""Failure detection + checkpoint-restart recovery tests."""

import math

import numpy as np
import pytest

from roc_tpu.core.graph import synthetic_dataset
from roc_tpu.models.gcn import build_gcn
from roc_tpu.train.trainer import TrainConfig, Trainer
from roc_tpu.utils.resilience import (CheckpointRotation, NumericFailure,
                                      check_finite, train_with_recovery)


@pytest.fixture()
def trainer():
    ds = synthetic_dataset(64, 6, in_dim=8, num_classes=3, seed=0)
    cfg = TrainConfig(epochs=100, eval_every=2, verbose=False,
                      symmetric=True)
    return Trainer(build_gcn([8, 8, 3]), ds, cfg)


def test_check_finite():
    check_finite({"train_loss": 1.0, "epoch": 3})
    with pytest.raises(NumericFailure):
        check_finite({"train_loss": float("nan"), "epoch": 3})
    with pytest.raises(NumericFailure):
        check_finite({"train_loss": float("inf"), "epoch": 3})


def test_rotation_keeps_last_k(trainer, tmp_path):
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=2)
    for _ in range(4):
        trainer.train(epochs=1)
        rot.save(trainer)
    assert rot.existing() == [3, 4]


def test_recovery_resumes_after_crash(trainer, tmp_path):
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=2)
    train_with_recovery(trainer, 6, rot, checkpoint_every=3)
    assert trainer.epoch == 6
    # simulate a process crash: brand-new trainer, same command
    ds = synthetic_dataset(64, 6, in_dim=8, num_classes=3, seed=0)
    cfg = TrainConfig(epochs=100, eval_every=2, verbose=False,
                      symmetric=True)
    t2 = Trainer(build_gcn([8, 8, 3]), ds, cfg)
    train_with_recovery(t2, 10, rot, checkpoint_every=3)
    assert t2.epoch == 10


def test_recovery_retries_on_numeric_failure(trainer, tmp_path):
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=2)
    train_with_recovery(trainer, 2, rot, checkpoint_every=2)
    fails = {"n": 0}
    orig_train = trainer.train

    def flaky_train(epochs=None):
        hist = orig_train(epochs=epochs)
        if fails["n"] < 2:
            fails["n"] += 1
            hist[-1]["train_loss"] = float("nan")
        return hist

    trainer.train = flaky_train
    seen = []
    train_with_recovery(trainer, 6, rot, checkpoint_every=2,
                        max_retries=3,
                        on_failure=lambda e: seen.append(str(e)))
    assert trainer.epoch == 6
    assert len(seen) == 2


def test_recovery_gives_up_after_max_retries(trainer, tmp_path):
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=2)
    train_with_recovery(trainer, 2, rot, checkpoint_every=2)
    orig_train = trainer.train

    def always_nan(epochs=None):
        hist = orig_train(epochs=epochs)
        hist[-1]["train_loss"] = float("nan")
        return hist

    trainer.train = always_nan
    with pytest.raises(NumericFailure):
        train_with_recovery(trainer, 8, rot, checkpoint_every=2,
                            max_retries=1)
